"""Tier-1 gate for the persistent AOT compile cache (ISSUE 3): with
FLAGS_jit_cache_dir UNSET every compile site behaves exactly as before —
no lowering, no hashing, no disk I/O, and per-call wrapper overhead
bounded like the monitor's disabled fast path. Plus: tools/aot_warm.py
--json exits 1 when any site fails to serialize."""
import importlib.util
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.framework import aot


@pytest.fixture(autouse=True)
def _flag_unset():
    paddle.set_flags({"jit_cache_dir": ""})
    yield
    paddle.set_flags({"jit_cache_dir": ""})


def _forbid_disk(monkeypatch):
    """Any touch of the cache machinery while the flag is unset is a
    regression — the zero-overhead contract."""
    def boom(*a, **k):
        raise AssertionError("AOT cache machinery ran with "
                             "FLAGS_jit_cache_dir unset")
    monkeypatch.setattr(aot, "_load_entry", boom)
    monkeypatch.setattr(aot, "_store_entry", boom)
    monkeypatch.setattr(aot, "_cache_key", boom)


class TestFlagUnsetIsExactlyBefore:
    def test_compile_cached_returns_the_jit_untouched(self, monkeypatch):
        _forbid_disk(monkeypatch)
        jitted = jax.jit(lambda a: a + 1)
        got, source = aot.compile_cached(jitted, (jnp.ones(3),), site="t")
        assert got is jitted and source == "bypass"

    def test_executor_and_trainer_paths_do_no_disk_io(self, monkeypatch):
        _forbid_disk(monkeypatch)
        import paddle_tpu.static as st
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        # executor
        paddle.seed(0)
        main, startup = st.Program(), st.Program()
        st.enable_static()
        try:
            with st.program_guard(main, startup):
                x = st.data("x", [None, 4])
                w = paddle.create_parameter([4, 4])
                y = paddle.matmul(x, w)
        finally:
            st.disable_static()
        exe = st.Executor()
        exe.run(startup)
        (r,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                       fetch_list=[y])
        assert np.isfinite(r).all()
        # trainer (1-layer linear regression keeps this cheap)
        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(), mesh=mesh)
        loss = tr.train_step(np.ones((2, 4), np.float32),
                             np.zeros((2, 1), np.float32))
        assert np.isfinite(float(np.asarray(loss._data)))
        # serving-style wrapper
        cj = aot.cached_jit(lambda a: a * 2, site="t", label="gate")
        np.testing.assert_array_equal(np.asarray(cj(jnp.ones(3))),
                                      np.full(3, 2.0))

    def test_metrics_identical_to_before(self):
        """Flag unset: the executor still reports miss(fresh)/hit(memory)
        exactly as the pre-AOT instrumentation did — one fresh compile,
        then memory hits (no disk series anywhere)."""
        import paddle_tpu.static as st

        monitor.reset()
        paddle.seed(0)
        main, startup = st.Program(), st.Program()
        st.enable_static()
        try:
            with st.program_guard(main, startup):
                x = st.data("x", [None, 4])
                w = paddle.create_parameter([4, 4])
                y = paddle.matmul(x, w)
        finally:
            st.disable_static()
        exe = st.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        exe.run(main, feed=feed, fetch_list=[y])
        exe.run(main, feed=feed, fetch_list=[y])
        cache = monitor.counter("compile_cache_total",
                                labelnames=("site", "event", "sig",
                                            "source"))
        sig = "x:float32[2,4]"
        assert cache.labels(site="executor", event="miss", sig=sig,
                            source="fresh").value == 1
        assert cache.labels(site="executor", event="hit", sig=sig,
                            source="memory").value == 1
        metric = monitor.default_registry().get("compile_cache_total")
        assert not any(s.labels.get("source") == "disk"
                       for s in metric.series())

    def test_aot_compile_forces_in_memory_without_flag(self, monkeypatch):
        """Warm-start must never hand back a lazy jit: Program.aot_compile
        with the flag unset still AOT-compiles (in memory, zero disk) and
        the later run() pays no compile."""
        _forbid_disk(monkeypatch)
        import paddle_tpu.static as st

        paddle.seed(0)
        main, startup = st.Program(), st.Program()
        st.enable_static()
        try:
            with st.program_guard(main, startup):
                x = st.data("x", [None, 4])
                w = paddle.create_parameter([4, 4])
                y = paddle.matmul(x, w)
        finally:
            st.disable_static()
        exe = st.Executor()
        exe.run(startup)
        assert main.aot_compile({"x": ((2, 4), "float32")},
                                fetch_list=[y]) == "fresh"
        compiles = monitor.counter("compile_total", labelnames=("site",))
        before = compiles.labels(site="executor").value
        (r,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                       fetch_list=[y])
        assert np.isfinite(r).all()
        assert compiles.labels(site="executor").value == before

    def test_wrapper_disabled_overhead(self):
        """The CachedJit fast path (flag unset, nothing warmed) must cost
        one empty-dict + flag check per call — same bar and method as
        test_monitor_disabled_overhead (<5us/call against a no-op target,
        ~25x the expected cost)."""
        import time

        sink = []
        cj = aot.cached_jit(jit=sink.append, site="t", label="overhead")
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            cj(None)
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0, (
            f"CachedJit disabled path costs {per_call_us:.2f}us/call — "
            "the flag-unset fast path regressed")
        assert len(sink) == n  # every call actually delegated


class TestAotWarmTool:
    def _load(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "aot_warm", os.path.join(repo, "tools", "aot_warm.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules.pop("aot_warm", None)
        spec.loader.exec_module(mod)
        return mod

    def test_no_cache_dir_is_an_error(self):
        aw = self._load()
        assert aw.main(["--model", "gpt", "--json"]) == 1

    def test_serialize_failure_exits_1(self, tmp_path, monkeypatch, capsys):
        """The CI contract: any site whose executable cannot be
        serialized must fail the warm run (a deploy would silently
        recompile otherwise)."""
        import json

        aw = self._load()

        def broken(compiled):
            raise ValueError("serialization intentionally broken")
        import jax.experimental.serialize_executable as se

        monkeypatch.setattr(se, "serialize", broken)
        rc = aw.main(["--model", "gpt", "--json",
                      "--cache-dir", str(tmp_path / "aot")])
        paddle.set_flags({"jit_cache_dir": ""})
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        assert report["totals"]["error"] >= 1
        msgs = [f["message"] for f in report["targets"]["gpt"]["findings"]
                if f["severity"] == "error"]
        assert any("serialize" in m for m in msgs)

    def test_warm_then_report_clean(self, tmp_path, capsys):
        import json

        aw = self._load()
        rc = aw.main(["--model", "gpt", "--json",
                      "--cache-dir", str(tmp_path / "aot")])
        paddle.set_flags({"jit_cache_dir": ""})
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert set(report) >= {"tool", "passes", "targets", "totals"}
        assert report["totals"]["error"] == 0
        assert os.listdir(str(tmp_path / "aot"))
