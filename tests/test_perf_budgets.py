"""Hardware-free perf regression gates (VERDICT r4 #5): while the TPU tunnel
is down, perf can silently rot. These tests compile the flagship programs
AOT on the suite's virtual-CPU backend and assert

- XLA cost-analysis FLOPs and bytes-accessed stay within tolerance of the
  budgets recorded in tests/perf_budgets.json (a refactor that doubles the
  bytes moved or the FLOPs of the train/decode step fails here, pre-TPU);
- the post-partitioning HLO of the dp/ZeRO-2 trainer and the tp serving
  step carries EXACTLY the recorded collective counts (one extra
  all-gather = failure).

Reference analog: tools/check_op_benchmark_result.py's >5% CI gate —
the same idea in compile-time form (SURVEY §6 tooling).

Regenerate budgets after an INTENTIONAL change:
    python tests/test_perf_budgets.py --record
(budget drift then shows up in the diff for review, like any golden file).

The wall-time floors (step time / MFU / dispatch fraction) live
separately, as perf-ledger rows in tests/perf_baseline.jsonl
(monitor/perfledger.py row schema, env-fingerprint-gated exactly like
every other ledger consumer — ISSUE 17 retired this file's private
fingerprint format). Re-pin them on a new machine with:
    python tests/test_perf_budgets.py --record-steptime
(appends rows — the ledger discipline; the newest env-matching row
wins).
"""
import json
import os
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
BUDGET_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "perf_budgets.json")

# the exact-HLO-count machinery moved into the analysis layer (the
# collective-count pass and this gate share one counter; same recorded
# format, so existing perf_budgets.json baselines stay valid)
from paddle_tpu.analysis.collectives import count_hlo_collectives

# FLOPs should be near-exact for fixed shapes; bytes-accessed wobbles more
# across XLA versions (layout/fusion choices), so its band is wider. The
# bands are tight enough that the failure the gate exists for — 2x bytes,
# an accidentally-doubled forward — cannot pass.
FLOPS_BAND = (0.75, 1.30)
BYTES_BAND = (0.50, 1.45)


_count_collectives = count_hlo_collectives


def _cost(compiled):
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):   # older jax returns [dict]
        cost = cost[0] if cost else {}
    return cost or {}


def _build_train(window=None, mesh_shape=None, stage=2):
    """The bench gpt2s train step (CPU-shrunk shapes), optionally windowed
    (the 16k flash config's CPU form) or dp-sharded over a virtual mesh."""
    import jax
    import jax.numpy as jnp

    import bench
    import paddle_tpu as paddle
    from paddle_tpu.core.generator import default_generator

    if mesh_shape is None:
        on_tpu, cfg, trainer, ids, labels = bench._gpt2s_setup(
            2, 128, window=window)
    else:
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainLoss)

        dp = int(np.prod(mesh_shape))
        mesh = build_mesh(mesh_shape, ("dp",),
                          devices=jax.devices()[:dp])
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=64, dropout=0.0)
        model = GPTForCausalLM(cfg)
        loss_layer = GPTPretrainLoss()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        trainer = SpmdTrainer(model, opt,
                              loss_fn=lambda lg, lb: loss_layer(lg, lb),
                              mesh=mesh, dp_axis="dp", sharding_stage=stage)
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, 512, (dp * 2, 64)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.randint(0, 512, (dp * 2, 64)).astype(np.int32))

    batch_arrays = (ids._data, labels._data)
    lr = jnp.asarray(trainer.optimizer.get_lr(), dtype=jnp.float32)
    key = default_generator().fold_in(0)
    with paddle.amp.auto_cast(True, dtype="bfloat16"):
        step_fn = trainer._build(list(batch_arrays))
        lowered = step_fn.lower(trainer.params, trainer.opt_state,
                                trainer.buffers, lr, key, *batch_arrays)
        return lowered.compile()


def _build_serving_step(tp=False):
    """The serving engine's greedy decode step — the serve/decode hot loop."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    tp_mesh = None
    if tp:
        from paddle_tpu.distributed.mesh import build_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        tp_mesh = build_mesh((4,), ("mp",), devices=jax.devices()[:4])
    eng = ServingEngine(m, max_batch=2, tp_mesh=tp_mesh)
    lowered = eng._step_greedy.lower(
        eng._params, eng._kc, eng._vc,
        jnp.zeros((eng.B,), jnp.int32), jnp.zeros((eng.B,), jnp.int32))
    return lowered.compile()


def _measure():
    out = {}
    c = _build_train()
    cost = _cost(c)
    out["gpt2s_train"] = {"flops": float(cost.get("flops", 0.0)),
                          "bytes": float(cost.get("bytes accessed", 0.0))}
    c = _build_train(window=64)
    cost = _cost(c)
    out["gpt2s_flash_window"] = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes": float(cost.get("bytes accessed", 0.0))}
    c = _build_serving_step()
    cost = _cost(c)
    out["serve_decode_step"] = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes": float(cost.get("bytes accessed", 0.0))}
    c = _build_train(mesh_shape=(8,), stage=2)
    out["dp8_zero2_collectives"] = _count_collectives(c.as_text())
    out["dp8_zero2_collectives_env"] = _collective_env()
    c = _build_serving_step(tp=True)
    out["tp4_serve_step_collectives"] = _count_collectives(c.as_text())
    return out


@pytest.fixture(scope="module")
def budgets():
    if not os.path.exists(BUDGET_PATH):
        pytest.fail("tests/perf_budgets.json missing — run "
                    "`python tests/test_perf_budgets.py --record`")
    return json.load(open(BUDGET_PATH))


@pytest.mark.parametrize("config", ["gpt2s_train", "gpt2s_flash_window",
                                    "serve_decode_step"])
def test_cost_budget(config, budgets):
    import jax

    if jax.devices()[0].platform != "cpu":
        pytest.skip("budgets recorded on the CPU backend")
    build = {"gpt2s_train": lambda: _build_train(),
             "gpt2s_flash_window": lambda: _build_train(window=64),
             "serve_decode_step": lambda: _build_serving_step()}[config]
    cost = _cost(build())
    rec = budgets[config]
    flops = float(cost.get("flops", 0.0))
    byts = float(cost.get("bytes accessed", 0.0))
    if rec["flops"]:
        r = flops / rec["flops"]
        assert FLOPS_BAND[0] <= r <= FLOPS_BAND[1], (
            f"{config}: FLOPs/step {flops:.3e} vs budget "
            f"{rec['flops']:.3e} (ratio {r:.2f}) — intentional? re-record")
    if rec["bytes"]:
        r = byts / rec["bytes"]
        assert BYTES_BAND[0] <= r <= BYTES_BAND[1], (
            f"{config}: bytes/step {byts:.3e} vs budget "
            f"{rec['bytes']:.3e} (ratio {r:.2f}) — intentional? re-record")


def test_flash_window_adds_no_material_overhead(budgets):
    """On CPU the windowed config falls back to dense-masked attention
    (the banded block-skipping lives in the TPU flash path), so its FLOPs
    budget must track the dense config's — a window path that ADDED
    compute (recomputing both branches, materializing the full mask per
    head) would blow this band. The O(s*W) saving itself is asserted
    analytically in bench._model_flops_per_token and measured on-chip."""
    dense = budgets["gpt2s_train"]["flops"]
    windowed = budgets["gpt2s_flash_window"]["flops"]
    if dense and windowed:
        assert windowed <= dense * 1.02


def _collective_env():
    """Environment fingerprint the all-reduce COUNT depends on: XLA's
    collective-combiner (one fused all-reduce vs one per gradient) varies
    with the jax/jaxlib release, not with our sharding."""
    import jax
    import jaxlib

    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__}


def test_dp8_zero2_collective_counts(budgets):
    import jax

    if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    got = _count_collectives(_build_train(mesh_shape=(8,),
                                          stage=2).as_text())
    want = budgets["dp8_zero2_collectives"]
    # structural floor independent of the recording: ZeRO-2 must scatter
    # grads and gather params somewhere in the step
    assert got["reduce-scatter"] + got["all-reduce"] >= 1
    assert got["all-gather"] >= 1
    # gather/scatter counts reflect OUR sharding structure and hold across
    # XLA versions — always compared exactly
    for fam in ("all-gather", "reduce-scatter"):
        assert got[fam] == want[fam], (
            f"dp8 ZeRO-2 {fam} count changed: {got} vs recorded {want} — "
            "an extra one means a sharding regression (re-record only if "
            "intentional)")
    # the all-reduce count additionally depends on XLA's collective
    # combiner: exact only when the recording's environment matches this
    # one, otherwise the env-dependent compare is skipped (re-record on
    # the new environment to pin it again)
    if got["all-reduce"] != want["all-reduce"]:
        if budgets.get("dp8_zero2_collectives_env") != _collective_env():
            pytest.skip(
                f"all-reduce count {got['all-reduce']} vs recorded "
                f"{want['all-reduce']}: the recording comes from a "
                "different jax/jaxlib whose collective combiner fuses "
                "differently — structure (gather/scatter) verified; "
                "re-record tests/perf_budgets.json here to re-pin")
        raise AssertionError(
            f"dp8 ZeRO-2 all-reduce count changed on the SAME "
            f"environment: {got} vs recorded {want} — a sharding "
            "regression (re-record only if intentional)")


def test_tp4_serve_step_collective_counts(budgets):
    import jax

    if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    got = _count_collectives(_build_serving_step(tp=True).as_text())
    want = budgets["tp4_serve_step_collectives"]
    assert got == want, (
        f"tp serving step collective counts changed: {got} vs {want} — "
        "the Megatron recipe is exactly two psums per layer (post-attn, "
        "post-mlp: 2L total); anything extra is a resharding bug")
    # structural form of the same claim, independent of the recording
    assert got["all-reduce"] == 2 * 2  # 2 psums x num_layers(=2)


# -- bandwidth-frugal dp: quantized all-reduce / update sharding --------------
# ISSUE 10 acceptance: on the dp8 mesh the quantized step's grad-reduce
# wire bytes drop >= 3.5x vs the fp32 payload, with the collective
# structure pinned EXACTLY (computed from the model, not recorded — the
# counts are ours, not XLA's combiner's). The quantized reduce family is
# classified by analysis/collectives.count_quantized_collectives.

QUANT_WIRE_RATIO = 3.5


def _compressed_step_jaxpr(quant, shard, min_size=1024):
    """Build the dp8 trainer under the compression flags, trace its step
    to a jaxpr (metering fires once, at trace — PR 2 semantics), and
    return (trainer, jaxpr, snapshot_families)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import monitor
    from paddle_tpu.core.generator import default_generator
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainLoss)

    old = {k: paddle.get_flags(["FLAGS_" + k])["FLAGS_" + k]
           for k in ("quantized_allreduce", "shard_weight_update",
                     "quantized_allreduce_min_size")}
    paddle.set_flags({"quantized_allreduce": quant,
                      "shard_weight_update": shard,
                      "quantized_allreduce_min_size": min_size})
    try:
        mesh = build_mesh((8,), ("dp",), devices=jax.devices()[:8])
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=64, dropout=0.0)
        model = GPTForCausalLM(cfg)
        loss_layer = GPTPretrainLoss()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        trainer = SpmdTrainer(model, opt,
                              loss_fn=lambda lg, lb: loss_layer(lg, lb),
                              mesh=mesh, dp_axis="dp")
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 512, (16, 64)).astype(np.int32)
        labels = rng.randint(0, 512, (16, 64)).astype(np.int32)
        step = trainer._build([jnp.asarray(ids), jnp.asarray(labels)])
        lr = jnp.asarray(1e-4, jnp.float32)
        key = default_generator().fold_in(0)
        monitor.reset()
        jaxpr = jax.make_jaxpr(step)(
            trainer.params, trainer.opt_state, trainer.buffers, lr, key,
            jnp.asarray(ids), jnp.asarray(labels))
        snap = monitor.snapshot()
        # counter/gauge series only: unlabeled HISTOGRAM series (e.g.
        # serving_ttft_ms, observed by an earlier test in the same
        # process) survive monitor.reset() zeroed and carry no "value"
        fams = {m["name"]: {tuple(sorted(s["labels"].items())): s["value"]
                            for s in m["series"] if "value" in s}
                for m in snap["metrics"] if m["series"]}
        return trainer, jaxpr, fams
    finally:
        paddle.set_flags(old)


def _series(fams, name, op):
    return fams.get(name, {}).get((("op", op),), 0.0)


def test_dp8_quantized_collectives_and_bytes():
    """The quantized dp8 step: EXACTLY one int8 reduce-scatter-phase
    exchange + one int8 all-gather (the fused grad bundle), the fp32
    grad all-reduce reduced to the loss/small-tensor pmeans, and the
    metered wire bytes >= 3.5x smaller than the fp32 payload they
    displaced."""
    import jax

    from paddle_tpu.analysis.collectives import (
        count_jaxpr_collectives, count_quantized_collectives)

    if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    trainer, jaxpr, fams = _compressed_step_jaxpr(quant=True, shard=False)
    q = count_quantized_collectives(jaxpr)
    assert q == {"quantized-reduce-scatter": 1,
                 "quantized-all-gather": 1}, (
        f"quantized exchange structure changed: {q} — the fused bundle "
        "must move through exactly one int8 all_to_all + one int8 "
        "all_gather")
    fam = count_jaxpr_collectives(jaxpr)
    # int8 payload + f32 scales per phase — nothing else may exchange
    assert fam.get("all-to-all", 0) == 2, fam
    assert fam.get("all-gather", 0) == 2, fam
    # fp32 all-reduces left: ONE loss pmean + ONE scalar qerr psum + one
    # pmean per ineligible (small) param + one per buffer — the big
    # grads are gone from the fp32 stream
    n_inel = sum(1 for n in trainer.params
                 if n not in trainer._qar_eligible)
    expected_ar = 2 + n_inel + len(trainer.buffers)
    assert fam.get("all-reduce", 0) == expected_ar, (
        f"fp32 all-reduce count {fam.get('all-reduce')} != "
        f"{expected_ar} (loss + qerr + {n_inel} small params + "
        f"{len(trainer.buffers)} buffers)")
    # byte budget: wire vs the fp32 payload it displaced (exact, from
    # the chokepoint's own trace-time metering)
    wire = _series(fams, "collective_bytes_total", "quantized_all_reduce")
    saved = _series(fams, "collective_bytes_saved_total",
                    "quantized_all_reduce")
    logical = wire + saved
    eligible_fp32 = sum(
        int(np.asarray(trainer.params[n]).size) * 4
        for n in trainer._qar_eligible)
    assert logical == eligible_fp32, (
        f"logical payload {logical} != eligible fp32 grad bytes "
        f"{eligible_fp32}")
    assert wire > 0 and logical >= QUANT_WIRE_RATIO * wire, (
        f"wire bytes {wire} vs fp32 payload {logical}: compression "
        f"ratio {logical / max(wire, 1):.2f}x < {QUANT_WIRE_RATIO}x")


def test_dp8_shard_update_collectives():
    """Update sharding alone: per param exactly one reduce-scatter (the
    grad) and one all-gather (the updated param) — the program-level
    proof that no replica computes the full update."""
    import jax

    from paddle_tpu.analysis.collectives import (
        count_jaxpr_collectives, count_quantized_collectives)

    if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    trainer, jaxpr, fams = _compressed_step_jaxpr(quant=False, shard=True)
    n = len(trainer.params)
    fam = count_jaxpr_collectives(jaxpr)
    assert fam.get("reduce-scatter", 0) == n, fam
    assert fam.get("all-gather", 0) == n, fam
    assert fam.get("all-reduce", 0) == 1 + len(trainer.buffers), fam
    assert count_quantized_collectives(jaxpr) == {
        "quantized-reduce-scatter": 0, "quantized-all-gather": 0}


def test_dp8_overlap_quantized_collectives():
    """FLAGS_overlap_grad_comm (ISSUE 11): the fused bundle splits into
    one int8 exchange pair PER eligible layer — independent legs XLA's
    scheduler can interleave with backward compute. Structure computed
    from the model, pinned exactly."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.analysis.collectives import (
        count_jaxpr_collectives, count_quantized_collectives)

    if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    old = paddle.get_flags(["FLAGS_overlap_grad_comm"])
    paddle.set_flags({"overlap_grad_comm": True})
    try:
        trainer, jaxpr, fams = _compressed_step_jaxpr(quant=True,
                                                      shard=False)
    finally:
        paddle.set_flags(old)
    n_el = len(trainer._qar_eligible)
    assert n_el > 1   # otherwise legs == bundle and this proves nothing
    q = count_quantized_collectives(jaxpr)
    assert q == {"quantized-reduce-scatter": n_el,
                 "quantized-all-gather": n_el}, (
        f"overlapped exchange structure changed: {q} — expected one "
        f"int8 leg per eligible layer ({n_el})")
    fam = count_jaxpr_collectives(jaxpr)
    # int8 payload + f32 scales per leg and phase
    assert fam.get("all-to-all", 0) == 2 * n_el, fam
    assert fam.get("all-gather", 0) == 2 * n_el, fam
    # the metered logical payload is unchanged: same grads, same bytes
    wire = _series(fams, "collective_bytes_total", "quantized_all_reduce")
    saved = _series(fams, "collective_bytes_saved_total",
                    "quantized_all_reduce")
    eligible_fp32 = sum(
        int(np.asarray(trainer.params[n]).size) * 4
        for n in trainer._qar_eligible)
    assert wire + saved == eligible_fp32
    assert wire > 0 and wire + saved >= QUANT_WIRE_RATIO * wire


def test_dp8_composed_quantized_shard_collectives():
    """Both flags: each eligible grad moves as ONE int8 reduce-scatter
    phase feeding the sharded update (no int8 all-gather — the updated
    params gather in fp32), small grads keep their exact fp32
    reduce-scatter."""
    import jax

    from paddle_tpu.analysis.collectives import (
        count_jaxpr_collectives, count_quantized_collectives)

    if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
        pytest.skip("needs the 8-virtual-device CPU mesh")
    trainer, jaxpr, fams = _compressed_step_jaxpr(quant=True, shard=True)
    n_el = len(trainer._qar_eligible)
    n_inel = len(trainer.params) - n_el
    assert n_el > 0
    q = count_quantized_collectives(jaxpr)
    assert q == {"quantized-reduce-scatter": n_el,
                 "quantized-all-gather": 0}, q
    fam = count_jaxpr_collectives(jaxpr)
    assert fam.get("reduce-scatter", 0) == n_inel, fam
    # one fp32 all-gather per param (the updated params going back out)
    # + one f32 scale all_to_all per eligible param rides in all-to-all
    assert fam.get("all-gather", 0) == len(trainer.params), fam
    assert fam.get("all-to-all", 0) == 2 * n_el, fam


# -- per-model step-time / MFU floors (ROADMAP item 3) ------------------------
# Wall-time floors are env-dependent in a way FLOPs budgets are not, so
# they are stored as perf-ledger rows (tests/perf_baseline.jsonl) keyed
# by the ledger's CORE env fingerprint: the gate only compares where the
# fingerprint matches THIS machine — elsewhere it skips with structure
# verified (--record-steptime appends a fresh row to pin the new
# environment; the newest matching row wins).

STEP_FLOOR_MODELS = ("gpt", "bert")
#: measured-vs-recorded slack: CI machines share cores; a true
#: regression (2x slower step from an accidental host sync or a
#: recompile-per-step bug) still blows through 3x
STEP_TIME_SLACK = 3.0

BASELINE_LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "perf_baseline.jsonl")


def _ledger_floor(site):
    """The newest env-matching baseline row's metrics for one budget
    site from the committed ledger, or None (skip: this machine has no
    recorded floor)."""
    from paddle_tpu.monitor import perfledger

    key = perfledger.fingerprint_key(perfledger.env_fingerprint())
    rows = [r for r in perfledger.load_rows(BASELINE_LEDGER)
            if r.get("site") == site
            and perfledger.fingerprint_key(r.get("env") or {}) == key]
    return (rows[-1].get("metrics") or None) if rows else None


def _bank_floor(site, metrics):
    """Append one baseline row (the ledger append-only discipline — a
    re-record never rewrites history, the diff shows both)."""
    from paddle_tpu.monitor import perfledger

    perfledger.append_row(BASELINE_LEDGER, {
        "v": perfledger.SCHEMA_VERSION, "ts": round(time.time(), 3),
        "site": site, "sig": None, "mesh": None,
        "env": perfledger.env_fingerprint(), "metrics": metrics})


def _floor_trainer(name):
    """A tiny train setup per model (metrics_dump shapes), with the cost
    registry populated via aot_build so stats()["mfu"] is finite."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import (BertConfig, BertForPretraining,
                                   BertPretrainLoss, GPTConfig,
                                   GPTForCausalLM, GPTPretrainLoss)

    dims = dict(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                dropout=0.0)
    paddle.seed(0)
    rng = np.random.RandomState(0)
    b, s = 2, 16
    if name == "gpt":
        model = GPTForCausalLM(GPTConfig(max_seq_len=64, **dims))
        loss = GPTPretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    elif name == "bert":
        model = BertForPretraining(BertConfig(max_position=64,
                                              intermediate_size=256,
                                              **dims))
        loss = BertPretrainLoss()
        batch = (rng.randint(0, 256, (b, s)).astype(np.int32),
                 np.zeros((b, s), np.int32),
                 rng.randint(0, 256, (b, s)).astype(np.int32))
    else:
        raise ValueError(name)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    trainer = SpmdTrainer(model, opt, loss_fn=loss, mesh=mesh)
    trainer.aot_build([(a.shape, a.dtype) for a in batch])
    tensors = [paddle.to_tensor(a) for a in batch]
    return trainer, tensors


def _measure_step_floor(name, warmup=2, steps=5):
    trainer, tensors = _floor_trainer(name)
    for _ in range(warmup):
        out = trainer.train_step(*tensors)
    np.asarray(out._data)           # device-complete before timing
    t0 = __import__("time").perf_counter()
    for _ in range(steps):
        out = trainer.train_step(*tensors)
    np.asarray(out._data)           # include the device tail
    wall_ms = (__import__("time").perf_counter() - t0) * 1e3 / steps
    st = trainer.stats()
    return {"step_ms": wall_ms, "mfu": st["mfu"]}


def _record_step_floors():
    for name in STEP_FLOOR_MODELS:
        _bank_floor("budget/" + name, _measure_step_floor(name))
    _bank_floor("budget/dispatch", _measure_dispatch_fraction())


@pytest.mark.parametrize("model", STEP_FLOOR_MODELS)
def test_step_time_and_mfu_floor(model):
    import jax

    if jax.devices()[0].platform != "cpu":
        pytest.skip("floors recorded on the CPU backend")
    want = _ledger_floor("budget/" + model)
    if not want:
        pytest.skip("no env-matching step-time baseline row — run "
                    "`python tests/test_perf_budgets.py "
                    "--record-steptime` to pin this machine")
    got = _measure_step_floor(model)
    assert got["step_ms"] <= want["step_ms"] * STEP_TIME_SLACK, (
        f"{model}: train step {got['step_ms']:.2f}ms vs recorded "
        f"{want['step_ms']:.2f}ms (> {STEP_TIME_SLACK}x) — a speed "
        "regression (host sync? recompile per step?); re-record only if "
        "intentional")
    # the MFU floor is the same claim through the cost registry: flops
    # are pinned by the budgets above, so mfu degrades iff step time does
    if want.get("mfu") and got.get("mfu"):
        assert got["mfu"] >= want["mfu"] / STEP_TIME_SLACK, (
            f"{model}: MFU {got['mfu']:.3e} vs recorded "
            f"{want['mfu']:.3e} — the speed loop went backwards")


# -- dispatch fraction floor (ISSUE 11) ---------------------------------------
# host-dispatch ms / step ms for the guarded tiny-GPT step, measured
# under FLAGS_benchmark (so sync_ms captures the device wait) with
# FLAGS_check_nan_inf armed. Before the deferred guard, the per-step
# verdict fetch blocked INSIDE the dispatch window and the fraction sat
# near 1.0; with the deferred drain the device wait lands in sync_ms.
# Same env-fingerprint discipline as the step-time floors.

DISPATCH_GAP_SHRINK = 0.75


def _measure_dispatch_fraction(warmup=2, steps=8):
    import paddle_tpu as paddle

    old = paddle.get_flags(["FLAGS_check_nan_inf", "FLAGS_benchmark"])
    paddle.set_flags({"check_nan_inf": True, "benchmark": True})
    try:
        trainer, tensors = _floor_trainer("gpt")
        for _ in range(warmup):
            trainer.train_step(*tensors)
        # reset the accounting windows after warmup/compile
        trainer._step_ms_sum = trainer._sync_ms_sum = 0.0
        trainer._step_count = 0
        for _ in range(steps):
            trainer.train_step(*tensors)
        bd = trainer.stats()["breakdown"]
        total = bd["dispatch_ms_total"] + bd["sync_ms_total"]
        return {"fraction": bd["dispatch_ms_total"] / total,
                "dispatch_ms": bd["dispatch_ms_total"] / steps,
                "sync_ms": bd["sync_ms_total"] / steps}
    finally:
        paddle.set_flags(old)


def test_dispatch_fraction_floor():
    import jax

    if jax.devices()[0].platform != "cpu":
        pytest.skip("floors recorded on the CPU backend")
    rec = _ledger_floor("budget/dispatch")
    if not rec:
        pytest.skip("no env-matching dispatch-fraction baseline row — "
                    "run `python tests/test_perf_budgets.py "
                    "--record-steptime` to pin this machine")
    got = _measure_dispatch_fraction()
    want = rec["fraction"]
    # the fraction lives in [0, 1], so gate the IDLE GAP (1 - fraction):
    # a reintroduced per-step blocking sync pushes the fraction toward
    # 1.0, eating the gap — allow at most DISPATCH_GAP_SHRINK of it to
    # vanish before failing (a multiplicative band on the fraction
    # itself would clamp to 1.0 and never fire)
    bound = want + (1.0 - want) * DISPATCH_GAP_SHRINK
    assert got["fraction"] <= bound, (
        f"guarded tiny-GPT dispatch fraction {got['fraction']:.4f} vs "
        f"recorded {want:.4f} (bound {bound:.4f}) — host work crept "
        "back between dispatches (a per-step sync?); re-record only if "
        "intentional")
    # the absolute half (the CPU backend dispatches near-synchronously,
    # so the ratio alone under-constrains): per-step host-dispatch ms
    # may not regress past the step-time slack
    assert got["dispatch_ms"] <= rec["dispatch_ms"] * STEP_TIME_SLACK, (
        f"guarded tiny-GPT host-dispatch {got['dispatch_ms']:.2f}ms/step "
        f"vs recorded {rec['dispatch_ms']:.2f} (> {STEP_TIME_SLACK}x) — "
        "a dispatch-path speed regression; re-record only if intentional")


def test_async_window_cuts_verdict_fetches():
    """The structural half of the ISSUE 11 acceptance criterion,
    machine-independent: the guarded tiny-GPT trainer under
    FLAGS_async_dispatch performs <= 1 verdict host-sync per
    FLAGS_async_window steps (the windowed drain), vs one per step for
    the window-1 path."""
    import paddle_tpu as paddle

    old = paddle.get_flags(["FLAGS_check_nan_inf", "FLAGS_async_dispatch",
                            "FLAGS_async_window"])
    paddle.set_flags({"check_nan_inf": True, "async_dispatch": True,
                      "async_window": 4})
    try:
        trainer, tensors = _floor_trainer("gpt")
        for _ in range(12):
            trainer.train_step(*tensors)
        assert trainer._verdict_fetches <= 12 // 4, (
            trainer._verdict_fetches)
        trainer.guard_sync()
        assert trainer._nonfinite_total == 0
    finally:
        paddle.set_flags(old)
    paddle.set_flags({"check_nan_inf": True})
    try:
        trainer, tensors = _floor_trainer("gpt")
        for _ in range(4):
            trainer.train_step(*tensors)
        # window 1: one drain per step (still deferred — entry fetches)
        assert trainer._verdict_fetches == 3
    finally:
        paddle.set_flags({"check_nan_inf": old["FLAGS_check_nan_inf"]})


def test_monitor_disabled_overhead():
    """Tier-1 overhead gate (ISSUE 2): with the monitor disabled every
    instrumented call site must cost ONE boolean check — bounded here
    absolutely (5us/call is ~25x the expected cost, far under any real
    per-step budget, yet two orders of magnitude below a lock+dict-hit
    implementation that forgot the fast path). Device-side cost is
    already gated by the FLOPs/bytes budgets above: the instrumentation
    is host-side only, so a compiled-program regression would trip them."""
    import time

    from paddle_tpu import monitor

    c = monitor.counter("overhead_probe_total")
    h = monitor.histogram("overhead_probe_ms")
    bound = monitor.counter("overhead_probe_labeled_total",
                            labelnames=("site",)).labels(site="x")
    n = 100_000
    monitor.disable()
    try:
        t0 = time.perf_counter()
        for _ in range(n):
            c.inc()
            h.observe(1.0)
            bound.inc()
        per_call_us = (time.perf_counter() - t0) / (3 * n) * 1e6
    finally:
        monitor.enable()
    assert per_call_us < 5.0, (
        f"monitor-disabled instrumentation costs {per_call_us:.2f}us/call "
        "— the disabled fast path regressed")
    # and disabled mode recorded NOTHING
    assert c.value == 0 and h.count == 0 and bound.value == 0


if __name__ == "__main__":
    if "--record" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        assert jax.devices()[0].platform == "cpu"
        budgets = _measure()
        json.dump(budgets, open(BUDGET_PATH, "w"), indent=1)
        _record_step_floors()
        print(f"recorded -> {BUDGET_PATH} (+ floors -> {BASELINE_LEDGER})")
        print(json.dumps(budgets, indent=1))
    elif "--record-steptime" in sys.argv:
        # append ONLY fresh step-time/MFU/dispatch floor rows to the
        # baseline ledger, leaving the FLOPs/collective budgets untouched
        # — the usual move when picking the floors up on a new machine
        import jax

        jax.config.update("jax_platforms", "cpu")
        assert jax.devices()[0].platform == "cpu"
        _record_step_floors()
        print(f"recorded step-time floor rows -> {BASELINE_LEDGER}")
    else:
        print(__doc__)
