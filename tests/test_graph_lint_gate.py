"""Tier-1 graph-lint gate: the analysis pass battery over the bundled
models, the serving decode step, and the framework source — every run.

Contract (ISSUE 1 acceptance + the reference's always-on REGISTER_PASS
validation layer):

 - >= 8 distinct passes registered;
 - gpt/bert/ernie forward and the serving decode step: ZERO
   error-severity findings, ever (errors are correctness hazards — a new
   one fails this gate loudly, like a new all-gather fails the perf gate);
 - warning counts per target pinned to tests/lint_baseline.json — a NEW
   warning fails until acknowledged by re-recording;
 - tools/op_coverage.py --json shares the graph_lint report schema and
   carries zero audit errors;
 - the CLI itself (`python tools/graph_lint.py --model gpt --json`) runs
   on the CPU mesh and reports through the shared schema.

Budget: in-process analysis is trace-only (no compilation), ~6 s; the one
subprocess CLI check pays a fresh interpreter+jax import. Not slow-marked.

Regenerate the baseline after an INTENTIONAL change:
    python tests/test_graph_lint_gate.py --record
"""
import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "lint_baseline.json")

GATED_TARGETS = ("gpt", "bert", "ernie", "serving", "source_lint")


def _load_graph_lint():
    spec = importlib.util.spec_from_file_location(
        "graph_lint", os.path.join(REPO, "tools", "graph_lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _full_report():
    return _load_graph_lint().build_report(
        models=("gpt", "bert", "ernie"), serving=True, source=True)


@pytest.fixture(scope="module")
def report():
    return _full_report()


@pytest.fixture(scope="module")
def baseline():
    if not os.path.exists(BASELINE_PATH):
        pytest.fail("tests/lint_baseline.json missing — run "
                    "`python tests/test_graph_lint_gate.py --record`")
    return json.load(open(BASELINE_PATH))


def test_pass_battery_registered(report):
    assert len(report["passes"]) >= 8, report["passes"]
    assert len(report["rules"]) >= 3, report["rules"]


def test_all_targets_present(report):
    assert set(report["targets"]) == set(GATED_TARGETS)


@pytest.mark.parametrize("target", GATED_TARGETS)
def test_zero_error_findings(report, target):
    rep = report["targets"][target]
    errors = [f for f in rep["findings"] if f["severity"] == "error"]
    assert errors == [], (
        f"{target}: NEW error-severity analysis findings:\n" + "\n".join(
            f"  [{f['pass']}] {f['message']} @ {f['where']}"
            for f in errors))


@pytest.mark.parametrize("target", GATED_TARGETS)
def test_warning_baseline(report, baseline, target):
    got = report["targets"][target]["counts"]["warning"]
    want = baseline["targets"][target]["warning"]
    assert got <= want, (
        f"{target}: {got} warning(s) vs recorded baseline {want} — a new "
        "analysis warning appeared; fix it or acknowledge via "
        "`python tests/test_graph_lint_gate.py --record`")


def test_op_coverage_shares_schema():
    spec = importlib.util.spec_from_file_location(
        "op_coverage", os.path.join(REPO, "tools", "op_coverage.py"))
    opcov = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(opcov)

    rep = opcov.json_report()
    # one schema across both tools: the gate reads either identically
    for r in (rep,):
        assert set(r) >= {"tool", "passes", "targets", "totals"}
        for t in r["targets"].values():
            assert set(t) >= {"name", "counts", "findings"}
            assert set(t["counts"]) == {"error", "warning", "info"}
    assert rep["totals"]["error"] == 0, rep["targets"]["op_coverage"][
        "findings"]


def test_cli_model_gpt_json():
    """The acceptance-criterion invocation, end to end on the CPU mesh."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graph_lint.py"),
         "--model", "gpt", "--json"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    rep = json.loads(out.stdout)
    assert rep["tool"] == "graph_lint"
    assert len(rep["passes"]) >= 8
    assert rep["targets"]["gpt"]["counts"]["error"] == 0
    assert rep["totals"]["error"] == 0


def _record():
    report = _full_report()
    base = {"targets": {n: r["counts"]
                        for n, r in report["targets"].items()}}
    json.dump(base, open(BASELINE_PATH, "w"), indent=1)
    print(f"recorded -> {BASELINE_PATH}")
    print(json.dumps(base, indent=1))


if __name__ == "__main__":
    if "--record" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")
        _record()
    else:
        print(__doc__)
