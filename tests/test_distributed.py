"""Distributed tests on a virtual 8-device CPU mesh (SURVEY.md §4: the TPU analog of
test_dist_base.py localhost multi-process NCCL tests + meta-optimizer graph assertions
-> here, sharding-spec and numeric equivalence assertions)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed.mesh import build_mesh, mesh_scope


def needs_8(n=8):
    return pytest.mark.skipif(len(jax.devices()) < n, reason="needs 8 devices")


class TestMesh:
    def test_build_default(self):
        m = build_mesh()
        assert m.devices.size == len(jax.devices())
        assert m.axis_names == ("dp",)

    def test_hybrid_mesh(self):
        m = build_mesh((2, 4), ("dp", "mp"))
        assert m.shape["dp"] == 2 and m.shape["mp"] == 4


class TestCollectivesInShardMap:
    def test_psum_allreduce(self):
        from jax.experimental.shard_map import shard_map

        mesh = build_mesh((8,), ("dp",))
        x = jnp.arange(8.0)

        def body(v):
            with dist.spmd_context("dp"):
                t = paddle.to_tensor(v)
                out = dist.all_reduce(t)
                return out._data

        f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        out = f(x)
        np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))

    def test_all_gather_and_scatter_reduce(self):
        from jax.experimental.shard_map import shard_map

        mesh = build_mesh((8,), ("dp",))
        x = jnp.arange(8.0).reshape(8, 1)

        def body(v):
            with dist.spmd_context("dp"):
                t = paddle.to_tensor(v)
                g = dist.all_gather(None, t)
                return g._data.reshape(1, -1)

        f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        out = f(x)
        assert out.shape == (8, 8)
        np.testing.assert_allclose(np.asarray(out)[0], np.arange(8.0))

    def test_ppermute_shift(self):
        from jax.experimental.shard_map import shard_map

        mesh = build_mesh((8,), ("dp",))
        x = jnp.arange(8.0).reshape(8, 1)

        def body(v):
            with dist.spmd_context("dp"):
                return dist.collective.p2p_shift(v, "dp", shift=1)

        f = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
        out = np.asarray(f(x)).ravel()
        np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))

    def test_eager_single_process_identity(self):
        t = paddle.to_tensor(np.ones(4, np.float32))
        out = dist.all_reduce(t)
        np.testing.assert_allclose(out.numpy(), np.ones(4))
        dist.barrier()
        assert dist.get_world_size() == 1


class TestSpmdTrainer:
    def _net_and_data(self, din=16, dout=4, n=64):
        rng = np.random.RandomState(0)
        net = nn.Sequential(nn.Linear(din, 32), nn.ReLU(), nn.Linear(32, dout))
        x = rng.randn(n, din).astype(np.float32)
        y = rng.randint(0, dout, n).astype(np.int64)
        return net, x, y

    def test_dp_training_matches_single(self):
        from paddle_tpu.distributed.spmd import SpmdTrainer

        paddle.seed(0)
        net, x, y = self._net_and_data()
        init_state = {k: v.numpy().copy() for k, v in net.state_dict().items()}

        # single-device eager reference
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        loss = nn.functional.cross_entropy(net(paddle.to_tensor(x)), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        ref = {k: v.numpy().copy() for k, v in net.state_dict().items()}
        ref_loss = float(loss.numpy())

        # sharded trainer on 8-dev mesh
        net2, _, _ = self._net_and_data()
        net2.set_state_dict(init_state)
        opt2 = paddle.optimizer.SGD(learning_rate=0.1, parameters=net2.parameters())
        mesh = build_mesh((8,), ("dp",))
        trainer = SpmdTrainer(net2, opt2, lambda o, l: nn.functional.cross_entropy(o, l), mesh=mesh)
        loss2 = trainer.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        np.testing.assert_allclose(float(loss2.numpy()), ref_loss, rtol=1e-4)
        trainer.sync_to_layer()
        for k in ref:
            np.testing.assert_allclose(net2.state_dict()[k].numpy(), ref[k], rtol=1e-4, atol=1e-5)

    def test_sharding_stage2_state_is_sharded(self):
        from paddle_tpu.distributed.spmd import SpmdTrainer

        net = nn.Linear(64, 512)  # weight big enough to shard
        opt = paddle.optimizer.Adam(learning_rate=0.001, parameters=net.parameters())
        mesh = build_mesh((8,), ("dp",))
        trainer = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                              mesh=mesh, sharding_stage=2)
        x = paddle.to_tensor(np.random.rand(16, 64).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(16, 512).astype(np.float32))
        loss = trainer.train_step(x, y)
        assert np.isfinite(float(loss.numpy()))
        m1 = trainer.opt_state["weight"]["moment1"]
        # sharded: each device holds 1/8 of the moment rows
        assert m1.sharding.spec != P() or m1.sharding.is_fully_replicated is False

    def test_stage3_param_sharding(self):
        from paddle_tpu.distributed.spmd import SpmdTrainer

        net = nn.Linear(64, 512)
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=net.parameters())
        mesh = build_mesh((8,), ("dp",))
        trainer = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                              mesh=mesh, sharding_stage=3)
        w = trainer.params["weight"]
        assert not w.sharding.is_fully_replicated
        x = paddle.to_tensor(np.random.rand(16, 64).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(16, 512).astype(np.float32))
        loss1 = float(trainer.train_step(x, y).numpy())
        loss2 = float(trainer.train_step(x, y).numpy())
        assert loss2 < loss1

    def test_gradient_accumulation(self):
        from paddle_tpu.distributed.spmd import SpmdTrainer

        paddle.seed(0)
        net = nn.Linear(8, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        mesh = build_mesh((8,), ("dp",))
        trainer = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                              mesh=mesh, accumulate_steps=2)
        x = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(16, 2).astype(np.float32))
        loss = trainer.train_step(x, y)
        assert np.isfinite(float(loss.numpy()))

    def test_recompute(self):
        from paddle_tpu.distributed.spmd import SpmdTrainer

        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        mesh = build_mesh((8,), ("dp",))
        trainer = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                              mesh=mesh, recompute=True)
        x = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(16, 2).astype(np.float32))
        assert np.isfinite(float(trainer.train_step(x, y).numpy()))


class TestTensorParallel:
    def test_column_row_parallel_specs(self):
        col = dist.ColumnParallelLinear(16, 32)
        row = dist.RowParallelLinear(32, 16)
        assert col.weight.spmd_spec == P(None, "mp")
        assert row.weight.spmd_spec == P("mp", None)
        emb = dist.VocabParallelEmbedding(100, 16)
        assert emb.weight.spmd_spec == P("mp", None)

    def test_tp_trainer_runs_on_mesh(self):
        from paddle_tpu.distributed.spmd import SpmdTrainer
        from paddle_tpu.distributed.split import collect_spmd_specs

        class TPNet(nn.Layer):
            def __init__(self):
                super().__init__()
                self.up = dist.ColumnParallelLinear(16, 64)
                self.down = dist.RowParallelLinear(64, 16)

            def forward(self, x):
                return self.down(nn.functional.relu(self.up(x)))

        net = TPNet()
        opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=net.parameters())
        mesh = build_mesh((2, 4), ("dp", "mp"))
        specs = collect_spmd_specs(net)
        assert "up.weight" in specs
        trainer = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                              mesh=mesh, extra_param_specs=specs)
        x = paddle.to_tensor(np.random.rand(8, 16).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(8, 16).astype(np.float32))
        loss = trainer.train_step(x, y)
        assert np.isfinite(float(loss.numpy()))
        assert not trainer.params["up.weight"].sharding.is_fully_replicated


class TestFleet:
    def test_strategy_fields(self):
        s = dist.fleet.DistributedStrategy()
        s.sharding = True
        s.sharding_configs = {"sharding_stage": 3, "gradient_merge_acc_step": 2}
        assert s.sharding_configs.sharding_stage == 3
        s.amp = True
        s.amp_configs = {"init_loss_scaling": 1024.0}
        assert s.amp_configs.init_loss_scaling == 1024.0
        s.recompute = True
        s.pipeline_configs = {"accumulate_steps": 4}
        assert s.pipeline_configs.accumulate_steps == 4

    def test_fleet_init_and_trainer(self):
        strategy = dist.fleet.DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"sharding_stage": 2}
        dist.fleet.init(is_collective=True, strategy=strategy)
        assert dist.fleet.worker_num() >= 1
        net = nn.Linear(32, 256)
        opt = paddle.optimizer.Adam(learning_rate=0.001, parameters=net.parameters())
        opt = dist.fleet.distributed_optimizer(opt)
        trainer = dist.fleet.build_trainer(net, loss_fn=lambda o, l: ((o - l) ** 2).mean())
        assert trainer.sharding_stage == 2
        x = paddle.to_tensor(np.random.rand(16, 32).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(16, 256).astype(np.float32))
        assert np.isfinite(float(trainer.train_step(x, y).numpy()))

    def test_fleet_dygraph_path(self):
        dist.fleet.init(is_collective=True)
        net = nn.Linear(4, 2)
        model = dist.fleet.distributed_model(net)  # world_size==1: passthrough
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        fopt = dist.fleet.distributed_optimizer(opt)
        x = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        loss = model(x).sum()
        fopt.minimize(loss)
        assert net.weight.grad is not None


class TestDataParallelEager:
    def test_single_process_passthrough(self):
        net = nn.Linear(4, 2)
        dp = paddle.DataParallel(net)
        x = paddle.to_tensor(np.random.rand(3, 4).astype(np.float32))
        out = dp(x)
        assert out.shape == [3, 2]
        out.sum().backward()
        assert net.weight.grad is not None
        assert len(dp.state_dict()) == len(net.state_dict())


class TestRecomputeOffload:
    def test_remat_offload_trains(self):
        """RecomputeConfig.enable_offload parity. On the CPU test backend the
        offload custom call has no lowering, so the trainer warns and falls
        back to plain recompute; the true offload branch is verified on the
        real TPU chip (pinned_host residuals, loss descends)."""
        import jax as _jax

        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=net.parameters())
        mesh = build_mesh((1,), ("dp",), devices=_jax.devices()[:1])
        import warnings as _w

        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            trainer = SpmdTrainer(net, opt, loss_fn=nn.CrossEntropyLoss(),
                                  mesh=mesh, recompute=True, remat_offload=True)
            x = paddle.randn([8, 16])
            y = paddle.to_tensor(np.random.RandomState(0).randint(0, 4, (8,)))
            l0 = float(np.asarray(trainer.train_step(x, y)._data))
            l1 = float(np.asarray(trainer.train_step(x, y)._data))
        assert np.isfinite(l0) and l1 < l0
        # the CPU downgrade is loud, not silent
        assert any("remat_offload ignored" in str(w.message) for w in rec)


class TestDistributedHapi:
    def test_model_fit_jit_on_8dev_mesh(self):
        """dist_hapi parity: Model.fit with the whole-step SpmdTrainer adapter
        over the 8-device dp mesh."""
        from paddle_tpu.distributed.mesh import build_mesh, mesh_scope

        paddle.seed(0)
        rng = np.random.RandomState(3)
        X = rng.randn(64, 8).astype(np.float32)
        Y = rng.randint(0, 3, (64, 1)).astype(np.int64)

        class DS(paddle.io.Dataset):
            def __len__(self):
                return 64

            def __getitem__(self, i):
                return X[i], Y[i]

        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
        model = paddle.Model(net, use_jit=True)
        model.prepare(paddle.optimizer.Adam(learning_rate=3e-2,
                                            parameters=net.parameters()),
                      nn.CrossEntropyLoss(), paddle.metric.Accuracy())
        mesh = build_mesh((8,), ("dp",))
        with mesh_scope(mesh):
            hist = model.fit(DS(), epochs=6, batch_size=32, verbose=0)
        res = model.evaluate(DS(), batch_size=32, verbose=0)
        acc = res["acc"] if isinstance(res, dict) else res[-1]
        acc = float(acc[0] if isinstance(acc, (list, tuple)) else acc)
        assert acc > 0.5


class TestTracedRng:
    def test_dropout_varies_per_step_in_jitted_trainer(self):
        """Dropout inside the compiled step must draw fresh masks per step
        (trace-time keys bake ONE mask into the program)."""
        from paddle_tpu.distributed.spmd import SpmdTrainer

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.Dropout(0.5), nn.Linear(32, 2))
        opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=net.parameters())
        mesh = build_mesh((8,), ("dp",))
        tr = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(), mesh=mesh)
        x = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(16, 2).astype(np.float32))
        # lr=0 -> params frozen; loss differences come from dropout masks only
        l1 = float(tr.train_step(x, y)._data)
        l2 = float(tr.train_step(x, y)._data)
        l3 = float(tr.train_step(x, y)._data)
        assert len({round(l1, 9), round(l2, 9), round(l3, 9)}) > 1, (l1, l2, l3)

    def test_dropout_varies_in_localsgd_step(self):
        """Review r2i: localsgd/dgc paths must thread the per-step rng too."""
        from paddle_tpu.distributed.spmd import SpmdTrainer

        paddle.seed(0)
        net = nn.Sequential(nn.Linear(8, 32), nn.Dropout(0.5), nn.Linear(32, 2))
        opt = paddle.optimizer.SGD(learning_rate=0.0, parameters=net.parameters())
        mesh = build_mesh((8,), ("dp",))
        tr = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                         mesh=mesh, localsgd_k=2)
        x = paddle.to_tensor(np.random.rand(16, 8).astype(np.float32))
        y = paddle.to_tensor(np.random.rand(16, 2).astype(np.float32))
        losses = {round(float(tr.train_step(x, y)._data), 9) for _ in range(3)}
        assert len(losses) > 1, losses
