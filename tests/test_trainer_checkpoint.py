"""Trainer checkpoint/resume (SpmdTrainer + PipelineTrainer state_dict):
save mid-training, restore into a FRESH trainer, and the loss trajectory
must continue bit-exact — optimizer moments and step counters included."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

import jax


def _data(n=5, b=4, s=16, vocab=512):
    rng = np.random.RandomState(0)
    return [(rng.randint(0, vocab, (b, s)).astype(np.int32),
             rng.randint(0, vocab, (b, s)).astype(np.int32))
            for _ in range(n)]


def _make_trainer(stage=2):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=16, dropout=0.0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    mesh = build_mesh((2,), ("dp",), devices=jax.devices()[:2])
    return SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(), mesh=mesh,
                       sharding_stage=stage)


class TestSpmdCheckpoint:
    def test_resume_is_bit_exact(self, tmp_path):
        batches = _data(6)
        ref = _make_trainer()
        ref_losses = [float(np.asarray(ref.train_step(x, y)._data))
                      for x, y in batches]

        tr = _make_trainer()
        for x, y in batches[:3]:
            tr.train_step(x, y)
        path = str(tmp_path / "ckpt.pdparams")
        paddle.save(tr.state_dict(), path)

        fresh = _make_trainer()  # new arrays, step 0
        fresh.set_state_dict(paddle.load(path))
        resumed = [float(np.asarray(fresh.train_step(x, y)._data))
                   for x, y in batches[3:]]
        np.testing.assert_array_equal(np.float32(resumed),
                                      np.float32(ref_losses[3:]))

    def test_without_opt_state_trajectory_differs(self):
        """Adam moments matter: restoring only params must NOT reproduce the
        uninterrupted trajectory (guards against checkpoints that silently
        drop optimizer state)."""
        batches = _data(6)
        ref = _make_trainer()
        ref_losses = [float(np.asarray(ref.train_step(x, y)._data))
                      for x, y in batches]

        tr = _make_trainer()
        for x, y in batches[:3]:
            tr.train_step(x, y)
        state = tr.state_dict()

        fresh = _make_trainer()
        partial = dict(state)
        partial["opt_state"] = fresh.state_dict()["opt_state"]  # zeros
        partial["optimizer_step_count"] = 0
        fresh.set_state_dict(partial)
        resumed = [float(np.asarray(fresh.train_step(x, y)._data))
                   for x, y in batches[3:]]
        assert not np.allclose(resumed, ref_losses[3:])


def test_pipeline_checkpoint_resume(tmp_path):
    from paddle_tpu import optimizer as popt
    from paddle_tpu.distributed.pipeline import PipelineTrainer

    def make():
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=4,
                        num_heads=2, max_seq_len=16, dropout=0.0)
        model = GPTForCausalLM(cfg)
        pre, stages, post = model.pipeline_split(4)
        opt = popt.AdamW(learning_rate=1e-3,
                         parameters=model.parameters())
        mesh = build_mesh((4,), ("pp",), devices=jax.devices()[:4])
        return PipelineTrainer(pre, stages, post, opt, mesh=mesh, n_micro=4)

    rng = np.random.RandomState(1)
    batches = [(rng.randint(0, 256, (4, 16)).astype(np.int32),
                rng.randint(0, 256, (4, 16)).astype(np.int32))
               for _ in range(4)]

    ref = make()
    ref_losses = [float(np.asarray(ref.train_step(x, y)._data))
                  for x, y in batches]

    tr = make()
    for x, y in batches[:2]:
        tr.train_step(x, y)
    path = str(tmp_path / "pp_ckpt.pdparams")
    paddle.save(tr.state_dict(), path)

    fresh = make()
    fresh.set_state_dict(paddle.load(path))
    resumed = [float(np.asarray(fresh.train_step(x, y)._data))
               for x, y in batches[2:]]
    np.testing.assert_array_equal(np.float32(resumed),
                                  np.float32(ref_losses[2:]))


def test_lr_scheduler_state_rides_checkpoint():
    """A step-dependent LR schedule must resume at its saved position, not
    restart from warmup (review r3 finding)."""
    def make():
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=16, dropout=0.0)
        model = GPTForCausalLM(cfg)
        sched = paddle.optimizer.lr.StepDecay(learning_rate=1e-2,
                                              step_size=2, gamma=0.1)
        opt = paddle.optimizer.AdamW(learning_rate=sched,
                                     parameters=model.parameters())
        mesh = build_mesh((2,), ("dp",), devices=jax.devices()[:2])
        return SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                           mesh=mesh), sched

    batches = _data(4, vocab=256)
    tr, sched = make()
    for x, y in batches[:3]:
        tr.train_step(x, y)
        sched.step()
    lr_at_save = float(tr.optimizer.get_lr())
    state = tr.state_dict()
    assert state["lr_scheduler"], state.keys()

    fresh, fresh_sched = make()
    assert float(fresh.optimizer.get_lr()) != lr_at_save  # fresh warmup LR
    fresh.set_state_dict(state)
    np.testing.assert_allclose(float(fresh.optimizer.get_lr()), lr_at_save)


def test_stale_checkpoint_fails_fast():
    import pytest

    tr = _make_trainer()
    state = tr.state_dict()
    bad = dict(state)
    bad["params"] = {k: v for k, v in list(state["params"].items())[:-1]}
    with pytest.raises(ValueError, match="missing"):
        tr.set_state_dict(bad)
    bad2 = dict(state)
    bad2["params"] = dict(state["params"], bogus_param=np.zeros(3))
    with pytest.raises(ValueError, match="unexpected"):
        tr.set_state_dict(bad2)
