"""Real-corpus parsing for text datasets (VERDICT r1 weak #7): miniature
archives in the EXACT formats the reference downloads (aclImdb tar, PTB
simple-examples tar, ml-1m zip) parse into the reference's sample shapes."""
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text.datasets import Imdb, Imikolov, Movielens


def _add_text(tar, name, text):
    data = text.encode()
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tar.addfile(info, io.BytesIO(data))


@pytest.fixture
def aclimdb_tar(tmp_path):
    p = str(tmp_path / "aclImdb_v1.tar.gz")
    with tarfile.open(p, "w:gz") as tar:
        docs = {
            "aclImdb/train/pos/0_9.txt": "a great great movie, truly great!",
            "aclImdb/train/pos/1_8.txt": "great fun; a great watch",
            "aclImdb/train/neg/0_2.txt": "a terrible movie. terrible!",
            "aclImdb/train/neg/1_1.txt": "terrible terrible terrible pacing",
            "aclImdb/test/pos/0_10.txt": "great movie",
            "aclImdb/test/neg/0_1.txt": "terrible movie",
        }
        for name, text in docs.items():
            _add_text(tar, name, text)
    return p


class TestImdbReal:
    def test_parses_and_labels(self, aclimdb_tar):
        ds = Imdb(data_file=aclimdb_tar, mode="train", cutoff=1)
        assert len(ds) == 4
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label.shape == (1,)
        # pos docs first (label 0), then neg (label 1) — reference ordering
        labels = [int(ds[i][1][0]) for i in range(len(ds))]
        assert labels == [0, 0, 1, 1]
        # 'great'(5) and 'terrible'(6) pass cutoff=1; dict sorted by -freq
        assert b"great" in ds.word_idx and b"terrible" in ds.word_idx
        assert ds.word_idx[b"terrible"] in (0, 1)

    def test_unk_mapping(self, aclimdb_tar):
        ds = Imdb(data_file=aclimdb_tar, mode="test", cutoff=1)
        assert len(ds) == 2
        unk = ds.word_idx[b"<unk>"]
        doc0, l0 = ds[0]  # "great movie"
        assert int(l0[0]) == 0
        assert doc0[0] == ds.word_idx[b"great"]

    def test_synthetic_fallback_without_file(self):
        ds = Imdb(mode="train")
        doc, label = ds[0]
        assert doc.dtype == np.int64 and label.shape == (1,)


@pytest.fixture
def ptb_tar(tmp_path):
    p = str(tmp_path / "simple-examples.tgz")
    train = "the cat sat on the mat\nthe dog sat on the log\n"
    valid = "a cat sat\n"
    with tarfile.open(p, "w:gz") as tar:
        _add_text(tar, "./simple-examples/data/ptb.train.txt", train)
        _add_text(tar, "./simple-examples/data/ptb.valid.txt", valid)
    return p


class TestImikolovReal:
    def test_ngram_windows(self, ptb_tar):
        ds = Imikolov(data_file=ptb_tar, data_type="NGRAM", window_size=3,
                      mode="train", min_word_freq=1)
        # line of 6 words -> ids len 8 (<s>..<e>) -> 6 windows of 3; x2 lines
        assert len(ds) == 12
        src, trg = ds[0]
        assert src.shape == (2,) and trg.shape == (1,)
        assert "<s>" in ds.word_idx and "<e>" in ds.word_idx

    def test_seq_mode(self, ptb_tar):
        ds = Imikolov(data_file=ptb_tar, data_type="SEQ", mode="valid" if False else "test",
                      min_word_freq=1)
        assert len(ds) == 1  # one valid line
        src, trg = ds[0]
        # next-word pairs: trg is src shifted by one
        assert len(src) == len(trg)

    def test_min_word_freq_prunes(self, ptb_tar):
        ds = Imikolov(data_file=ptb_tar, data_type="NGRAM", window_size=2,
                      mode="train", min_word_freq=2)
        assert "cat" in ds.word_idx   # appears in train+valid
        assert "log" not in ds.word_idx  # freq 1 -> pruned to <unk>


@pytest.fixture
def ml1m_zip(tmp_path):
    p = str(tmp_path / "ml-1m.zip")
    ratings = "\n".join([
        "1::1193::5::978300760",
        "1::661::3::978302109",
        "2::1193::4::978298413",
        "3::3408::2::978300275",
    ]) + "\n"
    with zipfile.ZipFile(p, "w") as zf:
        zf.writestr("ml-1m/ratings.dat", ratings)
    return p


class TestMovielensReal:
    def test_parses_ratings(self, ml1m_zip):
        tr = Movielens(data_file=ml1m_zip, mode="train", test_ratio=0.25,
                       rand_seed=0)
        te = Movielens(data_file=ml1m_zip, mode="test", test_ratio=0.25,
                       rand_seed=0)
        assert len(tr) + len(te) == 4
        u, m, r = tr[0]
        assert u.shape == (1,) and m.shape == (1,) and r.dtype == np.float32
        all_ratings = sorted([float(tr[i][2][0]) for i in range(len(tr))]
                             + [float(te[i][2][0]) for i in range(len(te))])
        assert all_ratings == [2.0, 3.0, 4.0, 5.0]


class TestReviewRegressions:
    def test_imdb_dot_slash_prefix(self, tmp_path):
        """Review r2g: './aclImdb/...' member names must parse."""
        p = str(tmp_path / "dot.tar.gz")
        with tarfile.open(p, "w:gz") as tar:
            _add_text(tar, "./aclImdb/train/pos/0.txt", "nice film")
            _add_text(tar, "./aclImdb/train/neg/0.txt", "bad film")
        ds = Imdb(data_file=p, mode="train", cutoff=0)
        assert len(ds) == 2

    def test_imdb_wrong_archive_raises(self, tmp_path):
        p = str(tmp_path / "junk.tar.gz")
        with tarfile.open(p, "w:gz") as tar:
            _add_text(tar, "other/file.txt", "nope")
        with pytest.raises(ValueError, match="aclImdb"):
            Imdb(data_file=p, mode="train")

    def test_imikolov_seq_fallback_shapes(self):
        """Review r2g: SEQ synthetic fallback returns equal-length pair."""
        ds = Imikolov(data_type="SEQ", window_size=6)
        src, trg = ds[0]
        assert len(src) == len(trg)


@pytest.fixture
def wmt14_tar(tmp_path):
    p = str(tmp_path / "wmt14.tgz")
    src_dict = "<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = "<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    train = "hello world\tbonjour monde\nhello\tbonjour\n"
    with tarfile.open(p, "w:gz") as tar:
        _add_text(tar, "wmt14/src.dict", src_dict)
        _add_text(tar, "wmt14/trg.dict", trg_dict)
        _add_text(tar, "wmt14/train/train", train)
    return p


class TestWMT14Real:
    def test_parallel_parse(self, wmt14_tar):
        from paddle_tpu.text.datasets import WMT14

        ds = WMT14(data_file=wmt14_tar, mode="train", dict_size=5)
        assert len(ds) == 2
        src, trg, trg_next = ds[0]
        # <s> hello world <e> -> [0, 3, 4, 1]
        np.testing.assert_array_equal(src, [0, 3, 4, 1])
        # trg: <s> bonjour monde ; trg_next: bonjour monde <e>
        np.testing.assert_array_equal(trg, [0, 3, 4])
        np.testing.assert_array_equal(trg_next, [3, 4, 1])

    def test_unk_and_wmt16_passthrough(self, wmt14_tar):
        from paddle_tpu.text.datasets import WMT14, WMT16

        ds = WMT14(data_file=wmt14_tar, mode="train", dict_size=3)
        src, _, _ = ds[0]  # hello/world beyond dict_size=3 -> UNK=2
        np.testing.assert_array_equal(src, [0, 2, 2, 1])
        ds16 = WMT16(data_file=wmt14_tar, mode="train", src_dict_size=5)
        assert len(ds16) == 2

    def test_wmt14_mode_and_archive_validation(self, tmp_path):
        from paddle_tpu.text.datasets import WMT14

        with pytest.raises(AssertionError):
            WMT14(mode="valid")
        p = str(tmp_path / "nodicts.tgz")
        with tarfile.open(p, "w:gz") as tar:
            _add_text(tar, "whatever.txt", "x")
        with pytest.raises(ValueError, match="src.dict"):
            WMT14(data_file=p, mode="train")

    def test_wmt16_trg_dict_size_honored(self, tmp_path):
        from paddle_tpu.text.datasets import WMT16

        p = str(tmp_path / "w16.tgz")
        with tarfile.open(p, "w:gz") as tar:
            _add_text(tar, "d/src.dict", "<s>\n<e>\n<unk>\na\n")
            _add_text(tar, "d/trg.dict", "<s>\n<e>\n<unk>\nb\nc\n")
            _add_text(tar, "d/train/train", "a\tb c\n")
        ds = WMT16(data_file=p, mode="train", src_dict_size=3,
                   trg_dict_size=5)
        src, trg, nxt = ds[0]
        # src 'a' beyond size-3 dict -> UNK; trg 'b','c' resolved (size 5)
        np.testing.assert_array_equal(src, [0, 2, 1])
        np.testing.assert_array_equal(nxt, [3, 4, 1])


@pytest.fixture
def conll05_tar(tmp_path):
    import gzip

    words = "The\ncat\nsat\n\nDogs\nbark\n\n"
    # props: col0 = predicate lemma or '-', col i+1 = labels for predicate i
    props = ("- (A0* *\n- *) *\nsit * (V*)\n\n"
             "- (A0*)\nbark (V*)\n\n")
    wgz = tmp_path / "test.wsj.words.gz"
    pgz = tmp_path / "test.wsj.props.gz"
    with gzip.open(wgz, "wb") as f:
        f.write(words.encode())
    with gzip.open(pgz, "wb") as f:
        f.write(props.encode())
    p = str(tmp_path / "conll05st-tests.tar.gz")
    import tarfile as tfmod

    with tfmod.open(p, "w:gz") as tar:
        tar.add(str(wgz), arcname="conll05st-release/test.wsj/words/test.wsj.words.gz")
        tar.add(str(pgz), arcname="conll05st-release/test.wsj/props/test.wsj.props.gz")
    return p


class TestConll05Real:
    def test_bio_conversion_and_samples(self, conll05_tar):
        from paddle_tpu.text.datasets import Conll05st

        ds = Conll05st(data_file=conll05_tar)
        # sentence 1 has 2 predicate columns, sentence 2 has 1 -> 3 samples
        assert len(ds) == 3
        words, pred, labels = ds[0]
        assert words.dtype == np.int64 and len(words) == 3
        assert len(labels) == 3
        wd, pd, ld = ds.get_dict()
        inv_l = {v: k for k, v in ld.items()}
        # first predicate col of sentence 1: (A0* *) * -> B-A0 I-A0 O
        assert [inv_l[i] for i in labels.tolist()] == ["B-A0", "I-A0", "O"]

    def test_synthetic_fallback(self):
        from paddle_tpu.text.datasets import Conll05st

        ds = Conll05st()
        row, pred, labels = ds[0]  # same 3-tuple shape as the real path
        assert row.dtype == np.int64 and pred.shape == (1,)
        wd, pd, ld = ds.get_dict()
        assert len(ld) == 20

    def test_trailing_sentence_without_blank_line(self, tmp_path):
        """Review r2k: the final sentence must not be dropped."""
        import gzip
        import tarfile as tfmod
        from paddle_tpu.text.datasets import Conll05st

        wgz = tmp_path / "x.words.gz"
        pgz = tmp_path / "x.props.gz"
        with gzip.open(wgz, "wb") as f:
            f.write(b"Only\nsentence\n")   # NO trailing blank line
        with gzip.open(pgz, "wb") as f:
            f.write(b"- (A0*)\nrun (V*)\n")
        p = str(tmp_path / "c.tgz")
        with tfmod.open(p, "w:gz") as tar:
            tar.add(str(wgz), arcname="rel/test.wsj/words/x.words.gz")
            tar.add(str(pgz), arcname="rel/test.wsj/props/x.props.gz")
        ds = Conll05st(data_file=p)
        assert len(ds) == 1

    def test_stale_dict_file_raises(self, tmp_path, conll05_tar):
        from paddle_tpu.text.datasets import Conll05st

        bad = tmp_path / "labels.dict"
        bad.write_text("O\n")  # missing B-A0 etc.
        with pytest.raises(ValueError, match="dict/corpus mismatch"):
            Conll05st(data_file=conll05_tar, target_dict_file=str(bad))
