"""Tier-1 gate for the numerics telescope (ISSUE 9): with FLAGS_numerics
unset the trainer is EXACTLY the pre-PR trainer — the compiled step is
byte-identical (params bit-equal across processes that did / did not
ever exercise the telescope), paddle_tpu.monitor.numerics is never even
imported, no numerics_* metric series or numerics/fetch span appears,
and the per-step overhead is the same one-boolean-check bar as the
monitor/failpoints/trace/blackbox fast paths. Plus: the
tools/metrics_dump.py --numerics and tools/parity_check.py exit-code
contracts are pinned."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor, trace
from paddle_tpu.testing import failpoints

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: metric families this PR introduced — with the flag unset NONE of them
#: may grow a series on the trainer path
NUMERICS_FAMILIES = (
    "numerics_grad_norm", "numerics_param_norm", "numerics_update_ratio",
    "numerics_grad_rms", "numerics_grad_absmax", "numerics_loss",
    "numerics_nonfinite_total", "numerics_anomaly_total",
    "numerics_fetch_ms")

_PLAIN_TRAINER = (
    "import jax; jax.config.update('jax_platforms', 'cpu')\n"
    "import hashlib\n"
    "import numpy as np\n"
    "import paddle_tpu as paddle\n"
    "from paddle_tpu import nn\n"
    "from paddle_tpu.distributed.mesh import build_mesh\n"
    "from paddle_tpu.distributed.spmd import SpmdTrainer\n"
    "def run_plain():\n"
    "    paddle.seed(0)\n"
    "    net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))\n"
    "    opt = paddle.optimizer.AdamW(learning_rate=1e-3,\n"
    "        parameters=net.parameters())\n"
    "    mesh = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
    "    tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)\n"
    "    x = paddle.to_tensor(np.ones((4, 8), np.float32))\n"
    "    y = paddle.to_tensor(np.ones((4, 4), np.float32))\n"
    "    for _ in range(3):\n"
    "        tr.train_step(x, y)\n"
    "    h = hashlib.sha256()\n"
    "    for k in sorted(tr.params):\n"
    "        h.update(np.ascontiguousarray(\n"
    "            np.asarray(tr.params[k])).tobytes())\n"
    "    return h.hexdigest()\n")


def _run(code):
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


class TestInertByDefault:
    def test_plain_subprocess_never_imports_numerics_and_pins_params(
            self):
        """The structural zero-overhead pin, cross-process: a plain
        trainer run (a) never imports the telescope module and (b)
        produces byte-identical params whether or not the telescope was
        ever armed earlier in the process."""
        plain = _run(
            _PLAIN_TRAINER +
            "digest = run_plain()\n"
            "import sys\n"
            "bad = [k for k in sys.modules\n"
            "       if k == 'paddle_tpu.monitor.numerics'\n"
            "       or k == 'paddle_tpu.testing.parity']\n"
            "assert not bad, f'telescope imported eagerly: {bad}'\n"
            "print('DIGEST', digest)\n")
        exercised = _run(
            _PLAIN_TRAINER +
            # arm the telescope, run a DIFFERENT trainer under it, then
            # disarm — the plain run after must be bit-identical to the
            # never-armed process's
            "paddle.set_flags({'numerics': True,\n"
            "                  'numerics_interval': 1})\n"
            "paddle.seed(1)\n"
            "net2 = nn.Linear(4, 2)\n"
            "opt2 = paddle.optimizer.SGD(learning_rate=0.1,\n"
            "    parameters=net2.parameters())\n"
            "mesh2 = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
            "tr2 = SpmdTrainer(net2, opt2, loss_fn=nn.MSELoss(),\n"
            "                  mesh=mesh2)\n"
            "tr2.train_step(np.ones((2, 4), np.float32),\n"
            "               np.zeros((2, 2), np.float32))\n"
            "assert tr2.stats()['numerics'] is not None\n"
            "paddle.set_flags({'numerics': False})\n"
            "print('DIGEST', run_plain())\n")
        d1 = plain.split("DIGEST ")[1].split()[0]
        d2 = exercised.split("DIGEST ")[1].split()[0]
        assert d1 == d2, (
            "flag-unset trainer params drifted after the telescope was "
            "exercised in-process — the disarmed step is not the pre-PR "
            "step")

    def test_flag_unset_zero_series_and_spans(self):
        """In-process form: a flag-unset trainer run moves no numerics_*
        series and emits no numerics/fetch span even with tracing on."""
        import jax

        from paddle_tpu import nn
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        monitor.reset()
        trace.clear()
        trace.enable()
        try:
            paddle.seed(0)
            net = nn.Linear(8, 4)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
            tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
            for _ in range(2):
                tr.train_step(np.ones((4, 8), np.float32),
                              np.zeros((4, 4), np.float32))
        finally:
            trace.disable()
        reg = monitor.default_registry()
        for family in NUMERICS_FAMILIES:
            metric = reg.get(family)
            assert metric is None or all(
                (s.count if hasattr(s, "count") and s.kind == "histogram"
                 else s.value) == 0
                for s in metric.series()), family
        assert "numerics/fetch" not in {s.name for s in trace.spans()}
        assert tr.stats()["numerics"] is None
        # the trainer's own span family is intact
        assert "train_step" in {s.name for s in trace.spans()}

    def test_disarmed_overhead_under_5us(self):
        """The flag-unset per-step additions are one flag lookup
        (_numerics_active) and one disabled transform() — both bounded
        at the same bar as every other disabled fast path."""
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            flags.get_flag("numerics")
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0, (
            f"numerics flag check costs {per_call_us:.2f}us/call")
        batch = [np.ones(4, np.float32)]
        failpoints.reset()
        t0 = time.perf_counter()
        for _ in range(n):
            failpoints.transform("trainer/batch", batch)
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0, (
            f"disarmed transform costs {per_call_us:.2f}us/call — the "
            "one-boolean fast path regressed")

    def test_lazy_attrs_not_star_exported(self):
        """The lazy numerics/parity attributes must stay OUT of
        __all__ — `from ... import *` resolves every listed name, which
        would import the telescope in a plain process."""
        import paddle_tpu.monitor as mon
        import paddle_tpu.testing as testing_pkg

        assert "numerics" not in mon.__all__
        assert "parity" not in testing_pkg.__all__

    def test_define_flag_preserves_pre_set_values(self):
        """Detector flags live in the lazily-imported module: a
        set_flags() made BEFORE that import must survive the module's
        own define_flag calls."""
        probe = "numerics_gate_probe_flag"
        try:
            paddle.set_flags({probe: 17})
            assert flags.define_flag(probe, 3, "probe") == 17
            assert flags.get_flag(probe) == 17
            assert flags._REGISTRY[probe]["default"] == 3
        finally:
            flags._REGISTRY.pop(probe, None)

    def test_registrations(self):
        """The trainer/batch site and the scale action are registered;
        arming a typo still fails fast."""
        assert "trainer/batch" in failpoints.SITES
        failpoints.arm("trainer/batch", "scale:2")
        try:
            assert failpoints.armed() == {"trainer/batch": "scale:2"}
        finally:
            failpoints.reset()
        with pytest.raises(ValueError):
            failpoints.arm("trainer/batch", "scale")
        assert flags.get_flag("numerics") is not None   # flag defined
        assert flags.get_flag("numerics_interval") == 1


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestNumericsToolGate:
    def test_metrics_dump_numerics_missing_metrics_exits_1(
            self, capsys, monkeypatch):
        md = _load_tool("metrics_dump")
        monkeypatch.setattr(md, "run_numerics_loop", lambda **kw: None)
        rc = md.main(["--numerics", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        missing = {f["message"].split("'")[1]
                   for f in report["targets"]["numerics"]["findings"]
                   if f["pass"] == "metrics-present"}
        assert "numerics_grad_norm" in missing
        assert "numerics_anomaly_total" in missing

    @pytest.mark.slow
    def test_metrics_dump_numerics_green_subprocess(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "metrics_dump.py"),
             "--numerics", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]

    def test_parity_check_identical_ab_exits_0(self, capsys):
        """The acceptance-criterion pin: an identical-config A/B (the
        PR 4 guard's bit-exact contract) exits 0."""
        pc = _load_tool("parity_check")
        rc = pc.main(["--ab", "check_nan_inf", "--steps", "2", "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["tool"] == "parity_check"
        assert report["totals"]["error"] == 0
        assert report["targets"]["check_nan_inf"]["report"][
            "max_abs_loss_diff"] == 0.0

    def test_parity_check_injected_divergence_exits_1_naming_stat(
            self, capsys):
        pc = _load_tool("parity_check")
        rc = pc.main(["--perturb-lr", "8", "--steps", "2", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        errs = [f for f in report["targets"]["perturb_lr"]["findings"]
                if f["severity"] == "error"]
        assert errs and "step" in errs[0]["message"]
        d = report["targets"]["perturb_lr"]["report"]["first_divergence"]
        assert d is not None and d["stat"]
        assert d["stat"] in errs[0]["message"]

    def test_parity_check_no_target_is_an_error(self):
        pc = _load_tool("parity_check")
        with pytest.raises(SystemExit):
            pc.main(["--json"])

    def test_chaos_numerics_pass_registered(self):
        cc = _load_tool("chaos_check")
        assert "numerics_anomaly" in cc.PASSES
