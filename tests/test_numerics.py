"""Numerics telescope unit coverage (ISSUE 9): fused on-device stat
correctness vs numpy on known tensors, drift-detector positive/negative
cases, history-ring bounds, blackbox-bundle inclusion, trainer/federated
integration, and the lockstep A/B parity harness."""
import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import monitor, nn, trace
from paddle_tpu.monitor import blackbox, numerics
from paddle_tpu.testing import failpoints as fp
from paddle_tpu.testing import parity


@pytest.fixture(autouse=True)
def _clean():
    monitor.reset()
    fp.reset()
    yield
    paddle.set_flags({"numerics": False, "numerics_interval": 1,
                      "check_nan_inf": False})
    monitor.reset()
    fp.reset()


def _mesh1():
    from paddle_tpu.distributed.mesh import build_mesh

    return build_mesh((1,), ("dp",), devices=jax.devices()[:1])


def _linear_trainer(lr=0.05, model_dims=(8, 4)):
    from paddle_tpu.distributed.spmd import SpmdTrainer

    paddle.seed(0)
    model = nn.Linear(*model_dims)
    opt = paddle.optimizer.SGD(learning_rate=lr,
                               parameters=model.parameters())
    return SpmdTrainer(model, opt, loss_fn=nn.MSELoss(), mesh=_mesh1())


def _batch(rows=4, din=8, dout=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(rows, din).astype(np.float32),
            rng.randn(rows, dout).astype(np.float32))


class TestDeviceStats:
    """The fused aggregation agrees with numpy on known tensors."""

    def test_stats_match_numpy(self):
        g = np.array([[3.0, -4.0], [0.5, 0.0]], np.float32)
        old = np.array([[1.0, 1.0], [1.0, 1.0]], np.float32)
        new = np.array([[1.1, 0.9], [1.0, 1.0]], np.float32)
        out = numerics.device_stats(
            ["w"], jnp.float32(2.5), {"w": jnp.asarray(g)},
            {"w": jnp.asarray(old)}, {"w": jnp.asarray(new)})
        assert set(out) == set(numerics.STAT_KEYS)
        np.testing.assert_allclose(out["grad_norm"],
                                   [np.linalg.norm(g)], rtol=1e-6)
        np.testing.assert_allclose(out["grad_rms"],
                                   [np.sqrt(np.mean(g ** 2))], rtol=1e-6)
        np.testing.assert_allclose(out["grad_absmax"], [4.0])
        np.testing.assert_allclose(out["grad_max"], [3.0])
        np.testing.assert_allclose(out["nonfinite"], [0.0])
        np.testing.assert_allclose(out["param_norm"],
                                   [np.linalg.norm(new)], rtol=1e-6)
        upd = np.linalg.norm(new - old)
        np.testing.assert_allclose(out["update_norm"], [upd], rtol=1e-6)
        np.testing.assert_allclose(
            out["update_ratio"], [upd / (np.linalg.norm(new) + 1e-12)],
            rtol=1e-6)
        np.testing.assert_allclose(
            out["quantiles"][0],
            np.quantile(np.abs(g).ravel(), numerics.QUANTILES), rtol=1e-5)
        np.testing.assert_allclose(out["loss"], 2.5)

    def test_nonfinite_counts_elements(self):
        g = np.array([np.nan, np.inf, 1.0, -np.inf], np.float32)
        p = np.ones(4, np.float32)
        out = numerics.device_stats(
            ["w"], jnp.float32(0.0), {"w": jnp.asarray(g)},
            {"w": jnp.asarray(p)}, {"w": jnp.asarray(p)})
        assert float(out["nonfinite"][0]) == 3.0

    def test_multi_layer_rows_follow_name_order(self):
        gs = {"a": jnp.ones((2,)), "b": jnp.full((3,), 2.0)}
        ps = {"a": jnp.zeros((2,)), "b": jnp.zeros((3,))}
        out = numerics.device_stats(["b", "a"], jnp.float32(0.0),
                                    gs, ps, ps)
        np.testing.assert_allclose(
            out["grad_norm"],
            [np.linalg.norm([2.0] * 3), np.linalg.norm([1.0] * 2)],
            rtol=1e-6)

    def test_digest_subsample_spans_the_whole_tensor(self):
        """Just past the cap, the stride must still cover the tail — a
        floor stride would quietly sample only the tensor's prefix."""
        n = numerics.DIGEST_CAP + 10
        src = np.asarray(numerics._digest_source(jnp.arange(n)))
        assert len(src) <= numerics.DIGEST_CAP
        assert src.max() >= n - 2   # the sample reaches the tail

    def test_digest_subsample_is_deterministic(self):
        rng = np.random.RandomState(0)
        g = rng.randn(numerics.DIGEST_CAP * 4).astype(np.float32)
        p = np.zeros_like(g)
        a = numerics.device_stats(["w"], jnp.float32(0.0),
                                  {"w": jnp.asarray(g)},
                                  {"w": jnp.asarray(p)},
                                  {"w": jnp.asarray(p)})
        b = numerics.device_stats(["w"], jnp.float32(0.0),
                                  {"w": jnp.asarray(g)},
                                  {"w": jnp.asarray(p)},
                                  {"w": jnp.asarray(p)})
        np.testing.assert_array_equal(np.asarray(a["quantiles"]),
                                      np.asarray(b["quantiles"]))


def _obs(gn=1.0, ratio=0.01, pn=10.0, nonf=0.0, loss=None):
    host = {"grad_norm": np.asarray([gn], np.float32),
            "update_ratio": np.asarray([ratio], np.float32),
            "param_norm": np.asarray([pn], np.float32),
            "nonfinite": np.asarray([nonf], np.float32)}
    if loss is not None:
        host["loss"] = np.float32(loss)
    return host


class TestDetectors:
    def test_grad_spike_fires_and_names_layer(self):
        mon = numerics.NumericsMonitor(["lyr"])
        for i in range(5):
            assert mon.observe(_obs(gn=1.0 + 0.01 * i), step=i) == []
        fired = mon.observe(_obs(gn=100.0), step=5)
        kinds = {(a["kind"], a["layer"]) for a in fired}
        assert ("grad_spike", "lyr") in kinds
        reg = monitor.default_registry().get("numerics_anomaly_total")
        assert reg.labels(kind="grad_spike", layer="lyr").value == 1

    def test_steady_training_never_fires(self):
        mon = numerics.NumericsMonitor(["lyr"])
        rng = np.random.RandomState(0)
        for i in range(30):
            fired = mon.observe(
                _obs(gn=1.0 + 0.05 * rng.randn(),
                     loss=2.0 - 0.05 * i), step=i)
            assert fired == [], fired

    def test_spike_needs_baseline_warmup(self):
        mon = numerics.NumericsMonitor(["lyr"])
        fired = mon.observe(_obs(gn=1000.0), step=0)
        assert not any(a["kind"] == "grad_spike" for a in fired)

    def test_dead_layer_streak_fires_once_and_rearms(self):
        paddle.set_flags({"numerics_dead_steps": 3})
        try:
            mon = numerics.NumericsMonitor(["lyr"])
            fired = []
            for i in range(5):
                fired += mon.observe(_obs(gn=0.0), step=i)
            dead = [a for a in fired if a["kind"] == "dead_layer"]
            assert len(dead) == 1 and dead[0]["layer"] == "lyr"
            mon.observe(_obs(gn=1.0), step=5)   # recovery resets streak
            fired = []
            for i in range(6, 9):
                fired += mon.observe(_obs(gn=0.0), step=i)
            assert sum(a["kind"] == "dead_layer" for a in fired) == 1
        finally:
            paddle.set_flags({"numerics_dead_steps": 3})

    def test_update_ratio_band(self):
        mon = numerics.NumericsMonitor(["lyr"])
        for i in range(4):
            assert mon.observe(_obs(ratio=0.01), step=i) == []
        fired = mon.observe(_obs(ratio=0.9), step=4)
        assert any(a["kind"] == "update_ratio" for a in fired)

    def test_update_ratio_ignores_fresh_zeroish_params(self):
        """A fresh zero-init param runs O(1) ratios through warmup — the
        rule must not cry wolf on it."""
        mon = numerics.NumericsMonitor(["bias"])
        fired = []
        for i, r in enumerate((1.0, 0.5, 0.35, 0.3)):
            fired += mon.observe(_obs(ratio=r, pn=0.05 * (i + 1)),
                                 step=i)
        assert not any(a["kind"] == "update_ratio" for a in fired), fired

    def test_nonfinite_fires_and_counts_elements(self):
        mon = numerics.NumericsMonitor(["lyr"])
        fired = mon.observe(_obs(gn=float("nan"), nonf=7.0), step=0)
        assert any(a["kind"] == "nonfinite" for a in fired)
        reg = monitor.default_registry().get("numerics_nonfinite_total")
        assert reg.labels(layer="lyr").value == 7.0

    def test_loss_plateau_fires_once_per_episode(self):
        paddle.set_flags({"numerics_plateau_window": 4})
        try:
            mon = numerics.NumericsMonitor(["lyr"])
            fired = []
            for i in range(8):
                fired += mon.observe(_obs(loss=1.2345), step=i)
            plateaus = [a for a in fired if a["kind"] == "loss_plateau"]
            assert len(plateaus) == 1 and plateaus[0]["layer"] == "loss"
            # motion clears the episode; a second flat stretch re-fires
            for i in range(8, 12):
                mon.observe(_obs(loss=1.0 - 0.2 * i), step=i)
            fired = []
            for i in range(12, 18):
                fired += mon.observe(_obs(loss=0.5), step=i)
            assert sum(a["kind"] == "loss_plateau" for a in fired) == 1
        finally:
            paddle.set_flags({"numerics_plateau_window": 8})

    def test_loss_plateau_window_clamped_to_history(self):
        """A window larger than the ring could never fill — the rule
        clamps to ring capacity instead of going silently dead."""
        paddle.set_flags({"numerics_history": 4,
                          "numerics_plateau_window": 64})
        try:
            mon = numerics.NumericsMonitor(["lyr"])
            fired = []
            for i in range(6):
                fired += mon.observe(_obs(loss=3.14), step=i)
            assert any(a["kind"] == "loss_plateau" for a in fired)
        finally:
            paddle.set_flags({"numerics_history": 64,
                              "numerics_plateau_window": 8})

    def test_history_ring_is_bounded(self):
        paddle.set_flags({"numerics_history": 8})
        try:
            mon = numerics.NumericsMonitor(["lyr"])
            for i in range(50):
                mon.observe(_obs(gn=float(i)), step=i)
            ring = mon.history("lyr", "grad_norm")
            assert len(ring) == 8
            assert ring[-1] == 49.0
            assert len(mon.anomalies) <= 64
        finally:
            paddle.set_flags({"numerics_history": 64})

    def test_snapshot_is_json_able(self):
        import json

        mon = numerics.NumericsMonitor(["lyr"])
        mon.observe(_obs(gn=float("nan"), nonf=1.0, loss=2.0), step=0)
        snap = mon.snapshot()
        assert snap["layers"]["lyr"]["nonfinite"] == 1.0
        json.dumps(snap, default=str)   # must not raise


class TestBlackboxInclusion:
    def test_bundle_carries_numerics_snapshot(self, tmp_path):
        was = blackbox.is_enabled()
        blackbox.enable(install=False)
        try:
            mon = numerics.NumericsMonitor(["lyr"], source="test")
            mon.observe(_obs(gn=3.0, loss=1.5), step=7)
            path = blackbox.dump("signal", site="test",
                                 dir_=str(tmp_path))
            bundle = blackbox.load_bundle(path)
            tables = [t for t in bundle["requests"]
                      if t.get("kind") == "numerics"]
            assert tables, bundle["requests"]
            table = tables[-1]["table"]
            assert table["source"] == "test"
            assert table["layers"]["lyr"]["grad_norm"] == 3.0
        finally:
            blackbox.reset()
            if not was:
                blackbox.disable()

    def test_anomaly_lands_in_flight_recorder_ring(self):
        was = blackbox.is_enabled()
        blackbox.enable(install=False)
        try:
            mon = numerics.NumericsMonitor(["lyr"])
            mon.observe(_obs(nonf=2.0), step=0)
            kinds = [r for r in blackbox.ring()
                     if r["kind"] == "numerics_anomaly"]
            assert kinds and kinds[-1]["rule"] == "nonfinite"
            assert kinds[-1]["layer"] == "lyr"
        finally:
            blackbox.reset()
            if not was:
                blackbox.disable()


class TestTrainerIntegration:
    def test_interval_batches_host_fetches(self):
        paddle.set_flags({"numerics": True, "numerics_interval": 3})
        tr = _linear_trainer()
        x, y = _batch()
        tr.train_step(x, y)
        tr.train_step(x, y)
        assert tr.stats()["numerics"] is None      # no fetch yet
        tr.train_step(x, y)                        # 3rd step: fetch
        snap = tr.stats()["numerics"]
        assert snap is not None and snap["fetches"] == 1
        assert set(snap["layers"]) == {"weight", "bias"}

    def test_fetch_span_and_metric_families(self):
        paddle.set_flags({"numerics": True, "numerics_interval": 1})
        trace.clear()
        trace.enable()
        try:
            tr = _linear_trainer()
            x, y = _batch()
            tr.train_step(x, y)
        finally:
            trace.disable()
        assert "numerics/fetch" in {s.name for s in trace.spans()}
        reg = monitor.default_registry()
        for fam in ("numerics_grad_norm", "numerics_update_ratio",
                    "numerics_param_norm", "numerics_fetch_ms"):
            metric = reg.get(fam)
            assert metric is not None and list(metric.series()), fam

    def test_stats_rows_align_with_sorted_param_names(self):
        """The jit returns dict pytrees key-sorted; the telescope's row
        order must match its layer-name order regardless."""
        paddle.set_flags({"numerics": True, "numerics_interval": 1})
        tr = _linear_trainer()
        x, y = _batch()
        for _ in range(2):
            tr.train_step(x, y)
        snap = tr.stats()["numerics"]
        host = tr.numerics_fetch()
        layers = sorted(tr.params)
        for i, name in enumerate(layers):
            assert snap["layers"][name]["grad_norm"] == pytest.approx(
                float(host["grad_norm"][i]))
        # the bias (dim 4) and weight (8x4) have different param norms —
        # misaligned rows would swap these
        w = np.asarray(tr.params["weight"])
        assert snap["layers"]["weight"]["param_norm"] == pytest.approx(
            float(np.linalg.norm(w)), rel=1e-5)

    def test_numerics_fetch_idempotent_per_step(self):
        paddle.set_flags({"numerics": True, "numerics_interval": 1})
        tr = _linear_trainer()
        x, y = _batch()
        tr.train_step(x, y)
        assert tr.stats()["numerics"]["fetches"] == 1
        tr.numerics_fetch()
        tr.numerics_fetch()
        assert tr.stats()["numerics"]["fetches"] == 1   # no re-observe

    def test_guarded_step_reports_poisoned_layers(self):
        """check_nan_inf + numerics: the skipped step still fetches
        stats naming WHICH layer went non-finite."""
        paddle.set_flags({"numerics": True, "numerics_interval": 1,
                          "check_nan_inf": True})
        tr = _linear_trainer()
        x, y = _batch()
        for _ in range(2):
            tr.train_step(x, y)
        with fp.scoped("trainer/batch=scale:nan"):
            tr.train_step(x, y)
        snap = tr.stats()["numerics"]
        assert tr.stats()["breakdown"]["nonfinite_skipped_total"] == 1
        nonf = [a for a in snap["anomalies"]
                if a["kind"] == "nonfinite"]
        assert nonf and all(a["layer"] in ("weight", "bias")
                            for a in nonf)
        # anomalies carry the OPTIMIZER step clock (same as the spans),
        # even though the guard skip did not advance it
        assert all(a["step"] == tr.optimizer._step_count for a in nonf)

    def test_spike_detector_fires_before_guard(self):
        """The chaos_check numerics_anomaly scenario in unit form: a
        finite 1e4x spike fires the detector while the guard stays
        silent; the nan step after trips the guard."""
        paddle.set_flags({"numerics": True, "numerics_interval": 1,
                          "check_nan_inf": True})
        tr = _linear_trainer()
        x, y = _batch()
        for _ in range(4):
            tr.train_step(x, y)
        assert not tr._numerics.anomalies
        with fp.scoped("trainer/batch=scale:10000"):
            tr.train_step(x, y)
        assert any(a["kind"] == "grad_spike"
                   for a in tr._numerics.anomalies)
        assert tr.stats()["breakdown"]["nonfinite_skipped_total"] == 0
        with fp.scoped("trainer/batch=scale:nan"):
            tr.train_step(x, y)
        assert tr.stats()["breakdown"]["nonfinite_skipped_total"] == 1

    def test_toggling_flag_recompiles_not_misunpacks(self):
        tr = _linear_trainer()
        x, y = _batch()
        tr.train_step(x, y)
        paddle.set_flags({"numerics": True, "numerics_interval": 1})
        tr.train_step(x, y)          # new exec key: recompile, no crash
        assert tr.stats()["numerics"]["fetches"] == 1
        paddle.set_flags({"numerics": False})
        loss = tr.train_step(x, y)
        assert math.isfinite(float(np.asarray(loss._data)))


class TestFailpointScaleAction:
    def test_parse_and_spec_roundtrip(self):
        acts = fp.parse("trainer/batch=scale:2.5")
        assert acts["trainer/batch"].spec() == "scale:2.5"
        acts = fp.parse("trainer/batch=scale:nan")
        assert math.isnan(acts["trainer/batch"].arg)
        with pytest.raises(ValueError):
            fp.parse("trainer/batch=scale")

    def test_transform_scales_floats_only(self):
        with fp.scoped("trainer/batch=scale:2"):
            out = fp.transform("trainer/batch",
                               [np.ones(3, np.float32),
                                np.ones(3, np.int32)])
        np.testing.assert_array_equal(out[0], 2 * np.ones(3))
        np.testing.assert_array_equal(out[1], np.ones(3, np.int32))
        assert out[1].dtype == np.int32
        assert fp.hits("trainer/batch") == 1

    def test_transform_disarmed_is_identity(self):
        x = [np.ones(3, np.float32)]
        out = fp.transform("trainer/batch", x)
        assert out is x

    def test_transform_fires_error_actions_too(self):
        with fp.scoped("trainer/batch=error:1"):
            with pytest.raises(fp.FailpointError):
                fp.transform("trainer/batch", [np.ones(2)])

    def test_plain_failpoint_ignores_scale(self):
        with fp.scoped("trainer/batch=scale:3"):
            fp.failpoint("trainer/batch")   # must not raise or consume
            assert fp.hits("trainer/batch") == 0


class TestFederatedWiring:
    def _averager(self):
        from paddle_tpu.federated import FederatedAverager

        paddle.seed(0)
        rng = np.random.RandomState(0)
        net = nn.Linear(6, 3)
        X = rng.randn(8, 6).astype(np.float32)
        Y = rng.randn(8, 3).astype(np.float32)
        data = [[(X[:4], Y[:4])], [(X[4:], Y[4:])]]
        return FederatedAverager(net, nn.MSELoss(), data, local_steps=1,
                                 local_lr=0.05, seed=0)

    def test_round_reports_through_numerics_path(self):
        paddle.set_flags({"numerics": True})
        fed = self._averager()
        fed.run(2)
        snap = fed._numerics.snapshot()
        assert snap["source"] == "federated"
        row = snap["layers"]["federated/round"]
        assert row["grad_norm"] > 0 and 0 <= row["update_ratio"] < 1
        reg = monitor.default_registry().get("numerics_update_ratio")
        assert reg.labels(layer="federated/round").value == pytest.approx(
            row["update_ratio"])

    def test_plain_round_stays_dark(self):
        fed = self._averager()
        fed.run(1)
        assert fed._numerics is None
        reg = monitor.default_registry().get("numerics_update_ratio")
        assert reg is None or not any(
            s.labels.get("layer") == "federated/round"
            for s in reg.series())


class TestParityHarness:
    def _build(self, lr=0.05):
        def f():
            return _linear_trainer(lr=lr)
        return f

    def _batches(self, n=3):
        return [_batch(seed=i) for i in range(n)]

    def test_identical_configs_pass_exact(self):
        report = parity.run_parity(self._build(), self._batches(),
                                   loss_rtol=0.0, loss_atol=0.0)
        assert not report["diverged"]
        assert report["max_abs_loss_diff"] == 0.0
        assert parity.assert_parity(report) is report

    def test_lr_perturbation_diverges_and_names_step_stat(self):
        report = parity.run_parity(
            self._build(), self._batches(),
            build_candidate=self._build(lr=0.5),
            loss_rtol=0.0, loss_atol=0.0)
        assert report["diverged"]
        d = report["first_divergence"]
        assert d["stat"] in ("loss",) + parity.STAT_COMPARE_KEYS
        with pytest.raises(parity.ParityDivergence) as e:
            parity.assert_parity(report)
        assert f"step {d['step']}" in str(e.value)
        assert d["stat"] in str(e.value)

    def test_declared_band_absorbs_small_divergence(self):
        report = parity.run_parity(
            self._build(), self._batches(),
            build_candidate=self._build(lr=0.05000001),
            loss_rtol=1e-3, loss_atol=1e-3, stat_rtol=0.05,
            stat_atol=0.05)
        assert not report["diverged"], report["first_divergence"]

    def test_flag_scope_undefines_introduced_flags(self):
        """A flag the scope INTRODUCED (its defining module not yet
        loaded) must be un-defined on exit — otherwise one side's
        candidate config would survive define_flag's existing-value-wins
        rule and leak into the other side."""
        from paddle_tpu import flags

        probe = "parity_probe_lazy_flag"
        assert probe not in flags._REGISTRY
        with parity.flag_scope({probe: 9}):
            assert flags.get_flag(probe) == 9
        assert probe not in flags._REGISTRY
        assert flags.get_flag(probe) is None

    def test_flag_scope_restores(self):
        from paddle_tpu import flags

        before = flags.get_flag("numerics")
        with parity.flag_scope({"numerics": True,
                                "FLAGS_numerics_interval": 7}):
            assert flags.get_flag("numerics") is True
            assert flags.get_flag("numerics_interval") == 7
        assert flags.get_flag("numerics") == before
        assert flags.get_flag("numerics_interval") == 1

    def test_harness_leaves_numerics_flag_unset(self):
        from paddle_tpu import flags

        parity.run_lockstep(self._build(), self._batches(1))
        assert not flags.get_flag("numerics")
