"""Tier-1 router gate: the multi-engine tier costs a plain single-engine
deployment NOTHING when no Router/DisaggregatedPool is constructed.

Pins (ISSUE 6 satellite):
 - constructing + running a plain ServingEngine never imports
   serving/router.py or serving/disagg.py (lazy package surface);
 - a plain engine run leaves ZERO router/kv_handoff metric series and
   ZERO route/kv_handoff spans;
 - the engine's idle step() stays host-cheap (the handoff queue adds one
   empty-list truthiness check);
 - tools/{trace_dump,metrics_dump}.py --router exit 1 when the router
   span/metric families are missing (the CI contract in executable form).
"""
import importlib.util
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, trace
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestZeroOverheadSingleEngine:
    def test_plain_engine_never_imports_router(self):
        """The structural form of 'zero overhead': no Router constructed
        -> the router/disagg modules are never even imported (and with
        them, none of their metric registrations)."""
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "import numpy as np\n"
            "import paddle_tpu as paddle\n"
            "from paddle_tpu.inference.serving import ServingEngine\n"
            "from paddle_tpu.models import GPTConfig, GPTForCausalLM\n"
            "paddle.seed(0)\n"
            "m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,\n"
            "    num_layers=1, num_heads=2, max_seq_len=32, dropout=0.0))\n"
            "m.eval()\n"
            "eng = ServingEngine(m, max_batch=1)\n"
            "eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=2)\n"
            "eng.run_until_complete()\n"
            "import sys\n"
            "bad = [k for k in sys.modules if k in (\n"
            "    'paddle_tpu.serving.router', 'paddle_tpu.serving.disagg')]\n"
            "assert not bad, f'router tier imported eagerly: {bad}'\n"
            "print('LAZY_OK')\n")
        out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                             capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
        assert "LAZY_OK" in out.stdout

    def test_plain_engine_zero_router_metrics_and_spans(self):
        monitor.reset()
        trace.clear()
        trace.enable()
        try:
            m = _model()
            eng = ServingEngine(m, max_batch=2)
            rng = np.random.RandomState(0)
            for n in (4, 7):
                eng.submit(rng.randint(0, 64, (n,)).astype(np.int32),
                           max_new_tokens=3)
            eng.run_until_complete()
        finally:
            trace.disable()
        flat = monitor.flatten(monitor.snapshot())
        # zeroed () series can survive monitor.reset() when an earlier
        # in-process test imported the router tier — zero overhead means
        # nothing was RECORDED by the plain engine run
        leaked = {k: v for k, v in flat.items()
                  if k.startswith(("router_", "kv_handoff"))
                  and (v["count"] if isinstance(v, dict) else v)}
        assert not leaked, leaked
        names = {s.name for s in trace.spans()}
        assert not names & {"route", "kv_handoff"}, names
        # the engine's own families are intact (the refactor onto the
        # DecodeModel registry changed no instrumentation)
        assert {"request", "queue_wait", "prefill", "decode"} <= names
        assert eng.stats()["requests"]["handoff"] == 0

    def test_idle_step_host_cost(self):
        """An idle engine step is pure host bookkeeping; the handoff
        queue must not add measurable work to it. 500us/step is ~100x
        the expected cost — loose enough for CI noise, far below any
        real decode step."""
        m = _model()
        eng = ServingEngine(m, max_batch=2)
        eng.step()   # one-time lazies out of the way
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            eng.step()
        per_step_us = (time.perf_counter() - t0) / n * 1e6
        assert per_step_us < 500.0, (
            f"idle step costs {per_step_us:.1f}us — the single-engine "
            "hot path regressed")


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestRouterToolGates:
    def test_trace_dump_router_missing_spans_exits_1(self, capsys,
                                                     monkeypatch):
        td = _load_tool("trace_dump")
        monkeypatch.setattr(trace, "enable", lambda: None)
        rc = td.main(["--router", "--json"])
        assert rc == 1
        import json

        report = json.loads(capsys.readouterr().out)
        missing = {f["message"].split("'")[1]
                   for f in report["targets"]["router"]["findings"]
                   if f["pass"] == "spans-present"}
        assert {"route", "kv_handoff"} <= missing

    def test_metrics_dump_router_missing_metrics_exits_1(self, capsys,
                                                         monkeypatch):
        md = _load_tool("metrics_dump")
        monkeypatch.setattr(md, "run_router_loop", lambda **kw: None)
        rc = md.main(["--router", "--json"])
        assert rc == 1
        import json

        report = json.loads(capsys.readouterr().out)
        missing = {f["message"].split("'")[1]
                   for f in report["targets"]["router"]["findings"]
                   if f["pass"] == "metrics-present"}
        # router_requests_total is labeled, so monitor.reset() drops its
        # series entirely; unlabeled families may survive as zeroed ()
        # series when an earlier in-process test touched them
        assert "router_requests_total" in missing

    @pytest.mark.slow
    def test_router_tools_green_end_to_end(self):
        """Subprocess CI form: both --router tools run clean at HEAD."""
        for tool in ("trace_dump", "metrics_dump"):
            out = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools",
                                              f"{tool}.py"),
                 "--router", "--json"],
                cwd=REPO, capture_output=True, text=True, timeout=560)
            assert out.returncode == 0, (tool, out.stderr[-2000:])
