"""paddle.onnx.export — real ONNX emission (reference onnx/export.py:21).

The exporter traces the layer to a jaxpr, lowers to ONNX opset-13 ops,
hand-emits the protobuf wire format, then parses the file back and
re-executes it in pure numpy against the layer's own output (1e-5).
These tests drive that pipeline over the flagship model families and the
failure contract (unsupported primitive -> loud error, no .onnx written).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.onnx import proto, runtime
from paddle_tpu.onnx.converter import UnsupportedOpError
from paddle_tpu.static import InputSpec


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(0)


def _layer_out(layer, x_np):
    layer.eval()
    out = layer(paddle.to_tensor(x_np))
    return np.asarray(out._data)


class TestWireFormat:
    def test_tensor_roundtrip(self):
        rng = np.random.RandomState(0)
        for arr in (rng.rand(3, 4).astype(np.float32),
                    rng.randint(0, 9, (2, 5)).astype(np.int64),
                    np.asarray(True),
                    rng.rand(1).astype(np.float16)):
            name, back = proto.parse_tensor(proto.tensor_proto("w", arr))
            assert name == "w"
            assert back.dtype == arr.dtype and back.shape == arr.shape
            np.testing.assert_array_equal(back, arr)

    def test_attribute_roundtrip(self):
        for val in (3, -7, 2.5, [1, 2, 3], b"constant"):
            k, v = proto.parse_attribute(proto.attribute("a", val))
            assert k == "a"
            if isinstance(val, float):
                assert abs(v - val) < 1e-7
            else:
                assert v == val

    def test_negative_int_varint(self):
        k, v = proto.parse_attribute(proto.attribute("axis", -1))
        assert v == -1


class TestLeNetExport:
    def test_export_parses_and_reexecutes(self, tmp_path):
        from paddle_tpu.vision.models import LeNet

        m = LeNet()
        p = paddle.onnx.export(
            m, str(tmp_path / "lenet"),
            input_spec=[InputSpec([1, 1, 28, 28], "float32")])
        assert p.endswith(".onnx") and os.path.getsize(p) > 1000
        model = proto.parse_model(open(p, "rb").read())
        assert model["opset"] == 13
        ops = {n["op_type"] for n in model["graph"]["nodes"]}
        # conv stack lowered to the standard op set, Relu as Max(x, 0)
        assert {"Conv", "MaxPool", "MatMul", "Add", "Max"} <= ops
        # independent check on FRESH input (not the export's example)
        rng = np.random.RandomState(7)
        x = rng.rand(1, 1, 28, 28).astype(np.float32)
        expect = _layer_out(m, x)
        (got,) = runtime.run(open(p, "rb").read(),
                             {model["graph"]["inputs"][0]["name"]: x})
        np.testing.assert_allclose(got, expect, atol=1e-5, rtol=1e-5)


class TestResNetExport:
    def test_resnet18_validates(self, tmp_path):
        from paddle_tpu.vision.models import resnet18

        m = resnet18(num_classes=10)
        p = paddle.onnx.export(
            m, str(tmp_path / "resnet18"),
            input_spec=[InputSpec([1, 3, 32, 32], "float32")])
        model = proto.parse_model(open(p, "rb").read())
        ops = {n["op_type"] for n in model["graph"]["nodes"]}
        assert "Conv" in ops and "MaxPool" in ops
        out = model["graph"]["outputs"][0]
        assert out["shape"] == [1, 10]


class TestGPTExport:
    def test_gpt_block_validates(self, tmp_path):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32, dropout=0.0)
        m = GPTForCausalLM(cfg)
        p = paddle.onnx.export(m, str(tmp_path / "gpt"),
                               input_spec=[InputSpec([1, 16], "int32")])
        model = proto.parse_model(open(p, "rb").read())
        ops = {n["op_type"] for n in model["graph"]["nodes"]}
        # embedding Gather, attention MatMuls, gelu Erf, softmax chain
        assert {"Gather", "MatMul", "Erf", "Exp", "ReduceSum",
                "ReduceMax"} <= ops
        assert model["graph"]["outputs"][0]["shape"] == [1, 16, 128]
        # fresh-input numpy re-execution matches the model
        ids = np.asarray([[1, 5, 9, 2, 0, 7, 3, 8, 11, 4, 6, 10, 12, 13,
                           14, 15]], np.int32)
        m.eval()
        expect = np.asarray(m(paddle.to_tensor(ids))._data)
        (got,) = runtime.run(open(p, "rb").read(), [ids])
        np.testing.assert_allclose(got, expect, atol=1e-4, rtol=1e-4)

    @pytest.mark.parametrize("kw", [{"num_kv_heads": 2},
                                    {"attention_window": 8}])
    def test_gpt_attention_variants(self, tmp_path, kw):
        # GQA (grouped einsums) and sliding-window (banded mask) lower to
        # the same standard op set and pass the numpy self-check
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32, dropout=0.0, **kw)
        p = paddle.onnx.export(GPTForCausalLM(cfg), str(tmp_path / "v"),
                               input_spec=[InputSpec([1, 16], "int32")])
        model = proto.parse_model(open(p, "rb").read())
        assert model["graph"]["outputs"][0]["shape"] == [1, 16, 128]

    def test_multi_output_forward(self, tmp_path):
        class TwoOut(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 3)

            def forward(self, x):
                h = self.fc(x)
                return h, paddle.nn.functional.softmax(h, axis=-1)

        p = paddle.onnx.export(TwoOut(), str(tmp_path / "two"),
                               input_spec=[InputSpec([2, 4], "float32")])
        model = proto.parse_model(open(p, "rb").read())
        assert len(model["graph"]["outputs"]) == 2


class TestWiderModelCoverage:
    def test_bert_multi_input_multi_output(self, tmp_path):
        from paddle_tpu.models import BertConfig, BertModel

        cfg = BertConfig(vocab_size=256, hidden_size=64, num_layers=2,
                         num_heads=4, intermediate_size=128,
                         max_position=64, dropout=0.0)
        p = paddle.onnx.export(BertModel(cfg), str(tmp_path / "bert"),
                               input_spec=[InputSpec([1, 16], "int32"),
                                           InputSpec([1, 16], "int32")])
        model = proto.parse_model(open(p, "rb").read())
        assert len(model["graph"]["inputs"]) == 2
        assert len(model["graph"]["outputs"]) == 2   # sequence + pooled
        assert model["graph"]["outputs"][0]["shape"] == [1, 16, 64]

    def test_mobilenetv2_group_convs(self, tmp_path):
        from paddle_tpu.vision.models import mobilenet_v2

        p = paddle.onnx.export(mobilenet_v2(num_classes=10),
                               str(tmp_path / "mb2"),
                               input_spec=[InputSpec([1, 3, 32, 32],
                                                     "float32")])
        model = proto.parse_model(open(p, "rb").read())
        groups = [n["attrs"].get("group", 1)
                  for n in model["graph"]["nodes"]
                  if n["op_type"] == "Conv"]
        assert max(groups) > 1   # the depthwise convs kept their groups


class TestFailureContract:
    def test_unsupported_primitive_raises_and_writes_no_onnx(self, tmp_path):
        class Sorts(nn.Layer):
            def forward(self, x):
                return paddle.sort(x, axis=-1)

        path = str(tmp_path / "sorts")
        with pytest.raises(UnsupportedOpError, match="sort"):
            paddle.onnx.export(Sorts(), path,
                               input_spec=[InputSpec([2, 8], "float32")])
        assert not os.path.exists(path + ".onnx")
        # the framework-native artifact IS still saved (r3 behavior kept)
        assert os.path.exists(path + ".pdmodel")

    def test_input_spec_required(self, tmp_path):
        with pytest.raises(ValueError, match="input_spec"):
            paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "x"))

    def test_self_check_catches_broken_graph(self, tmp_path, monkeypatch):
        # corrupt the runtime on purpose: validation must refuse the file
        import paddle_tpu.onnx.runtime as rt

        real_run = rt.run

        def bad_run(model_bytes, inputs):
            outs = real_run(model_bytes, inputs)
            return [o + 1.0 for o in outs]

        monkeypatch.setattr(rt, "run", bad_run)
        with pytest.raises(RuntimeError, match="self-check"):
            paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "bad"),
                               input_spec=[InputSpec([1, 2], "float32")])
        assert not os.path.exists(str(tmp_path / "bad") + ".onnx")

    def test_dynamic_batch_lenet(self, tmp_path):
        # r5 (VERDICT r4 #6): InputSpec with a None batch dim emits a
        # symbolic 'N' dim_param, proven by a second trace at batch+1 and
        # validated by re-execution at both batch sizes inside export;
        # here ALSO run the emitted graph at a third, never-traced batch
        from paddle_tpu.vision.models import LeNet

        from paddle_tpu.onnx import runtime as onnx_rt

        paddle.seed(0)
        net = LeNet()
        net.eval()
        p = str(tmp_path / "lenet_dyn")
        paddle.onnx.export(net, p,
                           input_spec=[InputSpec([None, 1, 28, 28],
                                                 "float32")])
        blob = open(p + ".onnx", "rb").read()
        # exact dim_param wire pattern: Dimension{dim_param="N"} inside a
        # TensorShapeProto (field 1, len 3 -> field 2, len 1, 'N') — a bare
        # b"N" check would match random weight bytes
        assert b"\x0a\x03\x12\x01N" in blob
        x5 = np.random.RandomState(0).rand(5, 1, 28, 28).astype("float32")
        (got,) = onnx_rt.run(blob, {"input_0": x5})
        want = np.asarray(net(paddle.to_tensor(x5))._data)
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    def test_dynamic_batch_gpt_reshape_heads(self, tmp_path):
        # transformer head split/merge reshapes EMBED the batch size; the
        # two-trace diff must rewrite them (single differing entry -> -1)
        from paddle_tpu.onnx import runtime as onnx_rt

        net = self._tiny_gpt()
        p = str(tmp_path / "gpt_dyn")
        paddle.onnx.export(net, p,
                           input_spec=[InputSpec([None, 16], "int32")])
        blob = open(p + ".onnx", "rb").read()
        ids = np.random.RandomState(1).randint(
            0, 64, (4, 16)).astype("int32")
        (got,) = onnx_rt.run(blob, {"input_0": ids})
        want = np.asarray(net(paddle.to_tensor(ids))._data)
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)

    def test_dynamic_batch_with_slice_on_batch_axis(self, tmp_path):
        # x[:, -1]-style slices trace with the full batch size in the
        # slice's ends vector; the rewrite emits INT64_MAX ("to the end")
        from paddle_tpu.onnx import runtime as onnx_rt

        class LastStep(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 3)

            def forward(self, x):
                return self.fc(x[:, -1])      # [B, T, 4] -> [B, 3]

        paddle.seed(0)
        net = LastStep()
        net.eval()
        p = str(tmp_path / "lastestep")
        paddle.onnx.export(net, p,
                           input_spec=[InputSpec([None, 5, 4], "float32")])
        blob = open(p + ".onnx", "rb").read()
        x = np.random.RandomState(3).rand(6, 5, 4).astype("float32")
        (got,) = onnx_rt.run(blob, {"input_0": x})
        want = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)

    def test_partial_batch_slice_raises_even_without_validator(self,
                                                               tmp_path):
        # x[:-1] slices the batch axis PARTIALLY: no symbolic form exists,
        # and the refusal must not depend on the (skippable) re-execution
        # validator — validate=False must still raise, never write
        class DropLast(nn.Layer):
            def forward(self, x):
                return x[:-1] * 2.0

        p = str(tmp_path / "droplast")
        with pytest.raises(UnsupportedOpError):
            paddle.onnx.export(DropLast(), p,
                               input_spec=[InputSpec([None, 3], "float32")],
                               validate=False)
        assert not os.path.exists(p + ".onnx")

    def test_batch_dependent_model_raises_under_dynamic(self, tmp_path):
        # a forward that genuinely computes WITH the batch size cannot be
        # batch-polymorphic: export must refuse, not emit a wrong graph
        class BatchConst(nn.Layer):
            def forward(self, x):
                b = x.shape[0]          # python int at trace time
                return x * float(b)

        p = str(tmp_path / "bd")
        with pytest.raises(UnsupportedOpError):
            paddle.onnx.export(BatchConst(), p,
                               input_spec=[InputSpec([None, 3],
                                                     "float32")])
        assert not os.path.exists(p + ".onnx")

    def _tiny_gpt(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        paddle.seed(0)
        net = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
            max_seq_len=16, dropout=0.0))
        net.eval()
        return net

    @pytest.mark.parametrize("family", ["SimpleRNN", "GRU", "LSTM"])
    def test_recurrent_layers_export_unrolled(self, tmp_path, family):
        # r5 (VERDICT r4 #6): the lax.scan time loop exports as an
        # UNROLLED graph; numpy re-execution validates it like any other
        paddle.seed(0)
        net = getattr(nn, family)(4, 8, 1)
        net.eval()
        p = str(tmp_path / family.lower())
        paddle.onnx.export(net, p,
                           input_spec=[InputSpec([2, 5, 4], "float32")])
        assert os.path.exists(p + ".onnx")

    def test_lstm_dynamic_batch(self, tmp_path):
        # scan unroll composes with the dynamic-batch rewrite: the
        # per-step reshapes embed B and must all get rewritten
        from paddle_tpu.onnx import runtime as onnx_rt

        paddle.seed(0)
        net = nn.LSTM(4, 6, 1)
        net.eval()
        p = str(tmp_path / "lstm_dyn")
        paddle.onnx.export(net, p,
                           input_spec=[InputSpec([None, 5, 4],
                                                 "float32")])
        blob = open(p + ".onnx", "rb").read()
        x = np.random.RandomState(2).rand(4, 5, 4).astype("float32")
        outs = onnx_rt.run(blob, {"input_0": x})
        ref = net(paddle.to_tensor(x))
        ref = ref if isinstance(ref, (tuple, list)) else [ref]
        flat = []
        for r in ref:
            flat.extend(r if isinstance(r, (tuple, list)) else [r])
        for got, want in zip(outs, flat):
            np.testing.assert_allclose(
                got, np.asarray(want._data), atol=1e-4, rtol=1e-4)

    def test_attribute_proto_rejects_ambiguous_lists(self):
        # empty and mixed lists have no safe wire encoding: raise, never
        # silently default to A_INTS (advisor finding r4)
        with pytest.raises(TypeError, match="empty list"):
            proto.attribute("axes", [])
        with pytest.raises(TypeError, match="mixed"):
            proto.attribute("vals", [1, "a"])
        # numpy float elements must encode as floats, not truncate to ints
        fl = proto.attribute("scales", [np.float32(0.5), np.float64(1.5)])
        assert fl == proto.attribute("scales", [0.5, 1.5])
        # numpy ints still take the ints path
        il = proto.attribute("axes", [np.int64(0), 1])
        assert il == proto.attribute("axes", [0, 1])

    def test_empty_axes_reductions_export_as_identity(self, tmp_path):
        # paddle.sum/max(x, axis=[]) traces to reduce_{sum,max}[axes=()],
        # which ONNX cannot express (empty axes = reduce-ALL there); the
        # converter must lower it to Identity and the self-check must pass
        class EmptyAxes(nn.Layer):
            def forward(self, x):
                return paddle.sum(x, axis=[]) + paddle.max(x, axis=[])

        p = str(tmp_path / "ea")
        paddle.onnx.export(EmptyAxes(), p,
                           input_spec=[InputSpec([2, 3], "float32")])
        assert os.path.exists(p + ".onnx")

    def test_nonstandard_opset_warns(self, tmp_path):
        with pytest.warns(UserWarning, match="opset 9"):
            paddle.onnx.export(nn.Linear(2, 2), str(tmp_path / "m9"),
                               input_spec=[InputSpec([1, 2], "float32")],
                               opset_version=9)
        assert os.path.exists(str(tmp_path / "m9") + ".onnx")
