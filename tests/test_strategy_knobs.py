"""Strategy-knob wiring tests (VERDICT r1 #2): enabling each fleet flag must
provably change the compiled program or the training dynamics — the TPU-native
rebirth of the reference's meta-optimizer graph-pattern tests
(test_fleet_sharding_meta_optimizer.py style: there ops are asserted in the
rewritten program; here shardings / HLO text / rank-divergence are asserted).
"""
import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.distributed.fleet.meta_optimizers.dgc_optimizer import (
    DGCMomentumOptimizer,
)


def needs_8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")


def _net(seed=0, din=8, dout=4):
    paddle.seed(seed)
    rng = np.random.RandomState(seed)
    net = nn.Linear(din, dout)
    init = {k: rng.randn(*v.shape).astype(np.float32) * 0.1
            for k, v in net.state_dict().items()}
    net.set_state_dict(init)
    return net, init


def _data(seed=1, n=32, din=8, dout=4):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, din).astype(np.float32)
    y = rng.randn(n, dout).astype(np.float32)
    return paddle.to_tensor(x), paddle.to_tensor(y)


MSE = staticmethod(lambda o, l: ((o - l) ** 2).mean())


def _lowered_text(trainer, x, y):
    """HLO text of the trainer's step for these inputs."""
    batch = [x._data, y._data]
    step = trainer._build(batch)
    lr = jnp.asarray(0.1, jnp.float32)
    rng = jax.random.key(0)
    return step.lower(trainer.params, trainer.opt_state, trainer.buffers,
                      lr, rng, *batch).as_text()


class TestLocalSGD:
    def test_k1_sgd_matches_plain_dp(self):
        """k=1 LocalSGD with SGD == plain DP: per-rank update then param
        pmean equals update with pmean'd grads (linearity of SGD)."""
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        x, y = _data()

        net_a, init = _net()
        opt_a = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_a.parameters())
        dp = SpmdTrainer(net_a, opt_a, lambda o, l: ((o - l) ** 2).mean(),
                         mesh=mesh)
        net_b, _ = _net()
        net_b.set_state_dict(init)
        opt_b = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=net_b.parameters())
        ls = SpmdTrainer(net_b, opt_b, lambda o, l: ((o - l) ** 2).mean(),
                         mesh=mesh, localsgd_k=1)

        for _ in range(3):
            la = float(dp.train_step(x, y)._data)
            lb = float(ls.train_step(x, y)._data)
            np.testing.assert_allclose(la, lb, rtol=1e-5)
        dp.sync_to_layer()
        # localsgd params carry a leading replica dim; all replicas synced
        for k, v in dp.params.items():
            reps = np.asarray(ls.params[k])
            np.testing.assert_allclose(reps[0], np.asarray(v), rtol=1e-4,
                                       atol=1e-6)

    def test_k2_ranks_diverge_then_sync(self):
        """The defining LocalSGD dynamic: replicas differ after an off-sync
        step and are identical after the k-th step's param pmean."""
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        net, _ = _net()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        tr = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                         mesh=mesh, localsgd_k=2)
        x, y = _data()

        tr.train_step(x, y)  # step 1: no sync
        w = np.asarray(tr.params["weight"])  # [8, din, dout] replicas
        spread1 = np.abs(w - w[0]).max()
        assert spread1 > 1e-7, "ranks saw different shards; replicas must differ"

        tr.train_step(x, y)  # step 2: pmean sync
        w = np.asarray(tr.params["weight"])
        spread2 = np.abs(w - w[0]).max()
        assert spread2 < 1e-6, f"after k-th step replicas must agree ({spread2})"

    def test_localsgd_program_differs_from_dp(self):
        """Jaxpr/HLO-level: the localsgd step compiles to a different program
        (param pmean gated on step count instead of per-step grad psum)."""
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        x, y = _data()
        net_a, _ = _net()
        dp = SpmdTrainer(net_a, paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net_a.parameters()),
            lambda o, l: ((o - l) ** 2).mean(), mesh=mesh)
        net_b, _ = _net()
        ls = SpmdTrainer(net_b, paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net_b.parameters()),
            lambda o, l: ((o - l) ** 2).mean(), mesh=mesh, localsgd_k=4)
        t_dp = _lowered_text(dp, x, y)
        t_ls = _lowered_text(ls, x, y)
        assert t_dp != t_ls
        # the gate: localsgd selects between synced and local params
        assert "stablehlo.select" in t_ls

    def test_localsgd_rejects_sharding(self):
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        net, _ = _net()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        with pytest.raises(ValueError, match="localsgd"):
            SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                        mesh=mesh, localsgd_k=2, sharding_stage=2)

    def test_fleet_strategy_routes_localsgd(self):
        needs_8()
        from paddle_tpu.distributed.fleet import DistributedStrategy, fleet

        strategy = DistributedStrategy()
        strategy.localsgd = True
        strategy.localsgd_configs.k_steps = 4
        strategy.localsgd_configs.begin_step = 2
        fleet.init(is_collective=True, strategy=strategy)
        net, _ = _net()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        tr = fleet.build_trainer(net, opt,
                                 loss_fn=lambda o, l: ((o - l) ** 2).mean())
        assert tr.localsgd_k == 4 and tr.localsgd_begin == 2


class TestDGC:
    def test_sparsity_zero_matches_plain_sgd_dp(self):
        """sparsity=0 -> full mask, residuals reset each step: the momentum-
        corrected allreduce degenerates to plain SGD on the mean grad."""
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        x, y = _data()
        net_a, init = _net()
        dp = SpmdTrainer(net_a, paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net_a.parameters()),
            lambda o, l: ((o - l) ** 2).mean(), mesh=mesh)
        net_b, _ = _net()
        net_b.set_state_dict(init)
        dgc_opt = DGCMomentumOptimizer(learning_rate=0.1, momentum=0.9,
                                       sparsity=0.0,
                                       parameters=net_b.parameters())
        dg = SpmdTrainer(net_b, dgc_opt, lambda o, l: ((o - l) ** 2).mean(),
                         mesh=mesh)
        assert dg._is_dgc()
        for _ in range(3):
            la = float(dp.train_step(x, y)._data)
            lb = float(dg.train_step(x, y)._data)
            np.testing.assert_allclose(la, lb, rtol=1e-5)

    def test_sparse_reduce_keeps_residuals_and_converges(self):
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        net, _ = _net()
        dgc_opt = DGCMomentumOptimizer(learning_rate=0.05, momentum=0.9,
                                       sparsity=0.75,
                                       parameters=net.parameters())
        tr = SpmdTrainer(net, dgc_opt, lambda o, l: ((o - l) ** 2).mean(),
                         mesh=mesh)
        x, y = _data()
        losses = [float(tr.train_step(x, y)._data) for _ in range(8)]
        assert losses[-1] < losses[0]
        # residuals are genuinely carried (the un-sent 75% accumulates)
        u = np.asarray(tr.opt_state["weight"]["dgc_u"])
        assert np.abs(u).max() > 0
        # and PER-RANK: replicas must not be forced equal
        assert u.shape[0] == 8

    def test_dgc_program_has_topk_sort(self):
        """HLO-level: DGC's top-k threshold compiles to a sort; plain DP SGD
        has none."""
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        x, y = _data()
        net_a, _ = _net()
        dp = SpmdTrainer(net_a, paddle.optimizer.SGD(
            learning_rate=0.1, parameters=net_a.parameters()),
            lambda o, l: ((o - l) ** 2).mean(), mesh=mesh)
        net_b, _ = _net()
        dg = SpmdTrainer(net_b, DGCMomentumOptimizer(
            learning_rate=0.1, sparsity=0.9, parameters=net_b.parameters()),
            lambda o, l: ((o - l) ** 2).mean(), mesh=mesh)
        t_dp = _lowered_text(dp, x, y)
        t_dg = _lowered_text(dg, x, y)
        assert "chlo.top_k" in t_dg or "sort" in t_dg
        assert "chlo.top_k" not in t_dp and "sort" not in t_dp

    def test_dgc_rejects_sharding(self):
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        net, _ = _net()
        dgc_opt = DGCMomentumOptimizer(learning_rate=0.1,
                                       parameters=net.parameters())
        with pytest.raises(ValueError, match="DGC"):
            SpmdTrainer(net, dgc_opt, lambda o, l: ((o - l) ** 2).mean(),
                        mesh=build_mesh((8,), ("dp",)), sharding_stage=2)


class TestStateOffload:
    def test_warns_and_ignores_on_cpu(self):
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        net, _ = _net()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            tr = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                             mesh=mesh, state_offload=True)
        assert any("state_offload" in str(x.message) for x in w)
        x, y = _data()
        assert np.isfinite(float(tr.train_step(x, y)._data))

    def test_offload_shardings_are_pinned_host(self):
        """The TPU path: every optimizer moment gets memory_kind=pinned_host
        (sharding_configs.offload parity); __step__ stays in device memory."""
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        net, _ = _net()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            tr = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                             mesh=mesh, state_offload=True)
        off = tr._offload_state_shardings(force=True)
        for pname, st in off.items():
            if pname == "__step__":
                continue
            for k, sh in st.items():
                assert sh.memory_kind == "pinned_host", (pname, k)

    def test_fleet_sharding_offload_routes(self):
        needs_8()
        from paddle_tpu.distributed.fleet import DistributedStrategy, fleet

        strategy = DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs.stage = 2
        strategy.sharding_configs.offload = True
        fleet.init(is_collective=True, strategy=strategy)
        net, _ = _net()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")  # CPU backend ignores the offload
            tr = fleet.build_trainer(
                net, opt, loss_fn=lambda o, l: ((o - l) ** 2).mean())
        assert tr.sharding_stage == 2 and tr.state_offload


class TestRecomputePolicy:
    """Selective remat: recompute_policy changes what jax.checkpoint saves,
    so the compiled HLO must differ from plain full recompute, and invalid
    names fail loudly."""

    def _mlp(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                             nn.Linear(64, 64), nn.ReLU(),
                             nn.Linear(64, 4))

    def test_dots_policy_changes_hlo_and_trains(self):
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        rng = np.random.RandomState(3)
        x = paddle.to_tensor(rng.randn(32, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randn(32, 4).astype(np.float32))

        def make(**kw):
            net = self._mlp()
            opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net.parameters())
            return SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                               mesh=mesh, recompute=True, **kw)

        plain = make()
        dots = make(recompute_policy="dots")
        t_plain = _lowered_text(plain, x, y)
        t_dots = _lowered_text(dots, x, y)
        assert t_plain != t_dots  # the policy reached the compiled program
        l0 = float(np.asarray(dots.train_step(x, y)._data))
        l5 = l0
        for _ in range(5):
            l5 = float(np.asarray(dots.train_step(x, y)._data))
        assert np.isfinite(l5) and l5 < l0

    def test_policy_parity_with_plain(self):
        """Remat policies change scheduling, not math: one step under
        'dots' equals one step under full recompute."""
        needs_8()
        mesh = build_mesh((8,), ("dp",))
        rng = np.random.RandomState(4)
        x = paddle.to_tensor(rng.randn(16, 16).astype(np.float32))
        y = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
        losses = []
        for kw in ({}, {"recompute_policy": "dots"},
                   {"recompute_policy": "nothing"}):
            net = self._mlp()
            opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net.parameters())
            tr = SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                             mesh=mesh, recompute=True, **kw)
            losses.append(float(np.asarray(tr.train_step(x, y)._data)))
        assert np.allclose(losses, losses[0], atol=1e-6), losses

    def test_invalid_policy_raises(self):
        needs_8()
        import pytest

        mesh = build_mesh((8,), ("dp",))
        net = self._mlp()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        with pytest.raises(ValueError, match="recompute_policy"):
            SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                        mesh=mesh, recompute=True,
                        recompute_policy="bogus")
        with pytest.raises(ValueError, match="requires recompute=True"):
            SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                        mesh=mesh, recompute_policy="dots")
        with pytest.raises(ValueError, match="pick one"):
            SpmdTrainer(net, opt, lambda o, l: ((o - l) ** 2).mean(),
                        mesh=mesh, recompute=True, remat_offload=True,
                        recompute_policy="dots")

    def test_strategy_checkpoints_maps_to_policy(self):
        """fleet surface: a policy name in recompute_configs.checkpoints
        reaches the trainer as recompute_policy."""
        needs_8()
        from paddle_tpu.distributed.fleet import DistributedStrategy, fleet

        strategy = DistributedStrategy()
        strategy.recompute = True
        strategy.recompute_configs.checkpoints = ["dots"]
        fleet.init(is_collective=True, strategy=strategy)
        net = self._mlp()
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=net.parameters())
        tr = fleet.build_trainer(
            net, opt, loss_fn=lambda o, l: ((o - l) ** 2).mean())
        assert tr.extra_kwargs.get("recompute_policy") == "dots"
