"""Continuous-batching serving engine (inference/serving.py — beyond the
reference): per-slot sequence positions over one fixed-shape KV cache,
admission by prefill + row copy, slots freed and reused mid-stream. Every
GREEDY (temperature=0) request's output must EXACTLY match a solo
`model.generate(temperature=0)` — the same parity bar the rest of the
serving stack holds; sampling requests get deterministic per-seed streams
that never disturb greedy neighbors."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def _model(**kw):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=128, dropout=0.0, **kw)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _ref_new_tokens(m, prompt, n, **kw):
    out = m.generate(paddle.to_tensor(prompt[None]), max_new_tokens=n,
                     temperature=0.0, **kw)
    return np.asarray(out._data)[0, len(prompt):]


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(0)


class TestParity:
    def test_interleaved_requests_match_solo_generate(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=3)
        prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
                   for n in (5, 9, 17, 3, 26)]
        rids = [eng.submit(p, max_new_tokens=12) for p in prompts]
        res = eng.run_until_complete()
        assert len(res) == 5
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(
                res[rid].tokens, _ref_new_tokens(m, p, 12))
            assert res[rid].finish_reason == "length"

    def test_staggered_submission_mid_stream(self, rng):
        # a request ARRIVING while others are mid-decode must not disturb
        # them, and must itself decode exactly
        m = _model()
        eng = ServingEngine(m, max_batch=2)
        p1 = rng.randint(0, 256, (6,)).astype(np.int32)
        p2 = rng.randint(0, 256, (11,)).astype(np.int32)
        r1 = eng.submit(p1, max_new_tokens=10)
        for _ in range(4):
            eng.step()                      # p1 is 4+ tokens in
        r2 = eng.submit(p2, max_new_tokens=10)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[r1].tokens,
                                      _ref_new_tokens(m, p1, 10))
        np.testing.assert_array_equal(res[r2].tokens,
                                      _ref_new_tokens(m, p2, 10))

    def test_bf16_and_int8_kv_compose(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=2, dtype="bfloat16",
                            cache_dtype="int8")
        prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
                   for n in (7, 13, 4)]
        rids = [eng.submit(p, max_new_tokens=8) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(
                res[rid].tokens,
                _ref_new_tokens(m, p, 8, dtype="bfloat16",
                                cache_dtype="int8"))

    def test_gqa_and_window_configs(self, rng):
        for kw in ({"num_kv_heads": 2}, {"attention_window": 16}):
            m = _model(**kw)
            eng = ServingEngine(m, max_batch=2)
            p = rng.randint(0, 256, (9,)).astype(np.int32)
            rid = eng.submit(p, max_new_tokens=10)
            res = eng.run_until_complete()
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref_new_tokens(m, p, 10))


class TestTensorParallel:
    def _mesh(self):
        import jax

        from paddle_tpu.distributed.mesh import build_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        return build_mesh((4,), ("mp",), devices=jax.devices()[:4])

    def test_tp_engine_matches_dense_engine_and_generate(self, rng):
        m = _model()
        mesh = self._mesh()
        eng = ServingEngine(m, max_batch=2, tp_mesh=mesh)
        prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
                   for n in (5, 9, 14)]
        rids = [eng.submit(p, max_new_tokens=8) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref_new_tokens(m, p, 8))

    def test_tp_with_int8_kv_and_sampling(self, rng):
        m = _model()
        mesh = self._mesh()
        eng = ServingEngine(m, max_batch=2, tp_mesh=mesh,
                            cache_dtype="int8")
        pg = rng.randint(0, 256, (7,)).astype(np.int32)
        ps = rng.randint(0, 256, (10,)).astype(np.int32)
        rg = eng.submit(pg, max_new_tokens=6)
        rs = eng.submit(ps, max_new_tokens=6, temperature=0.8, seed=11)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(
            res[rg].tokens, _ref_new_tokens(m, pg, 6, cache_dtype="int8"))
        # same-seed rerun reproduces the sampled stream under tp
        eng2 = ServingEngine(m, max_batch=2, tp_mesh=mesh,
                             cache_dtype="int8")
        rs2 = eng2.submit(ps, max_new_tokens=6, temperature=0.8, seed=11)
        res2 = eng2.run_until_complete()
        np.testing.assert_array_equal(res[rs].tokens, res2[rs2].tokens)


class TestSampling:
    def test_greedy_rows_unaffected_by_sampling_neighbor(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=2)
        pg = rng.randint(0, 256, (7,)).astype(np.int32)
        ps = rng.randint(0, 256, (9,)).astype(np.int32)
        rg = eng.submit(pg, max_new_tokens=10)                 # greedy
        rs = eng.submit(ps, max_new_tokens=10, temperature=0.9)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[rg].tokens,
                                      _ref_new_tokens(m, pg, 10))
        assert len(res[rs].tokens) == 10
        assert all(0 <= t < 256 for t in res[rs].tokens)

    def test_sampling_deterministic_per_seed(self, rng):
        m = _model()
        p = rng.randint(0, 256, (6,)).astype(np.int32)

        def run(seed):
            eng = ServingEngine(m, max_batch=1)
            rid = eng.submit(p, max_new_tokens=12, temperature=0.8,
                             seed=seed)
            return list(eng.run_until_complete()[rid].tokens)

        assert run(7) == run(7)            # same seed -> same stream
        outs = {tuple(run(s)) for s in (7, 8, 9, 10)}
        assert len(outs) > 1               # seeds actually vary the draw

    def test_top_k_one_is_greedy(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        p = rng.randint(0, 256, (8,)).astype(np.int32)
        rid = eng.submit(p, max_new_tokens=10, temperature=1.3, top_k=1)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[rid].tokens,
                                      _ref_new_tokens(m, p, 10))

    def test_top_p_tiny_nucleus_is_greedy(self, rng):
        # a nucleus small enough to keep only the top token reduces to
        # greedy (the top token always survives) — same as generate()
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        p = rng.randint(0, 256, (8,)).astype(np.int32)
        rid = eng.submit(p, max_new_tokens=10, temperature=0.7,
                         top_p=1e-9)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[rid].tokens,
                                      _ref_new_tokens(m, p, 10))

    def test_top_p_deterministic_and_composes_with_top_k(self, rng):
        m = _model()
        p = rng.randint(0, 256, (6,)).astype(np.int32)

        def run(top_p, top_k=None):
            eng = ServingEngine(m, max_batch=1)
            rid = eng.submit(p, max_new_tokens=12, temperature=0.9,
                             top_p=top_p, top_k=top_k, seed=5)
            return list(eng.run_until_complete()[rid].tokens)

        assert run(0.9) == run(0.9)             # deterministic per seed
        assert run(0.9, top_k=40) == run(0.9, top_k=40)
        # top_p=1.0 is exactly the no-nucleus path
        assert run(1.0) == run(None)

    def test_sampling_validation(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        with pytest.raises(ValueError, match="temperature"):
            eng.submit(np.zeros((3,), np.int32), temperature=-0.1)
        with pytest.raises(ValueError, match="top_k"):
            eng.submit(np.zeros((3,), np.int32), top_k=0)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit(np.zeros((3,), np.int32), temperature=0.5,
                       top_p=0.0)
        with pytest.raises(ValueError, match="top_p"):
            eng.submit(np.zeros((3,), np.int32), temperature=0.5,
                       top_p=1.5)
        with pytest.raises(ValueError, match="seed"):
            eng.submit(np.zeros((3,), np.int32), temperature=0.5,
                       seed=2 ** 31)


class TestChunkedPrefill:
    def test_chunked_matches_unchunked(self, rng):
        m = _model()
        for chunk, plen in ((16, 50), (7, 23), (32, 9)):   # incl. p < C
            eng = ServingEngine(m, max_batch=2, prefill_chunk=chunk)
            p = rng.randint(0, 256, (plen,)).astype(np.int32)
            rid = eng.submit(p, max_new_tokens=8)
            res = eng.run_until_complete()
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref_new_tokens(m, p, 8))

    def test_decode_interleaves_with_long_prefill(self, rng):
        # the whole point: an active request keeps emitting one token per
        # step WHILE a long prompt is being consumed chunk by chunk
        m = _model()
        eng = ServingEngine(m, max_batch=2, prefill_chunk=16)
        p_short = rng.randint(0, 256, (5,)).astype(np.int32)
        p_long = rng.randint(0, 256, (60,)).astype(np.int32)
        r_s = eng.submit(p_short, max_new_tokens=20)
        eng.step()                         # short admitted + 1 decode
        short_req = eng._slot_req[[s for s in range(2)
                                   if eng._slot_req[s]][0]]
        r_l = eng.submit(p_long, max_new_tokens=4)
        counts = []
        for _ in range(3):                 # 60/16 -> 4 chunks in flight
            eng.step()
            counts.append(len(short_req.output_ids))
        # short request gained a token EVERY step despite the prefill
        assert counts == [counts[0], counts[0] + 1, counts[0] + 2]
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[r_s].tokens,
                                      _ref_new_tokens(m, p_short, 20))
        np.testing.assert_array_equal(res[r_l].tokens,
                                      _ref_new_tokens(m, p_long, 4))

    def test_final_chunk_crossing_T_falls_back_whole_prefill(self, rng):
        # reviewer-reproduced corruption: T=128, chunk=96, prompt 100 —
        # the fixed-width final chunk would write past T and
        # dynamic_update_slice CLAMPS, shifting tokens onto valid prefix
        # columns. Such prompts must take the whole-prefill path instead.
        m = _model()
        eng = ServingEngine(m, max_batch=1, prefill_chunk=96)
        p = rng.randint(0, 256, (100,)).astype(np.int32)
        rid = eng.submit(p, max_new_tokens=6)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[rid].tokens,
                                      _ref_new_tokens(m, p, 6))

    def test_chunk_validation(self, rng):
        import jax

        m = _model()
        with pytest.raises(ValueError, match="prefill_chunk"):
            ServingEngine(m, max_batch=1, prefill_chunk=0)
        with pytest.raises(ValueError, match="prefill_chunk"):
            ServingEngine(m, max_batch=1, prefill_chunk=129)  # > T


class TestPrefixCaching:
    def test_prefix_cached_requests_match_full_prompt(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=2)
        prefix = rng.randint(0, 256, (20,)).astype(np.int32)
        pid = eng.register_prefix(prefix)
        sufs = [rng.randint(0, 256, (n,)).astype(np.int32)
                for n in (5, 11, 30)]
        rids = [eng.submit(s, max_new_tokens=8, prefix_id=pid)
                for s in sufs]
        res = eng.run_until_complete()
        for rid, s in zip(rids, sufs):
            full = np.concatenate([prefix, s])
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref_new_tokens(m, full, 8))
        # the prefix cache survives its consumers (the chunk program
        # donates; admissions must copy): a LATER request still works
        s2 = rng.randint(0, 256, (7,)).astype(np.int32)
        r2 = eng.submit(s2, max_new_tokens=6, prefix_id=pid)
        res2 = eng.run_until_complete()
        np.testing.assert_array_equal(
            res2[r2].tokens,
            _ref_new_tokens(m, np.concatenate([prefix, s2]), 6))

    def test_prefix_near_capacity_falls_back(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        prefix = rng.randint(0, 256, (90,)).astype(np.int32)
        pid = eng.register_prefix(prefix)
        s = rng.randint(0, 256, (30,)).astype(np.int32)  # 90+64-chunk > T
        rid = eng.submit(s, max_new_tokens=4, prefix_id=pid)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(
            res[rid].tokens,
            _ref_new_tokens(m, np.concatenate([prefix, s]), 4))

    def test_unregister_frees_prefix(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        prefix = rng.randint(0, 256, (10,)).astype(np.int32)
        pid = eng.register_prefix(prefix)
        s = rng.randint(0, 256, (4,)).astype(np.int32)
        rid = eng.submit(s, max_new_tokens=4, prefix_id=pid)
        eng.unregister_prefix(pid)
        # the QUEUED request already captured the combined prompt — it
        # must whole-prefill correctly despite the freed prefix cache
        res = eng.run_until_complete()
        np.testing.assert_array_equal(
            res[rid].tokens,
            _ref_new_tokens(m, np.concatenate([prefix, s]), 4))
        with pytest.raises(ValueError, match="prefix_id"):
            eng.submit(s, prefix_id=pid)
        with pytest.raises(ValueError, match="prefix_id"):
            eng.unregister_prefix(pid)

    def test_prefix_validation(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        with pytest.raises(ValueError, match="prefix_id"):
            eng.submit(np.zeros((3,), np.int32), prefix_id=99)
        with pytest.raises(ValueError, match="empty"):
            eng.register_prefix(np.zeros((0,), np.int32))
        with pytest.raises(ValueError, match="too long"):
            eng.register_prefix(np.zeros((200,), np.int32))


class TestTPComposition:
    """r5 (VERDICT r4 #3): chunked prefill and shared-prefix caching now
    COMPOSE with tensor-parallel serving — the side caches use the same
    head-sharded eval_shape + NamedSharding allocation as the persistent
    cache, and the chunk program runs inside the same shard_map recipe.
    Same exact-parity bar as every other serving mode."""

    def _mesh(self):
        import jax

        from paddle_tpu.distributed.mesh import build_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        return build_mesh((4,), ("mp",), devices=jax.devices()[:4])

    def test_tp_chunked_matches_generate(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=2, tp_mesh=self._mesh(),
                            prefill_chunk=8)
        prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
                   for n in (21, 6, 13)]
        rids = [eng.submit(p, max_new_tokens=7) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref_new_tokens(m, p, 7))

    def test_tp_prefix_matches_full_prompt(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=2, tp_mesh=self._mesh())
        prefix = rng.randint(0, 256, (20,)).astype(np.int32)
        pid = eng.register_prefix(prefix)
        sufs = [rng.randint(0, 256, (n,)).astype(np.int32)
                for n in (5, 12)]
        rids = [eng.submit(s, max_new_tokens=6, prefix_id=pid)
                for s in sufs]
        res = eng.run_until_complete()
        for rid, s in zip(rids, sufs):
            np.testing.assert_array_equal(
                res[rid].tokens,
                _ref_new_tokens(m, np.concatenate([prefix, s]), 6))

    def test_tp_with_fp8_kv(self, rng):
        # fp8 cache tuple shares int8's (vals, scales) structure, so the
        # head-sharded pytree-prefix spec must cover it identically
        m = _model()
        eng = ServingEngine(m, max_batch=2, tp_mesh=self._mesh(),
                            cache_dtype="fp8")
        p = rng.randint(0, 256, (9,)).astype(np.int32)
        rid = eng.submit(p, max_new_tokens=6)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(
            res[rid].tokens, _ref_new_tokens(m, p, 6, cache_dtype="fp8"))

    def test_tp_prefix_with_chunked_and_int8(self, rng):
        # the full matrix corner: tp x chunked x prefix x int8 KV
        m = _model()
        eng = ServingEngine(m, max_batch=2, tp_mesh=self._mesh(),
                            prefill_chunk=8, cache_dtype="int8")
        prefix = rng.randint(0, 256, (17,)).astype(np.int32)
        pid = eng.register_prefix(prefix)
        s = rng.randint(0, 256, (9,)).astype(np.int32)
        rid = eng.submit(s, max_new_tokens=5, prefix_id=pid)
        p2 = rng.randint(0, 256, (24,)).astype(np.int32)
        r2 = eng.submit(p2, max_new_tokens=5)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(
            res[rid].tokens,
            _ref_new_tokens(m, np.concatenate([prefix, s]), 5,
                            cache_dtype="int8"))
        # the plain-chunked half of the corner (no prefix) must hold too
        np.testing.assert_array_equal(
            res[r2].tokens, _ref_new_tokens(m, p2, 5, cache_dtype="int8"))

    def test_tp_prefix_near_capacity_falls_back(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1, tp_mesh=self._mesh())
        prefix = rng.randint(0, 256, (90,)).astype(np.int32)
        pid = eng.register_prefix(prefix)
        s = rng.randint(0, 256, (30,)).astype(np.int32)  # 90+64-chunk > T
        rid = eng.submit(s, max_new_tokens=4, prefix_id=pid)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(
            res[rid].tokens,
            _ref_new_tokens(m, np.concatenate([prefix, s]), 4))


class TestSlotLifecycle:
    def test_eos_frees_slot_for_queued_request(self, rng):
        m = _model()
        p = rng.randint(0, 256, (8,)).astype(np.int32)
        # pick one of the model's own greedy tokens as "eos" so a request
        # stops early deterministically — at its FIRST occurrence
        ref = _ref_new_tokens(m, p, 3)
        eos = int(ref[-1])
        first = list(ref).index(eos)
        eng = ServingEngine(m, max_batch=1, eos_token_id=eos)
        r1 = eng.submit(p, max_new_tokens=50)
        p2 = rng.randint(0, 256, (5,)).astype(np.int32)
        r2 = eng.submit(p2, max_new_tokens=6)      # waits for the slot
        res = eng.run_until_complete()
        assert res[r1].finish_reason == "eos"
        assert list(res[r1].tokens) == list(ref[:first + 1])
        ref2 = _ref_new_tokens(m, p2, 6)
        got2 = res[r2].tokens
        if eos in ref2:                            # engine-wide eos applies
            cut = list(ref2).index(eos) + 1
            assert list(got2) == list(ref2[:cut])
        else:
            np.testing.assert_array_equal(got2, ref2)

    def test_capacity_finish(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        p = rng.randint(0, 256, (120,)).astype(np.int32)  # near T=128
        rid = eng.submit(p, max_new_tokens=500)
        res = eng.run_until_complete()
        assert res[rid].finish_reason == "capacity"
        # T - len(prompt) + 1: the final token costs no cache column (it
        # falls out of the last forward), so the engine emits one MORE
        # token than generate's T-bound allows
        assert len(res[rid].tokens) == 128 - 120 + 1
        np.testing.assert_array_equal(res[rid].tokens[:8],
                                      _ref_new_tokens(m, p, 8))

    def test_errors(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        with pytest.raises(ValueError, match="empty"):
            eng.submit(np.zeros((0,), np.int32))
        with pytest.raises(ValueError, match="too long"):
            eng.submit(np.zeros((400,), np.int32))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(np.zeros((4,), np.int32), max_new_tokens=0)

    def test_one_token_requests_chain_through_admission(self, rng):
        # a request finishing DURING admission (max_new_tokens=1) must not
        # leave its slot idle while the queue is non-empty
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        prompts = [rng.randint(0, 256, (4 + i,)).astype(np.int32)
                   for i in range(3)]
        rids = [eng.submit(p, max_new_tokens=1) for p in prompts]
        eng.step()     # ONE step admits+finishes all three back-to-back
        assert all(r in eng._finished for r in rids)
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(eng._finished[rid].tokens,
                                          _ref_new_tokens(m, p, 1))

    def test_throughput_counts(self, rng):
        # N requests through B slots: total steps ~ ceil-scheduled, and
        # every request completes exactly once
        m = _model()
        eng = ServingEngine(m, max_batch=4)
        rids = [eng.submit(rng.randint(0, 256, (4 + i,)).astype(np.int32),
                           max_new_tokens=5) for i in range(10)]
        res = eng.run_until_complete()
        assert sorted(res) == sorted(rids)
        assert all(len(res[r].tokens) == 5 for r in rids)


class TestObservability:
    def test_get_request_across_lifecycle(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=1)
        p = rng.randint(0, 256, (5,)).astype(np.int32)
        r1 = eng.submit(p, max_new_tokens=3)
        r2 = eng.submit(p, max_new_tokens=3)
        assert eng.get_request(r2).rid == r2      # still queued (1 slot)
        eng.step()
        assert eng.get_request(r1).rid == r1      # in-flight or finished
        eng.run_until_complete()
        assert eng.get_request(r1).finished
        assert eng.get_request(r2).finished
        with pytest.raises(KeyError):
            eng.get_request(999)

    def test_engine_stats_and_monitor_counters_move(self, rng):
        """ISSUE 2: the serving.py docstring's promised latency trackers —
        stats() aggregates and the monitor's serving_* families both move
        over a drain, and the per-request view carries TTFT."""
        from paddle_tpu import monitor

        monitor.reset()
        m = _model()
        eng = ServingEngine(m, max_batch=2)
        rids = [eng.submit(rng.randint(0, 256, (4 + i,)).astype(np.int32),
                           max_new_tokens=4) for i in range(3)]
        eng.run_until_complete()
        s = eng.stats()
        assert s["requests"]["submitted"] == 3
        assert s["requests"]["finished"] == {"length": 3}
        assert s["tokens_generated"] == 12
        assert s["steps"].get("decode_greedy", 0) >= 3
        assert s["ttft_ms"]["count"] == 3
        assert s["inter_token_ms"]["count"] == 9   # 3 reqs x 3 gaps
        assert s["queue_wait_ms"]["count"] == 3
        assert 0 < s["batch_occupancy_avg"] <= 2
        # per-request view (the get_request latency-tracker surface)
        r = eng.get_request(rids[0])
        assert r.stats()["new_tokens"] == 4
        assert r.stats()["ttft_ms"] > 0
        assert r.stats()["inter_token"]["count"] == 3
        # the same families stream into the global monitor registry
        flat = monitor.flatten(monitor.snapshot())
        assert flat["serving_requests_submitted_total"] == 3
        assert flat["serving_requests_finished_total{reason=length}"] == 3
        assert flat["serving_tokens_total"] == 12
        assert flat["serving_ttft_ms"]["count"] == 3
        assert flat["serving_inter_token_ms"]["count"] == 9

    def test_prefix_and_spec_rates_in_stats(self, rng):
        from paddle_tpu import monitor

        monitor.reset()
        m = _model()
        eng = ServingEngine(m, max_batch=2)
        pre = rng.randint(0, 256, (8,)).astype(np.int32)
        pid = eng.register_prefix(pre)
        eng.submit(rng.randint(0, 256, (4,)).astype(np.int32),
                   max_new_tokens=2, prefix_id=pid)
        eng.run_until_complete()
        s = eng.stats()
        assert s["prefix_cache"] == {"hit": 1, "miss": 0, "hit_rate": 1.0}
        # speculative accounting: a self-draft engine accepts everything
        paddle.seed(0)
        eng2 = ServingEngine(_model(), max_batch=2, draft_model=_model(),
                             spec_k=3)
        eng2.submit(rng.randint(0, 256, (5,)).astype(np.int32),
                    max_new_tokens=7)
        eng2.run_until_complete()
        s2 = eng2.stats()
        assert s2["speculative"]["proposed"] > 0
        assert s2["speculative"]["accept_rate"] == 1.0  # draft == target
        assert s2["steps"].get("speculative", 0) >= 1


class TestSpeculative:
    """Speculative continuous batching (draft_model=): output must be
    BIT-IDENTICAL to plain greedy — the draft only changes how many
    target forwards it takes, never what is emitted."""

    def _draft(self):
        paddle.seed(7)
        d = GPTForCausalLM(GPTConfig(vocab_size=256, hidden_size=32,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=128, dropout=0.0))
        d.eval()
        return d

    def test_matches_plain_greedy_engine_and_generate(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=3, draft_model=self._draft(),
                            spec_k=4)
        prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
                   for n in (5, 9, 17, 3, 26)]
        rids = [eng.submit(p, max_new_tokens=12) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref_new_tokens(m, p, 12))

    def test_self_draft_accepts_everything(self, rng):
        # draft == target: every proposal accepted, so each round emits
        # spec_k+1 tokens and the drain takes ~1/(k+1) the steps
        m = _model()
        eng = ServingEngine(m, max_batch=1, draft_model=m, spec_k=3)
        p = rng.randint(0, 256, (6,)).astype(np.int32)
        rid = eng.submit(p, max_new_tokens=12)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
        res = eng._finished
        np.testing.assert_array_equal(res[rid].tokens,
                                      _ref_new_tokens(m, p, 12))
        # 1 admission step (emits 1) + ceil(11/4) spec rounds = 4 steps
        assert steps <= 5, steps

    def test_sampling_neighbor_falls_back_but_stays_exact(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=2, draft_model=self._draft(),
                            spec_k=4)
        pg = rng.randint(0, 256, (7,)).astype(np.int32)
        ps = rng.randint(0, 256, (9,)).astype(np.int32)
        rg = eng.submit(pg, max_new_tokens=10)
        rs = eng.submit(ps, max_new_tokens=10, temperature=0.9, seed=3)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[rg].tokens,
                                      _ref_new_tokens(m, pg, 10))
        assert len(res[rs].tokens) == 10

    def test_composes_with_chunked_and_prefix(self, rng):
        m = _model()
        eng = ServingEngine(m, max_batch=2, draft_model=self._draft(),
                            spec_k=3, prefill_chunk=8)
        prefix = rng.randint(0, 256, (20,)).astype(np.int32)
        pid = eng.register_prefix(prefix)
        s = rng.randint(0, 256, (6,)).astype(np.int32)
        r1 = eng.submit(s, max_new_tokens=8, prefix_id=pid)
        p2 = rng.randint(0, 256, (21,)).astype(np.int32)
        r2 = eng.submit(p2, max_new_tokens=8)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(
            res[r1].tokens,
            _ref_new_tokens(m, np.concatenate([prefix, s]), 8))
        np.testing.assert_array_equal(res[r2].tokens,
                                      _ref_new_tokens(m, p2, 8))

    def test_eos_mid_round_and_near_capacity_fallback(self, rng):
        m = _model()
        # run requests long enough to push pos toward max_seq_len=128 so
        # the near-capacity single-token fallback engages, and finish on
        # capacity — all still exact vs the plain engine
        eng = ServingEngine(m, max_batch=1, draft_model=self._draft(),
                            spec_k=4)
        p = rng.randint(0, 256, (100,)).astype(np.int32)
        rid = eng.submit(p, max_new_tokens=64)  # 100 + 64 > 128: capacity
        res = eng.run_until_complete()
        plain = ServingEngine(m, max_batch=1)
        rid_p = plain.submit(p, max_new_tokens=64)
        res_p = plain.run_until_complete()
        np.testing.assert_array_equal(res[rid].tokens, res_p[rid_p].tokens)
        assert res[rid].finish_reason == res_p[rid_p].finish_reason \
            == "capacity"
        # eos inside an accepted run truncates exactly like 1-token steps
        eng2 = ServingEngine(m, max_batch=1, draft_model=m, spec_k=4,
                             eos_token_id=int(
                                 _ref_new_tokens(m, p[:10], 6)[3]))
        rid2 = eng2.submit(p[:10], max_new_tokens=20)
        res2 = eng2.run_until_complete()
        eng3 = ServingEngine(m, max_batch=1, eos_token_id=int(
            _ref_new_tokens(m, p[:10], 6)[3]))
        rid3 = eng3.submit(p[:10], max_new_tokens=20)
        res3 = eng3.run_until_complete()
        np.testing.assert_array_equal(res2[rid2].tokens, res3[rid3].tokens)
        assert res2[rid2].finish_reason == res3[rid3].finish_reason

    def test_draft_cache_stays_warm_through_fallback(self, rng):
        # a sampling neighbor forces single-token fallback steps; once it
        # finishes, the surviving greedy slot must resume EFFECTIVE
        # speculation (draft cache kept in sync during fallback) — with
        # draft == target every proposal accepts, so the remaining tokens
        # arrive spec_k+1 per round
        m = _model()
        eng = ServingEngine(m, max_batch=2, draft_model=m, spec_k=3)
        pg = rng.randint(0, 256, (6,)).astype(np.int32)
        ps = rng.randint(0, 256, (8,)).astype(np.int32)
        rg = eng.submit(pg, max_new_tokens=30)
        rs = eng.submit(ps, max_new_tokens=4, temperature=0.9, seed=1)
        steps = 0
        while eng.has_work():
            eng.step()
            steps += 1
        res = eng._finished
        np.testing.assert_array_equal(res[rg].tokens,
                                      _ref_new_tokens(m, pg, 30))
        # ~4 fallback steps while the sampler lives (emits 4 + admission),
        # then (30 - ~5) remaining tokens at 4/round: well under the ~30
        # steps a cold draft cache would force
        assert steps <= 14, steps

    def test_validation(self, rng):
        m = _model()
        paddle.seed(3)
        bad_vocab = GPTForCausalLM(GPTConfig(
            vocab_size=128, hidden_size=32, num_layers=1, num_heads=2,
            max_seq_len=128, dropout=0.0))
        with pytest.raises(ValueError, match="vocabulary"):
            ServingEngine(m, draft_model=bad_vocab)
        with pytest.raises(ValueError, match="spec_k"):
            ServingEngine(m, draft_model=self._draft(), spec_k=0)
        short = GPTForCausalLM(GPTConfig(
            vocab_size=256, hidden_size=32, num_layers=1, num_heads=2,
            max_seq_len=64, dropout=0.0))
        short.eval()
        with pytest.raises(ValueError, match="max_seq_len"):
            ServingEngine(m, draft_model=short)


class TestSpeculativeTP:
    def test_tp_target_with_replicated_draft(self, rng):
        import jax

        from paddle_tpu.distributed.mesh import build_mesh

        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = build_mesh((4,), ("mp",), devices=jax.devices()[:4])
        m = _model()
        paddle.seed(7)
        d = GPTForCausalLM(GPTConfig(vocab_size=256, hidden_size=32,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=128, dropout=0.0))
        d.eval()
        eng = ServingEngine(m, max_batch=2, tp_mesh=mesh, draft_model=d,
                            spec_k=3)
        prompts = [rng.randint(0, 256, (n,)).astype(np.int32)
                   for n in (5, 11)]
        rids = [eng.submit(p, max_new_tokens=8) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            np.testing.assert_array_equal(res[rid].tokens,
                                          _ref_new_tokens(m, p, 8))
