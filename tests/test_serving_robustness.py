"""Serving robustness (inference/serving.py, docs/ROBUSTNESS.md): deadlines
finish overdue requests without touching batch-mates, cancel() evicts
anywhere in the lifecycle, the bounded queue rejects or priority-sheds,
per-slot failures are isolated (injected via the serving/slot failpoint),
health() reports ok/degraded/draining, and a stalled run_until_complete
fails its in-flight requests instead of leaving them dangling."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.inference.serving import QueueFullError, ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.testing import failpoints as fp


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.reset()
    yield
    fp.reset()


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def prompts():
    rng = np.random.RandomState(0)
    return [rng.randint(0, 64, (n,)).astype(np.int32) for n in (5, 9, 4)]


def _ref(m, p, n):
    out = m.generate(paddle.to_tensor(p[None]), max_new_tokens=n,
                     temperature=0.0)
    return np.asarray(out._data)[0, len(p):]


class TestDeadlines:
    def test_overdue_request_finishes_with_deadline_reason(self, model,
                                                           prompts):
        eng = ServingEngine(model, max_batch=2)
        r1 = eng.submit(prompts[0], max_new_tokens=6)
        r2 = eng.submit(prompts[1], max_new_tokens=6, deadline_ms=0.001)
        time.sleep(0.005)
        res = eng.run_until_complete()
        assert res[r2].finish_reason == "deadline"
        # the batch-mate is untouched: exact greedy parity
        np.testing.assert_array_equal(res[r1].tokens,
                                      _ref(model, prompts[0], 6))
        assert res[r1].finish_reason == "length"

    def test_mid_decode_deadline(self, model, prompts):
        eng = ServingEngine(model, max_batch=2)
        # warm the whole program family first: the deadline clock starts
        # at submit, and a cold first step pays seconds of compile
        eng.submit(prompts[2], max_new_tokens=2)
        eng.run_until_complete()
        r1 = eng.submit(prompts[0], max_new_tokens=30)
        r2 = eng.submit(prompts[1], max_new_tokens=30, deadline_ms=500)
        for _ in range(3):
            eng.step()
        assert not eng.get_request(r2).finished
        time.sleep(0.6)
        eng.step()
        assert eng.get_request(r2).finish_reason == "deadline"
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[r1].tokens,
                                      _ref(model, prompts[0], 30))

    def test_deadline_expiry_is_reported_by_step(self, model, prompts):
        """step() returns every request finished during THAT step —
        deadline expiries included, not just eos/length/error, or a
        caller consuming step()'s return leaks expired requests."""
        eng = ServingEngine(model, max_batch=1)
        rid = eng.submit(prompts[0], max_new_tokens=2, deadline_ms=0.001)
        time.sleep(0.005)
        done = eng.step()
        assert [r.rid for r in done] == [rid]
        assert done[0].finish_reason == "deadline"

    def test_deadline_metric_counts(self, model, prompts):
        monitor.reset()
        eng = ServingEngine(model, max_batch=1)
        eng.submit(prompts[0], max_new_tokens=2, deadline_ms=0.001)
        time.sleep(0.005)
        eng.run_until_complete()
        assert monitor.counter(
            "request_deadline_exceeded_total").value == 1

    def test_deadline_validation(self, model, prompts):
        eng = ServingEngine(model, max_batch=1)
        with pytest.raises(ValueError, match="deadline_ms"):
            eng.submit(prompts[0], deadline_ms=0)
        with pytest.raises(ValueError, match="deadline_ms"):
            eng.submit(prompts[0], deadline_ms=-5)


class TestCancel:
    def test_cancel_everywhere_in_the_lifecycle(self, model, prompts):
        eng = ServingEngine(model, max_batch=1)
        r1 = eng.submit(prompts[0], max_new_tokens=8)
        r2 = eng.submit(prompts[1], max_new_tokens=8)
        eng.step()                       # r1 active, r2 queued
        assert eng.cancel(r2) is True    # queued
        assert eng.get_request(r2).finish_reason == "cancelled"
        assert eng.cancel(r1) is True    # in-flight (slot freed)
        assert eng.cancel(r1) is False   # already finished
        with pytest.raises(KeyError):
            eng.cancel(10_000)
        assert not eng.has_work()

    def test_cancelled_slot_is_reused(self, model, prompts):
        eng = ServingEngine(model, max_batch=1)
        r1 = eng.submit(prompts[0], max_new_tokens=20)
        eng.step()
        eng.cancel(r1)
        r2 = eng.submit(prompts[1], max_new_tokens=5)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[r2].tokens,
                                      _ref(model, prompts[1], 5))


class TestBoundedQueue:
    def test_queue_full_raises(self, model, prompts):
        monitor.reset()
        eng = ServingEngine(model, max_batch=1, max_queue=1)
        eng.submit(prompts[0], max_new_tokens=2)
        with pytest.raises(QueueFullError, match="queue full"):
            eng.submit(prompts[1], max_new_tokens=2)
        shed = monitor.counter("request_shed_total", labelnames=("reason",))
        assert shed.labels(reason="queue_full").value == 1

    def test_higher_priority_sheds_lowest(self, model, prompts):
        monitor.reset()
        eng = ServingEngine(model, max_batch=1, max_queue=1)
        low = eng.submit(prompts[0], max_new_tokens=2, priority=0)
        high = eng.submit(prompts[1], max_new_tokens=2, priority=5)
        assert eng.get_request(low).finish_reason == "shed"
        shed = monitor.counter("request_shed_total", labelnames=("reason",))
        assert shed.labels(reason="preempted").value == 1
        res = eng.run_until_complete()
        assert res[high].finish_reason == "length"

    def test_equal_priority_does_not_shed(self, model, prompts):
        eng = ServingEngine(model, max_batch=1, max_queue=1)
        eng.submit(prompts[0], max_new_tokens=2, priority=3)
        with pytest.raises(QueueFullError):
            eng.submit(prompts[1], max_new_tokens=2, priority=3)

    def test_max_queue_validation(self, model):
        with pytest.raises(ValueError, match="max_queue"):
            ServingEngine(model, max_batch=1, max_queue=0)


class TestErrorIsolation:
    def test_injected_slot_error_evicts_only_that_request(self, model,
                                                          prompts):
        eng = ServingEngine(model, max_batch=2)
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts[:2]]
        eng.step()   # both admitted + first decode
        with fp.scoped("serving/slot=error:1"):
            eng.step()
        res = eng.run_until_complete()
        reasons = {rid: res[rid].finish_reason for rid in rids}
        assert sorted(reasons.values()) == ["error", "length"]
        # the survivor decodes to EXACT parity — its slot never noticed
        (surv,) = [rid for rid in rids if reasons[rid] == "length"]
        np.testing.assert_array_equal(
            res[surv].tokens,
            _ref(model, prompts[rids.index(surv)], 6))

    def test_step_site_error_propagates_but_state_survives(self, model,
                                                           prompts):
        eng = ServingEngine(model, max_batch=2)
        r1 = eng.submit(prompts[0], max_new_tokens=6)
        with fp.scoped("serving/step=error:1"):
            with pytest.raises(fp.FailpointError):
                eng.step()
        res = eng.run_until_complete()   # engine still functional
        np.testing.assert_array_equal(res[r1].tokens,
                                      _ref(model, prompts[0], 6))


class TestHealthAndDrain:
    def test_health_transitions(self, model, prompts):
        eng = ServingEngine(model, max_batch=2, max_queue=10)
        assert eng.health()["state"] == "ok"
        eng.submit(prompts[0], max_new_tokens=4)
        eng.step()
        with fp.scoped("serving/slot=error:1"):
            eng.step()
        assert eng.health()["state"] == "degraded"   # recent slot error
        eng.drain()
        assert eng.health()["state"] == "draining"
        with pytest.raises(RuntimeError, match="draining"):
            eng.submit(prompts[1])
        eng.drain(False)
        assert eng.health()["state"] == "degraded"   # error still recent

    def test_queue_pressure_degrades(self, model, prompts):
        eng = ServingEngine(model, max_batch=1, max_queue=2)
        eng.submit(prompts[0], max_new_tokens=2)
        eng.submit(prompts[1], max_new_tokens=2)
        assert eng.health()["state"] == "degraded"
        assert eng.stats()["health"]["state"] == "degraded"

    def test_stats_carries_health(self, model):
        eng = ServingEngine(model, max_batch=1)
        h = eng.stats()["health"]
        assert h["state"] == "ok" and h["queue_depth"] == 0


class TestStall:
    def test_non_convergence_fails_in_flight_requests(self, model, prompts):
        eng = ServingEngine(model, max_batch=1)
        r1 = eng.submit(prompts[0], max_new_tokens=30)
        r2 = eng.submit(prompts[1], max_new_tokens=30)
        with pytest.raises(RuntimeError) as ei:
            eng.run_until_complete(max_steps=3)
        msg = str(ei.value)
        assert "engine_stalled" in msg
        assert str(r1) in msg and str(r2) in msg
        for rid in (r1, r2):
            assert eng.get_request(rid).finish_reason == "engine_stalled"
        assert not eng.has_work()
