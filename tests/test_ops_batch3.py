"""Op-test burn-down, batch 3: norm / conv variants / linalg decompositions /
einsum / fft / vision-adjacent ops (SURVEY §4 continuation)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

from op_test import OpTest

rng = np.random.RandomState(21)
X = rng.randn(3, 4).astype(np.float32)


class TestEinsumOps(OpTest):
    def setUp(self):
        a = rng.randn(2, 3).astype(np.float32)
        b = rng.randn(3, 4).astype(np.float32)
        self.op = lambda a, b: paddle.einsum("ij,jk->ik", a, b)
        self.inputs = {"a": a, "b": b}
        self.outputs = [a @ b]

    def test(self):
        self.check_output()
        self.check_grad(["a", "b"])


class TestBmmOp(OpTest):
    def setUp(self):
        a = rng.randn(2, 3, 4).astype(np.float32)
        b = rng.randn(2, 4, 2).astype(np.float32)
        self.op = paddle.bmm
        self.inputs = {"a": a, "b": b}
        self.outputs = [a @ b]

    def test(self):
        self.check_output()
        self.check_grad(["a"], max_elems=24)


class TestCholeskyOp(OpTest):
    def setUp(self):
        a = rng.randn(4, 4).astype(np.float32)
        spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
        self.op = paddle.linalg.cholesky
        self.inputs = {"x": spd}
        self.outputs = [np.linalg.cholesky(spd)]

    def test(self):
        self.check_output(atol=1e-4, rtol=1e-4)


class TestSolveOps:
    def test_solve_and_triangular(self):
        a = rng.randn(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        b = rng.randn(3, 2).astype(np.float32)
        out = paddle.linalg.solve(paddle.to_tensor(a), paddle.to_tensor(b))
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.linalg.solve(a, b), atol=1e-4)

    def test_qr_svd_eigh(self):
        a = rng.randn(4, 3).astype(np.float32)
        q, r = paddle.linalg.qr(paddle.to_tensor(a))
        np.testing.assert_allclose(np.asarray(q._data) @ np.asarray(r._data),
                                   a, atol=1e-4)
        u, s, v = paddle.linalg.svd(paddle.to_tensor(a))  # paddle returns V
        np.testing.assert_allclose(
            (np.asarray(u._data)[:, :3] * np.asarray(s._data))
            @ np.asarray(v._data).T,
            a, atol=1e-4)
        sym = a.T @ a
        w, v = paddle.linalg.eigh(paddle.to_tensor(sym))
        np.testing.assert_allclose(
            np.asarray(v._data) @ np.diag(np.asarray(w._data)) @ np.asarray(v._data).T,
            sym, atol=1e-3)


class TestGroupedConvOp(OpTest):
    def setUp(self):
        x = rng.randn(1, 4, 5, 5).astype(np.float32)
        w = rng.randn(4, 2, 3, 3).astype(np.float32)  # groups=2
        self.op = lambda x, w: F.conv2d(x, w, padding=1, groups=2)
        self.inputs = {"x": x, "w": w}
        out = np.zeros((1, 4, 5, 5), np.float32)
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        for g in range(2):
            for co in range(2):
                oc = g * 2 + co
                for i in range(5):
                    for j in range(5):
                        out[0, oc, i, j] = np.sum(
                            xp[0, g * 2:(g + 1) * 2, i:i + 3, j:j + 3] * w[oc])
        self.outputs = [out]

    def test(self):
        self.check_output(atol=1e-4, rtol=1e-3)


class TestConvTransposeOp:
    def test_conv2d_transpose_shape_and_grad(self):
        x = paddle.to_tensor(rng.randn(1, 2, 4, 4).astype(np.float32))
        x.stop_gradient = False
        w = paddle.to_tensor(rng.randn(2, 3, 2, 2).astype(np.float32))
        out = F.conv2d_transpose(x, w, stride=2)
        assert tuple(out.shape) == (1, 3, 8, 8)
        out.sum().backward()
        assert x.grad is not None
        assert np.isfinite(np.asarray(x.grad._data)).all()


class TestNormOps:
    def test_batch_norm_functional_train_stats(self):
        x = rng.randn(8, 4).astype(np.float32)
        xt = paddle.to_tensor(x)
        rm = paddle.zeros([4])
        rv = paddle.ones([4])
        out = F.batch_norm(xt, rm, rv, training=True, momentum=0.9)
        ref = (x - x.mean(0)) / np.sqrt(x.var(0) + 1e-5)
        np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-4)

    def test_group_norm(self):
        x = rng.randn(2, 4, 3, 3).astype(np.float32)
        out = F.group_norm(paddle.to_tensor(x), num_groups=2, epsilon=1e-5)
        g = x.reshape(2, 2, 2 * 3 * 3)
        ref = ((g - g.mean(-1, keepdims=True))
               / np.sqrt(g.var(-1, keepdims=True) + 1e-5)).reshape(x.shape)
        np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-4)

    def test_instance_norm(self):
        x = rng.randn(2, 3, 4, 4).astype(np.float32)
        out = F.instance_norm(paddle.to_tensor(x))
        m = x.mean(axis=(2, 3), keepdims=True)
        v = x.var(axis=(2, 3), keepdims=True)
        np.testing.assert_allclose(np.asarray(out._data),
                                   (x - m) / np.sqrt(v + 1e-5), atol=1e-4)


class TestFFTOps:
    def test_fft_roundtrip(self):
        x = rng.randn(8).astype(np.float32)
        f = paddle.fft.fft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(f._data), np.fft.fft(x),
                                   atol=1e-4)
        back = paddle.fft.ifft(f)
        np.testing.assert_allclose(np.asarray(back._data).real, x, atol=1e-4)

    def test_rfft(self):
        x = rng.randn(8).astype(np.float32)
        f = paddle.fft.rfft(paddle.to_tensor(x))
        np.testing.assert_allclose(np.asarray(f._data), np.fft.rfft(x),
                                   atol=1e-4)


class TestVisionAdjacent:
    def test_pixel_shuffle(self):
        x = rng.randn(1, 4, 2, 2).astype(np.float32)
        out = F.pixel_shuffle(paddle.to_tensor(x), 2)
        assert tuple(out.shape) == (1, 1, 4, 4)

    def test_interpolate_bilinear_matches_numpy_corners(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        out = F.interpolate(paddle.to_tensor(x), size=(8, 8), mode="bilinear",
                            align_corners=True)
        o = np.asarray(out._data)
        assert o[0, 0, 0, 0] == 0.0 and o[0, 0, -1, -1] == 15.0

    def test_grid_sample_identity(self):
        x = rng.randn(1, 1, 4, 4).astype(np.float32)
        ys, xs = np.meshgrid(np.linspace(-1, 1, 4), np.linspace(-1, 1, 4),
                             indexing="ij")
        grid = np.stack([xs, ys], -1)[None].astype(np.float32)
        out = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                            align_corners=True)
        np.testing.assert_allclose(np.asarray(out._data), x, atol=1e-5)


class TestRNNCells:
    def test_lstm_cell_manual_reference(self):
        from paddle_tpu import nn

        paddle.seed(0)
        cell = nn.LSTMCell(4, 3)
        x = paddle.to_tensor(rng.randn(2, 4).astype(np.float32))
        h0 = paddle.to_tensor(np.zeros((2, 3), np.float32))
        c0 = paddle.to_tensor(np.zeros((2, 3), np.float32))
        out, (h1, c1) = cell(x, (h0, c0))
        # manual gate math from the cell's own weights
        wi = np.asarray(cell.weight_ih._data)
        wh = np.asarray(cell.weight_hh._data)
        bi = np.asarray(cell.bias_ih._data)
        bh = np.asarray(cell.bias_hh._data)
        z = np.asarray(x._data) @ wi.T + bi + np.zeros((2, 3)) @ wh.T + bh
        i, f, g, o = np.split(z, 4, axis=1)
        sig = lambda v: 1 / (1 + np.exp(-v))
        c_ref = sig(f) * 0 + sig(i) * np.tanh(g)
        h_ref = sig(o) * np.tanh(c_ref)
        np.testing.assert_allclose(np.asarray(h1._data), h_ref, atol=1e-4)
        np.testing.assert_allclose(np.asarray(c1._data), c_ref, atol=1e-4)

    def test_gru_sequence_shapes(self):
        from paddle_tpu import nn

        paddle.seed(0)
        gru = nn.GRU(input_size=4, hidden_size=3, num_layers=2)
        x = paddle.to_tensor(rng.randn(2, 5, 4).astype(np.float32))
        out, h = gru(x)
        assert tuple(out.shape) == (2, 5, 3)
        assert tuple(h.shape) == (2, 2, 3)
