"""dy2static control-flow transform tests (ifelse/loop transformer parity)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit.dy2static import (
    convert_ifelse, convert_while_loop, transform_function,
)


class TestRuntimeDispatch:
    def test_ifelse_python_pred(self):
        out = convert_ifelse(True, lambda _: (1,), lambda _: (2,))
        assert out == (1,)
        out = convert_ifelse(False, lambda _: (1,), lambda _: (2,))
        assert out == (2,)

    def test_ifelse_tensor_pred(self):
        x = paddle.to_tensor(3.0)
        (y,) = convert_ifelse(x > paddle.to_tensor(0.0),
                              lambda s: (s[0] * paddle.to_tensor(2.0),),
                              lambda s: (s[0] - paddle.to_tensor(1.0),),
                              seed=(x,))
        assert float(np.asarray(y._data)) == 6.0

    def test_ifelse_mismatched_branch_kinds_rejected(self):
        import jax.numpy as jnp

        x = paddle.to_tensor(1.0)
        with pytest.raises(TypeError, match="different value kinds"):
            convert_ifelse(x > paddle.to_tensor(0.0),
                           lambda s: (jnp.zeros(2),),
                           lambda s: (paddle.to_tensor(np.ones(2, np.float32)),))

    def test_while_python_cond(self):
        out = convert_while_loop(lambda c: c[0] < 5,
                                 lambda c: (c[0] + 1,), (0,))
        assert out == (5,)

    def test_while_tensor_cond(self):
        i0 = paddle.to_tensor(0.0)
        (i,) = convert_while_loop(
            lambda c: c[0] < paddle.to_tensor(5.0),
            lambda c: (c[0] + paddle.to_tensor(1.0),), (i0,))
        assert float(np.asarray(i._data)) == 5.0


class TestASTTransform:
    def test_if_transformed_and_jittable(self):
        def f(x):
            if (x.sum() > paddle.to_tensor(0.0)):
                y = x * paddle.to_tensor(2.0)
            else:
                y = x - paddle.to_tensor(1.0)
            return y

        new, n = transform_function(f)
        assert n == 1
        xp = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        xn = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
        np.testing.assert_allclose(np.asarray(new(xp)._data), [2.0, 4.0])
        np.testing.assert_allclose(np.asarray(new(xn)._data), [-2.0, -3.0])

        # under @to_static the lax.cond path compiles (no trace-time branch)
        static = paddle.jit.to_static(f)
        np.testing.assert_allclose(np.asarray(static(xp)._data), [2.0, 4.0])
        np.testing.assert_allclose(np.asarray(static(xn)._data), [-2.0, -3.0])

    def test_trace_only_would_freeze_branch(self):
        """Without the transform, tracing bakes in one branch — the transform
        is what makes both sides of the data-dependent if reachable."""
        def f(x):
            if (x.sum() > paddle.to_tensor(0.0)):
                y = x * paddle.to_tensor(2.0)
            else:
                y = x - paddle.to_tensor(1.0)
            return y

        static = paddle.jit.to_static(f)
        xp = paddle.to_tensor(np.array([1.0], np.float32))
        xn = paddle.to_tensor(np.array([-1.0], np.float32))
        # same shape/dtype -> same compiled cache entry; both branches correct
        np.testing.assert_allclose(np.asarray(static(xp)._data), [2.0])
        np.testing.assert_allclose(np.asarray(static(xn)._data), [-2.0])

    def test_while_transformed(self):
        def f(x):
            i = paddle.to_tensor(0.0)
            s = paddle.to_tensor(0.0)
            while (i < x):
                s = s + i
                i = i + paddle.to_tensor(1.0)
            return s

        new, n = transform_function(f)
        assert n == 1
        out = new(paddle.to_tensor(5.0))
        assert float(np.asarray(out._data)) == 10.0  # 0+1+2+3+4

        static = paddle.jit.to_static(f)
        out2 = static(paddle.to_tensor(5.0))
        assert float(np.asarray(out2._data)) == 10.0

    def test_untransformable_falls_back(self):
        def f(x):
            if x.sum() > paddle.to_tensor(0.0):
                return x  # return inside branch -> not transformed
            return x * paddle.to_tensor(2.0)

        new, n = transform_function(f)
        assert n == 0 and new is f

    def test_layer_forward_with_tensor_if(self):
        from paddle_tpu import nn

        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                h = self.fc(x)
                if (h.sum() > paddle.to_tensor(0.0)):
                    out = h * paddle.to_tensor(2.0)
                else:
                    out = -h
                return out

        paddle.seed(0)
        net = Net()
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4).astype(np.float32))
        eager = net(x)
        static_net = paddle.jit.to_static(Net())
        static_net.set_state_dict(net.state_dict())
        out = static_net(x)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(eager._data), atol=1e-5)

    def test_host_flag_ifs_not_transformed(self):
        def f(x, mask=None):
            if mask is not None:
                z = x + mask
                x = z * paddle.to_tensor(2.0)
            if isinstance(x, object):
                w = x
            return x

        # `is not None` / isinstance guards stay plain python — no NameError
        # from the untaken branch's unbound locals
        new, n = transform_function(f)
        assert n == 0

    def test_loop_local_temp_supported(self):
        """Regression: temporaries first assigned inside the loop body must
        not poison the carry (code-review finding)."""
        def f(x, n):
            i = paddle.to_tensor(0.0)
            while (i < n):
                t = x * paddle.to_tensor(2.0)
                x = x + t
                i = i + paddle.to_tensor(1.0)
            return x

        new, cnt = transform_function(f)
        assert cnt == 1
        out = new(paddle.to_tensor(1.0), paddle.to_tensor(3.0))
        assert float(np.asarray(out._data)) == 27.0  # x *= 3 each iter

    def test_if_augassign_supported(self):
        """Regression: aug-assign in a rewritten branch reads the pre-branch
        binding via the seed carry (code-review finding)."""
        def f(x, n):
            s = x
            if (n > paddle.to_tensor(0.0)):
                s += x
            return s

        new, cnt = transform_function(f)
        assert cnt == 1
        out = new(paddle.to_tensor(2.0), paddle.to_tensor(1.0))
        assert float(np.asarray(out._data)) == 4.0
        out = new(paddle.to_tensor(2.0), paddle.to_tensor(-1.0))
        assert float(np.asarray(out._data)) == 2.0

    def test_disjoint_branch_assignment_skipped(self):
        """`if: y=.. else: z=..` with no prior bindings cannot be rewritten."""
        def f(x):
            if (x.sum() > paddle.to_tensor(0.0)):
                y = x
            else:
                z = -x
            return x

        new, cnt = transform_function(f)
        assert cnt == 0

    def test_nested_if_in_while(self):
        """Regression: generated __dy2st_* helpers of an inner rewrite must
        not leak into the outer loop carry (code-review finding)."""
        def f(x):
            i = paddle.to_tensor(0.0)
            while (i < paddle.to_tensor(3.0)):
                if (x.sum() > paddle.to_tensor(0.0)):
                    x = x - paddle.to_tensor(1.0)
                else:
                    x = x + paddle.to_tensor(1.0)
                i = i + paddle.to_tensor(1.0)
            return x

        new, cnt = transform_function(f)
        assert cnt == 2
        out = new(paddle.to_tensor(np.array([2.0], np.float32)))
        # 3 iters: 2>0 -> 1; 1>0 -> 0; 0>0 false -> +1 => 1
        assert float(np.asarray(out._data)[0]) == 1.0

    def test_conditionally_bound_local_not_in_carry(self):
        """ADVICE r1: may-bound analysis swept a conditionally-assigned local
        into the seed and NameError'd at runtime. Must-bound analysis keeps
        it out of the carry (the tensor-if is then skipped or safe)."""
        def f(x, flag):
            if flag:            # host if: binds y only on one path
                y = x * 2.0
            if (x.sum() > paddle.to_tensor(0.0)):
                z = x + 1.0
            else:
                z = x - 1.0
            return z

        new, cnt = transform_function(f)
        out = new(paddle.to_tensor(np.array([1.0], np.float32)), False)
        assert float(np.asarray(out._data)[0]) == 2.0

    def test_none_local_falls_back_at_call_time(self):
        """ADVICE r1: a None local swept into the carry raised TypeError with
        no recovery. StaticFunction now falls back to plain tracing."""
        import warnings as _w

        class M(paddle.nn.Layer):
            def forward(self, x):
                state = None
                i = paddle.to_tensor(0.0)
                while (i < paddle.to_tensor(2.0)):
                    state = x if state is None else state + x
                    i = i + paddle.to_tensor(1.0)
                return state

        m = paddle.jit.to_static(M())
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            out = m(paddle.to_tensor(np.array([3.0], np.float32)))
        assert float(np.asarray(out._data)[0]) == 6.0

    def test_maybound_loop_write_not_dropped(self):
        """Code-review r2: a while-body write to a conditionally-bound name
        must not be discarded as a loop-local temp — the loop stays python
        (transform bails) so semantics are preserved."""
        def f(x, flag):
            i = paddle.to_tensor(0.0)
            if flag:
                y = paddle.to_tensor(0.0)
            while (i < x.sum()):
                y = i * 2.0
                i = i + 1.0
            return y

        new, cnt = transform_function(f)
        out = new(paddle.to_tensor(np.array([3.0], np.float32)), True)
        # eager semantics: loop runs i=0,1,2 -> y = 2*2 = 4
        assert float(np.asarray(out._data if hasattr(out, "_data") else out)) == 4.0

    def test_branch_structure_mismatch_falls_back(self):
        """Code-review r2: a tensor-if whose branches produce mismatched
        structures falls back to eager instead of hard-failing."""
        import warnings as _w

        class M(paddle.nn.Layer):
            def forward(self, x):
                if (x.sum() > paddle.to_tensor(0.0)):
                    z = x + 1.0
                else:
                    z = 0.0  # python float vs Tensor: structure mismatch
                return z

        m = paddle.jit.to_static(M())
        with _w.catch_warnings():
            _w.simplefilter("ignore")
            out = m(paddle.to_tensor(np.array([2.0], np.float32)))
        assert float(np.asarray(out._data)) == 3.0

    def test_bound_staticfunction_cached_on_instance(self):
        """Code-review r2: class-level @to_static methods must reuse one
        StaticFunction per instance (jit cache + fallback state persist)."""
        class M(paddle.nn.Layer):
            @paddle.jit.to_static
            def forward(self, x):
                return x * 2.0

        m = M()
        assert m.forward is m.forward


class TestLoopLowering:
    """VERDICT r1 #10: for-range, break/continue, fallback diagnostics."""

    def test_for_range_tensor_bound(self):
        """for i in range(n) with a traced bound compiles to lax.while_loop."""
        def f(n):
            acc = paddle.to_tensor(0.0)
            i0 = paddle.to_tensor(0.0)  # keeps acc float-kind stable
            for i in range(n):
                acc = acc + float(1.0) * (i0 + i)
            return acc

        new, cnt = transform_function(f)
        assert cnt >= 1
        out = new(paddle.to_tensor(np.int32(5)))
        assert float(np.asarray(out._data)) == 10.0  # 0+1+2+3+4

    def test_for_range_two_args_host_still_correct(self):
        def f(x):
            for i in range(2, 5):
                x = x + i
            return x

        new, cnt = transform_function(f)
        out = new(paddle.to_tensor(np.array([0.0], np.float32)))
        assert float(np.asarray(out._data)[0]) == 9.0  # 2+3+4

    def test_while_true_if_break(self):
        """`while True: ... if p: break` lowers to a flag-gated lax loop."""
        def f(x):
            i = paddle.to_tensor(0.0)
            while (i < paddle.to_tensor(100.0)):
                x = x * 2.0
                i = i + 1.0
                if (x.sum() > paddle.to_tensor(50.0)):
                    break
            return x, i

        new, cnt = transform_function(f)
        assert cnt >= 1
        x, i = new(paddle.to_tensor(np.array([1.0], np.float32)))
        assert float(np.asarray(x._data)[0]) == 64.0  # first power of 2 > 50
        assert float(np.asarray(i._data)) == 6.0

    def test_if_continue(self):
        """`if p: continue` guards the rest of the iteration."""
        def f(x):
            i = paddle.to_tensor(0.0)
            acc = paddle.to_tensor(0.0)
            while (i < paddle.to_tensor(6.0)):
                i = i + 1.0
                if (i % 2.0 < 1.0):
                    continue
                acc = acc + i
            return acc

        new, cnt = transform_function(f)
        assert cnt >= 1
        out = new(paddle.to_tensor(np.array([0.0], np.float32)))
        assert float(np.asarray(out._data)) == 9.0  # 1+3+5

    def test_fallback_warning_names_construct(self):
        import warnings as _w

        def f(x):
            i = paddle.to_tensor(0.0)
            while (i < x.sum()):
                for unsupported in [1, 2]:
                    if (x.sum() > paddle.to_tensor(0.0)):
                        break  # nested break: unsupported shape
                i = i + 1.0
            return i

        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            transform_function(f)
        msgs = [str(r.message) for r in rec]
        assert any("not rewritten" in m and "break" in m for m in msgs), msgs

    def test_host_loops_stay_quiet(self):
        import warnings as _w

        def f(x, flag):
            for item in [1, 2, 3]:
                if flag:
                    break
                x = x + item
            while flag:
                break
            return x

        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            transform_function(f)
        assert not [r for r in rec if "not rewritten" in str(r.message)], \
            [str(r.message) for r in rec]

    def test_for_range_with_continue_terminates(self):
        """Review r2c #1: continue must not skip the loop-var increment."""
        def f(x):
            acc = paddle.to_tensor(0.0)
            i0 = paddle.to_tensor(0.0)
            for i in range(5):
                if ((i0 + i) % 2.0 < 1.0):
                    continue
                acc = acc + (i0 + i)
            return acc

        new, cnt = transform_function(f)
        out = new(paddle.to_tensor(np.array([0.0], np.float32)))
        assert float(np.asarray(out._data)) == 4.0  # 1 + 3

    def test_while_true_break_under_to_static(self):
        """Review r2c #2: host-True first condition must still switch to lax
        when the break flag becomes traced (no TracerBoolConversionError)."""
        class M(paddle.nn.Layer):
            def forward(self, x):
                i = paddle.to_tensor(0.0)
                while True:
                    x = x * 2.0
                    i = i + 1.0
                    if (x.sum() > paddle.to_tensor(50.0)):
                        break
                return x

        m = paddle.jit.to_static(M())
        out = m(paddle.to_tensor(np.array([1.0], np.float32)))
        assert float(np.asarray(out._data)[0]) == 64.0

    def test_for_range_loop_var_python_semantics(self):
        """Review r2c #3: after the loop the var holds the last yielded value
        and body reassignment cannot derail the iteration count."""
        def f(x):
            for i in range(3):
                x = x + 1.0
            return x * 0.0 + i

        new, cnt = transform_function(f)
        out = new(paddle.to_tensor(np.array([0.0], np.float32)))
        assert float(np.asarray(out._data)[0]) == 2.0

        def g(x):
            cnt2 = paddle.to_tensor(0.0)
            for i in range(5):
                i = 0  # must not make the loop infinite
                cnt2 = cnt2 + 1.0
            return cnt2

        new_g, _ = transform_function(g)
        out = new_g(paddle.to_tensor(np.array([0.0], np.float32)))
        assert float(np.asarray(out._data)) == 5.0

    def test_break_short_circuits_loop_test(self):
        """Review r2d: once the break flag fires, the original loop test must
        not be re-evaluated (it may only be safe while in bounds)."""
        def f(lst, x):
            i = 0
            while lst[i] > 0:
                x = x + lst[i]
                i = i + 1
                if i >= len(lst):
                    break
            return x

        new, cnt = transform_function(f)
        out = new([1.0, 2.0, 3.0], paddle.to_tensor(np.array([0.0], np.float32)))
        assert float(np.asarray(out._data)[0]) == 6.0

    def test_dynamic_batch_jit_save_roundtrip(self, tmp_path):
        """Review r2d: None batch dims export symbolically — the loaded
        artifact serves ANY batch size."""
        import paddle_tpu as p

        net = p.nn.Sequential(p.nn.Linear(4, 3))
        prefix = str(tmp_path / "dyn")
        p.jit.save(net, prefix,
                   input_spec=[p.jit.InputSpec([None, 4], "float32")])
        loaded = p.jit.load(prefix)
        for bs in (1, 2, 7):
            x = p.to_tensor(np.ones((bs, 4), np.float32))
            got = loaded(x)
            np.testing.assert_allclose(np.asarray(got._data),
                                       np.asarray(net(x)._data), rtol=1e-5)


def test_print_transform_traced(capfd):
    import paddle_tpu as paddle

    @paddle.jit.to_static
    def f(x):
        print("value:", x)
        return x * 2

    out = f(paddle.to_tensor(np.array([3.0], np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), [6.0])
    # jax.debug.print writes to stdout once the computation runs
    import jax

    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    captured = capfd.readouterr()
    assert "value:" in captured.out


def test_assert_transform():
    import paddle_tpu as paddle

    @paddle.jit.to_static
    def f(x):
        assert x.sum() > 0, "must be positive"
        return x + 1

    out = f(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), [2.0, 3.0])

    # failing assert halts execution (reference Assert op semantics); the
    # bool arg is traced by the jitted wrapper, so the AssertionError from
    # the host callback surfaces wrapped in JAX's runtime error
    @paddle.jit.to_static
    def g(x, flag):
        assert flag, "flag off"
        return x

    with pytest.raises(Exception, match="flag off"):
        g(paddle.to_tensor(np.array([1.0], np.float32)), False)


def test_assert_msg_lazy():
    """ADVICE r2: a passing assert must not evaluate its msg expression
    (python semantics); a failing one must."""
    evals = []

    def expensive():
        evals.append(1)
        return "boom"

    @paddle.jit.to_static
    def ok(x):
        assert x.shape[0] > 0, expensive()
        return x + 1

    out = ok(paddle.to_tensor(np.ones(3, np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), 2.0)
    assert evals == []  # msg never computed on the passing path

    @paddle.jit.to_static
    def bad(x):
        assert x.shape[0] > 99, expensive()
        return x

    import pytest
    with pytest.raises(AssertionError, match="boom"):
        bad(paddle.to_tensor(np.ones(3, np.float32)))
    assert evals == [1]
