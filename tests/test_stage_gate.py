"""Tier-1 gate for the MPMD stage-program runtime (ISSUE 15): with
FLAGS_mpmd unset, PipelineTrainer and DisaggregatedPool are EXACTLY the
pre-PR runtimes — paddle_tpu.distributed.stage is never imported
(subprocess pin), pipeline params and pool completions are byte-identical
whether or not the armed MPMD path was ever exercised in-process, no
stage_graph/stage_step span and no {op=stage_edge} series appears, the
flag is joined into the dp trainer's _exec_key (and AOT extra_key) so an
armed world can never alias a disarmed executable, the disarmed per-step
flag checks cost the same one-lookup bar as every other disabled fast
path, and a post-construction toggle raises instead of silently
re-basing a live runtime. Plus: the tools/metrics_dump.py --mpmd,
tools/parity_check.py mpmd_* targets, and tools/chaos_check.py
stage_backpressure exit-code contracts."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor, trace
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.pipeline import PipelineTrainer
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.models import GPTConfig, GPTForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: span names this PR introduced — with the flag unset NONE may appear
STAGE_SPANS = ("stage_graph", "stage_step")


def _tiny_pipeline(**kw):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=32, dropout=0.0)
    model = GPTForCausalLM(cfg)
    pre, stages, post = model.pipeline_split(2)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    mesh = build_mesh((2,), ("pp",), devices=jax.devices()[:2])
    return PipelineTrainer(pre, stages, post, opt, mesh=mesh, n_micro=2,
                           schedule_mode="1F1B", **kw)


_PLAIN_RUNTIMES = (
    "import os\n"
    "os.environ.setdefault('XLA_FLAGS',\n"
    "    '--xla_force_host_platform_device_count=8')\n"
    "import jax; jax.config.update('jax_platforms', 'cpu')\n"
    "import hashlib\n"
    "import numpy as np\n"
    "import paddle_tpu as paddle\n"
    "from paddle_tpu.distributed.mesh import build_mesh\n"
    "from paddle_tpu.distributed.pipeline import PipelineTrainer\n"
    "from paddle_tpu.models import GPTConfig, GPTForCausalLM\n"
    "from paddle_tpu.serving.disagg import DisaggregatedPool\n"
    "def build_pipe(**kw):\n"
    "    paddle.seed(0)\n"
    "    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,\n"
    "                    num_heads=2, max_seq_len=32, dropout=0.0)\n"
    "    model = GPTForCausalLM(cfg)\n"
    "    pre, stages, post = model.pipeline_split(2)\n"
    "    opt = paddle.optimizer.AdamW(learning_rate=1e-3,\n"
    "        parameters=model.parameters())\n"
    "    mesh = build_mesh((2,), ('pp',), devices=jax.devices()[:2])\n"
    "    return PipelineTrainer(pre, stages, post, opt, mesh=mesh,\n"
    "                           n_micro=2, schedule_mode='1F1B', **kw)\n"
    "def run_pipe(**kw):\n"
    "    tr = build_pipe(**kw)\n"
    "    rng = np.random.RandomState(0)\n"
    "    for _ in range(2):\n"
    "        tr.train_step(rng.randint(0, 64, (4, 16)).astype(np.int32),\n"
    "                      rng.randint(0, 64, (4, 16)).astype(np.int32))\n"
    "    h = hashlib.sha256()\n"
    "    for k in sorted(tr.params):\n"
    "        h.update(np.ascontiguousarray(\n"
    "            np.asarray(tr.params[k])).tobytes())\n"
    "    return h.hexdigest()\n"
    "def run_pool(**kw):\n"
    "    paddle.seed(0)\n"
    "    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,\n"
    "                    num_heads=2, max_seq_len=64, dropout=0.0)\n"
    "    m = GPTForCausalLM(cfg)\n"
    "    m.eval()\n"
    "    rng = np.random.RandomState(0)\n"
    "    pool = DisaggregatedPool(m, prefill_workers=1,\n"
    "                             decode_engines=1, max_batch=2, **kw)\n"
    "    rids = [pool.submit(rng.randint(0, 64, (n,)).astype(np.int32),\n"
    "                        max_new_tokens=5) for n in (5, 8)]\n"
    "    res = pool.run_until_complete()\n"
    "    return tuple(tuple(int(t) for t in res[r].tokens)\n"
    "                 for r in rids)\n")


def _run(code):
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


class TestInertByDefault:
    @pytest.mark.slow
    def test_plain_subprocess_never_imports_stage_and_pins_outputs(self):
        """The structural zero-overhead pin, in one subprocess: plain
        pipeline + pool runs (a) never import distributed.stage, and
        (b) produce byte-identical params/completions before vs after
        armed MPMD runs of BOTH runtimes in the same process — the
        disarmed step is the pre-PR step, unpolluted by the armed
        path."""
        _run(
            _PLAIN_RUNTIMES +
            "d1 = run_pipe()\n"
            "c1 = run_pool()\n"
            "import sys\n"
            "assert 'paddle_tpu.distributed.stage' not in sys.modules, \\\n"
            "    'stage imported on the plain path'\n"
            "paddle.set_flags({'mpmd': True})\n"
            "run_pipe()\n"
            "c_armed = run_pool()\n"
            "run_pool(compress=8)\n"
            "assert 'paddle_tpu.distributed.stage' in sys.modules\n"
            "assert c_armed == c1, ('armed pool completions are not '\n"
            "    'byte-identical to the monolithic hand-off')\n"
            "paddle.set_flags({'mpmd': False})\n"
            "d2 = run_pipe()\n"
            "c2 = run_pool()\n"
            "assert d1 == d2, ('flag-unset pipeline params drifted after '\n"
            "    'the MPMD path was exercised in-process')\n"
            "assert c1 == c2, ('flag-unset pool completions drifted '\n"
            "    'after the MPMD path was exercised in-process')\n"
            "print('OK')\n")

    def test_flag_unset_zero_series_spans_and_no_runner(self):
        """In-process: a flag-unset pipeline run grows no stage-PR
        series, emits no stage_graph/stage_step span even with tracing
        on, and constructs no MPMD runner or edge objects."""
        monitor.reset()
        trace.clear()
        trace.enable()
        try:
            tr = _tiny_pipeline()
            rng = np.random.RandomState(0)
            for _ in range(2):
                tr.train_step(rng.randint(0, 64, (4, 16)).astype(np.int32),
                              rng.randint(0, 64, (4, 16)).astype(np.int32))
        finally:
            trace.disable()
        assert tr._mpmd_runner is None
        names = {s.name for s in trace.spans()}
        for span in STAGE_SPANS:
            assert span not in names, span
        flat = monitor.flatten(monitor.snapshot())
        # earlier tests in the same process may have left the (zeroed)
        # family registered — drift means a series actually moved
        stage_series = [k for k, v in flat.items()
                        if ("op=stage_edge" in k
                            or k.startswith("kv_handoff_bytes_total")) and v]
        assert not stage_series, stage_series

    def test_mpmd_joined_into_exec_key(self):
        """The flag is part of the dp trainer's executable identity: a
        disarmed trainer's exec key ends False, an armed twin's ends
        True and the keys differ ONLY in that leg — an armed world can
        never alias a disarmed executable (the same pair rides the AOT
        extra_key through _aot_compile)."""
        from paddle_tpu import nn

        def one_step():
            paddle.seed(0)
            net = nn.Linear(8, 4)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
            tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
            tr.train_step(np.ones((4, 8), np.float32),
                          np.zeros((4, 4), np.float32))
            return next(iter(tr._compiled_store))

        plain_key = one_step()
        assert plain_key[-1] is False
        paddle.set_flags({"mpmd": True})
        try:
            armed_key = one_step()
        finally:
            paddle.set_flags({"mpmd": False})
        assert armed_key[-1] is True
        assert plain_key[:-1] == armed_key[:-1]

    def test_disarmed_flag_checks_under_5us(self):
        """The flag-unset per-step additions are one get_flag lookup
        each (PipelineTrainer._mpmd_active / SpmdTrainer._mpmd_active)
        — bounded at the same bar as every other disabled fast path."""
        from paddle_tpu import nn

        tr = _tiny_pipeline()
        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        dp = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            tr._mpmd_active()
            dp._mpmd_active()
        per_call_us = (time.perf_counter() - t0) / (2 * n) * 1e6
        assert per_call_us < 5.0, (
            f"disarmed mpmd flag check costs {per_call_us:.2f}us")

    def test_post_construction_toggle_raises(self):
        """FLAGS_mpmd is consumed at construction: flipping it under a
        live disarmed trainer raises instead of silently re-basing the
        schedule onto stage programs mid-run."""
        tr = _tiny_pipeline()
        rng = np.random.RandomState(0)
        x = rng.randint(0, 64, (4, 16)).astype(np.int32)
        paddle.set_flags({"mpmd": True})
        try:
            with pytest.raises(RuntimeError, match="FLAGS_mpmd"):
                tr.train_step(x, x)
        finally:
            paddle.set_flags({"mpmd": False})

    def test_edge_options_require_the_flag(self):
        """stage_meshes/compress are MPMD edge options: passing them to
        a disarmed trainer is a loud error, not a silent no-op."""
        with pytest.raises(ValueError, match="mpmd"):
            _tiny_pipeline(compress=8)

    def test_flags_defined_and_default_off(self):
        assert flags.get_flag("mpmd") is False

    def test_chaos_pass_registered(self):
        spec = importlib.util.spec_from_file_location(
            "chaos_check", os.path.join(REPO, "tools", "chaos_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert "stage_backpressure" in mod.PASSES


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestStageToolGate:
    def test_metrics_dump_mpmd_missing_metrics_exits_1(
            self, capsys, monkeypatch):
        md = _load_tool("metrics_dump")
        monkeypatch.setattr(md, "run_mpmd_loop", lambda **kw: None)
        rc = md.main(["--mpmd", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        msgs = [f["message"]
                for f in report["targets"]["mpmd"]["findings"]
                if f["pass"] == "metrics-present"]
        assert any("kv_handoff_bytes_total" in m for m in msgs)
        assert any("op=stage_edge" in m for m in msgs)

    @pytest.mark.slow
    def test_metrics_dump_mpmd_green_subprocess(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "metrics_dump.py"),
             "--mpmd", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]

    @pytest.mark.slow
    def test_parity_mpmd_pipeline_exact_with_negative_control(
            self, capsys):
        """One CI lane, both directions: the acceptance-criterion pin —
        the armed 1F1B trajectory is EXACT (zero divergence) — AND its
        lr-perturbed twin diverges (exit 1), so the band is a gate, not
        a rubber stamp."""
        pc = _load_tool("parity_check")
        rc = pc.main(["--ab", "mpmd_pipeline", "--perturb-lr", "8",
                      "--steps", "2", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        targets = report["targets"]
        assert targets["mpmd_pipeline"]["counts"]["error"] == 0
        assert targets["mpmd_pipeline"]["report"][
            "max_abs_loss_diff"] == 0.0
        ctrl = targets["mpmd_pipeline+perturb_lr"]
        assert ctrl["counts"]["error"] == 1
        assert ctrl["report"]["diverged"]

    @pytest.mark.slow
    def test_parity_mpmd_quantized_edge_within_band(self, capsys):
        """The compress=8 activation edge trains inside its declared
        band against the unquantized armed reference."""
        pc = _load_tool("parity_check")
        rc = pc.main(["--ab", "mpmd_quantized_edge", "--steps", "2",
                      "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["totals"]["error"] == 0

    @pytest.mark.slow
    def test_chaos_stage_backpressure_green(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "chaos_check.py"),
             "--only", "stage_backpressure", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
        report = json.loads(out.stdout)
        assert report["totals"]["error"] == 0
