"""Tier-1 gate for the goodput ledger + weight-version lineage (ISSUE
20): with FLAGS_goodput unset, training is EXACTLY the pre-PR path —
paddle_tpu.monitor.goodput is never imported (subprocess pin), trained
params are byte-identical whether or not an armed run was ever
exercised in the same process (the accountant is NON-structural: it
books host-side wall clock and joins no executable key), no
goodput_seconds_total / goodput_fraction / serving_* series appears,
and the disarmed per-step hook costs the same one-lookup bar as every
other disabled fast path. Plus the tool contracts: metrics_dump
--goodput and the chaos goodput_attribution pass exit 0."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: metric families this PR introduced — with the flag unset NONE may move
GOODPUT_FAMILIES = ("goodput_seconds_total", "goodput_fraction",
                    "serving_weight_version",
                    "serving_stale_sessions_total")


def _tiny_dp():
    from paddle_tpu import nn

    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    return SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)


_PLAIN_TRAIN = (
    "import os\n"
    "os.environ.setdefault('XLA_FLAGS',\n"
    "    '--xla_force_host_platform_device_count=8')\n"
    "import jax; jax.config.update('jax_platforms', 'cpu')\n"
    "import hashlib\n"
    "import numpy as np\n"
    "import paddle_tpu as paddle\n"
    "from paddle_tpu import nn\n"
    "from paddle_tpu.distributed.mesh import build_mesh\n"
    "from paddle_tpu.distributed.spmd import SpmdTrainer\n"
    "def run():\n"
    "    paddle.seed(0)\n"
    "    net = nn.Linear(8, 4)\n"
    "    opt = paddle.optimizer.SGD(learning_rate=0.1,\n"
    "                               parameters=net.parameters())\n"
    "    mesh = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
    "    tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)\n"
    "    rng = np.random.RandomState(0)\n"
    "    for _ in range(3):\n"
    "        tr.train_step(rng.rand(4, 8).astype(np.float32),\n"
    "                      rng.rand(4, 4).astype(np.float32))\n"
    "    h = hashlib.sha256()\n"
    "    for k in sorted(tr.params):\n"
    "        h.update(np.ascontiguousarray(\n"
    "            np.asarray(tr.params[k])).tobytes())\n"
    "    return h.hexdigest()\n")


def _run(code):
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


class TestInertByDefault:
    @pytest.mark.slow
    def test_plain_subprocess_never_imports_goodput_and_pins_params(self):
        """The zero-overhead pin, in one subprocess: plain runs (a)
        never import monitor.goodput, and (b) train byte-identical
        params before vs after an ARMED run in the same process — and
        the armed run itself matches, because the accountant never
        touches the compiled program (non-structural)."""
        _run(
            _PLAIN_TRAIN +
            "h1 = run()\n"
            "import sys\n"
            "assert 'paddle_tpu.monitor.goodput' not in sys.modules,\\\n"
            "    'goodput imported on the plain path'\n"
            "paddle.set_flags({'goodput': True})\n"
            "h_armed = run()\n"
            "assert 'paddle_tpu.monitor.goodput' in sys.modules\n"
            "from paddle_tpu.monitor import goodput\n"
            "run_obj = goodput.current_run()\n"
            "assert run_obj is not None and \\\n"
            "    run_obj.buckets['step'] > 0, 'armed run booked no step'\n"
            "assert h_armed == h1, ('armed params are not byte-identical'\n"
            "    ' — the accountant leaked into the compiled step')\n"
            "goodput.reset()\n"
            "paddle.set_flags({'goodput': False})\n"
            "h2 = run()\n"
            "assert h1 == h2, ('flag-unset params drifted after the '\n"
            "    'armed accountant was exercised in-process')\n"
            "print('OK')\n")

    def test_flag_unset_zero_series(self):
        """In-process: a flag-unset run grows no goodput-PR series."""
        monitor.reset()
        tr = _tiny_dp()
        rng = np.random.RandomState(0)
        for _ in range(2):
            tr.train_step(rng.rand(4, 8).astype(np.float32),
                          rng.rand(4, 4).astype(np.float32))
        assert tr._goodput is None
        flat = monitor.flatten(monitor.snapshot())
        # earlier tests in the same process may have left the (zeroed)
        # family registered — drift means a series actually moved
        goodput_series = [k for k, v in flat.items()
                          if k.startswith(GOODPUT_FAMILIES) and v]
        assert not goodput_series, goodput_series

    def test_disarmed_flag_checks_under_5us(self):
        """The flag-unset per-step addition is one `is not None` on a
        construction-consumed attribute (plus the one get_flag lookup
        at construction) — bounded at the same bar as every other
        disabled fast path."""
        tr = _tiny_dp()
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            tr._goodput is not None
            flags.get_flag("goodput", False)
        per_call_us = (time.perf_counter() - t0) / (2 * n) * 1e6
        assert per_call_us < 5.0, (
            f"disarmed goodput check costs {per_call_us:.2f}us")

    def test_flags_defined_and_default_off(self):
        assert flags.get_flag("goodput") is False
        assert flags.get_flag("goodput_stall_s") == 2.0

    def test_weight_version_minted_without_flag(self):
        """Lineage is always on (it is metadata, not accounting): a
        plain trainer mints version 0/init and bumps per applied step
        with origin `step` — no goodput import involved."""
        tr = _tiny_dp()
        assert tr.weight_version.counter == 0
        assert tr.weight_version.origin == "init"
        rng = np.random.RandomState(0)
        tr.train_step(rng.rand(4, 8).astype(np.float32),
                      rng.rand(4, 4).astype(np.float32))
        assert tr.weight_version.counter == 1
        assert tr.weight_version.origin == "step"


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestGoodputToolGates:
    def test_perf_report_goodput_empty_ledger_exits_1(self, capsys,
                                                      tmp_path):
        """--goodput against a ledger with no run/goodput rows is a loud
        error, never a silent green."""
        pr = _load_tool("perf_report")
        rc = pr.main(["--goodput", "--path",
                      str(tmp_path / "missing.jsonl"), "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        msgs = [f for f in report["targets"]["goodput"]["findings"]
                if f["pass"] == "perf-ledger-empty"]
        assert msgs and msgs[0]["severity"] == "error"

    @pytest.mark.slow
    def test_metrics_dump_goodput_green_subprocess(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "metrics_dump.py"),
             "--goodput", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
        report = json.loads(out.stdout)
        assert report["totals"]["error"] == 0

    @pytest.mark.slow
    def test_chaos_goodput_attribution_green_subprocess(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "chaos_check.py"),
             "--only", "goodput_attribution", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, \
            out.stdout[-2000:] + out.stderr[-2000:]
        report = json.loads(out.stdout)
        assert report["totals"]["error"] == 0
        msgs = [f["message"] for t in report["targets"].values()
                for f in t["findings"]
                if f["pass"] == "goodput_attribution"]
        assert msgs and "kill time" in msgs[0], msgs
