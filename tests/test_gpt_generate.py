"""KV-cache autoregressive decoding (GPTForCausalLM.generate): the fused
prefill+scan program must reproduce the cache-free reference decode (full
re-forward through the model's own layer stack each step) token for token."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _reference_greedy(model, ids, n_new):
    """Cache-free decode: full forward over the growing sequence each step."""
    cur = np.asarray(ids)
    for _ in range(n_new):
        logits = model(paddle.to_tensor(cur.astype(np.int32)))
        nxt = np.argmax(np.asarray(logits._data)[:, -1], -1)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    return cur


class TestGenerate:
    def test_greedy_matches_cache_free_reference(self):
        model = _model()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 7)).astype(np.int32)
        want = _reference_greedy(model, ids, 9)
        got = np.asarray(
            model.generate(paddle.to_tensor(ids), max_new_tokens=9,
                           temperature=0.0)._data)
        np.testing.assert_array_equal(got, want)

    def test_single_new_token(self):
        model = _model()
        ids = np.arange(5, dtype=np.int32)[None]
        want = _reference_greedy(model, ids, 1)
        got = np.asarray(model.generate(paddle.to_tensor(ids),
                                        max_new_tokens=1,
                                        temperature=0.0)._data)
        np.testing.assert_array_equal(got, want)

    def test_sampling_seeded_deterministic_and_varies(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        a = np.asarray(model.generate(ids, max_new_tokens=8, temperature=1.0,
                                      top_k=20, seed=7)._data)
        b = np.asarray(model.generate(ids, max_new_tokens=8, temperature=1.0,
                                      top_k=20, seed=7)._data)
        c = np.asarray(model.generate(ids, max_new_tokens=8, temperature=1.0,
                                      top_k=20, seed=8)._data)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)  # different seed, different sample
        assert (a[:, :4] == 1).all()     # prompt preserved

    def test_eos_freezes_tail(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 3), np.int32))
        out = np.asarray(model.generate(ids, max_new_tokens=12,
                                        temperature=0.0,
                                        eos_token_id=int(
                                            _first_greedy_token(model)))._data)
        new = out[0, 3:]
        # the first emitted token IS the eos here, so the whole tail is eos
        assert (new == new[0]).all()

    def test_rejects_overlong_and_parallel_configs(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 60), np.int32))
        with pytest.raises(ValueError, match="max_seq_len"):
            model.generate(ids, max_new_tokens=10)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0,
                        num_experts=2, moe_every=1)
        moe = GPTForCausalLM(cfg)
        with pytest.raises(ValueError, match="dense"):
            moe.generate(paddle.to_tensor(np.ones((1, 4), np.int32)),
                         max_new_tokens=2)

    def test_weight_update_no_stale_cache(self):
        """Params pass as arguments, so training between generate calls must
        change the output without a retrace."""
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 128, (1, 6)).astype(np.int32))
        before = np.asarray(model.generate(ids, max_new_tokens=6,
                                           temperature=0.0)._data)
        for p in model.parameters():  # crude "training": perturb weights
            p.set_value(np.asarray(p._data) * 1.5 + 0.01)
        after = np.asarray(model.generate(ids, max_new_tokens=6,
                                          temperature=0.0)._data)
        want = _reference_greedy(model, np.asarray(ids._data), 6)
        np.testing.assert_array_equal(after, want)
        assert not np.array_equal(before, after)


def _first_greedy_token(model):
    ids = paddle.to_tensor(np.ones((1, 3), np.int32))
    logits = model(ids)
    return np.argmax(np.asarray(logits._data)[0, -1])


def test_untied_head_after_pipeline_split():
    """Review r3: pipeline_split installs a bias-free lm_head; generate must
    take the untied branch without a KeyError and match the model forward."""
    model = _model()
    model.pipeline_split(2)  # installs model.lm_head (bias_attr=False)
    assert getattr(model, "lm_head", None) is not None
    ids = np.random.RandomState(3).randint(0, 128, (1, 5)).astype(np.int32)
    want = _reference_greedy(model, ids, 4)
    got = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                    temperature=0.0)._data)
    np.testing.assert_array_equal(got, want)


def test_generate_validates_and_greedy_keeps_rng_state():
    model = _model()
    ids = paddle.to_tensor(np.ones((1, 4), np.int32))
    with pytest.raises(ValueError, match="max_new_tokens"):
        model.generate(ids, max_new_tokens=0)
    from paddle_tpu.core.generator import default_generator

    paddle.seed(123)
    model.generate(ids, max_new_tokens=2, temperature=0.0)
    offset_after = default_generator()._offset
    assert offset_after == 0  # greedy consumed no global randomness
