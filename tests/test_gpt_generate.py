"""KV-cache autoregressive decoding (GPTForCausalLM.generate): the fused
prefill+scan program must reproduce the cache-free reference decode (full
re-forward through the model's own layer stack each step) token for token."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import GPTConfig, GPTForCausalLM


def _model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _reference_greedy(model, ids, n_new):
    """Cache-free decode: full forward over the growing sequence each step."""
    cur = np.asarray(ids)
    for _ in range(n_new):
        logits = model(paddle.to_tensor(cur.astype(np.int32)))
        nxt = np.argmax(np.asarray(logits._data)[:, -1], -1)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    return cur


class TestGenerate:
    def test_greedy_matches_cache_free_reference(self):
        model = _model()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 7)).astype(np.int32)
        want = _reference_greedy(model, ids, 9)
        got = np.asarray(
            model.generate(paddle.to_tensor(ids), max_new_tokens=9,
                           temperature=0.0)._data)
        np.testing.assert_array_equal(got, want)

    def test_single_new_token(self):
        model = _model()
        ids = np.arange(5, dtype=np.int32)[None]
        want = _reference_greedy(model, ids, 1)
        got = np.asarray(model.generate(paddle.to_tensor(ids),
                                        max_new_tokens=1,
                                        temperature=0.0)._data)
        np.testing.assert_array_equal(got, want)

    def test_sampling_seeded_deterministic_and_varies(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        a = np.asarray(model.generate(ids, max_new_tokens=8, temperature=1.0,
                                      top_k=20, seed=7)._data)
        b = np.asarray(model.generate(ids, max_new_tokens=8, temperature=1.0,
                                      top_k=20, seed=7)._data)
        c = np.asarray(model.generate(ids, max_new_tokens=8, temperature=1.0,
                                      top_k=20, seed=8)._data)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, c)  # different seed, different sample
        assert (a[:, :4] == 1).all()     # prompt preserved

    def test_eos_freezes_tail(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 3), np.int32))
        out = np.asarray(model.generate(ids, max_new_tokens=12,
                                        temperature=0.0,
                                        eos_token_id=int(
                                            _first_greedy_token(model)))._data)
        new = out[0, 3:]
        # the first emitted token IS the eos here, so the whole tail is eos
        assert (new == new[0]).all()

    def test_rejects_overlong_and_parallel_configs(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 60), np.int32))
        with pytest.raises(ValueError, match="max_seq_len"):
            model.generate(ids, max_new_tokens=10)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0,
                        num_experts=2, moe_every=1)
        moe = GPTForCausalLM(cfg)
        with pytest.raises(ValueError, match="dense"):
            moe.generate(paddle.to_tensor(np.ones((1, 4), np.int32)),
                         max_new_tokens=2)

    def test_weight_update_no_stale_cache(self):
        """Params pass as arguments, so training between generate calls must
        change the output without a retrace."""
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 128, (1, 6)).astype(np.int32))
        before = np.asarray(model.generate(ids, max_new_tokens=6,
                                           temperature=0.0)._data)
        for p in model.parameters():  # crude "training": perturb weights
            p.set_value(np.asarray(p._data) * 1.5 + 0.01)
        after = np.asarray(model.generate(ids, max_new_tokens=6,
                                          temperature=0.0)._data)
        want = _reference_greedy(model, np.asarray(ids._data), 6)
        np.testing.assert_array_equal(after, want)
        assert not np.array_equal(before, after)


def _first_greedy_token(model):
    ids = paddle.to_tensor(np.ones((1, 3), np.int32))
    logits = model(ids)
    return np.argmax(np.asarray(logits._data)[0, -1])


def test_untied_head_after_pipeline_split():
    """Review r3: pipeline_split installs a bias-free lm_head; generate must
    take the untied branch without a KeyError and match the model forward."""
    model = _model()
    model.pipeline_split(2)  # installs model.lm_head (bias_attr=False)
    assert getattr(model, "lm_head", None) is not None
    ids = np.random.RandomState(3).randint(0, 128, (1, 5)).astype(np.int32)
    want = _reference_greedy(model, ids, 4)
    got = np.asarray(model.generate(paddle.to_tensor(ids), max_new_tokens=4,
                                    temperature=0.0)._data)
    np.testing.assert_array_equal(got, want)


def test_generate_validates_and_greedy_keeps_rng_state():
    model = _model()
    ids = paddle.to_tensor(np.ones((1, 4), np.int32))
    with pytest.raises(ValueError, match="max_new_tokens"):
        model.generate(ids, max_new_tokens=0)
    from paddle_tpu.core.generator import default_generator

    paddle.seed(123)
    model.generate(ids, max_new_tokens=2, temperature=0.0)
    offset_after = default_generator()._offset
    assert offset_after == 0  # greedy consumed no global randomness


class TestBeamSearch:
    def test_full_width_beam_matches_exhaustive_oracle(self):
        """With n_new=2 and num_beams=V the beam keeps ALL length-1 prefixes,
        so the search is truly exhaustive over the V^2 paths and must equal
        the brute-force argmax (oracle: one batched teacher-forced
        forward)."""
        import itertools

        paddle.seed(0)
        V, n_new = 10, 2
        cfg = GPTConfig(vocab_size=V, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=16, dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        ids = np.array([[3, 1, 4]], np.int32)
        s0 = ids.shape[1]

        paths = np.array(list(itertools.product(range(V), repeat=n_new)),
                         np.int32)                       # [V^n, n_new]
        batch = np.concatenate(
            [np.repeat(ids, len(paths), axis=0), paths], axis=1)
        logits = np.asarray(model(paddle.to_tensor(batch))._data)
        z = logits[:, s0 - 1:s0 - 1 + n_new]             # predicts each step
        lse = np.log(np.exp(z - z.max(-1, keepdims=True)).sum(-1)) \
            + z.max(-1)[..., 0:].reshape(z.shape[:-1])
        logp = np.take_along_axis(
            z, paths[..., None], -1)[..., 0] - lse       # [V^n, n_new]
        totals = logp.sum(-1)
        best = int(np.argmax(totals))

        seqs, scores = model.generate(paddle.to_tensor(ids),
                                      max_new_tokens=n_new, num_beams=V)
        got = tuple(np.asarray(seqs._data)[0, s0:])
        assert got == tuple(paths[best]), (got, paths[best])
        np.testing.assert_allclose(float(np.asarray(scores._data)[0]),
                                   totals[best], rtol=1e-4)

    def test_beam_shapes_and_finite_scores(self):
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(5).randint(0, 128, (2, 6)).astype(np.int32))
        seqs, scores = model.generate(ids, max_new_tokens=5, num_beams=4)
        assert np.asarray(seqs._data).shape == (2, 11)
        assert np.asarray(scores._data).shape == (2,)
        assert np.isfinite(np.asarray(scores._data)).all()

    def test_beam_single_new_token(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        seqs, _ = model.generate(ids, max_new_tokens=1, num_beams=3)
        want = _reference_greedy(model, np.asarray(ids._data), 1)
        np.testing.assert_array_equal(np.asarray(seqs._data), want)

    def test_beam_eos_freezes(self):
        model = _model()
        eos = int(_first_greedy_token(model))
        ids = paddle.to_tensor(np.ones((1, 3), np.int32))
        seqs, _ = model.generate(ids, max_new_tokens=8, num_beams=3,
                                 eos_token_id=eos)
        new = np.asarray(seqs._data)[0, 3:]
        hits = np.where(new == eos)[0]
        if hits.size:  # after the first eos, only eos follows
            assert (new[hits[0]:] == eos).all()


def test_beam_length_penalty_prefers_short_finished_beam():
    """GNMT normalization: with a huge length_penalty, a beam that finished
    early (shorter generated length) must win the final pick when scores are
    comparable; with penalty 0 ranking is by raw joint log-prob."""
    model = _model()
    eos = int(_first_greedy_token(model))
    ids = paddle.to_tensor(np.ones((1, 3), np.int32))
    s_short, sc_short = model.generate(ids, max_new_tokens=6, num_beams=4,
                                       eos_token_id=eos, length_penalty=8.0)
    s_raw, sc_raw = model.generate(ids, max_new_tokens=6, num_beams=4,
                                   eos_token_id=eos, length_penalty=0.0)
    # both runs are valid decodes; the knob must at least be able to change
    # the selected beam/score when early-eos beams exist
    a = np.asarray(s_short._data)
    b = np.asarray(s_raw._data)
    assert a.shape == b.shape == (1, 9)
    assert np.isfinite(np.asarray(sc_short._data)).all()
    assert np.isfinite(np.asarray(sc_raw._data)).all()


def test_beam_rejects_overwide():
    model = _model()
    ids = paddle.to_tensor(np.ones((1, 3), np.int32))
    with pytest.raises(ValueError, match="vocab_size"):
        model.generate(ids, max_new_tokens=2, num_beams=500)


def test_bf16_decode_close_to_f32():
    """Serving precision: dtype='bfloat16' halves the KV cache; greedy
    tokens must agree with f32 decode for most steps on a tiny model (bf16
    rounding can legitimately flip near-tie argmaxes, so exact equality is
    not required — but wholesale divergence means broken plumbing)."""
    model = _model()
    ids = paddle.to_tensor(
        np.random.RandomState(2).randint(0, 128, (2, 6)).astype(np.int32))
    f32 = np.asarray(model.generate(ids, max_new_tokens=8,
                                    temperature=0.0)._data)
    bf16 = np.asarray(model.generate(ids, max_new_tokens=8, temperature=0.0,
                                     dtype="bfloat16")._data)
    assert bf16.shape == f32.shape
    # compare GENERATED tokens only (the echoed prompt always matches);
    # bf16 rounding may flip near-tie argmaxes, wholesale divergence may not
    agree = (bf16[:, 6:] == f32[:, 6:]).mean()
    assert agree > 0.5, (agree, bf16, f32)
    import pytest
    with pytest.raises(ValueError, match="floating"):
        model.generate(ids, max_new_tokens=2, dtype="int32")


def test_beam_accepts_dtype_and_f32_is_default_path():
    model = _model()
    ids = paddle.to_tensor(np.ones((1, 4), np.int32))
    seqs, scores = model.generate(ids, max_new_tokens=3, num_beams=3,
                                  dtype="bfloat16")
    assert np.asarray(seqs._data).shape == (1, 7)
    assert np.isfinite(np.asarray(scores._data)).all()
    # explicit float32 must not duplicate the compiled program
    n_before = len(model._generate_compiled)
    model.generate(ids, max_new_tokens=3, temperature=0.0)
    n_mid = len(model._generate_compiled)
    model.generate(ids, max_new_tokens=3, temperature=0.0, dtype="float32")
    assert len(model._generate_compiled) == n_mid


class TestRaggedBatchDecode:
    def test_left_padded_rows_match_individual_decodes(self):
        """Batched ragged serving: each LEFT-padded row's greedy continuation
        must EXACTLY match decoding that prompt alone (positions, masks and
        cache columns all line up)."""
        model = _model()
        rng = np.random.RandomState(4)
        p1 = rng.randint(1, 128, 4).astype(np.int32)   # len 4
        p2 = rng.randint(1, 128, 7).astype(np.int32)   # len 7
        s0 = 7
        batch = np.zeros((2, s0), np.int32)
        batch[0, s0 - 4:] = p1
        batch[1] = p2
        mask = np.zeros((2, s0), np.int32)
        mask[0, s0 - 4:] = 1
        mask[1] = 1

        out = np.asarray(model.generate(
            paddle.to_tensor(batch), max_new_tokens=6, temperature=0.0,
            attention_mask=paddle.to_tensor(mask))._data)

        solo1 = np.asarray(model.generate(
            paddle.to_tensor(p1[None]), max_new_tokens=6,
            temperature=0.0)._data)
        solo2 = np.asarray(model.generate(
            paddle.to_tensor(p2[None]), max_new_tokens=6,
            temperature=0.0)._data)
        np.testing.assert_array_equal(out[0, s0:], solo1[0, 4:])
        np.testing.assert_array_equal(out[1, s0:], solo2[0, 7:])

    def test_mask_validation(self):
        model = _model()
        ids = paddle.to_tensor(np.ones((2, 5), np.int32))
        right_pad = paddle.to_tensor(
            np.array([[1, 1, 1, 0, 0]] * 2, np.int32))
        with pytest.raises(ValueError, match="LEFT-padded"):
            model.generate(ids, max_new_tokens=2, temperature=0.0,
                           attention_mask=right_pad)
        all_pad = paddle.to_tensor(np.zeros((2, 5), np.int32))
        with pytest.raises(ValueError, match="all-pad"):
            model.generate(ids, max_new_tokens=2, temperature=0.0,
                           attention_mask=all_pad)



def test_non_binary_mask_rejected():
    model = _model()
    ids = paddle.to_tensor(np.ones((1, 4), np.int32))
    bad = paddle.to_tensor(np.array([[0, 1, 2, 2]], np.int32))
    with pytest.raises(ValueError, match="binary"):
        model.generate(ids, max_new_tokens=2, temperature=0.0,
                       attention_mask=bad)


def test_ragged_beam_matches_solo_beam():
    """Beam search over a left-padded ragged batch: each row's best beam
    must match beam-decoding that prompt alone."""
    model = _model()
    rng = np.random.RandomState(6)
    p1 = rng.randint(1, 128, 3).astype(np.int32)
    p2 = rng.randint(1, 128, 6).astype(np.int32)
    s0 = 6
    batch = np.zeros((2, s0), np.int32)
    mask = np.zeros((2, s0), np.int32)
    batch[0, s0 - 3:] = p1; mask[0, s0 - 3:] = 1
    batch[1] = p2; mask[1] = 1

    seqs, scores = model.generate(paddle.to_tensor(batch), max_new_tokens=5,
                                  num_beams=3,
                                  attention_mask=paddle.to_tensor(mask))
    out = np.asarray(seqs._data)
    s1, sc1 = model.generate(paddle.to_tensor(p1[None]), max_new_tokens=5,
                             num_beams=3)
    s2, sc2 = model.generate(paddle.to_tensor(p2[None]), max_new_tokens=5,
                             num_beams=3)
    np.testing.assert_array_equal(out[0, s0:], np.asarray(s1._data)[0, 3:])
    np.testing.assert_array_equal(out[1, s0:], np.asarray(s2._data)[0, 6:])
    np.testing.assert_allclose(np.asarray(scores._data),
                               [float(np.asarray(sc1._data)[0]),
                                float(np.asarray(sc2._data)[0])], rtol=1e-5)


def test_top_p_sampling():
    """Nucleus sampling: top_p -> 0 degenerates to greedy (only the argmax
    survives the nucleus); seeded runs are deterministic."""
    model = _model()
    ids = paddle.to_tensor(np.ones((2, 4), np.int32))
    greedy = np.asarray(model.generate(ids, max_new_tokens=6,
                                       temperature=0.0)._data)
    tiny_p = np.asarray(model.generate(ids, max_new_tokens=6,
                                       temperature=1.0, top_p=1e-6,
                                       seed=0)._data)
    np.testing.assert_array_equal(tiny_p, greedy)
    a = np.asarray(model.generate(ids, max_new_tokens=6, temperature=1.0,
                                  top_p=0.9, seed=3)._data)
    b = np.asarray(model.generate(ids, max_new_tokens=6, temperature=1.0,
                                  top_p=0.9, seed=3)._data)
    np.testing.assert_array_equal(a, b)
    assert np.isfinite(a).all()


def test_beam_rejects_sampling_knobs():
    model = _model()
    ids = paddle.to_tensor(np.ones((1, 4), np.int32))
    with pytest.raises(ValueError, match="sampling knobs"):
        model.generate(ids, max_new_tokens=2, num_beams=2, top_p=0.9)
    with pytest.raises(ValueError, match="sampling knobs"):
        model.generate(ids, max_new_tokens=2, num_beams=2, top_k=5)


class TestInt8KVCache:
    """cache_dtype='int8': per-row absmax-quantized KV cache — half the bf16
    cache's HBM traffic in the HBM-bound decode loop."""

    def test_greedy_matches_f32_cache(self):
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 128, (2, 6)).astype(np.int32))
        f32 = np.asarray(model.generate(ids, max_new_tokens=8,
                                        temperature=0.0)._data)
        i8 = np.asarray(model.generate(ids, max_new_tokens=8, temperature=0.0,
                                       cache_dtype="int8")._data)
        assert i8.shape == f32.shape
        # int8 rounding can flip near-tie argmaxes; wholesale divergence
        # means broken quantization plumbing (same bar as the bf16 test)
        agree = (i8[:, 6:] == f32[:, 6:]).mean()
        assert agree > 0.5, (agree, i8, f32)

    def test_beam_search_with_int8_cache(self):
        """Beam search reorders the (values, scales) pair by parent beam —
        both components must travel together through repeat/gather/scan."""
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(3).randint(0, 128, (2, 5)).astype(np.int32))
        s_f, sc_f = model.generate(ids, max_new_tokens=6, num_beams=3)
        s_i, sc_i = model.generate(ids, max_new_tokens=6, num_beams=3,
                                   cache_dtype="int8")
        assert np.asarray(s_i._data).shape == np.asarray(s_f._data).shape
        assert np.isfinite(np.asarray(sc_i._data)).all()
        gen_f = np.asarray(s_f._data)[:, 5:]  # generated tokens only
        gen_i = np.asarray(s_i._data)[:, 5:]
        agree = (gen_i == gen_f).mean()
        assert agree > 0.5

    def test_composes_with_bf16_params_and_ragged_batch(self):
        model = _model()
        rng = np.random.RandomState(4)
        ids = np.full((2, 6), 7, np.int32)
        ids[1, :3] = 0  # left-padded row
        amask = np.ones((2, 6), np.int32)
        amask[1, :3] = 0
        ids_t = paddle.to_tensor(ids)
        out = model.generate(ids_t, max_new_tokens=4, temperature=0.0,
                             dtype="bfloat16", cache_dtype="int8",
                             attention_mask=paddle.to_tensor(amask))
        arr = np.asarray(out._data)
        assert arr.shape == (2, 10)
        assert np.isfinite(arr.astype(np.float64)).all()

    def test_rejects_unknown_cache_dtype(self):
        import pytest

        model = _model()
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        with pytest.raises(ValueError, match="cache_dtype"):
            model.generate(ids, max_new_tokens=2, cache_dtype="int4")

    def test_compiled_decode_temp_memory_shrinks(self):
        """XLA-level evidence the int8 cache is real: the compiled decode
        program's peak temp allocation must shrink vs the f32 cache (the
        quantized cache has to survive XLA's buffer assignment, not just
        the python-level dtype)."""
        import jax
        import pytest

        model = _model()
        ids = paddle.to_tensor(np.ones((2, 8), np.int32))
        model.generate(ids, max_new_tokens=32, temperature=0.0)
        model.generate(ids, max_new_tokens=32, temperature=0.0,
                       cache_dtype="int8")
        params = {n: p._data for n, p in model.named_parameters()}
        key = jax.random.key(0)  # typed key, matching production generate()
        sizes = {}
        for k, fn in model._generate_compiled.items():
            mem = fn.lower(params, ids._data, key,
                           None).compile().memory_analysis()
            t = getattr(mem, "temp_size_in_bytes", None)
            if t is None:
                pytest.skip("backend reports no memory analysis")
            sizes["int8" if "int8" in k else "f32"] = t
        assert sizes["int8"] < 0.75 * sizes["f32"], sizes


class TestFp8KVCache:
    """cache_dtype='fp8' (r5): float8_e4m3fn KV cache at int8's byte
    footprint — scaled casts keep a mantissa instead of integer
    rounding; the same (values, scales) plumbing as int8."""

    def test_greedy_tracks_f32_cache_closely(self):
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(5).randint(0, 128, (2, 6)).astype(np.int32))
        f32 = np.asarray(model.generate(ids, max_new_tokens=8,
                                        temperature=0.0)._data)
        f8 = np.asarray(model.generate(ids, max_new_tokens=8,
                                       temperature=0.0,
                                       cache_dtype="fp8")._data)
        assert f8.shape == f32.shape
        agree = (f8[:, 6:] == f32[:, 6:]).mean()
        assert agree > 0.5, (agree, f8, f32)

    def test_serving_engine_fp8_exact_parity_vs_generate_fp8(self):
        from paddle_tpu.inference.serving import ServingEngine

        model = _model()
        eng = ServingEngine(model, max_batch=2, cache_dtype="fp8")
        rng = np.random.RandomState(6)
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (5, 9)]
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            ref = np.asarray(model.generate(
                paddle.to_tensor(p[None]), max_new_tokens=6,
                temperature=0.0, cache_dtype="fp8")._data)[0, len(p):]
            np.testing.assert_array_equal(res[rid].tokens, ref)

    def test_cache_codec_dtypes_and_range(self):
        # the cache really stores the quantized dtype (int8 / e4m3fn), and
        # the fp8 codec's qmax=448 sits inside e4m3fn's representable range
        import jax.numpy as jnp

        from paddle_tpu.models.gpt import _decode_fns

        model = _model()
        cfg = model.cfg
        for cd in ("int8", "fp8"):
            _, _, cache_init = _decode_fns(cfg, False, False,
                                           cache_dtype=cd)
            kc, vc = cache_init(1, 8, jnp.float32)
            assert (kc[0].dtype == (jnp.int8 if cd == "int8"
                                    else jnp.float8_e4m3fn))
        x = jnp.asarray(447.0, jnp.float32).astype(jnp.float8_e4m3fn)
        assert float(x.astype(jnp.float32)) > 400.0

    def test_central_validation_covers_speculative(self):
        # the _QUANT table is the single interpreter of cache_dtype: a
        # typo through ANY entry point (here the speculative path, which
        # has no validation of its own) must raise, never silently serve
        # a full-precision cache
        model = _model()
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        with pytest.raises(ValueError, match="cache_dtype"):
            model.generate_speculative(model, ids, max_new_tokens=2,
                                       cache_dtype="f8")

    def test_engine_rejects_unknown_cache_dtype(self):
        from paddle_tpu.inference.serving import ServingEngine

        model = _model()
        with pytest.raises(ValueError, match="cache_dtype"):
            ServingEngine(model, cache_dtype="int4")


class TestSpeculativeDecoding:
    """generate_speculative: draft proposes k, target verifies in one
    forward; output must equal the target's own greedy decode."""

    def _pair(self):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=128, dropout=0.0)
        target = GPTForCausalLM(cfg)
        target.eval()
        paddle.seed(7)
        dcfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=1,
                         num_heads=2, max_seq_len=128, dropout=0.0)
        draft = GPTForCausalLM(dcfg)
        draft.eval()
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 128, (1, 6)).astype(np.int32))
        return target, draft, ids

    def test_matches_plain_greedy(self):
        target, draft, ids = self._pair()
        plain = np.asarray(target.generate(ids, max_new_tokens=20,
                                           temperature=0.0)._data)
        spec, rounds = target.generate_speculative(draft, ids,
                                                   max_new_tokens=20, k=4)
        np.testing.assert_array_equal(np.asarray(spec._data), plain)
        assert 1 <= rounds <= 20

    def test_perfect_draft_needs_fewer_rounds(self):
        """Draft == target: every proposal accepted, so the data-dependent
        while_loop exits in the ideal ceil(20/(k+1)) = 4 rounds (a small
        slack tolerates numeric near-ties on the random test model; rounds
        near 20 would mean acceptance — or the draft KV cache — broke)."""
        target, _, ids = self._pair()
        plain = np.asarray(target.generate(ids, max_new_tokens=20,
                                           temperature=0.0)._data)
        spec, rounds = target.generate_speculative(target, ids,
                                                   max_new_tokens=20, k=4)
        np.testing.assert_array_equal(np.asarray(spec._data), plain)
        assert rounds <= 5, rounds

    def test_validation(self):
        import pytest

        target, draft, ids = self._pair()
        with pytest.raises(ValueError, match="batch"):
            target.generate_speculative(
                draft, paddle.to_tensor(np.ones((2, 6), np.int32)),
                max_new_tokens=4)
        with pytest.raises(ValueError, match="k must"):
            target.generate_speculative(draft, ids, max_new_tokens=4, k=0)
        paddle.seed(1)
        other = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                         num_layers=1, num_heads=2,
                                         max_seq_len=128, dropout=0.0))
        other.eval()
        with pytest.raises(ValueError, match="vocab"):
            target.generate_speculative(other, ids, max_new_tokens=4)

    def test_composes_with_bf16_and_int8_cache(self):
        target, draft, ids = self._pair()
        spec, rounds = target.generate_speculative(
            draft, ids, max_new_tokens=12, k=3, dtype="bfloat16",
            cache_dtype="int8")
        arr = np.asarray(spec._data)
        assert arr.shape == (1, 18)
        assert ((0 <= arr) & (arr < 128)).all()


class TestTensorParallelDecode:
    """generate(tp_mesh=...): Megatron-style head/MLP-sharded serving of a
    DENSE model — local-head KV caches, two psums per layer; tokens must
    match the single-replica decode exactly."""

    def _mesh(self, n=4):
        import jax

        from paddle_tpu.distributed.mesh import build_mesh

        return build_mesh((n,), ("mp",), devices=jax.devices()[:n])

    def test_greedy_matches_dense(self):
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 128, (2, 6)).astype(np.int32))
        dense = np.asarray(model.generate(ids, max_new_tokens=8,
                                          temperature=0.0)._data)
        tp = np.asarray(model.generate(ids, max_new_tokens=8,
                                       temperature=0.0,
                                       tp_mesh=self._mesh())._data)
        np.testing.assert_array_equal(tp, dense)

    def test_ragged_and_int8_compose(self):
        model = _model()
        ids = np.full((2, 6), 7, np.int32)
        ids[1, :3] = 0
        amask = np.ones((2, 6), np.int32)
        amask[1, :3] = 0
        ids_t = paddle.to_tensor(ids)
        mk = paddle.to_tensor(amask)
        dense = np.asarray(model.generate(ids_t, max_new_tokens=6,
                                          temperature=0.0,
                                          attention_mask=mk)._data)
        tp = np.asarray(model.generate(ids_t, max_new_tokens=6,
                                       temperature=0.0, attention_mask=mk,
                                       tp_mesh=self._mesh())._data)
        np.testing.assert_array_equal(tp, dense)
        # int8 codec correctness under tp, in f32 so psum reassociation
        # cannot flip near-tie argmaxes (bf16 composition is exercised for
        # shape/compile by the drive below)
        i8_dense = np.asarray(model.generate(ids_t, max_new_tokens=6,
                                             temperature=0.0,
                                             cache_dtype="int8")._data)
        i8_tp = np.asarray(model.generate(ids_t, max_new_tokens=6,
                                          temperature=0.0,
                                          cache_dtype="int8",
                                          tp_mesh=self._mesh())._data)
        np.testing.assert_array_equal(i8_tp, i8_dense)
        bf = np.asarray(model.generate(ids_t, max_new_tokens=6,
                                       temperature=0.0, dtype="bfloat16",
                                       cache_dtype="int8",
                                       tp_mesh=self._mesh())._data)
        assert bf.shape == dense.shape

    def test_sampling_replicated_across_ranks(self):
        """Sampled decode under tp runs the categorical draw replicated on
        every rank with the same key — output must equal the dense sample
        with the same seed."""
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(3).randint(0, 128, (2, 5)).astype(np.int32))
        dense = np.asarray(model.generate(ids, max_new_tokens=6,
                                          temperature=0.8, top_k=20,
                                          seed=11)._data)
        tp = np.asarray(model.generate(ids, max_new_tokens=6,
                                       temperature=0.8, top_k=20, seed=11,
                                       tp_mesh=self._mesh())._data)
        np.testing.assert_array_equal(tp, dense)

    def test_validation(self):
        import pytest

        model = _model()
        ids = paddle.to_tensor(np.ones((1, 4), np.int32))
        with pytest.raises(ValueError, match="divisible"):
            model.generate(ids, max_new_tokens=2, tp_mesh=self._mesh(8))
        with pytest.raises(ValueError, match="divisible"):  # beam path too
            model.generate(ids, max_new_tokens=2, num_beams=2,
                           tp_mesh=self._mesh(8))
        with pytest.raises(ValueError, match="mp"):
            from paddle_tpu.distributed.mesh import build_mesh
            import jax
            bad = build_mesh((4,), ("dp",), devices=jax.devices()[:4])
            model.generate(ids, max_new_tokens=2, tp_mesh=bad)

    def test_beam_search_matches_dense(self):
        model = _model()
        ids = paddle.to_tensor(
            np.random.RandomState(3).randint(0, 128, (2, 5)).astype(np.int32))
        s_d, sc_d = model.generate(ids, max_new_tokens=6, num_beams=3)
        s_t, sc_t = model.generate(ids, max_new_tokens=6, num_beams=3,
                                   tp_mesh=self._mesh())
        np.testing.assert_array_equal(np.asarray(s_t._data),
                                      np.asarray(s_d._data))
        np.testing.assert_allclose(np.asarray(sc_t._data),
                                   np.asarray(sc_d._data), atol=1e-4)

    def test_speculative_under_tp(self):
        """Speculative decode with the TARGET sharded over mp (draft
        replicated) still reproduces the plain greedy output exactly."""
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=128, dropout=0.0)
        target = GPTForCausalLM(cfg)
        target.eval()
        paddle.seed(7)
        draft = GPTForCausalLM(GPTConfig(vocab_size=128, hidden_size=32,
                                         num_layers=1, num_heads=2,
                                         max_seq_len=128, dropout=0.0))
        draft.eval()
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 128, (1, 6)).astype(np.int32))
        plain = np.asarray(target.generate(ids, max_new_tokens=16,
                                           temperature=0.0)._data)
        spec, rounds = target.generate_speculative(
            draft, ids, max_new_tokens=16, k=4, tp_mesh=self._mesh())
        np.testing.assert_array_equal(np.asarray(spec._data), plain)
        assert 1 <= rounds <= 16


def test_speculative_eos_early_stop_matches_dense():
    """eos inside the accepted slice stops the speculative loop early and
    the output (eos-filled tail) matches dense generate with the same eos."""
    model = _model()
    ids = paddle.to_tensor(
        np.random.RandomState(2).randint(0, 128, (1, 6)).astype(np.int32))
    plain = np.asarray(model.generate(ids, max_new_tokens=20,
                                      temperature=0.0)._data)
    eos_tok = int(plain[0, 6 + 4])  # the 5th generated token as 'eos'
    dense = np.asarray(model.generate(ids, max_new_tokens=20,
                                      temperature=0.0,
                                      eos_token_id=eos_tok)._data)
    spec, rounds = model.generate_speculative(model, ids, max_new_tokens=20,
                                              k=4, eos_token_id=eos_tok)
    np.testing.assert_array_equal(np.asarray(spec._data), dense)
    # perfect draft without eos needs ceil(20/5)=4 rounds; the early eos
    # must cut that down
    assert rounds < 4, rounds


def test_attention_window_decode_matches_cache_free():
    """GPTConfig(attention_window=W): the KV-cache decode masks the same
    band the training forward uses, so greedy generate equals the
    cache-free windowed forward — and differs from full attention."""
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    attention_window=8)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(2).randint(0, 128, (2, 20)).astype(np.int32))
    cur = np.asarray(ids._data)
    for _ in range(10):
        logits = np.asarray(m(paddle.to_tensor(cur))._data)
        nxt = logits[:, -1].argmax(-1).astype(np.int32)[:, None]
        cur = np.concatenate([cur, nxt], axis=1)
    gen = np.asarray(m.generate(ids, max_new_tokens=10,
                                temperature=0.0)._data)
    np.testing.assert_array_equal(gen, cur)

    paddle.seed(0)
    full = GPTForCausalLM(GPTConfig(vocab_size=128, hidden_size=64,
                                    num_layers=2, num_heads=4,
                                    max_seq_len=64, dropout=0.0))
    full.eval()
    full.set_state_dict(m.state_dict())
    gen_full = np.asarray(full.generate(ids, max_new_tokens=10,
                                        temperature=0.0)._data)
    assert not (gen_full == gen).all()  # the window is actually active

    import pytest
    with pytest.raises(ValueError, match="attention_window"):
        GPTConfig(attention_window=0)


class TestGroupedQueryAttention:
    """GQA (num_kv_heads < num_heads): compact K/V heads shared per query
    group — the KV cache shrinks by heads/kv_heads while the math equals an
    MHA model whose kv weights are replicated per group."""

    def _gqa(self):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=64, dropout=0.0,
                        num_kv_heads=2)
        m = GPTForCausalLM(cfg)
        m.eval()
        return cfg, m

    def test_equals_mha_with_replicated_kv(self):
        """Replicating each kv head across its group inside an MHA model
        must reproduce the GQA forward exactly."""
        cfg, m = self._gqa()
        H, K = 4, 2
        hd = cfg.hidden_size // H
        paddle.seed(1)
        mha = GPTForCausalLM(GPTConfig(vocab_size=128, hidden_size=64,
                                       num_layers=2, num_heads=4,
                                       max_seq_len=64, dropout=0.0))
        mha.eval()
        sd = m.state_dict()
        out_sd = {}
        for n, v in mha.state_dict().items():
            src = np.asarray(sd[n].numpy()) if n in sd else None
            if n.endswith("attn.qkv.weight"):
                gq = np.asarray(sd[n].numpy())  # [h, (H+2K)*hd]
                q_w = gq[:, :H * hd]
                k_w = gq[:, H * hd:(H + K) * hd].reshape(-1, K, hd)
                v_w = gq[:, (H + K) * hd:].reshape(-1, K, hd)
                rep = lambda w: np.repeat(w, H // K, axis=1).reshape(
                    -1, H * hd)
                out_sd[n] = np.concatenate([q_w, rep(k_w), rep(v_w)], axis=1)
            elif n.endswith("attn.qkv.bias"):
                gb = np.asarray(sd[n].numpy())
                q_b = gb[:H * hd]
                k_b = gb[H * hd:(H + K) * hd].reshape(K, hd)
                v_b = gb[(H + K) * hd:].reshape(K, hd)
                rep = lambda w: np.repeat(w, H // K, axis=0).reshape(-1)
                out_sd[n] = np.concatenate([q_b, rep(k_b), rep(v_b)])
            else:
                out_sd[n] = src
        mha.set_state_dict(out_sd)
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 128, (2, 16)).astype(np.int32))
        np.testing.assert_allclose(np.asarray(mha(ids)._data),
                                   np.asarray(m(ids)._data),
                                   atol=1e-5, rtol=1e-5)

    def test_decode_matches_cache_free(self):
        cfg, m = self._gqa()
        ids = paddle.to_tensor(
            np.random.RandomState(2).randint(0, 128, (2, 12)).astype(np.int32))
        cur = np.asarray(ids._data)
        for _ in range(8):
            logits = np.asarray(m(paddle.to_tensor(cur))._data)
            nxt = logits[:, -1].argmax(-1).astype(np.int32)[:, None]
            cur = np.concatenate([cur, nxt], axis=1)
        gen = np.asarray(m.generate(ids, max_new_tokens=8,
                                    temperature=0.0)._data)
        np.testing.assert_array_equal(gen, cur)
        # int8 cache composes with the compact kv heads
        i8 = np.asarray(m.generate(ids, max_new_tokens=8, temperature=0.0,
                                   cache_dtype="int8")._data)
        agree = (i8[:, 12:] == gen[:, 12:]).mean()
        assert agree > 0.5

    def test_cache_holds_compact_kv_heads(self):
        from paddle_tpu.models.gpt import _decode_fns

        cfg, _ = self._gqa()
        import jax.numpy as jnp

        _, _, cache_init = _decode_fns(cfg, False, False)
        (kc), _ = cache_init(1, 32, jnp.float32)
        assert kc.shape[2] == 2  # kv heads, not the 4 query heads

    def test_trains(self):
        cfg, m = self._gqa()
        m.train()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=m.parameters())
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 128, (2, 16)).astype(np.int32))
        losses = []
        for _ in range(4):
            loss = m.loss(ids, ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_validation(self):
        import pytest

        with pytest.raises(ValueError, match="num_kv_heads"):
            GPTConfig(num_heads=4, num_kv_heads=3)
        with pytest.raises(ValueError, match="num_kv_heads"):
            GPTConfig(num_heads=4, num_kv_heads=0)
        with pytest.raises(ValueError, match="GQA"):
            GPTConfig(num_heads=4, num_kv_heads=2, tensor_parallel=True,
                      dropout=0.0)


def test_combined_serving_knobs_window_gqa_int8():
    """The serving knobs compose: sliding-window + GQA + int8 KV cache in
    one model — decode must still match the cache-free forward exactly
    (f32) and run finite with the quantized cache."""
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0,
                    num_kv_heads=2, attention_window=8)
    m = GPTForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(5).randint(0, 128, (2, 12)).astype(np.int32))
    cur = _reference_greedy(m, np.asarray(ids._data), 8)
    gen = np.asarray(m.generate(ids, max_new_tokens=8,
                                temperature=0.0)._data)
    np.testing.assert_array_equal(gen, cur)
    i8 = np.asarray(m.generate(ids, max_new_tokens=8, temperature=0.0,
                               cache_dtype="int8")._data)
    assert i8.shape == gen.shape
    agree = (i8[:, 12:] == gen[:, 12:]).mean()
    assert agree > 0.5
