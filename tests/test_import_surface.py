"""Every paddle_tpu submodule imports cleanly (wiring/regression smoke):
a rename or circular import anywhere in the package fails here by name."""
import importlib
import pkgutil

import paddle_tpu


def test_all_submodules_import():
    failures = []
    # onerror: walk_packages re-imports subpackages to descend; without it a
    # raising __init__ aborts the walk and discards collected failures
    for mod in pkgutil.walk_packages(
            paddle_tpu.__path__, prefix="paddle_tpu.",
            onerror=lambda name: failures.append((name, "walk error"))):
        try:
            importlib.import_module(mod.name)
        except Exception as e:  # noqa: BLE001 - collecting all failures
            failures.append((mod.name, repr(e)))
    assert not failures, failures


def test_public_namespaces_nonempty():
    import paddle_tpu as paddle

    for ns in ("nn", "tensor", "optimizer", "amp", "io", "jit", "static",
               "distributed", "metric", "vision", "text", "inference",
               "quantization", "models", "incubate", "utils", "profiler",
               "autograd", "onnx", "hapi"):
        mod = getattr(paddle, ns, None) or importlib.import_module(
            f"paddle_tpu.{ns}")
        assert len([n for n in dir(mod) if not n.startswith("_")]) > 0, ns
