"""The bench watchdog must never erase a completed measurement.

Round-3 failure mode (NOTES_r3.md): the 900s watchdog killed a run where
backend init + tracing completed but the first heavy measurement didn't,
reducing the whole round to an error line. The wedge-proofing contract:

- bench emits a micro metric (2-layer GPT canary) flushed BEFORE any heavy
  compile starts (bench.run_micro, wired in main() on TPU);
- if a LATER phase hangs, the watchdog re-emits the last complete metric
  line as the LAST json line and exits 0 (the driver parses the last line
  + return code);
- only a run with no measurement at all exits 3, with an "error" line
  that has no "metric"/"value" keys so it can never parse as a number.
"""
import json
import subprocess
import sys

REPO = __file__.rsplit("/tests/", 1)[0]


def _run(code):
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, cwd=REPO, timeout=120)
    lines = [l for l in r.stdout.strip().splitlines() if l.startswith("{")]
    return r.returncode, [json.loads(l) for l in lines]


def test_watchdog_reemits_last_good_line_and_exits_zero():
    rc, lines = _run(
        "import time\n"
        "import bench\n"
        "bench._emit({'metric': 'm', 'value': 1.0, 'unit': 'u',"
        " 'vs_baseline': 0.1})\n"
        "bench._arm_watchdog(1)\n"
        "time.sleep(30)\n")
    assert rc == 0
    last = lines[-1]
    assert last["metric"] == "m" and last["value"] == 1.0
    assert "watchdog_note" in last


def test_watchdog_rescue_of_micro_canary_exits_two():
    # the toy canary is driver-verifiable evidence of a healthy window, but
    # a run that only measured the canary must not book as a success (rc 0)
    rc, lines = _run(
        "import time\n"
        "import bench\n"
        "bench._emit({'metric': 'micro_gpt2_train_tokens_per_sec_per_chip',"
        " 'value': 5.0, 'unit': 'tokens/s', 'vs_baseline': 0.0,"
        " 'config': 'micro'})\n"
        "bench._arm_watchdog(1)\n"
        "time.sleep(30)\n")
    assert rc == 2
    last = lines[-1]
    assert last["config"] == "micro" and "watchdog_note" in last


def test_watchdog_with_no_measurement_exits_three_unparseable():
    rc, lines = _run(
        "import time\n"
        "import bench\n"
        "bench._arm_watchdog(1)\n"
        "time.sleep(30)\n")
    assert rc == 3
    last = lines[-1]
    assert "error" in last
    assert "metric" not in last and "value" not in last


def test_emit_tracks_last_good():
    import bench
    prev = bench._LAST_GOOD
    try:
        bench._emit({"metric": "x", "value": 2.0})
        assert bench._LAST_GOOD["metric"] == "x"
        assert bench._LAST_GOOD["value"] == 2.0
        # every emitted line carries the runtime-telemetry snapshot
        # (ISSUE 2: the recorded number is attributable to what ran)
        assert isinstance(bench._LAST_GOOD.get("monitor"), dict)
    finally:
        bench._LAST_GOOD = prev


def test_micro_canary_runs_on_cpu():
    # the canary itself must be cheap and correct everywhere: a wedge-proof
    # canary that crashes is worse than none
    import bench
    sps, mfu = bench.run_micro(quiet=True)
    assert sps > 0


def test_banked_legs_round_trip(tmp_path):
    # ROADMAP item 4: each completed leg persists to the --banked JSONL
    # as it lands and is skipped (re-used) on re-invocation
    import bench
    path = str(tmp_path / "banked.jsonl")
    try:
        bench._bank_load(path)
        assert bench._banked("headline") is None
        line = {"metric": "m", "value": 1.5, "unit": "u"}
        bench._bank("headline", line)
        bench._bank("sweep:8x1024", {"tps": 10.0, "mfu": 0.1})
        # a fresh loader (new invocation) sees both legs
        bench._bank_load(path)
        assert bench._banked("headline") == line
        assert bench._banked("sweep:8x1024") == {"tps": 10.0, "mfu": 0.1}
    finally:
        bench._bank_load(None)


def test_banked_file_tolerates_torn_tail(tmp_path):
    # a killed writer can leave a torn last line: the loader must keep
    # every complete leg instead of dying on the tail
    import bench
    path = str(tmp_path / "banked.jsonl")
    try:
        bench._bank_load(path)
        bench._bank("micro", {"metric": "m", "value": 2.0})
        with open(path, "a") as f:
            f.write('{"leg": "headline", "line": {"metr')  # torn
        bench._bank_load(path)
        assert bench._banked("micro") == {"metric": "m", "value": 2.0}
        assert bench._banked("headline") is None
    finally:
        bench._bank_load(None)


def test_banked_config_leg_skips_measurement(tmp_path):
    # a banked --config leg re-emits its stored line without re-measuring
    # (the second invocation finishes fast and marks the line banked)
    banked = str(tmp_path / "banked.jsonl")
    code = (
        "import sys\n"
        "sys.argv = ['bench.py', '--config', 'lenet', '--steps', '2',\n"
        "            '--batch', '4', '--banked', %r]\n"
        "import bench\n"
        "bench.main()\n" % banked
    )
    rc1, lines1 = _run(code)
    assert rc1 == 0
    first = lines1[-1]
    assert first["config"] == "lenet" and "banked" not in first
    rc2, lines2 = _run(code)
    assert rc2 == 0
    second = lines2[-1]
    assert second.get("banked") is True
    assert second["value"] == first["value"]


def test_heartbeat_beats_blackbox_beacon_and_context():
    # wedge attribution: every phase heartbeat beats the bench/phase
    # beacon and stamps the phase into the dump-bundle context
    import bench
    from paddle_tpu.monitor import blackbox
    blackbox.enable(install=False)
    try:
        blackbox.reset()
        bench._heartbeat("unit_test_phase", "start")
        assert blackbox.beacons()["bench/phase"]["count"] >= 1
        assert blackbox.context()["bench_phase"] == "unit_test_phase:start"
        assert any(r["kind"] == "bench_phase"
                   for r in blackbox.ring())
    finally:
        blackbox.disable()
        blackbox.reset()


def test_serve_mixed_reports_latency_percentiles():
    # r5 (VERDICT r4 #7): the serve bench's realism scenario — staggered
    # arrivals, sampling mix, chunked prefill — must produce a positive
    # aggregate rate and ordered latency percentiles
    import bench
    tps, p50, p99, t50, t99 = bench.run_serve_mixed(2, 4, quiet=True)
    assert tps > 0
    assert 0 < p50 <= p99      # inter-token
    assert 0 < t50 <= t99      # time-to-first-token
    # chunked prefill + drip arrivals: first tokens cost more than steady
    # decode steps in this scenario
    assert t50 > p50
