"""Persistent AOT compile cache (ISSUE 3): disk round-trips across
Executor / SpmdTrainer / ServingEngine, corrupt- and stale-entry
eviction, the LRU byte cap, warm-start API parity (warmed vs cold
bit-identical), and the cross-process zero-fresh-compile acceptance."""
import json
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.framework import aot


@pytest.fixture
def cache_dir(tmp_path):
    d = str(tmp_path / "aot")
    paddle.set_flags({"jit_cache_dir": d})
    monitor.reset()
    yield d
    paddle.set_flags({"jit_cache_dir": ""})


def _flat_compiles(site=None):
    out = {}
    metric = monitor.default_registry().get("compile_cache_total")
    if metric is None:
        return out
    for s in metric.series():
        if site and s.labels.get("site") != site:
            continue
        key = (s.labels.get("event"), s.labels.get("source"))
        out[key] = out.get(key, 0) + int(s.value)
    return out


def _make_trainer():
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=16, dropout=0.0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    return SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(), mesh=mesh)


def _train_batch():
    rng = np.random.RandomState(0)
    return (rng.randint(0, 256, (2, 16)).astype(np.int32),
            rng.randint(0, 256, (2, 16)).astype(np.int32))


def _make_engine(max_seq=32):
    from paddle_tpu.inference.serving import ServingEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=max_seq, dropout=0.0)
    model = GPTForCausalLM(cfg)
    model.eval()
    from paddle_tpu.inference.serving import ServingEngine as SE

    return SE(model, max_batch=2)


class TestCachedJitRoundTrip:
    def test_fresh_then_disk_then_memory(self, cache_dir):
        cj1 = aot.cached_jit(lambda a: a * 2 + 1, site="t", label="p1")
        x = jnp.arange(6.0)
        r1 = cj1(x)
        assert _flat_compiles("t") == {("miss", "fresh"): 1}
        assert len(os.listdir(cache_dir)) == 1
        # a fresh wrapper (new process stand-in): loads from disk
        monitor.reset()
        cj2 = aot.cached_jit(lambda a: a * 2 + 1, site="t", label="p1")
        r2 = cj2(x)
        assert _flat_compiles("t") == {("hit", "disk"): 1}
        r3 = cj2(x)
        assert _flat_compiles("t")[("hit", "memory")] == 1
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r2))
        np.testing.assert_array_equal(np.asarray(r1), np.asarray(r3))

    def test_distinct_programs_distinct_entries(self, cache_dir):
        aot.cached_jit(lambda a: a + 1, site="t", label="a")(jnp.ones(3))
        aot.cached_jit(lambda a: a + 2, site="t", label="b")(jnp.ones(3))
        assert len(os.listdir(cache_dir)) == 2

    def test_cost_registry_captures_disk_hit_executables(self, cache_dir):
        """ISSUE 5: a DESERIALIZED executable must land in the device
        cost registry exactly like a fresh compile — flops + HBM kinds
        under (site, program label) — so MFU/breakdown joins work in a
        warm-started process that never compiled anything."""
        from paddle_tpu.trace import costs

        costs.reset()
        fn = lambda a: (a @ a).sum()                      # noqa: E731
        x = jnp.ones((8, 8))
        aot.cached_jit(fn, site="t", label="matmul")(x)
        fresh = costs.get("t", "matmul")
        assert fresh is not None and fresh["flops"] > 0
        assert fresh["peak_bytes"] > 0
        # a fresh wrapper (new process stand-in): the disk hit re-records
        costs.reset()
        assert costs.get("t", "matmul") is None
        monitor.reset()
        cj2 = aot.cached_jit(fn, site="t", label="matmul")
        cj2(x)
        assert _flat_compiles("t") == {("hit", "disk"): 1}
        hit = costs.get("t", "matmul")
        assert hit is not None
        assert hit["flops"] == fresh["flops"]
        for kind in ("argument_bytes", "output_bytes", "temp_bytes"):
            assert hit[kind] == fresh[kind]
        flops_g = monitor.default_registry().get("program_flops")
        assert any(s.labels == {"site": "t", "sig": "matmul"}
                   and s.value == hit["flops"]
                   for s in flops_g.series())

    def test_corrupt_entry_evicted_and_recompiled(self, cache_dir):
        fn = lambda a: a * 3  # noqa: E731
        aot.cached_jit(fn, site="t", label="c")(jnp.ones(4))
        (name,) = os.listdir(cache_dir)
        path = os.path.join(cache_dir, name)
        with open(path, "wb") as f:
            f.write(b"not a pickle at all")
        monitor.reset()
        out = aot.cached_jit(fn, site="t", label="c")(jnp.ones(4))
        np.testing.assert_array_equal(np.asarray(out), np.full(4, 3.0))
        assert _flat_compiles("t") == {("miss", "fresh"): 1}
        evict = monitor.counter("aot_evict_total", labelnames=("reason",))
        assert evict.labels(reason="corrupt").value == 1
        # the bad file was replaced by a valid re-store
        with open(path, "rb") as f:
            assert pickle.load(f)["key"] == name[:-len(".aotx")]

    def test_version_mismatch_evicted(self, cache_dir):
        fn = lambda a: a - 1  # noqa: E731
        aot.cached_jit(fn, site="t", label="v")(jnp.ones(4))
        (name,) = os.listdir(cache_dir)
        path = os.path.join(cache_dir, name)
        with open(path, "rb") as f:
            entry = pickle.load(f)
        entry["jax"] = "0.0.0-not-this-one"
        with open(path, "wb") as f:
            pickle.dump(entry, f)
        monitor.reset()
        out = aot.cached_jit(fn, site="t", label="v")(jnp.ones(4))
        np.testing.assert_array_equal(np.asarray(out), np.zeros(4))
        assert _flat_compiles("t") == {("miss", "fresh"): 1}
        evict = monitor.counter("aot_evict_total", labelnames=("reason",))
        assert evict.labels(reason="version").value == 1

    def test_lru_cap_enforced(self, cache_dir):
        import time

        fns = [lambda a, i=i: a + i for i in range(4)]
        cjs = [aot.cached_jit(f, site="t", label=f"l{i}")
               for i, f in enumerate(fns)]
        cjs[0](jnp.ones(3))
        (first,) = os.listdir(cache_dir)
        one = os.stat(os.path.join(cache_dir, first)).st_size
        try:
            # cap at ~2.5 entries; spaced writes keep mtime ordering honest
            paddle.set_flags({"jit_cache_max_bytes": int(one * 2.5)})
            for cj in cjs[1:]:
                time.sleep(0.05)
                cj(jnp.ones(3))
            names = os.listdir(cache_dir)
            total = sum(os.stat(os.path.join(cache_dir, n)).st_size
                        for n in names)
            assert total <= int(one * 2.5)
            assert first not in names  # oldest went first
            evict = monitor.counter("aot_evict_total",
                                    labelnames=("reason",))
            assert evict.labels(reason="lru").value >= 1
        finally:
            paddle.set_flags({"jit_cache_max_bytes": 1 << 30})

    def test_warm_without_cache_dir_compiles_in_memory(self):
        """warm() is useful WITHOUT the disk flag: the signature is
        AOT-compiled in memory and live calls never retrace."""
        assert not aot.enabled()
        monitor.reset()
        cj = aot.cached_jit(lambda a: a * 5, site="t", label="w")
        assert cj.warm(jax.ShapeDtypeStruct((3,), jnp.float32))
        assert not cj.warm(jax.ShapeDtypeStruct((3,), jnp.float32))
        out = cj(jnp.ones(3, jnp.float32))
        np.testing.assert_array_equal(np.asarray(out), np.full(3, 5.0))
        assert _flat_compiles("t") == {("miss", "fresh"): 1,
                                       ("hit", "memory"): 1}


class TestExecutorWarmStart:
    def _program(self):
        import paddle_tpu.static as st

        paddle.seed(0)
        main, startup = st.Program(), st.Program()
        st.enable_static()
        try:
            with st.program_guard(main, startup):
                x = st.data("x", [None, 4])
                w = paddle.create_parameter([4, 4])
                y = paddle.matmul(x, w)
        finally:
            st.disable_static()
        return main, startup, y

    def test_disk_roundtrip_and_aot_compile(self, cache_dir):
        import paddle_tpu.static as st

        feed = {"x": np.ones((2, 4), np.float32)}
        exe = st.Executor()
        main, startup, y = self._program()
        exe.run(startup)
        (r1,) = exe.run(main, feed=feed, fetch_list=[y])
        assert _flat_compiles("executor") == {("miss", "fresh"): 1}
        # fresh identical program (new-process stand-in): disk hit
        monitor.reset()
        main2, startup2, y2 = self._program()
        exe.run(startup2)
        (r2,) = exe.run(main2, feed=feed, fetch_list=[y2])
        assert _flat_compiles("executor") == {("hit", "disk"): 1}
        np.testing.assert_array_equal(r1, r2)
        # aot_compile from specs: run() then needs no compile at all
        monitor.reset()
        main3, startup3, y3 = self._program()
        exe.run(startup3)
        assert main3.aot_compile({"x": ((2, 4), "float32")},
                                 fetch_list=[y3]) == "disk"
        (r3,) = exe.run(main3, feed=feed, fetch_list=[y3])
        assert _flat_compiles("executor") == {("hit", "disk"): 1,
                                              ("hit", "memory"): 1}
        np.testing.assert_array_equal(r1, r3)


class TestTrainerWarmStart:
    def test_aot_build_parity_and_disk_roundtrip(self, cache_dir):
        x, y = _train_batch()
        cold = _make_trainer()
        cold_losses = [float(np.asarray(cold.train_step(x, y)._data))
                       for _ in range(2)]
        assert _flat_compiles("trainer")[("miss", "fresh")] == 1
        # warm trainer: aot_build from specs loads the executable from
        # disk; the first train_step performs ZERO fresh compiles and the
        # trajectory is bit-identical to the cold trainer's
        monitor.reset()
        warm = _make_trainer()
        assert warm.aot_build([((2, 16), "int32"),
                               ((2, 16), "int32")]) == "disk"
        compiles = monitor.counter("compile_total", labelnames=("site",))
        before = compiles.labels(site="trainer").value
        warm_losses = [float(np.asarray(warm.train_step(x, y)._data))
                       for _ in range(2)]
        assert compiles.labels(site="trainer").value == before == 0
        assert warm_losses == cold_losses
        assert ("miss", "fresh") not in _flat_compiles("trainer")

    def test_partial_batch_does_not_evict_full_batch_entry(self, cache_dir):
        """Executables are kept per batch signature: a trailing partial
        batch compiles its own step instead of tripping the full-batch
        executable's call guard (which would evict a valid disk entry
        and permanently disable the compiled path)."""
        x, y = _train_batch()
        tr = _make_trainer()
        tr.train_step(x, y)
        n_entries = len(os.listdir(cache_dir))
        loss_p = tr.train_step(x[:1], y[:1])  # trailing partial batch
        assert np.isfinite(float(np.asarray(loss_p._data)))
        # own executable + own disk entry; nothing call-evicted
        assert len(os.listdir(cache_dir)) == n_entries + 1
        evict = monitor.counter("aot_evict_total", labelnames=("reason",))
        assert evict.labels(reason="call").value == 0
        # the full-batch signature still runs from its own executable
        compiles = monitor.counter("compile_total", labelnames=("site",))
        before = compiles.labels(site="trainer").value
        tr.train_step(x, y)
        assert compiles.labels(site="trainer").value == before
        flat = _flat_compiles("trainer")
        assert flat[("hit", "memory")] >= 1 and flat[("miss", "fresh")] == 2


class TestServingWarmStart:
    def test_warmup_parity_and_zero_compiles(self, cache_dir):
        rng = np.random.RandomState(0)
        prompt = rng.randint(0, 256, (8,)).astype(np.int32)
        cold = _make_engine()
        cold.submit(prompt, max_new_tokens=4)
        out_cold = cold.run_until_complete()[0].tokens.tolist()
        # fresh engine, warmed from shape specs: traffic compiles nothing
        monitor.reset()
        warm = _make_engine()
        counts = warm.warmup()
        assert counts["prefill"] >= 1 and counts["step_greedy"] == 1
        compiles = monitor.counter("compile_total", labelnames=("site",))
        before = compiles.labels(site="serving").value
        warm.submit(prompt, max_new_tokens=4)
        out_warm = warm.run_until_complete()[0].tokens.tolist()
        assert compiles.labels(site="serving").value == before
        assert out_warm == out_cold  # bit-identical greedy stream
        # everything the traffic used came from disk or memory
        flat = _flat_compiles("serving")
        traffic_fresh = flat.get(("miss", "fresh"), 0)
        # warmup itself may fresh-compile programs the cold engine never
        # ran (step_sample etc.) — but after warmup, zero more
        assert flat[("hit", "memory")] >= 3
        assert traffic_fresh <= counts_total_fresh(counts)

    def test_draft_engine_warmup_covers_admission(self):
        """Speculative engines row-copy into the DRAFT cache too (its
        shapes differ from the target's): warmup must cover those admit/
        copy signatures or the first admission pays a fresh compile.
        In-memory warm (no cache dir) — the flag-unset warm contract."""
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        assert not aot.enabled()
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0)
        target = GPTForCausalLM(cfg)
        draft = GPTForCausalLM(cfg)
        target.eval()
        draft.eval()
        eng = ServingEngine(target, max_batch=2, draft_model=draft,
                            spec_k=2)
        monitor.reset()
        eng.warmup(sampling=False)
        compiles = monitor.counter("compile_total", labelnames=("site",))
        before = compiles.labels(site="serving").value
        rng = np.random.RandomState(0)
        eng.submit(rng.randint(0, 256, (8,)).astype(np.int32),
                   max_new_tokens=4)
        assert eng.run_until_complete()[0].tokens.shape[0] == 4
        assert compiles.labels(site="serving").value == before

    def test_tp_engine_warmup_specs_carry_cache_sharding(self):
        """Tensor-parallel engines: eval_shape drops the side caches'
        NamedSharding, so warmup must re-attach it — otherwise the warmed
        admit/chunk executables are compiled for unsharded rows, rejected
        at first admission, and silently call-evicted."""
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        if len(jax.devices()) < 2:
            pytest.skip("needs 2 virtual devices")
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0)
        model = GPTForCausalLM(cfg)
        model.eval()
        mesh = build_mesh((2,), ("mp",), devices=jax.devices()[:2])
        eng = ServingEngine(model, max_batch=2, tp_mesh=mesh)
        monitor.reset()
        eng.warmup(sampling=False)
        rng = np.random.RandomState(0)
        eng.submit(rng.randint(0, 256, (8,)).astype(np.int32),
                   max_new_tokens=3)
        assert eng.run_until_complete()[0].tokens.shape[0] == 3
        evict = monitor.counter("aot_evict_total", labelnames=("reason",))
        assert evict.labels(reason="call").value == 0
        compiles = monitor.counter("compile_total", labelnames=("site",))
        flat = _flat_compiles("serving")
        # traffic ran the warmed executables: memory hits, no call-evicts
        assert flat[("hit", "memory")] >= 3

    def test_second_engine_warms_from_disk(self, cache_dir):
        e1 = _make_engine()
        e1.warmup(sampling=False)
        monitor.reset()
        e2 = _make_engine()
        e2.warmup(sampling=False)
        flat = _flat_compiles("serving")
        assert ("miss", "fresh") not in flat
        assert flat[("hit", "disk")] >= 4


def counts_total_fresh(counts):
    return sum(counts.values())


@pytest.mark.slow
class TestCrossProcess:
    """The acceptance criterion end to end: a FRESH PROCESS with a warm
    FLAGS_jit_cache_dir runs a gpt train step, an Executor program, and a
    ServingEngine decode loop with zero fresh XLA compiles (the monitor
    shows only disk/memory hits), and its results are bit-identical to
    the cold process that populated the cache."""

    SCRIPT = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu as paddle
import paddle_tpu.static as st
from paddle_tpu import monitor
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

rng = np.random.RandomState(0)
out = {}

# gpt train step
paddle.seed(0)
cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1, num_heads=2,
                max_seq_len=16, dropout=0.0)
model = GPTForCausalLM(cfg)
opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())
mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(), mesh=mesh)
x = rng.randint(0, 256, (2, 16)).astype(np.int32)
y = rng.randint(0, 256, (2, 16)).astype(np.int32)
out["loss"] = float(np.asarray(trainer.train_step(x, y)._data))

# executor program
paddle.seed(0)
main, startup = st.Program(), st.Program()
st.enable_static()
try:
    with st.program_guard(main, startup):
        xd = st.data("x", [None, 4])
        w = paddle.create_parameter([4, 4])
        yv = paddle.matmul(xd, w)
finally:
    st.disable_static()
exe = st.Executor()
exe.run(startup)
(r,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
               fetch_list=[yv])
out["exec_sum"] = float(np.asarray(r).sum())

# serving decode loop
paddle.seed(0)
smodel = GPTForCausalLM(GPTConfig(vocab_size=256, hidden_size=32,
                                  num_layers=1, num_heads=2,
                                  max_seq_len=32, dropout=0.0))
smodel.eval()
eng = ServingEngine(smodel, max_batch=2)
eng.submit(rng.randint(0, 256, (8,)).astype(np.int32), max_new_tokens=3)
res = eng.run_until_complete()
out["tokens"] = res[0].tokens.tolist()

flat = {}
m = monitor.default_registry().get("compile_cache_total")
for s in m.series():
    k = s.labels.get("event") + "_" + s.labels.get("source")
    flat[k] = flat.get(k, 0) + int(s.value)
out["cache"] = flat
ct = monitor.default_registry().get("compile_total")
out["fresh_compiles"] = sum(int(s.value) for s in ct.series()) if ct else 0
print("RESULT " + json.dumps(out))
"""

    def _run(self, cache_d):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   FLAGS_jit_cache_dir=cache_d, FLAGS_monitor="1",
                   XLA_FLAGS="--xla_force_host_platform_device_count=1")
        proc = subprocess.run([sys.executable, "-c", self.SCRIPT],
                              capture_output=True, text=True, timeout=900,
                              env=env,
                              cwd=os.path.dirname(os.path.dirname(
                                  os.path.abspath(__file__))))
        assert proc.returncode == 0, proc.stderr[-4000:]
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("RESULT ")][-1]
        return json.loads(line[len("RESULT "):])

    def test_second_process_compiles_nothing_fresh(self, tmp_path):
        d = str(tmp_path / "aot")
        cold = self._run(d)
        assert cold["fresh_compiles"] > 0
        warm = self._run(d)
        # zero fresh XLA compiles: only disk (and memory) sources appear
        assert warm["fresh_compiles"] == 0, warm["cache"]
        assert all(not k.endswith("_fresh") for k in warm["cache"])
        assert warm["cache"].get("hit_disk", 0) >= 3
        # warmed results bit-identical to the cold process
        assert warm["loss"] == cold["loss"]
        assert warm["exec_sum"] == cold["exec_sum"]
        assert warm["tokens"] == cold["tokens"]
