"""Tier-1 gate for the async dispatch + TPP stack (ISSUE 11): with
FLAGS_async_dispatch and FLAGS_tpp_kernels both unset, the trainer and
the GPT forward are EXACTLY the pre-PR ones — neither
paddle_tpu.distributed.async_dispatch nor paddle_tpu.ops.tpp is ever
imported (subprocess pin), params are byte-identical whether or not the
armed paths were exercised in-process, no async_*/tpp_* metric series or
dispatch/* span appears, train_step returns a plain Tensor (not a
StepHandle), and the disarmed per-step flag checks cost the same
one-lookup bar as every other disabled fast path. Plus: the
tools/metrics_dump.py --async exit-code contract and the
tools/chaos_check.py async_nonfinite registration."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor, trace
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: metric families this PR introduced — with the flags unset NONE of
#: them may grow a series on the trainer path
ASYNC_FAMILIES = ("async_verdict_fetch_total", "async_window_depth",
                  "tpp_kernel_calls_total")

_PLAIN_TRAINER = (
    "import jax; jax.config.update('jax_platforms', 'cpu')\n"
    "import hashlib\n"
    "import numpy as np\n"
    "import paddle_tpu as paddle\n"
    "from paddle_tpu import nn\n"
    "from paddle_tpu.distributed.mesh import build_mesh\n"
    "from paddle_tpu.distributed.spmd import SpmdTrainer\n"
    "def run_plain():\n"
    "    paddle.seed(0)\n"
    "    net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))\n"
    "    opt = paddle.optimizer.AdamW(learning_rate=1e-3,\n"
    "        parameters=net.parameters())\n"
    "    mesh = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
    "    tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)\n"
    "    x = paddle.to_tensor(np.ones((4, 8), np.float32))\n"
    "    y = paddle.to_tensor(np.ones((4, 4), np.float32))\n"
    "    for _ in range(3):\n"
    "        tr.train_step(x, y)\n"
    "    h = hashlib.sha256()\n"
    "    for k in sorted(tr.params):\n"
    "        h.update(np.ascontiguousarray(\n"
    "            np.asarray(tr.params[k])).tobytes())\n"
    "    return h.hexdigest()\n")


def _run(code):
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


class TestInertByDefault:
    @pytest.mark.slow
    def test_plain_subprocess_never_imports_async_or_tpp_and_pins_params(
            self):
        """The structural zero-overhead pin, in one subprocess: a plain
        trainer run (a) never imports async_dispatch or ops.tpp, and
        (b) produces byte-identical params before vs after an
        async-armed trainer AND a TPP-armed GPT forward ran in the same
        process — the disarmed paths are the pre-PR paths."""
        _run(
            _PLAIN_TRAINER +
            "d1 = run_plain()\n"
            "import sys\n"
            "assert 'paddle_tpu.distributed.async_dispatch' not in \\\n"
            "    sys.modules, 'async_dispatch imported on the plain path'\n"
            "assert 'paddle_tpu.ops.tpp' not in sys.modules, \\\n"
            "    'ops.tpp imported on the plain path'\n"
            "paddle.set_flags({'async_dispatch': True, 'async_window': 2,\n"
            "                  'check_nan_inf': True,\n"
            "                  'tpp_kernels': True})\n"
            "from paddle_tpu.models import (GPTConfig, GPTForCausalLM,\n"
            "                               GPTPretrainLoss)\n"
            "paddle.seed(1)\n"
            "cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,\n"
            "                num_heads=2, max_seq_len=32, dropout=0.0)\n"
            "m2 = GPTForCausalLM(cfg)\n"
            "opt2 = paddle.optimizer.AdamW(learning_rate=1e-3,\n"
            "    parameters=m2.parameters())\n"
            "mesh2 = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
            "tr2 = SpmdTrainer(m2, opt2, loss_fn=GPTPretrainLoss(),\n"
            "                  mesh=mesh2)\n"
            "rng = np.random.RandomState(0)\n"
            "ids = rng.randint(0, 64, (2, 16)).astype(np.int32)\n"
            "lb = rng.randint(0, 64, (2, 16)).astype(np.int32)\n"
            "for _ in range(3):\n"
            "    h = tr2.train_step(ids, lb)\n"
            "tr2.guard_sync()\n"
            "from paddle_tpu.distributed.async_dispatch import StepHandle\n"
            "assert isinstance(h, StepHandle)\n"
            "assert 'paddle_tpu.ops.tpp' in sys.modules\n"
            "from paddle_tpu.ops import tpp\n"
            "assert any(r['op'] == 'ln_matmul'\n"
            "           for r in tpp.registry_table())\n"
            "paddle.set_flags({'async_dispatch': False,\n"
            "                  'check_nan_inf': False,\n"
            "                  'tpp_kernels': False})\n"
            "d2 = run_plain()\n"
            "assert d1 == d2, ('flag-unset trainer params drifted after '\n"
            "    'the async/TPP paths were exercised in-process')\n"
            "print('OK')\n")

    def test_flag_unset_zero_series_spans_plain_tensor(self):
        """In-process: a flag-unset trainer run grows no async-PR
        series, emits no dispatch/* span even with tracing on, keeps a
        single executable, and returns a plain Tensor."""
        from paddle_tpu import nn
        from paddle_tpu.core.tensor import Tensor

        monitor.reset()
        trace.clear()
        trace.enable()
        try:
            paddle.seed(0)
            net = nn.Linear(8, 4)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
            tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
            for _ in range(3):
                out = tr.train_step(np.ones((4, 8), np.float32),
                                    np.zeros((4, 4), np.float32))
        finally:
            trace.disable()
        assert type(out) is Tensor
        reg = monitor.default_registry()
        for family in ASYNC_FAMILIES:
            metric = reg.get(family)
            assert metric is None or all(
                (s.count if hasattr(s, "count") and s.kind == "histogram"
                 else s.value) == 0
                for s in metric.series()), family
        assert not [s.name for s in trace.spans()
                    if s.name.startswith("dispatch/")]
        assert len(tr._compiled_store) == 1
        assert tr._pending_verdicts == []   # no guard, nothing pending
        assert tr._verdict_fetches == 0

    def test_disarmed_flag_checks_under_5us(self):
        """The flag-unset per-step additions — _async_active and the
        tpp_kernels get_flag — are one registry lookup each, bounded at
        the same bar as every other disabled fast path."""
        from paddle_tpu import nn

        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            tr._async_active()
            flags.get_flag("tpp_kernels", False)
        per_call_us = (time.perf_counter() - t0) / (2 * n) * 1e6
        assert per_call_us < 5.0, (
            f"disarmed async/tpp flag check costs {per_call_us:.2f}us")

    def test_flags_defined_with_defaults(self):
        assert flags.get_flag("async_dispatch") is False
        assert flags.get_flag("async_window") == 8
        assert flags.get_flag("tpp_kernels") is False
        assert flags.get_flag("overlap_grad_comm") is False

    def test_post_hoc_toggle_raises(self):
        from paddle_tpu import nn

        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
        paddle.set_flags({"async_dispatch": True})
        try:
            with pytest.raises(RuntimeError, match="async_dispatch"):
                tr.train_step(np.ones((2, 4), np.float32),
                              np.zeros((2, 2), np.float32))
        finally:
            paddle.set_flags({"async_dispatch": False})

    def test_overlap_without_quantized_raises(self):
        from paddle_tpu import nn

        paddle.set_flags({"overlap_grad_comm": True})
        try:
            paddle.seed(0)
            net = nn.Linear(4, 2)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
            with pytest.raises(ValueError, match="overlap_grad_comm"):
                SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
        finally:
            paddle.set_flags({"overlap_grad_comm": False})

    def test_chaos_pass_registered(self):
        spec = importlib.util.spec_from_file_location(
            "chaos_check", os.path.join(REPO, "tools", "chaos_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert "async_nonfinite" in mod.PASSES


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestAsyncToolGate:
    def test_metrics_dump_async_missing_metrics_exits_1(
            self, capsys, monkeypatch):
        md = _load_tool("metrics_dump")
        monkeypatch.setattr(md, "run_async_loop", lambda **kw: None)
        rc = md.main(["--async", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        msgs = [f["message"]
                for f in report["targets"]["async"]["findings"]
                if f["pass"] == "metrics-present"]
        assert any("async_verdict_fetch_total" in m for m in msgs)
        assert any("tpp_kernel_calls_total" in m for m in msgs)

    @pytest.mark.slow
    def test_metrics_dump_async_green_subprocess(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "metrics_dump.py"),
             "--async", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]

    @pytest.mark.slow
    def test_parity_async_exact_exits_0(self, capsys):
        """The acceptance-criterion pin: the async-dispatch A/B is
        verified EXACT (zero tolerance, zero divergence)."""
        pc = _load_tool("parity_check")
        rc = pc.main(["--ab", "async_dispatch", "--steps", "2",
                      "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["totals"]["error"] == 0
        assert report["targets"]["async_dispatch"]["report"][
            "max_abs_loss_diff"] == 0.0

    @pytest.mark.slow
    def test_parity_tpp_with_negative_control(self, capsys):
        """One CI lane, both directions: the TPP target passes its
        declared per-op band AND its lr-perturbed twin diverges (exit
        1) — the band is a gate, not a rubber stamp."""
        pc = _load_tool("parity_check")
        rc = pc.main(["--ab", "tpp_kernels", "--perturb-lr", "8",
                      "--steps", "2", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        targets = report["targets"]
        assert targets["tpp_kernels"]["counts"]["error"] == 0
        ctrl = targets["tpp_kernels+perturb_lr"]
        assert ctrl["counts"]["error"] == 1
        assert ctrl["report"]["diverged"]

    @pytest.mark.slow
    def test_chaos_async_nonfinite_green(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "chaos_check.py"),
             "--only", "async_nonfinite", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
        report = json.loads(out.stdout)
        assert report["totals"]["error"] == 0
