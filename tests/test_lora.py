"""LoRA adapter tests (incubate/lora.py — beyond-reference addition)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate.lora import (LoRALinear, apply_lora, lora_parameters,
                                      lora_state_dict, merge_lora)


class TinyNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.q_proj = nn.Linear(8, 8)
        self.v_proj = nn.Linear(8, 8)
        self.ffn = nn.Linear(8, 4)

    def forward(self, x):
        return self.ffn(nn.functional.relu(self.q_proj(x) + self.v_proj(x)))


def _x(b=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return paddle.to_tensor(rng.randn(b, d).astype(np.float32))


class TestLoRALinear:
    def test_init_is_identity(self, seed):
        base = nn.Linear(8, 4)
        x = _x()
        y0 = np.asarray(base(x)._data)
        wrapped = LoRALinear(base, r=2, alpha=4)
        np.testing.assert_allclose(np.asarray(wrapped(x)._data), y0,
                                   atol=1e-6)

    def test_base_frozen_adapters_train(self, seed):
        net = TinyNet()
        apply_lora(net, r=2)
        w_before = np.asarray(net.q_proj.base.weight.numpy()).copy()
        a_before = np.asarray(net.q_proj.lora_A.numpy()).copy()
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lora_parameters(net))
        x, target = _x(), _x(4, 4, seed=1)
        losses = []
        for _ in range(5):
            loss = nn.functional.mse_loss(net(x), target)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]
        np.testing.assert_array_equal(
            np.asarray(net.q_proj.base.weight.numpy()), w_before)
        assert np.abs(np.asarray(net.q_proj.lora_A.numpy())
                      - a_before).max() > 0

    def test_target_modules_filter(self, seed):
        net = TinyNet()
        replaced = apply_lora(net, r=2, target_modules=["q_proj", "v_proj"])
        assert sorted(replaced) == ["q_proj", "v_proj"]
        assert isinstance(net.q_proj, LoRALinear)
        assert isinstance(net.v_proj, LoRALinear)
        assert isinstance(net.ffn, nn.Linear)
        # freeze_rest froze the untouched ffn too
        assert not net.ffn.weight.trainable

    def test_double_wrap_raises(self, seed):
        net = TinyNet()
        apply_lora(net, r=2)
        try:
            apply_lora(net, r=2)
            raise AssertionError("second apply_lora should find no Linear")
        except ValueError:
            pass

    def test_merge_parity_and_cleanup(self, seed):
        net = TinyNet()
        apply_lora(net, r=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lora_parameters(net))
        x, target = _x(), _x(4, 4, seed=1)
        for _ in range(3):
            loss = nn.functional.mse_loss(net(x), target)
            loss.backward()
            opt.step()
            opt.clear_grad()
        y_lora = np.asarray(net(x)._data)
        n = merge_lora(net)
        assert n == 3
        assert isinstance(net.q_proj, nn.Linear)
        np.testing.assert_allclose(np.asarray(net(x)._data), y_lora,
                                   atol=1e-5, rtol=1e-5)
        # merged model is fully trainable again
        assert all(p.trainable for p in net.parameters())

    def test_adapter_state_dict_roundtrip(self):
        paddle.seed(7)
        net = TinyNet()
        apply_lora(net, r=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lora_parameters(net))
        x, target = _x(), _x(4, 4, seed=1)
        for _ in range(3):
            loss = nn.functional.mse_loss(net(x), target)
            loss.backward()
            opt.step()
            opt.clear_grad()
        sd = lora_state_dict(net)
        assert sorted(sd) == sorted(
            n for n, _ in net.named_parameters() if "lora_" in n)
        y = np.asarray(net(x)._data)

        paddle.seed(7)   # identical base init...
        net2 = TinyNet()
        paddle.seed(999)  # ...but different fresh adapters
        apply_lora(net2, r=2)
        assert np.abs(np.asarray(net2(x)._data) - y).max() > 1e-6
        named = dict(net2.named_parameters())
        for k, v in sd.items():
            named[k].set_value(v)  # the adapters carry the whole delta
        np.testing.assert_allclose(np.asarray(net2(x)._data), y,
                                   atol=1e-6)


class TestLoRAWithTrainer:
    def test_spmd_trainer_frozen_split(self, seed):
        import jax

        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        net = TinyNet()
        apply_lora(net, r=2)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lora_parameters(net))
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        trainer = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
        # only adapters are trainable params; bases route to the frozen set
        assert all("lora_" in n for n in trainer.params)
        assert any("lora_" not in n for n in trainer.frozen)
        x, target = _x(), _x(4, 4, seed=1)
        l0 = float(np.asarray(trainer.train_step(x, target)._data))
        l5 = l0
        for _ in range(5):
            l5 = float(np.asarray(trainer.train_step(x, target)._data))
        assert np.isfinite(l5) and l5 < l0


class TestLoRAAliasing:
    def test_shared_linear_gets_one_adapter_and_merges_once(self, seed):
        """A Linear registered under two parents (weight tying via module
        aliasing) must train ONE shared adapter and fold its delta exactly
        once on merge."""

        class Tied(nn.Layer):
            def __init__(self):
                super().__init__()
                self.enc = nn.Linear(8, 8)
                self.dec = self.enc  # same object, two registrations

            def forward(self, x):
                return self.dec(nn.functional.relu(self.enc(x)))

        net = Tied()
        apply_lora(net, r=2)
        assert net.enc is net.dec  # one shared wrapper
        assert isinstance(net.enc, LoRALinear)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lora_parameters(net))
        x, target = _x(), _x(4, 8, seed=1)
        for _ in range(3):
            loss = nn.functional.mse_loss(net(x), target)
            loss.backward()
            opt.step()
            opt.clear_grad()
        y = np.asarray(net(x)._data)
        assert merge_lora(net) == 1
        assert isinstance(net.enc, nn.Linear) and net.enc is net.dec
        np.testing.assert_allclose(np.asarray(net(x)._data), y,
                                   atol=1e-5, rtol=1e-5)

    def test_merge_restores_pre_lora_trainable_set(self, seed):
        """freeze_rest freezes unmatched layers; merge_lora must hand back
        the ORIGINAL trainable set, not leave the rest frozen."""
        net = TinyNet()
        net.ffn.bias.trainable = False  # user froze this before LoRA
        net.ffn.bias.stop_gradient = True
        apply_lora(net, r=2, target_modules=["q_proj"])
        assert not net.v_proj.weight.trainable  # freeze_rest
        merge_lora(net)
        assert net.v_proj.weight.trainable
        assert net.q_proj.weight.trainable
        assert not net.ffn.bias.trainable  # user's own freeze preserved


class TestLoRAOnGPT:
    def test_gpt_attention_adapters_then_merged_generate(self, seed):
        """LoRA on the flagship LM's attention projections: adapters train
        under the LM loss, and after merge the model serves through the
        name-addressed KV-cache generate path (which reads qualified param
        names like blocks.0.attn.proj.weight — merge must restore them)."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32, dropout=0.0)
        model = GPTForCausalLM(cfg)
        replaced = apply_lora(model, r=4, target_modules=["attn.qkv",
                                                          "attn.proj"])
        assert len(replaced) == 2 * cfg.num_layers
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lora_parameters(model))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, 64, (2, 16)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.randint(0, 64, (2, 16)).astype(np.int32))
        losses = []
        for _ in range(4):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]

        model.eval()
        logits_lora = np.asarray(model(ids)._data)
        assert merge_lora(model) == 2 * cfg.num_layers
        np.testing.assert_allclose(np.asarray(model(ids)._data),
                                   logits_lora, atol=1e-4, rtol=1e-4)
        out = model.generate(ids[:, :8], max_new_tokens=4)
        seqs = out[0] if isinstance(out, tuple) else out
        arr = np.asarray(seqs._data if hasattr(seqs, "_data") else seqs)
        assert arr.shape[-1] >= 4


class TestLoRAGuards:
    def test_repeat_apply_keeps_original_trainable_snapshot(self, seed):
        """A second apply_lora with disjoint targets must not overwrite the
        pre-LoRA snapshot with the post-freeze state: after merge, params
        untouched by either apply are trainable again."""
        net = TinyNet()
        apply_lora(net, r=2, target_modules=["q_proj"])
        apply_lora(net, r=2, target_modules=["ffn"])
        merge_lora(net)
        assert net.v_proj.weight.trainable  # wrapped by neither apply
        assert net.ffn.weight.trainable
        assert net.q_proj.weight.trainable

    def test_unmerged_generate_raises_helpful_error(self, seed):
        """The name-addressed KV-cache decode path cannot see un-merged
        adapters; generate must fail with a message pointing at merge_lora,
        not an opaque KeyError."""
        import pytest

        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, max_seq_len=32, dropout=0.0)
        model = GPTForCausalLM(cfg)
        apply_lora(model, r=2, target_modules=["attn.qkv"])
        model.eval()
        ids = paddle.to_tensor(np.zeros((1, 4), np.int32))
        with pytest.raises(ValueError, match="merge_lora"):
            model.generate(ids, max_new_tokens=2)


class TestLoRATensorParallel:
    def test_wraps_parallel_linears_and_merges(self, seed):
        """LoRA on a tensor-parallel GPT (Column/RowParallelLinear blocks):
        adapters train eagerly, bases stay frozen with their spmd_spec, and
        merge restores a forward identical to the trained LoRA model."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, max_seq_len=16, dropout=0.0,
                        tensor_parallel=True)
        model = GPTForCausalLM(cfg)
        replaced = apply_lora(model, r=2, target_modules=["attn.qkv",
                                                          "mlp.fc1"])
        assert len(replaced) == 2
        qkv = model.gpt.blocks[0].attn.qkv
        assert isinstance(qkv, LoRALinear)
        # frozen base keeps its tensor-parallel sharding annotation
        assert getattr(qkv.base.weight, "spmd_spec", None) is not None
        assert not qkv.base.weight.trainable
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lora_parameters(model))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, 64, (2, 8)).astype(np.int32))
        labels = paddle.to_tensor(
            rng.randint(0, 64, (2, 8)).astype(np.int32))
        losses = []
        for _ in range(3):
            loss = model.loss(ids, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]
        model.eval()
        y = np.asarray(model(ids)._data)
        assert merge_lora(model) == 2
        np.testing.assert_allclose(np.asarray(model(ids)._data), y,
                                   atol=1e-4, rtol=1e-4)


class TestUserModuleNamedBase:
    """Snapshot exclusion must key on wrapper MEMBERSHIP, not the '.base.'
    name pattern: a user submodule legitimately named 'base' has to survive
    a second apply_lora + merge with its trainable state restored."""

    def test_second_apply_restores_module_named_base(self):
        from paddle_tpu.incubate.lora import apply_lora, merge_lora

        class Enc(nn.Layer):
            def __init__(self):
                super().__init__()
                self.base = nn.Linear(4, 4)
                self.q = nn.Linear(4, 4)

            def forward(self, x):
                return self.q(self.base(x))

        paddle.seed(0)
        m = Enc()
        apply_lora(m, r=2, target_modules=["q"])
        assert not m.base.weight.trainable        # frozen by freeze_rest
        apply_lora(m, r=2, target_modules=["base"])
        merge_lora(m)
        assert m.base.weight.trainable
        assert m.q.weight.trainable
