"""Prefill/decode disaggregation (serving/disagg.py): the acceptance bar
is BIT-IDENTICAL completions vs the monolithic engine on the same
prompts, with every handoff metered (kv_handoff span +
kv_handoff_bytes_total)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor, trace
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM
from paddle_tpu.serving import decode_model as dm
from paddle_tpu.serving.disagg import DisaggregatedPool, PrefillWorker


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def rng():
    return np.random.RandomState(0)


class TestBitIdentical:
    def test_pool_matches_monolithic_engine(self, model, rng):
        prompts = [rng.randint(0, 128, (n,)).astype(np.int32)
                   for n in (5, 9, 17, 4, 12)]
        mono = ServingEngine(model, max_batch=2)
        mrids = [mono.submit(p, max_new_tokens=8) for p in prompts]
        mres = mono.run_until_complete()

        pool = DisaggregatedPool(model, prefill_workers=2,
                                 decode_engines=2, max_batch=2)
        prids = [pool.submit(p, max_new_tokens=8) for p in prompts]
        pres = pool.run_until_complete()
        for mr, pr in zip(mrids, prids):
            np.testing.assert_array_equal(pres[pr].tokens,
                                          mres[mr].tokens)
            assert pres[pr].finish_reason == mres[mr].finish_reason
        st = pool.stats()["pool"]
        assert st["handoffs"] == 5 and st["pending"] == 0
        # the split actually fanned decode work out
        assert len(st["per_engine"]) == 2

    def test_sampling_seeds_survive_the_handoff(self, model, rng):
        p = rng.randint(0, 128, (7,)).astype(np.int32)
        mono = ServingEngine(model, max_batch=2)
        r = mono.submit(p, max_new_tokens=6, temperature=0.8, top_k=20,
                        seed=1234)
        mres = mono.run_until_complete()
        pool = DisaggregatedPool(model, prefill_workers=1,
                                 decode_engines=1, max_batch=2)
        pr = pool.submit(p, max_new_tokens=6, temperature=0.8, top_k=20,
                         seed=1234)
        pres = pool.run_until_complete()
        np.testing.assert_array_equal(pres[pr].tokens, mres[r].tokens)

    def test_backpressure_more_requests_than_slots(self, model, rng):
        pool = DisaggregatedPool(model, prefill_workers=1,
                                 decode_engines=1, max_batch=2)
        prompts = [rng.randint(0, 128, (4 + i,)).astype(np.int32)
                   for i in range(6)]
        rids = [pool.submit(p, max_new_tokens=4) for p in prompts]
        pool.step()
        # only as many handoffs as the decode tier has room for
        assert pool.stats()["pool"]["handoffs"] <= 2
        res = pool.run_until_complete()
        assert len(res) == 6
        for rid, p in zip(rids, prompts):
            ref = model.generate(paddle.to_tensor(p[None]),
                                 max_new_tokens=4, temperature=0.0)
            np.testing.assert_array_equal(
                res[rid].tokens, np.asarray(ref._data)[0, len(p):])


class TestHandoffAccounting:
    def test_kv_handoff_metrics(self, model, rng):
        monitor.reset()
        pool = DisaggregatedPool(model, prefill_workers=1,
                                 decode_engines=1, max_batch=2)
        pool.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                    max_new_tokens=2)
        pool.submit(rng.randint(0, 128, (9,)).astype(np.int32),
                    max_new_tokens=2)
        pool.run_until_complete()
        flat = monitor.flatten(monitor.snapshot())
        # one [L=2, 1, KVh=2, T=64, hd=16] f32 row per side, two sides,
        # two handoffs
        expect = 2 * (2 * 2 * 2 * 64 * 16 * 4)
        assert flat["kv_handoff_bytes_total"] == expect
        assert flat["kv_handoff_total{event=ok}"] == 2
        assert pool.stats()["pool"]["handoff_bytes"] == expect

    def test_kv_handoff_span_threads_to_the_decode_request(self, model,
                                                           rng):
        trace.clear()
        trace.enable()
        try:
            pool = DisaggregatedPool(model, prefill_workers=1,
                                     decode_engines=1, max_batch=2)
            rid = pool.submit(rng.randint(0, 128, (5,)).astype(np.int32),
                              max_new_tokens=3)
            pool.run_until_complete()
        finally:
            trace.disable()
        handoffs = [s for s in trace.spans() if s.name == "kv_handoff"]
        assert len(handoffs) == 1
        sp = handoffs[0]
        assert sp.attrs["bytes"] > 0 and sp.attrs["engine"] == "decode0"
        # the engine request + decode spans joined the handoff's trace
        fam = {s.name for s in trace.spans()
               if s.trace_id == sp.trace_id}
        assert {"kv_handoff", "request", "decode"} <= fam
        assert pool.get_request(rid).trace_id == sp.trace_id


class TestAdmitPrefilled:
    def test_manual_worker_to_engine_handoff(self, model, rng):
        """The raw interface a remote prefill tier would drive: worker
        prefills, engine admits the row, outputs match submit()."""
        p = rng.randint(0, 128, (9,)).astype(np.int32)
        eng = ServingEngine(model, max_batch=2)
        r_direct = eng.submit(p, max_new_tokens=5)
        worker = PrefillWorker(model)
        kv_row, logits = worker.prefill(p)
        r_handoff = eng.admit_prefilled(p, kv_row, logits,
                                        max_new_tokens=5)
        res = eng.run_until_complete()
        np.testing.assert_array_equal(res[r_handoff].tokens,
                                      res[r_direct].tokens)
        assert dm.cache_row_bytes(kv_row) > 0
        assert worker.stats()["prefills"] == 1

    def test_handoff_queue_lifecycle(self, model, rng):
        p = rng.randint(0, 128, (5,)).astype(np.int32)
        eng = ServingEngine(model, max_batch=1)
        worker = PrefillWorker(model)
        kv_row, logits = worker.prefill(p)
        rid = eng.admit_prefilled(p, kv_row, logits, max_new_tokens=4)
        # visible while waiting in the handoff queue...
        assert eng.get_request(rid).rid == rid
        assert eng.has_work()
        assert eng.stats()["requests"]["handoff"] == 1
        # ...and cancellable there
        assert eng.cancel(rid) is True
        assert eng.get_request(rid).finish_reason == "cancelled"
        assert not eng.has_work()

    def test_admit_prefilled_validation(self, model, rng):
        p = rng.randint(0, 128, (5,)).astype(np.int32)
        eng = ServingEngine(model, max_batch=1)
        worker = PrefillWorker(model)
        kv_row, logits = worker.prefill(p)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.admit_prefilled(p, kv_row, logits, max_new_tokens=0)
        with pytest.raises(ValueError, match="empty prompt"):
            eng.admit_prefilled(np.zeros((0,), np.int32), kv_row, logits)
        eng.drain()
        with pytest.raises(RuntimeError, match="draining"):
            eng.admit_prefilled(p, kv_row, logits)

    def test_bounded_engine_rejects_handoff_when_full(self, model, rng):
        """max_queue bounds the TOTAL admission backlog (queue +
        handoff): a producer pushing prefilled rows past the bound gets
        QueueFullError instead of unbounded growth, and health() sees
        the handoff backlog as queue depth."""
        from paddle_tpu.inference.serving import QueueFullError

        p = rng.randint(0, 128, (5,)).astype(np.int32)
        eng = ServingEngine(model, max_batch=1, max_queue=2)
        worker = PrefillWorker(model)
        kv_row, logits = worker.prefill(p)
        eng.admit_prefilled(p, *worker.prefill(p), max_new_tokens=2)
        eng.admit_prefilled(p, *worker.prefill(p), max_new_tokens=2)
        assert eng.health()["queue_depth"] == 2
        assert eng.health()["state"] == "degraded"   # >= 80% of bound
        with pytest.raises(QueueFullError):
            eng.admit_prefilled(p, kv_row, logits, max_new_tokens=2)
        res = eng.run_until_complete()
        assert len(res) == 2

    def test_bounded_pool_never_wastes_a_prefill(self, model, rng):
        """With max_queue < max_batch the pool's backpressure must gate
        BEFORE the prefill forward runs: each prompt is prefilled exactly
        once (a row computed then rejected by QueueFullError would be
        recomputed every step)."""
        pool = DisaggregatedPool(model, prefill_workers=1,
                                 decode_engines=1, max_batch=4,
                                 max_queue=1)
        prompts = [rng.randint(0, 128, (4 + i,)).astype(np.int32)
                   for i in range(4)]
        rids = [pool.submit(p, max_new_tokens=3) for p in prompts]
        res = pool.run_until_complete()
        assert len(res) == 4
        assert pool.workers[0].stats()["prefills"] == 4
        for rid, p in zip(rids, prompts):
            ref = model.generate(paddle.to_tensor(p[None]),
                                 max_new_tokens=3, temperature=0.0)
            np.testing.assert_array_equal(
                res[rid].tokens, np.asarray(ref._data)[0, len(p):])

    def test_speculative_engine_rejects_handoff(self, model, rng):
        draft = model   # any valid decode model works as its own draft
        eng = ServingEngine(model, max_batch=1, draft_model=draft,
                            spec_k=2)
        worker = PrefillWorker(model)
        kv_row, logits = worker.prefill(
            rng.randint(0, 128, (5,)).astype(np.int32))
        with pytest.raises(RuntimeError, match="speculative"):
            eng.admit_prefilled(np.arange(3, dtype=np.int32), kv_row,
                                logits)

    def test_pool_submit_fails_fast_on_bad_args(self, model, rng):
        """Invalid decode args are rejected at pool.submit — a bad
        request that only failed at handoff time would wedge the pool
        (re-raised from every step, blocking the prefill queue)."""
        pool = DisaggregatedPool(model, prefill_workers=1,
                                 decode_engines=1, max_batch=2)
        p = rng.randint(0, 128, (5,)).astype(np.int32)
        with pytest.raises(ValueError, match="temperature"):
            pool.submit(p, temperature=-1)
        with pytest.raises(ValueError, match="max_new_tokens"):
            pool.submit(p, max_new_tokens=0)
        # the rejected submits left nothing pending; valid traffic flows
        rid = pool.submit(p, max_new_tokens=3)
        res = pool.run_until_complete()
        assert res[rid].finish_reason == "length"

    def test_worker_validation(self, model):
        worker = PrefillWorker(model)
        with pytest.raises(ValueError, match="empty prompt"):
            worker.prefill(np.zeros((0,), np.int32))
        with pytest.raises(ValueError, match="too long"):
            worker.prefill(np.zeros((64,), np.int32))
