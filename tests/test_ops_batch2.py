"""Op-test burn-down, batch 2: search / logic / stat / creation / indexing ops
(SURVEY §4 table-driven continuation)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

from op_test import OpTest

rng = np.random.RandomState(11)
X = rng.randn(3, 4).astype(np.float32)
Y = rng.randn(3, 4).astype(np.float32)
I2 = np.array([2, 0], np.int64)

CASES = [
    # search / sort
    ("argmax", paddle.argmax, {"x": X}, {"axis": 1}, [X.argmax(1)], None),
    ("argmin", paddle.argmin, {"x": X}, {"axis": 1}, [X.argmin(1)], None),
    ("argsort", paddle.argsort, {"x": X}, {"axis": 1}, [X.argsort(1)], None),
    ("sort", paddle.sort, {"x": X}, {"axis": 1}, [np.sort(X, 1)], ["x"]),
    ("where", paddle.where,
     {"c": X > 0, "x": X, "y": Y}, {}, [np.where(X > 0, X, Y)], None),
    ("masked_select", paddle.masked_select,
     {"x": X, "mask": np.ones((3, 4), bool)}, {}, [X.reshape(-1)], None),
    # logic
    ("equal", paddle.equal, {"x": X, "y": X}, {}, [np.ones((3, 4), bool)], None),
    ("not_equal", paddle.not_equal, {"x": X, "y": X}, {},
     [np.zeros((3, 4), bool)], None),
    ("greater_than", paddle.greater_than, {"x": X, "y": Y}, {}, [X > Y], None),
    ("less_equal", paddle.less_equal, {"x": X, "y": Y}, {}, [X <= Y], None),
    ("logical_and", paddle.logical_and,
     {"x": X > 0, "y": Y > 0}, {}, [(X > 0) & (Y > 0)], None),
    ("logical_not", paddle.logical_not, {"x": X > 0}, {}, [~(X > 0)], None),
    ("isfinite", paddle.isfinite, {"x": X}, {}, [np.isfinite(X)], None),
    ("allclose", paddle.allclose, {"x": X, "y": X}, {}, [np.array(True)], None),
    # stat
    ("std", paddle.std, {"x": X}, {}, [X.std(ddof=1)], None),
    ("var", paddle.var, {"x": X}, {}, [X.var(ddof=1)], None),
    ("median", paddle.median, {"x": np.arange(5).astype(np.float32)}, {},
     [np.float32(2.0)], None),
    ("quantile", paddle.quantile,
     {"x": np.arange(5).astype(np.float32)}, {"q": 0.5}, [np.float32(2.0)],
     None),
    # indexing / gather
    ("gather", paddle.gather, {"x": X, "index": I2}, {"axis": 0}, [X[I2]],
     None),
    ("index_select", paddle.index_select, {"x": X, "index": I2}, {"axis": 0},
     [X[I2]], None),
    ("take_along_axis", paddle.take_along_axis,
     {"x": X, "indices": X.argsort(1)}, {"axis": 1},
     [np.take_along_axis(X, X.argsort(1), 1)], None),
    ("diag", paddle.diag, {"x": np.arange(3).astype(np.float32)}, {},
     [np.diag(np.arange(3).astype(np.float32))], None),
    ("tril", paddle.tril, {"x": X}, {}, [np.tril(X)], None),
    ("triu", paddle.triu, {"x": X}, {}, [np.triu(X)], None),
    # linalg extras
    ("norm_fro", paddle.linalg.norm, {"x": X}, {},
     [np.linalg.norm(X)], None),
    ("cross", paddle.cross,
     {"x": np.array([[1., 0, 0]], np.float32),
      "y": np.array([[0., 1, 0]], np.float32)}, {"axis": 1},
     [np.array([[0., 0, 1]], np.float32)], None),
    # functional extras
    ("one_hot", F.one_hot, {"x": np.array([0, 2], np.int64)},
     {"num_classes": 3},
     [np.eye(3, dtype=np.float32)[[0, 2]]], None),
    ("normalize", F.normalize, {"x": X}, {"axis": 1},
     [X / np.linalg.norm(X, axis=1, keepdims=True)], ["x"]),
    ("pad1", F.pad, {"x": X}, {"pad": [1, 1, 0, 0]}, None, None),
    ("cosine_similarity", F.cosine_similarity, {"x1": X, "x2": Y}, {"axis": 1},
     [np.sum(X * Y, 1) / (np.linalg.norm(X, axis=1) *
                          np.linalg.norm(Y, axis=1))], None),
]


_EAGER_ONLY = {"masked_select"}  # dynamic output shape -> host-eager by design


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op(case):
    name, op, inputs, attrs, outputs, grad_inputs = case
    t = OpTest()
    t.op = op
    t.inputs = inputs
    t.attrs = attrs
    t.outputs = outputs
    if outputs is not None:
        t.check_output(atol=1e-4, rtol=1e-4, jit=name not in _EAGER_ONLY)
    if grad_inputs:
        t.check_grad(grad_inputs)


class TestCreationOps:
    """Creation ops have no tensor inputs — direct value checks."""

    def test_creation_family(self):
        np.testing.assert_array_equal(
            np.asarray(paddle.zeros([2, 3])._data), np.zeros((2, 3)))
        np.testing.assert_array_equal(
            np.asarray(paddle.ones([2])._data), np.ones(2))
        np.testing.assert_array_equal(
            np.asarray(paddle.full([2, 2], 7.0)._data), np.full((2, 2), 7.0))
        np.testing.assert_array_equal(
            np.asarray(paddle.arange(5)._data), np.arange(5))
        np.testing.assert_allclose(
            np.asarray(paddle.linspace(0, 1, 5)._data), np.linspace(0, 1, 5))
        np.testing.assert_array_equal(
            np.asarray(paddle.eye(3)._data), np.eye(3))
        x = paddle.to_tensor(X)
        np.testing.assert_array_equal(
            np.asarray(paddle.zeros_like(x)._data), np.zeros_like(X))
        np.testing.assert_array_equal(
            np.asarray(paddle.full_like(x, 2.0)._data), np.full_like(X, 2.0))

    def test_meshgrid_and_tril_indices(self):
        a, b = paddle.meshgrid(paddle.arange(2), paddle.arange(3))
        na, nb = np.meshgrid(np.arange(2), np.arange(3), indexing="ij")
        np.testing.assert_array_equal(np.asarray(a._data), na)
        np.testing.assert_array_equal(np.asarray(b._data), nb)


class TestTopkOp(OpTest):
    def setUp(self):
        self.op = paddle.topk
        self.inputs = {"x": X}
        self.attrs = {"k": 2, "axis": 1}
        idx = np.argsort(-X, 1)[:, :2]
        self.outputs = {"values": np.take_along_axis(X, idx, 1), "indices": idx}

    def test(self):
        self.check_output()


class TestUniqueOp(OpTest):
    def setUp(self):
        x = np.array([3., 1., 2., 1., 3.], np.float32)
        self.op = paddle.unique
        self.inputs = {"x": x}
        self.outputs = [np.array([1., 2., 3.], np.float32)]

    def test(self):
        self.check_output(jit=False)  # dynamic output shape
