"""Multi-process distributed harness (VERDICT r1 #5): spawn REAL processes
via fleetrun with jax.distributed.initialize on the CPU backend and assert
DP loss parity against a single-process run — the TPU-native rebirth of
test_dist_base.py's localhost-NCCL two-trainer comparison
(/root/reference/python/paddle/fluid/tests/unittests/test_dist_base.py:671).
"""
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_fleet(tmp_path, nproc, steps=5, timeout=420,
               script_name="dist_dp_script.py", devices_per_proc=1):
    out = str(tmp_path / f"losses_{script_name}_{nproc}.json")
    script = os.path.join(os.path.dirname(__file__), script_name)
    env = dict(
        os.environ,
        PYTHONPATH=os.getcwd(),
        XLA_FLAGS=f"--xla_force_host_platform_device_count="
                  f"{devices_per_proc}",
        JAX_PLATFORMS="cpu",
    )
    env.pop("PADDLE_TRAINER_ID", None)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.fleet.launch",
           "--nproc_per_node", str(nproc),
           "--start_port", str(_free_port()),
           script, "--out", out, "--steps", str(steps)]
    res = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout, env=env, cwd=os.getcwd())
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    with open(out) as f:
        return json.load(f)


@pytest.mark.slow
class TestMultiProcessDP:
    def test_two_process_dp_matches_single(self, tmp_path):
        """2 real processes (1 CPU device each, jax.distributed over the
        PADDLE_TRAINER_* protocol) must produce the same DP loss trajectory
        as a single process."""
        two = _run_fleet(tmp_path, nproc=2)
        one = _run_fleet(tmp_path, nproc=1)
        assert two["world"] == 2 and one["world"] == 1
        np.testing.assert_allclose(two["losses"], one["losses"],
                                   rtol=1e-4, atol=1e-6)
        # and training actually progressed
        assert two["losses"][-1] < two["losses"][0]


@pytest.mark.slow
class TestFourProcessHybrid:
    """VERDICT r3 #5: 4 processes x 2 CPU devices each — dp ACROSS
    processes x mp WITHIN (the multi-controller topology of a real pod) —
    with a mid-run cross-group checkpoint gather/restore. Loss-parity vs
    one process owning all 8 devices."""

    def test_hybrid_dp_mp_matches_single_process(self, tmp_path):
        multi = _run_fleet(tmp_path, nproc=4,
                           script_name="dist_hybrid_script.py",
                           devices_per_proc=2, timeout=900)
        single = _run_fleet(tmp_path, nproc=1,
                            script_name="dist_hybrid_script.py",
                            devices_per_proc=8, timeout=900)
        assert multi["world"] == 4 and multi["n_devices"] == 8
        assert single["world"] == 1 and single["n_devices"] == 8
        # bit-for-bit same global program; the mid-run state_dict()
        # gather + fresh-trainer restore (step 3) must not perturb it
        np.testing.assert_allclose(multi["losses"], single["losses"],
                                   rtol=1e-4, atol=1e-6)
        assert multi["losses"][-1] < multi["losses"][0]
