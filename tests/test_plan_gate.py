"""Tier-1 gate for the auto-parallelism planner (ISSUE 16).

Pins the planner's acceptance contract on the CPU mesh:

- the search ranks a non-trivial space (gpt: >= 8 valid candidates) and
  every rejection names the analyzer pass that killed the plan;
- for gpt AND bert the top-ranked plan beats the hand-written default
  (max-dp dense) on simulated cost — the cost model must reward the
  int8 gradient codec it prices from measured collective bytes;
- the winning config REALIZES: plan -> emit() -> realize_trainer() ->
  a few real train steps, to loss parity with the default plan's
  trainer (same seed, same data);
- the CLI exit-code contract: 0 with valid plans, 1 when the space is
  empty (one subprocess smoke each);
- one search stays under the recorded wall-second budget
  (tests/plan_budget.json) so graph_lint --plan cannot silently become
  the slow step of the battery.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu import flags as _flags  # noqa: E402
from paddle_tpu.analysis import plan_search  # noqa: E402

BUDGET_PATH = os.path.join(REPO, "tests", "plan_budget.json")
GATE_MODELS = ("gpt", "bert")


@pytest.fixture(scope="module")
def searches():
    """{model: (SearchResult, wall seconds)} — one search per model for
    the whole module (plan_search memoizes program-class traces)."""
    out = {}
    for model in GATE_MODELS:
        t0 = time.perf_counter()
        res = plan_search.search(model)
        out[model] = (res, time.perf_counter() - t0)
    return out


class TestPlanRanking:
    def test_gpt_ranks_at_least_eight_candidates(self, searches):
        res, _ = searches["gpt"]
        assert len(res.ranked) >= 8, [p.describe() for p, _ in res.ranked]

    @pytest.mark.parametrize("model", GATE_MODELS)
    def test_top_pick_beats_the_handwritten_default(self, searches,
                                                    model):
        res, _ = searches[model]
        best_plan, best_score = res.best
        default = plan_search.default_plan(res.profile, 8)
        default_score = next(
            s for p, s in res.ranked
            if p.describe() == default.describe())
        assert best_score["total_s"] < default_score["total_s"], (
            f"{model}: best {best_plan.describe()} "
            f"{best_score['total_s']:.2e}s vs default "
            f"{default.describe()} {default_score['total_s']:.2e}s")

    @pytest.mark.parametrize("model", GATE_MODELS)
    def test_every_rejection_names_an_analyzer_pass(self, searches,
                                                    model):
        res, _ = searches[model]
        assert res.rejected   # the space is not vacuously clean
        known = {"plan-invalid-config", "plan-hbm-over-budget",
                 "plan-handoff-mismatch", "collective-axis-mismatch",
                 "kernel-vmem-over-budget"}
        for plan, errs in res.rejected:
            passes = {e.pass_name for e in errs}
            assert passes and passes <= known, (plan.describe(), passes)

    def test_report_schema_and_totals(self, searches):
        res, _ = searches["gpt"]
        rep = res.to_report()
        d = rep.to_dict()
        assert d["counts"]["error"] == 0
        assert any(f["pass"] == "plan-ranked" for f in d["findings"])

    @pytest.mark.parametrize("model", GATE_MODELS)
    def test_search_under_recorded_budget(self, searches, model):
        with open(BUDGET_PATH, encoding="utf-8") as f:
            budget = json.load(f)["budget_s"]
        _, elapsed = searches[model]
        assert elapsed < budget[model], (
            f"{model} search took {elapsed:.1f}s, budget "
            f"{budget[model]:.0f}s — the plan battery has regressed; "
            "profile before raising tests/plan_budget.json")


class TestPlanRealizes:
    def _train(self, config, steps=5):
        """realize_trainer + `steps` real steps; restores flags AFTER
        training (construction consumes them; mid-life toggles raise)."""
        old = {k: bool(_flags.get_flag(k))
               for k in (config.get("flags") or {})}
        trainer, batch = plan_search.realize_trainer(config)
        try:
            return [float(np.asarray(trainer.train_step(*batch)._data))
                    for _ in range(steps)]
        finally:
            _flags.set_flags(old)

    @pytest.mark.parametrize("model", GATE_MODELS)
    def test_top3_plans_train_to_loss_parity_with_default(self, searches,
                                                          model):
        res, _ = searches[model]
        default = plan_search.default_plan(res.profile, 8)
        ref = self._train(plan_search.emit(default, res.profile))
        assert all(np.isfinite(ref))
        for plan, _score in res.ranked[:3]:
            got = self._train(plan_search.emit(plan, res.profile))
            assert all(np.isfinite(got)), plan.describe()
            # same seed + same data: the int8 gradient codec may
            # perturb the trajectory, but the tier-1 parity band
            # (docs/PERF.md) holds at these shapes
            assert abs(got[-1] - ref[-1]) < 0.1, \
                (plan.describe(), ref, got)
            assert got[-1] < got[0] + 1e-3, plan.describe()


class TestPlanCli:
    CLI = os.path.join(REPO, "tools", "plan_search.py")

    def test_exit_codes(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        ok = subprocess.run(
            [sys.executable, self.CLI, "--model", "bert", "--top", "1"],
            capture_output=True, text=True, env=env, timeout=600)
        assert ok.returncode == 0, ok.stdout + ok.stderr
        assert "plan_bert" in ok.stdout
        # a budget no plan can meet: plan-space-empty -> exit 1 (cheap:
        # the memory check rejects every candidate before any tracing)
        empty = subprocess.run(
            [sys.executable, self.CLI, "--model", "bert",
             "--hbm-gb", "0.0001"],
            capture_output=True, text=True, env=env, timeout=600)
        assert empty.returncode == 1, empty.stdout + empty.stderr
        assert "plan-space-empty" in empty.stdout
