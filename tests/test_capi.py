"""C inference API tests: build the native shim, load a jit-saved model through
the C ABI via ctypes, and compare against the in-process Python predictor."""
import ctypes
import functools
import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

_NATIVE = os.path.join(os.path.dirname(paddle.__file__), "native")
_SRC = os.path.join(_NATIVE, "capi.cc")
_SO = os.path.join(_NATIVE, "libpaddle_tpu_capi.so")


def _build():
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    inc = subprocess.run(["python3-config", "--includes"], check=True,
                         capture_output=True, text=True).stdout.split()
    subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-std=c++17", *inc,
                    "-o", _SO, _SRC], check=True, capture_output=True)
    return _SO


@functools.lru_cache(maxsize=1)
def _jax_export_works():
    """Probe the same path static/io.py's _write_export_artifact takes:
    some jax builds ship a jax.export whose export()/serialize() raises
    (io.py then warns 'jax.export serialization unavailable' and skips
    writing the .pdmodel.jaxexport artifact). Tests that require the
    durable artifact on disk can only run where the environment can
    actually produce one."""
    import jax
    import jax.numpy as jnp

    try:
        exported = jax.export.export(jax.jit(lambda x: x * 2))(
            jax.ShapeDtypeStruct((2,), jnp.float32))
        exported.serialize()
        return True
    except Exception:
        return False


class TestCAPI:
    def test_c_abi_predict_roundtrip(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        net.eval()
        prefix = str(tmp_path / "capi_model")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])

        lib = ctypes.CDLL(_build())
        lib.PD_Init.restype = ctypes.c_int
        lib.PD_CreatePredictor.restype = ctypes.c_void_p
        lib.PD_CreatePredictor.argtypes = [ctypes.c_char_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        lib.PD_PredictorRunFloat.restype = ctypes.c_int64
        lib.PD_PredictorRunFloat.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.PD_DestroyPredictor.argtypes = [ctypes.c_void_p]

        assert lib.PD_Init() == 0
        h = lib.PD_CreatePredictor(prefix.encode())
        assert h, lib.PD_GetLastError().decode()

        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        shape = (ctypes.c_int64 * 2)(2, 4)
        out_buf = (ctypes.c_float * 64)()
        out_shape = (ctypes.c_int64 * 8)()
        out_ndim = ctypes.c_int(0)
        n = lib.PD_PredictorRunFloat(
            h, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape, 2,
            out_buf, 64, out_shape, 8, ctypes.byref(out_ndim))
        assert n == 6, lib.PD_GetLastError().decode()
        assert list(out_shape[:out_ndim.value]) == [2, 3]

        got = np.array(out_buf[:6], np.float32).reshape(2, 3)
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(got, ref, atol=1e-5)
        lib.PD_DestroyPredictor(h)

    def test_c_abi_error_reporting(self):
        lib = ctypes.CDLL(_build())
        lib.PD_CreatePredictor.restype = ctypes.c_void_p
        lib.PD_CreatePredictor.argtypes = [ctypes.c_char_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        h = lib.PD_CreatePredictor(b"/nonexistent/model")
        assert not h
        assert b"load" in lib.PD_GetLastError()


class TestCAPITraining:
    """PD_CreateTrainer / PD_TrainStepFloat / PD_GetLoss / PD_TrainerSave
    (reference paddle/fluid/train/demo/demo_trainer.cc): real training from
    the C ABI, params device-side between calls."""

    def _lib(self):
        lib = ctypes.CDLL(_build())
        lib.PD_Init.restype = ctypes.c_int
        lib.PD_CreateTrainer.restype = ctypes.c_void_p
        lib.PD_CreateTrainer.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_double,
            ctypes.c_char_p]
        lib.PD_TrainStepFloat.restype = ctypes.c_int
        lib.PD_TrainStepFloat.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_int]
        lib.PD_GetLoss.restype = ctypes.c_double
        lib.PD_GetLoss.argtypes = [ctypes.c_void_p]
        lib.PD_TrainerSave.restype = ctypes.c_int
        lib.PD_TrainerSave.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.PD_DestroyTrainer.argtypes = [ctypes.c_void_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        return lib

    def test_train_loss_falls_and_save_serves(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Flatten(), nn.Linear(16, 32), nn.ReLU(),
                            nn.Linear(32, 4))
        # no input_spec: keep the PICKLED-layer artifact authoritative so
        # PD_TrainerSave's updated .pdiparams is what jit.load serves
        prefix = str(tmp_path / "train_model")
        paddle.jit.save(net, prefix)

        lib = self._lib()
        assert lib.PD_Init() == 0
        h = lib.PD_CreateTrainer(prefix.encode(), b"adam", 1e-2,
                                 b"cross_entropy")
        assert h, lib.PD_GetLastError().decode()

        rng = np.random.RandomState(0)
        x = rng.randn(8, 4, 4).astype(np.float32)
        y = rng.randint(0, 4, (8,)).astype(np.int64)
        xs = (ctypes.c_int64 * 3)(8, 4, 4)
        ys = (ctypes.c_int64 * 1)(8)
        losses = []
        for _ in range(30):
            rc = lib.PD_TrainStepFloat(
                h, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), xs, 3,
                y.ctypes.data_as(ctypes.c_void_p), ys, 1, 0)
            assert rc == 0, lib.PD_GetLastError().decode()
            losses.append(lib.PD_GetLoss(h))
        assert losses[-1] < losses[0] * 0.5, losses[:3] + losses[-3:]

        assert lib.PD_TrainerSave(h, prefix.encode()) == 0, \
            lib.PD_GetLastError().decode()
        lib.PD_DestroyTrainer(h)
        # trained params serve through jit.load (same artifact family)
        served = paddle.jit.load(prefix)
        out = np.asarray(served(paddle.to_tensor(x))._data)
        acc = (out.argmax(-1) == y).mean()
        assert acc >= 0.75, acc   # memorized the batch

    @pytest.mark.skipif(
        not _jax_export_works(),
        reason="this jax build's jax.export.export/serialize raises — "
               "static/io.py falls back to StableHLO text + params "
               "('jax.export serialization unavailable') and never "
               "writes the .pdmodel.jaxexport durable artifact this "
               "test shadows")
    def test_save_over_durable_artifact_serves_trained_params(self,
                                                              tmp_path):
        # jit.save WITH input_spec writes the durable jax.export artifact;
        # PD_TrainerSave must not let it shadow the trained weights
        paddle.seed(0)
        net = nn.Sequential(nn.Flatten(), nn.Linear(16, 4))
        prefix = str(tmp_path / "durable")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([4, 4, 4],
                                                         "float32")])
        assert os.path.exists(prefix + ".pdmodel.jaxexport")

        lib = self._lib()
        assert lib.PD_Init() == 0
        h = lib.PD_CreateTrainer(prefix.encode(), b"adam", 1e-2,
                                 b"cross_entropy")
        assert h, lib.PD_GetLastError().decode()
        rng = np.random.RandomState(0)
        x = rng.randn(4, 4, 4).astype(np.float32)
        y = np.arange(4).astype(np.int64)
        xs = (ctypes.c_int64 * 3)(4, 4, 4)
        ys = (ctypes.c_int64 * 1)(4)
        for _ in range(25):
            assert lib.PD_TrainStepFloat(
                h, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), xs, 3,
                y.ctypes.data_as(ctypes.c_void_p), ys, 1, 0) == 0
        assert lib.PD_TrainerSave(h, prefix.encode()) == 0
        lib.PD_DestroyTrainer(h)

        served = paddle.jit.load(prefix)
        out = np.asarray(served(paddle.to_tensor(x))._data)
        assert (out.argmax(-1) == y).mean() >= 0.75

    def test_trainer_error_paths(self, tmp_path):
        lib = self._lib()
        assert lib.PD_Init() == 0
        assert not lib.PD_CreateTrainer(b"/nonexistent/m", b"adam", 1e-3,
                                        b"cross_entropy")
        paddle.seed(0)
        prefix = str(tmp_path / "m")
        paddle.jit.save(nn.Linear(4, 2), prefix)
        assert not lib.PD_CreateTrainer(prefix.encode(), b"nope", 1e-3,
                                        b"cross_entropy")
        assert b"optimizer" in lib.PD_GetLastError()
        h = lib.PD_CreateTrainer(prefix.encode(), b"sgd", 1e-3, b"mse")
        assert h, lib.PD_GetLastError().decode()
        bad_shape = (ctypes.c_int64 * 1)(-3)
        rc = lib.PD_TrainStepFloat(h, None, bad_shape, 1, None, bad_shape,
                                   1, 1)
        assert rc == -1
        lib.PD_DestroyTrainer(h)


class TestStandaloneCHost:
    """A REAL C host binary (gcc + libpython embed) drives the C ABI from a
    non-Python process — exercising PD_Init's GIL release (ADVICE r1 medium:
    PyEval_SaveThread) and a worker-thread call path like the Go client's
    goroutine migration."""

    C_SRC = r'''
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <pthread.h>

extern int PD_Init(void);
extern void* PD_CreatePredictor(const char*);
extern long long PD_PredictorRunFloat(void*, const float*, const long long*,
                                      int, float*, long long,
                                      long long*, int, int*);
extern void PD_DestroyPredictor(void*);
extern const char* PD_GetLastError(void);

static const char* g_prefix;
static int g_ok = 0;

static void* worker(void* arg) {
    /* a DIFFERENT OS thread than the one that ran PD_Init: deadlocks
       unless PD_Init released the GIL */
    void* p = PD_CreatePredictor(g_prefix);
    if (!p) { fprintf(stderr, "create: %s\n", PD_GetLastError()); return 0; }
    float in[8]; long long shape[2] = {2, 4};
    for (int i = 0; i < 8; ++i) in[i] = 1.0f;
    float out[64]; long long out_shape[8]; int out_ndim = 0;
    long long n = PD_PredictorRunFloat(p, in, shape, 2, out, 64,
                                       out_shape, 8, &out_ndim);
    if (n <= 0) { fprintf(stderr, "run: %s\n", PD_GetLastError()); return 0; }
    PD_DestroyPredictor(p);
    g_ok = 1;
    printf("C_HOST_OK n=%lld first=%f\n", n, out[0]);
    return 0;
}

int main(int argc, char** argv) {
    g_prefix = argv[1];
    if (PD_Init() != 0) { fprintf(stderr, "init failed\n"); return 1; }
    pthread_t t;
    pthread_create(&t, 0, worker, 0);
    pthread_join(t, 0);
    return g_ok ? 0 : 2;
}
'''

    TRAIN_C_SRC = r'''
#include <stdio.h>
#include <stdlib.h>

extern int PD_Init(void);
extern void* PD_CreateTrainer(const char*, const char*, double, const char*);
extern int PD_TrainStepFloat(void*, const float*, const long long*, int,
                             const void*, const long long*, int, int);
extern double PD_GetLoss(void*);
extern int PD_TrainerSave(void*, const char*);
extern void PD_DestroyTrainer(void*);
extern const char* PD_GetLastError(void);

/* deterministic LCG: the whole dataset is authored in C — no Python-side
   data path involved */
static unsigned long long lcg_state = 42;
static float lcg_uniform(void) {
    lcg_state = lcg_state * 6364136223846793005ULL + 1442695040888963407ULL;
    return (float)((lcg_state >> 33) & 0xFFFFFF) / (float)0xFFFFFF;
}

int main(int argc, char** argv) {
    const char* prefix = argv[1];
    if (PD_Init() != 0) { fprintf(stderr, "init failed\n"); return 1; }
    void* t = PD_CreateTrainer(prefix, "adam", 1e-2, "cross_entropy");
    if (!t) { fprintf(stderr, "create: %s\n", PD_GetLastError()); return 1; }

    enum { B = 8, C = 1, H = 28, W = 28, STEPS = 50 };
    static float x[B * C * H * W];
    static long long y[B];
    long long xs[4] = {B, C, H, W};
    long long ys[1] = {B};
    for (int i = 0; i < B * C * H * W; ++i) x[i] = lcg_uniform();
    for (int i = 0; i < B; ++i) y[i] = (long long)(lcg_uniform() * 10) % 10;

    double first = 0, last = 0;
    for (int s = 0; s < STEPS; ++s) {
        if (PD_TrainStepFloat(t, x, xs, 4, y, ys, 1, 0) != 0) {
            fprintf(stderr, "step %d: %s\n", s, PD_GetLastError());
            return 1;
        }
        last = PD_GetLoss(t);
        if (s == 0) first = last;
    }
    if (PD_TrainerSave(t, prefix) != 0) {
        fprintf(stderr, "save: %s\n", PD_GetLastError());
        return 1;
    }
    PD_DestroyTrainer(t);
    printf("C_TRAIN_OK first=%f last=%f\n", first, last);
    return (last < first * 0.5) ? 0 : 2;
}
'''

    def _compile_host(self, tmp_path, src_text, name):
        so = _build()
        csrc = str(tmp_path / f"{name}.c")
        with open(csrc, "w") as f:
            f.write(src_text)
        exe = str(tmp_path / name)
        # embed the SAME interpreter that runs pytest (a PATH python3-config
        # could belong to a different python whose site-packages lack jax)
        import sysconfig

        ver = sysconfig.get_config_var("VERSION")
        libdir = sysconfig.get_config_var("LIBDIR")
        ldflags = [f"-L{libdir}", f"-lpython{ver}", "-ldl", "-lm"]
        subprocess.run(
            ["gcc", "-O1", csrc, "-o", exe, so, *ldflags, "-lpthread",
             f"-Wl,-rpath,{os.path.dirname(so)}", f"-Wl,-rpath,{libdir}"],
            check=True, capture_output=True, text=True)
        return exe

    def _host_env(self):
        # the embedded interpreter runs no conftest: PADDLE_TPU_FORCE_CPU
        # makes the package itself pin the CPU backend at import
        repo_root = os.path.dirname(os.path.dirname(paddle.__file__))
        pythonpath = repo_root + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else "")
        return dict(os.environ, JAX_PLATFORMS="cpu",
                    PADDLE_TPU_FORCE_CPU="1", PYTHONPATH=pythonpath)

    def test_c_host_trains_lenet(self, tmp_path):
        """The reference's standalone native trainer, TPU-shaped: a pure C
        binary loads a jit.save'd LeNet, runs 50 real train steps (jitted
        fwd+bwd+Adam, params device-side), and the loss falls."""
        from paddle_tpu.vision.models import LeNet

        paddle.seed(0)
        prefix = str(tmp_path / "lenet_train")
        paddle.jit.save(LeNet(), prefix)   # pickled-layer artifact

        exe = self._compile_host(tmp_path, self.TRAIN_C_SRC, "train_host")
        res = subprocess.run([exe, prefix], capture_output=True, text=True,
                             timeout=600, env=self._host_env())
        assert res.returncode == 0, (res.stdout, res.stderr[-1500:])
        assert "C_TRAIN_OK" in res.stdout, res.stdout
        # the C-trained params landed in the artifact and serve in-process
        served = paddle.jit.load(prefix)
        out = served(paddle.to_tensor(
            np.zeros((1, 1, 28, 28), np.float32)))
        assert tuple(out.shape) == (1, 10)

    def test_c_host_binary(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        net.eval()
        prefix = str(tmp_path / "chost_model")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])

        exe = self._compile_host(tmp_path, self.C_SRC, "host")
        res = subprocess.run([exe, prefix], capture_output=True, text=True,
                             timeout=300, env=self._host_env())
        assert res.returncode == 0, (res.stdout, res.stderr[-1500:])
        assert "C_HOST_OK" in res.stdout, res.stdout
