"""C inference API tests: build the native shim, load a jit-saved model through
the C ABI via ctypes, and compare against the in-process Python predictor."""
import ctypes
import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

_NATIVE = os.path.join(os.path.dirname(paddle.__file__), "native")
_SRC = os.path.join(_NATIVE, "capi.cc")
_SO = os.path.join(_NATIVE, "libpaddle_tpu_capi.so")


def _build():
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    inc = subprocess.run(["python3-config", "--includes"], check=True,
                         capture_output=True, text=True).stdout.split()
    subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-std=c++17", *inc,
                    "-o", _SO, _SRC], check=True, capture_output=True)
    return _SO


class TestCAPI:
    def test_c_abi_predict_roundtrip(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        net.eval()
        prefix = str(tmp_path / "capi_model")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])

        lib = ctypes.CDLL(_build())
        lib.PD_Init.restype = ctypes.c_int
        lib.PD_CreatePredictor.restype = ctypes.c_void_p
        lib.PD_CreatePredictor.argtypes = [ctypes.c_char_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        lib.PD_PredictorRunFloat.restype = ctypes.c_int64
        lib.PD_PredictorRunFloat.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.PD_DestroyPredictor.argtypes = [ctypes.c_void_p]

        assert lib.PD_Init() == 0
        h = lib.PD_CreatePredictor(prefix.encode())
        assert h, lib.PD_GetLastError().decode()

        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        shape = (ctypes.c_int64 * 2)(2, 4)
        out_buf = (ctypes.c_float * 64)()
        out_shape = (ctypes.c_int64 * 8)()
        out_ndim = ctypes.c_int(0)
        n = lib.PD_PredictorRunFloat(
            h, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape, 2,
            out_buf, 64, out_shape, 8, ctypes.byref(out_ndim))
        assert n == 6, lib.PD_GetLastError().decode()
        assert list(out_shape[:out_ndim.value]) == [2, 3]

        got = np.array(out_buf[:6], np.float32).reshape(2, 3)
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(got, ref, atol=1e-5)
        lib.PD_DestroyPredictor(h)

    def test_c_abi_error_reporting(self):
        lib = ctypes.CDLL(_build())
        lib.PD_CreatePredictor.restype = ctypes.c_void_p
        lib.PD_CreatePredictor.argtypes = [ctypes.c_char_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        h = lib.PD_CreatePredictor(b"/nonexistent/model")
        assert not h
        assert b"load" in lib.PD_GetLastError()
