"""C inference API tests: build the native shim, load a jit-saved model through
the C ABI via ctypes, and compare against the in-process Python predictor."""
import ctypes
import os
import subprocess

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

_NATIVE = os.path.join(os.path.dirname(paddle.__file__), "native")
_SRC = os.path.join(_NATIVE, "capi.cc")
_SO = os.path.join(_NATIVE, "libpaddle_tpu_capi.so")


def _build():
    if (os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    inc = subprocess.run(["python3-config", "--includes"], check=True,
                         capture_output=True, text=True).stdout.split()
    subprocess.run(["g++", "-O2", "-fPIC", "-shared", "-std=c++17", *inc,
                    "-o", _SO, _SRC], check=True, capture_output=True)
    return _SO


class TestCAPI:
    def test_c_abi_predict_roundtrip(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        net.eval()
        prefix = str(tmp_path / "capi_model")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])

        lib = ctypes.CDLL(_build())
        lib.PD_Init.restype = ctypes.c_int
        lib.PD_CreatePredictor.restype = ctypes.c_void_p
        lib.PD_CreatePredictor.argtypes = [ctypes.c_char_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        lib.PD_PredictorRunFloat.restype = ctypes.c_int64
        lib.PD_PredictorRunFloat.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib.PD_DestroyPredictor.argtypes = [ctypes.c_void_p]

        assert lib.PD_Init() == 0
        h = lib.PD_CreatePredictor(prefix.encode())
        assert h, lib.PD_GetLastError().decode()

        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        shape = (ctypes.c_int64 * 2)(2, 4)
        out_buf = (ctypes.c_float * 64)()
        out_shape = (ctypes.c_int64 * 8)()
        out_ndim = ctypes.c_int(0)
        n = lib.PD_PredictorRunFloat(
            h, x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), shape, 2,
            out_buf, 64, out_shape, 8, ctypes.byref(out_ndim))
        assert n == 6, lib.PD_GetLastError().decode()
        assert list(out_shape[:out_ndim.value]) == [2, 3]

        got = np.array(out_buf[:6], np.float32).reshape(2, 3)
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(got, ref, atol=1e-5)
        lib.PD_DestroyPredictor(h)

    def test_c_abi_error_reporting(self):
        lib = ctypes.CDLL(_build())
        lib.PD_CreatePredictor.restype = ctypes.c_void_p
        lib.PD_CreatePredictor.argtypes = [ctypes.c_char_p]
        lib.PD_GetLastError.restype = ctypes.c_char_p
        h = lib.PD_CreatePredictor(b"/nonexistent/model")
        assert not h
        assert b"load" in lib.PD_GetLastError()


class TestStandaloneCHost:
    """A REAL C host binary (gcc + libpython embed) drives the C ABI from a
    non-Python process — exercising PD_Init's GIL release (ADVICE r1 medium:
    PyEval_SaveThread) and a worker-thread call path like the Go client's
    goroutine migration."""

    C_SRC = r'''
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <pthread.h>

extern int PD_Init(void);
extern void* PD_CreatePredictor(const char*);
extern long long PD_PredictorRunFloat(void*, const float*, const long long*,
                                      int, float*, long long,
                                      long long*, int, int*);
extern void PD_DestroyPredictor(void*);
extern const char* PD_GetLastError(void);

static const char* g_prefix;
static int g_ok = 0;

static void* worker(void* arg) {
    /* a DIFFERENT OS thread than the one that ran PD_Init: deadlocks
       unless PD_Init released the GIL */
    void* p = PD_CreatePredictor(g_prefix);
    if (!p) { fprintf(stderr, "create: %s\n", PD_GetLastError()); return 0; }
    float in[8]; long long shape[2] = {2, 4};
    for (int i = 0; i < 8; ++i) in[i] = 1.0f;
    float out[64]; long long out_shape[8]; int out_ndim = 0;
    long long n = PD_PredictorRunFloat(p, in, shape, 2, out, 64,
                                       out_shape, 8, &out_ndim);
    if (n <= 0) { fprintf(stderr, "run: %s\n", PD_GetLastError()); return 0; }
    PD_DestroyPredictor(p);
    g_ok = 1;
    printf("C_HOST_OK n=%lld first=%f\n", n, out[0]);
    return 0;
}

int main(int argc, char** argv) {
    g_prefix = argv[1];
    if (PD_Init() != 0) { fprintf(stderr, "init failed\n"); return 1; }
    pthread_t t;
    pthread_create(&t, 0, worker, 0);
    pthread_join(t, 0);
    return g_ok ? 0 : 2;
}
'''

    def test_c_host_binary(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
        net.eval()
        prefix = str(tmp_path / "chost_model")
        paddle.jit.save(net, prefix,
                        input_spec=[paddle.jit.InputSpec([2, 4], "float32")])

        so = _build()
        csrc = str(tmp_path / "host.c")
        with open(csrc, "w") as f:
            f.write(self.C_SRC)
        exe = str(tmp_path / "host")
        # embed the SAME interpreter that runs pytest (a PATH python3-config
        # could belong to a different python whose site-packages lack jax)
        import sysconfig

        ver = sysconfig.get_config_var("VERSION")
        libdir = sysconfig.get_config_var("LIBDIR")
        ldflags = [f"-L{libdir}", f"-lpython{ver}", "-ldl", "-lm"]
        subprocess.run(
            ["gcc", "-O1", csrc, "-o", exe, so, *ldflags, "-lpthread",
             f"-Wl,-rpath,{os.path.dirname(so)}", f"-Wl,-rpath,{libdir}"],
            check=True, capture_output=True, text=True)
        repo_root = os.path.dirname(os.path.dirname(paddle.__file__))
        pythonpath = repo_root + (
            os.pathsep + os.environ["PYTHONPATH"]
            if os.environ.get("PYTHONPATH") else "")
        # the embedded interpreter runs no conftest: PADDLE_TPU_FORCE_CPU
        # makes the package itself pin the CPU backend at import
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PADDLE_TPU_FORCE_CPU="1", PYTHONPATH=pythonpath)
        res = subprocess.run([exe, prefix], capture_output=True, text=True,
                             timeout=300, env=env)
        assert res.returncode == 0, (res.stdout, res.stderr[-1500:])
        assert "C_HOST_OK" in res.stdout, res.stdout
