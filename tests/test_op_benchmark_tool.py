"""Op-benchmark harness tests (reference op_tester.cc + CI gate parity)."""
import json
import subprocess
import sys
import os


def test_run_and_compare(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tool = os.path.join(repo, "tools", "op_benchmark.py")
    base = str(tmp_path / "base.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    res = subprocess.run([sys.executable, tool, "run", "--cpu",
                          "--out", base, "--repeat", "2"],
                         capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-1500:]
    prof = json.load(open(base))
    assert len(prof["ops"]) >= 10
    assert all(v["mean_us"] > 0 for v in prof["ops"].values())

    # identical profiles: gate passes
    res = subprocess.run([sys.executable, tool, "compare", base, base],
                         capture_output=True, text=True, timeout=60, env=env)
    assert res.returncode == 0 and '"OK"' in res.stdout

    # manufactured regression: gate fails naming the op
    slow = dict(prof)
    slow["ops"] = {k: dict(v) for k, v in prof["ops"].items()}
    slow["ops"]["matmul_1024"]["mean_us"] *= 2
    newp = str(tmp_path / "new.json")
    json.dump(slow, open(newp, "w"))
    res = subprocess.run([sys.executable, tool, "compare", base, newp],
                         capture_output=True, text=True, timeout=60, env=env)
    assert res.returncode == 1 and "matmul_1024" in res.stdout


def test_tape_leak_warning():
    """VERDICT r1 weak #10: unbounded forward-only taping warns."""
    import warnings
    import paddle_tpu as paddle
    from paddle_tpu.core import tape as tape_mod

    t = tape_mod.global_tape()
    t.clear()
    old = tape_mod._LEAK_WARN_THRESHOLD
    tape_mod._LEAK_WARN_THRESHOLD = 50
    try:
        x = paddle.to_tensor([1.0])
        x.stop_gradient = False
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            y = x
            for _ in range(60):
                y = y * 1.0
        assert any("tape holds" in str(r.message) for r in rec)
    finally:
        tape_mod._LEAK_WARN_THRESHOLD = old
        t.clear()
