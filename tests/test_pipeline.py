"""Pipeline-parallel TRAINING tests (VERDICT r1 #1).

Reference parity: the reference trains through PipelineOptimizer +
SectionWorker's 1F1B micro-batch schedule (framework/section_worker.cc:98-141);
its tests assert loss equivalence of pipelined vs plain programs. Here: a GPT
stack trained on an 8-virtual-device pp=4 x dp=2 mesh must match the
non-pipelined loss trajectory step for step.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.pipeline import PipelineTrainer
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.models import GPTConfig, GPTForCausalLM

import jax


def _tiny_model(seed=0):
    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=4, num_heads=4,
                    max_seq_len=64, dropout=0.0)
    return GPTForCausalLM(cfg)


class _SeqWrapper(nn.Layer):
    """Sequential composition of the same pre/stages/post pieces — the
    non-pipelined ground truth sharing identical parameter tensors."""

    def __init__(self, pre, stages, post):
        super().__init__()
        self.pre = pre
        self.stages = nn.LayerList(stages)
        self.post = post

    def forward(self, x, labels):
        h = self.pre(x)
        for s in self.stages:
            h = s(h)
        return self.post(h, labels)


def _batch(rng, b=8, s=32, vocab=512):
    x = rng.randint(0, vocab, (b, s)).astype(np.int32)
    y = rng.randint(0, vocab, (b, s)).astype(np.int32)
    return x, y


def test_pipeline_training_matches_sequential():
    """pp=4 x dp=2 pipelined training == non-pipelined, step for step."""
    n = len(jax.devices())
    if n < 8:
        pytest.skip("needs 8 devices")
    mesh = build_mesh((4, 2), ("pp", "dp"))

    model = _tiny_model()
    pre, stages, post = model.pipeline_split(4)

    # pipelined trainer
    opt_pp = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    pp_trainer = PipelineTrainer(pre, stages, post, opt_pp, mesh=mesh,
                                 n_micro=4, schedule_mode="F-then-B")

    # sequential ground truth (same parameter tensors -> identical init)
    ref = _SeqWrapper(pre, stages, post)
    opt_ref = optimizer.AdamW(learning_rate=1e-3, parameters=ref.parameters())
    ref_mesh = build_mesh((8,), ("dp",))
    ref_trainer = SpmdTrainer(ref, opt_ref, loss_fn=None, mesh=ref_mesh)

    rng = np.random.RandomState(0)
    losses_pp, losses_ref = [], []
    for _ in range(4):
        x, y = _batch(rng)
        losses_pp.append(float(pp_trainer.train_step(x, y)._data))
        losses_ref.append(float(ref_trainer.train_step(x, y)._data))
    np.testing.assert_allclose(losses_pp, losses_ref, rtol=2e-4, atol=2e-5)
    # and the trajectory actually went somewhere
    assert losses_pp[-1] < losses_pp[0]


def test_pipeline_1f1b_remat_changes_program():
    """schedule_mode='1F1B' must change the compiled program (per-tick remat),
    not just set a dead flag — HLO/jaxpr-level assertion (VERDICT r1 #2 style)."""
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs 4 devices")
    mesh = build_mesh((4, 2), ("pp", "dp")) if n >= 8 else build_mesh((4,), ("pp",))

    texts = {}
    for mode in ("F-then-B", "1F1B"):
        model = _tiny_model()
        pre, stages, post = model.pipeline_split(4)
        opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
        tr = PipelineTrainer(pre, stages, post, opt, mesh=mesh, n_micro=4,
                             schedule_mode=mode)
        import jax.numpy as jnp

        def probe(flat, x_micro, y_micro):
            t = {"pre": {}, "stage": {}, "post": {}}
            for k, v in flat.items():
                g, name = k.split("::", 1)
                t[g][name] = v
            from paddle_tpu.distributed.pipeline import _pure_call

            h = jax.vmap(lambda xi: _pure_call(tr.pre, t["pre"], xi))(x_micro)
            outs = tr._pipelined(t["stage"], h)
            losses = jax.vmap(
                lambda oi, yi: _pure_call(tr.post_loss, t["post"], oi, yi))(outs, y_micro)
            return jnp.mean(losses)

        rng = np.random.RandomState(0)
        x, y = _batch(rng)
        xm = x.reshape(4, 2, 32)
        ym = y.reshape(4, 2, 32)
        with mesh:
            jaxpr = jax.make_jaxpr(jax.grad(probe))(tr.params, xm, ym)
        texts[mode] = str(jaxpr)
    assert "remat" in texts["1F1B"]
    assert "remat" not in texts["F-then-B"]


def test_pipeline_via_fleet_strategy():
    """fleet.build_trainer consumes pp_degree/schedule_mode -> PipelineTrainer."""
    n = len(jax.devices())
    if n < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed.fleet import DistributedStrategy, fleet

    strategy = DistributedStrategy()
    strategy.pipeline = True
    strategy.pipeline_configs.pp_degree = 4
    strategy.pipeline_configs.accumulate_steps = 4
    strategy.hybrid_configs.pp_degree = 4
    strategy.hybrid_configs.dp_degree = 2
    fleet.init(is_collective=True, strategy=strategy)

    model = _tiny_model()
    opt = optimizer.AdamW(learning_rate=1e-3, parameters=model.parameters())
    trainer = fleet.build_trainer(model, opt)
    assert isinstance(trainer, PipelineTrainer)

    rng = np.random.RandomState(0)
    x, y = _batch(rng)
    l0 = float(trainer.train_step(x, y)._data)
    l1 = float(trainer.train_step(x, y)._data)
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0  # same batch twice -> loss must drop


def test_pipeline_respects_trainable_flag():
    """Frozen params (trainable=False) must not move under pipelined training."""
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs 4 devices")
    mesh = build_mesh((4,), ("pp",), devices=jax.devices()[:4])
    model = _tiny_model()
    pre, stages, post = model.pipeline_split(4)
    wte = dict(pre.named_parameters())["wte.weight"]
    wte.trainable = False
    before = np.asarray(wte._data).copy()
    opt = optimizer.SGD(learning_rate=1e-1, parameters=model.parameters())
    tr = PipelineTrainer(pre, stages, post, opt, mesh=mesh, n_micro=4)
    rng = np.random.RandomState(0)
    x, y = _batch(rng)
    tr.train_step(x, y)
    tr.sync_to_layer()
    np.testing.assert_array_equal(np.asarray(wte._data), before)
    assert "pre::wte.weight" not in tr.params
    assert "pre::wte.weight" in tr.frozen


def test_pipeline_sync_to_layer_roundtrip():
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs 4 devices")
    mesh = build_mesh((4,), ("pp",), devices=jax.devices()[:4])
    model = _tiny_model()
    pre, stages, post = model.pipeline_split(4)
    opt = optimizer.SGD(learning_rate=1e-2, parameters=model.parameters())
    tr = PipelineTrainer(pre, stages, post, opt, mesh=mesh, n_micro=4,
                         dp_axis="dp")
    rng = np.random.RandomState(0)
    x, y = _batch(rng)
    tr.train_step(x, y)
    tr.sync_to_layer()
    # stage params written back must equal the trainer's stacked copies
    stacked = tr.params["stage::blocks.0.ln1.weight"]
    host = np.asarray(jax.device_get(stacked))
    for i, s in enumerate(stages):
        got = np.asarray(dict(s.named_parameters())["blocks.0.ln1.weight"]._data)
        np.testing.assert_allclose(got, host[i], rtol=1e-6)


def test_1f1b_peak_memory_below_gpipe():
    """VERDICT r2 #5: the 1F1B remat schedule exists to bound live memory —
    XLA's own memory analysis must show its transient working set well under
    GPipe's O(n_ticks) residual retention for the SAME model/config."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "pipeline_memory", os.path.join(repo, "tools", "pipeline_memory.py"))
    pm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pm)

    import jax

    devices = jax.devices()[:4]
    # small vocab so stage-block residuals (what the schedule bounds), not
    # the replicated embedding/head, dominate the transient working set
    gpipe = pm.measure("F-then-B", 4, 4, 256, 256, 8, devices, vocab=512)
    f1b = pm.measure("1F1B", 4, 4, 256, 256, 8, devices, vocab=512)
    assert f1b["temp_bytes"] < 0.5 * gpipe["temp_bytes"], (
        f1b["temp_bytes"], gpipe["temp_bytes"])
