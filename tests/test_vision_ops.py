"""Detection op tests vs numpy references (operators/detection/ op-test pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def ref_nms(boxes, scores, thresh):
    order = np.argsort(-scores)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(boxes[i, 0], boxes[order[1:], 0])
        yy1 = np.maximum(boxes[i, 1], boxes[order[1:], 1])
        xx2 = np.minimum(boxes[i, 2], boxes[order[1:], 2])
        yy2 = np.minimum(boxes[i, 3], boxes[order[1:], 3])
        w = np.maximum(0.0, xx2 - xx1)
        h = np.maximum(0.0, yy2 - yy1)
        inter = w * h
        a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        a_o = (boxes[order[1:], 2] - boxes[order[1:], 0]) * (boxes[order[1:], 3] - boxes[order[1:], 1])
        iou = inter / (a_i + a_o - inter + 1e-9)
        order = order[1:][iou <= thresh]
    return sorted(keep)


class TestNMS:
    def test_matches_reference(self):
        rng = np.random.RandomState(0)
        xy = rng.rand(50, 2) * 100
        wh = rng.rand(50, 2) * 30 + 5
        boxes = np.concatenate([xy, xy + wh], axis=1).astype(np.float32)
        scores = rng.rand(50).astype(np.float32)
        kept = vops.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores))
        expect = ref_nms(boxes, scores, 0.5)
        assert sorted(kept.numpy().tolist()) == expect

    def test_categories(self):
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [0, 0, 10, 10]], np.float32)
        scores = np.array([0.9, 0.8, 0.7], np.float32)
        cats = np.array([0, 0, 1])
        kept = vops.nms(paddle.to_tensor(boxes), 0.5, paddle.to_tensor(scores),
                        paddle.to_tensor(cats), categories=[0, 1])
        # box 1 suppressed by box 0 (same class); box 2 kept (different class)
        assert sorted(kept.numpy().tolist()) == [0, 2]

    def test_multiclass_nms_shapes(self):
        rng = np.random.RandomState(1)
        bboxes = rng.rand(2, 20, 4).astype(np.float32) * 50
        bboxes[..., 2:] += bboxes[..., :2]
        scores = rng.rand(2, 3, 20).astype(np.float32)
        out, valid = vops.multiclass_nms(paddle.to_tensor(bboxes), paddle.to_tensor(scores),
                                         keep_top_k=10, background_label=-1)
        assert out.shape == [2, 10, 6]
        assert (valid.numpy() >= 0).all()


class TestYoloBox:
    def test_shapes_and_ranges(self):
        N, an, C, H, W = 2, 3, 5, 4, 4
        x = np.random.RandomState(0).randn(N, an * (5 + C), H, W).astype(np.float32)
        img = np.array([[320, 320], [416, 416]], np.int32)
        boxes, scores = vops.yolo_box(paddle.to_tensor(x), paddle.to_tensor(img),
                                      anchors=[10, 13, 16, 30, 33, 23], class_num=C)
        assert boxes.shape == [N, an * H * W, 4]
        assert scores.shape == [N, an * H * W, C]
        b = boxes.numpy()
        assert (b[0, :, 0] <= 320).all() and (b[0] >= 0).all()


class TestRoiAlign:
    def test_constant_map(self):
        feat = np.full((1, 2, 8, 8), 3.0, np.float32)
        rois = np.array([[0, 0, 4, 4], [2, 2, 6, 6]], np.float32)
        out = vops.roi_align(paddle.to_tensor(feat), paddle.to_tensor(rois),
                             paddle.to_tensor(np.array([2])), output_size=2, aligned=True)
        assert out.shape == [2, 2, 2, 2]
        np.testing.assert_allclose(out.numpy(), np.full((2, 2, 2, 2), 3.0), rtol=1e-5)


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(0)
        priors = rng.rand(10, 4).astype(np.float32)
        priors[:, 2:] = priors[:, :2] + rng.rand(10, 2).astype(np.float32) + 0.2
        var = np.full((10, 4), 0.1, np.float32)
        targets = priors + 0.05
        enc = vops.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                             paddle.to_tensor(targets), code_type="encode_center_size")
        dec = vops.box_coder(paddle.to_tensor(priors), paddle.to_tensor(var),
                             enc, code_type="decode_center_size")
        np.testing.assert_allclose(dec.numpy(), targets, atol=1e-4)


class TestPriorBox:
    def test_shapes(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 4, 4), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
        boxes, var = vops.prior_box(feat, img, min_sizes=[16.0], aspect_ratios=[1.0, 2.0], flip=True)
        assert boxes.shape[0] == 4 and boxes.shape[1] == 4
        assert boxes.shape == var.shape
