"""hapi summary/flops full parity (reference hapi/model_summary.py —
hook-driven per-layer shapes, trainable split, memory footer — and
hapi/dynamic_flops.py per-layer FLOPs)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.hapi.model_summary import summary_string


@pytest.fixture(autouse=True)
def _seed():
    paddle.seed(0)


def _lenet():
    from paddle_tpu.vision.models import LeNet

    return LeNet()


class TestSummaryTable:
    def test_lenet_per_layer_shapes(self, capsys):
        info = paddle.summary(_lenet(), (1, 1, 28, 28))
        out = capsys.readouterr().out
        # column-for-column comparable to the reference table
        assert "Layer (type)" in out and "Input Shape" in out \
            and "Output Shape" in out and "Param #" in out
        assert "Conv2D-1" in out and "[1, 6, 28, 28]" in out
        assert "MaxPool2D-3" in out and "[1, 6, 14, 14]" in out
        assert "Linear-7" in out and "[1, 400]" in out and "[1, 120]" in out
        assert "Total params: 61,610" in out
        assert "Trainable params: 61,610" in out
        assert "Non-trainable params: 0" in out
        # memory estimate footer
        assert "Input size (MB):" in out
        assert "Forward/backward pass size (MB):" in out
        assert "Params size (MB): 0.24" in out
        assert "Estimated Total Size (MB):" in out
        assert info == {"total_params": 61610, "trainable_params": 61610}

    def test_gpt_per_layer_shapes(self, capsys):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32, dropout=0.0)
        info = paddle.summary(GPTForCausalLM(cfg), (1, 16), dtypes="int32")
        out = capsys.readouterr().out
        assert "Embedding-1" in out or "Embedding" in out
        assert "[1, 16, 64]" in out          # hidden stream shape
        assert "[1, 16, 192]" in out         # fused qkv projection
        assert "GPTAttention" in out         # nested custom layers appear
        assert info["total_params"] == info["trainable_params"] > 0

    def test_batch_dim_none_becomes_one(self):
        _, info = summary_string(_lenet(), (None, 1, 28, 28))
        assert info["records"][0]["input_shape"] == [1, 1, 28, 28]
        with pytest.raises(ValueError, match="batch"):
            summary_string(_lenet(), (None, None, 28, 28))

    def test_input_tensor_instead_of_size(self):
        x = paddle.to_tensor(np.zeros((2, 1, 28, 28), np.float32))
        _, info = summary_string(_lenet(), input=x)
        assert info["records"][0]["input_shape"] == [2, 1, 28, 28]
        assert info["total_params"] == 61610

    def test_trainable_split(self, capsys):
        net = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
        for p in net[0].parameters():
            p.stop_gradient = True
        info = paddle.summary(net, (1, 4))
        out = capsys.readouterr().out
        assert info["total_params"] == 4 * 8 + 8 + 8 * 2 + 2
        assert info["trainable_params"] == 8 * 2 + 2
        assert "Non-trainable params: 40" in out

    def test_training_mode_restored(self):
        net = _lenet()
        net.train()
        summary_string(net, (1, 1, 28, 28))
        assert net.training
        net.eval()
        summary_string(net, (1, 1, 28, 28))
        assert not net.training

    def test_root_level_params_counted(self):
        class WithRootParam(nn.Layer):
            def __init__(self):
                super().__init__()
                self.w = self.create_parameter([7, 7])
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(x) + self.w.sum()

        _, info = summary_string(WithRootParam(), (1, 4))
        assert info["total_params"] == 7 * 7 + 4 * 4 + 4

    def test_weight_shared_layer_not_double_counted(self):
        class Shared(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(4, 4)

            def forward(self, x):
                return self.fc(self.fc(x))

        _, info = summary_string(Shared(), (1, 4))
        assert info["total_params"] == 4 * 4 + 4
        # the layer still appears twice in the execution table
        assert [r["key"] for r in info["records"]] \
            == ["Linear-1", "Linear-2"]

    def test_model_summary_falls_back_to_input_specs(self, capsys):
        from paddle_tpu.static import InputSpec

        m = paddle.Model(nn.Linear(4, 2),
                         inputs=[InputSpec([None, 4], "float32")])
        info = m.summary()
        capsys.readouterr()
        assert info["total_params"] == 4 * 2 + 2

    def test_multi_input(self):
        class TwoIn(nn.Layer):
            def __init__(self):
                super().__init__()
                self.a = nn.Linear(3, 4)
                self.b = nn.Linear(5, 4)

            def forward(self, x, y):
                return self.a(x) + self.b(y)

        _, info = summary_string(TwoIn(), [(1, 3), (1, 5)])
        keys = [r["key"] for r in info["records"]]
        assert keys == ["Linear-1", "Linear-2"]


class TestFlops:
    def test_lenet_flops_exact(self):
        # conv: 2 * prod(w) * out_hw * batch; linear: 2 * batch * prod(w)
        expect = (2 * (6 * 1 * 3 * 3) * 28 * 28
                  + 2 * (16 * 6 * 5 * 5) * 10 * 10
                  + 2 * (400 * 120 + 120 * 84 + 84 * 10))
        assert paddle.flops(_lenet(), (1, 1, 28, 28)) == expect

    def test_gpt_flops_counts_attention(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32, dropout=0.0)
        b, s, h = 1, 16, 64
        linears_per_block = 2 * b * s * (h * 3 * h + h * h
                                         + h * 4 * h + 4 * h * h)
        attn_per_block = 4 * b * s * s * h
        assert paddle.flops(GPTForCausalLM(cfg), (b, s)) \
            == 2 * (linears_per_block + attn_per_block)  # 2 blocks

    def test_print_detail_table(self, capsys):
        total = paddle.flops(_lenet(), (1, 1, 28, 28), print_detail=True)
        out = capsys.readouterr().out
        assert "FLOPs" in out and f"Total FLOPs: {total:,}" in out
        assert "Conv2D-1" in out

    def test_custom_ops_override(self):
        class Odd(nn.Layer):
            def forward(self, x):
                return x * 2

        net = nn.Sequential(nn.Linear(4, 4), Odd())
        base = paddle.flops(net, (1, 4))
        with_custom = paddle.flops(
            net, (1, 4), custom_ops={Odd: lambda l, i, o: 1000})
        assert with_custom == base + 1000
