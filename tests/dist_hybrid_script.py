"""Worker for the 4-process HYBRID harness (test_dist_multiproc.py):
dp ACROSS processes x mp WITHIN each process — the multi-controller
topology a real pod runs (each host owns a tensor-parallel group slice,
data parallelism spans hosts).

Each of the 4 processes brings 2 virtual CPU devices (XLA_FLAGS from the
test); jax.distributed stitches them into one 8-device mesh (dp=4, mp=2)
where a process's two local devices form its mp pair. Mid-run the FULL
train state is gathered (trainer.state_dict() — a cross-group collect of
ZeRO-sharded params + Adam moments) and restored into a FRESH trainer;
the loss trajectory must continue unperturbed and match a single-process
8-device control run.

Reference parity: scales test_dist_base.py's 2-trainer pattern
(python/paddle/fluid/tests/unittests/test_dist_base.py:671) to the
4-process hybrid the reference runs via fleetrun on real clusters.
"""
import argparse
import json

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--save_at", type=int, default=3,
                    help="gather+restore the train state before this step")
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.distributed.split import collect_spmd_specs
    from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

    denv.init_distributed()
    rank = denv.get_rank()
    n_devices = len(jax.devices())
    assert n_devices == 8, n_devices
    mesh = build_mesh((4, 2), ("dp", "mp"))   # mp pair = one process

    def make_trainer():
        paddle.seed(0)
        cfg = GPTConfig.tiny()
        cfg.tensor_parallel = True            # Column/RowParallel over 'mp'
        model = GPTForCausalLM(cfg)
        loss_layer = GPTPretrainLoss()
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        trainer = SpmdTrainer(
            model, opt,
            loss_fn=lambda logits, labels: loss_layer(logits, labels),
            mesh=mesh, dp_axis="dp", sharding_stage=2,
            extra_param_specs=collect_spmd_specs(model))
        return cfg, trainer

    cfg, trainer = make_trainer()
    rng = np.random.RandomState(1)
    ids = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (8, 32)).astype(np.int32)

    losses = []
    for step in range(args.steps):
        if step == args.save_at:
            # cross-group gather of the FULL sharded train state, restored
            # into a brand-new trainer — the trajectory must not notice
            state = trainer.state_dict()
            _, trainer = make_trainer()
            trainer.set_state_dict(state)
        loss = trainer.train_step(paddle.to_tensor(ids),
                                  paddle.to_tensor(labels))
        losses.append(float(np.asarray(loss._data)))

    if rank == 0:
        with open(args.out, "w") as f:
            json.dump({"world": denv.get_world_size(),
                       "n_devices": n_devices, "losses": losses}, f)
    print(f"rank {rank} done: {losses[-1]:.6f}", flush=True)


if __name__ == "__main__":
    main()
