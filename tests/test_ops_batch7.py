"""Op-test burn-down, batch 7: sequence family (padded+length LoD design),
misc reference ops (l1_norm, squared_l2_norm, cos_sim, space_to_depth,
pad_constant_like, add_position_encoding, bilinear_tensor_product, conv_shift,
row_conv, im2sequence, partial_concat/sum, sampling_id, shuffle_batch) and the
detection additions (anchor_generator, box_clip, target_assign, yolov3_loss
verified against a loop-for-loop numpy port of the reference kernel)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.vision import ops as V

rng = np.random.RandomState(11)


def _randn(*shape):
    return rng.randn(*shape).astype(np.float32)


def _np(t):
    return np.asarray(t._data)


# --------------------------- sequence family ------------------------------

X = _randn(3, 5, 2)
LEN = np.array([5, 3, 0], np.int64)


def test_sequence_pool_modes():
    for mode, ref in [
        ("sum", lambda v, n: v[:n].sum(0)),
        ("average", lambda v, n: v[:n].mean(0)),
        ("sqrt", lambda v, n: v[:n].sum(0) / np.sqrt(n)),
        ("max", lambda v, n: v[:n].max(0)),
        ("min", lambda v, n: v[:n].min(0)),
        ("first", lambda v, n: v[0]),
        ("last", lambda v, n: v[n - 1]),
    ]:
        got = _np(F.sequence_pool(paddle.to_tensor(X), paddle.to_tensor(LEN),
                                  mode))
        for b in range(3):
            if LEN[b] == 0:
                np.testing.assert_allclose(got[b], 0.0, err_msg=mode)
            else:
                np.testing.assert_allclose(got[b], ref(X[b], LEN[b]),
                                           rtol=1e-5, err_msg=mode)


def test_sequence_pool_grad():
    x = paddle.to_tensor(X)
    x.stop_gradient = False
    F.sequence_pool(x, paddle.to_tensor(LEN), "sum").sum().backward()
    g = _np(x.grad)
    np.testing.assert_allclose(g[0], 1.0)          # all 5 steps valid
    np.testing.assert_allclose(g[1, 3:], 0.0)      # padding gets no grad
    np.testing.assert_allclose(g[2], 0.0)


def test_sequence_softmax():
    x = _randn(2, 4)
    ln = np.array([3, 4])
    got = _np(F.sequence_softmax(paddle.to_tensor(x), paddle.to_tensor(ln)))
    for b in range(2):
        e = np.exp(x[b, :ln[b]] - x[b, :ln[b]].max())
        np.testing.assert_allclose(got[b, :ln[b]], e / e.sum(), rtol=1e-5)
        np.testing.assert_allclose(got[b, ln[b]:], 0.0)


def test_sequence_reverse():
    x = _randn(2, 4, 3)
    ln = np.array([3, 4])
    got = _np(F.sequence_reverse(paddle.to_tensor(x), paddle.to_tensor(ln)))
    np.testing.assert_allclose(got[0, :3], x[0, :3][::-1])
    np.testing.assert_allclose(got[0, 3], x[0, 3])  # padding untouched
    np.testing.assert_allclose(got[1], x[1][::-1])


def test_sequence_expand():
    x = _randn(2, 4, 2)
    lx = np.array([2, 1])
    lr = np.array([4, 3])
    got = _np(F.sequence_expand(paddle.to_tensor(x), paddle.to_tensor(lx),
                                paddle.to_tensor(lr)))
    # row 0 cycles its 2 valid steps to fill 4; row 1 tiles its single step
    np.testing.assert_allclose(got[0], np.stack([x[0, 0], x[0, 1],
                                                 x[0, 0], x[0, 1]]))
    np.testing.assert_allclose(got[1, :3], np.stack([x[1, 0]] * 3))
    np.testing.assert_allclose(got[1, 3], 0.0)


def test_sequence_slice():
    x = _randn(2, 5)
    ln = np.array([5, 4])
    out, newlen = F.sequence_slice(paddle.to_tensor(x), paddle.to_tensor(ln),
                                   np.array([1, 0]), np.array([2, 3]))
    got = _np(out)
    np.testing.assert_allclose(got[0, :2], x[0, 1:3])
    np.testing.assert_allclose(got[0, 2:], 0.0)
    np.testing.assert_allclose(got[1, :3], x[1, :3])
    np.testing.assert_allclose(_np(newlen), [2, 3])


def test_sequence_concat():
    a = _randn(2, 3)
    b = _randn(2, 2)
    la = np.array([2, 3])
    lb = np.array([1, 2])
    out, total = F.sequence_concat([paddle.to_tensor(a), paddle.to_tensor(b)],
                                   [paddle.to_tensor(la), paddle.to_tensor(lb)])
    got = _np(out)
    np.testing.assert_allclose(_np(total), [3, 5])
    np.testing.assert_allclose(got[0, :3], [a[0, 0], a[0, 1], b[0, 0]])
    np.testing.assert_allclose(got[1, :5],
                               [a[1, 0], a[1, 1], a[1, 2], b[1, 0], b[1, 1]])
    np.testing.assert_allclose(got[0, 3:], 0.0)


def test_sequence_enumerate_erase_reshape_scatter():
    ids = np.array([[1, 2, 3, 0], [4, 5, 0, 0]], np.int64)
    ln = np.array([3, 2])
    win = _np(F.sequence_enumerate(ids, ln, 2, pad_value=9))
    np.testing.assert_allclose(win[0], [[1, 2], [2, 3], [3, 9], [9, 9]])
    np.testing.assert_allclose(win[1], [[4, 5], [5, 9], [9, 9], [9, 9]])

    out, nl = F.sequence_erase(np.array([[1, 7, 2, 7], [7, 7, 5, 0]], np.int64),
                               np.array([4, 3]), [7])
    np.testing.assert_allclose(_np(out)[0, :2], [1, 2])
    np.testing.assert_allclose(_np(nl), [2, 1])

    data = _randn(2, 4, 6)
    out2, nl2 = F.sequence_reshape(paddle.to_tensor(data),
                                   np.array([2, 4]), 12)
    assert _np(out2).shape == (2, 2, 12)
    np.testing.assert_allclose(_np(nl2), [1, 2])

    base = np.zeros((2, 5), np.float32)
    got = _np(F.sequence_scatter(paddle.to_tensor(base),
                                 np.array([[0, 2], [1, 3]]),
                                 paddle.to_tensor(np.ones((2, 2), np.float32)),
                                 np.array([2, 1])))
    np.testing.assert_allclose(got[0], [1, 0, 1, 0, 0])
    np.testing.assert_allclose(got[1], [0, 1, 0, 0, 0])  # 2nd update masked


def test_sequence_conv():
    B, T, D, M, CL = 2, 4, 3, 5, 3
    x = _randn(B, T, D)
    ln = np.array([4, 2])
    w = _randn(CL * D, M)
    got = _np(F.sequence_conv(paddle.to_tensor(x), paddle.to_tensor(ln),
                              paddle.to_tensor(w), CL))
    # numpy reference: context [-1, 0, 1] rows (zero outside sequence)
    for b in range(B):
        for t in range(int(ln[b])):
            ctx = []
            for c in range(CL):
                p = t + c - 1
                ctx.append(x[b, p] if 0 <= p < ln[b] else np.zeros(D))
            np.testing.assert_allclose(got[b, t], np.concatenate(ctx) @ w,
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got[b, int(ln[b]):], 0.0)


# ------------------------------ misc ops ----------------------------------

def test_misc_norms_and_sims():
    x = _randn(3, 4)
    np.testing.assert_allclose(float(_np(F.l1_norm(paddle.to_tensor(x)))),
                               np.abs(x).sum(), rtol=1e-5)
    np.testing.assert_allclose(
        float(_np(F.squared_l2_norm(paddle.to_tensor(x)))), (x * x).sum(),
        rtol=1e-5)
    y = _randn(3, 4)
    got = _np(F.cos_sim(paddle.to_tensor(x), paddle.to_tensor(y)))
    exp = (x * y).sum(1) / (np.linalg.norm(x, axis=1) * np.linalg.norm(y, axis=1))
    np.testing.assert_allclose(got.ravel(), exp, rtol=1e-5)
    # broadcast single-row y
    got1 = _np(F.cos_sim(paddle.to_tensor(x), paddle.to_tensor(y[:1])))
    exp1 = (x * y[:1]).sum(1) / (np.linalg.norm(x, axis=1) * np.linalg.norm(y[0]))
    np.testing.assert_allclose(got1.ravel(), exp1, rtol=1e-5)


def test_space_to_depth_matches_pixel_unshuffle_reorder():
    x = np.arange(1 * 2 * 4 * 4, dtype=np.float32).reshape(1, 2, 4, 4)
    got = _np(F.space_to_depth(paddle.to_tensor(x), 2))
    assert got.shape == (1, 8, 2, 2)
    # block (0,0) of channel 0 lands in the first output channel
    np.testing.assert_allclose(got[0, 0], x[0, 0, 0::2, 0::2])


def test_pad_constant_like_and_position_encoding():
    x = np.zeros((3, 4), np.float32)
    y = _randn(2, 3)
    got = _np(F.pad_constant_like(paddle.to_tensor(x), paddle.to_tensor(y),
                                  pad_value=7.0))
    np.testing.assert_allclose(got[:2, :3], y)
    np.testing.assert_allclose(got[2, :], 7.0)
    np.testing.assert_allclose(got[:, 3], 7.0)

    v = _randn(2, 5, 6)
    pe = _np(F.add_position_encoding(paddle.to_tensor(v), alpha=2.0, beta=1.0))
    half = 3
    pos, i = 1, 0
    expected = 2.0 * v[0, pos, i] + np.sin(pos / (10000 ** (i / half)))
    np.testing.assert_allclose(pe[0, pos, i], expected, rtol=1e-5)
    expected_cos = 2.0 * v[0, pos, half] + np.cos(pos / (10000 ** (0 / half)))
    np.testing.assert_allclose(pe[0, pos, half], expected_cos, rtol=1e-5)


def test_bilinear_tensor_product_and_conv_shift():
    x, y = _randn(3, 4), _randn(3, 5)
    w = _randn(2, 4, 5)
    b = _randn(2)
    got = _np(F.bilinear_tensor_product(paddle.to_tensor(x),
                                        paddle.to_tensor(y),
                                        paddle.to_tensor(w),
                                        paddle.to_tensor(b)))
    exp = np.stack([x @ w[k] @ y.T for k in range(2)], 1)
    exp = np.stack([exp[i, :, i] for i in range(3)]) + b
    np.testing.assert_allclose(got, exp, rtol=1e-4)

    a = _randn(2, 6)
    k = _randn(2, 3)
    got = _np(F.conv_shift(paddle.to_tensor(a), paddle.to_tensor(k)))
    exp = np.zeros((2, 6), np.float32)
    for b_ in range(2):
        for i in range(6):
            for j in range(3):
                exp[b_, i] += a[b_, (i + j - 1) % 6] * k[b_, j]
    np.testing.assert_allclose(got, exp, rtol=1e-4)


def test_row_conv():
    x = _randn(2, 5, 3)
    w = _randn(3, 3)  # future_context=3
    ln = np.array([5, 3])
    got = _np(F.row_conv(paddle.to_tensor(x), paddle.to_tensor(w),
                         paddle.to_tensor(ln)))
    for b in range(2):
        for t in range(int(ln[b])):
            exp = np.zeros(3, np.float32)
            for c in range(3):
                if t + c < ln[b]:
                    exp += w[c] * x[b, t + c]
            np.testing.assert_allclose(got[b, t], exp, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got[b, int(ln[b]):], 0.0)


def test_im2sequence_partial_and_shuffle():
    x = np.arange(1 * 1 * 4 * 4, dtype=np.float32).reshape(1, 1, 4, 4)
    got = _np(F.im2sequence(paddle.to_tensor(x), 2, 2))
    assert got.shape == (1, 4, 4)
    np.testing.assert_allclose(got[0, 0], [0, 1, 4, 5])
    np.testing.assert_allclose(got[0, 3], [10, 11, 14, 15])

    a, b = _randn(2, 4), _randn(2, 4)
    pc = _np(F.partial_concat([paddle.to_tensor(a), paddle.to_tensor(b)],
                              start_index=1, length=2))
    np.testing.assert_allclose(pc, np.concatenate([a[:, 1:3], b[:, 1:3]], 1))
    ps = _np(F.partial_sum([paddle.to_tensor(a), paddle.to_tensor(b)],
                           start_index=1, length=2))
    np.testing.assert_allclose(ps, a[:, 1:3] + b[:, 1:3])

    paddle.seed(5)
    sb = _np(F.shuffle_batch(paddle.to_tensor(a)))
    assert sorted(sb[:, 0].tolist()) == sorted(a[:, 0].tolist())

    paddle.seed(5)
    probs = np.array([[0.0, 1.0, 0.0], [0.0, 0.0, 1.0]], np.float32)
    sid = _np(F.sampling_id(paddle.to_tensor(probs)))
    np.testing.assert_allclose(sid, [1, 2])


# ------------------------- detection additions ----------------------------

def test_anchor_generator():
    x = paddle.to_tensor(np.zeros((1, 8, 2, 3), np.float32))
    anchors, variances = V.anchor_generator(
        x, anchor_sizes=[64.0], aspect_ratios=[1.0, 2.0],
        variances=[0.1, 0.1, 0.2, 0.2], stride=[16.0, 16.0], offset=0.5)
    a = _np(anchors)
    assert a.shape == (2, 3, 2, 4)
    # reference math at cell (0, 0), ar=1, size=64:
    xc = 0.5 * 15
    base = round(np.sqrt(16 * 16 / 1.0))
    aw = 64 / 16 * base
    np.testing.assert_allclose(a[0, 0, 0],
                               [xc - 0.5 * (aw - 1), xc - 0.5 * (aw - 1),
                                xc + 0.5 * (aw - 1), xc + 0.5 * (aw - 1)])
    v = _np(variances)
    np.testing.assert_allclose(v[1, 2, 1], [0.1, 0.1, 0.2, 0.2])


def test_box_clip_and_target_assign():
    boxes = np.array([[[-5, -5, 50, 60], [10, 10, 20, 20]]], np.float32)
    im = np.array([[40.0, 30.0, 1.0]], np.float32)
    got = _np(V.box_clip(paddle.to_tensor(boxes), paddle.to_tensor(im)))
    np.testing.assert_allclose(got[0, 0], [0, 0, 29, 39])
    np.testing.assert_allclose(got[0, 1], [10, 10, 20, 20])

    x = _randn(1, 3, 2)
    mi = np.array([[2, -1, 0, 1]], np.int64)
    out, wt = V.target_assign(paddle.to_tensor(x), paddle.to_tensor(mi),
                              mismatch_value=5.0)
    o, w = _np(out), _np(wt)
    np.testing.assert_allclose(o[0, 0], x[0, 2])
    np.testing.assert_allclose(o[0, 1], 5.0)
    np.testing.assert_allclose(w.ravel(), [1, 0, 1, 1])
    # negative indices force mismatch_value with weight 1
    out2, wt2 = V.target_assign(paddle.to_tensor(x), paddle.to_tensor(mi),
                                negative_indices=np.array([[3]], np.int64),
                                mismatch_value=5.0)
    np.testing.assert_allclose(_np(out2)[0, 3], 5.0)
    np.testing.assert_allclose(_np(wt2).ravel(), [1, 0, 1, 1])


def _yolo_loss_np(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                  class_num, ignore_thresh, downsample_ratio,
                  use_label_smooth=True, scale_xy=1.0):
    """Loop-for-loop port of yolov3_loss_op.h Compute (the oracle)."""
    def sig(v):
        return 1 / (1 + np.exp(-v))

    def sce(p, t):
        return max(p, 0) - p * t + np.log1p(np.exp(-abs(p)))

    def iou_cwh(a, b):
        ax1, ay1, ax2, ay2 = a[0] - a[2] / 2, a[1] - a[3] / 2, a[0] + a[2] / 2, a[1] + a[3] / 2
        bx1, by1, bx2, by2 = b[0] - b[2] / 2, b[1] - b[3] / 2, b[0] + b[2] / 2, b[1] + b[3] / 2
        iw = max(0.0, min(ax2, bx2) - max(ax1, bx1))
        ih = max(0.0, min(ay2, by2) - max(ay1, by1))
        inter = iw * ih
        return inter / max(a[2] * a[3] + b[2] * b[3] - inter, 1e-10)

    N, _, H, W = x.shape
    mask_num = len(anchor_mask)
    an_num = len(anchors) // 2
    input_size = downsample_ratio * H
    xr = x.reshape(N, mask_num, 5 + class_num, H, W)
    smooth = min(1.0 / class_num, 1.0 / 40) if use_label_smooth else 0.0
    pos_l, neg_l = 1 - smooth, smooth
    bias = -0.5 * (scale_xy - 1)
    loss = np.zeros(N)
    for i in range(N):
        obj = np.zeros((mask_num, H, W))
        for j in range(mask_num):
            for k in range(H):
                for l in range(W):
                    px = (l + sig(xr[i, j, 0, k, l]) * scale_xy + bias) / W
                    py = (k + sig(xr[i, j, 1, k, l]) * scale_xy + bias) / H
                    pw = np.exp(xr[i, j, 2, k, l]) * anchors[2 * anchor_mask[j]] / input_size
                    ph = np.exp(xr[i, j, 3, k, l]) * anchors[2 * anchor_mask[j] + 1] / input_size
                    best = 0.0
                    for t in range(gt_box.shape[1]):
                        if gt_box[i, t, 2] <= 0 or gt_box[i, t, 3] <= 0:
                            continue
                        best = max(best, iou_cwh((px, py, pw, ph), gt_box[i, t]))
                    if best > ignore_thresh:
                        obj[j, k, l] = -1
        for t in range(gt_box.shape[1]):
            gt = gt_box[i, t]
            if gt[2] <= 0 or gt[3] <= 0:
                continue
            gi, gj = int(gt[0] * W), int(gt[1] * H)
            best_iou, best_n = 0.0, 0
            for a_ in range(an_num):
                cand = (0, 0, anchors[2 * a_] / input_size,
                        anchors[2 * a_ + 1] / input_size)
                iou = iou_cwh(cand, (0, 0, gt[2], gt[3]))
                if iou > best_iou:
                    best_iou, best_n = iou, a_
            if best_n not in anchor_mask:
                continue
            mj = anchor_mask.index(best_n)
            score = gt_score[i, t]
            tx, ty = gt[0] * W - gi, gt[1] * H - gj
            tw = np.log(gt[2] * input_size / anchors[2 * best_n])
            th = np.log(gt[3] * input_size / anchors[2 * best_n + 1])
            sc = (2 - gt[2] * gt[3]) * score
            loss[i] += sce(xr[i, mj, 0, gj, gi], tx) * sc
            loss[i] += sce(xr[i, mj, 1, gj, gi], ty) * sc
            loss[i] += abs(xr[i, mj, 2, gj, gi] - tw) * sc
            loss[i] += abs(xr[i, mj, 3, gj, gi] - th) * sc
            for c in range(class_num):
                tgt = pos_l if c == gt_label[i, t] else neg_l
                loss[i] += sce(xr[i, mj, 5 + c, gj, gi], tgt) * score
            obj[mj, gj, gi] = score
        for j in range(mask_num):
            for k in range(H):
                for l in range(W):
                    o = obj[j, k, l]
                    if o > 1e-5:
                        loss[i] += sce(xr[i, j, 4, k, l], 1.0) * o
                    elif o > -0.5:
                        loss[i] += sce(xr[i, j, 4, k, l], 0.0)
    return loss


def test_yolov3_loss_vs_reference_port():
    N, H, W, C = 2, 4, 4, 3
    anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]
    anchor_mask = [0, 1, 2]
    mask_num = len(anchor_mask)
    x = _randn(N, mask_num * (5 + C), H, W) * 0.5
    gt_box = np.zeros((N, 3, 4), np.float32)
    gt_box[0, 0] = [0.3, 0.4, 0.2, 0.2]
    gt_box[0, 1] = [0.7, 0.6, 0.4, 0.5]
    gt_box[1, 0] = [0.5, 0.5, 0.1, 0.3]
    gt_label = rng.randint(0, C, (N, 3)).astype(np.int64)
    gt_score = np.ones((N, 3), np.float32)
    got = _np(V.yolov3_loss(paddle.to_tensor(x), paddle.to_tensor(gt_box),
                            paddle.to_tensor(gt_label), anchors, anchor_mask,
                            C, ignore_thresh=0.5, downsample_ratio=8))
    exp = _yolo_loss_np(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                        C, 0.5, 8)
    np.testing.assert_allclose(got, exp, rtol=1e-4)
    # grad flows through predictions
    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    V.yolov3_loss(xt, paddle.to_tensor(gt_box), paddle.to_tensor(gt_label),
                  anchors, anchor_mask, C, 0.5, 8).sum().backward()
    g = _np(xt.grad)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_review_fixes_batch7():
    # im2sequence asymmetric [top, left, bottom, right] padding
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    got = _np(F.im2sequence(paddle.to_tensor(x), 2, 2, padding=[1, 0, 1, 0]))
    assert got.shape == (1, 3 * 2, 4)  # oh=(4+2-2)/2+1=3, ow=2

    # shuffle_batch: fresh permutation per call under default seed
    paddle.seed(3)
    big = np.arange(64, dtype=np.float32).reshape(64, 1)
    p1 = _np(F.shuffle_batch(paddle.to_tensor(big))).ravel()
    p2 = _np(F.shuffle_batch(paddle.to_tensor(big))).ravel()
    assert not np.array_equal(p1, p2)

    # partial_concat with negative start_index counts from the end
    a = _randn(2, 4)
    pc = _np(F.partial_concat([paddle.to_tensor(a)], start_index=-2, length=2))
    np.testing.assert_allclose(pc, a[:, -2:])

    # target_assign 2-D negative indices get mismatch_value
    lab = np.array([[7, 8, 9]], np.float32)
    mi = np.array([[1, 0, 2, 0]], np.int64)
    out, wt = V.target_assign(paddle.to_tensor(lab), paddle.to_tensor(mi),
                              negative_indices=np.array([[3]], np.int64),
                              mismatch_value=0.0)
    np.testing.assert_allclose(_np(out).ravel(), [8, 7, 9, 0])
    np.testing.assert_allclose(_np(wt).ravel(), [1, 1, 1, 1])


def test_yolov3_loss_cell_collision_later_gt_wins():
    N, H, W, C = 1, 4, 4, 2
    anchors = [10, 13, 16, 30]
    anchor_mask = [0, 1]
    x = _randn(N, 2 * (5 + C), H, W) * 0.3
    # two gts in the SAME cell matching the same anchor, different scores
    gt_box = np.zeros((N, 2, 4), np.float32)
    gt_box[0, 0] = [0.3, 0.3, 0.08, 0.10]
    gt_box[0, 1] = [0.3, 0.3, 0.08, 0.11]
    gt_label = np.array([[0, 1]], np.int64)
    gt_score = np.array([[0.4, 0.9]], np.float32)
    got = _np(V.yolov3_loss(paddle.to_tensor(x), paddle.to_tensor(gt_box),
                            paddle.to_tensor(gt_label), anchors, anchor_mask,
                            C, 0.7, 8, gt_score=paddle.to_tensor(gt_score)))
    exp = _yolo_loss_np(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                        C, 0.7, 8)
    np.testing.assert_allclose(got, exp, rtol=1e-4)


def test_batch8_losses():
    # bpr_loss vs loop
    x = _randn(3, 4)
    y = np.array([1, 0, 3], np.int64)
    got = _np(F.bpr_loss(paddle.to_tensor(x), paddle.to_tensor(y))).ravel()
    exp = np.zeros(3)
    for i in range(3):
        s = 0.0
        for j in range(4):
            if j == y[i]:
                continue
            s += -np.log(1 / (1 + np.exp(-(x[i, y[i]] - x[i, j]))))
        exp[i] = s / 3
    np.testing.assert_allclose(got, exp, rtol=1e-5)

    # modified huber: v<-1 -> -4v ; v<1 -> (1-v)^2 ; else 0
    xs = np.array([-2.0, 0.5, 3.0], np.float32)
    ys = np.array([1.0, 1.0, 1.0], np.float32)
    got = _np(F.modified_huber_loss(paddle.to_tensor(xs), paddle.to_tensor(ys)))
    np.testing.assert_allclose(got, [8.0, 0.25, 0.0])

    # center_loss: loss + center update rule
    feat = _randn(4, 3)
    lab = np.array([0, 1, 1, 2], np.int64)
    centers0 = _randn(5, 3).copy()
    ct = paddle.to_tensor(centers0.copy())
    loss, new_c = F.center_loss(paddle.to_tensor(feat), paddle.to_tensor(lab),
                                5, 0.1, ct)
    np.testing.assert_allclose(
        _np(loss).ravel(),
        [0.5 * ((centers0[c] - feat[i]) ** 2).sum()
         for i, c in enumerate(lab)], rtol=1e-5)
    exp_c = centers0.copy()
    for c in range(5):
        idx = np.nonzero(lab == c)[0]
        if len(idx):
            diff = (centers0[c] - feat[idx]).sum(0)
            exp_c[c] -= 0.1 * diff / (1 + len(idx))
    np.testing.assert_allclose(_np(new_c), exp_c, rtol=1e-5)
    np.testing.assert_allclose(_np(ct), exp_c, rtol=1e-5)  # updated in place


def test_batch8_feature_ops():
    # cvm
    x = np.abs(_randn(2, 4)) + 0.5
    got = _np(F.cvm(paddle.to_tensor(x), None, use_cvm=True))
    np.testing.assert_allclose(got[:, 0], np.log(x[:, 0] + 1), rtol=1e-5)
    np.testing.assert_allclose(got[:, 1],
                               np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1),
                               rtol=1e-5)
    np.testing.assert_allclose(got[:, 2:], x[:, 2:])
    got2 = _np(F.cvm(paddle.to_tensor(x), None, use_cvm=False))
    np.testing.assert_allclose(got2, x[:, 2:])

    # data_norm: y = (x - sum/size) * sqrt(size/square_sum)
    xv = _randn(3, 2)
    bsz = np.array([4.0, 4.0], np.float32)
    bsum = np.array([2.0, -1.0], np.float32)
    bsq = np.array([9.0, 16.0], np.float32)
    got = _np(F.data_norm(paddle.to_tensor(xv), paddle.to_tensor(bsz),
                          paddle.to_tensor(bsum), paddle.to_tensor(bsq)))
    np.testing.assert_allclose(
        got, (xv - bsum / bsz) * np.sqrt(bsz / bsq), rtol=1e-5)

    # affine_channel
    img = _randn(2, 3, 2, 2)
    s = _randn(3)
    b = _randn(3)
    got = _np(F.affine_channel(paddle.to_tensor(img), paddle.to_tensor(s),
                               paddle.to_tensor(b)))
    np.testing.assert_allclose(got, img * s[None, :, None, None]
                               + b[None, :, None, None], rtol=1e-5)

    # ctc_align: merge repeats then drop blanks
    ids = np.array([[1, 1, 0, 2, 2, 3], [0, 0, 4, 4, 0, 0]], np.int64)
    ln = np.array([6, 4])
    out, nl = F.ctc_align(paddle.to_tensor(ids), paddle.to_tensor(ln),
                          blank=0, merge_repeated=True)
    np.testing.assert_allclose(_np(out)[0, :3], [1, 2, 3])
    np.testing.assert_allclose(_np(out)[0, 3:], 0)
    np.testing.assert_allclose(_np(out)[1, :1], [4])
    np.testing.assert_allclose(_np(nl), [3, 1])

    # fsp matrix
    a = _randn(2, 3, 4, 4)
    bb = _randn(2, 5, 4, 4)
    got = _np(F.fsp_matrix(paddle.to_tensor(a), paddle.to_tensor(bb)))
    exp = np.einsum("bchw,bdhw->bcd", a, bb) / 16
    np.testing.assert_allclose(got, exp, rtol=1e-4)

    # spp output size: C * (1 + 4 + 16)
    img2 = _randn(2, 3, 8, 8)
    got = _np(F.spp(paddle.to_tensor(img2), 3, "max"))
    assert got.shape == (2, 3 * 21)
    np.testing.assert_allclose(got[:, :3], img2.max(axis=(2, 3)), rtol=1e-5)


def test_density_prior_box():
    feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 16, 16), np.float32))
    boxes, var = V.density_prior_box(
        feat, img, densities=[2], fixed_sizes=[4.0], fixed_ratios=[1.0],
        variances=[0.1, 0.1, 0.2, 0.2], offset=0.5)
    b = _np(boxes)
    assert b.shape == (2, 2, 4, 4)  # density^2 * ratios = 4 priors per cell
    # loop-port of the reference kernel for cell (0, 0)
    step_w = step_h = 8.0
    step_avg = 8
    shift = step_avg // 2
    cx = cy = 0.5 * 8
    dcx = cx - step_avg / 2 + shift / 2
    exp0 = [max((dcx - 2) / 16, 0), max((dcx - 2) / 16, 0),
            min((dcx + 2) / 16, 1), min((dcx + 2) / 16, 1)]
    np.testing.assert_allclose(b[0, 0, 0], exp0, rtol=1e-5)
    np.testing.assert_allclose(_np(var)[0, 0, 0], [0.1, 0.1, 0.2, 0.2])
    # boxes all inside [0, 1]
    assert (b >= 0).all() and (b <= 1).all()


def test_collect_fpn_proposals():
    r1 = np.array([[0, 0, 10, 10], [1, 1, 5, 5]], np.float32)
    r2 = np.array([[2, 2, 8, 8]], np.float32)
    s1 = np.array([0.9, 0.2], np.float32)
    s2 = np.array([0.5], np.float32)
    out = V.collect_fpn_proposals([r1, r2], [s1, s2], 2, 3, post_nms_top_n=2)
    got = _np(out)
    np.testing.assert_allclose(got[0], r1[0])  # score 0.9
    np.testing.assert_allclose(got[1], r2[0])  # score 0.5


def test_nce_vs_loop():
    B, D, R, K = 3, 4, 7, 5
    x = _randn(B, D)
    w = _randn(R, D)
    b = _randn(R)
    lab = np.array([2, 0, 6], np.int64)
    got = _np(F.nce(paddle.to_tensor(x), paddle.to_tensor(lab),
                    paddle.to_tensor(w), paddle.to_tensor(b),
                    num_total_classes=R, num_neg_samples=K,
                    sampler="uniform", seed=9)).ravel()
    # reproduce the draw and the reference cost (nce_op.h:202-205)
    rng_ = np.random.RandomState(9)
    neg = rng_.randint(0, R, size=(B, K))
    exp = np.zeros(B)
    for i in range(B):
        ids = [lab[i]] + list(neg[i])
        for j, c in enumerate(ids):
            o = 1 / (1 + np.exp(-(w[c] @ x[i] + b[c])))
            bb = K * (1.0 / R)
            exp[i] += -np.log(o / (o + bb)) if j == 0 else -np.log(bb / (o + bb))
    np.testing.assert_allclose(got, exp, rtol=1e-4)
    # grads flow to input and weight
    xt = paddle.to_tensor(x); xt.stop_gradient = False
    wt = paddle.to_tensor(w); wt.stop_gradient = False
    F.nce(xt, paddle.to_tensor(lab), wt, paddle.to_tensor(b),
          num_total_classes=R, num_neg_samples=K, seed=9).sum().backward()
    assert np.abs(_np(xt.grad)).sum() > 0 and np.abs(_np(wt.grad)).sum() > 0
    # log_uniform sampler runs and is finite
    got2 = _np(F.nce(paddle.to_tensor(x), paddle.to_tensor(lab),
                     paddle.to_tensor(w), num_total_classes=R,
                     num_neg_samples=K, sampler="log_uniform", seed=3))
    assert np.isfinite(got2).all()


def test_polygon_box_transform():
    x = _randn(1, 4, 2, 3)
    got = _np(V.polygon_box_transform(paddle.to_tensor(x)))
    for c in range(4):
        for h in range(2):
            for w in range(3):
                exp = (4 * w - x[0, c, h, w]) if c % 2 == 0 else (4 * h - x[0, c, h, w])
                assert abs(got[0, c, h, w] - exp) < 1e-5


def test_mine_hard_examples_max_negative():
    cls = np.array([[0.5, 0.9, 0.1, 0.7, 0.3]], np.float32)
    mi = np.array([[2, -1, -1, -1, -1]], np.int64)
    md = np.array([[0.8, 0.1, 0.2, 0.9, 0.3]], np.float32)
    neg, upd = V.mine_hard_examples(cls, mi, md, neg_pos_ratio=2.0,
                                    neg_dist_threshold=0.5)
    # eligible: priors 1, 2, 4 (unmatched, dist<0.5); cap = 1 pos * 2 = 2
    # top-2 by cls_loss: 1 (0.9), 4 (0.3)
    np.testing.assert_allclose(_np(neg[0]), [1, 4])
    np.testing.assert_allclose(_np(upd), mi)  # unchanged in max_negative


def test_mine_hard_examples_hard_example():
    cls = np.array([[0.5, 0.9, 0.1]], np.float32)
    loc = np.array([[0.0, 0.0, 0.6]], np.float32)
    mi = np.array([[1, -1, 0]], np.int64)
    md = np.zeros((1, 3), np.float32)
    neg, upd = V.mine_hard_examples(cls, mi, md, loc_loss=loc,
                                    sample_size=2,
                                    mining_type="hard_example")
    # losses: [0.5, 0.9, 0.7] -> top-2 = priors 1, 2; positive 0 unselected
    # loses its match; selected negatives = [1]
    np.testing.assert_allclose(_np(neg[0]), [1])
    np.testing.assert_allclose(_np(upd), [[-1, -1, 0]])


def test_rpn_target_assign():
    anchors = np.array([[0, 0, 9, 9], [10, 10, 19, 19], [0, 0, 4, 4],
                        [30, 30, 49, 49]], np.float32)
    gts = np.array([[0, 0, 9, 9], [31, 31, 48, 48]], np.float32)
    loc, score, tbox, tlbl, biw = V.rpn_target_assign(
        None, None, anchors, gts, None, rpn_batch_size_per_im=4,
        rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
        rpn_negative_overlap=0.3, use_random=False)
    loc = _np(loc)
    lbl = _np(tlbl).ravel()
    # anchors 0 and 3 hold the per-gt max overlaps -> fg; 1 and 2 are bg/else
    assert set(loc.tolist()) == {0, 3}
    assert (lbl[:2] == 1).all()
    tbox = _np(tbox)
    # anchor 0 == gt 0: zero deltas
    row0 = tbox[list(loc).index(0)]
    np.testing.assert_allclose(row0, 0.0, atol=1e-5)
    np.testing.assert_allclose(_np(biw), 1.0)


def test_generate_proposal_labels():
    rois = np.array([[0, 0, 9, 9], [1, 1, 10, 10], [50, 50, 60, 60],
                     [30, 0, 40, 9]], np.float32)
    gts = np.array([[0, 0, 9, 9]], np.float32)
    cls = np.array([3], np.int64)
    out_rois, labels, targets, biw, bow = V.generate_proposal_labels(
        rois, cls, np.array([0], np.int64), gts,
        np.array([[100.0, 100.0, 1.0]], np.float32),
        batch_size_per_im=4, fg_fraction=0.5, fg_thresh=0.5,
        bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=5, use_random=False)
    lab = _np(labels).ravel()
    r = _np(out_rois)
    # fg: roi 0 (IoU 1 via itself...) — roi 0 == gt and appended gt both fg
    n_fg = (lab > 0).sum()
    assert n_fg >= 1 and (lab[:n_fg] == 3).all()
    # fg box targets live in class-3 slot, inside weights mark it
    t = _np(targets)
    w = _np(biw)
    assert t.shape == (len(lab), 20)
    assert w[0, 12:16].sum() == 4.0 and w[0, :12].sum() == 0.0
    # bg rows have zero weights everywhere
    assert w[lab == 0].sum() == 0.0


def test_box_decoder_and_assign():
    pb = np.array([[0, 0, 9, 9], [10, 10, 29, 19]], np.float32)
    pv = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    tb = rng.randn(2, 3 * 4).astype(np.float32) * 0.5
    sc = np.array([[0.8, 0.1, 0.7], [0.2, 0.9, 0.3]], np.float32)
    db, ab = V.box_decoder_and_assign(pb, pv, tb, sc, box_clip=4.135)
    db, ab = _np(db), _np(ab)
    # loop-port of the reference kernel
    for i in range(2):
        pw = pb[i, 2] - pb[i, 0] + 1
        ph = pb[i, 3] - pb[i, 1] + 1
        pcx, pcy = pb[i, 0] + pw / 2, pb[i, 1] + ph / 2
        for j in range(3):
            o = j * 4
            dw = min(pv[2] * tb[i, o + 2], 4.135)
            dh = min(pv[3] * tb[i, o + 3], 4.135)
            cx = pv[0] * tb[i, o] * pw + pcx
            cy = pv[1] * tb[i, o + 1] * ph + pcy
            bw, bh = np.exp(dw) * pw, np.exp(dh) * ph
            exp = [cx - bw / 2, cy - bh / 2, cx + bw / 2 - 1, cy + bh / 2 - 1]
            np.testing.assert_allclose(db[i, o: o + 4], exp, rtol=1e-4)
    # assignment picks best non-background class (2 for roi0, 1 for roi1)
    np.testing.assert_allclose(ab[0], db[0, 8:12], rtol=1e-6)
    np.testing.assert_allclose(ab[1], db[1, 4:8], rtol=1e-6)


def test_tdm_child_and_sampler():
    # tree: 0 unused; 1=root(non-item, children 2,3); 2,3 leaves (items 10, 11)
    #        cols: [item_id, layer_id, ancestor_id, child0, child1]
    info = np.array([
        [0, 0, 0, 0, 0],
        [0, 0, 0, 2, 3],
        [10, 1, 1, 0, 0],
        [11, 1, 1, 0, 0],
    ], np.int64)
    child, mask = F.tdm_child(np.array([1, 2]), info, child_nums=2)
    np.testing.assert_allclose(_np(child), [[2, 3], [0, 0]])
    np.testing.assert_allclose(_np(mask), [[1, 1], [0, 0]])

    # travel paths for leaves (rows indexed by leaf id): layers = [root-level,
    # leaf-level]; layer node lists: layer0 = [1], layer1 = [2, 3]
    travel = np.zeros((4, 2), np.int64)
    travel[2] = [1, 2]
    travel[3] = [1, 3]
    layer = np.array([1, 2, 3], np.int64)
    out, lab, msk = F.tdm_sampler(np.array([2, 3]), travel, layer,
                                  neg_samples_num_list=[0, 1],
                                  layer_offset_lod=[0, 1, 3], seed=4)
    o, l, m = _np(out), _np(lab), _np(msk)
    # row 0 (leaf 2): [pos 1] [pos 2, neg 3]; row 1 (leaf 3): [1] [3, 2]
    np.testing.assert_allclose(o[0], [1, 2, 3])
    np.testing.assert_allclose(o[1], [1, 3, 2])
    np.testing.assert_allclose(l, [[1, 1, 0], [1, 1, 0]])
    np.testing.assert_allclose(m, 1)


def test_match_matrix_tensor():
    B, Lx, Ly, D1, D2, T = 2, 3, 4, 5, 6, 2
    x = _randn(B, Lx, D1)
    y = _randn(B, Ly, D2)
    w = _randn(D1, T, D2)
    lx = np.array([3, 2])
    ly = np.array([4, 1])
    got = _np(F.match_matrix_tensor(paddle.to_tensor(x), paddle.to_tensor(y),
                                    paddle.to_tensor(w), lx, ly, dim_t=T))
    assert got.shape == (B, T, Lx, Ly)
    for b in range(B):
        for t in range(T):
            exp = x[b] @ w[:, t, :] @ y[b].T
            exp[lx[b]:, :] = 0
            exp[:, ly[b]:] = 0
            np.testing.assert_allclose(got[b, t], exp, rtol=1e-4, atol=1e-5)
    # grads flow through all three inputs
    xt, yt, wt = (paddle.to_tensor(v) for v in (x, y, w))
    for t in (xt, yt, wt):
        t.stop_gradient = False
    F.match_matrix_tensor(xt, yt, wt, lx, ly, dim_t=T).sum().backward()
    for t in (xt, yt, wt):
        assert np.abs(_np(t.grad)).sum() > 0


def test_prroi_pool():
    # constant feature map -> every bin averages to the constant
    x = np.full((1, 2, 6, 6), 4.0, np.float32)
    # interior roi (within pixel centers [0, 5]): bilinear surface is exactly
    # constant there; outside the centers the interpolant decays to zero
    # (zero-padding convention of the original PrRoI pooling)
    rois = np.array([[0.7, 0.9, 4.3, 4.9]], np.float32)
    got = _np(V.prroi_pool(paddle.to_tensor(x), paddle.to_tensor(rois),
                           paddle.to_tensor(np.array([1], np.int32)), 2))
    np.testing.assert_allclose(got, 4.0, rtol=1e-4)
    # linear ramp f(x, y) = x: bin average == analytic mean of x over the bin
    ramp = np.tile(np.arange(6, dtype=np.float32)[None, :], (6, 1))
    xr = ramp[None, None]
    rois2 = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
    got2 = _np(V.prroi_pool(paddle.to_tensor(xr), paddle.to_tensor(rois2),
                            paddle.to_tensor(np.array([1], np.int32)), 2))
    # bins split x-range [1, 5] into [1, 3] and [3, 5]: means 2 and 4
    np.testing.assert_allclose(got2[0, 0, :, 0], [2.0, 2.0], rtol=1e-4)
    np.testing.assert_allclose(got2[0, 0, :, 1], [4.0, 4.0], rtol=1e-4)
    # differentiable wrt features
    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    V.prroi_pool(xt, paddle.to_tensor(rois),
                 paddle.to_tensor(np.array([1], np.int32)), 2).sum().backward()
    assert np.abs(_np(xt.grad)).sum() > 0


def test_locality_aware_nms():
    # three near-identical boxes in sequence + one far box: the run of three
    # merges into one score-weighted box; far box survives separately
    boxes = np.array([[[0, 0, 10, 10], [0.2, 0.2, 10.2, 10.2],
                       [0.1, 0.1, 10.1, 10.1], [50, 50, 60, 60]]], np.float32)
    scores = np.zeros((1, 1, 4), np.float32)
    scores[0, 0] = [0.5, 0.3, 0.2, 0.9]
    out, num = V.locality_aware_nms(paddle.to_tensor(boxes),
                                    paddle.to_tensor(scores),
                                    score_threshold=0.1, nms_top_k=10,
                                    keep_top_k=5, nms_threshold=0.5)
    o = _np(out)[0]
    assert int(_np(num)[0]) == 2
    # merged box score = 0.5+0.3+0.2 = 1.0 (tops the far box's 0.9)
    np.testing.assert_allclose(o[0, 1], 1.0, rtol=1e-5)
    np.testing.assert_allclose(o[1, 1], 0.9, rtol=1e-5)
    # merged coords = weighted average, near [0.1, 0.1, 10.1, 10.1]
    assert abs(o[0, 2] - 0.11) < 0.1 and abs(o[0, 5] - 10.1) < 0.15


def test_retinanet_detection_output():
    # one level, two anchors, two classes; zero deltas decode to the anchors
    anchors = np.array([[0, 0, 9, 9], [20, 20, 39, 39]], np.float32)
    deltas = np.zeros((2, 4), np.float32)
    scores = np.array([[0.9, 0.1], [0.05, 0.8]], np.float32)
    out, num = V.retinanet_detection_output(
        [deltas], [scores], [anchors],
        np.array([100.0, 100.0, 1.0], np.float32),
        score_threshold=0.3, keep_top_k=5, nms_threshold=0.5)
    o = _np(out)
    # last level thresholds at 0.0, so ALL 4 (anchor, class) pairs become
    # candidates; per-class NMS keeps the best per location -> 4 entries but
    # the two high-score ones lead
    assert int(_np(num)[0]) >= 2
    assert o[0, 1] == pytest.approx(0.9) and o[0, 0] == 0
    np.testing.assert_allclose(o[0, 2:], [0, 0, 9, 9], atol=1e-4)
    assert o[1, 1] == pytest.approx(0.8) and o[1, 0] == 1
    np.testing.assert_allclose(o[1, 2:], [20, 20, 39, 39], atol=1e-4)


def test_roi_perspective_transform():
    # axis-aligned quad == plain crop+resize of that rectangle
    H = W = 8
    x = np.arange(H * W, dtype=np.float32).reshape(1, 1, H, W)
    # quad corners (x0,y0)=(1,1) top-left, (6,1), (6,5), (1,5) — reference
    # order: 0-1 top edge, 1-2 right edge
    quad = np.array([[1, 1, 6, 1, 6, 5, 1, 5]], np.float32)
    out, mask, mat = V.roi_perspective_transform(
        paddle.to_tensor(x), paddle.to_tensor(quad), 5, 6)
    o = _np(out)
    assert o.shape == (1, 1, 5, 6)
    # corner (0, 0) of the output maps exactly to the quad's first corner
    np.testing.assert_allclose(o[0, 0, 0, 0], x[0, 0, 1, 1], rtol=1e-4)
    # output is monotone along rows (sampling a monotone ramp)
    assert (np.diff(o[0, 0, 0, :]) >= -1e-3).all()
    np.testing.assert_allclose(_np(mask)[0, 0], 1)
    # grad flows to the feature map
    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    o2, _, _ = V.roi_perspective_transform(xt, paddle.to_tensor(quad), 5, 6)
    o2.sum().backward()
    assert np.abs(_np(xt.grad)).sum() > 0


def test_similarity_focus():
    x = np.zeros((1, 2, 3, 3), np.float32)
    x[0, 0] = [[9, 1, 1], [1, 5, 1], [1, 1, 7]]   # maxima on the diagonal
    x[0, 1] = rng.rand(3, 3)
    got = _np(F.similarity_focus(paddle.to_tensor(x), axis=1, indexes=[0]))
    # output IS the broadcast 0/1 mask (reference writes 1s, never gates x):
    # identity pattern (picks (0,0)=9 then (2,2)=7 then (1,1)=5)
    exp_mask = np.eye(3, dtype=np.float32)
    np.testing.assert_allclose(got[0, 0], exp_mask, rtol=1e-6)
    np.testing.assert_allclose(got[0, 1], exp_mask, rtol=1e-6)


def test_var_conv_2d():
    B, C, H, W, CO = 2, 2, 6, 6, 3
    x = _randn(B, C, H, W)
    w = _randn(CO, C * 3 * 3)
    rl = np.array([6, 3])
    cl = np.array([6, 4])
    got = _np(F.var_conv_2d(paddle.to_tensor(x), rl, cl, paddle.to_tensor(w),
                            C, CO, 3))
    import jax, jax.numpy as jnp
    # sample 0 (full size) matches a plain conv
    full = np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(x[:1]), jnp.asarray(w.reshape(CO, C, 3, 3)), (1, 1),
        [(1, 1), (1, 1)], dimension_numbers=("NCHW", "OIHW", "NCHW")))
    np.testing.assert_allclose(got[0], full[0], rtol=1e-4, atol=1e-5)
    # sample 1: outputs beyond its valid region are zero
    np.testing.assert_allclose(got[1, :, 3:, :], 0.0)
    np.testing.assert_allclose(got[1, :, :, 4:], 0.0)


def test_retinanet_target_assign():
    anchors = np.array([[0, 0, 9, 9], [10, 10, 19, 19], [30, 30, 49, 49],
                        [100, 100, 109, 109]], np.float32)
    gts = np.array([[0, 0, 9, 9], [31, 31, 48, 48]], np.float32)
    labs = np.array([3, 7], np.int64)
    loc, score, tbox, tlbl, biw, fg_num = V.retinanet_target_assign(
        None, None, anchors, None, gts, labs, np.array([0, 0], np.int64),
        None, positive_overlap=0.5, negative_overlap=0.4)
    loc = _np(loc)
    lbl = _np(tlbl).ravel()
    # anchors 0 and 2 are fg (hold per-gt maxima); labels carry gt classes
    assert set(loc.tolist()) == {0, 2}
    assert set(lbl[:2].tolist()) == {3, 7}
    # all remaining anchors are bg with label 0 (no subsampling)
    assert (lbl[2:] == 0).all() and len(lbl) == 4
    assert int(_np(fg_num)[0]) == 3  # fg + 1
    row0 = _np(tbox)[list(loc).index(0)]
    np.testing.assert_allclose(row0, 0.0, atol=1e-5)


def test_tree_conv():
    # tree: 1 -> (2, 3); features one-hot per node
    edges = np.array([[1, 2], [1, 3], [0, 0]], np.int32)
    feats = np.eye(3, dtype=np.float32)          # node i-1 -> e_i
    F_, O, M = 3, 2, 1
    w = rng.randn(F_, 3, O, M).astype(np.float32)
    got = _np(F.tree_conv(paddle.to_tensor(feats), edges, O, M, max_depth=2,
                          act=None, filter=paddle.to_tensor(w)))
    assert got.shape == (3, O, M)
    # manual: patch for root 1 = {1 (d0), 2 (idx1, len2, d1), 3 (idx2, len2, d1)}
    d = 2.0
    def etas(index, pclen, depth):
        et = (d - depth) / d
        tmp = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
        el = (1 - et) * tmp
        er = (1 - et) * (1 - el)
        return el, er, et
    patch = np.zeros((F_, 3), np.float32)
    for node, (i_, p_, dep) in [(1, (1, 1, 0)), (2, (1, 2, 1)), (3, (2, 2, 1))]:
        el, er, et = etas(i_, p_, dep)
        patch[node - 1] += np.array([el, er, et]) * 1.0  # one-hot features
    exp0 = patch.reshape(-1) @ w.reshape(3 * F_, O * M)
    np.testing.assert_allclose(got[0].reshape(-1), exp0, rtol=1e-4)
    # leaves' patches contain only themselves (depth cap): eta_t = 1
    exp1 = (np.eye(3)[1][:, None] * np.array([0.0, 0.0, 1.0])[None, :]
            * np.array([0.5, (1 - 0.0), 1.0])[None, :] * 0 + 0)
    # simpler: node 2's patch = {(2, idx1, len1, d0)} -> etas (0.5*0, ..., 1)
    el, er, et = etas(1, 1, 0)
    p2 = np.zeros((F_, 3), np.float32)
    p2[1] = [el, er, et]
    np.testing.assert_allclose(got[1].reshape(-1),
                               p2.reshape(-1) @ w.reshape(3 * F_, O * M),
                               rtol=1e-4)


def test_correlation():
    N, C, H, W = 1, 2, 6, 6
    x = _randn(N, C, H, W)
    y = _randn(N, C, H, W)
    got = _np(F.correlation(paddle.to_tensor(x), paddle.to_tensor(y),
                            pad_size=2, kernel_size=1, max_displacement=2,
                            stride1=1, stride2=2))
    drad, D = 1, 3
    assert got.shape[1] == D * D
    # loop-port of the CUDA kernel for a couple of positions
    xp = np.pad(x, ((0, 0), (0, 0), (2, 2), (2, 2)))
    yp = np.pad(y, ((0, 0), (0, 0), (2, 2), (2, 2)))
    for (tj, ti, oy, ox) in [(0, 0, 1, 1), (1, -1, 2, 3)]:
        h1, w1 = 2 + oy, 2 + ox
        h2, w2 = h1 + tj * 2, w1 + ti * 2
        exp = (xp[0, :, h1, w1] * yp[0, :, h2, w2]).sum() / C
        tc = (tj + drad) * D + (ti + drad)
        np.testing.assert_allclose(got[0, tc, oy, ox], exp, rtol=1e-4)


def test_deformable_psroi_pooling():
    # zero offsets + group 1x1 degenerates to average pooling of the bin
    C = 2
    x = np.full((1, C, 8, 8), 5.0, np.float32)
    rois = np.array([[0, 0, 7, 7]], np.float32)
    tr = np.zeros((1, 2, 2, 2), np.float32)
    got = _np(V.deformable_psroi_pooling(
        paddle.to_tensor(x), paddle.to_tensor(rois), paddle.to_tensor(tr),
        spatial_scale=1.0, group_size=(1, 1), pooled_height=2, pooled_width=2,
        sample_per_part=2, position_sensitive=False))
    assert got.shape == (1, C, 2, 2)
    np.testing.assert_allclose(got, 5.0, rtol=1e-4)
    # nonzero offset shifts sampling: ramp feature changes the bin mean
    ramp = np.tile(np.arange(8, dtype=np.float32)[None, :], (8, 1))[None, None]
    tr2 = np.zeros((1, 2, 2, 2), np.float32)
    tr2[0, 0] = 1.0  # x-offset of trans_std * roi_w
    base = _np(V.deformable_psroi_pooling(
        paddle.to_tensor(ramp), paddle.to_tensor(rois), paddle.to_tensor(tr),
        pooled_height=2, pooled_width=2, sample_per_part=2,
        position_sensitive=False))
    shifted = _np(V.deformable_psroi_pooling(
        paddle.to_tensor(ramp), paddle.to_tensor(rois), paddle.to_tensor(tr2),
        pooled_height=2, pooled_width=2, sample_per_part=2, trans_std=0.2,
        position_sensitive=False))
    assert (shifted[0, 0] > base[0, 0] - 1e-6).all()
    assert shifted[0, 0, 0, 0] > base[0, 0, 0, 0] + 0.5
    # grads flow to features and offsets
    xt = paddle.to_tensor(ramp)
    tt = paddle.to_tensor(tr2)
    xt.stop_gradient = False
    tt.stop_gradient = False
    V.deformable_psroi_pooling(xt, paddle.to_tensor(rois), tt,
                               pooled_height=2, pooled_width=2,
                               sample_per_part=2, trans_std=0.2,
                               position_sensitive=False).sum().backward()
    assert np.abs(_np(xt.grad)).sum() > 0
    assert np.abs(_np(tt.grad)).sum() > 0


def test_generate_mask_labels():
    # one gt: a square polygon covering the left half of its box
    gt_segms = [[[0, 0, 4, 0, 4, 8, 0, 8]]]
    rois = np.array([[0, 0, 8, 8], [20, 20, 30, 30]], np.float32)
    labels = np.array([2, 0], np.int64)  # roi 0 fg class 2, roi 1 bg
    mask_rois, has_mask, mask = V.generate_mask_labels(
        np.array([[8.0, 8.0, 1.0]], np.float32), np.array([2], np.int64),
        np.array([0], np.int64), gt_segms, rois, labels,
        num_classes=4, resolution=4)
    m = _np(mask)
    assert m.shape == (1, 4 * 16)
    grid = m[0, 2 * 16:3 * 16].reshape(4, 4)
    # left half of the roi is inside the polygon
    np.testing.assert_allclose(grid[:, :2], 1)
    np.testing.assert_allclose(grid[:, 2:], 0)
    # other class slots stay -1
    assert (m[0, :2 * 16] == -1).all() and (m[0, 3 * 16:] == -1).all()
    np.testing.assert_allclose(_np(has_mask).ravel(), [0])


def test_bilateral_slice():
    # constant identity grid: out = a*x + b with a=2, b=0.5 everywhere
    N, Ci, Co, H, W = 1, 1, 1, 4, 4
    gd, gh, gw = 2, 2, 2
    grid = np.zeros((N, (Ci + 1) * Co, gd, gh, gw), np.float32)
    grid[:, 0] = 2.0   # multiplier on x
    grid[:, 1] = 0.5   # offset row
    x = _randn(N, Ci, H, W)
    guide = np.full((N, H, W), 0.5, np.float32)
    got = _np(F.bilateral_slice(paddle.to_tensor(x), paddle.to_tensor(guide),
                                paddle.to_tensor(grid), has_offset=True))
    np.testing.assert_allclose(got, 2.0 * x + 0.5, rtol=1e-4)
    # grads flow to input, guide, grid
    xt, gt_, grt = (paddle.to_tensor(v) for v in (x, guide, grid))
    for t in (xt, gt_, grt):
        t.stop_gradient = False
    F.bilateral_slice(xt, gt_, grt, has_offset=True).sum().backward()
    for t in (xt, grt):
        assert np.abs(_np(t.grad)).sum() > 0


def test_correlation_kernel3():
    # kernel_size=3: border = max_disp + 1; loop-port check incl. zero padding
    N, C, H, W = 1, 2, 8, 8
    x = _randn(N, C, H, W)
    y = _randn(N, C, H, W)
    pad, ks, md, s1, s2 = 3, 3, 2, 1, 2
    got = _np(F.correlation(paddle.to_tensor(x), paddle.to_tensor(y),
                            pad_size=pad, kernel_size=ks, max_displacement=md,
                            stride1=s1, stride2=s2))
    kr = 1
    border = md + kr
    Hp = H + 2 * pad
    Ho = int(np.ceil((Hp - 2 * border) / s1))
    assert got.shape == (N, 9, Ho, Ho)
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    yp = np.pad(y, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    nelems = ks * ks * C
    for (tj, ti, oy, ox) in [(0, 0, 0, 0), (-1, 1, 2, 1), (1, -1, Ho - 1, 3)]:
        h1, w1 = border + oy * s1, border + ox * s1
        h2, w2 = h1 + tj * s2, w1 + ti * s2
        exp = 0.0
        for j in range(-kr, kr + 1):
            for i in range(-kr, kr + 1):
                exp += (xp[0, :, h1 + j, w1 + i] * yp[0, :, h2 + j, w2 + i]).sum()
        tc = (tj + 1) * 3 + (ti + 1)
        np.testing.assert_allclose(got[0, tc, oy, ox], exp / nelems, rtol=1e-4)



def test_tree_conv_batched_tanh_default():
    edges = np.array([[[1, 2], [1, 3], [0, 0]]] * 2, np.int32)
    feats = np.stack([np.eye(3, dtype=np.float32)] * 2)
    w = rng.randn(3, 3, 2, 1).astype(np.float32)
    got = _np(F.tree_conv(paddle.to_tensor(feats), edges, 2, 1, max_depth=2,
                          filter=paddle.to_tensor(w)))
    assert got.shape == (2, 3, 2, 1)
    # default act is tanh (fluid.contrib parity)
    raw = _np(F.tree_conv(paddle.to_tensor(feats[0]), edges[0], 2, 1,
                          max_depth=2, act=None, filter=paddle.to_tensor(w)))
    np.testing.assert_allclose(got[0], np.tanh(raw), rtol=1e-5)
    np.testing.assert_allclose(got[0], got[1], rtol=1e-6)


def test_sequence_family_jit_parity():
    """The padded+length design's point: every sequence op also jits."""
    import jax

    x = _randn(2, 6, 3)
    ln = np.array([6, 4])
    cases = [
        lambda xv, lv: F.sequence_pool(xv, lv, "sum"),
        lambda xv, lv: F.sequence_pool(xv, lv, "max"),
        lambda xv, lv: F.sequence_reverse(xv, lv),
        lambda xv, lv: F.sequence_expand(xv, lv, lv),
    ]
    for op in cases:
        eager = _np(op(paddle.to_tensor(x), paddle.to_tensor(ln)))

        def raw(a, b):
            return op(paddle.to_tensor(a), paddle.to_tensor(b))._data

        jitted = np.asarray(jax.jit(raw)(x, ln))
        np.testing.assert_allclose(eager, jitted, rtol=1e-5)
    # sequence_softmax (2-D) as well
    s = _randn(2, 6)
    eager = _np(F.sequence_softmax(paddle.to_tensor(s), paddle.to_tensor(ln)))
    jitted = np.asarray(jax.jit(
        lambda a, b: F.sequence_softmax(paddle.to_tensor(a),
                                        paddle.to_tensor(b))._data)(s, ln))
    np.testing.assert_allclose(eager, jitted, rtol=1e-5)


def test_teacher_student_loss_grad_clamps_at_bounds():
    """ADVICE r2: reference grad kernel zeroes dx outside the soft_max
    bounds; forward value stays unclamped."""
    # click + teacher 0.5: loss = 2*softplus(x) - 1.5x, grad = 2*sigmoid(x)
    # - 1.5, which is 0.5 at x=+20 UNLESS the bound clamp zeroes it
    x = np.array([0.5, 20.0, -20.0], np.float32)
    y = np.array([1.5, 1.5, 1.5], np.float32)
    xt = paddle.to_tensor(x); xt.stop_gradient = False
    out = F.teacher_student_sigmoid_loss(xt, paddle.to_tensor(y),
                                         soft_max_up_bound=15.0,
                                         soft_max_lower_bound=-15.0)
    out.sum().backward()
    g = _np(xt.grad)
    np.testing.assert_allclose(g[0], 2 / (1 + np.exp(-0.5)) - 1.5, atol=1e-5)
    np.testing.assert_allclose(g[1:], 0.0, atol=1e-7)  # outside bounds: dx=0
    # forward keeps the UNCLAMPED value: 2*softplus(20) - 1.5*20 = 10
    np.testing.assert_allclose(_np(out)[1], 10.0, atol=1e-3)


def test_cross_entropy_returns_input_dtype():
    """ADVICE r2: bf16 logits -> bf16 loss (fp32 accumulation inside)."""
    import ml_dtypes

    logits = paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(ml_dtypes.bfloat16))
    label = paddle.to_tensor(np.array([1, 2, 3, 4], np.int64))
    for reduction in ("mean", "none", "sum"):
        out = F.cross_entropy(logits, label, reduction=reduction)
        assert np.asarray(out._data).dtype == ml_dtypes.bfloat16, reduction
    f32 = F.cross_entropy(paddle.to_tensor(
        np.random.RandomState(0).randn(4, 8).astype(np.float32)), label)
    assert np.asarray(f32._data).dtype == np.float32


def test_sequence_expand_rejects_overlong_ref():
    """ADVICE r2: ref_length > padded T raises instead of truncating."""
    import pytest

    x = paddle.to_tensor(np.ones((2, 3, 4), np.float32))
    lx = paddle.to_tensor(np.array([3, 2], np.int64))
    with pytest.raises(ValueError, match="exceeds x's padded length"):
        F.sequence_expand(x, lx, paddle.to_tensor(np.array([5, 2], np.int64)))
    out = F.sequence_expand(x, lx, paddle.to_tensor(np.array([3, 3], np.int64)))
    assert _np(out).shape == (2, 3, 4)
