"""Book tests — small models trained to convergence thresholds.

Reference parity: fluid/tests/book/ (test_fit_a_line.py, test_word2vec_book.py,
test_recognize_digits.py) — the reference gates on reaching a loss/accuracy
threshold, not just 'loss went down'."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


class TestFitALine:
    def test_linear_regression_converges(self):
        """fit_a_line: recover a known linear map to tight MSE."""
        paddle.seed(0)
        rng = np.random.RandomState(0)
        true_w = rng.randn(13, 1).astype(np.float32)
        X = rng.randn(256, 13).astype(np.float32)
        Y = X @ true_w + 0.01 * rng.randn(256, 1).astype(np.float32)

        net = nn.Linear(13, 1)
        opt = paddle.optimizer.Adam(learning_rate=0.1,
                                    parameters=net.parameters())
        xs, ys = paddle.to_tensor(X), paddle.to_tensor(Y)
        loss_val = None
        for _ in range(150):
            loss = F.mse_loss(net(xs), ys)
            loss.backward()
            opt.step()
            opt.clear_grad()
            loss_val = float(np.asarray(loss._data))
        assert loss_val < 1e-2, f"fit_a_line failed to converge: {loss_val}"
        w = np.asarray(net.weight._data)
        np.testing.assert_allclose(w, true_w, atol=0.05)


class TestWord2Vec:
    def test_skipgram_embeddings_learn_cooccurrence(self):
        """word2vec book test: after training on a deterministic corpus,
        words that co-occur score higher than words that never do."""
        paddle.seed(0)
        V, D = 20, 8
        rng = np.random.RandomState(1)
        # synthetic corpus: word 2i and 2i+1 always co-occur
        centers, contexts = [], []
        for _ in range(400):
            i = rng.randint(0, V // 2)
            centers.append(2 * i)
            contexts.append(2 * i + 1)
        centers = np.asarray(centers, np.int64)
        contexts = np.asarray(contexts, np.int64)

        emb_in = nn.Embedding(V, D)
        emb_out = nn.Embedding(V, D)
        opt = paddle.optimizer.Adam(
            learning_rate=0.05,
            parameters=list(emb_in.parameters()) + list(emb_out.parameters()))

        for start in range(0, 400, 100):
            for _ in range(10):
                c = paddle.to_tensor(centers[start:start + 100])
                o = paddle.to_tensor(contexts[start:start + 100])
                h = emb_in(c)                      # [B, D]
                logits = paddle.matmul(h, emb_out.weight, transpose_y=True)
                loss = F.cross_entropy(logits, o)
                loss.backward()
                opt.step()
                opt.clear_grad()

        wi = np.asarray(emb_in.weight._data)
        wo = np.asarray(emb_out.weight._data)
        scores = wi @ wo.T                        # [V, V]
        # each even word must rank its partner top-1 among all words
        correct = sum(int(scores[2 * i].argmax()) == 2 * i + 1
                      for i in range(V // 2))
        assert correct >= V // 2 - 1, f"only {correct}/{V//2} pairs learned"


class TestRecognizeDigits:
    def test_mlp_reaches_accuracy_threshold(self):
        """recognize_digits: blobby synthetic 'digits' to >=90% train accuracy
        via the high-level Model API."""
        paddle.seed(0)
        rng = np.random.RandomState(2)
        n, n_cls = 256, 10
        protos = rng.randn(n_cls, 64).astype(np.float32) * 2
        labels = rng.randint(0, n_cls, n).astype(np.int64)
        X = protos[labels] + 0.3 * rng.randn(n, 64).astype(np.float32)

        class DS(paddle.io.Dataset):
            def __len__(self):
                return n

            def __getitem__(self, i):
                return X[i], labels[i:i + 1]

        net = nn.Sequential(nn.Linear(64, 32), nn.ReLU(), nn.Linear(32, n_cls))
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.Adam(learning_rate=1e-2,
                                  parameters=net.parameters()),
            nn.CrossEntropyLoss(), paddle.metric.Accuracy())
        model.fit(DS(), epochs=5, batch_size=64, verbose=0)
        result = model.evaluate(DS(), batch_size=64, verbose=0)
        acc = result["acc"] if isinstance(result, dict) else result[-1]
        acc = float(acc[0] if isinstance(acc, (list, tuple)) else acc)
        assert acc >= 0.9, f"digit accuracy {acc} < 0.9"
