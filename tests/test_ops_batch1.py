"""Op-test burn-down, batch 1: elementwise / reduce / manipulation / activation /
loss ops against numpy references with numeric gradient checks (SURVEY §4 —
the reference's 1005-file op_test suite, table-driven here)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

from op_test import OpTest

rng = np.random.RandomState(7)


def _pos(*shape):
    return (rng.rand(*shape) + 0.5).astype(np.float32)


def _randn(*shape):
    return rng.randn(*shape).astype(np.float32)


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


X = _randn(3, 4)
Y = _randn(3, 4)
P = _pos(3, 4)
V6 = _randn(6)

# (id, op, inputs, attrs, expected outputs, grad_inputs or None)
CASES = [
    ("add", paddle.add, {"x": X, "y": Y}, {}, [X + Y], ["x", "y"]),
    ("subtract", paddle.subtract, {"x": X, "y": Y}, {}, [X - Y], ["x", "y"]),
    ("multiply", paddle.multiply, {"x": X, "y": Y}, {}, [X * Y], ["x", "y"]),
    ("divide", paddle.divide, {"x": X, "y": P}, {}, [X / P], ["x", "y"]),
    ("pow", paddle.pow, {"x": P}, {"y": 3.0}, [P ** 3.0], ["x"]),
    ("exp", paddle.exp, {"x": X}, {}, [np.exp(X)], ["x"]),
    ("log", paddle.log, {"x": P}, {}, [np.log(P)], ["x"]),
    ("sqrt", paddle.sqrt, {"x": P}, {}, [np.sqrt(P)], ["x"]),
    ("rsqrt", paddle.rsqrt, {"x": P}, {}, [1 / np.sqrt(P)], ["x"]),
    ("abs", paddle.abs, {"x": X + 0.3}, {}, [np.abs(X + 0.3)], ["x"]),
    ("tanh", paddle.tanh, {"x": X}, {}, [np.tanh(X)], ["x"]),
    ("maximum", paddle.maximum, {"x": X, "y": Y}, {}, [np.maximum(X, Y)], None),
    ("minimum", paddle.minimum, {"x": X, "y": Y}, {}, [np.minimum(X, Y)], None),
    ("clip", paddle.clip, {"x": X}, {"min": -0.5, "max": 0.5},
     [np.clip(X, -0.5, 0.5)], None),
    ("floor", paddle.floor, {"x": X * 3}, {}, [np.floor(X * 3)], None),
    ("ceil", paddle.ceil, {"x": X * 3}, {}, [np.ceil(X * 3)], None),
    ("round", paddle.round, {"x": X * 3}, {}, [np.round(X * 3)], None),
    ("sign", paddle.sign, {"x": X}, {}, [np.sign(X)], None),
    ("reciprocal", paddle.reciprocal, {"x": P}, {}, [1 / P], ["x"]),
    ("square", paddle.square, {"x": X}, {}, [X * X], ["x"]),
    # reductions
    ("mean", paddle.mean, {"x": X}, {}, [X.mean()], ["x"]),
    ("sum", paddle.sum, {"x": X}, {"axis": 1}, [X.sum(1)], ["x"]),
    ("max", paddle.max, {"x": X}, {"axis": 0}, [X.max(0)], None),
    ("min", paddle.min, {"x": X}, {"axis": 0}, [X.min(0)], None),
    ("prod", paddle.prod, {"x": P}, {"axis": 1}, [P.prod(1)], ["x"]),
    ("logsumexp", paddle.logsumexp, {"x": X}, {"axis": 1},
     [np.log(np.exp(X).sum(1))], ["x"]),
    # linalg
    ("matmul", paddle.matmul, {"x": _randn(3, 4), "y": _randn(4, 2)}, {},
     None, ["x", "y"]),
    ("matmul_tx", paddle.matmul, {"x": _randn(4, 3), "y": _randn(4, 2)},
     {"transpose_x": True}, None, ["x", "y"]),
    ("dot", paddle.dot, {"x": V6, "y": _randn(6)}, {}, None, ["x", "y"]),
    ("t", paddle.t, {"x": X}, {}, [X.T], ["x"]),
    # manipulation
    ("reshape", paddle.reshape, {"x": X}, {"shape": [4, 3]},
     [X.reshape(4, 3)], ["x"]),
    ("transpose", paddle.transpose, {"x": X}, {"perm": [1, 0]}, [X.T], ["x"]),
    ("squeeze", paddle.squeeze, {"x": X[None]}, {"axis": 0}, [X], None),
    ("unsqueeze", paddle.unsqueeze, {"x": X}, {"axis": 0}, [X[None]], None),
    ("flip", paddle.flip, {"x": X}, {"axis": [0]}, [X[::-1]], None),
    ("roll", paddle.roll, {"x": V6}, {"shifts": 2}, [np.roll(V6, 2)], None),
    ("cumsum", paddle.cumsum, {"x": X}, {"axis": 1}, [X.cumsum(1)], ["x"]),
    ("cumprod", paddle.cumprod, {"x": P}, {"dim": 1}, [P.cumprod(1)], ["x"]),
    ("tile", paddle.tile, {"x": X}, {"repeat_times": [2, 1]},
     [np.tile(X, (2, 1))], None),
    ("expand", paddle.expand, {"x": _randn(1, 4)}, {"shape": [3, 4]}, None,
     None),
    # activations
    ("relu", F.relu, {"x": X}, {}, [np.maximum(X, 0)], None),
    ("sigmoid", F.sigmoid, {"x": X}, {}, [1 / (1 + np.exp(-X))], ["x"]),
    ("softmax", F.softmax, {"x": X}, {"axis": -1}, [_softmax_np(X)], ["x"]),
    ("log_softmax", F.log_softmax, {"x": X}, {"axis": -1},
     [np.log(_softmax_np(X))], ["x"]),
    ("elu", F.elu, {"x": X}, {"alpha": 1.0},
     [np.where(X > 0, X, np.exp(X) - 1)], None),
    ("softplus", F.softplus, {"x": X}, {}, [np.log1p(np.exp(X))], ["x"]),
    ("hardtanh", F.hardtanh, {"x": X * 2}, {}, [np.clip(X * 2, -1, 1)], None),
    ("leaky_relu", F.leaky_relu, {"x": X}, {"negative_slope": 0.1},
     [np.where(X > 0, X, 0.1 * X)], None),
    ("gelu", F.gelu, {"x": X}, {}, None, ["x"]),
    ("silu", F.silu, {"x": X}, {}, [X / (1 + np.exp(-X))], ["x"]),
    # losses
    ("mse_loss", F.mse_loss, {"input": X, "label": Y}, {},
     [((X - Y) ** 2).mean()], ["input"]),
    ("l1_loss", F.l1_loss, {"input": X, "label": Y}, {},
     [np.abs(X - Y).mean()], None),
    ("log_loss", F.log_loss, {"input": _pos(4, 1) / 2, "label": _pos(4, 1) / 2},
     {}, None, ["input"]),
]


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op(case):
    name, op, inputs, attrs, outputs, grad_inputs = case

    t = OpTest()
    t.op = op
    t.inputs = inputs
    t.attrs = attrs
    if outputs is None:
        # reference computed by the op itself in f64-ish sanity mode: only
        # grad-check these (they're jnp-backed; output equality is circular)
        t.outputs = None
    else:
        t.outputs = outputs

    if outputs is not None:
        t.check_output(atol=1e-4, rtol=1e-4)
    if grad_inputs:
        t.check_grad(grad_inputs)


class TestCrossEntropyOp(OpTest):
    def setUp(self):
        logits = _randn(4, 5)
        labels = np.array([0, 2, 4, 1], np.int64)
        self.op = lambda x: F.cross_entropy(x, paddle.to_tensor(labels))
        self.inputs = {"x": logits}
        p = _softmax_np(logits)
        self.outputs = [np.mean([-np.log(p[i, labels[i]]) for i in range(4)])]

    def test(self):
        self.check_output(atol=1e-5)
        self.check_grad(["x"])


class TestLayerNormOp(OpTest):
    def setUp(self):
        x = _randn(2, 8)
        self.op = lambda x: F.layer_norm(x, 8)
        self.inputs = {"x": x}
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        self.outputs = [(x - mu) / np.sqrt(var + 1e-5)]

    def test(self):
        self.check_output(atol=1e-4, rtol=1e-3)
        self.check_grad(["x"], atol=5e-3, rtol=5e-2)


class TestConv2DOp(OpTest):
    def setUp(self):
        x = _randn(1, 2, 5, 5)
        w = _randn(3, 2, 3, 3)
        self.op = lambda x, w: F.conv2d(x, w, stride=1, padding=1)
        self.inputs = {"x": x, "w": w}
        # direct numpy convolution reference
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        out = np.zeros((1, 3, 5, 5), np.float32)
        for co in range(3):
            for i in range(5):
                for j in range(5):
                    out[0, co, i, j] = np.sum(
                        xp[0, :, i:i + 3, j:j + 3] * w[co])
        self.outputs = [out]

    def test(self):
        self.check_output(atol=1e-4, rtol=1e-3)


class TestAvgPoolOp(OpTest):
    def setUp(self):
        x = _randn(1, 1, 4, 4)
        self.op = lambda x: F.avg_pool2d(x, kernel_size=2, stride=2)
        self.inputs = {"x": x}
        out = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
        self.outputs = [out]

    def test(self):
        self.check_output()
        self.check_grad(["x"])
