"""Model encryption (native AES-256-CTR) + VOC2012 dataset tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework.crypto import AESCipher, CipherFactory, is_encrypted


class TestAESCipher:
    def test_nist_ctr_vector(self):
        """NIST SP 800-38A F.5.5 (AES-256-CTR, first block) against the raw
        native core — proves the AES schedule/block function is real AES."""
        from paddle_tpu.framework.crypto import _ctr

        key = bytes.fromhex(
            "603deb1015ca71be2b73aef0857d77811f352c073b6108d72d9810a30914dff4")
        iv = bytes.fromhex("f0f1f2f3f4f5f6f7f8f9fafbfcfdfeff")
        pt = bytes.fromhex("6bc1bee22e409f96e93d7e117393172a")
        expect = bytes.fromhex("601ec313775789a5b7a7f504bbf3d228")
        assert _ctr(key, iv, pt) == expect

    def test_roundtrip_and_tamper_detection(self):
        c = AESCipher("my-secret-key")
        msg = b"weights" * 1000 + b"tail"
        blob = c.encrypt(msg)
        assert blob[:4] == b"PTAE"
        assert c.decrypt(blob) == msg
        # wrong key fails closed
        with pytest.raises(ValueError):
            AESCipher("other-key").decrypt(blob)
        # bit-flip fails closed
        bad = bytearray(blob)
        bad[-1] ^= 1
        with pytest.raises(ValueError):
            c.decrypt(bytes(bad))

    def test_factory_generates_working_cipher(self):
        key = CipherFactory.generate_key()
        c = CipherFactory.create_cipher(key)
        assert c.decrypt(c.encrypt(b"abc")) == b"abc"

    def test_save_load_encrypted_state_dict(self, tmp_path):
        paddle.seed(0)
        layer = paddle.nn.Linear(4, 3)
        path = str(tmp_path / "m.pdparams")
        paddle.save(layer.state_dict(), path, encryption_key="k1")
        assert is_encrypted(path)
        # load without key -> clear error; with key -> tensors restored
        with pytest.raises(ValueError):
            paddle.load(path)
        state = paddle.load(path, encryption_key="k1")
        np.testing.assert_array_equal(np.asarray(state["weight"]._data),
                                      np.asarray(layer.weight._data))


class TestVOC2012:
    def test_synthetic_segmentation_pairs(self):
        from paddle_tpu.vision.datasets import VOC2012

        ds = VOC2012(mode="train")
        assert len(ds) == 200
        img, lab = ds[0]
        assert img.shape == (3, 64, 64) and img.dtype == np.uint8
        assert lab.shape == (64, 64) and lab.dtype == np.int64
        assert 0 <= lab.min() and lab.max() <= 20
        # masks actually contain objects
        assert (lab > 0).any()
        # val split differs from train
        dv = VOC2012(mode="valid")
        assert len(dv) == 50

    def test_mode_validated(self):
        from paddle_tpu.vision.datasets import VOC2012

        with pytest.raises(ValueError):
            VOC2012(mode="trainval")

    def test_directory_layout(self, tmp_path):
        from paddle_tpu.vision.datasets import VOC2012

        root = tmp_path / "VOCdevkit" / "VOC2012"
        (root / "ImageSets" / "Segmentation").mkdir(parents=True)
        (root / "JPEGImages").mkdir()
        (root / "SegmentationClass").mkdir()
        (root / "ImageSets" / "Segmentation" / "train.txt").write_text(
            "img1\nimg2\n")
        try:
            from PIL import Image
        except ImportError:
            pytest.skip("Pillow unavailable")
        for sid in ("img1", "img2"):
            Image.fromarray(np.zeros((10, 12, 3), np.uint8)).save(
                root / "JPEGImages" / f"{sid}.jpg")
            Image.fromarray(np.full((10, 12), 5, np.uint8)).save(
                root / "SegmentationClass" / f"{sid}.png")
        ds = VOC2012(data_file=str(tmp_path / "VOCdevkit"), mode="train")
        assert len(ds) == 2
        img, lab = ds[0]
        assert img.shape == (3, 10, 12)
        assert lab.dtype == np.int64 and (lab == 5).all()


class TestEncryptedDygraphCheckpoint:
    def test_load_dygraph_forwards_key(self, tmp_path):
        paddle.seed(0)
        layer = paddle.nn.Linear(3, 2)
        base = str(tmp_path / "model")
        paddle.save(layer.state_dict(), base + ".pdparams",
                    encryption_key="kk")
        from paddle_tpu.framework.io import load_dygraph

        para, _ = load_dygraph(base, encryption_key="kk")
        np.testing.assert_array_equal(np.asarray(para["weight"]._data),
                                      np.asarray(layer.weight._data))


class TestLoadStrictKey:
    def test_key_on_plain_file_rejected(self, tmp_path):
        """ADVICE r1: load(encryption_key=...) on an unencrypted file must
        raise, not silently fall back to plain pickle."""
        import pytest
        import paddle_tpu as paddle

        p = str(tmp_path / "m.pdparams")
        paddle.save({"w": paddle.to_tensor([1.0])}, p)
        with pytest.raises(ValueError, match="not encrypted"):
            paddle.load(p, encryption_key="0" * 32)
