"""Flash-attention kernel tests (interpret mode on CPU): fwd + custom-VJP bwd
against the naive softmax(QK^T)V reference."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu.ops.flash_attention import flash_attention


def _naive(q, k, v, causal):
    b, s, h, d = q.shape
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def _qkv(b=1, s=256, h=2, d=64, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, s, h, d).astype(np.float32) * 0.5)
            for _ in range(3)]


class TestFlashForward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_naive(self, causal):
        q, k, v = _qkv(seed=1)
        out = flash_attention(q, k, v, causal=causal, interpret=True)
        ref = _naive(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_head_dim_64_supported_on_tpu_gate(self):
        from paddle_tpu.ops.flash_attention import supported, _on_tpu

        if _on_tpu():
            assert supported((8, 4096, 12, 64), "float32")
        # shape gates independent of platform
        assert not supported((8, 100, 12, 64), "float32")   # seq % 128
        assert not supported((8, 1024, 12, 48), "float32")  # d % 64


class TestFlashBackward:
    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_naive(self, causal):
        q, k, v = _qkv(s=256, seed=2)

        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, causal=causal, interpret=True)
            return jnp.sum(o * jnp.cos(o))  # non-trivial cotangent

        def loss_naive(q, k, v):
            o = _naive(q, k, v, causal)
            return jnp.sum(o * jnp.cos(o))

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gn = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gn, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_bf16_grads_finite(self):
        q, k, v = [x.astype(jnp.bfloat16) for x in _qkv(seed=3)]

        def loss(q):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=True).astype(jnp.float32))

        g = jax.grad(loss)(q)
        assert g.dtype == jnp.bfloat16
        assert np.isfinite(np.asarray(g, np.float32)).all()


def test_use_flash_knob_consumed():
    """GPTConfig.use_flash=False must actually bypass the flash route (no
    dead knobs — VERDICT r1 weak #2 class)."""
    from unittest import mock

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.nn import functional as F
    from paddle_tpu.ops import flash_attention as fa

    q = paddle.to_tensor(np.random.RandomState(0).randn(1, 256, 2, 64).astype(np.float32))
    calls = []
    orig = fa.supported

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    with mock.patch.object(fa, "supported", side_effect=spy):
        F.scaled_dot_product_attention(q, q, q, is_causal=True, use_flash=False)
    # gate short-circuits before consulting the kernel when use_flash=False
    assert not calls


def test_block_flag_forces_block_size():
    """FLAGS_flash_attention_block must override the auto block choice (the
    on-chip tuning knob) and still produce correct output; invalid values
    fail loudly rather than silently fall back. The resolved flag is a
    static arg of the inner jit, so the forced-128 call below retraces with
    blk=128 even though earlier tests cached this shape at auto blk=256 —
    the correctness check genuinely exercises the forced block."""
    from paddle_tpu import flags
    from paddle_tpu.ops.flash_attention import _block_for

    assert _block_for(1024) == 512  # auto picks the largest
    try:
        flags.set_flags({"flash_attention_block": 128})
        assert _block_for(1024) == 128
        q, k, v = _qkv(s=256, seed=3)
        out = flash_attention(q, k, v, causal=True, interpret=True)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_naive(q, k, v, True)),
                                   atol=2e-5, rtol=2e-5)
        flags.set_flags({"flash_attention_block": 384})
        with pytest.raises(ValueError):
            _block_for(1024)
        flags.set_flags({"flash_attention_block": 512})
        with pytest.raises(ValueError):
            _block_for(256)  # does not divide
    finally:
        flags.set_flags({"flash_attention_block": 0})
    assert _block_for(1024) == 512


class TestSlidingWindow:
    """window=W (Mistral-style): out-of-band block pairs are SKIPPED, so
    compute scales O(s*W); in-band positions mask exactly."""

    def _ref(self, q, k, v, w):
        s_ = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(q.shape[-1])
        n = q.shape[1]
        qp = jnp.arange(n)[:, None]
        kp = jnp.arange(n)[None, :]
        keep = (qp >= kp) & ((qp - kp) < w)
        s_ = jnp.where(keep[None, None], s_, -1e30)
        return jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s_, -1), v)

    @pytest.mark.parametrize("window", [1, 64, 100, 256, 1000])
    def test_matches_windowed_reference_multiblock(self, window):
        """s=512 at the forced 128 block -> a 4x4 block grid: the band
        skip predicate, the clip index maps, and the masked-block
        alpha-wipe all execute (a single-block grid tests none of them)."""
        from paddle_tpu import flags

        q, k, v = _qkv(s=512, seed=5)
        try:
            flags.set_flags({"flash_attention_block": 128})
            out = flash_attention(q, k, v, causal=True, interpret=True,
                                  window=window)
        finally:
            flags.set_flags({"flash_attention_block": 0})
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(self._ref(q, k, v, window)),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_windowed_reference_multiblock(self):
        from paddle_tpu import flags

        q, k, v = _qkv(s=512, seed=6)
        wt = jnp.asarray(np.random.RandomState(7)
                         .randn(*q.shape).astype(np.float32))

        def f(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           interpret=True, window=100) * wt)

        def fr(q, k, v):
            return jnp.sum(self._ref(q, k, v, 100) * wt)

        try:
            flags.set_flags({"flash_attention_block": 128})
            g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        finally:
            flags.set_flags({"flash_attention_block": 0})
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-5)

    def test_validation(self):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="causal"):
            flash_attention(q, k, v, causal=False, interpret=True, window=64)
        with pytest.raises(ValueError, match="positive"):
            flash_attention(q, k, v, causal=True, interpret=True, window=0)
        with pytest.raises(ValueError, match="positive"):
            flash_attention(q, k, v, causal=True, interpret=True,
                            window=True)
        out = flash_attention(q, k, v, causal=True, interpret=True,
                              window=np.int64(64))  # numpy ints accepted
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, k, v, 64)),
            atol=2e-5, rtol=2e-5)
