"""Op-test burn-down, batch 6: the round-2 gap families — hierarchical/ranking/
distillation losses, CRF + viterbi, edit distance, fold/channel_shuffle,
index_add/segment reductions, and the detection ops (iou_similarity,
bipartite_match, roi_pool, psroi_pool, matrix_nms, distribute_fpn_proposals,
generate_proposals, deform_conv2d). Reference: operators/{hierarchical_sigmoid,
hinge_loss,rank_loss,teacher_student_sigmoid_loss,edit_distance,
linear_chain_crf,crf_decoding}_op.cc + operators/detection/."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F
from paddle_tpu.vision import ops as V

from op_test import OpTest

rng = np.random.RandomState(42)


def _randn(*shape):
    return rng.randn(*shape).astype(np.float32)


def _softplus(x):
    return np.maximum(x, 0) + np.log1p(np.exp(-np.abs(x)))


X2 = _randn(4, 5)
Y01 = rng.randint(0, 2, (4, 5)).astype(np.float32)

# --- simple elementwise losses -------------------------------------------

CASES = [
    ("hinge_loss", F.hinge_loss, {"input": X2, "label": Y01}, {},
     [np.maximum(0, 1 - (2 * Y01 - 1) * X2)], ["input"]),
    ("rank_loss", F.rank_loss,
     {"label": Y01[:, :1], "left": X2[:, :1], "right": X2[:, 1:2]}, {},
     [_softplus(X2[:, :1] - X2[:, 1:2]) - Y01[:, :1] * (X2[:, :1] - X2[:, 1:2])
      + np.minimum(X2[:, :1] - X2[:, 1:2], 0) * 0],
     ["left", "right"]),
    ("dice_loss", F.dice_loss,
     {"input": np.abs(_randn(3, 4)) + 0.1,
      "label": rng.randint(0, 4, (3, 1)).astype(np.int64)}, {}, None,
     ["input"]),
    ("channel_shuffle", F.channel_shuffle,
     {"x": _randn(1, 6, 3, 3)}, {"groups": 3},
     [None],  # filled below from the numpy reference
     ["x"]),
]


def _channel_shuffle_np(x, groups):
    n, c, h, w = x.shape
    return x.reshape(n, groups, c // groups, h, w).swapaxes(1, 2).reshape(n, c, h, w)


CASES[3] = ("channel_shuffle", F.channel_shuffle,
            {"x": CASES[3][2]["x"]}, {"groups": 3},
            [_channel_shuffle_np(CASES[3][2]["x"], 3)], ["x"])


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op(case):
    name, op, inputs, attrs, outputs, grad_inputs = case
    t = OpTest()
    t.op = op
    t.inputs = inputs
    t.attrs = attrs
    t.outputs = outputs
    if outputs is not None:
        t.check_output(atol=1e-4, rtol=1e-4)
    if grad_inputs:
        t.check_grad(grad_inputs)


def test_teacher_student_sigmoid_loss():
    x = _randn(6)
    lab = np.array([-2.0, -1.0, 0.0, 0.4, 1.0, 1.9], np.float32)
    got = np.asarray(F.teacher_student_sigmoid_loss(
        paddle.to_tensor(x), paddle.to_tensor(lab))._data)
    sp = _softplus(x)
    exp = np.empty_like(x)
    for i, y in enumerate(lab):
        if y < -1:
            exp[i] = sp[i]
        elif y < 0:
            exp[i] = sp[i] - x[i]
        elif y < 1:
            exp[i] = sp[i] + sp[i] - x[i] * y
        else:
            exp[i] = sp[i] - x[i] + sp[i] - x[i] * (y - 1)
    np.testing.assert_allclose(got, exp, rtol=1e-5, atol=1e-6)


def test_hsigmoid_loss_default_tree():
    NC, D = 7, 4
    x = _randn(5, D)
    w = _randn(NC - 1, D)
    b = _randn(NC - 1)
    lab = rng.randint(0, NC, (5,)).astype(np.int64)
    got = np.asarray(F.hsigmoid_loss(
        paddle.to_tensor(x), paddle.to_tensor(lab), NC, paddle.to_tensor(w),
        paddle.to_tensor(b))._data).ravel()

    def ref(xi, c):
        total, bpos, leaf = 0.0, 0, c + NC
        while (leaf >> (bpos + 1)) >= 1:
            node = (leaf >> (bpos + 1)) - 1
            bit = (leaf >> bpos) & 1
            z = w[node] @ xi + b[node]
            total += max(z, 0) - z * bit + np.log1p(np.exp(-abs(z)))
            bpos += 1
        return total

    np.testing.assert_allclose(got, [ref(x[i], int(lab[i])) for i in range(5)],
                               rtol=1e-4)


def test_hsigmoid_loss_custom_path_and_grad():
    # custom 3-node path per sample
    x = paddle.to_tensor(_randn(2, 4))
    x.stop_gradient = False
    w = paddle.to_tensor(_randn(5, 4))
    w.stop_gradient = False
    table = np.array([[0, 2, 4], [1, 3, -1]], np.int64)
    code = np.array([[1, 0, 1], [0, 1, 0]], np.int64)
    out = F.hsigmoid_loss(x, paddle.to_tensor(np.array([0, 1])), 6, w,
                          path_table=table, path_code=code)
    out.sum().backward()
    assert np.asarray(out._data).shape == (2, 1)
    assert np.isfinite(np.asarray(x.grad._data)).all()
    g = np.asarray(w.grad._data)
    assert np.abs(g[4]).sum() > 0 and np.abs(g).sum() > 0
    # padded (-1) node must get zero grad from row 1's path
    assert np.isfinite(g).all()


def test_edit_distance():
    h = np.array([[1, 2, 3, 4], [5, 5, 5, 0]], np.int64)
    r = np.array([[1, 3, 3, 0, 0], [5, 6, 0, 0, 0]], np.int64)
    hl = np.array([4, 3])
    rl = np.array([3, 2])
    d, n = F.edit_distance(h, r, normalized=False, input_length=hl,
                           label_length=rl)
    got = np.asarray(d._data).ravel()

    def lev(a, b):
        dp = np.zeros((len(a) + 1, len(b) + 1))
        dp[:, 0] = np.arange(len(a) + 1)
        dp[0, :] = np.arange(len(b) + 1)
        for i in range(1, len(a) + 1):
            for j in range(1, len(b) + 1):
                dp[i, j] = min(dp[i - 1, j] + 1, dp[i, j - 1] + 1,
                               dp[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
        return dp[-1, -1]

    np.testing.assert_allclose(got, [lev([1, 2, 3, 4], [1, 3, 3]),
                                     lev([5, 5, 5], [5, 6])])
    assert int(np.asarray(n._data)[0]) == 2
    # normalized divides by reference length
    dn, _ = F.edit_distance(h, r, normalized=True, input_length=hl,
                            label_length=rl)
    np.testing.assert_allclose(np.asarray(dn._data).ravel(), got / rl)
    # ignored tokens are removed from both sides first
    di, _ = F.edit_distance(h, r, normalized=False, ignored_tokens=[5],
                            input_length=hl, label_length=rl)
    np.testing.assert_allclose(np.asarray(di._data).ravel()[1],
                               lev([], [6]))


def test_fold_inverts_unfold():
    x = _randn(2, 3, 6, 6)
    u = F.unfold(paddle.to_tensor(x), 2, strides=2)
    f = F.fold(u, (6, 6), 2, strides=2)
    np.testing.assert_allclose(np.asarray(f._data), x, rtol=1e-6)
    # overlapping windows accumulate: ones through unfold(3, stride 1, pad 1)
    ones = np.ones((1, 1, 4, 4), np.float32)
    u2 = F.unfold(paddle.to_tensor(ones), 3, strides=1, paddings=1)
    f2 = np.asarray(F.fold(u2, (4, 4), 3, strides=1, paddings=1)._data)
    assert f2[0, 0, 1, 1] > f2[0, 0, 0, 0]  # interior counted by more windows


def test_index_add_and_segment():
    x = paddle.to_tensor(np.zeros((4, 3), np.float32))
    out = paddle.index_add(x, paddle.to_tensor(np.array([1, 1, 3])), 0,
                           paddle.to_tensor(np.ones((3, 3), np.float32)))
    np.testing.assert_allclose(np.asarray(out._data)[:, 0], [0, 2, 0, 1])

    from paddle_tpu.incubate import segment_max, segment_mean, segment_sum

    data = np.array([[1., 2.], [3., 4.], [10., 20.]], np.float32)
    ids = np.array([0, 0, 2])
    np.testing.assert_allclose(
        np.asarray(segment_sum(data, ids)._data),
        [[4, 6], [0, 0], [10, 20]])
    np.testing.assert_allclose(
        np.asarray(segment_mean(data, ids)._data),
        [[2, 3], [0, 0], [10, 20]])
    np.testing.assert_allclose(
        np.asarray(segment_max(data, ids)._data),
        [[3, 4], [0, 0], [10, 20]])


def test_tensor_unfold_windows():
    from paddle_tpu.tensor.manipulation import unfold as t_unfold

    x = np.arange(10, dtype=np.float32)
    got = np.asarray(t_unfold(paddle.to_tensor(x), 0, 4, 3)._data)
    np.testing.assert_allclose(got, [[0, 1, 2, 3], [3, 4, 5, 6], [6, 7, 8, 9]])


def test_viterbi_decode_bruteforce():
    from paddle_tpu.text import viterbi_decode

    B, L, T = 2, 4, 3
    pot = _randn(B, L, T)
    trans = _randn(T, T)
    lens = np.array([4, 2], np.int32)
    for include in (False, True):
        s, p = viterbi_decode(paddle.to_tensor(pot), paddle.to_tensor(trans),
                              paddle.to_tensor(lens),
                              include_bos_eos_tag=include)
        s, p = np.asarray(s._data), np.asarray(p._data)
        for b in range(B):
            ln = lens[b]
            best, bestpath = -1e30, None
            for path in itertools.product(range(T), repeat=int(ln)):
                sc = pot[b, 0, path[0]] + (trans[T - 2, path[0]] if include else 0)
                for t in range(1, ln):
                    sc += trans[path[t - 1], path[t]] + pot[b, t, path[t]]
                if include:
                    sc += trans[path[-1], T - 1]
                if sc > best:
                    best, bestpath = sc, path
            assert abs(best - s[b]) < 1e-4
            assert tuple(p[b, :ln]) == bestpath


def test_linear_chain_crf_bruteforce_and_grad():
    from paddle_tpu.text import linear_chain_crf

    B, L, T = 2, 4, 3
    pot = _randn(B, L, T)
    tr2 = _randn(T + 2, T)
    lab = rng.randint(0, T, (B, L)).astype(np.int64)
    lens = np.array([4, 3], np.int32)
    em = paddle.to_tensor(pot)
    em.stop_gradient = False
    tt = paddle.to_tensor(tr2)
    tt.stop_gradient = False
    loss = linear_chain_crf(em, tt, paddle.to_tensor(lab),
                            paddle.to_tensor(lens))
    got = np.asarray(loss._data)
    start, stop, mat = tr2[0], tr2[1], tr2[2:]
    for b in range(B):
        ln = lens[b]
        scores = []
        for path in itertools.product(range(T), repeat=int(ln)):
            sc = start[path[0]] + pot[b, 0, path[0]]
            for t in range(1, ln):
                sc += mat[path[t - 1], path[t]] + pot[b, t, path[t]]
            sc += stop[path[-1]]
            scores.append(sc)
        m = max(scores)
        logz = np.log(np.sum(np.exp(np.array(scores) - m))) + m
        gold = start[lab[b, 0]] + pot[b, 0, lab[b, 0]]
        for t in range(1, ln):
            gold += mat[lab[b, t - 1], lab[b, t]] + pot[b, t, lab[b, t]]
        gold += stop[lab[b, ln - 1]]
        assert abs((logz - gold) - got[b, 0]) < 1e-3
    loss.sum().backward()
    assert np.isfinite(np.asarray(em.grad._data)).all()
    assert np.abs(np.asarray(tt.grad._data)).sum() > 0


def test_mean_iou():
    from paddle_tpu.metric import mean_iou

    pred = np.array([0, 0, 1, 1, 2], np.int64)
    lab = np.array([0, 1, 1, 1, 0], np.int64)
    m, wrong, correct = mean_iou(pred, lab, 3)
    # class 0: correct 1, union 2+2-1=3 -> 1/3; class 1: correct 2, union 2+3-2=3
    # -> 2/3; class 2: union 1 (pred only) -> 0; mean over present = 1/3
    np.testing.assert_allclose(float(np.asarray(m._data)),
                               (1 / 3 + 2 / 3 + 0) / 3, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(correct._data), [1, 2, 0])
    # mismatches increment wrong for BOTH label and prediction class
    # (ref mean_iou_op.h:95-96): pixels (pred=0,lab=1) and (pred=2,lab=0)
    np.testing.assert_allclose(np.asarray(wrong._data), [2, 1, 1])


# --- detection family -----------------------------------------------------

def _iou_np(a, b, off=0.0):
    out = np.zeros((len(a), len(b)))
    for i in range(len(a)):
        for j in range(len(b)):
            ix = max(0.0, min(a[i, 2], b[j, 2]) - max(a[i, 0], b[j, 0]) + off)
            iy = max(0.0, min(a[i, 3], b[j, 3]) - max(a[i, 1], b[j, 1]) + off)
            inter = ix * iy
            ar_a = max(0, a[i, 2] - a[i, 0] + off) * max(0, a[i, 3] - a[i, 1] + off)
            ar_b = max(0, b[j, 2] - b[j, 0] + off) * max(0, b[j, 3] - b[j, 1] + off)
            u = ar_a + ar_b - inter
            out[i, j] = inter / u if u > 0 else 0
    return out


def test_iou_similarity():
    a = np.abs(_randn(5, 4))
    a[:, 2:] += a[:, :2]
    b = np.abs(_randn(6, 4))
    b[:, 2:] += b[:, :2]
    got = np.asarray(V.iou_similarity(paddle.to_tensor(a),
                                      paddle.to_tensor(b))._data)
    np.testing.assert_allclose(got, _iou_np(a, b), atol=1e-5)
    got2 = np.asarray(V.iou_similarity(paddle.to_tensor(a), paddle.to_tensor(b),
                                       box_normalized=False)._data)
    np.testing.assert_allclose(got2, _iou_np(a, b, 1.0), atol=1e-5)


def test_bipartite_match():
    D = rng.rand(4, 6).astype(np.float32)
    idx, dist = V.bipartite_match(paddle.to_tensor(D))
    idx, dist = np.asarray(idx._data), np.asarray(dist._data)
    d = D.copy()
    exp_idx = -np.ones(6, np.int32)
    exp_d = np.zeros(6)
    for _ in range(4):
        i, j = np.unravel_index(np.argmax(d), d.shape)
        if d[i, j] <= 1e-6:
            break
        exp_idx[j] = i
        exp_d[j] = D[i, j]
        d[i, :] = -1
        d[:, j] = -1
    assert (idx == exp_idx).all()
    np.testing.assert_allclose(dist, exp_d, atol=1e-6)
    idx2, _ = V.bipartite_match(paddle.to_tensor(D),
                                match_type="per_prediction",
                                overlap_threshold=0.0)
    assert (np.asarray(idx2._data) >= 0).all()


def test_roi_pool():
    x = _randn(2, 3, 8, 8)
    rois = np.array([[0, 0, 4, 4], [1, 1, 6, 5], [2, 0, 7, 7]], np.float32)
    bn = np.array([2, 1], np.int32)
    got = np.asarray(V.roi_pool(paddle.to_tensor(x), paddle.to_tensor(rois),
                                paddle.to_tensor(bn), 2, 1.0)._data)

    def ref(feat, roi, ph_n=2, pw_n=2):
        x1, y1, x2, y2 = [int(round(v)) for v in roi]
        rh, rw = max(y2 - y1 + 1, 1), max(x2 - x1 + 1, 1)
        C, H, W = feat.shape
        out = np.zeros((C, ph_n, pw_n), np.float32)
        for ph in range(ph_n):
            for pw in range(pw_n):
                hs = max(int(np.floor(ph * rh / ph_n)) + y1, 0)
                he = min(int(np.ceil((ph + 1) * rh / ph_n)) + y1, H)
                ws = max(int(np.floor(pw * rw / pw_n)) + x1, 0)
                we = min(int(np.ceil((pw + 1) * rw / pw_n)) + x1, W)
                if he <= hs or we <= ws:
                    continue
                out[:, ph, pw] = feat[:, hs:he, ws:we].max(axis=(1, 2))
        return out

    exp = np.stack([ref(x[0], rois[0]), ref(x[0], rois[1]), ref(x[1], rois[2])])
    np.testing.assert_allclose(got, exp, atol=1e-5)
    # grad flows to the feature map
    xt = paddle.to_tensor(x)
    xt.stop_gradient = False
    V.roi_pool(xt, paddle.to_tensor(rois), paddle.to_tensor(bn),
               2).sum().backward()
    assert np.abs(np.asarray(xt.grad._data)).sum() > 0


def test_psroi_pool():
    c_out, phn = 2, 2
    x = np.ones((1, c_out * phn * phn, 6, 6), np.float32) * 3.0
    rois = np.array([[0, 0, 5, 5]], np.float32)
    got = np.asarray(V.psroi_pool(paddle.to_tensor(x), paddle.to_tensor(rois),
                                  paddle.to_tensor(np.array([1], np.int32)),
                                  phn, 1.0)._data)
    assert got.shape == (1, c_out, phn, phn)
    np.testing.assert_allclose(got, 3.0)
    # position sensitivity: channel block k feeds only bin k
    x2 = np.zeros((1, c_out * phn * phn, 6, 6), np.float32)
    x2[0, 0] = 7.0  # (c=0, ph=0, pw=0) block
    got2 = np.asarray(V.psroi_pool(paddle.to_tensor(x2), paddle.to_tensor(rois),
                                   paddle.to_tensor(np.array([1], np.int32)),
                                   phn, 1.0)._data)
    assert got2[0, 0, 0, 0] == pytest.approx(7.0)
    assert np.abs(got2).sum() == pytest.approx(7.0)


def test_matrix_nms():
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [20, 20, 30, 30]]], np.float32)
    scores = np.zeros((1, 2, 3), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7]
    out, num = V.matrix_nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                            score_threshold=0.1, keep_top_k=3,
                            background_label=0)
    o = np.asarray(out._data)[0]
    assert int(np.asarray(num._data)[0]) == 3
    assert o[0, 1] == pytest.approx(0.9)       # top box undecayed
    assert o[1, 1] == pytest.approx(0.7)       # distinct box ~undecayed
    # linear decay of the overlapping box: s * (1-iou)/(1-0)
    iou = _iou_np(boxes[0, :1], boxes[0, 1:2])[0, 0]
    assert o[2, 1] == pytest.approx(0.8 * (1 - iou), rel=1e-4)
    # gaussian decay
    outg, _ = V.matrix_nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                           score_threshold=0.1, keep_top_k=3,
                           use_gaussian=True, gaussian_sigma=2.0,
                           background_label=0)
    og = np.asarray(outg._data)[0]
    assert og[2, 1] == pytest.approx(0.8 * np.exp(-(iou ** 2) * 2.0), rel=1e-4)


def test_distribute_fpn_proposals():
    rois = np.array([[0, 0, 16, 16], [0, 0, 64, 64], [0, 0, 224, 224],
                     [0, 0, 500, 500]], np.float32)
    multi, restore, nums = V.distribute_fpn_proposals(
        paddle.to_tensor(rois), 2, 5, 4, 224,
        rois_num=paddle.to_tensor(np.array([4], np.int32)))
    counts = [np.asarray(m._data).shape[0] for m in multi]
    assert sum(counts) == 4
    assert counts[0] >= 1 and counts[-1] >= 1  # smallest + largest split apart
    # restore index maps concatenated-multi order back to input order
    cat = np.concatenate([np.asarray(m._data) for m in multi if
                          np.asarray(m._data).size], axis=0)
    ri = np.asarray(restore._data).ravel()
    np.testing.assert_allclose(cat[ri], rois)


def test_generate_proposals():
    H = W = 4
    A = 2
    anchors = np.zeros((H, W, A, 4), np.float32)
    for y in range(H):
        for x in range(W):
            anchors[y, x, 0] = [x * 8, y * 8, x * 8 + 8, y * 8 + 8]
            anchors[y, x, 1] = [x * 8, y * 8, x * 8 + 16, y * 8 + 16]
    var = np.ones((H, W, A, 4), np.float32)
    sc = rng.rand(1, A, H, W).astype(np.float32)
    dl = np.zeros((1, 4 * A, H, W), np.float32)  # zero deltas: rois == anchors
    rois, rsc, num = V.generate_proposals(
        paddle.to_tensor(sc), paddle.to_tensor(dl),
        paddle.to_tensor(np.array([[32.0, 32.0]], np.float32)),
        paddle.to_tensor(anchors), paddle.to_tensor(var),
        pre_nms_top_n=32, post_nms_top_n=8, nms_thresh=0.8, min_size=1.0)
    r = np.asarray(rois._data)[0]
    s = np.asarray(rsc._data)[0]
    n = int(np.asarray(num._data)[0])
    assert r.shape == (8, 4) and 1 <= n <= 8
    # scores sorted desc over the valid region
    assert all(s[i] >= s[i + 1] for i in range(n - 1))
    # every valid roi is a clipped anchor (zero deltas)
    flat_anchors = anchors.reshape(-1, 4)
    clipped = flat_anchors.copy()
    clipped[:, 0::2] = np.clip(clipped[:, 0::2], 0, 32)
    clipped[:, 1::2] = np.clip(clipped[:, 1::2], 0, 32)
    for i in range(n):
        assert any(np.allclose(r[i], c, atol=1e-4) for c in clipped)


def test_deform_conv2d():
    import jax
    import jax.numpy as jnp

    x = _randn(2, 4, 7, 7)
    w = _randn(6, 4, 3, 3)
    off0 = np.zeros((2, 18, 7, 7), np.float32)
    got = np.asarray(V.deform_conv2d(paddle.to_tensor(x),
                                     paddle.to_tensor(off0),
                                     paddle.to_tensor(w), padding=1)._data)
    exp = np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    np.testing.assert_allclose(got, exp, atol=1e-3)
    # modulated (v2): mask of ones is identity, mask of 0.5 halves the output
    m1 = np.ones((2, 9, 7, 7), np.float32)
    got2 = np.asarray(V.deform_conv2d(paddle.to_tensor(x),
                                      paddle.to_tensor(off0),
                                      paddle.to_tensor(w), padding=1,
                                      mask=paddle.to_tensor(m1))._data)
    np.testing.assert_allclose(got2, exp, atol=1e-3)
    got3 = np.asarray(V.deform_conv2d(paddle.to_tensor(x),
                                      paddle.to_tensor(off0),
                                      paddle.to_tensor(w), padding=1,
                                      mask=paddle.to_tensor(m1 * 0.5))._data)
    np.testing.assert_allclose(got3, exp * 0.5, atol=1e-3)
    # integer offset (+1, +1) == conv over shifted input (interior check)
    off1 = np.ones((2, 18, 7, 7), np.float32)
    got4 = np.asarray(V.deform_conv2d(paddle.to_tensor(x),
                                      paddle.to_tensor(off1),
                                      paddle.to_tensor(w), padding=1)._data)
    x_shift = np.zeros_like(x)
    x_shift[:, :, :-1, :-1] = x[:, :, 1:, 1:]
    exp4 = np.asarray(jax.lax.conv_general_dilated(
        jnp.asarray(x_shift), jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW")))
    np.testing.assert_allclose(got4[:, :, 1:-2, 1:-2], exp4[:, :, 1:-2, 1:-2],
                               atol=1e-3)
    # grads flow to x, offset, weight
    xt, ot, wt = (paddle.to_tensor(v) for v in (x, off0 + 0.3, w))
    for t in (xt, ot, wt):
        t.stop_gradient = False
    V.deform_conv2d(xt, ot, wt, padding=1).sum().backward()
    for t in (xt, ot, wt):
        assert np.isfinite(np.asarray(t.grad._data)).all()
        assert np.abs(np.asarray(t.grad._data)).sum() > 0


def test_fold_unfold_asymmetric_padding():
    # [top, left, bottom, right] 4-element paddle layout must roundtrip
    x = _randn(1, 2, 5, 5)
    u = F.unfold(paddle.to_tensor(x), 2, strides=1, paddings=[1, 0, 0, 0])
    # out_h = (5 + 1 + 0 - 2)//1 + 1 = 5, out_w = 4
    assert np.asarray(u._data).shape == (1, 2 * 4, 5 * 4)
    ones = np.ones((1, 1, 4, 4), np.float32)
    u2 = F.unfold(paddle.to_tensor(ones), 2, strides=2, paddings=[1, 1, 1, 1])
    f2 = F.fold(u2, (4, 4), 2, strides=2, paddings=[1, 1, 1, 1])
    np.testing.assert_allclose(np.asarray(f2._data), ones)


def test_matrix_nms_single_background_class():
    boxes = np.array([[[0, 0, 10, 10]]], np.float32)
    scores = np.ones((1, 1, 1), np.float32)
    out, num = V.matrix_nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                            score_threshold=0.1, keep_top_k=2,
                            background_label=0)
    assert int(np.asarray(num._data)[0]) == 0
    assert (np.asarray(out._data) == -1).all()


def test_deform_conv2d_layer_class():
    """DeformConv2D Layer (reference python/paddle/vision/ops.py:598): wraps
    the functional op with learned weight/bias; v1 and v2 (mask) paths."""
    paddle.seed(0)
    layer = V.DeformConv2D(in_channels=3, out_channels=5, kernel_size=3,
                           padding=1)
    assert tuple(layer.weight.shape) == (5, 3, 3, 3)
    assert tuple(layer.bias.shape) == (5,)
    x = paddle.to_tensor(_randn(2, 3, 8, 8))
    off = paddle.to_tensor(np.zeros((2, 18, 8, 8), np.float32))
    out = layer(x, off)
    assert tuple(out.shape) == (2, 5, 8, 8)
    # zero offsets == plain conv with the layer's own weight
    want = np.asarray(V.deform_conv2d(
        x, off, layer.weight, bias=layer.bias, padding=1)._data)
    np.testing.assert_allclose(np.asarray(out._data), want, atol=1e-5)
    # v2: mask of ones is identity
    m = paddle.to_tensor(np.ones((2, 9, 8, 8), np.float32))
    out2 = layer(x, off, mask=m)
    np.testing.assert_allclose(np.asarray(out2._data), want, atol=1e-4)
    # trains: grads reach the layer params
    loss = layer(x, off).sum()
    loss.backward()
    assert np.abs(np.asarray(layer.weight.grad._data)).sum() > 0
    # bias_attr=False drops the bias
    nl = V.DeformConv2D(3, 5, 3, bias_attr=False)
    assert nl.bias is None
    # groups must divide channels
    import pytest
    with pytest.raises(ValueError, match="divisible"):
        V.DeformConv2D(3, 4, 3, groups=2)


def test_class_center_sample():
    """PartialFC sampling: all positives kept, budget filled with negatives,
    sampled set sorted, labels remapped into it."""
    paddle.seed(7)
    label = np.array([3, 11, 3, 42, 7, 11], np.int64)
    num_classes, num_samples = 64, 16
    remapped, sampled = F.class_center_sample(
        paddle.to_tensor(label), num_classes, num_samples)
    s = np.asarray(sampled._data)
    r = np.asarray(remapped._data)
    assert s.shape == (num_samples,) and r.shape == label.shape
    assert (np.diff(s) > 0).all()  # sorted, distinct
    assert (s >= 0).all() and (s < num_classes).all()
    for cls in np.unique(label):  # every positive was sampled
        assert cls in s
    np.testing.assert_array_equal(s[r], label)  # remap round-trips
    # seed-deterministic
    paddle.seed(7)
    r2, s2 = F.class_center_sample(paddle.to_tensor(label), num_classes,
                                   num_samples)
    np.testing.assert_array_equal(np.asarray(s2._data), s)
    # all-classes budget: sampled == arange
    paddle.seed(1)
    _, s_all = F.class_center_sample(paddle.to_tensor(label), 8, 8)
    np.testing.assert_array_equal(np.asarray(s_all._data), np.arange(8))
    import pytest
    with pytest.raises(ValueError, match="num_samples"):
        F.class_center_sample(paddle.to_tensor(label), 8, 9)
