"""fluid-era data pipeline parity: paddle.batch + paddle.reader decorators +
paddle.dataset reader creators (python/paddle/batch.py, reader/decorator.py,
dataset/)."""
import numpy as np

import paddle_tpu as paddle


def test_batch_and_drop_last():
    r = lambda: iter(range(10))
    batches = list(paddle.batch(r, 3)())
    assert batches[0] == [0, 1, 2] and batches[-1] == [9]
    batches = list(paddle.batch(r, 3, drop_last=True)())
    assert batches[-1] == [6, 7, 8] and len(batches) == 3


def test_reader_decorators():
    r = lambda: iter(range(6))
    assert list(paddle.reader.firstn(r, 3)()) == [0, 1, 2]
    assert list(paddle.reader.buffered(r, 2)()) == list(range(6))
    assert list(paddle.reader.chain(r, r)()) == list(range(6)) * 2
    assert sorted(paddle.reader.shuffle(r, 4)()) == list(range(6))
    assert list(paddle.reader.map_readers(lambda a, b: a + b, r, r)()) == [
        0, 2, 4, 6, 8, 10]
    comp = paddle.reader.compose(r, r)
    assert list(comp())[0] == (0, 0)
    c = paddle.reader.cache(r)
    assert list(c()) == list(range(6)) and list(c()) == list(range(6))
    assert list(paddle.reader.xmap_readers(lambda x: x * 2, r, 2, 4)()) == [
        0, 2, 4, 6, 8, 10]


def test_dataset_reader_creators():
    tr = paddle.dataset.uci_housing.train()
    first = next(iter(tr()))
    assert first[0].shape == (13,) and first[1].shape == (1,)
    assert len(paddle.dataset.uci_housing.feature_names) == 13
    # composes with paddle.batch
    b = next(iter(paddle.batch(tr, 4)()))
    assert len(b) == 4

    mn = paddle.dataset.mnist.test()
    img, lab = next(iter(mn()))
    assert img.shape[-1] == 28 * 28 or img.shape == (28, 28) or img.shape == (1, 28, 28)

    wd = paddle.dataset.imdb.word_dict()
    assert len(wd) > 10


def test_compat_and_sysconfig():
    assert paddle.compat.to_text(b"abc") == "abc"
    assert paddle.compat.to_bytes("abc") == b"abc"
    assert paddle.sysconfig.get_lib().endswith("native")
    assert paddle.regularizer.L2Decay(1e-4).coeff == 1e-4


def test_fleet_data_generator():
    import io
    import sys

    from paddle_tpu.distributed.fleet import MultiSlotDataGenerator

    class Gen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def iters():
                yield [("ids", [1, 2, 3]), ("label", [0])]

            return iters

    g = Gen()
    out = io.StringIO()
    old = sys.stdout
    sys.stdout = out
    try:
        g.run_from_memory()
    finally:
        sys.stdout = old
    assert out.getvalue().strip() == "3 1 2 3 1 0"


def test_fleet_util_file_shard():
    from paddle_tpu.distributed.fleet import UtilBase

    u = UtilBase()
    files = [f"f{i}" for i in range(5)]
    assert u.get_file_shard(files) == files  # world_size 1


def test_utils_profiler_and_download(tmp_path):
    import paddle_tpu as paddle

    with paddle.utils.Profiler():
        _ = 1 + 1
    src = tmp_path / "a.txt"
    src.write_text("hi")
    dst = tmp_path / "b.txt"
    assert paddle.utils.download(str(src), str(dst)) == str(dst)
    assert dst.read_text() == "hi"
    import pytest

    with pytest.raises(RuntimeError, match="egress"):
        paddle.utils.download("https://example.com/x")
    assert paddle.utils.require_version("2.0")


def test_incubate_layer_helper():
    from paddle_tpu.incubate import LayerHelper

    h = LayerHelper("fc")
    p = h.create_parameter(shape=[3, 2])
    assert list(p.shape) == [3, 2] and not p.stop_gradient
