"""Driver script for test_ps_launch: run under the PS launcher as either a
PSERVER or TRAINER process (test_fleet_launch_ps.sh analog)."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy
from paddle_tpu.distributed.fleet.meta_optimizers import PsDenseOptimizer
from paddle_tpu.distributed.fleet.role_maker import PaddleCloudRoleMaker


def main():
    strategy = DistributedStrategy()
    strategy.a_sync = False  # sync push-pull
    fleet.init(role_maker=PaddleCloudRoleMaker(is_collective=False), is_collective=False,
               strategy=strategy)
    if fleet.is_server():
        fleet.init_server()
        fleet.run_server()
        return
    fleet.init_worker()
    client = fleet.ps_runtime.client
    paddle.seed(0)
    lin = paddle.nn.Linear(2, 1)
    opt = PsDenseOptimizer(lin.parameters(), client, optimizer="sgd", lr=0.1)
    rng = np.random.RandomState(0)
    X = rng.randn(64, 2).astype(np.float32)
    Y = X @ np.array([[2.0], [-1.0]], np.float32)
    first = last = None
    for i in range(30):
        xb, yb = paddle.to_tensor(X[i % 56:i % 56 + 8]), paddle.to_tensor(Y[i % 56:i % 56 + 8])
        loss = paddle.mean((lin(xb) - yb) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
        v = float(np.asarray(loss._data))
        first = v if first is None else first
        last = v
    assert last < first, (first, last)
    print(f"PS_LAUNCH_OK trainer={fleet.worker_index()} first={first:.4f} last={last:.4f}")
    fleet.stop_worker()


if __name__ == "__main__":
    main()
