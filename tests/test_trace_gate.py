"""Tier-1 gate for the tracing layer (ISSUE 5): with FLAGS_trace unset
every span call site is a single boolean check — no Span object is ever
constructed, nothing lands in the ring buffer, no trace/cost metric
series appear, and serving/trainer behavior is bit-identical to the
pre-PR engines — at the same <5µs/call bar as the monitor/failpoints
fast paths. Plus: tools/trace_dump.py --json exit codes are pinned."""
import importlib.util
import os
import sys
import time

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import monitor, trace

#: metric families this PR introduced — with the flag unset NONE of them
#: may grow a series on the serving/trainer/executor paths
TRACE_FAMILIES = ("program_flops", "program_hbm_bytes",
                  "device_hbm_used_bytes")


@pytest.fixture(autouse=True)
def _disabled():
    trace.disable()
    trace.clear()
    yield
    trace.disable()
    trace.clear()


def _forbid_spans(monkeypatch):
    """Constructing a Span (or recording one) with tracing off is a
    regression — the zero-overhead contract."""
    def boom(*a, **k):
        raise AssertionError("trace span machinery ran with FLAGS_trace "
                             "unset")
    monkeypatch.setattr(trace, "Span", boom)
    monkeypatch.setattr(trace, "_record", boom)


def _tiny_model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestInertByDefault:
    def test_disabled_span_under_5us(self):
        """Same bar and method as the monitor/failpoint/CachedJit gates:
        a disabled span call is one boolean check."""
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            with trace.span("gate", subsystem="t", a=1):
                pass
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0, (
            f"disabled span costs {per_call_us:.2f}us/call — the "
            "one-boolean fast path regressed")
        t0 = time.perf_counter()
        for _ in range(n):
            trace.start_span("gate").end()
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0
        assert not trace.spans()

    def test_hot_paths_never_construct_spans(self, monkeypatch, tmp_path):
        _forbid_spans(monkeypatch)
        # checkpoint write + read
        p = str(tmp_path / "s.pdparams")
        paddle.save({"w": paddle.to_tensor(np.ones(3))}, p)
        paddle.load(p)
        # collective
        from paddle_tpu.distributed import collective

        collective.all_reduce(paddle.to_tensor(np.ones(2, np.float32)))
        # executor compile + run
        import paddle_tpu.static as st

        paddle.seed(0)
        main, startup = st.Program(), st.Program()
        st.enable_static()
        try:
            with st.program_guard(main, startup):
                x = st.data("x", [None, 4])
                w = paddle.create_parameter([4, 4])
                y = paddle.matmul(x, w)
        finally:
            st.disable_static()
        exe = st.Executor()
        exe.run(startup)
        (r,) = exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                       fetch_list=[y])
        assert np.isfinite(r).all()
        # trainer step
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(), mesh=mesh)
        tr.train_step(np.ones((2, 4), np.float32),
                      np.zeros((2, 1), np.float32))
        assert not trace.spans()

    def test_serving_and_trainer_metrics_have_zero_trace_drift(self):
        """Flag unset: the serving + trainer paths leave the metric
        registry exactly as the pre-PR instrumentation did — none of the
        trace/cost families grows a series, the serving engine keeps
        exact solo-generate parity, and the compile paths stay on the
        lazy-jit bypass (no forced AOT: miss/fresh accounting only)."""
        from paddle_tpu.inference.serving import ServingEngine

        monitor.reset()
        m = _tiny_model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
                   for n in (5, 9)]
        eng = ServingEngine(m, max_batch=2)
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            ref = m.generate(paddle.to_tensor(p[None]), max_new_tokens=6,
                             temperature=0.0)
            np.testing.assert_array_equal(
                res[rid].tokens, np.asarray(ref._data)[0, len(p):])
            assert res[rid].trace_id is None   # no identity minted
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(), mesh=mesh)
        tr.train_step(np.ones((2, 4), np.float32),
                      np.zeros((2, 1), np.float32))

        reg = monitor.default_registry()
        for family in TRACE_FAMILIES:
            metric = reg.get(family)
            assert metric is None or not list(metric.series()), family
        # compile accounting unchanged: everything fresh/memory, no disk
        cache = reg.get("compile_cache_total")
        assert not any(s.labels.get("source") == "disk"
                       for s in cache.series())
        # stats() still works without the cost registry: wall-time split
        # present, flops/mfu absent rather than wrong
        assert tr.stats()["mfu"] is None
        bd = eng.stats()["breakdown"]
        assert bd["wall_ms_total"] > 0
        assert "mfu" not in bd
        assert not trace.spans()

    def test_snapshot_structure_identical_across_traced_import(self):
        """The registry snapshot taken after a flag-unset workload must
        be structurally identical whether or not the trace module has
        ever been exercised in-process — same families, same series
        keys, same counter values (histogram sums carry wall time and
        are compared on count only)."""
        from paddle_tpu.inference.serving import ServingEngine

        def run_once():
            monitor.reset()
            m = _tiny_model()
            rng = np.random.RandomState(0)
            eng = ServingEngine(m, max_batch=2)
            eng.submit(rng.randint(0, 64, (5,)).astype(np.int32),
                       max_new_tokens=4)
            eng.run_until_complete()
            out = {}
            for fam in monitor.snapshot()["metrics"]:
                for s in fam["series"]:
                    key = (fam["name"],
                           tuple(sorted(s["labels"].items())))
                    out[key] = (s["count"] if fam["type"] == "histogram"
                                else s["value"])
            return out

        base = run_once()
        # exercise the tracer heavily in between (enabled, then off)
        trace.enable()
        for i in range(50):
            with trace.span(f"noise{i}"):
                pass
        trace.disable()
        trace.clear()
        again = run_once()
        assert base == again


class TestTraceDumpTool:
    def _load(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "trace_dump", os.path.join(repo, "tools", "trace_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules.pop("trace_dump", None)
        spec.loader.exec_module(mod)
        return mod

    def test_serving_report_clean_and_chrome_written(self, capsys,
                                                     tmp_path):
        import json

        td = self._load()
        out = str(tmp_path / "t.json")
        rc = td.main(["--serving", "--json", "--chrome", out])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert set(report) >= {"tool", "passes", "targets", "totals"}
        assert report["tool"] == "trace_dump"
        assert report["totals"]["error"] == 0
        assert report["targets"]["serving"]["trace"]["spans"] > 0
        assert report["targets"]["serving"]["cost_table"]
        with open(out) as f:
            doc = json.load(f)
        assert any(e.get("cat") == "span" for e in doc["traceEvents"])

    def test_missing_span_family_exits_1(self, capsys, monkeypatch):
        """The CI contract: a workload whose required span families do
        not appear fails the run. Silence the tracer and watch it burn."""
        import json

        td = self._load()
        monkeypatch.setattr(trace, "enable", lambda: None)
        rc = td.main(["--serving", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        errs = [f for f in report["targets"]["serving"]["findings"]
                if f["severity"] == "error"]
        assert any(f["pass"] == "spans-present" for f in errs)

    def test_no_target_is_an_error(self):
        td = self._load()
        with pytest.raises(SystemExit):
            td.main(["--json"])
