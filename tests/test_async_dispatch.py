"""Async double-buffered dispatch (ISSUE 11, docs/PERF.md): the armed
trainer's loss trajectory is BIT-exact vs the synchronous path while the
per-step host-sync count drops to <= 1 per FLAGS_async_window steps; the
deferred guard keeps the FLAGS_max_skip_steps contract; prefetch()
double-buffers batch marshalling; the serving engine's async step emits
identical tokens with the admission window overlapped; and the
overlapped quantized exchange stays inside the quantized parity band."""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    paddle.set_flags({"async_dispatch": False, "async_window": 8,
                      "check_nan_inf": False, "max_skip_steps": 3,
                      "benchmark": False})


def _gpt_trainer(lr=1e-2):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=32, dropout=0.0)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    return SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(), mesh=mesh)


def _batches(steps, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, 64, (2, 16)).astype(np.int32),
             rng.randint(0, 64, (2, 16)).astype(np.int32))
            for _ in range(steps)]


def _linear_trainer():
    """Float-input trainer for guard-poisoning tests (a NaN batch flows
    straight into the loss; the trainer/batch scale failpoint only
    poisons FLOAT arrays, which GPT's int32 token batches are not)."""
    paddle.seed(0)
    model = paddle.nn.Linear(4, 1)
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=model.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    return SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                       mesh=mesh)


X = np.ones((2, 4), np.float32)
Y = np.zeros((2, 1), np.float32)
XNAN = X.copy()
XNAN[0, 0] = np.nan


def _run(async_on, steps=6, guard=True, window=3):
    paddle.set_flags({"async_dispatch": async_on, "async_window": window,
                      "check_nan_inf": guard})
    tr = _gpt_trainer()
    losses = [tr.train_step(*b) for b in _batches(steps)]
    tr.guard_sync()
    out = [float(np.asarray(l._data)) for l in losses]
    params = {k: np.asarray(v).copy() for k, v in tr.params.items()}
    return tr, out, params


class TestTrainerAsync:
    def test_loss_trajectory_bit_exact_vs_sync(self):
        """The acceptance criterion: armed on the tiny-GPT trainer, the
        loss trajectory is bit-exact vs the synchronous path (the
        compiled program is byte-identical; only the host's fetch
        timing moves) — params byte-equal too."""
        _, sync_losses, sync_params = _run(False)
        _, async_losses, async_params = _run(True)
        assert sync_losses == async_losses
        for k in sync_params:
            assert sync_params[k].tobytes() == async_params[k].tobytes(), k

    def test_host_sync_count_drops_to_window_rate(self):
        """Per-step host-sync count <= 1/FLAGS_async_window steps: 12
        guarded steps under window 4 cost exactly 3 verdict drains
        (plus the final guard_sync for the tail)."""
        paddle.set_flags({"async_dispatch": True, "async_window": 4,
                          "check_nan_inf": True})
        tr = _gpt_trainer()
        for b in _batches(12):
            tr.train_step(*b)
        # drains happen at ENTRY once the window fills (so the device
        # had the whole host gap to finish): steps 5 and 9 fetched
        # windows of 4; the final 4 are still banked, fetched by the
        # first boundary that wants them
        assert tr._verdict_fetches == 2
        assert len(tr._pending_verdicts) == 4
        tr.guard_sync()
        assert tr._verdict_fetches == 3
        assert len(tr._pending_verdicts) == 0
        assert tr._nonfinite_total == 0

    def test_returns_step_handle_with_schedule_identity(self):
        paddle.set_flags({"async_dispatch": True})
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.distributed.async_dispatch import StepHandle

        tr = _gpt_trainer()
        b = _batches(2)
        h0 = tr.train_step(*b[0])
        h1 = tr.train_step(*b[1])
        assert isinstance(h0, StepHandle) and isinstance(h0, Tensor)
        assert (h0.scheduled_step, h1.scheduled_step) == (0, 1)
        assert np.isfinite(h1.result())

    def test_deferred_skip_books_within_window_and_rewinds_schedule(self):
        paddle.set_flags({"async_dispatch": True, "async_window": 4,
                          "check_nan_inf": True})
        tr = _linear_trainer()
        tr.train_step(X, Y)
        tr.train_step(X, Y)
        tr.guard_sync()
        snap = {k: np.asarray(v).copy() for k, v in tr.params.items()}
        count = tr.optimizer._step_count
        tr.train_step(XNAN, Y)
        assert tr._nonfinite_total == 0          # not fetched yet
        assert len(tr._pending_verdicts) == 1    # in flight, in window
        tr.guard_sync()
        assert tr._nonfinite_total == 1
        assert tr.optimizer._step_count == count   # schedule rewound
        for k in snap:
            assert np.asarray(tr.params[k]).tobytes() \
                == snap[k].tobytes(), k

    def test_mid_window_skip_burns_its_position_no_rng_aliasing(self):
        """A skip that is NOT the newest dispatch must not rewind the
        schedule: later applied steps already consumed the following
        rng positions — rewinding would duplicate an applied step's
        dropout rng. Only a trailing skip rewinds (the retry slot)."""
        paddle.set_flags({"async_dispatch": True, "async_window": 8,
                          "check_nan_inf": True})
        tr = _linear_trainer()
        tr.train_step(X, Y)        # pos 0, applied
        tr.train_step(XNAN, Y)     # pos 1, skipped on device
        tr.train_step(X, Y)        # pos 2, applied
        tr.train_step(X, Y)        # pos 3, applied
        count = tr.optimizer._step_count
        tr.guard_sync()
        assert tr._nonfinite_total == 1
        assert tr.optimizer._step_count == count   # pos 1 burned
        # trailing skip: the newest dispatch DOES rewind (retry slot)
        tr.train_step(XNAN, Y)
        count = tr.optimizer._step_count
        tr.guard_sync()
        assert tr.optimizer._step_count == count - 1

    def test_deferred_raise_stays_within_max_skip_contract(self):
        paddle.set_flags({"async_dispatch": True, "async_window": 8,
                          "check_nan_inf": True, "max_skip_steps": 1})
        tr = _linear_trainer()
        tr.train_step(XNAN, Y)
        tr.train_step(XNAN, Y)
        with pytest.raises(FloatingPointError, match="max_skip_steps"):
            tr.guard_sync()

    def test_prefetch_double_buffers_and_stays_bit_exact(self):
        paddle.set_flags({"async_dispatch": True})
        batches = _batches(4)
        tr = _gpt_trainer()
        plain = [float(np.asarray(tr.train_step(*b)._data))
                 for b in batches]
        paddle.set_flags({"async_dispatch": True})
        tr2 = _gpt_trainer()
        losses = []
        tr2.prefetch(*batches[0])
        for i, b in enumerate(batches):
            # step N consumes its staged copies; batch N+1 is staged
            # while step N's device work is still in flight — the
            # double-buffer. Keyed by array object identity.
            losses.append(float(np.asarray(tr2.train_step(*b)._data)))
            if i + 1 < len(batches):
                tr2.prefetch(*batches[i + 1])
        assert tr2._prefetch_hits == 4
        assert losses == plain

    def test_benchmark_keeps_same_call_visibility(self):
        """FLAGS_benchmark forces a per-step device sync anyway — the
        deferred verdict settles inside the same call, preserving the
        pre-PR skip visibility for benchmarked runs."""
        paddle.set_flags({"async_dispatch": False, "check_nan_inf": True,
                          "benchmark": True})
        tr = _linear_trainer()
        tr.train_step(XNAN, Y)
        assert tr._nonfinite_total == 1          # no guard_sync needed


class TestServingAsync:
    def _model(self):
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=64, dropout=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m

    def test_async_engine_tokens_bit_exact_and_overlap_attributed(self):
        from paddle_tpu import trace
        from paddle_tpu.inference.serving import ServingEngine

        m = self._model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
                   for n in (5, 9, 4)]

        def run(async_on):
            paddle.set_flags({"async_dispatch": async_on})
            try:
                eng = ServingEngine(m, max_batch=2)
                rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
                res = eng.run_until_complete()
                return eng, {r: res[r].tokens.tolist() for r in rids}
            finally:
                paddle.set_flags({"async_dispatch": False})

        _, sync_tokens = run(False)
        trace.clear()
        trace.enable()
        try:
            eng, async_tokens = run(True)
        finally:
            trace.disable()
        assert sync_tokens == async_tokens
        bd = eng.stats()["breakdown"]["async_overlap"]
        assert bd["rounds"] > 0
        assert bd["dispatch_ms"] >= 0 and bd["overlap_ms"] >= 0
        names = {s.name for s in trace.spans()}
        assert "dispatch/decode" in names
        assert "dispatch/overlap" in names
        assert "dispatch/fetch" in names

    def test_plain_engine_has_no_async_breakdown_or_spans(self):
        from paddle_tpu import trace
        from paddle_tpu.inference.serving import ServingEngine

        m = self._model()
        trace.clear()
        trace.enable()
        try:
            eng = ServingEngine(m, max_batch=1)
            eng.submit(np.asarray([1, 2, 3], np.int32), max_new_tokens=3)
            eng.run_until_complete()
        finally:
            trace.disable()
        assert "async_overlap" not in eng.stats()["breakdown"]
        assert not [s.name for s in trace.spans()
                    if s.name.startswith("dispatch/")]


class TestOverlapGradComm:
    def test_overlap_legs_stay_in_quantized_band(self):
        """The overlapped (per-leg) quantized exchange vs the fused
        bundle: different stochastic-rounding draws, same quantization
        scheme — lockstep parity within the quantized_allreduce band."""
        from paddle_tpu.testing import parity

        def build():
            paddle.seed(0)
            cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                            num_heads=2, max_seq_len=32, dropout=0.0)
            model = GPTForCausalLM(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=model.parameters())
            mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
            return SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                               mesh=mesh)

        report = parity.run_parity(
            build, _batches(3),
            reference_flags={"quantized_allreduce": True,
                             "quantized_allreduce_min_size": 1},
            candidate_flags={"quantized_allreduce": True,
                             "quantized_allreduce_min_size": 1,
                             "overlap_grad_comm": True},
            loss_rtol=0.08, loss_atol=0.05, stat_rtol=0.6, stat_atol=0.1)
        assert not report["diverged"], report["first_divergence"]
