"""The end-to-end LM book test (ROADMAP item 5, VERDICT #7).

The train -> save -> serve proof on real (in-repo, deterministic) data:
tiny GPT trained on the character corpus via ``Model.fit`` to a pinned
loss threshold, checkpointed durably through ``CheckpointSaver``,
reloaded into a FRESH differently-seeded model, and served through
``ServingEngine`` — with the served greedy completion equal to the
direct ``generate()`` output, token for token.

The whole chain trains once (module-scoped fixture, ~7 s on the CPU
harness); the threshold (0.35) carries ~2x margin over the calibrated
16-epoch loss (~0.19).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.incubate.checkpoint.auto_checkpoint import CheckpointSaver
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

LOSS_THRESHOLD = 0.35
SEQ_LEN = 16
CFG = dict(vocab_size=32, hidden_size=64, num_layers=1, num_heads=2,
           max_seq_len=64, dropout=0.0)


@pytest.fixture(scope="module")
def corpus():
    return paddle.dataset.tiny_corpus()


@pytest.fixture(scope="module")
def trained(corpus):
    """Train once via Model.fit (jit adapter: the whole step is one XLA
    program, batch sharded over the 8-device dp mesh); returns
    (network, eval_loss)."""
    X, Y = corpus.examples(seq_len=SEQ_LEN, stride=4)

    class DS(paddle.io.Dataset):
        def __len__(self):
            return len(X)

        def __getitem__(self, i):
            return X[i], Y[i]

    paddle.seed(0)
    net = GPTForCausalLM(GPTConfig(**CFG))
    model = paddle.Model(net, use_jit=True)
    model.prepare(
        paddle.optimizer.Adam(learning_rate=3e-3,
                              parameters=net.parameters()),
        GPTPretrainLoss())
    model.fit(DS(), epochs=16, batch_size=16, shuffle=True, verbose=0,
              drop_last=True)
    logs = model.evaluate(DS(), batch_size=16, verbose=0)
    return net, float(logs["loss"])


@pytest.fixture(scope="module")
def checkpoint_dir(trained, tmp_path_factory):
    """Durable checkpoint of the trained weights via CheckpointSaver
    (atomic rename commit + corrupt-fallback recovery, docs/ROBUSTNESS.md)."""
    net, loss = trained
    d = tmp_path_factory.mktemp("book_lm_ckpt")
    saver = CheckpointSaver(str(d))
    no = saver.save_checkpoint({"model": net.state_dict()},
                               meta={"loss": loss})
    assert no == 0
    return str(d)


@pytest.fixture(scope="module")
def restored(checkpoint_dir):
    """A FRESH model (different seed — nothing survives but the
    checkpoint bytes) restored from the newest valid checkpoint."""
    state, meta = CheckpointSaver(checkpoint_dir).load_checkpoint()
    assert state is not None and "loss" in meta
    paddle.seed(12345)
    net = GPTForCausalLM(GPTConfig(**CFG))
    net.set_state_dict(state["model"])
    net.eval()
    return net, meta


def _greedy_new_tokens(net, prompt, n):
    out = net.generate(paddle.to_tensor(prompt[None]), max_new_tokens=n,
                       temperature=0)
    seqs = out[0] if isinstance(out, tuple) else out
    ids = np.asarray(seqs._data if hasattr(seqs, "_data") else seqs)
    return ids[0, len(prompt):]


class TestBookLM:
    def test_fit_reaches_loss_threshold(self, trained):
        _, loss = trained
        assert np.isfinite(loss)
        assert loss < LOSS_THRESHOLD, (
            f"tiny-GPT Model.fit stalled at loss {loss:.4f} "
            f">= {LOSS_THRESHOLD}")

    def test_checkpoint_restores_identical_weights(self, trained,
                                                   restored):
        net, _ = trained
        net2, meta = restored
        want = {n: np.asarray(t._data)
                for n, t in net.state_dict().items()}
        got = {n: np.asarray(t._data)
               for n, t in net2.state_dict().items()}
        assert sorted(want) == sorted(got)
        for n in want:
            np.testing.assert_array_equal(want[n], got[n], err_msg=n)
        assert meta["loss"] < LOSS_THRESHOLD

    def test_served_completions_match_direct_generate(self, restored,
                                                      corpus):
        """The book proof's last leg: the checkpoint served through the
        continuous-batching ServingEngine decodes the SAME greedy tokens
        as direct generate(), across interleaved requests — and the
        completion is real learned structure (in-vocabulary text), not
        noise."""
        net, _ = restored
        prompts = [corpus.encode("the cat "), corpus.encode("the owl ")]
        n_new = 10
        eng = ServingEngine(net, max_batch=2)
        rids = [eng.submit(p, max_new_tokens=n_new) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            served = np.asarray(res[rid].tokens)
            np.testing.assert_array_equal(served,
                                          _greedy_new_tokens(net, p, n_new))
            assert all(0 <= t < corpus.vocab_size for t in served)
            assert set(corpus.decode(served)) <= set(corpus.text)
