"""Adversarial numerics sweep (VERDICT r2 #10): bf16 tolerance tiers and
degenerate shapes for the op families most likely to ship in user models.

bf16 tier: ops run on bfloat16 inputs and must stay within bf16-appropriate
tolerance of their float32 result (rtol ~1e-2 — one part in 2^8 mantissa).
Degenerate tier: len-0 sequences, empty box sets, single-element reductions,
all-ignored losses — shapes real pipelines hit at epoch boundaries."""
import ml_dtypes
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F

BF16 = ml_dtypes.bfloat16


def _np(t):
    return np.asarray(t._data)


def _t32(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


def _t16(a):
    return paddle.to_tensor(np.asarray(a, np.float32).astype(BF16))


def _close_bf16(got, want, rtol=2e-2, atol=2e-2):
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               rtol=rtol, atol=atol)


class TestBf16Tier:
    """Each op: bf16 result within bf16 tolerance of its f32 result."""

    def setup_method(self, _):
        self.rng = np.random.RandomState(0)

    def test_matmul(self):
        a, b = self.rng.randn(16, 32), self.rng.randn(32, 8)
        _close_bf16(_np(paddle.matmul(_t16(a), _t16(b))),
                    _np(paddle.matmul(_t32(a), _t32(b))), rtol=3e-2, atol=5e-2)

    def test_linear_layer(self):
        paddle.seed(0)
        lin = nn.Linear(24, 12)
        x = self.rng.randn(6, 24)
        want = _np(lin(_t32(x)))
        with paddle.amp.auto_cast(True, dtype="bfloat16"):
            got = _np(lin(_t32(x)))
        _close_bf16(got, want, rtol=3e-2, atol=5e-2)

    def test_softmax_log_softmax(self):
        x = self.rng.randn(5, 64) * 4
        _close_bf16(_np(F.softmax(_t16(x))), _np(F.softmax(_t32(x))),
                    atol=1e-2)
        _close_bf16(_np(F.log_softmax(_t16(x))), _np(F.log_softmax(_t32(x))),
                    rtol=3e-2, atol=5e-2)

    def test_layer_norm(self):
        paddle.seed(0)
        ln = nn.LayerNorm([32])
        x = self.rng.randn(4, 32) * 10 + 3
        _close_bf16(_np(ln(_t16(x))), _np(ln(_t32(x))), rtol=3e-2, atol=5e-2)

    def test_batch_norm_eval(self):
        paddle.seed(0)
        bn = nn.BatchNorm2D(3)
        bn.eval()
        x = self.rng.randn(2, 3, 8, 8)
        _close_bf16(_np(bn(_t16(x))), _np(bn(_t32(x))), rtol=3e-2, atol=5e-2)

    def test_conv2d(self):
        paddle.seed(0)
        conv = nn.Conv2D(3, 6, 3, padding=1)
        x = self.rng.randn(2, 3, 8, 8)
        _close_bf16(_np(conv(_t16(x))), _np(conv(_t32(x))),
                    rtol=3e-2, atol=8e-2)

    def test_cross_entropy_bf16_finite_and_close(self):
        logits = self.rng.randn(16, 128) * 3
        labels = paddle.to_tensor(
            self.rng.randint(0, 128, 16).astype(np.int64))
        got = _np(F.cross_entropy(_t16(logits), labels))
        want = _np(F.cross_entropy(_t32(logits), labels))
        assert got.dtype == BF16  # output-dtype parity
        _close_bf16(got, want, rtol=3e-2, atol=5e-2)

    def test_sdpa_attention(self):
        q = self.rng.randn(2, 4, 16, 8)
        k = self.rng.randn(2, 4, 16, 8)
        v = self.rng.randn(2, 4, 16, 8)
        f = F.scaled_dot_product_attention
        _close_bf16(_np(f(_t16(q), _t16(k), _t16(v))),
                    _np(f(_t32(q), _t32(k), _t32(v))), rtol=3e-2, atol=5e-2)

    def test_mean_sum_large_reduction(self):
        # 64k elements: naive bf16 accumulation would lose ~all precision;
        # reductions must accumulate wider
        x = np.full((65536,), 1.001)
        got = float(np.asarray(_np(paddle.mean(_t16(x))), np.float32))
        assert abs(got - 1.001) < 2e-2, got

    def test_gelu_tanh_activations(self):
        x = self.rng.randn(64) * 3
        _close_bf16(_np(F.gelu(_t16(x))), _np(F.gelu(_t32(x))),
                    rtol=3e-2, atol=3e-2)
        _close_bf16(_np(paddle.tanh(_t16(x))), _np(paddle.tanh(_t32(x))),
                    atol=1e-2)

    def test_adamw_step_bf16_grads(self):
        paddle.seed(0)
        lin = nn.Linear(8, 4)
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=lin.parameters())
        x = _t16(self.rng.randn(4, 8))
        with paddle.amp.auto_cast(True, dtype="bfloat16"):
            lin(x).sum().backward()
        opt.step()
        for p in lin.parameters():
            assert np.isfinite(_np(p).astype(np.float32)).all()


class TestDegenerateShapes:
    def test_nms_empty_and_all_below_threshold(self):
        from paddle_tpu.vision.ops import matrix_nms, multiclass_nms

        boxes = np.array([[[0, 0, 4, 4], [1, 1, 5, 5]]], np.float32)
        scores = np.full((1, 3, 2), 0.001, np.float32)
        out, num = multiclass_nms(paddle.to_tensor(boxes),
                                  paddle.to_tensor(scores),
                                  score_threshold=0.5)
        assert int(_np(num)[0]) == 0
        out2, num2 = matrix_nms(paddle.to_tensor(boxes),
                                paddle.to_tensor(
                                    np.full((1, 2, 2), 0.001, np.float32)),
                                score_threshold=0.5, keep_top_k=4)
        assert int(_np(num2)[0]) == 0

    def test_sequence_ops_len0(self):
        x = paddle.to_tensor(np.ones((3, 4, 2), np.float32))
        lens = paddle.to_tensor(np.array([0, 2, 4], np.int64))
        for mode in ("sum", "average", "max"):
            out = F.sequence_pool(x, lens, pool_type=mode)
            v = _np(out)
            assert np.isfinite(v).all(), mode
            assert np.allclose(v[0], 0.0), (mode, v[0])  # len-0 row is zero
        x2 = paddle.to_tensor(np.ones((3, 4), np.float32))
        sm = F.sequence_softmax(x2, lens)
        v = _np(sm)
        assert np.isfinite(v).all()
        np.testing.assert_allclose(v[0], 0.0)  # len-0 row: all-pad -> 0 prob
        rv = F.sequence_reverse(x, lens)
        assert np.isfinite(_np(rv)).all()

    def test_single_element_reductions(self):
        one = paddle.to_tensor(np.array([3.5], np.float32))
        assert float(_np(paddle.mean(one))) == 3.5
        assert float(_np(paddle.max(one))) == 3.5
        assert float(_np(paddle.std(one))) == 0.0 or np.isnan(
            float(_np(paddle.std(one))))  # N-1 denominator: nan is honest
        scalar = paddle.to_tensor(np.float32(2.0))
        assert float(_np(paddle.sum(scalar))) == 2.0

    def test_topk_k_equals_size_and_argmax_single(self):
        x = paddle.to_tensor(np.array([3.0, 1.0, 2.0], np.float32))
        vals, idx = paddle.topk(x, k=3)
        np.testing.assert_allclose(_np(vals), [3.0, 2.0, 1.0])
        y = paddle.to_tensor(np.array([[7.0]], np.float32))
        assert int(_np(paddle.argmax(y))) == 0

    def test_cross_entropy_all_ignored(self):
        logits = _t32(np.random.RandomState(0).randn(4, 6))
        labels = paddle.to_tensor(np.full((4,), -100, np.int64))
        out = F.cross_entropy(logits, labels, ignore_index=-100)
        assert np.isfinite(_np(out)).all()  # 0/0 guard: mean over none
        np.testing.assert_allclose(float(_np(out)), 0.0, atol=1e-6)

    def test_viterbi_len1_and_min_lengths(self):
        from paddle_tpu.text import viterbi_decode

        pot = _t32(np.random.RandomState(0).randn(2, 1, 4))
        trans = _t32(np.random.RandomState(1).randn(4, 4))
        lens = paddle.to_tensor(np.array([1, 1], np.int64))
        score, path = viterbi_decode(pot, trans, lens,
                                     include_bos_eos_tag=False)
        assert _np(path).shape == (2, 1)
        assert np.isfinite(_np(score)).all()

    def test_ctc_loss_zero_length_label(self):
        logp = _t32(np.random.RandomState(0).randn(6, 2, 5))
        labels = paddle.to_tensor(np.zeros((2, 3), np.int32))
        in_lens = paddle.to_tensor(np.array([6, 6], np.int64))
        lab_lens = paddle.to_tensor(np.array([0, 2], np.int64))
        loss = F.ctc_loss(logp, labels, in_lens, lab_lens)
        assert np.isfinite(_np(loss).astype(np.float32)).all()

    def test_clip_degenerate_range(self):
        x = _t32([-1.0, 0.5, 2.0])
        np.testing.assert_allclose(_np(paddle.clip(x, min=1.0, max=1.0)), 1.0)

    def test_embedding_all_padding(self):
        w = _t32(np.random.RandomState(0).randn(6, 4))
        ids = paddle.to_tensor(np.zeros((3,), np.int64))
        out = F.embedding(ids, w, padding_idx=0)
        np.testing.assert_allclose(_np(out), 0.0)

    def test_interpolate_to_one_pixel(self):
        x = _t32(np.random.RandomState(0).rand(1, 2, 8, 8))
        out = F.interpolate(x, size=[1, 1], mode="bilinear")
        assert tuple(out.shape) == (1, 2, 1, 1)
        assert np.isfinite(_np(out)).all()

    def test_roi_align_zero_area_box(self):
        from paddle_tpu.vision.ops import roi_align

        x = _t32(np.random.RandomState(0).rand(1, 2, 8, 8))
        boxes = paddle.to_tensor(np.array([[3.0, 3.0, 3.0, 3.0]], np.float32))
        out = roi_align(x, boxes,
                        boxes_num=paddle.to_tensor(np.array([1], np.int32)),
                        output_size=2)
        assert np.isfinite(_np(out)).all()

    def test_concat_with_zero_dim(self):
        a = _t32(np.ones((0, 4)))
        b = _t32(np.ones((3, 4)))
        out = paddle.concat([a, b], axis=0)
        assert tuple(out.shape) == (3, 4)

    def test_norm_single_and_bn_batch1(self):
        paddle.seed(0)
        bn = nn.BatchNorm2D(2)
        bn.eval()
        out = bn(_t32(np.random.RandomState(0).rand(1, 2, 1, 1)))
        assert np.isfinite(_np(out)).all()
        ln = nn.LayerNorm([1])
        out2 = ln(_t32(np.ones((2, 1))))
        assert np.isfinite(_np(out2)).all()  # zero variance row

    def test_bipartite_match_degenerate(self):
        from paddle_tpu.vision.ops import bipartite_match

        dist = _t32(np.zeros((1, 3)))  # all-zero similarity
        idx, d = bipartite_match(dist)
        assert _np(idx).shape == (3,)

    def test_expand_and_tile_zero_sized(self):
        x = _t32(np.ones((1, 3)))
        out = paddle.expand(x, [4, 3])
        assert tuple(out.shape) == (4, 3)
        g = paddle.gather(
            _t32(np.arange(5)), paddle.to_tensor(np.array([], np.int64)))
        assert tuple(g.shape) == (0,)
