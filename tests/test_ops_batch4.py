"""Op-test burn-down, batch 4 (VERDICT r1 #3): trig/special/rounding math,
int/bool edge dtypes, comparison/logical/bitwise families, cast matrix —
numpy-referenced with gradient checks wherever a grad exists (reference
op_test.py:255 pattern, table-driven)."""
import numpy as np
import pytest
import scipy.special as sps

import paddle_tpu as paddle

from op_test import OpTest

rng = np.random.RandomState(11)


def _randn(*shape):
    return rng.randn(*shape).astype(np.float32)


def _pos(*shape):
    return (rng.rand(*shape) + 0.5).astype(np.float32)


def _unit(*shape):
    return (rng.rand(*shape) * 1.6 - 0.8).astype(np.float32)


X = _randn(3, 4)
P = _pos(3, 4)
U = _unit(3, 4)
I32 = rng.randint(-10, 10, (3, 4)).astype(np.int32)
J32 = rng.randint(1, 10, (3, 4)).astype(np.int32)
I64 = rng.randint(-10, 10, (3, 4)).astype(np.int64)
B1 = rng.rand(3, 4) > 0.5
B2 = rng.rand(3, 4) > 0.5

CASES = [
    # --- trigonometry ------------------------------------------------------
    ("sin", paddle.sin, {"x": X}, {}, [np.sin(X)], ["x"]),
    ("cos", paddle.cos, {"x": X}, {}, [np.cos(X)], ["x"]),
    ("tan", paddle.tan, {"x": U}, {}, [np.tan(U)], ["x"]),
    ("asin", paddle.asin, {"x": U}, {}, [np.arcsin(U)], ["x"]),
    ("acos", paddle.acos, {"x": U}, {}, [np.arccos(U)], ["x"]),
    ("atan", paddle.atan, {"x": X}, {}, [np.arctan(X)], ["x"]),
    ("sinh", paddle.sinh, {"x": X}, {}, [np.sinh(X)], ["x"]),
    ("cosh", paddle.cosh, {"x": X}, {}, [np.cosh(X)], ["x"]),
    ("tanh2", paddle.tanh, {"x": U}, {}, [np.tanh(U)], ["x"]),
    ("asinh", paddle.asinh, {"x": X}, {}, [np.arcsinh(X)], ["x"]),
    ("acosh", paddle.acosh, {"x": P + 1.0}, {}, [np.arccosh(P + 1.0)], ["x"]),
    ("atanh", paddle.atanh, {"x": U}, {}, [np.arctanh(U)], ["x"]),
    ("atan2", paddle.atan2, {"x": X, "y": P}, {}, [np.arctan2(X, P)],
     ["x", "y"]),
    ("deg2rad", paddle.deg2rad, {"x": X * 90}, {}, [np.deg2rad(X * 90)], None),
    ("rad2deg", paddle.rad2deg, {"x": X}, {}, [np.rad2deg(X)], None),
    # --- exp/log family ----------------------------------------------------
    ("expm1", paddle.expm1, {"x": U}, {}, [np.expm1(U)], ["x"]),
    ("log1p", paddle.log1p, {"x": P}, {}, [np.log1p(P)], ["x"]),
    ("log2", paddle.log2, {"x": P}, {}, [np.log2(P)], ["x"]),
    ("log10", paddle.log10, {"x": P}, {}, [np.log10(P)], ["x"]),
    ("logit", paddle.logit, {"x": (rng.rand(3, 4) * 0.8 + 0.1).astype(np.float32)},
     {}, None, ["x"]),
    ("logaddexp", paddle.logaddexp, {"x": X, "y": X.T.copy().T}, {},
     [np.logaddexp(X, X)], None) if hasattr(paddle, "logaddexp") else None,
    # --- special functions -------------------------------------------------
    ("erf", paddle.erf, {"x": X}, {}, [sps.erf(X)], ["x"]),
    ("erfinv", paddle.erfinv, {"x": U * 0.9}, {}, [sps.erfinv(U * 0.9)], ["x"]),
    ("lgamma", paddle.lgamma, {"x": P + 0.5}, {}, [sps.gammaln(P + 0.5)], ["x"]),
    ("digamma", paddle.digamma, {"x": P + 0.5}, {}, [sps.digamma(P + 0.5)], ["x"]),
    ("i0", paddle.i0, {"x": U}, {}, [sps.i0(U)], None),
    ("polygamma", paddle.polygamma, {"x": P + 1.0}, {"n": 1},
     [sps.polygamma(1, P + 1.0).astype(np.float32)], None),
    # --- rounding / parts --------------------------------------------------
    ("trunc", paddle.trunc, {"x": X * 3}, {}, [np.trunc(X * 3)], None),
    ("frac", paddle.frac, {"x": X * 3}, {}, [X * 3 - np.trunc(X * 3)], None),
    ("nan_to_num",
     paddle.nan_to_num,
     {"x": np.array([[np.nan, np.inf, -np.inf, 1.0]], np.float32)}, {},
     [np.array([[0.0, np.finfo(np.float32).max,
                 np.finfo(np.float32).min, 1.0]], np.float32)], None),
    ("isfinite", paddle.isfinite,
     {"x": np.array([1.0, np.inf, np.nan], np.float32)}, {},
     [np.array([True, False, False])], None),
    ("isinf", paddle.isinf,
     {"x": np.array([1.0, np.inf, np.nan], np.float32)}, {},
     [np.array([False, True, False])], None),
    ("isnan", paddle.isnan,
     {"x": np.array([1.0, np.inf, np.nan], np.float32)}, {},
     [np.array([False, False, True])], None),
    # --- binary math -------------------------------------------------------
    ("remainder_f", paddle.remainder, {"x": X * 5, "y": P * 2}, {},
     [np.mod(X * 5, P * 2)], None),
    ("remainder_i", paddle.remainder, {"x": I32, "y": J32}, {},
     [np.mod(I32, J32)], None),
    ("mod_alias", paddle.mod, {"x": I64, "y": J32.astype(np.int64)}, {},
     [np.mod(I64, J32.astype(np.int64))], None),
    ("floor_divide", paddle.floor_divide, {"x": I32, "y": J32}, {},
     [I32 // J32], None),
    ("fmax", paddle.fmax, {"x": X, "y": X[::-1].copy()}, {},
     [np.fmax(X, X[::-1])], None),
    ("fmin", paddle.fmin, {"x": X, "y": X[::-1].copy()}, {},
     [np.fmin(X, X[::-1])], None),
    ("heaviside", paddle.heaviside, {"x": X, "y": P}, {},
     [np.heaviside(X, P)], None),
    ("hypot", paddle.hypot, {"x": X, "y": P}, {}, [np.hypot(X, P)],
     ["x", "y"]),
    ("lerp", paddle.lerp, {"x": X, "y": P, "weight": np.float32(0.3)}, {},
     [X + 0.3 * (P - X)], None),
    ("copysign", paddle.copysign, {"x": P, "y": X}, {},
     [np.copysign(P, X)], None),
    ("nextafter", paddle.nextafter, {"x": X, "y": P}, {},
     [np.nextafter(X, P)], None),
    ("ldexp", paddle.ldexp, {"x": X, "y": J32[:, :4].astype(np.float32)}, {},
     [np.ldexp(X, J32)], None),
    ("frexp", paddle.frexp, {"x": P}, {},
     list(np.frexp(P)), None),
    ("gcd", paddle.gcd, {"x": np.abs(I64) + 1, "y": J32.astype(np.int64)}, {},
     [np.gcd(np.abs(I64) + 1, J32.astype(np.int64))], None),
    ("lcm", paddle.lcm, {"x": np.abs(I64) + 1, "y": J32.astype(np.int64)}, {},
     [np.lcm(np.abs(I64) + 1, J32.astype(np.int64))], None),
    # --- int/bool dtype edges for core elementwise ------------------------
    ("add_i32", paddle.add, {"x": I32, "y": J32}, {}, [I32 + J32], None),
    ("add_i64", paddle.add, {"x": I64, "y": I64}, {}, [I64 + I64], None),
    ("mul_i32", paddle.multiply, {"x": I32, "y": J32}, {}, [I32 * J32], None),
    ("sub_i64", paddle.subtract, {"x": I64, "y": I64}, {}, [I64 - I64], None),
    ("abs_i32", paddle.abs, {"x": I32}, {}, [np.abs(I32)], None),
    ("sign_i32", paddle.sign, {"x": I32}, {}, [np.sign(I32)], None),
    ("max_i64", paddle.maximum, {"x": I64, "y": -I64}, {},
     [np.maximum(I64, -I64)], None),
    ("pow_i32", paddle.pow, {"x": J32}, {"y": 2},
     [(J32.astype(np.int64) ** 2).astype(np.int32)], None),
    ("sum_bool", paddle.sum, {"x": B1}, {}, [B1.sum()], None),
    ("sum_i32_axis", paddle.sum, {"x": I32}, {"axis": 0}, [I32.sum(0)], None),
    ("prod_i64", paddle.prod, {"x": np.abs(I64[:2, :2]) % 3 + 1}, {},
     [(np.abs(I64[:2, :2]) % 3 + 1).prod()], None),
    ("cumsum_i32", paddle.cumsum, {"x": I32}, {"axis": 1},
     [I32.cumsum(1)], None),
    # --- comparisons (float + int) ----------------------------------------
    ("equal_f", paddle.equal, {"x": X, "y": X.copy()}, {}, [X == X], None),
    ("equal_i", paddle.equal, {"x": I32, "y": J32}, {}, [I32 == J32], None),
    ("not_equal", paddle.not_equal, {"x": I32, "y": J32}, {},
     [I32 != J32], None),
    ("greater_than", paddle.greater_than, {"x": X, "y": U}, {}, [X > U], None),
    ("greater_equal", paddle.greater_equal, {"x": I32, "y": J32}, {},
     [I32 >= J32], None),
    ("less_than", paddle.less_than, {"x": X, "y": U}, {}, [X < U], None),
    ("less_equal", paddle.less_equal, {"x": I32, "y": J32}, {},
     [I32 <= J32], None),
    # --- logical ------------------------------------------------------------
    ("logical_and", paddle.logical_and, {"x": B1, "y": B2}, {},
     [B1 & B2], None),
    ("logical_or", paddle.logical_or, {"x": B1, "y": B2}, {}, [B1 | B2], None),
    ("logical_xor", paddle.logical_xor, {"x": B1, "y": B2}, {},
     [B1 ^ B2], None),
    ("logical_not", paddle.logical_not, {"x": B1}, {}, [~B1], None),
    ("logical_and_i", paddle.logical_and, {"x": I32, "y": J32}, {},
     [(I32 != 0) & (J32 != 0)], None),
    # --- bitwise ------------------------------------------------------------
    ("bitwise_and", paddle.bitwise_and, {"x": I32, "y": J32}, {},
     [I32 & J32], None),
    ("bitwise_or", paddle.bitwise_or, {"x": I32, "y": J32}, {},
     [I32 | J32], None),
    ("bitwise_xor", paddle.bitwise_xor, {"x": I32, "y": J32}, {},
     [I32 ^ J32], None),
    ("bitwise_not", paddle.bitwise_not, {"x": I32}, {}, [~I32], None),
    ("bitwise_and_b", paddle.bitwise_and, {"x": B1, "y": B2}, {},
     [B1 & B2], None),
    # --- reductions ---------------------------------------------------------
    ("amax", paddle.amax, {"x": X}, {"axis": 1}, [X.max(1)], None),
    ("amin", paddle.amin, {"x": X}, {"axis": 0}, [X.min(0)], None),
    ("all_op", paddle.all, {"x": B1}, {"axis": 1}, [B1.all(1)], None),
    ("any_op", paddle.any, {"x": B1}, {"axis": 0}, [B1.any(0)], None),
    ("count_nonzero", paddle.count_nonzero, {"x": I32}, {},
     [np.count_nonzero(I32)], None),
    ("logsumexp", paddle.logsumexp, {"x": X}, {"axis": 1},
     [sps.logsumexp(X, axis=1)], ["x"]),
    ("logcumsumexp", paddle.logcumsumexp, {"x": X}, {"axis": 1},
     [np.logaddexp.accumulate(X, axis=1)], None),
    ("nanmean", paddle.nanmean,
     {"x": np.where(B1, X, np.nan).astype(np.float32)}, {},
     [np.nanmean(np.where(B1, X, np.nan))], None),
    ("nansum", paddle.nansum,
     {"x": np.where(B1, X, np.nan).astype(np.float32)}, {},
     [np.nansum(np.where(B1, X, np.nan))], None),
    ("diff", paddle.diff, {"x": X}, {}, [np.diff(X)], None),
    # --- misc math ----------------------------------------------------------
    ("sgn_real", paddle.sgn, {"x": X}, {}, [np.sign(X)], None),
    ("multiply_scalar_like", paddle.scale, {"x": X},
     {"scale": 2.5, "bias": 1.0}, [X * 2.5 + 1.0], ["x"])
    if hasattr(paddle, "scale") else None,
    ("stanh", paddle.stanh, {"x": X}, {},
     [1.7159 * np.tanh(0.67 * X)], ["x"]) if hasattr(paddle, "stanh") else None,
    ("cast_f2i", paddle.cast, {"x": X * 3}, {"dtype": "int32"},
     [(X * 3).astype(np.int32)], None),
    ("cast_i2f", paddle.cast, {"x": I32}, {"dtype": "float32"},
     [I32.astype(np.float32)], None),
    ("cast_f2b", paddle.cast, {"x": np.array([0.0, 1.0, -2.0], np.float32)},
     {"dtype": "bool"}, [np.array([False, True, True])], None),
    ("cast_b2i", paddle.cast, {"x": B1}, {"dtype": "int64"},
     [B1.astype(np.int64)], None),
    ("vander", paddle.vander, {"x": np.array([1.0, 2.0, 3.0], np.float32)},
     {"n": 4}, [np.vander(np.array([1.0, 2.0, 3.0]), 4)], None),
    ("kron", paddle.kron, {"x": X[:2, :2], "y": X[:2, :2]}, {},
     [np.kron(X[:2, :2], X[:2, :2])], ["x", "y"]),
    ("outer", paddle.outer, {"x": X[0], "y": X[1]}, {},
     [np.outer(X[0], X[1])], ["x", "y"]),
    ("inner", paddle.inner, {"x": X, "y": X}, {}, [np.inner(X, X)], None),
    ("dot", paddle.dot, {"x": X[0], "y": X[1]}, {},
     [np.dot(X[0], X[1])], ["x", "y"]),
    ("cross", paddle.cross, {"x": _randn(3, 3), "y": _randn(3, 3)},
     {"axis": 1}, None, ["x", "y"]),
    ("trace", paddle.trace, {"x": X[:3, :3]}, {}, [np.trace(X[:3, :3])],
     ["x"]),
    ("diagonal", paddle.diagonal, {"x": X[:3, :3]}, {},
     [np.diagonal(X[:3, :3])], None),
]
CASES = [c for c in CASES if c is not None]


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op(case):
    name, op, inputs, attrs, outputs, grad_inputs = case
    t = OpTest()
    t.op = op
    t.inputs = inputs
    t.attrs = attrs
    t.outputs = outputs
    if outputs is not None:
        t.check_output(atol=1e-4, rtol=1e-4)
    if grad_inputs:
        t.check_grad(grad_inputs)
