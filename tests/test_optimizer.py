"""Optimizer update-rule correctness vs hand-computed references
(operators/optimizers/*_op.cc math) + LR schedules (optimizer/lr.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import optimizer as opt


def make_param(val):
    return paddle.ParamBase(np.asarray(val, dtype=np.float32))


def set_grad(p, g):
    p.grad = paddle.to_tensor(np.asarray(g, dtype=np.float32))


class TestRules:
    def test_sgd(self):
        p = make_param([1.0, 2.0])
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        set_grad(p, [1.0, 1.0])
        o.step()
        np.testing.assert_allclose(p.numpy(), [0.9, 1.9], rtol=1e-6)

    def test_momentum(self):
        p = make_param([1.0])
        o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
        set_grad(p, [1.0])
        o.step()
        np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-6)
        set_grad(p, [1.0])
        o.step()
        # v = 0.9*1 + 1 = 1.9 ; p = 0.9 - 0.1*1.9 = 0.71
        np.testing.assert_allclose(p.numpy(), [0.71], rtol=1e-5)

    def test_adam_matches_reference_formula(self):
        p = make_param([1.0])
        o = opt.Adam(learning_rate=0.1, beta1=0.9, beta2=0.999, epsilon=1e-8, parameters=[p])
        g = 0.5
        set_grad(p, [g])
        o.step()
        m = 0.1 * g
        v = 0.001 * g * g
        lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
        expect = 1.0 - lr_t * m / (np.sqrt(v) + 1e-8)
        np.testing.assert_allclose(p.numpy(), [expect], rtol=1e-5)

    def test_adamw_decoupled_decay(self):
        p1 = make_param([1.0])
        p2 = make_param([1.0])
        a = opt.Adam(learning_rate=0.1, parameters=[p1], weight_decay=0.0)
        w = opt.AdamW(learning_rate=0.1, parameters=[p2], weight_decay=0.1)
        set_grad(p1, [0.5])
        set_grad(p2, [0.5])
        a.step()
        w.step()
        assert p2.numpy()[0] < p1.numpy()[0]  # decay shrinks the weight

    def test_adagrad_rmsprop_adadelta_adamax(self):
        for cls, kw in [
            (opt.Adagrad, dict(learning_rate=0.1)),
            (opt.RMSProp, dict(learning_rate=0.1)),
            (opt.Adadelta, dict(learning_rate=1.0)),
            (opt.Adamax, dict(learning_rate=0.1)),
            (opt.Ftrl, dict(learning_rate=0.1)),
        ]:
            p = make_param([1.0, -1.0])
            o = cls(parameters=[p], **kw)
            before = p.numpy().copy()
            for _ in range(3):
                set_grad(p, [0.5, -0.5])
                o.step()
            assert not np.allclose(p.numpy(), before)

    def test_lamb_trust_ratio(self):
        p = make_param(np.ones(10))
        o = opt.Lamb(learning_rate=0.01, parameters=[p])
        set_grad(p, np.full(10, 0.1))
        o.step()
        assert (p.numpy() < 1.0).all()

    def test_lars(self):
        p = make_param(np.ones(10))
        o = opt.Lars(learning_rate=0.1, parameters=[p])
        set_grad(p, np.full(10, 0.1))
        o.step()
        assert (p.numpy() < 1.0).all()

    def test_weight_decay_l2(self):
        p = make_param([1.0])
        o = opt.SGD(learning_rate=0.1, parameters=[p], weight_decay=0.5)
        set_grad(p, [0.0])
        o.step()
        # g_eff = 0 + 0.5*1 -> p = 1 - 0.05
        np.testing.assert_allclose(p.numpy(), [0.95], rtol=1e-5)

    def test_grad_clip_in_step(self):
        from paddle_tpu.nn import ClipGradByGlobalNorm

        p = make_param(np.zeros(4))
        o = opt.SGD(learning_rate=1.0, parameters=[p], grad_clip=ClipGradByGlobalNorm(1.0))
        set_grad(p, np.full(4, 100.0))
        o.step()
        np.testing.assert_allclose(np.linalg.norm(p.numpy()), 1.0, rtol=1e-4)

    def test_minimize_and_state_dict(self):
        p = make_param([2.0])
        o = opt.Adam(learning_rate=0.1, parameters=[p])
        x = paddle.to_tensor(np.array([3.0], np.float32))
        loss = (p * x).sum()
        o.minimize(loss)
        sd = o.state_dict()
        assert "step" in sd
        o2 = opt.Adam(learning_rate=0.1, parameters=[p])
        o2.set_state_dict(sd)
        assert o2._step_count == 1


class TestFunctionalView:
    def test_functional_matches_eager(self):
        p_eager = make_param(np.ones(4))
        o1 = opt.Adam(learning_rate=0.1, parameters=[p_eager])
        g = np.full(4, 0.3, np.float32)
        set_grad(p_eager, g)
        o1.step()

        o2 = opt.Adam(learning_rate=0.1)
        params = {"w": np.ones(4, np.float32)}
        state = o2.functional_init({"w": paddle.to_tensor(params["w"])._data})
        new_p, new_s = o2.functional_apply(
            {"w": paddle.to_tensor(params["w"])._data}, {"w": paddle.to_tensor(g)._data}, state
        )
        np.testing.assert_allclose(np.asarray(new_p["w"]), p_eager.numpy(), rtol=1e-6)


class TestLRSchedules:
    def test_step_decay(self):
        s = opt.lr.StepDecay(learning_rate=1.0, step_size=2, gamma=0.5)
        vals = [s()]
        for _ in range(4):
            s.step()
            vals.append(s())
        np.testing.assert_allclose(vals, [1.0, 1.0, 0.5, 0.5, 0.25])

    def test_multistep_piecewise_exp(self):
        s = opt.lr.MultiStepDecay(1.0, [2, 4], 0.1)
        for _ in range(5):
            s.step()
        np.testing.assert_allclose(s(), 0.01, rtol=1e-6)
        pw = opt.lr.PiecewiseDecay([2, 4], [0.1, 0.05, 0.01])
        assert pw() == 0.1
        e = opt.lr.ExponentialDecay(1.0, 0.9)
        e.step()
        np.testing.assert_allclose(e(), 0.9, rtol=1e-6)

    def test_warmup_cosine_noam(self):
        w = opt.lr.LinearWarmup(learning_rate=1.0, warmup_steps=10, start_lr=0.0, end_lr=1.0)
        for _ in range(5):
            w.step()
        assert 0.4 < w() < 0.6
        c = opt.lr.CosineAnnealingDecay(1.0, T_max=10)
        for _ in range(10):
            c.step()
        np.testing.assert_allclose(c(), 0.0, atol=1e-6)
        n = opt.lr.NoamDecay(d_model=512, warmup_steps=100)
        assert n() > 0

    def test_reduce_on_plateau(self):
        r = opt.lr.ReduceOnPlateau(learning_rate=1.0, patience=1, factor=0.5)
        r.step(1.0)
        r.step(1.0)
        r.step(1.0)
        assert r() == 0.5

    def test_scheduler_with_optimizer(self):
        p = make_param([1.0])
        sched = opt.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.1)
        o = opt.SGD(learning_rate=sched, parameters=[p])
        assert o.get_lr() == 0.1
        sched.step()
        assert abs(o.get_lr() - 0.01) < 1e-9
        set_grad(p, [1.0])
        o.step()
        np.testing.assert_allclose(p.numpy(), [1.0 - 0.01], rtol=1e-5)


class TestAmp:
    def test_autocast_matmul_bf16(self):
        import jax.numpy as jnp

        a = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        b = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
        with paddle.amp.auto_cast(True, dtype="bfloat16"):
            out = paddle.matmul(a, b)
        assert out.dtype == jnp.bfloat16.dtype

    def test_grad_scaler_roundtrip(self):
        p = make_param([1.0])
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
        x = paddle.to_tensor(np.array([2.0], np.float32))
        loss = (p * x).sum()
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(o)
        # grad was 2*2=4 scaled, unscaled to 2 -> p = 1 - 0.2
        np.testing.assert_allclose(p.numpy(), [0.8], rtol=1e-5)

    def test_scaler_skips_inf(self):
        p = make_param([1.0])
        o = opt.SGD(learning_rate=0.1, parameters=[p])
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        p.grad = paddle.to_tensor(np.array([np.inf], np.float32))
        scaler.step(o)
        np.testing.assert_allclose(p.numpy(), [1.0])  # update skipped
        assert scaler._scale < 4.0 or scaler._bad_steps > 0
