"""Tier-1 gate for the black-box flight recorder (ISSUE 7): with
FLAGS_blackbox unset every beacon()/note() call site is a single boolean
check — no beacon registers, nothing lands in the ring, no blackbox_*
metric series appears, NO sentinel thread starts, and serving behavior
is bit-identical to the pre-PR engine — the same <5µs/call bar as the
monitor/failpoints/trace fast paths. Plus: tools/blackbox_dump.py
--read/--json exit codes are pinned."""
import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor import blackbox

#: metric families this PR introduced — with the flag unset NONE of them
#: may grow a series on any instrumented path
BLACKBOX_FAMILIES = ("blackbox_dump_total", "blackbox_ring_events_total")


@pytest.fixture(autouse=True)
def _disabled():
    blackbox.stop_sentinel()
    blackbox.disable()
    blackbox.reset()
    yield
    blackbox.stop_sentinel()
    blackbox.disable()
    blackbox.reset()


def _tiny_model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestInertByDefault:
    def test_disabled_beacon_under_5us(self):
        """Same bar and method as the monitor/failpoint/trace gates: a
        disabled beacon is one boolean check."""
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            blackbox.beacon("gate")
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0, (
            f"disabled beacon costs {per_call_us:.2f}us/call — the "
            "one-boolean fast path regressed")
        t0 = time.perf_counter()
        for _ in range(n):
            blackbox.note("gate", a=1)
        per_call_us = (time.perf_counter() - t0) / n * 1e6
        assert per_call_us < 5.0
        assert blackbox.beacons() == {}
        assert blackbox.ring() == []

    def test_no_sentinel_thread_with_flag_unset(self):
        """The sentinel thread only exists once armed: a default process
        must never grow a watcher thread."""
        assert not blackbox.sentinel_running()
        names = {t.name for t in threading.enumerate()}
        assert blackbox.SENTINEL_THREAD_NAME not in names
        # beacons with the flag unset must not auto-start it either
        for _ in range(10):
            blackbox.beacon("gate")
        assert not blackbox.sentinel_running()

    def test_serving_parity_and_zero_metric_drift(self):
        """Flag unset: the beacon-instrumented serving + trainer paths
        leave the registry without a single blackbox_* series, the
        engine keeps exact solo-generate parity, and no beacon site
        registers anywhere."""
        from paddle_tpu.inference.serving import ServingEngine

        monitor.reset()
        m = _tiny_model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 64, (n,)).astype(np.int32)
                   for n in (5, 9)]
        eng = ServingEngine(m, max_batch=2)
        rids = [eng.submit(p, max_new_tokens=6) for p in prompts]
        res = eng.run_until_complete()
        for rid, p in zip(rids, prompts):
            ref = m.generate(paddle.to_tensor(p[None]), max_new_tokens=6,
                             temperature=0.0)
            np.testing.assert_array_equal(
                res[rid].tokens, np.asarray(ref._data)[0, len(p):])
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer
        import jax

        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                         mesh=mesh)
        tr.train_step(np.ones((2, 4), np.float32),
                      np.zeros((2, 1), np.float32))

        reg = monitor.default_registry()
        for family in BLACKBOX_FAMILIES:
            # the family may EXIST if an earlier test exercised the
            # recorder in-process (registries keep zeroed series across
            # reset); the gate is that this flag-unset workload never
            # MOVES it
            metric = reg.get(family)
            assert metric is None or all(
                s.value == 0 for s in metric.series()), family
        assert blackbox.beacons() == {}
        assert blackbox.ring() == []

    def test_snapshot_structure_identical_across_blackbox_use(self):
        """The registry snapshot after a flag-unset workload must be
        structurally identical whether or not the recorder was ever
        exercised in-process (enabled, then back off)."""
        from paddle_tpu.inference.serving import ServingEngine

        def run_once():
            monitor.reset()
            m = _tiny_model()
            rng = np.random.RandomState(0)
            eng = ServingEngine(m, max_batch=2)
            eng.submit(rng.randint(0, 64, (5,)).astype(np.int32),
                       max_new_tokens=4)
            eng.run_until_complete()
            out = {}
            for fam in monitor.snapshot()["metrics"]:
                for s in fam["series"]:
                    key = (fam["name"],
                           tuple(sorted(s["labels"].items())))
                    out[key] = (s["count"] if fam["type"] == "histogram"
                                else s["value"])
            return out

        base = run_once()
        # exercise the beacon machinery heavily in between (beacons only:
        # note()/dump() legitimately register their blackbox_* counters —
        # opting the recorder in IS allowed to grow the registry), then
        # flip it back off
        blackbox.enable(install=False)
        for i in range(50):
            blackbox.beacon(f"noise{i % 3}")
            blackbox.set_context("noise", i)
        blackbox.disable()
        blackbox.reset()
        again = run_once()
        assert base == again


class TestBlackboxDumpTool:
    def _load(self):
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "blackbox_dump", os.path.join(repo, "tools",
                                          "blackbox_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules.pop("blackbox_dump", None)
        spec.loader.exec_module(mod)
        return mod

    def _bundle(self, tmp_path):
        blackbox.enable(install=False)
        try:
            blackbox.beacon("gate_tool")
            path = blackbox.dump("signal", site="gate_tool",
                                 dir_=str(tmp_path))
        finally:
            blackbox.disable()
        assert path is not None
        return path

    def test_valid_bundle_exits_zero(self, tmp_path, capsys):
        tool = self._load()
        path = self._bundle(tmp_path)
        rc = tool.main(["--read", path, "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["tool"] == "blackbox_dump"
        assert set(report) >= {"tool", "passes", "targets", "totals"}
        assert report["totals"]["error"] == 0
        (target,) = report["targets"].values()
        assert target["bundle"]["site"] == "gate_tool"

    def test_missing_bundle_exits_one(self, tmp_path, capsys):
        tool = self._load()
        rc = tool.main(["--read", str(tmp_path / "nope.json"), "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        errs = [f for t in report["targets"].values()
                for f in t["findings"] if f["severity"] == "error"]
        assert any(f["pass"] == "bundle-valid" for f in errs)

    def test_malformed_bundle_exits_one(self, tmp_path):
        tool = self._load()
        bad = tmp_path / "bad.json"
        bad.write_text("{definitely not json")
        assert tool.main(["--read", str(bad)]) == 1
        # well-formed JSON missing required keys is just as malformed
        partial = tmp_path / "partial.json"
        partial.write_text(json.dumps({"reason": "stall"}))
        assert tool.main(["--read", str(partial)]) == 1

    def test_pretty_printer_names_the_wedge(self, tmp_path, capsys):
        tool = self._load()
        path = self._bundle(tmp_path)
        rc = tool.main(["--read", path])
        assert rc == 0
        out = capsys.readouterr().out
        assert "gate_tool" in out
        assert "threads" in out

    def test_no_action_is_an_error(self):
        tool = self._load()
        with pytest.raises(SystemExit):
            tool.main([])
