"""Book examples, part 2 (reference fluid/tests/book/ parity): the five
canonical end-to-end programs not covered by test_book.py — image
classification (CNN), sentiment (LSTM over padded sequences), recommender
(embedding factorization), machine translation (encoder-decoder + greedy
decode), and label semantic roles (BiLSTM + linear-chain CRF + viterbi)."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.nn import functional as F

rng = np.random.RandomState(3)


def _train(model, opt, loss_fn, batches, steps=12):
    losses = []
    for i in range(steps):
        x, y = batches[i % len(batches)]
        loss = loss_fn(model, paddle.to_tensor(x), paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    return losses


def test_book_image_classification():
    """conv -> bn -> pool -> fc image classifier learns a separable signal."""
    net = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1), nn.BatchNorm2D(8), nn.ReLU(),
        nn.MaxPool2D(2), nn.Flatten(), nn.Linear(8 * 4 * 4, 4))
    opt = paddle.optimizer.Adam(learning_rate=5e-3, parameters=net.parameters())
    # class k = image whose channel mean is shifted by k
    xs, ys = [], []
    for _ in range(4):
        y = rng.randint(0, 4, (16,)).astype(np.int64)
        x = rng.randn(16, 3, 8, 8).astype(np.float32) + y[:, None, None, None]
        xs.append(x)
        ys.append(y)
    batches = list(zip(xs, ys))

    def loss_fn(m, x, y):
        return F.cross_entropy(m(x), y)

    losses = _train(net, opt, loss_fn, batches, steps=16)
    assert losses[-1] < losses[0] * 0.7, losses


def test_book_understand_sentiment_lstm():
    """LSTM over padded token sequences + sequence_last_step readout."""
    V_, D, H = 50, 16, 32

    class SentimentNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(V_, D)
            self.lstm = nn.LSTM(D, H)
            self.fc = nn.Linear(H, 2)

        def forward(self, ids, length):
            h, _ = self.lstm(self.emb(ids))
            pooled = F.sequence_last_step(h, length)
            return self.fc(pooled)

    net = SentimentNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    # label 1 sequences contain token 7 at the end of the valid region
    batches = []
    for _ in range(3):
        ids = rng.randint(10, V_, (8, 12)).astype(np.int64)
        lens = rng.randint(4, 12, (8,)).astype(np.int64)
        y = rng.randint(0, 2, (8,)).astype(np.int64)
        for b in range(8):
            if y[b]:
                ids[b, lens[b] - 1] = 7
        batches.append(((ids, lens), y))

    losses = []
    for i in range(18):
        (ids, lens), y = batches[i % len(batches)]
        logits = net(paddle.to_tensor(ids), paddle.to_tensor(lens))
        loss = F.cross_entropy(logits, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    assert losses[-1] < losses[0] * 0.8, losses


def test_book_recommender_system():
    """Embedding factorization (movielens shape): rating ~ user·item."""
    U, M, D = 30, 40, 8

    class Recommender(nn.Layer):
        def __init__(self):
            super().__init__()
            self.u = nn.Embedding(U, D)
            self.m = nn.Embedding(M, D)

        def forward(self, uid, mid):
            return (self.u(uid) * self.m(mid)).sum(axis=-1)

    net = Recommender()
    opt = paddle.optimizer.Adam(learning_rate=5e-2, parameters=net.parameters())
    true_u = rng.randn(U, 3).astype(np.float32)
    true_m = rng.randn(M, 3).astype(np.float32)
    # fixed training set, multiple epochs (book-example shape)
    uid = rng.randint(0, U, (128,))
    mid = rng.randint(0, M, (128,))
    r = (true_u[uid] * true_m[mid]).sum(1).astype(np.float32)
    losses = []
    for i in range(40):
        pred = net(paddle.to_tensor(uid), paddle.to_tensor(mid))
        loss = F.mse_loss(pred, paddle.to_tensor(r))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_book_machine_translation():
    """GRU encoder-decoder with teacher forcing + greedy decode."""
    Vs, Vt, D, H = 30, 25, 12, 24

    class Seq2Seq(nn.Layer):
        def __init__(self):
            super().__init__()
            self.src_emb = nn.Embedding(Vs, D)
            self.tgt_emb = nn.Embedding(Vt, D)
            self.enc = nn.GRU(D, H)
            self.dec = nn.GRU(D, H)
            self.out = nn.Linear(H, Vt)

        def forward(self, src, tgt_in):
            _, hN = self.enc(self.src_emb(src))
            dec_out, _ = self.dec(self.tgt_emb(tgt_in), hN)
            return self.out(dec_out)

        def greedy(self, src, bos, steps):
            _, h = self.enc(self.src_emb(src))
            tok = paddle.to_tensor(np.full((src.shape[0], 1), bos, np.int64))
            outs = []
            for _ in range(steps):
                o, h = self.dec(self.tgt_emb(tok), h)
                tok = self.out(o).argmax(axis=-1)
                outs.append(np.asarray(tok._data))
            return np.concatenate(outs, axis=1)

    net = Seq2Seq()
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    # task: copy source prefix into target — fixed corpus, multiple epochs
    src = rng.randint(2, Vs, (32, 6)).astype(np.int64)
    tgt = (src[:, :5] % (Vt - 2)) + 2
    tgt_in = np.concatenate([np.ones((32, 1), np.int64), tgt[:, :-1]], 1)
    losses = []
    for i in range(50):
        logits = net(paddle.to_tensor(src), paddle.to_tensor(tgt_in))
        b, s, v = logits.shape
        loss = F.cross_entropy(logits.reshape([b * s, v]),
                               paddle.to_tensor(tgt.reshape(-1)))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    assert losses[-1] < losses[0] * 0.6, losses
    dec = net.greedy(paddle.to_tensor(src), bos=1, steps=5)
    assert dec.shape == (32, 5)


def test_book_label_semantic_roles_crf():
    """BiLSTM emissions + linear_chain_crf loss + viterbi decode (SRL shape)."""
    from paddle_tpu.text import linear_chain_crf, viterbi_decode

    V_, D, H, T = 40, 12, 16, 5

    class SRL(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(V_, D)
            self.lstm = nn.LSTM(D, H, direction="bidirect")
            self.fc = nn.Linear(2 * H, T)

        def forward(self, ids):
            h, _ = self.lstm(self.emb(ids))
            return self.fc(h)

    net = SRL()
    trans = paddle.to_tensor(rng.randn(T + 2, T).astype(np.float32) * 0.1)
    trans.stop_gradient = False
    params = net.parameters() + [trans]
    opt = paddle.optimizer.Adam(learning_rate=2e-2, parameters=params)
    # tag = token id mod T (deterministic mapping the model can learn)
    losses = []
    for i in range(15):
        ids = rng.randint(0, V_, (6, 8)).astype(np.int64)
        tags = (ids % T).astype(np.int64)
        lens = np.full((6,), 8, np.int32)
        em = net(paddle.to_tensor(ids))
        loss = linear_chain_crf(em, trans, paddle.to_tensor(tags),
                                paddle.to_tensor(lens)).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data)))
    assert losses[-1] < losses[0] * 0.8, losses
    # viterbi decode with the learned transitions recovers most tags
    ids = rng.randint(0, V_, (4, 8)).astype(np.int64)
    em = net(paddle.to_tensor(ids))
    # drop the start/stop rows for the [T, T] decoder transition
    tr_np = np.asarray(trans._data)[2:]
    _, path = viterbi_decode(em.detach(), paddle.to_tensor(tr_np),
                             paddle.to_tensor(np.full((4,), 8, np.int32)),
                             include_bos_eos_tag=False)
    acc = (np.asarray(path._data) == (ids % T)).mean()
    assert acc > 0.5, acc


def test_beam_search_decoder():
    """BeamSearchDecoder + dynamic_decode find the argmax path of a biased
    GRU language model (beam 1 == greedy; wider beams score >= greedy)."""
    V_, D, H, K = 12, 8, 16, 3

    emb = nn.Embedding(V_, D)
    cell = nn.GRUCell(D, H)
    out_fc = nn.Linear(H, V_)

    def output_fn(h):
        return out_fc(h)

    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=2, beam_size=K,
                               embedding_fn=emb, output_fn=output_fn)
    B = 2
    init = cell.get_initial_states(paddle.to_tensor(np.zeros((B, D), np.float32)))
    ids, scores, lens = nn.dynamic_decode(dec, inits=init, max_step_num=6,
                                          return_length=True)
    ids_np = np.asarray(ids._data)
    assert ids_np.shape[0] == B and ids_np.shape[2] == K
    assert np.asarray(lens._data).max() <= 6
    # scores sorted descending across beams
    sc = np.asarray(scores._data)
    assert (np.diff(sc, axis=1) <= 1e-5).all()
    # greedy (beam 1) matches the top beam of the same model
    dec1 = nn.BeamSearchDecoder(cell, start_token=1, end_token=2, beam_size=1,
                                embedding_fn=emb, output_fn=output_fn)
    ids1, sc1 = nn.dynamic_decode(dec1, inits=init, max_step_num=6)
    assert np.asarray(sc1._data)[0, 0] <= sc[0, 0] + 1e-5


def test_beam_search_lengths_follow_parents():
    """Lengths must be gathered along parent lineages, not beam slots."""
    V_, D, H, K = 8, 4, 8, 2
    emb = nn.Embedding(V_, D)
    cell = nn.GRUCell(D, H)
    fc = nn.Linear(H, V_)
    dec = nn.BeamSearchDecoder(cell, start_token=1, end_token=2, beam_size=K,
                               embedding_fn=emb, output_fn=fc)
    init = cell.get_initial_states(paddle.to_tensor(np.zeros((1, D), np.float32)))
    ids, _, lens = nn.dynamic_decode(dec, inits=init, max_step_num=5,
                                     return_length=True)
    ids_np, lens_np = np.asarray(ids._data), np.asarray(lens._data)
    # each slot's length equals the count of its OWN pre-end tokens + end
    for k in range(K):
        seq = ids_np[0, :, k]
        if 2 in seq:
            assert lens_np[0, k] <= len(seq)
        assert lens_np[0, k] >= 1
