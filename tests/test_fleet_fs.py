"""fleet utils fs tests (reference distributed/fleet/utils/fs.py parity)."""
import os

import pytest

from paddle_tpu.distributed.fleet.utils import (
    FSFileExistsError, FSFileNotExistsError, HDFSClient, LocalFS,
)


class TestLocalFS:
    def test_full_lifecycle(self, tmp_path):
        fs = LocalFS()
        root = str(tmp_path / "ckpt")
        fs.mkdirs(root)
        assert fs.is_dir(root) and fs.is_exist(root)

        f = os.path.join(root, "epoch_0")
        fs.touch(f)
        assert fs.is_file(f)
        with pytest.raises(FSFileExistsError):
            fs.touch(f, exist_ok=False)

        sub = os.path.join(root, "sub")
        fs.mkdirs(sub)
        dirs, files = fs.ls_dir(root)
        assert dirs == ["sub"] and files == ["epoch_0"]

        dst = os.path.join(root, "epoch_1")
        fs.mv(f, dst)
        assert fs.is_file(dst) and not fs.is_exist(f)
        with pytest.raises(FSFileNotExistsError):
            fs.mv(f, dst)

        with open(dst, "wb") as fh:
            fh.write(b"abc")
        assert fs.cat(dst) == b"abc"

        fs.delete(root)
        assert not fs.is_exist(root)
        assert fs.ls_dir(root) == ([], [])
        assert not fs.need_upload_download()

    def test_mv_overwrite(self, tmp_path):
        fs = LocalFS()
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        fs.touch(a)
        fs.touch(b)
        with pytest.raises(FSFileExistsError):
            fs.mv(a, b)
        fs.mv(a, b, overwrite=True)
        assert fs.is_exist(b) and not fs.is_exist(a)


class TestHDFSClient:
    def test_clear_error_without_hadoop(self):
        client = HDFSClient(hadoop_home="/nonexistent")
        assert not client.available()
        assert client.need_upload_download()
        with pytest.raises(RuntimeError, match="hadoop binary"):
            client.mkdirs("/tmp/x")
