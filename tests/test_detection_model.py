"""PP-YOLOE-style detector + Pallas NMS kernel tests (BASELINE config #5)."""
import numpy as np
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.nms_pallas import nms_keep_mask_pallas
from paddle_tpu.vision.models import PPYOLOE, PPYOLOELoss, ppyoloe_tiny
from paddle_tpu.vision.ops import nms_mask


def _greedy_nms_ref(boxes, thresh):
    """Numpy greedy NMS on score-desc-sorted boxes."""
    n = len(boxes)
    keep = np.ones(n, bool)
    for i in range(n):
        if not keep[i]:
            continue
        for j in range(i + 1, n):
            if not keep[j]:
                continue
            ix1 = max(boxes[i, 0], boxes[j, 0])
            iy1 = max(boxes[i, 1], boxes[j, 1])
            ix2 = min(boxes[i, 2], boxes[j, 2])
            iy2 = min(boxes[i, 3], boxes[j, 3])
            inter = max(ix2 - ix1, 0) * max(iy2 - iy1, 0)
            a_i = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
            a_j = (boxes[j, 2] - boxes[j, 0]) * (boxes[j, 3] - boxes[j, 1])
            iou = inter / max(a_i + a_j - inter, 1e-9)
            if iou > thresh:
                keep[j] = False
    return keep


class TestPallasNMS:
    def _rand_boxes(self, n, seed=0):
        rng = np.random.RandomState(seed)
        xy = rng.rand(n, 2) * 100
        wh = rng.rand(n, 2) * 30 + 1
        return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)

    def test_matches_greedy_reference_interpret(self):
        for seed in (0, 1, 2):
            boxes = self._rand_boxes(100, seed)
            keep = np.asarray(nms_keep_mask_pallas(jnp.asarray(boxes), 0.5,
                                                   interpret=True))
            ref = _greedy_nms_ref(boxes, 0.5)
            np.testing.assert_array_equal(keep, ref)

    def test_matches_xla_scan_path(self):
        boxes = self._rand_boxes(64, seed=3)
        scores = np.random.RandomState(4).rand(64).astype(np.float32)
        order = np.argsort(-scores)
        keep_pallas_sorted = np.asarray(nms_keep_mask_pallas(
            jnp.asarray(boxes[order]), 0.4, interpret=True))
        keep_pallas = np.zeros(64, bool)
        keep_pallas[order] = keep_pallas_sorted
        keep_xla = np.asarray(nms_mask(jnp.asarray(boxes), jnp.asarray(scores),
                                       0.4, use_pallas=False))
        np.testing.assert_array_equal(keep_pallas, keep_xla)

    def test_padding_boxes_never_suppress(self):
        # 3 boxes -> padded to 128; pads are zero-area and must not interfere
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                         np.float32)
        keep = np.asarray(nms_keep_mask_pallas(jnp.asarray(boxes), 0.5,
                                               interpret=True))
        np.testing.assert_array_equal(keep, [True, False, True])


class TestPPYOLOE:
    def test_forward_shapes(self):
        paddle.seed(0)
        model = ppyoloe_tiny(num_classes=4)
        model.eval()
        x = paddle.randn([1, 3, 64, 64])
        outs = model(x)
        assert len(outs) == 3
        for (cls, reg), stride in zip(outs, model.strides):
            assert tuple(cls.shape) == (1, 4, 64 // stride, 64 // stride)
            assert tuple(reg.shape) == (1, 4, 64 // stride, 64 // stride)

    def test_decode_boxes_valid(self):
        paddle.seed(0)
        model = ppyoloe_tiny(num_classes=4)
        model.eval()
        outs = model(paddle.randn([2, 3, 64, 64]))
        boxes, scores = model.decode(outs)
        A = sum((64 // s) ** 2 for s in model.strides)
        assert tuple(boxes.shape) == (2, A, 4)
        assert tuple(scores.shape) == (2, 4, A)
        b = np.asarray(boxes._data)
        assert (b[..., 2] >= b[..., 0]).all() and (b[..., 3] >= b[..., 1]).all()
        s = np.asarray(scores._data)
        assert (s >= 0).all() and (s <= 1).all()

    def test_postprocess_returns_detections(self):
        paddle.seed(0)
        model = ppyoloe_tiny(num_classes=4)
        model.eval()
        outs = model(paddle.randn([1, 3, 64, 64]))
        res = model.postprocess(outs, score_threshold=0.0, keep_top_k=10)
        # multiclass_nms returns (out [N, keep_top_k, 6], valid counts)
        out, counts = res if isinstance(res, tuple) else (res, None)
        assert tuple(out.shape)[0] == 1

    def test_loss_trains(self):
        paddle.seed(0)
        model = ppyoloe_tiny(num_classes=4)
        model.eval()  # freeze BN stats for a deterministic descent check
        loss_fn = PPYOLOELoss(num_classes=4)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        x = paddle.randn([1, 3, 64, 64])
        A = sum((64 // s) ** 2 for s in model.strides)
        rng = np.random.RandomState(0)
        gt_boxes = paddle.to_tensor(rng.rand(1, A, 4).astype(np.float32) * 64)
        labels = rng.randint(0, 5, (1, A))  # 4 == background
        gt_labels = paddle.to_tensor(labels.astype(np.int64))
        losses = []
        for _ in range(3):
            decoded = model.decode(model(x))
            loss = loss_fn(decoded, (gt_boxes, gt_labels))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestNMSMaskFilters:
    def test_top_k_caps_kept_boxes(self):
        rng = np.random.RandomState(5)
        boxes = np.concatenate([rng.rand(50, 2) * 500,
                                rng.rand(50, 2) * 20 + 500], axis=1)
        scores = rng.rand(50).astype(np.float32)
        keep = np.asarray(nms_mask(jnp.asarray(boxes.astype(np.float32)),
                                   jnp.asarray(scores), 0.99, top_k=5,
                                   use_pallas=False))
        assert keep.sum() <= 5
        # the kept ones are the top-scored survivors
        assert set(np.nonzero(keep)[0]) <= set(np.argsort(-scores)[:5])

    def test_class0_detections_survive_postprocess(self):
        """Regression: background_label default must not eat class 0."""
        paddle.seed(0)
        model = ppyoloe_tiny(num_classes=2)
        model.eval()
        outs = model(paddle.randn([1, 3, 64, 64]))
        out, counts = model.postprocess(outs, score_threshold=0.0, keep_top_k=50)
        labels = np.asarray(out._data)[0, :, 0]
        valid = int(np.asarray(counts._data)[0])
        assert (labels[:valid] == 0).any(), "class-0 detections were dropped"
