"""HuggingFace GPT-2 weight bridge: logits parity between the converted
GPTForCausalLM and the torch GPT2LMHeadModel on identical (random) weights —
external validation of the model math against an independent implementation,
plus decode parity through the KV-cache generate path."""
import numpy as np
import pytest

import paddle_tpu as paddle

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _pair():
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=160, n_positions=64, n_embd=48, n_layer=3, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0))
    hf.eval()
    from paddle_tpu.models import gpt2_from_huggingface

    ours = gpt2_from_huggingface(hf_model=hf)
    return hf, ours


class TestHFBridge:
    def test_logits_parity(self):
        hf, ours = _pair()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 160, (2, 17)).astype(np.int64)
        with torch.no_grad():
            want = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(ours(paddle.to_tensor(ids.astype(np.int32)))._data)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_greedy_decode_parity(self):
        hf, ours = _pair()
        ids = np.arange(1, 9, dtype=np.int64)[None]
        with torch.no_grad():
            want = hf.generate(
                torch.tensor(ids), max_new_tokens=8, do_sample=False,
                pad_token_id=0).numpy()
        got = np.asarray(ours.generate(
            paddle.to_tensor(ids.astype(np.int32)), max_new_tokens=8,
            temperature=0.0)._data)
        np.testing.assert_array_equal(got, want)

    def test_validation_paths(self):
        from transformers import GPT2Config, GPT2LMHeadModel

        from paddle_tpu.models import gpt2_from_huggingface

        hf = GPT2LMHeadModel(GPT2Config(
            vocab_size=32, n_positions=16, n_embd=16, n_layer=1, n_head=2))
        ours = gpt2_from_huggingface(hf_model=hf)  # sanity: converts fine
        assert tuple(ours.gpt.wte.weight.shape) == (32, 16)
        with pytest.raises(ValueError, match="pass hf_model= or model_name="):
            gpt2_from_huggingface()
        # exact-erf checkpoints map to gelu_approx=False
        hf_erf = GPT2LMHeadModel(GPT2Config(
            vocab_size=32, n_positions=16, n_embd=16, n_layer=1, n_head=2,
            activation_function="gelu"))
        assert gpt2_from_huggingface(hf_model=hf_erf).cfg.gelu_approx is False
        # unsupported activations refuse instead of silently diverging
        hf_relu = GPT2LMHeadModel(GPT2Config(
            vocab_size=32, n_positions=16, n_embd=16, n_layer=1, n_head=2,
            activation_function="relu"))
        with pytest.raises(ValueError, match="activation_function"):
            gpt2_from_huggingface(hf_model=hf_relu)

    def test_shape_guard_catches_layout_regression(self):
        """The put() shape check must catch a transposed/mismatched weight
        (the exact failure a layout regression would produce)."""
        from transformers import GPT2Config, GPT2LMHeadModel

        from paddle_tpu.models import hf_bridge

        hf = GPT2LMHeadModel(GPT2Config(
            vocab_size=32, n_positions=16, n_embd=16, n_layer=1, n_head=2))
        sd = dict(hf.state_dict())
        # simulate a layout bug: transpose the packed qkv weight
        sd["transformer.h.0.attn.c_attn.weight"] = \
            sd["transformer.h.0.attn.c_attn.weight"].T.contiguous()
        hf.state_dict = lambda: sd  # feed the bad layout to the bridge
        with pytest.raises(ValueError, match="attn.qkv.weight"):
            hf_bridge.gpt2_from_huggingface(hf_model=hf)


class TestBertBridge:
    def test_hidden_and_pooler_parity(self):
        from transformers import BertConfig as HFCfg, BertModel as HFBert

        from paddle_tpu.models import bert_from_huggingface

        torch.manual_seed(0)
        hf = HFBert(HFCfg(vocab_size=200, hidden_size=48, num_hidden_layers=2,
                          num_attention_heads=4, intermediate_size=96,
                          max_position_embeddings=64, type_vocab_size=2,
                          hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0))
        hf.eval()
        ours = bert_from_huggingface(hf_model=hf)

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 200, (2, 11)).astype(np.int64)
        toktype = rng.randint(0, 2, (2, 11)).astype(np.int64)
        with torch.no_grad():
            out = hf(torch.tensor(ids), token_type_ids=torch.tensor(toktype))
        seq, pooled = ours(paddle.to_tensor(ids.astype(np.int32)),
                           paddle.to_tensor(toktype.astype(np.int32)))
        np.testing.assert_allclose(np.asarray(seq._data),
                                   out.last_hidden_state.numpy(),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(np.asarray(pooled._data),
                                   out.pooler_output.numpy(),
                                   rtol=2e-4, atol=2e-4)

    def test_unsupported_activation_refuses(self):
        from transformers import BertConfig as HFCfg, BertModel as HFBert

        from paddle_tpu.models import bert_from_huggingface

        hf = HFBert(HFCfg(vocab_size=32, hidden_size=16, num_hidden_layers=1,
                          num_attention_heads=2, intermediate_size=32,
                          hidden_act="relu"))
        with pytest.raises(ValueError, match="hidden_act"):
            bert_from_huggingface(hf_model=hf)


def test_bert_bridge_threads_layer_norm_eps():
    """Real BERT checkpoints use layer_norm_eps=1e-12; every converted
    LayerNorm must carry it (framework default is 1e-5)."""
    from transformers import BertConfig as HFCfg, BertModel as HFBert

    from paddle_tpu.models import bert_from_huggingface
    from paddle_tpu.nn.layer.norm import LayerNorm

    hf = HFBert(HFCfg(vocab_size=32, hidden_size=16, num_hidden_layers=1,
                      num_attention_heads=2, intermediate_size=32))
    ours = bert_from_huggingface(hf_model=hf)
    lns = [sub for _, sub in ours.named_sublayers(include_self=True)
           if isinstance(sub, LayerNorm)]
    assert lns and all(ln._epsilon == 1e-12 for ln in lns)


def test_bert_bridge_rejects_poolerless():
    from transformers import BertConfig as HFCfg, BertForMaskedLM

    from paddle_tpu.models import bert_from_huggingface

    hf = BertForMaskedLM(HFCfg(vocab_size=32, hidden_size=16,
                               num_hidden_layers=1, num_attention_heads=2,
                               intermediate_size=32))
    with pytest.raises(ValueError, match="pooler"):
        bert_from_huggingface(hf_model=hf)


def test_bert_parity_without_token_type_ids():
    """Verify-drive regression: omitting token_type_ids must still add the
    segment-0 embedding (BERT semantics), keeping torch parity."""
    from transformers import BertConfig as HFCfg, BertModel as HFBert

    from paddle_tpu.models import bert_from_huggingface

    torch.manual_seed(2)
    hf = HFBert(HFCfg(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=64,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)).eval()
    ours = bert_from_huggingface(hf_model=hf)
    ids = np.random.RandomState(0).randint(0, 100, (1, 9)).astype(np.int64)
    with torch.no_grad():
        want = hf(torch.tensor(ids)).last_hidden_state.numpy()
    seq, _ = ours(paddle.to_tensor(ids.astype(np.int32)))
    np.testing.assert_allclose(np.asarray(seq._data), want,
                               rtol=2e-4, atol=2e-4)


class TestBertTaskHeads:
    """Fine-tune heads over BertModel: shapes + a tiny separable fine-tune
    actually learns (classification), spans flow (QA), tags flow (token)."""

    def _cfg(self):
        from paddle_tpu.models import BertConfig

        return BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                          num_heads=2, intermediate_size=64, max_position=32,
                          dropout=0.0)

    def test_sequence_classification_learns(self):
        from paddle_tpu.models import BertForSequenceClassification

        paddle.seed(0)
        model = BertForSequenceClassification(self._cfg(), num_classes=2)
        opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                    parameters=model.parameters())
        loss_fn = paddle.nn.CrossEntropyLoss()
        rng = np.random.RandomState(0)
        # class 0 sentences use tokens < 32, class 1 tokens >= 32
        n = 64
        ys = rng.randint(0, 2, n)
        xs = np.where(ys[:, None] == 0,
                      rng.randint(0, 32, (n, 12)),
                      rng.randint(32, 64, (n, 12))).astype(np.int32)
        accs = []
        for i in range(30):
            logits = model(paddle.to_tensor(xs))
            loss = loss_fn(logits, paddle.to_tensor(ys.astype(np.int64)))
            loss.backward()
            opt.step()
            opt.clear_grad()
            accs.append(float((np.argmax(np.asarray(logits._data), -1)
                               == ys).mean()))
        assert accs[-1] > 0.9, accs[::10]

    def test_token_and_qa_heads_shapes_and_grads(self):
        from paddle_tpu.models import (BertForQuestionAnswering,
                                       BertForTokenClassification)

        paddle.seed(0)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 64, (2, 10)).astype(np.int32))
        tok = BertForTokenClassification(self._cfg(), num_classes=5)
        out = tok(ids)
        assert tuple(out.shape) == (2, 10, 5)
        out.sum().backward()
        assert np.abs(np.asarray(
            tok.classifier.weight.grad._data)).sum() > 0

        qa = BertForQuestionAnswering(self._cfg())
        start, end = qa(ids)
        assert tuple(start.shape) == (2, 10) and tuple(end.shape) == (2, 10)
        (start.sum() + end.sum()).backward()
        assert np.abs(np.asarray(qa.qa_outputs.weight.grad._data)).sum() > 0


def test_bert_attention_mask_parity_with_hf():
    """[b, s] keep-masks (the HF/paddle convention) must work and match the
    torch reference at valid positions (masked positions are don't-care)."""
    from transformers import BertConfig as HFCfg, BertModel as HFBert

    from paddle_tpu.models import bert_from_huggingface

    torch.manual_seed(0)
    hf = HFBert(HFCfg(vocab_size=100, hidden_size=32, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=64,
                      hidden_dropout_prob=0.0,
                      attention_probs_dropout_prob=0.0)).eval()
    ours = bert_from_huggingface(hf_model=hf)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 100, (2, 10)).astype(np.int64)
    mask = np.ones((2, 10), np.int64)
    mask[0, 6:] = 0
    mask[1, 8:] = 0
    with torch.no_grad():
        want = hf(torch.tensor(ids),
                  attention_mask=torch.tensor(mask)).last_hidden_state.numpy()
    seq, _ = ours(paddle.to_tensor(ids.astype(np.int32)),
                  attention_mask=paddle.to_tensor(mask.astype(np.int32)))
    got = np.asarray(seq._data)
    np.testing.assert_allclose(got[0, :6], want[0, :6], rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(got[1, :8], want[1, :8], rtol=2e-4, atol=2e-4)


def test_ernie_key_padding_mask_works():
    """ERNIE shares the normalized mask path: [b, s] keep-masks must change
    attention (masked vs unmasked outputs differ at valid positions) and not
    crash."""
    from paddle_tpu.models import ErnieConfig, ErnieModel

    paddle.seed(0)
    m = ErnieModel(ErnieConfig(vocab_size=64, hidden_size=32, num_layers=1,
                               num_heads=2, intermediate_size=64,
                               dropout=0.0))
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, 64, (1, 8)).astype(np.int32))
    mask = paddle.to_tensor(
        np.array([[1, 1, 1, 1, 0, 0, 0, 0]], np.int32))
    full, _ = m(ids)
    masked, _ = m(ids, attention_mask=mask)
    a, b = np.asarray(full._data), np.asarray(masked._data)
    assert np.isfinite(b).all()
    assert not np.allclose(a[0, :4], b[0, :4])  # masking changed attention


def test_transformer_encoder_direct_2d_mask():
    """The shared stack itself (not just the model zoo) accepts [b, s]
    keep-masks — nn.TransformerEncoder is the public paddle surface."""
    from paddle_tpu import nn

    paddle.seed(0)
    layer = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 1)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 6, 16).astype(np.float32))
    mask = paddle.to_tensor(np.array([[1, 1, 1, 0, 0, 0],
                                      [1, 1, 1, 1, 1, 1]], np.int32))
    out = enc(x, mask)
    assert np.isfinite(np.asarray(out._data)).all()


def test_float_additive_2d_mask_unchanged():
    """Review r3: a float additive mask (0 / -1e9, broadcast over batch)
    must keep additive semantics — not be bool-inverted by the keep-mask
    expansion."""
    from paddle_tpu import nn

    paddle.seed(0)
    layer = nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 1)
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 6, 16).astype(np.float32))
    add_mask = np.zeros((1, 6), np.float32)
    add_mask[0, 3:] = -1e9  # mask keys 3..5
    keep_mask = np.array([[1, 1, 1, 0, 0, 0]] * 2, np.int32)
    out_add = np.asarray(enc(x, paddle.to_tensor(add_mask))._data)
    out_keep = np.asarray(enc(x, paddle.to_tensor(keep_mask))._data)
    np.testing.assert_allclose(out_add, out_keep, rtol=1e-5, atol=1e-5)


def test_gpt2_roundtrip_ours_to_hf():
    """Reverse bridge: a (randomly initialized) GPTForCausalLM exports into
    a torch GPT2LMHeadModel with logits parity — the round trip out of the
    framework."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.models.hf_bridge import gpt2_to_huggingface

    paddle.seed(3)
    cfg = GPTConfig(vocab_size=120, hidden_size=48, num_layers=2, num_heads=4,
                    max_seq_len=32, dropout=0.0, gelu_approx=True)
    ours = GPTForCausalLM(cfg)
    ours.eval()
    hf = gpt2_to_huggingface(ours)
    ids = np.random.RandomState(0).randint(0, 120, (2, 9)).astype(np.int64)
    want = np.asarray(ours(paddle.to_tensor(ids.astype(np.int32)))._data)
    with torch.no_grad():
        got = hf(torch.tensor(ids)).logits.numpy()
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_gpt2_roundtrip_rejects_untied():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.models.hf_bridge import gpt2_to_huggingface

    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                                 num_heads=2, max_seq_len=16, dropout=0.0))
    m.pipeline_split(2)  # installs untied lm_head
    with pytest.raises(ValueError, match="untied"):
        gpt2_to_huggingface(m)


def test_reverse_bridge_guards():
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   gpt2_to_huggingface)
    from transformers import GPT2Config, GPT2LMHeadModel

    paddle.seed(0)
    # activation mismatch with a caller-provided hf_model refuses
    erf_model = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                         num_layers=1, num_heads=2,
                                         max_seq_len=16, dropout=0.0,
                                         gelu_approx=False))
    hf = GPT2LMHeadModel(GPT2Config(vocab_size=64, n_positions=16, n_embd=32,
                                    n_layer=1, n_head=2))  # gelu_new default
    with pytest.raises(ValueError, match="activation_function"):
        gpt2_to_huggingface(erf_model, hf_model=hf)
    # MoE refuses with a clear error, not a KeyError
    moe = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                   num_layers=2, num_heads=2, max_seq_len=16,
                                   dropout=0.0, num_experts=2, moe_every=1))
    with pytest.raises(ValueError, match="MoE"):
        gpt2_to_huggingface(moe)


def test_ragged_decode_parity_with_hf():
    """Left-padded batched generate must match transformers' own padded
    greedy decode token for token (positions + masks validated externally)."""
    from transformers import GPT2Config, GPT2LMHeadModel

    from paddle_tpu.models import gpt2_from_huggingface

    torch.manual_seed(5)
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=96, n_positions=48, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)).eval()
    ours = gpt2_from_huggingface(hf_model=hf)

    rng = np.random.RandomState(0)
    s0 = 8
    ids = np.zeros((2, s0), np.int64)
    mask = np.zeros((2, s0), np.int64)
    for r, n in enumerate((4, 8)):
        ids[r, s0 - n:] = rng.randint(1, 96, n)
        mask[r, s0 - n:] = 1
    with torch.no_grad():
        want = hf.generate(torch.tensor(ids),
                           attention_mask=torch.tensor(mask),
                           max_new_tokens=7, do_sample=False,
                           pad_token_id=0).numpy()
    got = np.asarray(ours.generate(
        paddle.to_tensor(ids.astype(np.int32)), max_new_tokens=7,
        temperature=0.0,
        attention_mask=paddle.to_tensor(mask.astype(np.int32)))._data)
    np.testing.assert_array_equal(got[:, s0:], want[:, s0:])


def test_ernie_bridge_parity_with_task_types():
    """transformers ErnieModel (task-type embeddings on) converts with
    hidden-state + pooler parity — third external model validation."""
    from transformers import ErnieConfig as HFCfg, ErnieModel as HFErnie

    from paddle_tpu.models import ernie_from_huggingface

    torch.manual_seed(1)
    hf = HFErnie(HFCfg(vocab_size=150, hidden_size=32, num_hidden_layers=2,
                       num_attention_heads=2, intermediate_size=64,
                       max_position_embeddings=64, type_vocab_size=2,
                       task_type_vocab_size=3, use_task_id=True,
                       hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0)).eval()
    ours = ernie_from_huggingface(hf_model=hf)
    assert ours.embeddings.task_type is not None

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 150, (2, 10)).astype(np.int64)
    tok = rng.randint(0, 2, (2, 10)).astype(np.int64)
    task = rng.randint(0, 3, (2, 10)).astype(np.int64)
    with torch.no_grad():
        out = hf(torch.tensor(ids), token_type_ids=torch.tensor(tok),
                 task_type_ids=torch.tensor(task))
    seq, pooled = ours(paddle.to_tensor(ids.astype(np.int32)),
                       paddle.to_tensor(tok.astype(np.int32)),
                       task_type_ids=paddle.to_tensor(task.astype(np.int32)))
    np.testing.assert_allclose(np.asarray(seq._data),
                               out.last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(pooled._data),
                               out.pooler_output.numpy(),
                               rtol=2e-4, atol=2e-4)
