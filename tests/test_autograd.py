"""Autograd engine tests — BasicEngine/GradientAccumulator semantics
(imperative/basic_engine.cc:265, gradient_accumulator.h:27) + numeric-gradient checks
(op_test.py get_numeric_gradient analog)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(a, sg=False):
    return paddle.to_tensor(np.asarray(a, dtype=np.float32), stop_gradient=sg)


def numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x)
    for i in range(x.size):
        xp = x.copy().reshape(-1)
        xm = x.copy().reshape(-1)
        xp[i] += eps
        xm[i] -= eps
        g.reshape(-1)[i] = (f(xp.reshape(x.shape)) - f(xm.reshape(x.shape))) / (2 * eps)
    return g


class TestBackward:
    def test_simple_chain(self):
        x = t([2.0])
        y = x * x + 3 * x
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [7.0], rtol=1e-5)

    def test_matmul_grad(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(4, 2).astype(np.float32)
        x, y = t(a), t(b)
        loss = paddle.matmul(x, y).sum()
        loss.backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 2)) @ b.T, rtol=1e-4)
        np.testing.assert_allclose(y.grad.numpy(), a.T @ np.ones((3, 2)), rtol=1e-4)

    def test_grad_accumulation(self):
        x = t([1.0, 2.0])
        (x * 2).sum().backward()
        (x * 3).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [5.0, 5.0])
        x.clear_grad()
        assert x.grad is None

    def test_multi_consumer(self):
        x = t([2.0])
        y = x * x
        z = y + y * y
        z.backward()
        # dz/dy = 1 + 2y = 9 at y=4; dy/dx = 2x = 4 -> dz/dx = 36
        np.testing.assert_allclose(x.grad.numpy(), [36.0], rtol=1e-5)

    def test_stop_gradient(self):
        x = t([1.0])
        w = t([2.0], sg=True)
        y = x * w
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])
        assert w.grad is None

    def test_detach(self):
        x = t([3.0])
        d = x.detach()
        assert d.stop_gradient
        y = x * d
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [3.0])

    def test_numeric_grad_check_softmax_ce(self):
        logits = np.random.rand(4, 5).astype(np.float32)
        labels = np.array([1, 0, 3, 2])

        def f(lv):
            import jax.nn as jnn
            import jax.numpy as jnp

            lp = jnn.log_softmax(jnp.asarray(lv), axis=-1)
            return float(-lp[np.arange(4), labels].mean())

        x = t(logits)
        loss = paddle.nn.functional.cross_entropy(x, paddle.to_tensor(labels))
        loss.backward()
        ng = numeric_grad(f, logits)
        np.testing.assert_allclose(x.grad.numpy(), ng, atol=1e-2)

    def test_retain_graph(self):
        x = t([2.0])
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [8.0])

    def test_backward_with_grad_tensor(self):
        x = t([1.0, 2.0])
        y = x * 2
        y.backward(paddle.to_tensor([1.0, 0.5]))
        np.testing.assert_allclose(x.grad.numpy(), [2.0, 1.0])

    def test_no_grad(self):
        x = t([1.0])
        with paddle.no_grad():
            y = x * 2
        assert y.stop_gradient

    def test_inplace_add(self):
        from paddle_tpu.tensor.math import add_

        x = t([1.0])
        y = x * 2
        add_(y, paddle.to_tensor([1.0]))
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [2.0])


class TestPaddleGrad:
    def test_grad_api(self):
        x = t([3.0])
        y = x * x
        (gx,) = paddle.grad(y, x)
        np.testing.assert_allclose(gx.numpy(), [6.0])
        assert x.grad is None  # paddle.grad does not pollute .grad

    def test_double_like_grad_create_graph(self):
        x = t([2.0])
        y = x * x * x
        (gx,) = paddle.grad(y, x, create_graph=True)
        np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-5)


class TestHooks:
    def test_register_hook(self):
        x = t([1.0])
        seen = []
        x.register_hook(lambda g: seen.append(g.numpy().copy()))
        (x * 5).sum().backward()
        assert len(seen) == 1
        np.testing.assert_allclose(seen[0], [5.0])

    def test_hook_modify(self):
        x = t([1.0])
        x.register_hook(lambda g: g * 0)
        (x * 5).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0.0])


class TestPyLayer:
    def test_custom_vjp(self):
        from paddle_tpu.autograd import PyLayer

        class Square(PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * x

            @staticmethod
            def backward(ctx, grad):
                (x,) = ctx.saved_tensor()
                return grad * 2 * x

        x = t([3.0])
        y = Square.apply(x)
        y.backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0])


class TestDoubleGrad:
    """paddle.grad(create_graph=True) — PartialGradEngine double-grad parity
    (imperative/partial_grad_engine.cc)."""

    def test_second_derivative_of_cubic(self):
        x = paddle.to_tensor(np.array([2.0], np.float32))
        x.stop_gradient = False
        y = x * x * x
        (g,) = paddle.grad(y, x, create_graph=True)
        np.testing.assert_allclose(g.numpy(), [12.0], rtol=1e-6)  # 3x^2
        (g2,) = paddle.grad(g, x)
        np.testing.assert_allclose(g2.numpy(), [12.0], rtol=1e-6)  # 6x

    def test_gradient_penalty_pattern(self):
        """WGAN-GP shape: backward through a grad-norm penalty updates params."""
        paddle.seed(0)
        w = paddle.to_tensor(np.array([[1.5, -0.5], [0.3, 2.0]], np.float32))
        w.stop_gradient = False
        x = paddle.to_tensor(np.array([[1.0, 2.0]], np.float32))
        x.stop_gradient = False
        out = paddle.matmul(x, w).sum()
        (gx,) = paddle.grad(out, x, create_graph=True)
        penalty = ((gx * gx).sum() - 1.0) ** 2
        penalty.backward()
        # d(penalty)/dw exists and is finite (flows through the taped grad)
        assert w.grad is not None
        assert np.all(np.isfinite(w.grad.numpy()))
        # analytic: gx_i = sum_j w_ij -> gx = [1.0, 2.3];
        # penalty = (sum_i gx_i^2 - 1)^2; dP/dw_ij = 4*s*gx_i (const over j)
        gxv = np.array([1.5 + (-0.5), 0.3 + 2.0])
        s = float((gxv ** 2).sum() - 1.0)
        expect = np.repeat((4 * s * gxv)[:, None], 2, axis=1)
        np.testing.assert_allclose(w.grad.numpy(), expect, rtol=1e-4)

    def test_plain_backward_unaffected(self):
        x = paddle.to_tensor(np.array([3.0], np.float32))
        x.stop_gradient = False
        (x * x).backward()
        np.testing.assert_allclose(x.grad.numpy(), [6.0], rtol=1e-6)

    def test_inplace_before_create_graph_raises(self):
        """Review r2j: re-deriving a vjp at inplace-mutated values would be
        silently wrong — raise instead (inplace-version check parity)."""
        from paddle_tpu import nn

        x = paddle.to_tensor(np.array([0.7], np.float32))
        x.stop_gradient = False
        y = x * 1.0
        z = nn.functional.tanh_(y) if hasattr(nn.functional, "tanh_") else None
        if z is None:
            y2 = x * 1.0
            y2.add_(paddle.to_tensor(np.array([1.0], np.float32)))
            z = y2 * 2.0
        with pytest.raises(RuntimeError, match="in-place"):
            paddle.grad(z, x, create_graph=True)

    def test_hooks_fire_in_create_graph_backward(self):
        calls = []
        x = paddle.to_tensor(np.array([2.0], np.float32))
        x.stop_gradient = False
        x.register_hook(lambda g: calls.append(1) or g * 2.0)
        y = x * x
        (g,) = paddle.grad(y, x, create_graph=True)
        assert calls, "hook did not fire"
        np.testing.assert_allclose(g.numpy(), [8.0], rtol=1e-6)  # 2x * 2

    def test_tape_compacted_after_create_graph(self):
        from paddle_tpu.core.tape import global_tape

        t = global_tape()
        t.clear()
        x = paddle.to_tensor(np.array([2.0], np.float32))
        x.stop_gradient = False
        for _ in range(5):
            y = x * x
            (g,) = paddle.grad(y, x, create_graph=True)
            (g,) = paddle.grad(g, x)
        assert len(t.nodes) < 50, len(t.nodes)
        t.clear()
