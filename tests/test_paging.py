"""Paged KV block pool + paged-attention kernel + batched multi-LoRA decode
(ISSUE 18 tentpole, docs/SERVING.md "Paged KV & multi-LoRA").

Covers the three layers separately so a failure names its layer:

- ``PagePool`` host bookkeeping: whole-budget reservation (backpressure
  BEFORE mutation), refcounted shared prefixes, the gather/scatter
  round-trip that makes paged decode bit-identical, int8 cold pages
  within the row codec's declared band, and the ``AdapterRegistry``
  LRU/pin discipline;
- the ``ops/tpp.py paged_attention`` kernel: dense + int8 parity against
  the pure-lax reference at the bundled audit shape, and ZERO
  pallas_audit findings for its manifest entries (the budget-verified
  bar);
- the armed engine: paged-vs-dense byte-identity, multi-LoRA pooled vs
  dedicated byte-identity and vs a merged-weights model at token level,
  plus the composition/armed-kwarg error surface.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags
from paddle_tpu.models import GPTConfig, GPTForCausalLM

CFG = dict(vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
           max_seq_len=64, dropout=0.0)


@pytest.fixture
def paged():
    """Arm FLAGS_paged_kv for the test (the flag is read at ENGINE
    CONSTRUCTION; the fixture restores the prior value)."""
    old = flags.get_flag("paged_kv", False)
    paddle.set_flags({"paged_kv": True})
    yield
    paddle.set_flags({"paged_kv": old})


def _model(cfg_over=None):
    paddle.seed(0)
    m = GPTForCausalLM(GPTConfig(**{**CFG, **(cfg_over or {})}))
    m.eval()
    return m


def _export_adapter(model, seed, std=0.3):
    """A LoRA export over `model` with lora_B randomized strongly enough
    that the adapter's delta flips greedy tokens."""
    from paddle_tpu.incubate.lora import apply_lora, export_lora

    m2 = GPTForCausalLM(GPTConfig(**CFG))
    m2.load_dict(model.state_dict())
    apply_lora(m2, r=4, alpha=8)
    rng = np.random.RandomState(seed)
    for n_, p_ in m2.named_parameters():
        if "lora_B" in n_:
            p_.set_value(paddle.to_tensor(
                rng.normal(0, std, p_.shape).astype(np.float32)))
    return m2, export_lora(m2)


def _drain(eng, jobs):
    rids = [eng.submit(list(p), **kw) for p, kw in jobs]
    res = eng.run_until_complete()
    return [tuple(int(t) for t in res[r].output_ids) for r in rids]


# ---------------------------------------------------------------------------
# PagePool host bookkeeping
# ---------------------------------------------------------------------------

class TestPagePool:
    def _pool(self, n_blocks=8, bs=4, cold_after=None, max_seq=16):
        from paddle_tpu.serving.paging import PagePool

        return PagePool((2, 2, 8), np.float32, bs, n_blocks, 2, max_seq,
                        cold_after=cold_after)

    def _row(self, pool, seed=0):
        """A dense [L, KVh, T, hd] slot row with distinct values."""
        L, KVh, hd = pool.dims
        rng = np.random.RandomState(seed)
        return (rng.randn(L, KVh, pool.max_seq, hd).astype(np.float32),
                rng.randn(L, KVh, pool.max_seq, hd).astype(np.float32))

    def test_geometry_and_null_frame(self):
        pool = self._pool()
        assert pool.maxb == 4 and pool.bs == 4
        assert pool.kp.shape == (8, 2, 2, 4, 8)
        assert np.all(np.asarray(pool.kp[0]) == 0)      # null frame
        assert pool.free_blocks() == 7                  # frame 0 held
        assert pool.blocks_for(1) == 1
        assert pool.blocks_for(4) == 1
        assert pool.blocks_for(5) == 2
        # one block, both sides, f32
        assert pool.block_bytes == 2 * 2 * 2 * 4 * 8 * 4

    def test_reserve_whole_budget_then_free(self):
        pool = self._pool()
        need = pool.reserve(0, 10)                      # 3 blocks
        assert need == 3 and pool.free_blocks() == 4
        assert np.count_nonzero(pool.tables[0]) == 3
        with pytest.raises(RuntimeError):
            pool.reserve(0, 4)                          # double reservation
        pool.free_slot(0)
        assert pool.free_blocks() == 7
        assert np.all(pool.tables[0] == 0)

    def test_full_pool_raises_before_any_mutation(self):
        from paddle_tpu.serving.paging import PagePoolFullError

        pool = self._pool(n_blocks=3)                   # 2 usable frames
        tables0 = pool.tables.copy()
        with pytest.raises(PagePoolFullError):
            pool.reserve(0, 16)                         # needs 4 > 2
        assert pool.free_blocks() == 2                  # nothing leaked
        assert np.array_equal(pool.tables, tables0)

    def test_shared_prefix_refcounts(self):
        pool = self._pool()
        kc, vc = self._row(pool)
        n_shared = pool.put_prefix("p", kc, vc, 8)      # 2 full blocks
        assert n_shared == 2
        frames = pool.prefix_frames("p")
        assert len(frames) == 2 and pool.free_blocks() == 5
        pool.reserve(0, 12, shared_frames=frames)       # 2 shared + 1 priv
        pool.reserve(1, 12, shared_frames=frames)
        assert pool.free_blocks() == 3                  # only 2 private new
        assert pool.refs[frames[0]] == 3                # pin + 2 sessions
        pool.free_slot(0)
        pool.free_slot(1)
        assert pool.refs[frames[0]] == 1                # registry pin left
        pool.drop_prefix("p")
        assert pool.free_blocks() == 7

    def test_gather_scatter_roundtrip(self):
        from paddle_tpu.serving.paging import gather_dense, scatter_cols
        import jax.numpy as jnp

        pool = self._pool()
        kc, vc = self._row(pool, seed=3)
        pool.reserve(0, pool.max_seq)                   # whole table private
        pool.admit_row(0, jnp.asarray(kc), jnp.asarray(vc))
        kd, vd = gather_dense(pool.kp, pool.vp, pool.tables_device())
        # slot 0 round-trips the admitted row exactly
        np.testing.assert_array_equal(np.asarray(kd[:, 0]), kc)
        np.testing.assert_array_equal(np.asarray(vd[:, 0]), vc)
        # slot 1 reads the null frame: all-zero columns
        assert np.all(np.asarray(kd[:, 1]) == 0)
        # frontier write-back: poke column 5 and scatter it home
        kd2 = kd.at[:, 0, :, 5, :].set(7.0)
        pool.kp, pool.vp = scatter_cols(
            pool.kp, pool.vp, kd2, vd, pool.tables_device(),
            jnp.asarray([5, 0], jnp.int32))
        kd3, _ = gather_dense(pool.kp, pool.vp, pool.tables_device())
        assert np.all(np.asarray(kd3[:, 0, :, 5, :]) == 7.0)
        np.testing.assert_array_equal(np.asarray(kd3[:, 0, :, :5, :]),
                                      kc[:, :, :5, :])

    def test_cold_page_roundtrip_within_codec_band(self):
        pool = self._pool(cold_after=1)
        kc, vc = self._row(pool, seed=4)
        pool.put_prefix("p", kc, vc, 8)
        frames = pool.prefix_frames("p")
        hot = np.asarray(pool.kp[np.array(frames)])
        for _ in range(3):
            pool.sweep()
        st = pool.stats()
        assert st["cold_pages"] == 2 and st["cold_bytes"] > 0
        assert pool.free_blocks() == 7                  # frames freed
        back_frames = pool.prefix_frames("p")           # touch: decompress
        assert pool.stats()["cold_pages"] == 0
        back = np.asarray(pool.kp[np.array(back_frames)])
        # deterministic nearest-rounding row codec: |err| <= absmax/254
        band = np.abs(hot).max(axis=-1, keepdims=True) / 254.0 + 1e-7
        assert float((np.abs(back - hot) - band).max()) <= 0

    def test_sessions_pin_frames_against_cold_sweep(self):
        pool = self._pool(cold_after=1)
        kc, vc = self._row(pool)
        pool.put_prefix("p", kc, vc, 8)
        frames = pool.prefix_frames("p")
        pool.reserve(0, 12, shared_frames=frames)
        for _ in range(3):
            pool.sweep()
        assert pool.stats()["cold_pages"] == 0          # live ref blocks it


class TestAdapterRegistry:
    def test_lru_eviction_and_hits(self):
        from paddle_tpu.serving.paging import AdapterRegistry

        reg = AdapterRegistry(2)
        s_a, ev = reg.admit("a")
        assert ev is None and s_a in (1, 2)
        s_b, ev = reg.admit("b")
        assert ev is None and s_b != s_a
        assert reg.lookup("a") == s_a                   # touches LRU
        s_c, ev = reg.admit("c")
        assert ev == "b" and s_c == s_b                 # b was LRU
        assert reg.peek("b") is None
        assert reg.lookup("missing") is None

    def test_pinning_blocks_lru_and_full_pin_raises(self):
        from paddle_tpu.serving.paging import AdapterRegistry

        reg = AdapterRegistry(2)
        reg.admit("a", pin=True)
        reg.admit("b", pin=True)
        with pytest.raises(RuntimeError):
            reg.admit("c")                              # everything pinned
        reg.evict("b")
        slot, ev = reg.admit("c")
        assert ev is None
        with pytest.raises(ValueError):
            reg.admit("c")                              # duplicate load
        with pytest.raises(KeyError):
            reg.evict("b")                              # already gone


# ---------------------------------------------------------------------------
# the paged_attention kernel (ops/tpp.py)
# ---------------------------------------------------------------------------

class TestPagedAttentionKernel:
    def _case(self, quantized, seed=0):
        from paddle_tpu.ops import tpp

        B, H, hd, bs, maxb = tpp._PAGED_AUDIT_SHAPES[0]
        NB = B + 3
        rng = np.random.RandomState(seed)
        q = rng.randn(B, H, hd).astype(np.float32)
        tables = np.zeros((B, maxb), np.int32)
        lengths = rng.randint(1, maxb * bs, (B,)).astype(np.int32)
        for b in range(B):
            n = -(-int(lengths[b]) // bs)
            tables[b, :n] = rng.choice(np.arange(1, NB), n, replace=False)
        if quantized:
            kp = rng.randint(-127, 128, (NB, H, bs, hd)).astype(np.int8)
            vp = rng.randint(-127, 128, (NB, H, bs, hd)).astype(np.int8)
            ks = rng.rand(NB, H, bs, 1).astype(np.float32) * 0.02
            vs = rng.rand(NB, H, bs, 1).astype(np.float32) * 0.02
            return q, kp, vp, tables, lengths, ks, vs
        kp = rng.randn(NB, H, bs, hd).astype(np.float32)
        vp = rng.randn(NB, H, bs, hd).astype(np.float32)
        return q, kp, vp, tables, lengths, None, None

    @pytest.mark.parametrize("quantized", [False, True])
    def test_kernel_matches_reference(self, quantized):
        from paddle_tpu.ops import tpp

        args = self._case(quantized)
        got = np.asarray(tpp.paged_attention(*args))
        want = np.asarray(tpp.paged_attention_ref(*args))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)

    def test_audit_manifest_zero_findings(self):
        """The budget-verified bar: every bundled paged_attention audit
        entry (dense AND int8) passes pallas_audit with ZERO findings."""
        from paddle_tpu.analysis import pallas_audit as pa
        from paddle_tpu.ops import tpp

        entries = [e for e in tpp.audit_manifest()
                   if e["op"] == "paged_attention"]
        assert len(entries) >= 2            # dense + int8 per shape
        for e in entries:
            findings = pa.audit_entry(e)
            assert findings == [], (
                f"{e['kernel']}: {[f.message for f in findings]}")


# ---------------------------------------------------------------------------
# the armed engine
# ---------------------------------------------------------------------------

class TestPagedEngineParity:
    def _jobs(self):
        out = []
        for i, p in enumerate([[3, 14, 15, 9, 2, 6], [7, 1, 19],
                               [21, 22, 23, 24]]):
            kw = dict(max_new_tokens=6)
            if i == 2:
                kw.update(temperature=0.8, top_k=16, seed=11)
            out.append((p, kw))
        return out

    def test_paged_engine_byte_identical_to_dense(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        m = _model()
        paged_out = _drain(ServingEngine(m, max_batch=4), self._jobs())
        paddle.set_flags({"paged_kv": False})
        dense_out = _drain(ServingEngine(m, max_batch=4), self._jobs())
        assert paged_out == dense_out

    def test_paged_kwargs_require_the_flag(self):
        from paddle_tpu.inference.serving import ServingEngine

        assert not flags.get_flag("paged_kv", False)
        for kw in ({"page_block": 8}, {"page_blocks": 16},
                   {"max_adapters": 2}, {"lora_rank": 4},
                   {"page_cold_steps": 3}):
            with pytest.raises(ValueError, match="paged_kv"):
                ServingEngine(_model(), max_batch=2, **kw)

    def test_armed_rejects_unported_compositions(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        m = _model()
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(m, max_batch=2, cache_dtype="int8")
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(m, max_batch=2, draft_model=_model())
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(m, max_batch=2, prefill_chunk=16)
        eng = ServingEngine(m, max_batch=2)
        with pytest.raises(RuntimeError, match="admit_prefilled"):
            eng.admit_prefilled(None, None, None, 4)

    def test_disarming_under_a_live_engine_raises(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        eng = ServingEngine(_model(), max_batch=2)
        eng.submit([3, 4], max_new_tokens=2)
        paddle.set_flags({"paged_kv": False})
        try:
            with pytest.raises(RuntimeError, match="disarmed"):
                eng.step()
        finally:
            paddle.set_flags({"paged_kv": True})

    def test_oversized_request_rejected_at_submit(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        eng = ServingEngine(_model(), max_batch=2, page_blocks=3)
        with pytest.raises(ValueError, match="KV blocks"):
            eng.submit(list(range(2, 42)), max_new_tokens=20)

    def test_tiny_pool_requeues_to_bit_exact_completion(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        m = _model()
        jobs = [([5, 6, 7], dict(max_new_tokens=20)),
                ([9, 2], dict(max_new_tokens=20)),
                ([11, 4, 8, 1], dict(max_new_tokens=20))]
        tiny = _drain(ServingEngine(m, max_batch=4, page_blocks=5), jobs)
        roomy = _drain(ServingEngine(m, max_batch=4), jobs)
        assert tiny == roomy


class TestMultiLoRA:
    def test_pooled_matches_dedicated_and_merged(self, paged):
        from paddle_tpu.inference.serving import ServingEngine
        from paddle_tpu.incubate.lora import merge_lora

        m = _model()
        m2, exp = _export_adapter(m, seed=1)
        prompt = [3, 14, 15, 9, 2, 6]

        pooled = ServingEngine(m, max_batch=2, max_adapters=2)
        pooled.load_adapter("x", exp)
        _, exp_other = _export_adapter(m, seed=2)
        pooled.load_adapter("y", exp_other)
        rid = pooled.submit(list(prompt), max_new_tokens=8, adapter="x")
        out = [int(t)
               for t in pooled.run_until_complete()[rid].output_ids]

        dedicated = ServingEngine(m, max_batch=2, max_adapters=2)
        dedicated.load_adapter("x", exp)
        rid2 = dedicated.submit(list(prompt), max_new_tokens=8,
                                adapter="x")
        ded = [int(t)
               for t in dedicated.run_until_complete()[rid2].output_ids]
        assert out == ded                   # byte-identical: same math

        # semantic anchor: factored delta == merged weights at token
        # level (greedy argmax rollout of the merged model)
        merge_lora(m2)
        m2.eval()
        ids = list(prompt)
        for _ in range(8):
            lg = np.asarray(
                m2(paddle.to_tensor(np.asarray([ids], np.int64))))[0, -1]
            ids.append(int(lg.argmax()))
        assert out == ids[len(prompt):]

    def test_base_requests_unaffected_by_loaded_adapters(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        m = _model()
        _, exp = _export_adapter(m, seed=1)
        jobs = [([3, 14, 15], dict(max_new_tokens=6))]
        plain = _drain(ServingEngine(m, max_batch=2), jobs)
        withad = ServingEngine(m, max_batch=2, max_adapters=2)
        withad.load_adapter("x", exp)
        assert _drain(withad, jobs) == plain   # slot 0 delta: exact zero

    def test_adapter_error_surface(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        m = _model()
        _, exp = _export_adapter(m, seed=1)
        eng = ServingEngine(m, max_batch=2, max_adapters=2)
        with pytest.raises(ValueError, match="not loaded"):
            eng.submit([3, 4], max_new_tokens=2, adapter="ghost")
        eng.load_adapter("x", exp)
        with pytest.raises(ValueError, match="already loaded"):
            eng.load_adapter("x", exp)
        eng.evict_adapter("x")
        with pytest.raises(ValueError, match="not loaded"):
            eng.submit([3, 4], max_new_tokens=2, adapter="x")

    def test_evict_then_reload_bit_exact(self, paged):
        from paddle_tpu.inference.serving import ServingEngine

        m = _model()
        _, expA = _export_adapter(m, seed=1)
        _, expB = _export_adapter(m, seed=2)
        eng = ServingEngine(m, max_batch=2, max_adapters=2)
        eng.load_adapter("a", expA)
        rid = eng.submit([3, 4, 5], max_new_tokens=6, adapter="a")
        ref = [int(t) for t in eng.run_until_complete()[rid].output_ids]
        eng.evict_adapter("a")
        eng.load_adapter("b", expB)
        eng.load_adapter("a", expA)         # different slot this time
        rid2 = eng.submit([3, 4, 5], max_new_tokens=6, adapter="a")
        out = [int(t) for t in eng.run_until_complete()[rid2].output_ids]
        assert out == ref
