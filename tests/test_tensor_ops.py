"""Op correctness vs numpy reference — the OpTest pattern
(python/paddle/fluid/tests/unittests/op_test.py:255 check_output_with_place) with
jax-native numeric gradient checks (op_test.py:110 get_numeric_gradient analog)."""
import numpy as np
import pytest

import paddle_tpu as paddle


def t(a, sg=True):
    return paddle.to_tensor(np.asarray(a), stop_gradient=sg)


class TestCreation:
    def test_ones_zeros_full(self):
        assert paddle.ones([2, 3]).numpy().tolist() == np.ones((2, 3)).tolist()
        assert paddle.zeros([4]).shape == [4]
        assert float(paddle.full([1], 3.5).numpy()[0]) == 3.5

    def test_arange_linspace_eye(self):
        np.testing.assert_allclose(paddle.arange(5).numpy(), np.arange(5))
        np.testing.assert_allclose(paddle.linspace(0, 1, 5).numpy(), np.linspace(0, 1, 5), rtol=1e-6)
        np.testing.assert_allclose(paddle.eye(3).numpy(), np.eye(3))

    def test_like_family(self):
        x = t(np.random.rand(3, 4).astype(np.float32))
        assert paddle.ones_like(x).shape == [3, 4]
        assert paddle.zeros_like(x).numpy().sum() == 0
        assert paddle.full_like(x, 2.0).numpy().mean() == 2.0

    def test_tril_triu_diag(self):
        a = np.random.rand(4, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.tril(t(a)).numpy(), np.tril(a))
        np.testing.assert_allclose(paddle.triu(t(a), 1).numpy(), np.triu(a, 1))
        v = np.array([1.0, 2.0, 3.0], dtype=np.float32)
        np.testing.assert_allclose(paddle.diag(t(v)).numpy(), np.diag(v))

    def test_to_tensor_dtypes(self):
        assert str(paddle.to_tensor([1, 2]).dtype) == "int64" or paddle.to_tensor([1, 2]).dtype == np.dtype("int32")
        x = paddle.to_tensor([1.0, 2.0])
        assert x.dtype == np.dtype("float32")


class TestMath:
    def test_elementwise_binary(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(3, 4).astype(np.float32) + 0.5
        np.testing.assert_allclose(paddle.add(t(a), t(b)).numpy(), a + b, rtol=1e-6)
        np.testing.assert_allclose(paddle.subtract(t(a), t(b)).numpy(), a - b, rtol=1e-6)
        np.testing.assert_allclose(paddle.multiply(t(a), t(b)).numpy(), a * b, rtol=1e-6)
        np.testing.assert_allclose(paddle.divide(t(a), t(b)).numpy(), a / b, rtol=1e-5)
        np.testing.assert_allclose(paddle.maximum(t(a), t(b)).numpy(), np.maximum(a, b))
        np.testing.assert_allclose(paddle.pow(t(a), 2).numpy(), a**2, rtol=1e-6)

    def test_operator_overloads(self):
        a = np.random.rand(3).astype(np.float32)
        x = t(a)
        np.testing.assert_allclose((x + 1).numpy(), a + 1, rtol=1e-6)
        np.testing.assert_allclose((2 * x).numpy(), 2 * a, rtol=1e-6)
        np.testing.assert_allclose((1 - x).numpy(), 1 - a, rtol=1e-6)
        np.testing.assert_allclose((x / 2).numpy(), a / 2, rtol=1e-6)
        np.testing.assert_allclose((-x).numpy(), -a, rtol=1e-6)
        assert ((x > 0.5).numpy() == (a > 0.5)).all()

    def test_unary(self):
        a = np.random.rand(5).astype(np.float32) + 0.1
        np.testing.assert_allclose(paddle.exp(t(a)).numpy(), np.exp(a), rtol=1e-6)
        np.testing.assert_allclose(paddle.log(t(a)).numpy(), np.log(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.sqrt(t(a)).numpy(), np.sqrt(a), rtol=1e-6)
        np.testing.assert_allclose(paddle.tanh(t(a)).numpy(), np.tanh(a), rtol=1e-6)
        np.testing.assert_allclose(paddle.abs(t(-a)).numpy(), a, rtol=1e-6)
        np.testing.assert_allclose(paddle.floor(t(a * 3)).numpy(), np.floor(a * 3))

    def test_reductions(self):
        a = np.random.rand(3, 4, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.sum(t(a)).numpy(), a.sum(), rtol=1e-5)
        np.testing.assert_allclose(paddle.mean(t(a), axis=1).numpy(), a.mean(1), rtol=1e-5)
        np.testing.assert_allclose(paddle.max(t(a), axis=[0, 2]).numpy(), a.max((0, 2)))
        np.testing.assert_allclose(paddle.min(t(a), keepdim=True).numpy(), a.min(keepdims=True).reshape(1, 1, 1))
        np.testing.assert_allclose(paddle.prod(t(a), axis=0).numpy(), a.prod(0), rtol=1e-5)

    def test_cumsum_clip(self):
        a = np.random.rand(3, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.cumsum(t(a), axis=1).numpy(), a.cumsum(1), rtol=1e-5)
        np.testing.assert_allclose(paddle.clip(t(a), 0.2, 0.8).numpy(), a.clip(0.2, 0.8))

    def test_matmul_family(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(4, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.matmul(t(a), t(b)).numpy(), a @ b, rtol=1e-5)
        np.testing.assert_allclose(paddle.matmul(t(a), t(b.T), transpose_y=True).numpy(), a @ b, rtol=1e-5)
        v = np.random.rand(4).astype(np.float32)
        np.testing.assert_allclose(paddle.mv(t(a), t(v)).numpy(), a @ v, rtol=1e-5)
        c = np.random.rand(2, 3, 4).astype(np.float32)
        d = np.random.rand(2, 4, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.bmm(t(c), t(d)).numpy(), c @ d, rtol=1e-5)

    def test_einsum(self):
        a = np.random.rand(3, 4).astype(np.float32)
        b = np.random.rand(4, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.einsum("ij,jk->ik", t(a), t(b)).numpy(), a @ b, rtol=1e-5)


class TestManipulation:
    def test_reshape_transpose_flatten(self):
        a = np.random.rand(2, 3, 4).astype(np.float32)
        assert paddle.reshape(t(a), [6, 4]).shape == [6, 4]
        np.testing.assert_allclose(paddle.transpose(t(a), [2, 0, 1]).numpy(), a.transpose(2, 0, 1))
        assert paddle.flatten(t(a), 1, -1).shape == [2, 12]

    def test_concat_split_stack(self):
        a = np.random.rand(2, 3).astype(np.float32)
        b = np.random.rand(2, 3).astype(np.float32)
        np.testing.assert_allclose(paddle.concat([t(a), t(b)], axis=0).numpy(), np.concatenate([a, b], 0))
        np.testing.assert_allclose(paddle.stack([t(a), t(b)], axis=1).numpy(), np.stack([a, b], 1))
        parts = paddle.split(t(a), 3, axis=1)
        assert len(parts) == 3 and parts[0].shape == [2, 1]
        parts = paddle.split(t(a), [1, -1], axis=1)
        assert parts[1].shape == [2, 2]

    def test_squeeze_unsqueeze_tile_expand(self):
        a = np.random.rand(1, 3, 1).astype(np.float32)
        assert paddle.squeeze(t(a)).shape == [3]
        assert paddle.unsqueeze(t(a.squeeze()), [0, 2]).shape == [1, 3, 1]
        assert paddle.tile(t(a.squeeze()), [2, 2]).shape == [2, 6]
        assert paddle.expand(t(np.zeros((1, 3), np.float32)), [4, 3]).shape == [4, 3]

    def test_gather_scatter(self):
        a = np.random.rand(5, 3).astype(np.float32)
        idx = np.array([0, 2, 4])
        np.testing.assert_allclose(paddle.gather(t(a), t(idx), axis=0).numpy(), a[idx])
        upd = np.ones((2, 3), np.float32)
        out = paddle.scatter(t(a), t(np.array([1, 3])), t(upd))
        expect = a.copy()
        expect[[1, 3]] = 1.0
        np.testing.assert_allclose(out.numpy(), expect)

    def test_where_masked(self):
        a = np.random.rand(3, 3).astype(np.float32)
        b = np.random.rand(3, 3).astype(np.float32)
        cond = a > 0.5
        np.testing.assert_allclose(paddle.where(t(cond), t(a), t(b)).numpy(), np.where(cond, a, b))
        np.testing.assert_allclose(paddle.masked_select(t(a), t(cond)).numpy(), a[cond])

    def test_flip_roll_unique(self):
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(paddle.flip(t(a), [0]).numpy(), a[::-1])
        np.testing.assert_allclose(paddle.roll(t(a), 1, 1).numpy(), np.roll(a, 1, 1))
        u = paddle.unique(t(np.array([3, 1, 2, 1, 3])))
        np.testing.assert_allclose(u.numpy(), [1, 2, 3])

    def test_indexing(self):
        a = np.random.rand(4, 5).astype(np.float32)
        x = t(a)
        np.testing.assert_allclose(x[1].numpy(), a[1])
        np.testing.assert_allclose(x[1:3, 2:].numpy(), a[1:3, 2:])
        x[0] = 0.0
        assert x.numpy()[0].sum() == 0


class TestSearchSortStat:
    def test_argmax_topk_sort(self):
        a = np.random.rand(3, 5).astype(np.float32)
        np.testing.assert_allclose(paddle.argmax(t(a), axis=1).numpy(), a.argmax(1))
        vals, idx = paddle.topk(t(a), 2, axis=1)
        np.testing.assert_allclose(vals.numpy(), np.sort(a, 1)[:, ::-1][:, :2], rtol=1e-6)
        np.testing.assert_allclose(paddle.sort(t(a), axis=1).numpy(), np.sort(a, 1))
        np.testing.assert_allclose(paddle.argsort(t(a), axis=1).numpy(), np.argsort(a, 1, kind="stable"))

    def test_stat(self):
        a = np.random.rand(10, 4).astype(np.float32)
        np.testing.assert_allclose(paddle.std(t(a)).numpy(), a.std(ddof=1), rtol=1e-5)
        np.testing.assert_allclose(paddle.var(t(a), axis=0).numpy(), a.var(0, ddof=1), rtol=1e-5)
        np.testing.assert_allclose(paddle.median(t(a)).numpy(), np.median(a), rtol=1e-6)

    def test_logic(self):
        a = np.array([1, 2, 3])
        b = np.array([1, 0, 3])
        assert (paddle.equal(t(a), t(b)).numpy() == (a == b)).all()
        assert bool(paddle.allclose(t(a.astype(np.float32)), t(a.astype(np.float32))))
        assert bool(paddle.equal_all(t(a), t(a)))


class TestLinalg:
    def test_norm_det_inv(self):
        a = np.random.rand(3, 3).astype(np.float32) + np.eye(3, dtype=np.float32)
        np.testing.assert_allclose(paddle.linalg.norm(t(a)).numpy(), np.linalg.norm(a), rtol=1e-5)
        np.testing.assert_allclose(paddle.linalg.det(t(a)).numpy(), np.linalg.det(a), rtol=1e-4)
        np.testing.assert_allclose(paddle.linalg.inv(t(a)).numpy(), np.linalg.inv(a), rtol=1e-4, atol=1e-5)

    def test_svd_qr_cholesky(self):
        a = np.random.rand(4, 3).astype(np.float32)
        u, s, v = paddle.linalg.svd(t(a))
        np.testing.assert_allclose((u.numpy() * s.numpy()) @ v.numpy().T, a, atol=1e-4)
        q, r = paddle.linalg.qr(t(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-5)
        spd = a.T @ a + np.eye(3, dtype=np.float32)
        l = paddle.linalg.cholesky(t(spd))
        np.testing.assert_allclose(l.numpy() @ l.numpy().T, spd, atol=1e-4)

    def test_solve(self):
        a = np.random.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
        b = np.random.rand(3, 2).astype(np.float32)
        np.testing.assert_allclose(paddle.linalg.solve(t(a), t(b)).numpy(), np.linalg.solve(a, b), rtol=1e-4, atol=1e-5)


class TestRandom:
    def test_shapes_and_determinism(self, seed):
        assert paddle.rand([3, 4]).shape == [3, 4]
        assert paddle.randn([5]).shape == [5]
        r = paddle.randint(0, 10, [100])
        assert (r.numpy() >= 0).all() and (r.numpy() < 10).all()
        p = paddle.randperm(10).numpy()
        assert sorted(p.tolist()) == list(range(10))
        paddle.seed(123)
        a = paddle.rand([4]).numpy()
        paddle.seed(123)
        b = paddle.rand([4]).numpy()
        np.testing.assert_allclose(a, b)

    def test_bernoulli_multinomial(self, seed):
        p = paddle.bernoulli(t(np.full((1000,), 0.3, np.float32)))
        assert 0.2 < p.numpy().mean() < 0.4
        m = paddle.multinomial(t(np.array([0.1, 0.0, 0.9], np.float32)), 50, replacement=True)
        assert set(m.numpy().tolist()) <= {0, 2}


def test_set_printoptions_and_compat_apis():
    """API-coverage tail: set_printoptions drives Tensor repr (framework-
    local, numpy global state untouched); cudnn/monkey-patch/op-version
    compat surfaces exist and answer honestly."""
    import numpy as np

    import paddle_tpu as paddle

    before = np.get_printoptions()["threshold"]
    try:
        paddle.set_printoptions(precision=2, threshold=5)
        r = repr(paddle.to_tensor(np.linspace(0, 1, 50).astype(np.float32)))
        assert "..." in r  # summarized past the threshold
        assert np.get_printoptions()["threshold"] == before  # numpy untouched
    finally:
        paddle.set_printoptions(precision=8, threshold=1000)
    assert paddle.get_cudnn_version() is None
    assert paddle.monkey_patch_variable() is None
    from paddle_tpu.utils import OpLastCheckpointChecker

    checker = OpLastCheckpointChecker()
    assert checker.filter_updates("relu") == []
    assert OpLastCheckpointChecker() is checker  # singleton like the reference
