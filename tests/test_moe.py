"""MoE / expert-parallelism tests on the 8-device CPU mesh (beyond-reference
capability, SURVEY.md §2.3 last row)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.moe import (
    compute_capacity, expert_parallel_moe, moe_dense, topk_gating,
)


def _params(E=8, d=16, f=32, seed=0):
    rng = np.random.RandomState(seed)
    gate = jnp.asarray(rng.randn(d, E).astype(np.float32) * 0.1)
    w1 = jnp.asarray(rng.randn(E, d, f).astype(np.float32) * 0.1)
    b1 = jnp.asarray(rng.randn(E, f).astype(np.float32) * 0.1)
    w2 = jnp.asarray(rng.randn(E, f, d).astype(np.float32) * 0.1)
    b2 = jnp.asarray(rng.randn(E, d).astype(np.float32) * 0.1)
    return gate, w1, b1, w2, b2


class TestGating:
    def test_topk_gating_shapes_and_weights(self):
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(16, 4).astype(np.float32))
        combine, dispatch, aux = topk_gating(logits, k=2, capacity=16)
        assert combine.shape == (16, 4, 16)
        assert dispatch.shape == (16, 4, 16)
        # with ample capacity nothing dropped: weights sum to 1 per token
        np.testing.assert_allclose(np.asarray(combine.sum(axis=(1, 2))),
                                   np.ones(16), atol=1e-5)
        # each (expert, slot) holds at most one token
        assert int(dispatch.astype(jnp.int32).sum(axis=0).max()) <= 1
        assert float(aux) > 0

    def test_capacity_drops_tokens(self):
        # all tokens prefer expert 0 -> only `capacity` survive at choice 1
        logits = jnp.tile(jnp.array([[10.0, 0.0, -10.0, -10.0]]), (12, 1))
        combine, dispatch, aux = topk_gating(logits, k=1, capacity=4)
        kept = int(dispatch.astype(jnp.int32).sum())
        assert kept == 4
        # dropped tokens have zero combine weight
        w = np.asarray(combine.sum(axis=(1, 2)))
        assert (w[:4] > 0).all() and (w[4:] == 0).all()


class TestDenseMoE:
    def test_matches_per_token_reference(self):
        """moe_dense == explicit per-token top-k expert mixture (no drops)."""
        E, d, f, T = 4, 16, 32, 24
        gate, w1, b1, w2, b2 = _params(E, d, f)
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))
        out, aux = moe_dense(x, gate, w1, b1, w2, b2, k=2, capacity_factor=8.0)

        probs = np.asarray(jax.nn.softmax(x @ gate, axis=-1))
        ref = np.zeros((T, d), np.float32)
        for t in range(T):
            top = np.argsort(-probs[t])[:2]
            wsum = probs[t][top].sum()
            for e in top:
                h = np.asarray(jax.nn.gelu(x[t] @ w1[e] + b1[e]))
                y = h @ np.asarray(w2[e]) + np.asarray(b2[e])
                ref[t] += probs[t][e] / wsum * y
        np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4)

    def test_grads_flow_to_experts_and_gate(self):
        E, d, f, T = 4, 8, 16, 16
        gate, w1, b1, w2, b2 = _params(E, d, f, seed=2)
        rng = np.random.RandomState(3)
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))

        def loss(gate, w1):
            out, aux = moe_dense(x, gate, w1, b1, w2, b2, k=2)
            return jnp.sum(out ** 2) + 0.01 * aux

        g_gate, g_w1 = jax.grad(loss, argnums=(0, 1))(gate, w1)
        assert np.abs(np.asarray(g_gate)).max() > 0
        assert np.abs(np.asarray(g_w1)).max() > 0
        assert np.isfinite(np.asarray(g_w1)).all()


class TestExpertParallel:
    def test_ep_matches_dense(self):
        """8-way expert-parallel == single-shard dense when nothing is dropped.

        Tokens are sharded over 'ep'; per-shard gating is per-token so results
        agree exactly with the dense path at ample capacity.
        """
        E, d, f, T = 8, 16, 32, 64
        gate, w1, b1, w2, b2 = _params(E, d, f, seed=4)
        rng = np.random.RandomState(5)
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))
        mesh = build_mesh((8,), ("ep",))

        out_ep, aux_ep = expert_parallel_moe(x, gate, w1, b1, w2, b2, mesh,
                                             k=2, capacity_factor=8.0)
        # dense reference shard-by-shard (capacity is computed per shard)
        outs, auxs = [], []
        for s in range(8):
            xs = x[s * 8:(s + 1) * 8]
            o, a = moe_dense(xs, gate, w1, b1, w2, b2, k=2, capacity_factor=8.0)
            outs.append(np.asarray(o))
            auxs.append(float(a))
        np.testing.assert_allclose(np.asarray(out_ep), np.concatenate(outs),
                                   atol=2e-4)
        np.testing.assert_allclose(float(aux_ep), np.mean(auxs), atol=1e-4)

    def test_ep_differentiable_under_jit(self):
        E, d, f, T = 8, 8, 16, 32
        gate, w1, b1, w2, b2 = _params(E, d, f, seed=6)
        rng = np.random.RandomState(7)
        x = jnp.asarray(rng.randn(T, d).astype(np.float32))
        mesh = build_mesh((8,), ("ep",))

        @jax.jit
        def loss(x, w1):
            out, aux = expert_parallel_moe(x, gate, w1, b1, w2, b2, mesh, k=1)
            return jnp.sum(out ** 2) + 0.01 * aux

        g = jax.grad(loss, argnums=1)(x, w1)
        assert g.shape == w1.shape
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).max() > 0


class TestMoELayer:
    def test_layer_forward_backward(self):
        paddle.seed(0)
        layer = nn.MoELayer(d_model=16, d_ff=32, num_experts=4, k=2)
        x = paddle.randn([2, 8, 16])
        y = layer(x)
        assert tuple(y.shape) == (2, 8, 16)
        assert layer.aux_loss is not None
        total = (y ** paddle.to_tensor(2.0)).sum() + layer.aux_loss
        total.backward()
        g = layer.w1.grad
        assert g is not None and np.isfinite(np.asarray(g._data)).all()
        assert np.abs(np.asarray(layer.gate_weight.grad._data)).max() > 0

    def test_layer_ep_mesh_matches_dense(self):
        paddle.seed(0)
        mesh = build_mesh((8,), ("ep",))
        layer = nn.MoELayer(d_model=16, d_ff=32, num_experts=8, k=2,
                            capacity_factor=8.0)
        x = paddle.randn([8, 4, 16])
        y_dense = layer(x)
        layer.mesh = mesh
        y_ep = layer(x)
        # shard-size differences in capacity can reorder drops; ample capacity
        # makes the two paths numerically equal
        np.testing.assert_allclose(np.asarray(y_ep._data),
                                   np.asarray(y_dense._data), atol=1e-3)


class TestGPTMoE:
    def test_gpt_moe_trains(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2, num_heads=4,
                        max_seq_len=32, dropout=0.0, num_experts=4, moe_every=2)
        model = GPTForCausalLM(cfg)
        # exactly one of the two blocks is MoE
        kinds = [type(b.mlp).__name__ for b in model.gpt.blocks]
        assert kinds == ["GPTMLP", "MoELayer"]
        ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 256, (2, 16)))
        loss = model.loss(ids, ids)
        loss.backward()
        moe = model.gpt.blocks[1].mlp
        assert moe.w1.grad is not None
        assert np.isfinite(np.asarray(moe.w1.grad._data)).all()
        assert np.abs(np.asarray(moe.gate_weight.grad._data)).max() > 0

    def test_moe_plus_tensor_parallel_rejected(self):
        from paddle_tpu.models import GPTConfig

        with pytest.raises(ValueError):
            GPTConfig(num_experts=4, tensor_parallel=True)

    def test_spmd_trainer_includes_aux_loss(self):
        """SpmdTrainer with an external loss_fn must still train the router
        (code-review finding: aux loss silently dropped)."""
        from paddle_tpu.distributed.spmd import SpmdTrainer
        from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss

        paddle.seed(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
                        max_seq_len=32, dropout=0.0, num_experts=4, moe_every=2)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        trainer = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(), mesh=mesh)
        ids = paddle.to_tensor(np.random.RandomState(0).randint(0, 128, (2, 16)))
        gate_name = next(n for n in trainer.params if "gate_weight" in n)
        before = np.asarray(trainer.params[gate_name])
        trainer.train_step(ids, ids)
        after = np.asarray(trainer.params[gate_name])
        assert np.abs(after - before).max() > 0, "router got no gradient"

    def test_bad_moe_every_rejected(self):
        from paddle_tpu.models import GPTConfig

        with pytest.raises(ValueError):
            GPTConfig(num_experts=4, moe_every=0)
        with pytest.raises(ValueError):
            GPTConfig(num_experts=4, num_layers=2, moe_every=3)
