"""Unit tests for the graph-analysis pass registry (paddle_tpu.analysis).

One positive + one negative case per builtin pass over minimal synthetic
jaxprs, registry contract tests (duplicate names rejected, severity
ordering stable), source-lint rule tests, the Program/Predictor analysis
hooks, and regression assertions for the real findings the passes
surfaced in paddle_tpu itself (int64 position arange; np.random sites).
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.analysis import (  # noqa: E402
    AnalysisReport,
    Finding,
    count_hlo_collectives,
    registered_passes,
    run_passes,
)
from paddle_tpu.analysis.registry import register_pass  # noqa: E402
from paddle_tpu.analysis.source_lint import lint_source  # noqa: E402


def _by_pass(report, name):
    return [f for f in report.findings if f.pass_name == name]


# ---------------------------------------------------------------------------
# registry contract
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_battery_size(self):
        # the issue's contract: >= 8 distinct registered jaxpr passes
        assert len(registered_passes()) >= 8
        assert len(set(registered_passes())) == len(registered_passes())

    def test_duplicate_name_rejected(self):
        with pytest.raises(ValueError, match="already registered"):
            @register_pass("host-sync")
            def clone(ctx):  # pragma: no cover
                return []

    def test_bad_severity_rejected(self):
        with pytest.raises(ValueError, match="severity"):
            register_pass("x-bad-severity", severity="fatal")
        with pytest.raises(ValueError, match="severity"):
            Finding("p", "catastrophic", "m")

    def test_unknown_pass_rejected(self):
        with pytest.raises(ValueError, match="unknown analysis pass"):
            run_passes(lambda x: x + 1, 1.0, passes=["no-such-pass"])

    def test_severity_ordering_stable(self):
        rep = AnalysisReport(name="t")
        rep.add(Finding("dead-code", "info", "i1"))
        rep.add(Finding("host-sync", "warning", "w1"))
        rep.add(Finding("prng-key-reuse", "error", "e1"))
        rep.add(Finding("host-sync", "error", "e2"))
        rep.sort()
        sevs = [f.severity for f in rep.findings]
        assert sevs == ["error", "error", "warning", "info"]
        # within a severity, registration order breaks the tie (host-sync
        # registered before prng-key-reuse)
        assert [f.pass_name for f in rep.findings[:2]] == [
            "host-sync", "prng-key-reuse"]
        # sorting again is a no-op (stable)
        again = [f.message for f in rep.sort().findings]
        assert again == ["e2", "e1", "w1", "i1"]

    def test_report_roundtrip(self):
        rep = run_passes(lambda x: x * 2.0, jnp.ones(3), name="t")
        d = rep.to_dict()
        assert d["name"] == "t"
        assert set(d["counts"]) == {"error", "warning", "info"}
        for f in d["findings"]:
            assert set(f) == {"pass", "severity", "message", "where"}

    def test_pass_subset_runs(self):
        rep = run_passes(lambda x: x + 1.0, jnp.ones(3),
                         passes=["host-sync"])
        assert rep.findings == []


# ---------------------------------------------------------------------------
# per-pass positive/negative cases
# ---------------------------------------------------------------------------


class TestHostSync:
    def test_positive_pure_callback(self):
        def f(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2, jax.ShapeDtypeStruct(
                    (3,), np.float32), x)

        rep = run_passes(f, jnp.ones(3), passes=["host-sync"])
        assert len(rep.errors) == 1
        assert "pure_callback" in rep.errors[0].message

    def test_positive_debug_callback_is_warning(self):
        def f(x):
            jax.debug.print("x={}", x)
            return x + 1

        rep = run_passes(f, jnp.ones(3), passes=["host-sync"])
        assert not rep.errors and len(rep.warnings) == 1

    def test_negative(self):
        rep = run_passes(lambda x: jnp.sin(x) + 1, jnp.ones(3),
                         passes=["host-sync"])
        assert rep.findings == []


class TestPrngKeyReuse:
    def test_positive_same_key_two_samplers(self):
        def f(k):
            return jax.random.uniform(k, (3,)) + jax.random.normal(k, (3,))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert len(rep.errors) == 1
        assert "consumed 2x" in rep.errors[0].message

    def test_positive_double_split(self):
        # split(k) twice yields IDENTICAL subkeys — reuse even though no
        # sampler touches k directly
        def f(k, x):
            k1, _ = jax.random.split(k)
            k2, _ = jax.random.split(k)
            return (jax.random.uniform(k1, (2,))
                    + jax.random.uniform(k2, (2,)) + x)

        rep = run_passes(f, jax.random.key(0), jnp.ones(2),
                         passes=["prng-key-reuse"])
        assert len(rep.errors) >= 1

    def test_negative_split_chain(self):
        def f(k):
            k1, k2 = jax.random.split(k)
            return jax.random.uniform(k1, (3,)) + jax.random.normal(
                k2, (3,))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert rep.findings == []

    def test_negative_fold_in_distinct_data(self):
        # the documented-safe compress.py idiom: per-rank/per-phase
        # fold_ins of ONE key with DISTINCT data (ISSUE 13 fix — this
        # false-positived the first time the quantized program was
        # analyzed)
        def f(k):
            a = jax.random.uniform(jax.random.fold_in(k, 1), (2,))
            b = jax.random.uniform(jax.random.fold_in(k, 2), (2,))
            return a + b

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert rep.findings == []

    def test_positive_fold_in_same_data_twice(self):
        def f(k):
            a = jax.random.uniform(jax.random.fold_in(k, 7), (2,))
            b = jax.random.normal(jax.random.fold_in(k, 7), (2,))
            return a + b

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert len(rep.errors) == 1

    def test_positive_sink_mixed_with_fold(self):
        # a raw sink consumption of a key that is ALSO folded stays a
        # finding (the review-caught false-negative window)
        def f(k):
            a = jax.random.uniform(k, (2,))
            b = jax.random.uniform(jax.random.fold_in(k, 3), (2,))
            return a + b

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert len(rep.errors) == 1
        assert "random_fold_in" in rep.errors[0].message

    def test_negative_distinct_slices_of_split(self):
        # the canonical dropout chain: keys[0] / keys[1] are different
        # slices of one split — aliases must not be conflated
        def f(k):
            keys = jax.random.split(k, 4)
            return (jax.random.uniform(keys[0], (2,))
                    + jax.random.uniform(keys[1], (2,))
                    + jax.random.uniform(keys[2], (2,)))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert rep.findings == []

    def test_negative_traced_index_selection(self):
        # keys[i] / keys[j] with TRACED indices: value-dependent selection
        # must stay conservative (distinct identities), never a
        # false-positive error on correct code
        def f(k, i, j):
            keys = jax.random.split(k, 4)
            return (jax.random.uniform(keys[i], (2,))
                    + jax.random.uniform(keys[j], (2,)))

        rep = run_passes(f, jax.random.key(0), jnp.int32(0), jnp.int32(1),
                         passes=["prng-key-reuse"])
        assert rep.findings == []

    def test_positive_same_slice_twice(self):
        def f(k):
            keys = jax.random.split(k, 4)
            return (jax.random.uniform(keys[0], (2,))
                    + jax.random.normal(keys[0], (2,)))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert len(rep.errors) == 1


class TestPrngConstKey:
    def test_positive_baked_key(self):
        k = jax.random.key(7)   # closed over -> baked trace constant

        def f(x):
            return x + jax.random.uniform(k, (3,))

        rep = run_passes(f, jnp.ones(3), passes=["prng-const-key"])
        assert len(rep.warnings) == 1
        assert "baked" in rep.warnings[0].message

    def test_negative_threaded_key(self):
        def f(k, x):
            return x + jax.random.uniform(k, (3,))

        rep = run_passes(f, jax.random.key(0), jnp.ones(3),
                         passes=["prng-const-key"])
        assert rep.findings == []


class TestDtypePromotion:
    def test_positive_bf16_widening(self):
        def f(x):
            return x.astype(jnp.float32) * 2.0

        rep = run_passes(f, jnp.ones(3, jnp.bfloat16),
                         passes=["dtype-promotion"])
        assert len(rep.warnings) == 1
        assert "bfloat16->float32" in rep.warnings[0].message

    def test_negative_same_width(self):
        def f(x):
            return x.astype(jnp.int32) + 1

        rep = run_passes(f, jnp.ones(3, jnp.float32),
                         passes=["dtype-promotion"])
        assert rep.findings == []

    def test_aggregated_count(self):
        def f(x, y):
            return x.astype(jnp.float32) + y.astype(jnp.float32)

        rep = run_passes(f, jnp.ones(3, jnp.bfloat16),
                         jnp.ones(3, jnp.bfloat16),
                         passes=["dtype-promotion"])
        assert len(rep.warnings) == 1       # one finding per (src, dst)
        assert "x2" in rep.warnings[0].message


class TestDeadCode:
    def test_positive(self):
        def f(x):
            dead = jnp.sin(x) * 2.0  # noqa: F841 — deliberately unused
            return x + 1

        rep = run_passes(f, jnp.ones(3), passes=["dead-code"])
        assert len(rep.findings) == 1
        assert "sin" in rep.findings[0].message

    def test_negative(self):
        rep = run_passes(lambda x: jnp.sin(x) + 1, jnp.ones(3),
                         passes=["dead-code"])
        assert rep.findings == []


class TestRecompileHazard:
    def test_positive_scalar_const(self):
        c = jnp.float32(3.0)   # 0-d array closed over -> trace const

        def f(x):
            return x * c

        rep = run_passes(f, jnp.ones(3), passes=["recompile-hazard"])
        assert len(rep.findings) == 1
        assert "scalar" in rep.findings[0].message

    def test_positive_large_baked_array(self):
        w = jnp.ones((64, 64))

        def f(x):
            return x @ w

        rep = run_passes(f, jnp.ones((2, 64)), passes=["recompile-hazard"],
                         large_threshold=1024)
        assert len(rep.warnings) == 1
        assert "closed over" in rep.warnings[0].message

    def test_negative_args_only(self):
        rep = run_passes(lambda x, w: x @ w, jnp.ones((2, 4)),
                         jnp.ones((4, 4)), passes=["recompile-hazard"])
        assert rep.findings == []


class TestCollectiveCount:
    def test_positive_psum(self):
        closed = jax.make_jaxpr(lambda x: jax.lax.psum(x, "i"),
                                axis_env=[("i", 2)])(1.0)
        rep = run_passes(closed, passes=["collective-count"])
        assert len(rep.findings) == 1
        assert "all-reduce" in rep.findings[0].message

    def test_negative(self):
        rep = run_passes(lambda x: x + 1, jnp.ones(3),
                         passes=["collective-count"])
        assert rep.findings == []

    def test_hlo_counter_format(self):
        # the exact-count machinery the perf-budget gate shares
        hlo = ("%a = all-reduce(x), %b = all-gather-start(y), "
               "%c = reduce-scatter(z), %d = all-reduce(w)")
        got = count_hlo_collectives(hlo)
        assert got == {"all-reduce": 2, "all-gather": 1,
                       "reduce-scatter": 1}


class TestQuantizedCollectiveClassifier:
    """count_quantized_collectives: the int8 exchange/gather pair of a
    wire-compressed all-reduce (distributed/compress.py), classified by
    payload dtype so the perf-budget gate can pin exact counts."""

    @staticmethod
    def _pair(dtype):
        def f(x):
            q = x.astype(dtype).reshape(2, -1)
            ex = jax.lax.all_to_all(q, "i", split_axis=0, concat_axis=0)
            return jax.lax.all_gather(ex.reshape(-1)[:4], "i",
                                      tiled=True)

        return jax.make_jaxpr(f, axis_env=[("i", 2)])(jnp.ones(8))

    def test_positive_int8_pair(self):
        from paddle_tpu.analysis.collectives import \
            count_quantized_collectives

        got = count_quantized_collectives(self._pair(jnp.int8).jaxpr)
        assert got == {"quantized-reduce-scatter": 1,
                       "quantized-all-gather": 1}

    def test_negative_fp32_pair_not_classified(self):
        from paddle_tpu.analysis.collectives import \
            count_quantized_collectives

        got = count_quantized_collectives(self._pair(jnp.float32).jaxpr)
        assert got == {"quantized-reduce-scatter": 0,
                       "quantized-all-gather": 0}

    def test_negative_plain_model(self):
        from paddle_tpu.analysis.collectives import \
            count_quantized_collectives

        closed = jax.make_jaxpr(lambda x: x @ x)(jnp.ones((4, 4)))
        got = count_quantized_collectives(closed.jaxpr)
        assert sum(got.values()) == 0

    def test_pass_emits_classification(self):
        rep = run_passes(self._pair(jnp.int8),
                         passes=["collective-count"])
        msgs = [f.message for f in _by_pass(rep, "collective-count")]
        assert any("quantized reduce family" in m for m in msgs), msgs

    def test_pass_silent_without_quantized_ops(self):
        closed = jax.make_jaxpr(lambda x: jax.lax.psum(x, "i"),
                                axis_env=[("i", 2)])(1.0)
        rep = run_passes(closed, passes=["collective-count"])
        msgs = [f.message for f in _by_pass(rep, "collective-count")]
        assert msgs and not any("quantized" in m for m in msgs)


class TestImplicitReplication:
    """The ISSUE 13 upgrade of unsharded-large-tensor: spec propagation
    with provenance — only replication MATERIALIZED in-graph fires."""

    def _mesh(self, n=2):
        return jax.sharding.Mesh(np.array(jax.devices()[:n]), ("dp",))

    def test_positive_materialized_with_provenance(self):
        mesh = self._mesh()

        def f(x):
            big = jnp.broadcast_to(jnp.arange(64, dtype=jnp.float32),
                                   (64, 64))
            return x + big.sum()

        from jax.sharding import NamedSharding, PartitionSpec as P

        cj = jax.make_jaxpr(jax.jit(
            f, in_shardings=NamedSharding(mesh, P("dp"))))(jnp.ones((8,)))
        rep = run_passes(cj, passes=["implicit-replication"], mesh=mesh,
                         large_threshold=1024)
        assert len(rep.warnings) == 1
        msg = rep.warnings[0].message
        assert "materialized replicated" in msg
        assert "provenance:" in msg and "broadcast_in_dim" in msg

    def test_negative_derived_from_sharded_input(self):
        mesh = self._mesh()
        from jax.sharding import NamedSharding, PartitionSpec as P

        def f(x):
            return (x @ x.T).sum()

        cj = jax.make_jaxpr(jax.jit(
            f, in_shardings=NamedSharding(mesh, P("dp"))))(
                jnp.ones((64, 64)))
        rep = run_passes(cj, passes=["implicit-replication"], mesh=mesh,
                         large_threshold=1024)
        assert rep.findings == []

    def test_negative_declared_replicated_input_is_intentional(self):
        mesh = self._mesh()
        from jax.sharding import NamedSharding, PartitionSpec as P

        def f(w):
            return w * 0.01   # dp-replicated weight-decay-style math

        cj = jax.make_jaxpr(jax.jit(
            f, in_shardings=NamedSharding(mesh, P())))(jnp.ones((64, 64)))
        rep = run_passes(cj, passes=["implicit-replication"], mesh=mesh,
                         large_threshold=1024)
        assert rep.findings == []

    def test_negative_no_mesh(self):
        def f(x):
            return jnp.broadcast_to(jnp.arange(64, dtype=jnp.float32),
                                    (64, 64)).sum() + x

        rep = run_passes(f, jnp.ones(()),
                         passes=["implicit-replication"],
                         large_threshold=1024)
        assert rep.findings == []

    def test_negative_constrained_value_not_flagged(self):
        mesh = self._mesh()
        from jax.sharding import NamedSharding, PartitionSpec as P

        def f(x):
            big = jnp.broadcast_to(jnp.arange(64, dtype=jnp.float32),
                                   (64, 64))
            big = jax.lax.with_sharding_constraint(
                big, NamedSharding(mesh, P("dp")))
            return x + big.sum()

        cj = jax.make_jaxpr(jax.jit(
            f, in_shardings=NamedSharding(mesh, P("dp"))))(jnp.ones((8,)))
        rep = run_passes(cj, passes=["implicit-replication"], mesh=mesh,
                         large_threshold=1024)
        assert rep.findings == []


class TestDonationMiss:
    def test_positive_info_when_unknown(self):
        def f(state, x):
            return state + x, jnp.sum(x)

        rep = run_passes(f, jnp.ones((64, 64)), jnp.ones((64, 64)),
                         passes=["donation-miss"], large_threshold=1024)
        assert len(rep.findings) == 1
        assert rep.findings[0].severity == "info"

    def test_positive_warning_with_known_donation(self):
        def f(state, x):
            return state + x

        rep = run_passes(f, jnp.ones((64, 64)), jnp.ones((64, 64)),
                         passes=["donation-miss"], large_threshold=1024,
                         donated=set())
        assert [f.severity for f in rep.findings].count("warning") == 1

    def test_negative_donated(self):
        def f(state, x):
            return state + x

        rep = run_passes(f, jnp.ones((64, 64)), jnp.ones((64, 64)),
                         passes=["donation-miss"], large_threshold=1024,
                         donated={0, 1})
        assert rep.findings == []


# ---------------------------------------------------------------------------
# source-lint rules
# ---------------------------------------------------------------------------


class TestSourceLint:
    def test_np_random_positive(self):
        src = ("import numpy as np\n"
               "def op(x):\n"
               "    return x + np.random.randn(3)\n")
        fs = lint_source(src, "nn/functional/fake.py", traced=True)
        assert [f.pass_name for f in fs] == ["np-random-in-traced-code"]
        assert fs[0].severity == "error"
        assert fs[0].where == "nn/functional/fake.py:3"

    def test_np_random_init_exempt(self):
        src = ("import numpy as np\n"
               "class L:\n"
               "    def __init__(self):\n"
               "        self.w = np.random.randn(3)\n")
        assert lint_source(src, "nn/x.py", traced=True) == []

    def test_np_random_untraced_module_exempt(self):
        src = ("import numpy as np\n"
               "def sample(x):\n"
               "    return np.random.permutation(x)\n")
        assert lint_source(src, "io/sampler.py", traced=False) == []

    def test_suppression_comment(self):
        src = ("import numpy as np\n"
               "def op(x):\n"
               "    r = np.random.RandomState(0)  "
               "# lint: allow(np-random-in-traced-code)\n"
               "    return x\n")
        assert lint_source(src, "nn/x.py", traced=True) == []

    def test_time_in_traced_code(self):
        src = ("import time\n"
               "def fwd(x):\n"
               "    return x * time.time()\n")
        fs = lint_source(src, "models/x.py", traced=True)
        assert [f.pass_name for f in fs] == ["time-in-traced-code"]
        assert fs[0].severity == "warning"

    def test_mutable_default_positive(self):
        src = ("class MyBlock(nn.Layer):\n"
               "    def forward(self, x, hooks=[]):\n"
               "        return x\n")
        fs = lint_source(src, "nn/layer/fake.py", traced=True)
        assert [f.pass_name for f in fs] == ["mutable-default-arg"]
        assert fs[0].severity == "error"

    def test_mutable_default_non_layer_exempt(self):
        src = ("class Helper:\n"
               "    def run(self, x, hooks=[]):\n"
               "        return x\n")
        assert lint_source(src, "nn/layer/fake.py", traced=True) == []

    def test_private_model_import_in_serving_positive(self):
        # both module-level and function-level imports are caught
        src = ("from ..models.gpt import _decode_fns\n"
               "def build():\n"
               "    from ..models.gpt import _tp_wrap, GPTConfig\n")
        fs = lint_source(src, "inference/serving.py", traced=False)
        assert [f.pass_name for f in fs] == \
            ["private-model-import-in-serving"] * 2
        assert all(f.severity == "error" for f in fs)
        assert fs[0].where == "inference/serving.py:1"
        # the serving/ package is covered too
        fs = lint_source("from ..models.bert import _x\n",
                         "serving/router.py", traced=False)
        assert [f.pass_name for f in fs] == \
            ["private-model-import-in-serving"]

    def test_private_model_import_public_and_elsewhere_exempt(self):
        # public names are the supported surface
        assert lint_source("from ..models.gpt import GPTForCausalLM\n",
                           "inference/predictor.py", traced=False) == []
        # model modules may use their own privates (adapter registration)
        assert lint_source("from .gpt import _decode_fns\n",
                           "models/zoo.py", traced=True) == []
        # non-serving packages are out of scope for this rule
        assert lint_source("from ..models.gpt import _decode_fns\n",
                           "hapi/model.py", traced=False) == []

    def test_private_model_import_allow_marker(self):
        src = ("from ..models.gpt import _x  "
               "# lint: allow(private-model-import-in-serving)\n")
        assert lint_source(src, "inference/serving.py", traced=False) == []


class TestNonreducedClientOutput:
    """ISSUE 8 lint satellite: a client_map result must not escape a
    federated/ API without passing through a federated_* reduce (or carry
    an explicit `# lint: allow(client_output)` marker)."""

    def test_positive_assigned_then_returned(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return vals\n")
        fs = lint_source(src, "federated/primitives.py", traced=False)
        assert [f.pass_name for f in fs] == ["nonreduced-client-output"]
        assert fs[0].severity == "error"
        assert "federated_sum" in fs[0].message

    def test_positive_direct_return(self):
        src = ("def api(xs):\n"
               "    return client_map(fn, xs)\n")
        fs = lint_source(src, "federated/averaging.py", traced=False)
        assert [f.pass_name for f in fs] == ["nonreduced-client-output"]

    def test_positive_in_tuple_return(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    total = federated_sum(other(xs))\n"
               "    return total, vals\n")
        fs = lint_source(src, "federated/x.py", traced=False)
        assert [f.pass_name for f in fs] == ["nonreduced-client-output"]

    def test_negative_value_fed_through_reduce_expression(self):
        """A name consumed INSIDE a reduce's argument expression counts
        as reduced (the heuristic clears every name the reduce saw)."""
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return federated_sum(vals * 2)\n")
        assert lint_source(src, "federated/x.py", traced=False) == []

    def test_negative_reduced_before_return(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return federated_mean(vals)\n")
        assert lint_source(src, "federated/primitives.py",
                           traced=False) == []

    def test_negative_client_reduce_chokepoint(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    out = _coll.client_reduce(vals)\n"
               "    return out\n")
        assert lint_source(src, "federated/primitives.py",
                           traced=False) == []

    def test_negative_rebound_name(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    vals = federated_sum(vals)\n"
               "    return vals\n")
        assert lint_source(src, "federated/x.py", traced=False) == []

    def test_allow_marker_short_and_full(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return vals  # lint: allow(client_output)\n")
        assert lint_source(src, "federated/primitives.py",
                           traced=False) == []
        src2 = ("def api(xs):\n"
                "    vals = client_map(fn, xs)\n"
                "    return vals  # lint: allow(nonreduced-client-output)\n")
        assert lint_source(src2, "federated/primitives.py",
                           traced=False) == []

    def test_rule_scoped_to_federated_modules(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return vals\n")
        assert lint_source(src, "distributed/spmd.py", traced=False) == []
        assert lint_source(src, "nn/layer/common.py", traced=True) == []

    def test_repo_federated_package_is_clean(self):
        """paddle_tpu's own federated/ modules hold the bar the rule
        sets (any deliberate client-placed return carries the marker)."""
        import os

        import paddle_tpu.federated as fed

        root = os.path.dirname(os.path.abspath(fed.__file__))
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn), encoding="utf-8") as f:
                src = f.read()
            fs = lint_source(src, f"federated/{fn}", traced=False)
            assert [f_ for f_ in fs
                    if f_.pass_name == "nonreduced-client-output"] == []


# ---------------------------------------------------------------------------
# analysis hooks: static Program and inference Predictor
# ---------------------------------------------------------------------------


class TestAnalysisHooks:
    def test_program_analysis_jaxpr(self):
        import paddle_tpu as paddle
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [None, 8], "float32")
                w = paddle.ones([8, 4])
                w.persistable = True
                y = paddle.nn.functional.relu(paddle.matmul(x, w))
            exe = static.Executor()
            exe.run(startup)
            exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                    fetch_list=[y])
            closed = main.analysis_jaxpr(
                feed={"x": np.ones((2, 8), np.float32)})
            assert closed.jaxpr.eqns, "expected a non-empty replay jaxpr"
            rep = run_passes(closed, name="static_program")
            assert rep.errors == []
        finally:
            paddle.disable_static()

    def test_program_analysis_jaxpr_train_form(self):
        # a program with an optimizer attached traces the TRAIN step —
        # the graph Executor.run actually executes for it (fwd + grads +
        # update), not the eval forward
        import paddle_tpu as paddle
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [None, 4], "float32")
                w = paddle.ones([4, 1])
                w.persistable = True
                loss = paddle.mean(paddle.matmul(x, w))
                opt = paddle.optimizer.SGD(learning_rate=0.1)
                opt.minimize(loss)
            exe = static.Executor()
            exe.run(startup)
            eval_closed = main.clone(for_test=True).analysis_jaxpr(
                feed={"x": np.ones((2, 4), np.float32)})
            train_closed = main.analysis_jaxpr(
                feed={"x": np.ones((2, 4), np.float32)})
            # train step takes (params, opt_state, lr, feed) and computes
            # grads + the update — strictly more work than the eval form
            assert len(train_closed.jaxpr.eqns) > len(
                eval_closed.jaxpr.eqns)
            assert run_passes(train_closed, name="train_prog").errors == []
        finally:
            paddle.disable_static()

    def test_program_analysis_jaxpr_empty_program(self):
        import paddle_tpu.static as static

        with pytest.raises(ValueError, match="empty program"):
            static.Program().analysis_jaxpr()

    def test_predictor_analysis_jaxpr(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu import jit as pjit
        from paddle_tpu.inference.predictor import Config, create_predictor
        from paddle_tpu.jit import InputSpec

        m = paddle.nn.Linear(8, 4)
        path = str(tmp_path / "lin")
        pjit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        pred = create_predictor(Config(path))
        closed = pred.analysis_jaxpr(
            inputs=[np.ones((2, 8), np.float32)])
        assert closed.jaxpr.eqns
        assert run_passes(closed, name="predictor").errors == []

    def test_predictor_surplus_input_does_not_poison(self, tmp_path):
        # an accidental extra positional input fails ITS call (the layer
        # rejects the arity) but must not persist into later calls
        import paddle_tpu as paddle
        from paddle_tpu import jit as pjit
        from paddle_tpu.inference.predictor import Config, create_predictor
        from paddle_tpu.jit import InputSpec

        m = paddle.nn.Linear(8, 4)
        path = str(tmp_path / "lin")
        pjit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        pred = create_predictor(Config(path))
        x = np.ones((2, 8), np.float32)
        with pytest.raises(TypeError):
            pred.run([x, np.ones((2, 8), np.float32)])
        assert pred.get_input_names() == ["input_0"]
        (out,) = pred.run([x])
        assert out.shape == (2, 4)


class TestToHostFlag:
    def test_error_mode_names_the_sync(self):
        import paddle_tpu as paddle

        paddle.set_flags({"trace_host_sync": "error"})
        try:
            def f(x):
                return paddle.to_tensor(x).numpy()

            with pytest.raises(RuntimeError, match="host sync"):
                jax.jit(f)(np.ones(3, np.float32))
        finally:
            paddle.set_flags({"trace_host_sync": "silent"})

    def test_warn_mode_warns_then_jax_raises(self):
        import paddle_tpu as paddle

        paddle.set_flags({"trace_host_sync": "warn"})
        try:
            def f(x):
                return paddle.to_tensor(x).item()

            with pytest.warns(UserWarning, match="host sync"):
                with pytest.raises(Exception):
                    jax.jit(f)(np.ones((), np.float32))
        finally:
            paddle.set_flags({"trace_host_sync": "silent"})

    def test_eager_unaffected(self):
        import paddle_tpu as paddle

        t = paddle.to_tensor([1.0, 2.0])
        assert t.numpy().tolist() == [1.0, 2.0]
        assert paddle.to_tensor(3.5).item() == 3.5


class TestStepLoopHostSync:
    """ISSUE 11: per-step host pulls inside the trainer/serving hot
    paths are errors unless they carry the allow-marker."""

    HOT = ("import numpy as np\n"
           "class SpmdTrainer:\n"
           "    def _train_step_impl(self, x):\n"
           "        return np.asarray(x)\n")

    def test_positive_np_asarray_in_hot_path(self):
        fs = lint_source(self.HOT,
                         os.path.join("distributed", "spmd.py"))
        assert [f.pass_name for f in fs] == ["step-loop-host-sync"]
        assert fs[0].severity == "error"

    def test_positive_item_and_block_until_ready(self):
        src = ("class ServingEngine:\n"
               "    def _step_inner(self, toks):\n"
               "        toks.block_until_ready()\n"
               "        return toks.item()\n")
        fs = lint_source(src, os.path.join("inference", "serving.py"))
        assert [f.pass_name for f in fs] == ["step-loop-host-sync"] * 2

    def test_positive_nested_closure_in_hot_path_counts(self):
        src = ("import numpy as np\n"
               "class SpmdTrainer:\n"
               "    def _drain_verdicts(self, vals):\n"
               "        def inner(v):\n"
               "            return np.asarray(v)\n"
               "        return [inner(v) for v in vals]\n")
        fs = lint_source(src, os.path.join("distributed", "spmd.py"))
        assert [f.pass_name for f in fs] == ["step-loop-host-sync"]

    def test_negative_allow_marker(self):
        src = ("import numpy as np\n"
               "class SpmdTrainer:\n"
               "    def _train_step_impl(self, x):\n"
               "        return np.asarray(x)"
               "  # lint: allow(step-loop-host-sync)\n")
        assert lint_source(src,
                           os.path.join("distributed", "spmd.py")) == []

    def test_negative_outside_hot_functions_and_files(self):
        src = ("import numpy as np\n"
               "class SpmdTrainer:\n"
               "    def stats(self, x):\n"
               "        return np.asarray(x)\n")
        assert lint_source(src,
                           os.path.join("distributed", "spmd.py")) == []
        assert lint_source(self.HOT, "nn/layer/fake.py",
                           traced=False) == []

    def test_repo_hot_paths_are_clean(self):
        # the ISSUE 11 satellite: after the deferred-guard fix, the
        # live spmd/serving hot paths carry ONLY allow-marked syncs
        from paddle_tpu.analysis.source_lint import lint_path

        fs = [f for f in lint_path()
              if f.pass_name == "step-loop-host-sync"]
        assert fs == [], [f.where for f in fs]

    def test_repo_allow_markers_still_present(self):
        # the deliberate syncs double as documentation: the windowed
        # drain fetch, the benchmark sync, the decode token fetch
        for rel, needle in (
                ("paddle_tpu/distributed/spmd.py", "device_get"),
                ("paddle_tpu/inference/serving.py", "np.asarray"),
        ):
            src = open(os.path.join(REPO, rel)).read()
            marked = [ln for ln in src.splitlines()
                      if "lint: allow(step-loop-host-sync)" in ln]
            assert any(needle in ln for ln in marked), (rel, needle)


# ---------------------------------------------------------------------------
# regression assertions for the real findings the passes surfaced
# ---------------------------------------------------------------------------


class TestRepoRegressions:
    def test_model_position_ids_are_int32(self):
        # the passes' first real catch: all four position embeddings
        # requested arange(dtype="int64"), truncated with a per-call
        # UserWarning (x64 off). Pinned here via the trace-warnings
        # channel: tracing each bundled model must be warning-clean.
        from paddle_tpu.analysis import analyze_model

        for name in ("gpt", "bert", "ernie"):
            rep = analyze_model(name)
            assert _by_pass(rep, "trace-warnings") == [], (
                f"{name}: tracing the forward raised python warnings "
                f"again: {[f.message for f in rep.findings]}")
            assert rep.errors == []

    def test_no_unsuppressed_np_random_in_traced_code(self):
        # the two deliberate eager-host samplers (nce, tdm_sampler) carry
        # `# lint: allow(...)` markers; anything NEW fails here
        from paddle_tpu.analysis.source_lint import lint_path

        fs = [f for f in lint_path()
              if f.pass_name == "np-random-in-traced-code"]
        assert fs == [], [f.where for f in fs]

    def test_allow_markers_still_present(self):
        # the suppressions double as documentation — removing the comment
        # (or the guard it documents) must trip the gate, not pass silently
        for rel in ("paddle_tpu/nn/functional/extension.py",
                    "paddle_tpu/nn/functional/loss.py"):
            src = open(os.path.join(REPO, rel)).read()
            assert "lint: allow(np-random-in-traced-code)" in src, rel


# ---------------------------------------------------------------------------
# ISSUE 12: contract-auditor passes (flag / import / observability / thread)
# ---------------------------------------------------------------------------

from paddle_tpu.analysis import allowlist  # noqa: E402
from paddle_tpu.analysis import flag_audit  # noqa: E402
from paddle_tpu.analysis import import_graph  # noqa: E402
from paddle_tpu.analysis import obs_audit  # noqa: E402
from paddle_tpu.analysis.source_lint import (  # noqa: E402
    THREAD_SHARED_MODULES, lint_thread_discipline)


def _flag_findings(sources, **kw):
    kw.setdefault("hot_paths", {})
    kw.setdefault("lazy_modules", ())
    return flag_audit.audit_inventory(flag_audit.collect(sources), **kw)


def _rules_of(findings):
    return {f.pass_name for f in findings}


class TestFlagAudit:
    def test_orphan_flag_unread_planted(self):
        fs = _flag_findings({"m.py": 'define_flag("dead_probe", 0, "h")\n'})
        assert _rules_of(fs) == {"orphan-flag-unread"}
        assert fs[0].severity == "error"
        assert "dead_probe" in fs[0].message

    def test_read_flag_is_not_orphan(self):
        fs = _flag_findings({
            "m.py": 'define_flag("live_probe", 0, "h")\n',
            "n.py": 'x = get_flag("live_probe", 0)\n'})
        assert fs == []

    def test_orphan_flag_undefined_planted(self):
        fs = _flag_findings({"m.py": 'x = get_flag("never_defined")\n'})
        assert _rules_of(fs) == {"orphan-flag-undefined"}

    def test_missing_help_planted(self):
        fs = _flag_findings({
            "m.py": 'define_flag("helpless", 1)\n'
                    'y = get_flag("helpless")\n'})
        assert _rules_of(fs) == {"flag-missing-help"}

    def test_conflicting_default_planted(self):
        fs = _flag_findings({
            "a.py": 'define_flag("dup", 1, "h")\nga = get_flag("dup")\n',
            "b.py": 'define_flag("dup", 2, "h")\n'})
        assert "flag-default-conflict" in _rules_of(fs)

    def test_default_drift_warns(self):
        fs = _flag_findings({
            "a.py": 'define_flag("drifty", 8, "h")\n',
            "b.py": 'x = get_flag("drifty", 4)\n'})
        assert _rules_of(fs) == {"flag-default-drift"}
        assert all(f.severity == "warning" for f in fs)

    def test_structural_key_miss_planted(self):
        src = ('define_flag("structural_probe", False, "h")\n'
               'def consume(self):\n'
               '    self._sp = get_flag("structural_probe", False)\n')
        fs = _flag_findings({"m.py": src},
                            structural=("structural_probe",))
        assert "structural-flag-key-miss" in _rules_of(fs)

    def test_structural_flag_reaching_exec_key_is_clean(self):
        src = ('define_flag("structural_ok", False, "h")\n'
               'def consume(self):\n'
               '    self._sp = get_flag("structural_ok", False)\n'
               'def _exec_key(self, sig):\n'
               '    return (sig, self._sp)\n')
        fs = _flag_findings({"m.py": src}, structural=("structural_ok",))
        assert fs == []

    def test_structural_flag_via_extra_key_is_clean(self):
        src = ('define_flag("structural_ek", False, "h")\n'
               'def consume(self):\n'
               '    self._ek = get_flag("structural_ek", False)\n'
               'def compile(self):\n'
               '    c = compile_cached(f, extra_key=("t", self._ek))\n')
        fs = _flag_findings({"m.py": src}, structural=("structural_ek",))
        assert fs == []

    def test_structural_flag_via_carrier_hop_is_clean(self):
        # the spmd.py shape: _resolve() consumes the flag, its result is
        # assigned to self._q, and self._q joins the key
        src = ('define_flag("structural_hop", False, "h")\n'
               'def _resolve(self):\n'
               '    return get_flag("structural_hop", False)\n'
               'def __init__(self):\n'
               '    self._q = self._resolve()\n'
               'def _exec_key(self, sig):\n'
               '    return (sig, self._q)\n')
        fs = _flag_findings({"m.py": src},
                            structural=("structural_hop",))
        assert fs == []

    def test_hot_path_flag_read_planted(self):
        src = ('define_flag("hot_probe", False, "h")\n'
               'def train_step(self):\n'
               '    if get_flag("hot_probe", False):\n'
               '        pass\n')
        fs = _flag_findings({"m.py": src}, structural=("hot_probe",),
                            hot_paths={"m.py": {"train_step"}})
        assert "hot-path-flag-read" in _rules_of(fs)

    def test_active_checker_read_is_sanctioned(self):
        src = ('define_flag("hot_ok", False, "h")\n'
               'def _guard_active(self):\n'
               '    return get_flag("hot_ok", False) == self._g\n'
               'def _exec_key(self, sig):\n'
               '    return (sig, self._guard_active())\n')
        fs = _flag_findings({"m.py": src}, structural=("hot_ok",),
                            hot_paths={"m.py": {"_guard_active"}})
        assert fs == []

    def test_allow_marker_suppresses_orphan(self):
        fs = _flag_findings({
            "m.py": 'define_flag("stub", 0, "h")'
                    '  # lint: allow(orphan-flag)\n'})
        assert fs == []

    def test_repo_flags_are_clean(self):
        assert flag_audit.audit_package() == []

    def test_repo_structural_flags_all_reach_keys(self):
        # every declared structural flag exists AND joins a key — the
        # acceptance-criterion form of the pass over the real tree
        scans = flag_audit.collect(flag_audit.package_sources())
        defined = set()
        for s in scans.values():
            defined |= {n for n, _, _, _ in s.defines}
        assert set(flag_audit.STRUCTURAL_FLAGS) <= defined


class TestImportGraphAudit:
    def _graph(self, sources):
        return import_graph.build_graph(sources=sources)

    def test_eager_leak_planted(self):
        g = self._graph({
            "pkg": "",
            "pkg.core": "from . import heavy\n",
            "pkg.heavy": "",
        })
        fs = import_graph.audit_graph(g, manifest=("pkg.heavy",),
                                      roots=("pkg.core",))
        assert [f.pass_name for f in fs] == ["lazy-module-leak"]
        assert "pkg.core -> pkg.heavy" in fs[0].message

    def test_function_local_import_is_lazy(self):
        g = self._graph({
            "pkg": "",
            "pkg.core": "def go():\n    from . import heavy\n",
            "pkg.heavy": "",
        })
        fs = import_graph.audit_graph(g, manifest=("pkg.heavy",),
                                      roots=("pkg.core",))
        assert fs == []

    def test_allow_marked_module_level_import_is_conditional(self):
        g = self._graph({
            "pkg": "",
            "pkg.core": "from . import heavy"
                        "  # lint: allow(lazy-import)\n",
            "pkg.heavy": "",
        })
        fs = import_graph.audit_graph(g, manifest=("pkg.heavy",),
                                      roots=("pkg.core",))
        assert fs == []

    def test_transitive_leak_reports_chain(self):
        g = self._graph({
            "pkg": "",
            "pkg.a": "from . import b\n",
            "pkg.b": "from . import heavy\n",
            "pkg.heavy": "",
        })
        fs = import_graph.audit_graph(g, manifest=("pkg.heavy",),
                                      roots=("pkg.a",))
        assert len(fs) == 1
        assert "pkg.a -> pkg.b -> pkg.heavy" in fs[0].message

    def test_subtree_manifest_entry(self):
        g = self._graph({
            "pkg": "",
            "pkg.core": "from .fed import avg\n",
            "pkg.fed": "",
            "pkg.fed.avg": "",
        })
        fs = import_graph.audit_graph(g, manifest=("pkg.fed",),
                                      roots=("pkg.core",))
        leaked = {f.where for f in fs}
        assert "pkg.fed.avg" in leaked and "pkg.fed" in leaked

    def test_stale_manifest_entry(self):
        g = self._graph({"pkg": "", "pkg.core": ""})
        fs = import_graph.audit_graph(g, manifest=("pkg.ghost",),
                                      roots=("pkg.core",))
        assert [f.pass_name for f in fs] == ["lazy-manifest-stale"]

    def test_repo_manifest_modules_exist(self):
        g = import_graph.build_graph()
        for entry in import_graph.LAZY_MODULES:
            assert g.expand(entry), entry

    def test_repo_plain_closure_is_clean(self):
        # the one generated check unifying the ten subprocess no-import
        # pins: every manifest-lazy module stays out of the closure
        assert import_graph.audit_package() == []

    def test_repo_closure_is_nontrivial(self):
        # guard against the checker trivially passing on a broken graph
        g = import_graph.build_graph()
        closure = g.eager_closure(import_graph.PLAIN_CLOSURE_ROOTS)
        assert len(closure) > 50
        assert "paddle_tpu.distributed.spmd" in closure
        assert "paddle_tpu.monitor" in closure


_OBS_DOC = """
# doc

## Metric family reference

| family | kind |
|---|---|
| `good_total` | counter |

## Span name reference

| span | subsystem |
|---|---|
| `phase` | app |
| `collective/<op>` | collective |
"""


class TestObsAudit:
    def test_clean_inventory(self):
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'with _trace.span("phase"):\n    pass\n'}
        assert obs_audit.audit_inventory(srcs, _OBS_DOC) == []

    def test_undocumented_metric_planted(self):
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'with _trace.span("phase"):\n    pass\n'
                        '_D = _monitor.gauge("rogue_gauge", "h")\n'}
        fs = obs_audit.audit_inventory(srcs, _OBS_DOC)
        assert [f.pass_name for f in fs] == ["metric-undocumented"]
        assert "rogue_gauge" in fs[0].message

    def test_doc_stale_metric(self):
        fs = obs_audit.audit_inventory({"m.py": "x = 1\n"}, _OBS_DOC)
        assert "metric-doc-stale" in {f.pass_name for f in fs}

    def test_undocumented_span_planted(self):
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'sp = _trace.start_span("rogue_span")\n'}
        fs = obs_audit.audit_inventory(srcs, _OBS_DOC)
        assert "span-undocumented" in {f.pass_name for f in fs}

    def test_dynamic_span_row_accepted(self):
        # collective/<op> has no literal call site; DYNAMIC_SPANS covers it
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'with _trace.span("phase"):\n    pass\n'}
        fs = obs_audit.audit_inventory(srcs, _OBS_DOC)
        assert "span-doc-stale" not in {f.pass_name for f in fs}

    def test_stale_span_row(self):
        doc = _OBS_DOC + "| `gone_span` | app |\n"
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'with _trace.span("phase"):\n    pass\n'}
        fs = obs_audit.audit_inventory(srcs, doc)
        assert "span-doc-stale" in {f.pass_name for f in fs}

    def test_required_family_gone_planted(self):
        dump = '_REQUIRED = {"train": ("good_total", "vanished_total")}\n'
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'with _trace.span("phase"):\n    pass\n'}
        fs = obs_audit.audit_inventory(srcs, _OBS_DOC, dump_source=dump)
        assert [f.pass_name for f in fs] == ["required-family-gone"]
        assert "vanished_total" in fs[0].message

    def test_required_series_families_checked(self):
        dump = ('_REQUIRED_SERIES = {"q": (("lost_total", "op", "x"),)}\n')
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'with _trace.span("phase"):\n    pass\n'}
        fs = obs_audit.audit_inventory(srcs, _OBS_DOC, dump_source=dump)
        assert "required-family-gone" in {f.pass_name for f in fs}

    def test_allow_marker_suppresses_undocumented(self):
        srcs = {"m.py": '_C = _monitor.counter("good_total", "h")\n'
                        'with _trace.span("phase"):\n    pass\n'
                        '_P = _monitor.gauge("private_g", "h")'
                        '  # lint: allow(undocumented-metric)\n'}
        fs = obs_audit.audit_inventory(srcs, _OBS_DOC)
        assert fs == []

    def test_harvest_is_receiver_scoped(self):
        # only the telemetry module aliases register: a bare emit()
        # helper (the analysis passes' own finding emitters) or a
        # foreign .counter() must not be harvested
        srcs = {"m.py": 'emit("deadcode", scan, 1, "msg")\n'
                        'scan.counter("not_a_metric", 2)\n'
                        'sp.span("not_a_span")\n'}
        assert obs_audit.code_span_names(srcs) == {}
        assert obs_audit.code_metric_families(srcs) == {}

    def test_repo_observability_is_clean(self):
        assert obs_audit.audit_package() == []


_THREADED_BAD = """
import threading
_LOCK = threading.Lock()
_STATE = {}
_COUNT = [0]

def worker():
    _STATE["k"] = 1
    _COUNT[0] += 1

threading.Thread(target=worker, daemon=True).start()
"""

_THREADED_GOOD = """
import threading
_LOCK = threading.Lock()
_STATE = {}

def worker():
    local = {}
    local["k"] = 1
    with _LOCK:
        _STATE["k"] = 1

threading.Thread(target=worker, daemon=True).start()
"""


class TestThreadDisciplineLint:
    def test_unlocked_write_planted(self):
        fs = lint_thread_discipline(_THREADED_BAD, "m.py", "_LOCK")
        assert {f.pass_name for f in fs} == {"unlocked-thread-shared-write"}
        assert len(fs) == 2   # _STATE and _COUNT

    def test_locked_and_local_writes_are_clean(self):
        assert lint_thread_discipline(_THREADED_GOOD, "m.py",
                                      "_LOCK") == []

    def test_thread_subclass_run_is_a_root(self):
        src = ("import threading\n"
               "_LOCK = threading.Lock()\n"
               "_S = {}\n"
               "class W(threading.Thread):\n"
               "    def run(self):\n"
               "        _S['x'] = 1\n")
        fs = lint_thread_discipline(src, "m.py", "_LOCK")
        assert len(fs) == 1 and fs[0].pass_name == \
            "unlocked-thread-shared-write"

    def test_reachable_callee_is_policed(self):
        src = ("import threading\n"
               "_LOCK = threading.Lock()\n"
               "_S = {}\n"
               "def helper():\n"
               "    _S['x'] = 1\n"
               "def body():\n"
               "    helper()\n"
               "threading.Thread(target=body).start()\n")
        fs = lint_thread_discipline(src, "m.py", "_LOCK")
        assert len(fs) == 1

    def test_unreachable_function_not_policed(self):
        src = ("import threading\n"
               "_LOCK = threading.Lock()\n"
               "_S = {}\n"
               "def not_a_thread():\n"
               "    _S['x'] = 1\n"
               "def body():\n"
               "    pass\n"
               "threading.Thread(target=body).start()\n")
        assert lint_thread_discipline(src, "m.py", "_LOCK") == []

    def test_allow_marker_suppresses(self):
        src = ("import threading\n"
               "_LOCK = threading.Lock()\n"
               "_ON = [False]\n"
               "def body():\n"
               "    _ON[0] = True  # lint: allow(thread-shared-write)\n"
               "threading.Thread(target=body).start()\n")
        assert lint_thread_discipline(src, "m.py", "_LOCK") == []

    def test_nested_function_param_shadows_global(self):
        # a nested def's parameter named like a module global is LOCAL —
        # writing through it must not be flagged
        src = ("import threading\n"
               "_LOCK = threading.Lock()\n"
               "_STATE = {}\n"
               "def worker():\n"
               "    def fmt(_STATE):\n"
               "        _STATE['k'] = 1\n"
               "    fmt({})\n"
               "threading.Thread(target=worker).start()\n")
        assert lint_thread_discipline(src, "m.py", "_LOCK") == []

    def test_missing_designated_lock_is_loud(self):
        src = "import threading\n_S = {}\n"
        fs = lint_thread_discipline(src, "m.py", "_MISSING_LOCK")
        assert len(fs) == 1
        assert "appears nowhere" in fs[0].message

    def test_repo_thread_modules_are_clean(self):
        for rel, lock in THREAD_SHARED_MODULES.items():
            src = open(os.path.join(REPO, "paddle_tpu", rel)).read()
            assert lint_thread_discipline(src, rel, lock) == [], rel


class TestAllowlistConsolidation:
    def test_every_rule_has_spellings(self):
        from paddle_tpu.analysis import contract_rules

        for rule in contract_rules():
            sp = allowlist.spellings(rule)
            assert sp[0] == rule

    def test_aliases_resolve(self):
        lines = ["x = 1  # lint: allow(client_output)"]
        assert allowlist.allowed(lines, 1, "nonreduced-client-output")
        assert not allowlist.allowed(lines, 1, "orphan-flag-unread")

    def test_source_lint_shares_the_table(self):
        # the old private copy is gone: source_lint re-exports the shared
        # alias table object
        from paddle_tpu.analysis import source_lint

        assert source_lint._RULE_ALIASES is allowlist.RULE_ALIASES


# ---------------------------------------------------------------------------
# ISSUE 13: sharding-flow passes (planted pos/neg per rule)
# ---------------------------------------------------------------------------


def _smap():
    try:
        from jax import shard_map as sm
    except ImportError:
        from jax.experimental.shard_map import shard_map as sm
    return sm


def _mesh4(names=("dp",)):
    import math

    n = 4 if len(names) == 1 else 4
    devs = np.array(jax.devices()[:n])
    if len(names) > 1:
        devs = devs.reshape((2, 2))
    return jax.sharding.Mesh(devs, names)


class TestCollectiveAxisMismatch:
    def _traced_psum(self, axis="dp"):
        from jax.sharding import PartitionSpec as P

        mesh = _mesh4()

        def g(x):
            return jax.lax.psum(x, axis)

        return jax.make_jaxpr(_smap()(g, mesh=mesh, in_specs=P("dp"),
                                      out_specs=P(),
                                      check_rep=False))(jnp.ones((8,)))

    def test_positive_axis_absent_from_deployment_mesh(self):
        other = jax.sharding.Mesh(np.array(jax.devices()[:4]), ("x",))
        rep = run_passes(self._traced_psum(),
                         passes=["collective-axis-mismatch"], mesh=other)
        msgs = [f.message for f in rep.errors]
        assert any("'dp' absent from the deployment mesh" in m
                   for m in msgs), msgs
        assert any("shard_map binds axis 'dp'" in m for m in msgs)

    def test_positive_mesh_axis_size_mismatch(self):
        bigger = jax.sharding.Mesh(
            np.array(jax.devices()[:8]), ("dp",))
        rep = run_passes(self._traced_psum(),
                         passes=["collective-axis-mismatch"], mesh=bigger)
        assert any("size" in f.message for f in rep.errors), \
            [f.message for f in rep.errors]

    def test_negative_matching_mesh(self):
        rep = run_passes(self._traced_psum(),
                         passes=["collective-axis-mismatch"],
                         mesh=_mesh4())
        assert rep.findings == []

    def test_negative_no_deployment_mesh(self):
        # self-consistent program, no mesh to check against
        rep = run_passes(self._traced_psum(),
                         passes=["collective-axis-mismatch"])
        assert rep.findings == []


class TestPpermuteMalformed:
    def _traced(self, perm):
        from jax.sharding import PartitionSpec as P

        mesh = _mesh4()

        def g(x):
            return jax.lax.ppermute(x, "dp", perm)

        return jax.make_jaxpr(_smap()(g, mesh=mesh, in_specs=P("dp"),
                                      out_specs=P("dp"),
                                      check_rep=False))(jnp.ones((8,)))

    def test_positive_non_bijective(self):
        rep = run_passes(self._traced([(0, 1), (1, 1)]),
                         passes=["ppermute-malformed"], mesh=_mesh4())
        assert any("not a bijection" in f.message for f in rep.errors), \
            [f.message for f in rep.errors]

    def test_positive_self_referential(self):
        rep = run_passes(self._traced([(0, 0), (1, 2)]),
                         passes=["ppermute-malformed"], mesh=_mesh4())
        assert any("self-referential" in f.message for f in rep.errors)

    def test_positive_out_of_range(self):
        from paddle_tpu.analysis.sharding_flow import check_permutation

        problems = check_permutation(((0, 7),), axis_size=4)
        assert any("outside the axis size" in p for p in problems)

    def test_negative_ring(self):
        ring = [(i, (i + 1) % 4) for i in range(4)]
        rep = run_passes(self._traced(ring),
                         passes=["ppermute-malformed"], mesh=_mesh4())
        assert rep.findings == []

    def test_check_permutation_unit(self):
        from paddle_tpu.analysis.sharding_flow import check_permutation

        assert check_permutation([(0, 1), (1, 0)]) == []
        assert check_permutation([(0, 1), (0, 2)])      # dup source
        assert check_permutation([(1, 1)])              # self edge


class TestBranchCollectiveMismatch:
    def _traced(self, both_arms):
        from jax.sharding import PartitionSpec as P

        mesh = _mesh4()

        def taken(v):
            return jax.lax.psum(v, "dp")

        def other(v):
            return taken(v) if both_arms else v * 2.0

        def g(x):
            return jax.lax.cond(x[0] > 0, taken, other, x)

        return jax.make_jaxpr(_smap()(g, mesh=mesh, in_specs=P("dp"),
                                      out_specs=P("dp"),
                                      check_rep=False))(jnp.ones((8,)))

    def test_positive_one_arm_collective(self):
        rep = run_passes(self._traced(both_arms=False),
                         passes=["branch-collective-mismatch"],
                         mesh=_mesh4())
        assert len(rep.errors) == 1
        assert "different collective sequences" in rep.errors[0].message
        assert "arm[0]" in rep.errors[0].message

    def test_negative_matched_arms(self):
        rep = run_passes(self._traced(both_arms=True),
                         passes=["branch-collective-mismatch"],
                         mesh=_mesh4())
        assert rep.findings == []

    def test_while_predicate_collective_warns(self):
        from jax.sharding import PartitionSpec as P

        mesh = _mesh4()

        def g(x):
            def cond(c):
                return jax.lax.psum(c.sum(), "dp") < 10.0

            def body(c):
                return c + 1.0

            return jax.lax.while_loop(cond, body, x)

        cj = jax.make_jaxpr(_smap()(g, mesh=mesh, in_specs=P("dp"),
                                    out_specs=P("dp"),
                                    check_rep=False))(jnp.ones((8,)))
        rep = run_passes(cj, passes=["branch-collective-mismatch"],
                         mesh=_mesh4())
        assert len(rep.warnings) == 1
        assert "while-loop predicate" in rep.warnings[0].message

    def test_fori_loop_negative(self):
        # counter-predicate loops (the pipeline schedule) stay silent
        from jax.sharding import PartitionSpec as P

        mesh = _mesh4()

        def g(x):
            return jax.lax.fori_loop(
                0, 4, lambda i, c: jax.lax.ppermute(
                    c, "dp", [(j, (j + 1) % 4) for j in range(4)]), x)

        cj = jax.make_jaxpr(_smap()(g, mesh=mesh, in_specs=P("dp"),
                                    out_specs=P("dp"),
                                    check_rep=False))(jnp.ones((8,)))
        rep = run_passes(cj, passes=["branch-collective-mismatch"],
                         mesh=_mesh4())
        assert rep.findings == []


class TestReshardingChurn:
    def test_positive_spec_flip(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh4()

        def f(x):
            y = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp")))
            return jax.lax.with_sharding_constraint(
                y * 1.0, NamedSharding(mesh, P(None)))

        cj = jax.make_jaxpr(jax.jit(f))(jnp.ones((64, 64)))
        rep = run_passes(cj, passes=["resharding-churn"], mesh=mesh,
                         large_threshold=1024)
        assert len(rep.warnings) == 1
        msg = rep.warnings[0].message
        assert "re-constrained" in msg and "all-gather" in msg

    def test_negative_same_spec_twice(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh4()

        def f(x):
            y = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp")))
            return jax.lax.with_sharding_constraint(
                y * 1.0, NamedSharding(mesh, P("dp")))

        cj = jax.make_jaxpr(jax.jit(f))(jnp.ones((64, 64)))
        rep = run_passes(cj, passes=["resharding-churn"], mesh=mesh,
                         large_threshold=1024)
        assert rep.findings == []

    def test_negative_small_tensor(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = _mesh4()

        def f(x):
            y = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp")))
            return jax.lax.with_sharding_constraint(
                y * 1.0, NamedSharding(mesh, P(None)))

        cj = jax.make_jaxpr(jax.jit(f))(jnp.ones((8, 8)))
        rep = run_passes(cj, passes=["resharding-churn"], mesh=mesh,
                         large_threshold=1024)
        assert rep.findings == []


# ---------------------------------------------------------------------------
# ISSUE 13: handoff schemas (planted drift + validation matrix)
# ---------------------------------------------------------------------------


class TestHandoffSchema:
    def _schema(self):
        return {
            "edge": "test_edge",
            "producer": "paddle_tpu/serving/disagg.py::PrefillWorker.prefill",
            "consumer": ("paddle_tpu/inference/serving.py::"
                         "ServingEngine.admit_prefilled"),
            "payload": {
                "kc": {"shape": ("L", 1, "T"), "dtype": "$cache",
                       "quantizable": True},
                "logits": {"shape": ("V",), "dtype": "float32"},
            },
        }

    def test_validate_good_payload_binds_dims(self):
        from paddle_tpu.analysis import handoff_schema as hs

        binds = hs.validate(self._schema(),
                            {"kc": np.zeros((2, 1, 8), np.float32),
                             "logits": np.zeros((16,), np.float32)})
        assert binds == {"L": 2, "T": 8, "V": 16}

    def test_validate_cross_leaf_consistency(self):
        from paddle_tpu.analysis import handoff_schema as hs

        sch = self._schema()
        sch["payload"]["vc"] = {"shape": ("L", 1, "T"),
                                "dtype": "float32"}
        with pytest.raises(hs.HandoffMismatch, match="'L'"):
            hs.validate(sch, {"kc": np.zeros((2, 1, 8), np.float32),
                              "vc": np.zeros((3, 1, 8), np.float32),
                              "logits": np.zeros((16,), np.float32)})

    def test_validate_quantized_pair(self):
        from paddle_tpu.analysis import handoff_schema as hs

        vals = np.zeros((2, 1, 8), np.int8)
        scales = np.zeros((2, 1, 1), np.float32)
        hs.validate(self._schema(),
                    {"kc": (vals, scales),
                     "logits": np.zeros((16,), np.float32)},
                    dtypes={"cache": "int8"})
        # scales must be f32
        with pytest.raises(hs.HandoffMismatch, match="scales"):
            hs.validate(self._schema(),
                        {"kc": (vals, scales.astype(np.float16)),
                         "logits": np.zeros((16,), np.float32)})
        # the VALUES dtype honors the declaration too: a producer built
        # with a different cache codec must fail, not corrupt the cache
        with pytest.raises(hs.HandoffMismatch, match=r"kc\.values"):
            hs.validate(self._schema(),
                        {"kc": (vals.astype(np.uint8), scales),
                         "logits": np.zeros((16,), np.float32)},
                        dtypes={"cache": "int8"})

    def test_validate_missing_leaf_and_wrong_rank(self):
        from paddle_tpu.analysis import handoff_schema as hs

        with pytest.raises(hs.HandoffMismatch, match="missing leaf"):
            hs.validate(self._schema(),
                        {"kc": np.zeros((2, 1, 8), np.float32)})
        with pytest.raises(hs.HandoffMismatch, match="rank"):
            hs.validate(self._schema(),
                        {"kc": np.zeros((2, 1), np.float32),
                         "logits": np.zeros((16,), np.float32)})

    def test_wildcard_trailing_dims(self):
        from paddle_tpu.analysis import handoff_schema as hs

        sch = {"edge": "e", "producer": "p", "consumer": "c",
               "payload": {"act": {"shape": ("mb", "..."),
                                   "dtype": "float32"}}}
        hs.validate(sch, {"act": np.zeros((4, 7, 9), np.float32)},
                    dims={"mb": 4})
        with pytest.raises(hs.HandoffMismatch, match="'mb'"):
            hs.validate(sch, {"act": np.zeros((5, 7, 9), np.float32)},
                        dims={"mb": 4})

    def test_planted_drift_detected(self):
        from paddle_tpu.analysis import handoff_schema as hs

        decl = self._schema()
        base = {"edges": {"test_edge": hs.fingerprint(decl)}}
        assert hs.check_baseline({"test_edge": decl}, base) == []

        drifted = dict(decl, payload={
            "kc": {"shape": ("L", 1, "T"), "dtype": "bfloat16",
                   "quantizable": True},
            "logits": {"shape": ("V",), "dtype": "float32"}})
        fs = hs.check_baseline({"test_edge": drifted}, base)
        assert len(fs) == 1 and fs[0].pass_name == "handoff-schema-drift"
        assert "kc" in fs[0].message and "bfloat16" in fs[0].message

    def test_unpinned_and_stale_edges(self):
        from paddle_tpu.analysis import handoff_schema as hs

        decl = self._schema()
        fs = hs.check_baseline({"test_edge": decl}, {"edges": {}})
        assert fs[0].pass_name == "handoff-schema-unpinned"
        fs = hs.check_baseline({}, {"edges": {"gone": {}}})
        assert fs[0].pass_name == "handoff-baseline-stale"

    def test_extraction_rejects_non_literal(self, tmp_path):
        from paddle_tpu.analysis import handoff_schema as hs

        mod = tmp_path / "decl.py"
        mod.write_text("X = 1\nHANDOFF_SCHEMA = make_schema()\n")
        with pytest.raises(ValueError, match="pure literal"):
            hs.extract_declaration("decl.py", "HANDOFF_SCHEMA",
                                   pkg_root=str(tmp_path))
        with pytest.raises(ValueError, match="no module-level literal"):
            hs.extract_declaration("decl.py", "OTHER_SCHEMA",
                                   pkg_root=str(tmp_path))

    def test_site_check_catches_unwired_consumer(self, tmp_path):
        from paddle_tpu.analysis import handoff_schema as hs

        mod = tmp_path / "m.py"
        mod.write_text("def produce():\n    pass\n")
        fs = hs._site_check("e", "consumer", "m.py::produce",
                            "HANDOFF_SCHEMA", True, str(tmp_path))
        assert fs and "never references" in fs[0].message
        fs = hs._site_check("e", "consumer", "m.py::missing_fn",
                            "HANDOFF_SCHEMA", False, str(tmp_path))
        assert fs and "not found" in fs[0].message


# ---------------------------------------------------------------------------
# ISSUE 13: pallas kernel budget audit (planted violations)
# ---------------------------------------------------------------------------


class TestPallasAudit:
    def test_planted_vmem_over_budget_names_buffers(self):
        from paddle_tpu.analysis import pallas_audit as pa

        entry = {"kernel": "planted.big", "matmul": False,
                 "grid": {"m": (4096, 2048)},
                 "buffers": [
                     {"name": "x", "block": (2048, 2048),
                      "dtype": "float32"},
                     {"name": "w", "block": (2048, 2048),
                      "dtype": "float32"}]}
        fs = [f for f in pa.audit_entry(entry)
              if f.pass_name == "kernel-vmem-over-budget"]
        assert len(fs) == 1
        msg = fs[0].message
        # per-buffer breakdown, double-buffering accounted
        assert "w=32768KiB" in msg and "x=32768KiB" in msg
        assert "double-buffered" in msg

    def test_planted_int8_accumulator(self):
        from paddle_tpu.analysis import pallas_audit as pa

        entry = {"kernel": "planted.int8", "matmul": True,
                 "in_dtype": "int8", "acc_dtype": "int8",
                 "grid": {}, "buffers": []}
        fs = pa.audit_entry(entry)
        assert any(f.pass_name == "kernel-low-precision-accumulator"
                   and "saturate" in f.message for f in fs)
        # f32 accumulator passes
        entry["acc_dtype"] = "float32"
        assert pa.audit_entry(entry) == []

    def test_planted_ragged_grid(self):
        from paddle_tpu.analysis import pallas_audit as pa

        entry = {"kernel": "planted.ragged", "matmul": False,
                 "grid": {"m": (100, 32)}, "buffers": []}
        fs = pa.audit_entry(entry)
        assert any(f.pass_name == "kernel-grid-indivisible"
                   and "ragged 4-wide tail" in f.message for f in fs)

    def test_planted_sublane_misalignment_warns(self):
        from paddle_tpu.analysis import pallas_audit as pa

        entry = {"kernel": "planted.sub", "matmul": False, "grid": {},
                 "buffers": [{"name": "x", "block": (12, 128),
                              "dtype": "bfloat16"}]}
        fs = pa.audit_entry(entry)
        assert any(f.severity == "warning" and "min tile" in f.message
                   for f in fs)

    def test_double_buffer_accounting(self):
        from paddle_tpu.analysis import pallas_audit as pa

        streamed = {"name": "x", "block": (128, 128), "dtype": "float32"}
        resident = dict(streamed, stream=False)
        assert pa.buffer_bytes(streamed) == 2 * pa.buffer_bytes(resident)

    def test_manifest_derives_from_live_block_tables(self):
        # the audit shapes go through the SAME pick_block the runtime
        # uses — a block-table change flows into the audit
        from paddle_tpu.analysis import pallas_audit as pa
        from paddle_tpu.ops import tpp

        entries = [e for e in pa.collect_manifest()
                   if e["kernel"].startswith("tpp.matmul")]
        assert entries
        for e in entries:
            m, bm = e["grid"]["m"]
            assert bm == tpp.pick_block(m)


# ---------------------------------------------------------------------------
# ISSUE 16: flow summary + wire bytes (the cost model's two data feeds)
# ---------------------------------------------------------------------------


class TestFlowSummary:
    def _psum_program(self, n=4):
        from jax.sharding import PartitionSpec as P

        mesh = jax.sharding.Mesh(np.array(jax.devices()[:n]), ("dp",))

        def g(x):
            return jax.lax.psum(x, "dp")

        return jax.make_jaxpr(_smap()(g, mesh=mesh, in_specs=P("dp"),
                                      out_specs=P(),
                                      check_rep=False))(jnp.ones((8,)))

    def test_reduce_bytes_ring_factored(self):
        from paddle_tpu.analysis.sharding_flow import flow_summary

        s = flow_summary(self._psum_program(n=4))
        # one psum over a (2,) f32 shard (8 elems / 4 devices): payload
        # 8 bytes x the 2(n-1)/n = 1.5 reduce ring factor
        assert s["collective_counts"] == {"reduce": 1, "exchange": 0,
                                          "permute": 0}
        assert s["collective_bytes"]["reduce"] == pytest.approx(12.0)
        assert s["collective_bytes_total"] == pytest.approx(12.0)

    def test_plain_program_has_no_collectives(self):
        from paddle_tpu.analysis.sharding_flow import flow_summary

        s = flow_summary(jax.make_jaxpr(lambda x: x * 2.0)(
            jnp.ones((8,))))
        assert s["collective_bytes_total"] == 0.0
        assert s["resharding_events"] == 0

    def test_sharding_summaries_cover_the_battery(self):
        from paddle_tpu.analysis.sharding_flow import sharding_summaries

        out = sharding_summaries(targets=["gpt_train"])
        assert set(out) == {"gpt_train"}
        s = out["gpt_train"]
        assert set(s) >= {"collective_bytes", "collective_counts",
                          "collective_bytes_total",
                          "resharding_churn_bytes", "resharding_events"}


class TestWireBytes:
    DIMS = {"mb": 2, "t": 16, "d": 64}

    def test_dense_activation_edge(self):
        from paddle_tpu.analysis.handoff_schema import wire_bytes

        assert wire_bytes("mpmd_activation", self.DIMS) == 2 * 16 * 64 * 4

    def test_compressed_matches_measured_ratio(self):
        # the 4 / (1 + 4/D) int8-row-codec wire ratio StageEdge measures
        from paddle_tpu.analysis.handoff_schema import wire_bytes

        dense = wire_bytes("mpmd_activation", self.DIMS)
        comp = wire_bytes("mpmd_activation", self.DIMS, compress=8)
        assert comp < dense
        assert dense / comp == pytest.approx(4.0 / (1.0 + 4.0 / 64))

    def test_grad_edge_never_compresses(self):
        # grad edge declares no quantizable leaves: compress is a no-op
        from paddle_tpu.analysis.handoff_schema import wire_bytes

        assert wire_bytes("mpmd_grad", self.DIMS, compress=8) == \
            wire_bytes("mpmd_grad", self.DIMS)

    def test_unbound_dim_raises(self):
        from paddle_tpu.analysis.handoff_schema import wire_bytes

        with pytest.raises(ValueError, match="unbound dim"):
            wire_bytes("mpmd_activation", {"mb": 2, "t": 16})

    def test_unknown_edge_and_bad_compress_raise(self):
        from paddle_tpu.analysis.handoff_schema import wire_bytes

        with pytest.raises(ValueError):
            wire_bytes("no_such_edge", {})
        with pytest.raises(ValueError, match="compress"):
            wire_bytes("mpmd_activation", self.DIMS, compress=4)


# ---------------------------------------------------------------------------
# ISSUE 16: plan verifier (planted bad plans -> the NAMED analyzer pass)
# ---------------------------------------------------------------------------


def _fake_profile(**kw):
    from paddle_tpu.analysis.cost_model import ModelProfile

    base = dict(name="fake", n_layers=2, hidden=64, seq=16, vocab=256,
                step_flops=1e9, step_bytes=1e8, param_bytes=1 << 19,
                opt_bytes=1 << 20, qar_eligible_bytes=1 << 18,
                supports_pipeline=True, supports_mp=True)
    base.update(kw)
    return ModelProfile(**base)


def _passes_of(errs):
    return sorted({e.pass_name for e in errs})


class TestPlanVerifier:
    def _verify(self, plan, profile=None, **kw):
        from paddle_tpu.analysis.plan_search import verify_plan

        errs, _ = verify_plan(plan, profile or _fake_profile(),
                              devices=8, trace_classes=False, **kw)
        return errs

    def test_mp_axis_larger_than_mesh_rejected_by_sharding_pass(self):
        # dp2 x mp8 wants 16 devices on an 8-device pool: the deployment
        # mesh can only give mp 4 — the EXISTING collective-axis-mismatch
        # pass rejects it, not a crash and not a planner-private check
        from paddle_tpu.analysis.cost_model import Plan

        errs = self._verify(Plan(dp=2, mp=8))
        assert _passes_of(errs) == ["collective-axis-mismatch"]
        assert "size 8" in errs[0].message and "4" in errs[0].message

    def test_vmem_busting_stage_rejected_by_pallas_pass(self):
        from paddle_tpu.analysis.cost_model import Plan

        errs = self._verify(Plan(pp=2, n_micro=2),
                            profile=_fake_profile(hidden=1 << 22))
        assert "kernel-vmem-over-budget" in _passes_of(errs)
        assert any("16 MiB" in e.message for e in errs)

    def test_grad_edge_compress_rejected_by_handoff_validator(self):
        # pipeline grad edges are declared dense; a plan that tries to
        # quantize one is caught by the schema validator, wrapped as
        # plan-handoff-mismatch with the validator's own message
        from paddle_tpu.analysis.cost_model import Plan

        errs = self._verify(Plan(pp=2, n_micro=2,
                                 compress_grad_edge=True))
        assert _passes_of(errs) == ["plan-handoff-mismatch"]
        assert "mpmd_grad" in errs[0].message

    def test_hbm_over_budget_rejected(self):
        from paddle_tpu.analysis.cost_model import CostModel, Plan

        errs = self._verify(Plan(dp=2), cm=CostModel(hbm_bytes=1 << 20))
        assert _passes_of(errs) == ["plan-hbm-over-budget"]

    def test_config_nonsense_rejected(self):
        from paddle_tpu.analysis.cost_model import Plan

        # dp=3 does not divide the global batch of 16
        errs = self._verify(Plan(dp=3))
        assert _passes_of(errs) == ["plan-invalid-config"]
        # quantized allreduce needs dp > 1
        errs = self._verify(Plan(dp=1, quantized_allreduce=True))
        assert _passes_of(errs) == ["plan-invalid-config"]

    def test_valid_plan_scores_finite_and_emits_runnable_config(self):
        from paddle_tpu.analysis.cost_model import CostModel, Plan
        from paddle_tpu.analysis.plan_search import emit

        prof = _fake_profile()
        plan = Plan(dp=2)
        assert self._verify(plan) == []
        score = CostModel().score(plan, prof)
        assert np.isfinite(score["total_s"]) and score["total_s"] > 0
        cfg = emit(plan, prof)
        assert cfg["kind"] == "spmd"
        assert cfg["mesh"] == {"shape": [2], "axes": ["dp"]}
        assert cfg["flags"] == {"quantized_allreduce": False}

    def test_pipeline_plan_emits_stage_graph_config(self):
        from paddle_tpu.analysis.cost_model import Plan
        from paddle_tpu.analysis.plan_search import emit

        cfg = emit(Plan(pp=2, n_micro=4, edge_compress=8),
                   _fake_profile())
        assert cfg["kind"] == "stage_graph"
        assert cfg["flags"] == {"mpmd": True}
        assert cfg["pipeline"]["n_micro"] == 4
        assert cfg["pipeline"]["stage_layers"] == [[0], [1]]
        assert cfg["pipeline"]["compress"] == 8


class TestCostModelMonotonicity:
    def test_more_dp_means_less_hbm_per_device(self):
        # fixed global batch: activations shrink with dp (strong scaling)
        from paddle_tpu.analysis.cost_model import CostModel, Plan

        cm, prof = CostModel(), _fake_profile()
        mems = [cm.score(Plan(dp=d), prof)["mem_bytes_per_device"]
                for d in (2, 4, 8)]
        assert mems[0] > mems[1] > mems[2]

    def test_edge_compress_means_fewer_wire_bytes(self):
        from paddle_tpu.analysis.cost_model import CostModel, Plan

        cm, prof = CostModel(), _fake_profile()
        dense, _ = cm.comm_terms(Plan(pp=2, n_micro=4), prof)
        comp, _ = cm.comm_terms(Plan(pp=2, n_micro=4, edge_compress=8),
                                prof)
        assert 0 < comp["edge_wire_bytes"] < dense["edge_wire_bytes"]

    def test_quantized_allreduce_means_fewer_sync_bytes(self):
        from paddle_tpu.analysis.cost_model import CostModel, Plan

        cm, prof = CostModel(), _fake_profile()
        dense, _ = cm.comm_terms(Plan(dp=8), prof)
        quant, _ = cm.comm_terms(Plan(dp=8, quantized_allreduce=True),
                                 prof)
        assert 0 < quant["dp_sync_bytes"] < dense["dp_sync_bytes"]
