"""Unit tests for the graph-analysis pass registry (paddle_tpu.analysis).

One positive + one negative case per builtin pass over minimal synthetic
jaxprs, registry contract tests (duplicate names rejected, severity
ordering stable), source-lint rule tests, the Program/Predictor analysis
hooks, and regression assertions for the real findings the passes
surfaced in paddle_tpu itself (int64 position arange; np.random sites).
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from paddle_tpu.analysis import (  # noqa: E402
    AnalysisReport,
    Finding,
    count_hlo_collectives,
    registered_passes,
    run_passes,
)
from paddle_tpu.analysis.registry import register_pass  # noqa: E402
from paddle_tpu.analysis.source_lint import lint_source  # noqa: E402


def _by_pass(report, name):
    return [f for f in report.findings if f.pass_name == name]


# ---------------------------------------------------------------------------
# registry contract
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_battery_size(self):
        # the issue's contract: >= 8 distinct registered jaxpr passes
        assert len(registered_passes()) >= 8
        assert len(set(registered_passes())) == len(registered_passes())

    def test_duplicate_name_rejected(self):
        with pytest.raises(ValueError, match="already registered"):
            @register_pass("host-sync")
            def clone(ctx):  # pragma: no cover
                return []

    def test_bad_severity_rejected(self):
        with pytest.raises(ValueError, match="severity"):
            register_pass("x-bad-severity", severity="fatal")
        with pytest.raises(ValueError, match="severity"):
            Finding("p", "catastrophic", "m")

    def test_unknown_pass_rejected(self):
        with pytest.raises(ValueError, match="unknown analysis pass"):
            run_passes(lambda x: x + 1, 1.0, passes=["no-such-pass"])

    def test_severity_ordering_stable(self):
        rep = AnalysisReport(name="t")
        rep.add(Finding("dead-code", "info", "i1"))
        rep.add(Finding("host-sync", "warning", "w1"))
        rep.add(Finding("prng-key-reuse", "error", "e1"))
        rep.add(Finding("host-sync", "error", "e2"))
        rep.sort()
        sevs = [f.severity for f in rep.findings]
        assert sevs == ["error", "error", "warning", "info"]
        # within a severity, registration order breaks the tie (host-sync
        # registered before prng-key-reuse)
        assert [f.pass_name for f in rep.findings[:2]] == [
            "host-sync", "prng-key-reuse"]
        # sorting again is a no-op (stable)
        again = [f.message for f in rep.sort().findings]
        assert again == ["e2", "e1", "w1", "i1"]

    def test_report_roundtrip(self):
        rep = run_passes(lambda x: x * 2.0, jnp.ones(3), name="t")
        d = rep.to_dict()
        assert d["name"] == "t"
        assert set(d["counts"]) == {"error", "warning", "info"}
        for f in d["findings"]:
            assert set(f) == {"pass", "severity", "message", "where"}

    def test_pass_subset_runs(self):
        rep = run_passes(lambda x: x + 1.0, jnp.ones(3),
                         passes=["host-sync"])
        assert rep.findings == []


# ---------------------------------------------------------------------------
# per-pass positive/negative cases
# ---------------------------------------------------------------------------


class TestHostSync:
    def test_positive_pure_callback(self):
        def f(x):
            return jax.pure_callback(
                lambda a: np.asarray(a) * 2, jax.ShapeDtypeStruct(
                    (3,), np.float32), x)

        rep = run_passes(f, jnp.ones(3), passes=["host-sync"])
        assert len(rep.errors) == 1
        assert "pure_callback" in rep.errors[0].message

    def test_positive_debug_callback_is_warning(self):
        def f(x):
            jax.debug.print("x={}", x)
            return x + 1

        rep = run_passes(f, jnp.ones(3), passes=["host-sync"])
        assert not rep.errors and len(rep.warnings) == 1

    def test_negative(self):
        rep = run_passes(lambda x: jnp.sin(x) + 1, jnp.ones(3),
                         passes=["host-sync"])
        assert rep.findings == []


class TestPrngKeyReuse:
    def test_positive_same_key_two_samplers(self):
        def f(k):
            return jax.random.uniform(k, (3,)) + jax.random.normal(k, (3,))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert len(rep.errors) == 1
        assert "consumed 2x" in rep.errors[0].message

    def test_positive_double_split(self):
        # split(k) twice yields IDENTICAL subkeys — reuse even though no
        # sampler touches k directly
        def f(k, x):
            k1, _ = jax.random.split(k)
            k2, _ = jax.random.split(k)
            return (jax.random.uniform(k1, (2,))
                    + jax.random.uniform(k2, (2,)) + x)

        rep = run_passes(f, jax.random.key(0), jnp.ones(2),
                         passes=["prng-key-reuse"])
        assert len(rep.errors) >= 1

    def test_negative_split_chain(self):
        def f(k):
            k1, k2 = jax.random.split(k)
            return jax.random.uniform(k1, (3,)) + jax.random.normal(
                k2, (3,))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert rep.findings == []

    def test_negative_distinct_slices_of_split(self):
        # the canonical dropout chain: keys[0] / keys[1] are different
        # slices of one split — aliases must not be conflated
        def f(k):
            keys = jax.random.split(k, 4)
            return (jax.random.uniform(keys[0], (2,))
                    + jax.random.uniform(keys[1], (2,))
                    + jax.random.uniform(keys[2], (2,)))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert rep.findings == []

    def test_negative_traced_index_selection(self):
        # keys[i] / keys[j] with TRACED indices: value-dependent selection
        # must stay conservative (distinct identities), never a
        # false-positive error on correct code
        def f(k, i, j):
            keys = jax.random.split(k, 4)
            return (jax.random.uniform(keys[i], (2,))
                    + jax.random.uniform(keys[j], (2,)))

        rep = run_passes(f, jax.random.key(0), jnp.int32(0), jnp.int32(1),
                         passes=["prng-key-reuse"])
        assert rep.findings == []

    def test_positive_same_slice_twice(self):
        def f(k):
            keys = jax.random.split(k, 4)
            return (jax.random.uniform(keys[0], (2,))
                    + jax.random.normal(keys[0], (2,)))

        rep = run_passes(f, jax.random.key(0), passes=["prng-key-reuse"])
        assert len(rep.errors) == 1


class TestPrngConstKey:
    def test_positive_baked_key(self):
        k = jax.random.key(7)   # closed over -> baked trace constant

        def f(x):
            return x + jax.random.uniform(k, (3,))

        rep = run_passes(f, jnp.ones(3), passes=["prng-const-key"])
        assert len(rep.warnings) == 1
        assert "baked" in rep.warnings[0].message

    def test_negative_threaded_key(self):
        def f(k, x):
            return x + jax.random.uniform(k, (3,))

        rep = run_passes(f, jax.random.key(0), jnp.ones(3),
                         passes=["prng-const-key"])
        assert rep.findings == []


class TestDtypePromotion:
    def test_positive_bf16_widening(self):
        def f(x):
            return x.astype(jnp.float32) * 2.0

        rep = run_passes(f, jnp.ones(3, jnp.bfloat16),
                         passes=["dtype-promotion"])
        assert len(rep.warnings) == 1
        assert "bfloat16->float32" in rep.warnings[0].message

    def test_negative_same_width(self):
        def f(x):
            return x.astype(jnp.int32) + 1

        rep = run_passes(f, jnp.ones(3, jnp.float32),
                         passes=["dtype-promotion"])
        assert rep.findings == []

    def test_aggregated_count(self):
        def f(x, y):
            return x.astype(jnp.float32) + y.astype(jnp.float32)

        rep = run_passes(f, jnp.ones(3, jnp.bfloat16),
                         jnp.ones(3, jnp.bfloat16),
                         passes=["dtype-promotion"])
        assert len(rep.warnings) == 1       # one finding per (src, dst)
        assert "x2" in rep.warnings[0].message


class TestDeadCode:
    def test_positive(self):
        def f(x):
            dead = jnp.sin(x) * 2.0  # noqa: F841 — deliberately unused
            return x + 1

        rep = run_passes(f, jnp.ones(3), passes=["dead-code"])
        assert len(rep.findings) == 1
        assert "sin" in rep.findings[0].message

    def test_negative(self):
        rep = run_passes(lambda x: jnp.sin(x) + 1, jnp.ones(3),
                         passes=["dead-code"])
        assert rep.findings == []


class TestRecompileHazard:
    def test_positive_scalar_const(self):
        c = jnp.float32(3.0)   # 0-d array closed over -> trace const

        def f(x):
            return x * c

        rep = run_passes(f, jnp.ones(3), passes=["recompile-hazard"])
        assert len(rep.findings) == 1
        assert "scalar" in rep.findings[0].message

    def test_positive_large_baked_array(self):
        w = jnp.ones((64, 64))

        def f(x):
            return x @ w

        rep = run_passes(f, jnp.ones((2, 64)), passes=["recompile-hazard"],
                         large_threshold=1024)
        assert len(rep.warnings) == 1
        assert "closed over" in rep.warnings[0].message

    def test_negative_args_only(self):
        rep = run_passes(lambda x, w: x @ w, jnp.ones((2, 4)),
                         jnp.ones((4, 4)), passes=["recompile-hazard"])
        assert rep.findings == []


class TestCollectiveCount:
    def test_positive_psum(self):
        closed = jax.make_jaxpr(lambda x: jax.lax.psum(x, "i"),
                                axis_env=[("i", 2)])(1.0)
        rep = run_passes(closed, passes=["collective-count"])
        assert len(rep.findings) == 1
        assert "all-reduce" in rep.findings[0].message

    def test_negative(self):
        rep = run_passes(lambda x: x + 1, jnp.ones(3),
                         passes=["collective-count"])
        assert rep.findings == []

    def test_hlo_counter_format(self):
        # the exact-count machinery the perf-budget gate shares
        hlo = ("%a = all-reduce(x), %b = all-gather-start(y), "
               "%c = reduce-scatter(z), %d = all-reduce(w)")
        got = count_hlo_collectives(hlo)
        assert got == {"all-reduce": 2, "all-gather": 1,
                       "reduce-scatter": 1}


class TestQuantizedCollectiveClassifier:
    """count_quantized_collectives: the int8 exchange/gather pair of a
    wire-compressed all-reduce (distributed/compress.py), classified by
    payload dtype so the perf-budget gate can pin exact counts."""

    @staticmethod
    def _pair(dtype):
        def f(x):
            q = x.astype(dtype).reshape(2, -1)
            ex = jax.lax.all_to_all(q, "i", split_axis=0, concat_axis=0)
            return jax.lax.all_gather(ex.reshape(-1)[:4], "i",
                                      tiled=True)

        return jax.make_jaxpr(f, axis_env=[("i", 2)])(jnp.ones(8))

    def test_positive_int8_pair(self):
        from paddle_tpu.analysis.collectives import \
            count_quantized_collectives

        got = count_quantized_collectives(self._pair(jnp.int8).jaxpr)
        assert got == {"quantized-reduce-scatter": 1,
                       "quantized-all-gather": 1}

    def test_negative_fp32_pair_not_classified(self):
        from paddle_tpu.analysis.collectives import \
            count_quantized_collectives

        got = count_quantized_collectives(self._pair(jnp.float32).jaxpr)
        assert got == {"quantized-reduce-scatter": 0,
                       "quantized-all-gather": 0}

    def test_negative_plain_model(self):
        from paddle_tpu.analysis.collectives import \
            count_quantized_collectives

        closed = jax.make_jaxpr(lambda x: x @ x)(jnp.ones((4, 4)))
        got = count_quantized_collectives(closed.jaxpr)
        assert sum(got.values()) == 0

    def test_pass_emits_classification(self):
        rep = run_passes(self._pair(jnp.int8),
                         passes=["collective-count"])
        msgs = [f.message for f in _by_pass(rep, "collective-count")]
        assert any("quantized reduce family" in m for m in msgs), msgs

    def test_pass_silent_without_quantized_ops(self):
        closed = jax.make_jaxpr(lambda x: jax.lax.psum(x, "i"),
                                axis_env=[("i", 2)])(1.0)
        rep = run_passes(closed, passes=["collective-count"])
        msgs = [f.message for f in _by_pass(rep, "collective-count")]
        assert msgs and not any("quantized" in m for m in msgs)


class TestUnshardedLargeTensor:
    def _mesh(self):
        return jax.sharding.Mesh(np.array(jax.devices()[:2]), ("dp",))

    def test_positive(self):
        def f(x, y):
            return (x @ y) * 2.0

        rep = run_passes(f, jnp.ones((32, 32)), jnp.ones((32, 32)),
                         passes=["unsharded-large-tensor"],
                         mesh=self._mesh(), large_threshold=512)
        assert len(rep.warnings) == 1
        assert "no sharding constraint" in rep.warnings[0].message

    def test_negative_no_mesh(self):
        def f(x, y):
            return (x @ y) * 2.0

        rep = run_passes(f, jnp.ones((32, 32)), jnp.ones((32, 32)),
                         passes=["unsharded-large-tensor"],
                         large_threshold=512)
        assert rep.findings == []


class TestDonationMiss:
    def test_positive_info_when_unknown(self):
        def f(state, x):
            return state + x, jnp.sum(x)

        rep = run_passes(f, jnp.ones((64, 64)), jnp.ones((64, 64)),
                         passes=["donation-miss"], large_threshold=1024)
        assert len(rep.findings) == 1
        assert rep.findings[0].severity == "info"

    def test_positive_warning_with_known_donation(self):
        def f(state, x):
            return state + x

        rep = run_passes(f, jnp.ones((64, 64)), jnp.ones((64, 64)),
                         passes=["donation-miss"], large_threshold=1024,
                         donated=set())
        assert [f.severity for f in rep.findings].count("warning") == 1

    def test_negative_donated(self):
        def f(state, x):
            return state + x

        rep = run_passes(f, jnp.ones((64, 64)), jnp.ones((64, 64)),
                         passes=["donation-miss"], large_threshold=1024,
                         donated={0, 1})
        assert rep.findings == []


# ---------------------------------------------------------------------------
# source-lint rules
# ---------------------------------------------------------------------------


class TestSourceLint:
    def test_np_random_positive(self):
        src = ("import numpy as np\n"
               "def op(x):\n"
               "    return x + np.random.randn(3)\n")
        fs = lint_source(src, "nn/functional/fake.py", traced=True)
        assert [f.pass_name for f in fs] == ["np-random-in-traced-code"]
        assert fs[0].severity == "error"
        assert fs[0].where == "nn/functional/fake.py:3"

    def test_np_random_init_exempt(self):
        src = ("import numpy as np\n"
               "class L:\n"
               "    def __init__(self):\n"
               "        self.w = np.random.randn(3)\n")
        assert lint_source(src, "nn/x.py", traced=True) == []

    def test_np_random_untraced_module_exempt(self):
        src = ("import numpy as np\n"
               "def sample(x):\n"
               "    return np.random.permutation(x)\n")
        assert lint_source(src, "io/sampler.py", traced=False) == []

    def test_suppression_comment(self):
        src = ("import numpy as np\n"
               "def op(x):\n"
               "    r = np.random.RandomState(0)  "
               "# lint: allow(np-random-in-traced-code)\n"
               "    return x\n")
        assert lint_source(src, "nn/x.py", traced=True) == []

    def test_time_in_traced_code(self):
        src = ("import time\n"
               "def fwd(x):\n"
               "    return x * time.time()\n")
        fs = lint_source(src, "models/x.py", traced=True)
        assert [f.pass_name for f in fs] == ["time-in-traced-code"]
        assert fs[0].severity == "warning"

    def test_mutable_default_positive(self):
        src = ("class MyBlock(nn.Layer):\n"
               "    def forward(self, x, hooks=[]):\n"
               "        return x\n")
        fs = lint_source(src, "nn/layer/fake.py", traced=True)
        assert [f.pass_name for f in fs] == ["mutable-default-arg"]
        assert fs[0].severity == "error"

    def test_mutable_default_non_layer_exempt(self):
        src = ("class Helper:\n"
               "    def run(self, x, hooks=[]):\n"
               "        return x\n")
        assert lint_source(src, "nn/layer/fake.py", traced=True) == []

    def test_private_model_import_in_serving_positive(self):
        # both module-level and function-level imports are caught
        src = ("from ..models.gpt import _decode_fns\n"
               "def build():\n"
               "    from ..models.gpt import _tp_wrap, GPTConfig\n")
        fs = lint_source(src, "inference/serving.py", traced=False)
        assert [f.pass_name for f in fs] == \
            ["private-model-import-in-serving"] * 2
        assert all(f.severity == "error" for f in fs)
        assert fs[0].where == "inference/serving.py:1"
        # the serving/ package is covered too
        fs = lint_source("from ..models.bert import _x\n",
                         "serving/router.py", traced=False)
        assert [f.pass_name for f in fs] == \
            ["private-model-import-in-serving"]

    def test_private_model_import_public_and_elsewhere_exempt(self):
        # public names are the supported surface
        assert lint_source("from ..models.gpt import GPTForCausalLM\n",
                           "inference/predictor.py", traced=False) == []
        # model modules may use their own privates (adapter registration)
        assert lint_source("from .gpt import _decode_fns\n",
                           "models/zoo.py", traced=True) == []
        # non-serving packages are out of scope for this rule
        assert lint_source("from ..models.gpt import _decode_fns\n",
                           "hapi/model.py", traced=False) == []

    def test_private_model_import_allow_marker(self):
        src = ("from ..models.gpt import _x  "
               "# lint: allow(private-model-import-in-serving)\n")
        assert lint_source(src, "inference/serving.py", traced=False) == []


class TestNonreducedClientOutput:
    """ISSUE 8 lint satellite: a client_map result must not escape a
    federated/ API without passing through a federated_* reduce (or carry
    an explicit `# lint: allow(client_output)` marker)."""

    def test_positive_assigned_then_returned(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return vals\n")
        fs = lint_source(src, "federated/primitives.py", traced=False)
        assert [f.pass_name for f in fs] == ["nonreduced-client-output"]
        assert fs[0].severity == "error"
        assert "federated_sum" in fs[0].message

    def test_positive_direct_return(self):
        src = ("def api(xs):\n"
               "    return client_map(fn, xs)\n")
        fs = lint_source(src, "federated/averaging.py", traced=False)
        assert [f.pass_name for f in fs] == ["nonreduced-client-output"]

    def test_positive_in_tuple_return(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    total = federated_sum(other(xs))\n"
               "    return total, vals\n")
        fs = lint_source(src, "federated/x.py", traced=False)
        assert [f.pass_name for f in fs] == ["nonreduced-client-output"]

    def test_negative_value_fed_through_reduce_expression(self):
        """A name consumed INSIDE a reduce's argument expression counts
        as reduced (the heuristic clears every name the reduce saw)."""
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return federated_sum(vals * 2)\n")
        assert lint_source(src, "federated/x.py", traced=False) == []

    def test_negative_reduced_before_return(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return federated_mean(vals)\n")
        assert lint_source(src, "federated/primitives.py",
                           traced=False) == []

    def test_negative_client_reduce_chokepoint(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    out = _coll.client_reduce(vals)\n"
               "    return out\n")
        assert lint_source(src, "federated/primitives.py",
                           traced=False) == []

    def test_negative_rebound_name(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    vals = federated_sum(vals)\n"
               "    return vals\n")
        assert lint_source(src, "federated/x.py", traced=False) == []

    def test_allow_marker_short_and_full(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return vals  # lint: allow(client_output)\n")
        assert lint_source(src, "federated/primitives.py",
                           traced=False) == []
        src2 = ("def api(xs):\n"
                "    vals = client_map(fn, xs)\n"
                "    return vals  # lint: allow(nonreduced-client-output)\n")
        assert lint_source(src2, "federated/primitives.py",
                           traced=False) == []

    def test_rule_scoped_to_federated_modules(self):
        src = ("def api(xs):\n"
               "    vals = client_map(fn, xs)\n"
               "    return vals\n")
        assert lint_source(src, "distributed/spmd.py", traced=False) == []
        assert lint_source(src, "nn/layer/common.py", traced=True) == []

    def test_repo_federated_package_is_clean(self):
        """paddle_tpu's own federated/ modules hold the bar the rule
        sets (any deliberate client-placed return carries the marker)."""
        import os

        import paddle_tpu.federated as fed

        root = os.path.dirname(os.path.abspath(fed.__file__))
        for fn in sorted(os.listdir(root)):
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn), encoding="utf-8") as f:
                src = f.read()
            fs = lint_source(src, f"federated/{fn}", traced=False)
            assert [f_ for f_ in fs
                    if f_.pass_name == "nonreduced-client-output"] == []


# ---------------------------------------------------------------------------
# analysis hooks: static Program and inference Predictor
# ---------------------------------------------------------------------------


class TestAnalysisHooks:
    def test_program_analysis_jaxpr(self):
        import paddle_tpu as paddle
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [None, 8], "float32")
                w = paddle.ones([8, 4])
                w.persistable = True
                y = paddle.nn.functional.relu(paddle.matmul(x, w))
            exe = static.Executor()
            exe.run(startup)
            exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                    fetch_list=[y])
            closed = main.analysis_jaxpr(
                feed={"x": np.ones((2, 8), np.float32)})
            assert closed.jaxpr.eqns, "expected a non-empty replay jaxpr"
            rep = run_passes(closed, name="static_program")
            assert rep.errors == []
        finally:
            paddle.disable_static()

    def test_program_analysis_jaxpr_train_form(self):
        # a program with an optimizer attached traces the TRAIN step —
        # the graph Executor.run actually executes for it (fwd + grads +
        # update), not the eval forward
        import paddle_tpu as paddle
        import paddle_tpu.static as static

        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data("x", [None, 4], "float32")
                w = paddle.ones([4, 1])
                w.persistable = True
                loss = paddle.mean(paddle.matmul(x, w))
                opt = paddle.optimizer.SGD(learning_rate=0.1)
                opt.minimize(loss)
            exe = static.Executor()
            exe.run(startup)
            eval_closed = main.clone(for_test=True).analysis_jaxpr(
                feed={"x": np.ones((2, 4), np.float32)})
            train_closed = main.analysis_jaxpr(
                feed={"x": np.ones((2, 4), np.float32)})
            # train step takes (params, opt_state, lr, feed) and computes
            # grads + the update — strictly more work than the eval form
            assert len(train_closed.jaxpr.eqns) > len(
                eval_closed.jaxpr.eqns)
            assert run_passes(train_closed, name="train_prog").errors == []
        finally:
            paddle.disable_static()

    def test_program_analysis_jaxpr_empty_program(self):
        import paddle_tpu.static as static

        with pytest.raises(ValueError, match="empty program"):
            static.Program().analysis_jaxpr()

    def test_predictor_analysis_jaxpr(self, tmp_path):
        import paddle_tpu as paddle
        from paddle_tpu import jit as pjit
        from paddle_tpu.inference.predictor import Config, create_predictor
        from paddle_tpu.jit import InputSpec

        m = paddle.nn.Linear(8, 4)
        path = str(tmp_path / "lin")
        pjit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        pred = create_predictor(Config(path))
        closed = pred.analysis_jaxpr(
            inputs=[np.ones((2, 8), np.float32)])
        assert closed.jaxpr.eqns
        assert run_passes(closed, name="predictor").errors == []

    def test_predictor_surplus_input_does_not_poison(self, tmp_path):
        # an accidental extra positional input fails ITS call (the layer
        # rejects the arity) but must not persist into later calls
        import paddle_tpu as paddle
        from paddle_tpu import jit as pjit
        from paddle_tpu.inference.predictor import Config, create_predictor
        from paddle_tpu.jit import InputSpec

        m = paddle.nn.Linear(8, 4)
        path = str(tmp_path / "lin")
        pjit.save(m, path, input_spec=[InputSpec([None, 8], "float32")])
        pred = create_predictor(Config(path))
        x = np.ones((2, 8), np.float32)
        with pytest.raises(TypeError):
            pred.run([x, np.ones((2, 8), np.float32)])
        assert pred.get_input_names() == ["input_0"]
        (out,) = pred.run([x])
        assert out.shape == (2, 4)


class TestToHostFlag:
    def test_error_mode_names_the_sync(self):
        import paddle_tpu as paddle

        paddle.set_flags({"trace_host_sync": "error"})
        try:
            def f(x):
                return paddle.to_tensor(x).numpy()

            with pytest.raises(RuntimeError, match="host sync"):
                jax.jit(f)(np.ones(3, np.float32))
        finally:
            paddle.set_flags({"trace_host_sync": "silent"})

    def test_warn_mode_warns_then_jax_raises(self):
        import paddle_tpu as paddle

        paddle.set_flags({"trace_host_sync": "warn"})
        try:
            def f(x):
                return paddle.to_tensor(x).item()

            with pytest.warns(UserWarning, match="host sync"):
                with pytest.raises(Exception):
                    jax.jit(f)(np.ones((), np.float32))
        finally:
            paddle.set_flags({"trace_host_sync": "silent"})

    def test_eager_unaffected(self):
        import paddle_tpu as paddle

        t = paddle.to_tensor([1.0, 2.0])
        assert t.numpy().tolist() == [1.0, 2.0]
        assert paddle.to_tensor(3.5).item() == 3.5


class TestStepLoopHostSync:
    """ISSUE 11: per-step host pulls inside the trainer/serving hot
    paths are errors unless they carry the allow-marker."""

    HOT = ("import numpy as np\n"
           "class SpmdTrainer:\n"
           "    def _train_step_impl(self, x):\n"
           "        return np.asarray(x)\n")

    def test_positive_np_asarray_in_hot_path(self):
        fs = lint_source(self.HOT,
                         os.path.join("distributed", "spmd.py"))
        assert [f.pass_name for f in fs] == ["step-loop-host-sync"]
        assert fs[0].severity == "error"

    def test_positive_item_and_block_until_ready(self):
        src = ("class ServingEngine:\n"
               "    def _step_inner(self, toks):\n"
               "        toks.block_until_ready()\n"
               "        return toks.item()\n")
        fs = lint_source(src, os.path.join("inference", "serving.py"))
        assert [f.pass_name for f in fs] == ["step-loop-host-sync"] * 2

    def test_positive_nested_closure_in_hot_path_counts(self):
        src = ("import numpy as np\n"
               "class SpmdTrainer:\n"
               "    def _drain_verdicts(self, vals):\n"
               "        def inner(v):\n"
               "            return np.asarray(v)\n"
               "        return [inner(v) for v in vals]\n")
        fs = lint_source(src, os.path.join("distributed", "spmd.py"))
        assert [f.pass_name for f in fs] == ["step-loop-host-sync"]

    def test_negative_allow_marker(self):
        src = ("import numpy as np\n"
               "class SpmdTrainer:\n"
               "    def _train_step_impl(self, x):\n"
               "        return np.asarray(x)"
               "  # lint: allow(step-loop-host-sync)\n")
        assert lint_source(src,
                           os.path.join("distributed", "spmd.py")) == []

    def test_negative_outside_hot_functions_and_files(self):
        src = ("import numpy as np\n"
               "class SpmdTrainer:\n"
               "    def stats(self, x):\n"
               "        return np.asarray(x)\n")
        assert lint_source(src,
                           os.path.join("distributed", "spmd.py")) == []
        assert lint_source(self.HOT, "nn/layer/fake.py",
                           traced=False) == []

    def test_repo_hot_paths_are_clean(self):
        # the ISSUE 11 satellite: after the deferred-guard fix, the
        # live spmd/serving hot paths carry ONLY allow-marked syncs
        from paddle_tpu.analysis.source_lint import lint_path

        fs = [f for f in lint_path()
              if f.pass_name == "step-loop-host-sync"]
        assert fs == [], [f.where for f in fs]

    def test_repo_allow_markers_still_present(self):
        # the deliberate syncs double as documentation: the windowed
        # drain fetch, the benchmark sync, the decode token fetch
        for rel, needle in (
                ("paddle_tpu/distributed/spmd.py", "device_get"),
                ("paddle_tpu/inference/serving.py", "np.asarray"),
        ):
            src = open(os.path.join(REPO, rel)).read()
            marked = [ln for ln in src.splitlines()
                      if "lint: allow(step-loop-host-sync)" in ln]
            assert any(needle in ln for ln in marked), (rel, needle)


# ---------------------------------------------------------------------------
# regression assertions for the real findings the passes surfaced
# ---------------------------------------------------------------------------


class TestRepoRegressions:
    def test_model_position_ids_are_int32(self):
        # the passes' first real catch: all four position embeddings
        # requested arange(dtype="int64"), truncated with a per-call
        # UserWarning (x64 off). Pinned here via the trace-warnings
        # channel: tracing each bundled model must be warning-clean.
        from paddle_tpu.analysis import analyze_model

        for name in ("gpt", "bert", "ernie"):
            rep = analyze_model(name)
            assert _by_pass(rep, "trace-warnings") == [], (
                f"{name}: tracing the forward raised python warnings "
                f"again: {[f.message for f in rep.findings]}")
            assert rep.errors == []

    def test_no_unsuppressed_np_random_in_traced_code(self):
        # the two deliberate eager-host samplers (nce, tdm_sampler) carry
        # `# lint: allow(...)` markers; anything NEW fails here
        from paddle_tpu.analysis.source_lint import lint_path

        fs = [f for f in lint_path()
              if f.pass_name == "np-random-in-traced-code"]
        assert fs == [], [f.where for f in fs]

    def test_allow_markers_still_present(self):
        # the suppressions double as documentation — removing the comment
        # (or the guard it documents) must trip the gate, not pass silently
        for rel in ("paddle_tpu/nn/functional/extension.py",
                    "paddle_tpu/nn/functional/loss.py"):
            src = open(os.path.join(REPO, rel)).read()
            assert "lint: allow(np-random-in-traced-code)" in src, rel
