"""Runtime telemetry layer (paddle_tpu.monitor): registry contract,
exporter schema round-trip, disabled-mode no-op, and the instrumented
hot paths (Executor, trainer, Tensor._to_host, collectives, checkpoint
I/O, serving engine) actually moving their counters.

Reference analog: platform/monitor.h StatRegistry + STAT_ADD and the
profiler.cc RecordEvent layer — ISSUE 2's acceptance criteria live here:
a gpt train step and a ServingEngine decode loop must each produce a
non-empty snapshot with compile-cache + step-latency (+ TTFT/inter-token
for serving) exported identically via JSON and Prometheus text.
"""
import json
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import monitor
from paddle_tpu.monitor.registry import (LABEL_CARDINALITY_CAP,
                                         OVERFLOW_LABEL, StatRegistry)


@pytest.fixture(autouse=True)
def _fresh_registry():
    monitor.enable()
    monitor.reset()
    yield
    monitor.enable()


class TestRegistryContract:
    def test_counter_gauge_histogram_basics(self):
        r = StatRegistry()
        c = r.counter("c_total")
        c.inc()
        c.inc(2.5)
        assert c.value == 3.5
        g = r.gauge("g")
        g.set(7)
        g.dec(2)
        g.inc(1)
        assert g.value == 6.0
        h = r.histogram("h_ms", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(5.0)
        h.observe(50.0)
        assert h.count == 3
        assert h.sum == pytest.approx(55.5)

    def test_get_or_create_returns_same_metric(self):
        r = StatRegistry()
        assert r.counter("x") is r.counter("x")

    def test_kind_and_label_conflicts_raise(self):
        r = StatRegistry()
        r.counter("x")
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("x")
        r.counter("y", labelnames=("a",))
        with pytest.raises(ValueError, match="labels"):
            r.counter("y", labelnames=("b",))

    def test_histogram_bucket_conflict_raises(self):
        r = StatRegistry()
        h = r.histogram("h", buckets=(1.0, 10.0))
        assert r.histogram("h", buckets=(10.0, 1.0)) is h  # order-insensitive
        with pytest.raises(ValueError, match="buckets"):
            r.histogram("h", buckets=(100.0, 200.0))

    def test_counter_cannot_decrease(self):
        r = StatRegistry()
        with pytest.raises(ValueError, match="cannot decrease"):
            r.counter("x").inc(-1)

    def test_wrong_method_for_kind(self):
        r = StatRegistry()
        with pytest.raises(TypeError):
            r.counter("x").observe(1)
        with pytest.raises(TypeError):
            r.histogram("h").set(1)
        with pytest.raises(TypeError):
            r.counter("x").dec()

    def test_labels_validation(self):
        r = StatRegistry()
        c = r.counter("x", labelnames=("op",))
        with pytest.raises(ValueError, match="declares labels"):
            c.inc()  # labeled metric needs .labels(...)
        with pytest.raises(ValueError, match="takes labels"):
            c.labels(other="y")
        c.labels(op="a").inc(2)
        c.labels(op="b").inc(3)
        vals = {s.labels["op"]: s.value for s in c.series()}
        assert vals == {"a": 2.0, "b": 3.0}

    def test_thread_safety(self):
        r = StatRegistry()
        c = r.counter("t_total")
        h = r.histogram("t_ms", buckets=(10.0,))
        n, threads = 2000, []

        def work():
            for _ in range(n):
                c.inc()
                h.observe(1.0)

        for _ in range(4):
            threads.append(threading.Thread(target=work))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == 4 * n
        assert h.count == 4 * n

    def test_reset_keeps_metrics_registered(self):
        r = StatRegistry()
        c = r.counter("x")
        lc = r.counter("y", labelnames=("k",))
        c.inc(5)
        lc.labels(k="v").inc(2)
        r.reset()
        assert r.get("x") is c
        assert c.value == 0.0
        assert lc.series() == []   # labeled children dropped
        c.inc()                    # cached handles still work
        lc.labels(k="v").inc()
        assert c.value == 1.0


class TestHistogramBuckets:
    def test_le_is_inclusive_and_cumulative(self):
        r = StatRegistry()
        h = r.histogram("h", buckets=(1.0, 5.0, 10.0))
        for v in (0.5, 1.0, 3.0, 10.0, 99.0):
            h.observe(v)
        (series,) = h.series()
        d = series.to_dict()
        # cumulative: <=1 -> 2 (0.5, 1.0 inclusive), <=5 -> 3, <=10 -> 4
        assert d["buckets"] == [[1.0, 2], [5.0, 3], [10.0, 4], ["+Inf", 5]]
        assert d["count"] == 5
        assert d["sum"] == pytest.approx(113.5)

    def test_default_buckets_sorted(self):
        assert list(monitor.DEFAULT_BUCKETS) == \
            sorted(monitor.DEFAULT_BUCKETS)


class TestLabelCardinalityCap:
    def test_overflow_series(self):
        r = StatRegistry()
        c = r.counter("x", labelnames=("sig",))
        for i in range(LABEL_CARDINALITY_CAP + 40):
            c.labels(sig=f"s{i}").inc()
        series = c.series()
        assert len(series) <= LABEL_CARDINALITY_CAP + 1
        overflow = [s for s in series
                    if s.labels["sig"] == OVERFLOW_LABEL]
        assert len(overflow) == 1
        # nothing lost: every inc landed somewhere
        assert sum(s.value for s in series) == LABEL_CARDINALITY_CAP + 40


class TestExporters:
    def _build(self, r):
        r.counter("req_total", "reqs", labelnames=("op",)) \
            .labels(op="all-reduce").inc(3)
        r.gauge("occ").set(2)
        h = r.histogram("lat_ms", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(20.0)

    def test_prometheus_round_trip(self):
        r = StatRegistry()
        self._build(r)
        snap = r.snapshot()
        text = monitor.to_prometheus(snap)
        parsed = monitor.parse_prometheus(text)
        assert parsed[("req_total", frozenset({("op", "all-reduce")}))] == 3
        assert parsed[("occ", frozenset())] == 2
        assert parsed[("lat_ms_bucket", frozenset({("le", "1")}))] == 1
        assert parsed[("lat_ms_bucket", frozenset({("le", "+Inf")}))] == 2
        assert parsed[("lat_ms_sum", frozenset())] == pytest.approx(20.5)
        assert parsed[("lat_ms_count", frozenset())] == 2

    def test_json_and_prometheus_share_one_snapshot(self):
        """Identical export: both wire forms are pure functions of ONE
        snapshot dict — counter/gauge values and histogram count/sum must
        agree sample for sample."""
        r = StatRegistry()
        self._build(r)
        snap = r.snapshot()
        via_json = json.loads(monitor.to_json(snap))
        parsed = monitor.parse_prometheus(monitor.to_prometheus(snap))
        for m in via_json["metrics"]:
            for s in m["series"]:
                key = frozenset(s["labels"].items())
                if m["type"] in ("counter", "gauge"):
                    assert parsed[(m["name"], key)] == s["value"]
                else:
                    assert parsed[(m["name"] + "_count", key)] == s["count"]
                    assert parsed[(m["name"] + "_sum", key)] == \
                        pytest.approx(s["sum"])
                    from paddle_tpu.monitor.exporters import _num

                    for le, cum in s["buckets"]:
                        le_s = "+Inf" if le == "+Inf" else _num(le)
                        assert parsed[(m["name"] + "_bucket",
                                       key | {("le", le_s)})] == cum

    def test_round_trip_escaped_label_values(self):
        """Backslash-then-n, quotes, and newlines in label VALUES must
        survive to_prometheus -> parse_prometheus exactly (single-pass
        unescape; sequential replaces decode 'backslash n' as newline)."""
        r = StatRegistry()
        c = r.counter("esc_total", labelnames=("v",))
        tricky = ["a\\nb", 'say "hi"', "line1\nline2", "back\\slash", "x,y"]
        for i, v in enumerate(tricky):
            c.labels(v=v).inc(i + 1)
        parsed = monitor.parse_prometheus(
            monitor.to_prometheus(r.snapshot()))
        for i, v in enumerate(tricky):
            assert parsed[("esc_total", frozenset({("v", v)}))] == i + 1

    def test_flatten(self):
        r = StatRegistry()
        self._build(r)
        flat = monitor.flatten(r.snapshot())
        assert flat["req_total{op=all-reduce}"] == 3.0
        assert flat["occ"] == 2.0
        assert flat["lat_ms"]["count"] == 2

    def test_jsonl_event_log(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        old = paddle.get_flags("FLAGS_monitor_log_path")
        paddle.set_flags({"monitor_log_path": path})
        try:
            rec = monitor.log_event("bench_phase", phase="headline",
                                    status="start")
            assert rec["event"] == "bench_phase"
            r = StatRegistry()
            r.counter("x").inc()
            monitor.log_snapshot(r.snapshot())
            lines = [json.loads(ln) for ln in
                     open(path).read().splitlines()]
            assert lines[0]["phase"] == "headline"
            assert lines[1]["event"] == "snapshot"
            assert lines[1]["snapshot"]["metrics"][0]["name"] == "x"
        finally:
            paddle.set_flags({"monitor_log_path":
                              old.get("FLAGS_monitor_log_path", "")})

    def test_event_log_disabled_without_path(self):
        paddle.set_flags({"monitor_log_path": ""})
        assert monitor.log_event("x") is None


class TestDisabledMode:
    def test_disabled_records_nothing(self):
        r = StatRegistry()
        c = r.counter("x")
        h = r.histogram("h")
        g = r.gauge("g")
        r.disable()
        c.inc()
        h.observe(1.0)
        g.set(5)
        assert c.value == 0.0
        assert h.count == 0
        assert g.value == 0.0
        r.enable()
        c.inc()
        assert c.value == 1.0

    def test_default_registry_toggle(self):
        c = monitor.counter("toggle_probe_total")
        monitor.disable()
        c.inc()
        assert c.value == 0.0
        monitor.enable()
        c.inc()
        assert c.value == 1.0

    def test_timed_skips_clock_when_disabled(self):
        h = monitor.histogram("timed_probe_ms")
        with monitor.timed(h):
            pass
        assert h.count == 1
        monitor.disable()
        with monitor.timed(h):
            pass
        monitor.enable()
        assert h.count == 1


class TestStatMacros:
    def test_stat_add_sub_reset(self):
        monitor.STAT_ADD("STAT_gpu0_mem", 100)
        monitor.STAT_ADD("STAT_gpu0_mem", 20)
        monitor.STAT_SUB("STAT_gpu0_mem", 50)
        assert monitor.gauge("STAT_gpu0_mem").value == 70
        monitor.STAT_RESET("STAT_gpu0_mem")
        assert monitor.gauge("STAT_gpu0_mem").value == 0


class TestInstrumentedHotPaths:
    def test_host_sync_counter_moves(self):
        c = monitor.counter("host_sync_total")
        before = c.value
        t = paddle.to_tensor([1.0, 2.0])
        t.numpy()
        t.item(0)
        t.tolist()
        assert c.value == before + 3

    def test_collective_count_and_bytes(self):
        import paddle_tpu.distributed as dist

        t = paddle.to_tensor(np.ones((4, 4), np.float32))
        dist.all_reduce(t)
        calls = monitor.counter("collective_calls_total",
                                labelnames=("op",))
        byts = monitor.counter("collective_bytes_total",
                               labelnames=("op",))
        assert calls.labels(op="all-reduce").value == 1
        assert byts.labels(op="all-reduce").value == 64.0

    def test_checkpoint_counters(self, tmp_path):
        p = str(tmp_path / "ck.pdparams")
        paddle.save({"w": paddle.to_tensor([1.0, 2.0])}, p)
        paddle.load(p)
        c = monitor.counter("checkpoint_total", labelnames=("op",))
        h = monitor.histogram("checkpoint_ms", labelnames=("op",))
        b = monitor.counter("checkpoint_bytes_total", labelnames=("op",))
        assert c.labels(op="save").value == 1
        assert c.labels(op="load").value == 1
        assert h.labels(op="save").count == 1
        assert b.labels(op="load").value > 0


class TestProfilerJaxTraceFix:
    def test_stop_from_another_thread_stops_the_trace(self, monkeypatch,
                                                      tmp_path):
        """The satellite fix: the jax device-trace flag is PROCESS state —
        stop_profiler from a different thread than the starter must stop
        the trace (it used to silently leak it via threading.local)."""
        from paddle_tpu import profiler as prof

        calls = []
        monkeypatch.setattr("jax.profiler.start_trace",
                            lambda d: calls.append(("start", d)))
        monkeypatch.setattr("jax.profiler.stop_trace",
                            lambda: calls.append(("stop",)))
        prof.start_profiler(log_dir=str(tmp_path))
        assert calls == [("start", str(tmp_path))]
        t = threading.Thread(target=prof.stop_profiler)
        t.start()
        t.join()
        assert calls[-1] == ("stop",)
        # and the flag is cleared: a second stop must not double-stop
        prof.stop_profiler()
        assert calls.count(("stop",)) == 1


def _tiny_static_program():
    import paddle_tpu.static as st

    main, startup = st.Program(), st.Program()
    st.enable_static()
    try:
        with st.program_guard(main, startup):
            x = st.data("x", [None, 4])
            w = paddle.create_parameter([4, 4])
            y = paddle.matmul(x, w)
    finally:
        st.disable_static()
    return main, startup, y


class TestExecutorInstrumentation:
    def test_cache_hit_miss_and_step_latency(self):
        import paddle_tpu.static as st

        main, startup, y = _tiny_static_program()
        exe = st.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        cache = monitor.counter("compile_cache_total",
                                labelnames=("site", "event", "sig",
                                            "source"))
        steps = monitor.histogram("step_latency_ms", labelnames=("site",))
        sig = "x:float32[2,4]"
        before = steps.labels(site="executor").count
        exe.run(main, feed=feed, fetch_list=[y])
        exe.run(main, feed=feed, fetch_list=[y])
        assert cache.labels(site="executor", event="miss",
                            sig=sig, source="fresh").value == 1
        assert cache.labels(site="executor", event="hit",
                            sig=sig, source="memory").value == 1
        assert steps.labels(site="executor").count == before + 2
        assert monitor.counter(
            "compile_total", labelnames=("site",)).labels(
            site="executor").value == 1
        # a NEW feed signature is a new cache entry -> a second miss
        exe.run(main, feed={"x": np.ones((3, 4), np.float32)},
                fetch_list=[y])
        assert cache.labels(site="executor", event="miss",
                            sig="x:float32[3,4]", source="fresh").value == 1

    def test_flags_benchmark_counts_syncs(self):
        import paddle_tpu.static as st

        main, startup, y = _tiny_static_program()
        exe = st.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        sync = monitor.counter("benchmark_sync_total",
                               labelnames=("site",))
        before = sync.labels(site="executor").value
        exe.run(main, feed=feed, fetch_list=[y])
        assert sync.labels(site="executor").value == before  # flag off
        paddle.set_flags({"benchmark": True})
        try:
            exe.run(main, feed=feed, fetch_list=[y])
        finally:
            paddle.set_flags({"benchmark": False})
        assert sync.labels(site="executor").value == before + 1

    def test_disabled_monitor_records_nothing_on_run(self):
        import paddle_tpu.static as st

        main, startup, y = _tiny_static_program()
        exe = st.Executor()
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        monitor.disable()
        try:
            exe.run(main, feed=feed, fetch_list=[y])
        finally:
            monitor.enable()
        steps = monitor.histogram("step_latency_ms", labelnames=("site",))
        assert steps.labels(site="executor").count == 0


class TestMetricsDumpTool:
    def _load(self):
        import importlib.util
        import os
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "metrics_dump", os.path.join(repo, "tools", "metrics_dump.py"))
        mod = importlib.util.module_from_spec(spec)
        sys.modules.pop("metrics_dump", None)
        spec.loader.exec_module(mod)
        return mod

    def test_report_shares_graph_lint_schema(self):
        """The CI contract: metrics_dump --json reads through the same
        loader as graph_lint/op_coverage (tool/passes/targets/totals;
        targets carry name/counts/findings)."""
        md = self._load()
        rep = md.build_report(["serving"])
        assert set(rep) >= {"tool", "passes", "targets", "totals"}
        assert rep["tool"] == "metrics_dump"
        for t in rep["targets"].values():
            assert set(t) >= {"name", "counts", "findings"}
            assert set(t["counts"]) == {"error", "warning", "info"}
        assert rep["totals"]["error"] == 0, rep["targets"]["serving"][
            "findings"]
        # the serving snapshot carries the acceptance histograms
        fams = {m["name"] for m in
                rep["targets"]["serving"]["snapshot"]["metrics"]
                if m["series"]}
        assert {"serving_ttft_ms", "serving_inter_token_ms"} <= fams


class TestAcceptanceEndToEnd:
    """ISSUE 2 acceptance: one gpt train step and one serving decode loop
    each produce a non-empty snapshot with the required families, exported
    identically via JSON and Prometheus text."""

    def _roundtrip_identical(self, snap):
        parsed = monitor.parse_prometheus(monitor.to_prometheus(snap))
        via_json = json.loads(monitor.to_json(snap))
        for m in via_json["metrics"]:
            for s in m["series"]:
                key = frozenset(s["labels"].items())
                if m["type"] in ("counter", "gauge"):
                    assert parsed[(m["name"].replace("-", "_"), key)] == \
                        s["value"]
                else:
                    assert parsed[(m["name"] + "_count", key)] == s["count"]

    def test_gpt_train_step_snapshot(self):
        md = TestMetricsDumpTool()._load()
        monitor.reset()
        md.run_train_step("gpt")
        snap = monitor.snapshot()
        fams = {m["name"] for m in snap["metrics"] if m["series"]}
        assert {"compile_cache_total", "compile_total",
                "step_latency_ms"} <= fams
        self._roundtrip_identical(snap)

    def test_serving_decode_loop_snapshot(self):
        md = TestMetricsDumpTool()._load()
        monitor.reset()
        stats = md.run_serving_loop()
        snap = monitor.snapshot()
        fams = {m["name"] for m in snap["metrics"] if m["series"]}
        assert {"serving_ttft_ms", "serving_inter_token_ms",
                "serving_queue_wait_ms", "serving_tokens_total"} <= fams
        self._roundtrip_identical(snap)
        assert stats["tokens_generated"] > 0
        assert stats["ttft_ms"]["count"] == 2
