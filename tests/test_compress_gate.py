"""Tier-1 gate for the bandwidth-frugal dp stack (ISSUE 10): with
FLAGS_quantized_allreduce and FLAGS_shard_weight_update both unset, the
trainer is EXACTLY the pre-PR trainer — paddle_tpu.distributed.compress
is never imported (subprocess pin), params are byte-identical whether or
not the compressed path was ever exercised in-process, no
collective_bytes_saved_total / quantize_error_norm series or
collective/quantized span appears, one executable serves the whole run
(zero recompile drift), and the per-step flag checks cost the same
one-lookup bar as every other disabled fast path. Plus: the
tools/metrics_dump.py --quantized, tools/parity_check.py target, and
tools/chaos_check.py quantized_nonfinite exit-code contracts."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor, trace
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: metric families this PR introduced — with the flags unset NONE of
#: them may grow a series on the trainer path
COMPRESS_FAMILIES = ("collective_bytes_saved_total", "quantize_error_norm")

_PLAIN_TRAINER = (
    "import jax; jax.config.update('jax_platforms', 'cpu')\n"
    "import hashlib\n"
    "import numpy as np\n"
    "import paddle_tpu as paddle\n"
    "from paddle_tpu import nn\n"
    "from paddle_tpu.distributed.mesh import build_mesh\n"
    "from paddle_tpu.distributed.spmd import SpmdTrainer\n"
    "def run_plain():\n"
    "    paddle.seed(0)\n"
    "    net = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 4))\n"
    "    opt = paddle.optimizer.AdamW(learning_rate=1e-3,\n"
    "        parameters=net.parameters())\n"
    "    mesh = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
    "    tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)\n"
    "    x = paddle.to_tensor(np.ones((4, 8), np.float32))\n"
    "    y = paddle.to_tensor(np.ones((4, 4), np.float32))\n"
    "    for _ in range(3):\n"
    "        tr.train_step(x, y)\n"
    "    h = hashlib.sha256()\n"
    "    for k in sorted(tr.params):\n"
    "        h.update(np.ascontiguousarray(\n"
    "            np.asarray(tr.params[k])).tobytes())\n"
    "    return h.hexdigest()\n")


def _run(code):
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


class TestInertByDefault:
    def test_plain_subprocess_never_imports_compress_and_pins_params(
            self):
        """The structural zero-overhead pin, in one subprocess: a plain
        trainer run (a) never imports distributed.compress, and (b)
        produces byte-identical params before vs after a quantized +
        update-sharded trainer ran in the same process — the disarmed
        step is the pre-PR step, unpolluted by the armed path."""
        _run(
            _PLAIN_TRAINER +
            "d1 = run_plain()\n"
            "import sys\n"
            "assert 'paddle_tpu.distributed.compress' not in \\\n"
            "    sys.modules, 'compress imported on the plain path'\n"
            "paddle.set_flags({'quantized_allreduce': True,\n"
            "    'quantized_allreduce_min_size': 1,\n"
            "    'shard_weight_update': True})\n"
            "paddle.seed(1)\n"
            "net2 = nn.Linear(4, 2)\n"
            "opt2 = paddle.optimizer.SGD(learning_rate=0.1,\n"
            "    parameters=net2.parameters())\n"
            "mesh2 = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
            "tr2 = SpmdTrainer(net2, opt2, loss_fn=nn.MSELoss(),\n"
            "                  mesh=mesh2)\n"
            "tr2.train_step(np.ones((2, 4), np.float32),\n"
            "               np.zeros((2, 2), np.float32))\n"
            "assert tr2.quantize_error() is not None\n"
            "assert 'paddle_tpu.distributed.compress' in sys.modules\n"
            "paddle.set_flags({'quantized_allreduce': False,\n"
            "                  'shard_weight_update': False})\n"
            "d2 = run_plain()\n"
            "assert d1 == d2, ('flag-unset trainer params drifted after '\n"
            "    'the compressed path was exercised in-process')\n"
            "print('OK')\n")

    def test_flag_unset_zero_series_spans_and_recompiles(self):
        """In-process: a flag-unset trainer run grows no compress-PR
        series, emits no collective/quantized span even with tracing on,
        and one executable serves every step (no exec-key churn)."""
        from paddle_tpu import nn

        monitor.reset()
        trace.clear()
        trace.enable()
        try:
            paddle.seed(0)
            net = nn.Linear(8, 4)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
            tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
            for _ in range(3):
                tr.train_step(np.ones((4, 8), np.float32),
                              np.zeros((4, 4), np.float32))
        finally:
            trace.disable()
        reg = monitor.default_registry()
        for family in COMPRESS_FAMILIES:
            metric = reg.get(family)
            assert metric is None or all(
                (s.count if hasattr(s, "count") and s.kind == "histogram"
                 else s.value) == 0
                for s in metric.series()), family
        assert "collective/quantized" not in {s.name
                                              for s in trace.spans()}
        assert len(tr._compiled_store) == 1
        key = next(iter(tr._compiled_store))
        assert key[-2:] == (False, False)   # the two new exec-key legs
        assert tr.stats()["quantize_error_norm"] is None
        assert "__qar_residual__" not in tr.opt_state

    def test_disarmed_flag_checks_under_5us(self):
        """The flag-unset per-step additions are two get_flag lookups
        (_compress_active / _shard_update_active) — bounded at the same
        bar as every other disabled fast path."""
        from paddle_tpu import nn

        paddle.seed(0)
        net = nn.Linear(4, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            tr._compress_active()
            tr._shard_update_active()
        per_call_us = (time.perf_counter() - t0) / (2 * n) * 1e6
        assert per_call_us < 5.0, (
            f"disarmed compress flag check costs {per_call_us:.2f}us")

    def test_flags_defined_and_read_at_ctor(self):
        assert flags.get_flag("quantized_allreduce") is False
        assert flags.get_flag("shard_weight_update") is False
        assert flags.get_flag("quantized_allreduce_bits") == 8
        assert flags.get_flag("quantized_allreduce_min_size") == 1024

    def test_chaos_pass_registered(self):
        spec = importlib.util.spec_from_file_location(
            "chaos_check", os.path.join(REPO, "tools", "chaos_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert "quantized_nonfinite" in mod.PASSES


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestCompressToolGate:
    def test_metrics_dump_quantized_missing_metrics_exits_1(
            self, capsys, monkeypatch):
        md = _load_tool("metrics_dump")
        monkeypatch.setattr(md, "run_quantized_loop", lambda **kw: None)
        rc = md.main(["--quantized", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        msgs = [f["message"]
                for f in report["targets"]["quantized"]["findings"]
                if f["pass"] == "metrics-present"]
        assert any("collective_bytes_saved_total" in m for m in msgs)
        assert any("op=quantized_all_reduce" in m for m in msgs)

    @pytest.mark.slow
    def test_metrics_dump_quantized_green_subprocess(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "metrics_dump.py"),
             "--quantized", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]

    @pytest.mark.slow
    def test_parity_shard_weight_update_exact_exits_0(self, capsys):
        """The acceptance-criterion pin: the update-sharding A/B is
        verified EXACT (zero tolerance, zero divergence)."""
        pc = _load_tool("parity_check")
        rc = pc.main(["--ab", "shard_weight_update", "--steps", "2",
                      "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["totals"]["error"] == 0
        assert report["targets"]["shard_weight_update"]["report"][
            "max_abs_loss_diff"] == 0.0

    @pytest.mark.slow
    def test_parity_quantized_with_negative_control(self, capsys):
        """One CI lane, both directions: the quantized target passes its
        declared band AND its lr-perturbed twin diverges (exit 1) —
        the band is a gate, not a rubber stamp."""
        pc = _load_tool("parity_check")
        rc = pc.main(["--ab", "quantized_allreduce", "--perturb-lr",
                      "8", "--steps", "2", "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        targets = report["targets"]
        assert targets["quantized_allreduce"]["counts"]["error"] == 0
        ctrl = targets["quantized_allreduce+perturb_lr"]
        assert ctrl["counts"]["error"] == 1
        assert ctrl["report"]["diverged"]

    @pytest.mark.slow
    def test_chaos_quantized_nonfinite_green(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "chaos_check.py"),
             "--only", "quantized_nonfinite", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
        report = json.loads(out.stdout)
        assert report["totals"]["error"] == 0
