"""paddle.static.nn full-surface tests (reference python/paddle/static/nn/
__init__.py's 22-name __all__): every name exists and executes; control flow
(cond/case/switch_case/while_loop) checks both host and traced dispatch."""
import numpy as np
import pytest

import paddle_tpu as paddle

S = paddle.static.nn

REFERENCE_ALL = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "case",
    "cond", "conv2d", "conv2d_transpose", "conv3d", "conv3d_transpose",
    "create_parameter", "crf_decoding", "data_norm", "deform_conv2d",
    "group_norm", "instance_norm", "layer_norm", "multi_box_head", "nce",
    "prelu", "py_func", "row_conv", "spectral_norm", "switch_case",
    "while_loop", "sparse_embedding",
]


def _rand(*s):
    return paddle.to_tensor(np.random.RandomState(0).rand(*s).astype("float32"))


def test_reference_all_names_exist():
    missing = [n for n in REFERENCE_ALL if not hasattr(S, n)]
    assert missing == [], missing


class TestStaticNnOps:
    def test_embedding_and_sparse(self):
        ids = paddle.to_tensor(np.array([[1, 2], [3, 0]], np.int64))
        out = S.embedding(ids, size=[8, 5])
        assert tuple(out.shape) == (2, 2, 5)
        out2 = S.sparse_embedding(ids, size=[8, 5])
        assert tuple(out2.shape) == (2, 2, 5)

    def test_convs(self):
        x = _rand(1, 3, 8, 8)
        assert tuple(S.conv2d_transpose(x, 4, 3).shape)[1] == 4
        v = _rand(1, 2, 4, 6, 6)
        assert tuple(S.conv3d(v, 3, 3, padding=1).shape) == (1, 3, 4, 6, 6)
        assert tuple(S.conv3d_transpose(v, 3, 3).shape)[1] == 3

    def test_norms_and_activation(self):
        x = _rand(2, 4, 6, 6)
        assert tuple(S.group_norm(x, 2).shape) == (2, 4, 6, 6)
        assert tuple(S.instance_norm(x).shape) == (2, 4, 6, 6)
        out = S.layer_norm(x, begin_norm_axis=1, act="relu")
        assert float(np.asarray(out._data).min()) >= 0
        d = _rand(4, 6)
        assert tuple(S.data_norm(d).shape) == (4, 6)

    def test_param_creating_ops(self):
        x = _rand(3, 5)
        y = _rand(3, 7)
        out = S.bilinear_tensor_product(x, y, size=4)
        assert tuple(out.shape) == (3, 4)
        p = S.prelu(_rand(2, 3, 4, 4), mode="channel")
        assert tuple(p.shape) == (2, 3, 4, 4)
        r = S.row_conv(_rand(2, 6, 5), future_context_size=2)
        assert tuple(r.shape) == (2, 6, 5)
        lab = paddle.to_tensor(np.array([[1], [2], [0]], np.int64))
        n = S.nce(x, lab, num_total_classes=10, num_neg_samples=3)
        assert np.isfinite(np.asarray(n._data)).all()

    def test_spectral_norm_unit_sigma(self):
        w = _rand(6, 4)
        wn = np.asarray(S.spectral_norm(w, power_iters=20)._data)
        s = np.linalg.svd(wn, compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, atol=1e-3)

    def test_deform_conv2d_functional_form(self):
        x = _rand(1, 3, 6, 6)
        off = paddle.to_tensor(np.zeros((1, 18, 6, 6), np.float32))
        m = paddle.to_tensor(np.ones((1, 9, 6, 6), np.float32))
        out = S.deform_conv2d(x, off, m, num_filters=4, filter_size=3,
                              padding=1)
        assert tuple(out.shape) == (1, 4, 6, 6)

    def test_crf_decoding(self):
        T = 4
        em = _rand(2, 5, T)
        trans = _rand(T + 2, T)
        path = S.crf_decoding(em, trans,
                              length=paddle.to_tensor(
                                  np.array([5, 3], np.int64)))
        p = np.asarray(path._data)
        assert p.shape == (2, 5) and (p >= 0).all() and (p < T).all()
        assert (p[1, 3:] == 0).all()  # past-length positions zeroed
        # label form returns 0/1 correctness (same lengths)
        ok = S.crf_decoding(em, trans, label=path,
                            length=paddle.to_tensor(
                                np.array([5, 3], np.int64)))
        assert (np.asarray(ok._data) == 1).all()

    def test_multi_box_head(self):
        feats = [_rand(1, 8, 4, 4), _rand(1, 8, 2, 2)]
        img = _rand(1, 3, 32, 32)
        locs, confs, boxes, vars_ = S.multi_box_head(
            feats, img, base_size=32, num_classes=3,
            aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90)
        P = boxes.shape[0]
        assert locs.shape[1] == P and confs.shape[1] == P
        assert tuple(confs.shape)[2] == 3 and tuple(vars_.shape) == (P, 4)


class TestControlFlow:
    def test_cond_host(self):
        a = _rand(2, 2)
        out = S.cond(paddle.to_tensor(np.True_), lambda: a + 1, lambda: a - 1)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(a._data) + 1)

    def test_cond_traced(self):
        @paddle.jit.to_static
        def f(x, flag):
            return S.cond(flag, lambda: x * 2.0, lambda: x * 3.0)

        x = paddle.to_tensor(np.ones((2,), np.float32))
        got_t = f(x, paddle.to_tensor(np.array(True)))
        got_f = f(x, paddle.to_tensor(np.array(False)))
        np.testing.assert_allclose(np.asarray(got_t._data), 2.0)
        np.testing.assert_allclose(np.asarray(got_f._data), 3.0)

    def test_case_picks_first_true(self):
        x = _rand(3)
        out = S.case(
            [(paddle.to_tensor(np.False_), lambda: x * 0.0),
             (paddle.to_tensor(np.True_), lambda: x + 5.0)],
            default=lambda: x)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(x._data) + 5.0)

    def test_switch_case_host_and_default(self):
        x = _rand(2)
        fns = {1: lambda: x + 1.0, 3: lambda: x + 3.0}
        out = S.switch_case(paddle.to_tensor(np.int32(3)), fns,
                            default=lambda: x)
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(x._data) + 3.0)
        out2 = S.switch_case(paddle.to_tensor(np.int32(7)), fns,
                             default=lambda: x - 1.0)
        np.testing.assert_allclose(np.asarray(out2._data),
                                   np.asarray(x._data) - 1.0)

    def test_switch_case_traced(self):
        @paddle.jit.to_static
        def f(x, i):
            return S.switch_case(
                i, {0: lambda: x, 2: lambda: x * 10.0},
                default=lambda: x * 100.0)

        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(
            np.asarray(f(x, paddle.to_tensor(np.int32(2)))._data), 10.0)
        np.testing.assert_allclose(
            np.asarray(f(x, paddle.to_tensor(np.int32(5)))._data), 100.0)

    def test_while_loop_host(self):
        i = paddle.to_tensor(np.int32(0))
        s = paddle.to_tensor(np.float32(0))
        i2, s2 = S.while_loop(lambda i, s: i < 5,
                              lambda i, s: [i + 1, s + 2.0], [i, s])
        assert int(np.asarray(i2._data)) == 5
        np.testing.assert_allclose(np.asarray(s2._data), 10.0)

    def test_while_loop_traced(self):
        @paddle.jit.to_static
        def f(n):
            i = paddle.to_tensor(np.int32(0))
            s = paddle.to_tensor(np.float32(1))
            i, s = S.while_loop(lambda i, s: i < n,
                                lambda i, s: [i + 1, s * 2.0], [i, s])
            return s

        out = f(paddle.to_tensor(np.int32(6)))
        np.testing.assert_allclose(np.asarray(out._data), 64.0)

    def test_assert_api(self):
        paddle.static.Assert(paddle.to_tensor(np.True_))  # passes silently
        with pytest.raises(AssertionError, match="Assert failed"):
            paddle.static.Assert(paddle.to_tensor(np.False_),
                                 data=[paddle.to_tensor(
                                     np.array([1.5], np.float32))])


def test_conv2d_transpose_groups_dilation_routing():
    """Review r3: groups/dilation must land in their own slots."""
    x = _rand(1, 4, 8, 8)
    out = S.conv2d_transpose(x, 4, 3, groups=2, dilation=1)
    assert tuple(out.shape)[1] == 4
    # dilation=2 grows the output of a transpose conv; groups must not
    d1 = S.conv2d_transpose(x, 4, 3, dilation=1).shape[-1]
    d2 = S.conv2d_transpose(x, 4, 3, dilation=2).shape[-1]
    assert d2 > d1, (d1, d2)


def test_prelu_element_mode():
    x = paddle.to_tensor(
        np.random.RandomState(0).randn(2, 3, 4, 4).astype(np.float32))
    out = S.prelu(x, mode="element")
    assert tuple(out.shape) == (2, 3, 4, 4)
    xv = np.asarray(x._data)
    np.testing.assert_allclose(np.asarray(out._data),
                               np.where(xv >= 0, xv, 0.25 * xv), rtol=1e-6)


def test_cond_none_branch():
    """A None branch (reference-permitted) must not crash; like the
    reference's static cond, BOTH branches are built, so a None-returning
    fn is valid only alongside a None/omitted other branch."""
    assert S.cond(paddle.to_tensor(np.False_), lambda: None) is None
    assert S.cond(paddle.to_tensor(np.True_), lambda: None, None) is None
    assert S.cond(paddle.to_tensor(np.True_), None,
                  lambda: None) is None


def test_conv2d_transpose_output_size_derives_kernel():
    """Review r3b: filter_size=None derives the kernel from output_size
    (reference semantics), instead of silently using k=1."""
    x = _rand(1, 3, 8, 8)
    out = S.conv2d_transpose(x, 4, filter_size=None, output_size=16, stride=2)
    assert tuple(out.shape) == (1, 4, 16, 16)
    with pytest.raises(ValueError, match="required"):
        S.conv2d_transpose(x, 4, filter_size=None)
