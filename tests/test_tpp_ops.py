"""TPP micro-kernel registry (ISSUE 11, ops/tpp.py): each blocked
primitive matches its reference math within a per-op band (fp32
interpret mode is bit-exact for the elementwise kernels and
accumulation-order-tight for the matmuls), the two ported ops
differentiate correctly (reference-math backward), the registry keys by
(op, dtype, block) and meters calls + analytic costs, and the GPT block
routes through the ports only under FLAGS_tpp_kernels with a dense
fallback for shapes the registry can't tile."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle

#: per-op comparison bands (CPU interpret mode, fp32): elementwise
#: kernels are bit-exact; blocked matmuls may differ by accumulation
#: order only
TOL = {"matmul": 1e-5, "bias_act": 0.0, "softmax_rows": 1e-6,
       "masked_reduce": 0.0, "ln_matmul": 1e-5, "fused_mlp": 1e-5}


@pytest.fixture(autouse=True)
def _restore_flags():
    yield
    paddle.set_flags({"tpp_kernels": False})


@pytest.fixture(scope="module")
def tpp():
    from paddle_tpu.ops import tpp as mod

    return mod


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(0)
    return {
        "x": jnp.asarray(rng.randn(24, 32).astype(np.float32)),
        "w1": jnp.asarray(rng.randn(32, 128).astype(np.float32) * 0.1),
        "b1": jnp.asarray(rng.randn(128).astype(np.float32) * 0.1),
        "w2": jnp.asarray(rng.randn(128, 32).astype(np.float32) * 0.1),
        "b2": jnp.asarray(rng.randn(32).astype(np.float32) * 0.1),
        "gamma": jnp.asarray(1.0 + 0.1 * rng.randn(32).astype(np.float32)),
        "beta": jnp.asarray(0.1 * rng.randn(32).astype(np.float32)),
        "mask": jnp.asarray(rng.rand(24, 32) > 0.5),
    }


class TestMicroKernels:
    def test_matmul_bias_act_epilogue(self, tpp, data):
        got = tpp.matmul(data["x"], data["w1"], bias=data["b1"],
                         act="gelu")
        ref = jax.nn.gelu(data["x"] @ data["w1"] + data["b1"],
                          approximate=False)
        assert float(jnp.abs(got - ref).max()) <= TOL["matmul"]

    def test_matmul_input_activation(self, tpp, data):
        got = tpp.matmul(data["x"], data["w1"], in_act="relu")
        ref = jnp.maximum(data["x"], 0.0) @ data["w1"]
        assert float(jnp.abs(got - ref).max()) <= TOL["matmul"]

    def test_bias_act(self, tpp, data):
        got = tpp.bias_act(data["x"] @ data["w1"], data["b1"],
                           act="gelu")
        ref = jax.nn.gelu(data["x"] @ data["w1"] + data["b1"],
                          approximate=False)
        assert float(jnp.abs(got - ref).max()) <= TOL["bias_act"]

    def test_softmax_rows(self, tpp, data):
        got = tpp.softmax_rows(data["x"])
        ref = jax.nn.softmax(data["x"], axis=-1)
        assert float(jnp.abs(got - ref).max()) <= TOL["softmax_rows"]

    def test_masked_reduce_sum_and_max(self, tpp, data):
        x, mask = data["x"], data["mask"]
        got = tpp.masked_reduce(x, mask, "sum")[:, 0]
        ref = jnp.where(mask, x, 0.0).sum(-1)
        assert float(jnp.abs(got - ref).max()) <= TOL["masked_reduce"]
        gmax = tpp.masked_reduce(x, mask, "max")[:, 0]
        rmax = jnp.where(mask, x, -jnp.inf).max(-1)
        assert float(jnp.abs(gmax - rmax).max()) <= TOL["masked_reduce"]

    def test_untileable_shapes_raise(self, tpp):
        with pytest.raises(ValueError, match="tile"):
            tpp.matmul(jnp.zeros((7, 32)), jnp.zeros((32, 32)))
        assert tpp.supported_2d(7, 32, 32, "float32") is None
        assert tpp.supported_2d(24, 32, 32, "int32") is None


class TestPortedOps:
    def test_ln_matmul_forward_and_grads(self, tpp, data):
        x, g, be = data["x"], data["gamma"], data["beta"]
        w, b = data["w1"], data["b1"]
        got = tpp.ln_matmul(x, g, be, w, b)
        ref = tpp._ln_matmul_ref(x, g, be, w, b)
        assert float(jnp.abs(got - ref).max()) <= TOL["ln_matmul"]
        for argnum in range(5):
            gk = jax.grad(lambda *a: tpp.ln_matmul(*a).sum(),
                          argnums=argnum)(x, g, be, w, b)
            gr = jax.grad(lambda *a: tpp._ln_matmul_ref(*a).sum(),
                          argnums=argnum)(x, g, be, w, b)
            assert float(jnp.abs(gk - gr).max()) <= 1e-4, argnum

    def test_fused_mlp_forward_and_grads(self, tpp, data):
        args = (data["x"], data["w1"], data["b1"], data["w2"],
                data["b2"])
        got = tpp.fused_mlp(*args, False)
        ref = tpp._mlp_ref(*args, False)
        assert float(jnp.abs(got - ref).max()) <= TOL["fused_mlp"]
        for argnum in range(5):
            gk = jax.grad(lambda *a: tpp.fused_mlp(*a, False).sum(),
                          argnums=argnum)(*args)
            gr = jax.grad(lambda *a: tpp._mlp_ref(*a, False).sum(),
                          argnums=argnum)(*args)
            assert float(jnp.abs(gk - gr).max()) <= 1e-4, argnum

    def test_tanh_gelu_variant(self, tpp, data):
        args = (data["x"], data["w1"], data["b1"], data["w2"],
                data["b2"])
        got = tpp.fused_mlp(*args, True)
        ref = tpp._mlp_ref(*args, True)
        assert float(jnp.abs(got - ref).max()) <= TOL["fused_mlp"]


class TestRegistry:
    def test_keyed_by_op_dtype_block_and_counts_calls(self, tpp, data):
        before = {(r["op"], r["dtype"], tuple(r["block"])): r["calls"]
                  for r in tpp.registry_table()}
        tpp.softmax_rows(data["x"])
        tpp.softmax_rows(data["x"])
        after = {(r["op"], r["dtype"], tuple(r["block"])): r["calls"]
                 for r in tpp.registry_table()}
        key = ("softmax_rows", "float32", (8, 32))
        assert after[key] == before.get(key, 0) + 2

    def test_cost_registry_visible(self, tpp, data):
        from paddle_tpu.trace import costs

        tpp.ln_matmul(data["x"], data["gamma"], data["beta"],
                      data["w1"], data["b1"])
        entry = costs.get("tpp", "ln_matmul")
        assert entry is not None
        assert entry["flops"] > 0 and entry["calls"] >= 1

    def test_call_counter_metered(self, tpp, data):
        from paddle_tpu import monitor

        reg = monitor.default_registry()
        fam = reg.get("tpp_kernel_calls_total")
        base = 0
        if fam is not None:
            base = sum(s.value for s in fam.series()
                       if s.labels.get("op") == "softmax_rows")
        tpp.softmax_rows(data["x"])
        fam = monitor.default_registry().get("tpp_kernel_calls_total")
        now = sum(s.value for s in fam.series()
                  if s.labels.get("op") == "softmax_rows")
        assert now == base + 1


class TestGPTIntegration:
    def _forward_logits(self, tpp_on, hidden=32, seq=16):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        paddle.set_flags({"tpp_kernels": tpp_on})
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=hidden, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0)
        m = GPTForCausalLM(cfg)
        m.eval()
        ids = paddle.to_tensor(
            np.arange(2 * seq, dtype=np.int32).reshape(2, seq) % 64)
        return np.asarray(m(ids)._data)

    def test_armed_forward_matches_dense_in_band(self):
        dense = self._forward_logits(False)
        armed = self._forward_logits(True)
        np.testing.assert_allclose(armed, dense, rtol=1e-4, atol=1e-5)

    def test_untileable_model_falls_back_dense_bitexact(self):
        # hidden 36 has no registry block edge: the armed forward must
        # take the dense path and stay BIT-identical
        dense = self._forward_logits(False, hidden=36)
        armed = self._forward_logits(True, hidden=36)
        assert dense.tobytes() == armed.tobytes()

    def test_ports_land_in_registry_after_armed_train_step(self, tpp):
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainLoss)

        paddle.set_flags({"tpp_kernels": True})
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=32, dropout=0.0)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        tr = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                         mesh=mesh)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (2, 16)).astype(np.int32)
        lb = rng.randint(0, 64, (2, 16)).astype(np.int32)
        loss = tr.train_step(ids, lb)
        assert np.isfinite(float(np.asarray(loss._data)))
        ops = {r["op"].split("|")[0] for r in tpp.registry_table()}
        assert "ln_matmul" in ops
        assert any(o.startswith("fused_mlp") for o in ops)
