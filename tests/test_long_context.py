"""Ring attention / Ulysses / pipeline tests on the 8-device CPU mesh — numeric
equivalence against unsharded references (beyond-reference capability, SURVEY.md §5)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.long_context import (
    full_attention_reference,
    sequence_parallel_attention,
)
from paddle_tpu.distributed.mesh import build_mesh


def qkv(b=2, s=32, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(b, s, h, d).astype(np.float32)) for _ in range(3)]


class TestRingAttention:
    def test_matches_full_attention(self):
        q, k, v = qkv()
        mesh = build_mesh((8,), ("sp",))
        out = sequence_parallel_attention(q, k, v, mesh, impl="ring", causal=False)
        ref = full_attention_reference(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    def test_causal_matches(self):
        q, k, v = qkv(seed=1)
        mesh = build_mesh((8,), ("sp",))
        out = sequence_parallel_attention(q, k, v, mesh, impl="ring", causal=True)
        ref = full_attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    def test_differentiable(self):
        q, k, v = qkv(seed=2)
        mesh = build_mesh((8,), ("sp",))

        def loss(q_):
            return jnp.sum(sequence_parallel_attention(q_, k, v, mesh, impl="ring") ** 2)

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).max() > 0


class TestUlysses:
    def test_matches_full_attention(self):
        q, k, v = qkv(h=8)  # heads divisible by sp=8
        mesh = build_mesh((8,), ("sp",))
        out = sequence_parallel_attention(q, k, v, mesh, impl="ulysses", causal=False)
        ref = full_attention_reference(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)

    def test_causal(self):
        q, k, v = qkv(h=8, seed=3)
        mesh = build_mesh((8,), ("sp",))
        out = sequence_parallel_attention(q, k, v, mesh, impl="ulysses", causal=True)
        ref = full_attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


class TestPipeline:
    def test_pipeline_matches_sequential(self):
        from paddle_tpu.distributed.pipeline import Pipeline

        paddle.seed(0)
        stages = [nn.Linear(16, 16) for _ in range(8)]
        mesh = build_mesh((8,), ("pp",))
        pipe = Pipeline(stages, mesh, n_micro=4)
        x = np.random.RandomState(0).randn(8, 16).astype(np.float32)
        out = pipe.run(paddle.to_tensor(x))
        # sequential reference
        ref = paddle.to_tensor(x)
        for s in stages:
            ref = s(ref)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-4)

    def test_pipeline_more_micro_batches(self):
        from paddle_tpu.distributed.pipeline import Pipeline

        stages = [nn.Linear(8, 8) for _ in range(4)]
        mesh = build_mesh((4, 2), ("pp", "dp"))
        pipe = Pipeline(stages, mesh, n_micro=8)
        x = np.random.randn(16, 8).astype(np.float32)
        out = pipe.run(paddle.to_tensor(x))
        ref = paddle.to_tensor(x)
        for s in stages:
            ref = s(ref)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-4)


class TestGPTSequenceParallel:
    def test_gpt_sp_matches_dense_attention(self):
        """GPT with ring-attention SP == the same weights run dense."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        mesh = build_mesh((8,), ("sp",))
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=64, dropout=0.0,
                        sequence_parallel=True, sp_mesh=mesh)
        model = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 256, (2, 64)).astype(np.int64))
        logits_sp = model(ids)
        # same weights, dense path
        for blk in model.gpt.blocks:
            blk.attn.sp_mesh = None
        logits_dense = model(ids)
        np.testing.assert_allclose(np.asarray(logits_sp._data),
                                   np.asarray(logits_dense._data),
                                   atol=2e-3)

    def test_gpt_sp_trains(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        mesh = build_mesh((8,), ("sp",))
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=32, num_layers=1,
                        num_heads=4, max_seq_len=64, dropout=0.0,
                        sequence_parallel=True, sp_mesh=mesh)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        ids = paddle.to_tensor(
            np.random.RandomState(1).randint(0, 256, (2, 64)).astype(np.int64))
        losses = []
        for _ in range(3):
            loss = model.loss(ids, ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]

    def test_sp_config_validation(self):
        from paddle_tpu.models import GPTConfig

        mesh = build_mesh((8,), ("sp",))
        with pytest.raises(ValueError):  # no mesh
            GPTConfig(sequence_parallel=True, dropout=0.0)
        with pytest.raises(ValueError):  # dropout unsupported under SP
            GPTConfig(sequence_parallel=True, sp_mesh=mesh, dropout=0.1)
        with pytest.raises(ValueError):  # ulysses head divisibility
            GPTConfig(num_heads=4, sequence_parallel=True, sp_mesh=mesh,
                      dropout=0.0, sp_impl="ulysses")


class TestRingFlash:
    """ring_flash: ring attention whose per-block math runs the Pallas flash
    kernels (interpret mode on CPU) — values AND gradients must match the
    dense reference (the custom VJP re-rotates K/V through the flash
    backward kernels with global lse)."""

    def _qkv_big(self, seed=0):
        # per-shard seq must be a multiple of the 128 flash block: 8*128
        rng = np.random.RandomState(seed)
        return [jnp.asarray(rng.randn(1, 1024, 2, 64).astype(np.float32) * .5)
                for _ in range(3)]

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        q, k, v = self._qkv_big()
        mesh = build_mesh((8,), ("sp",))
        out = sequence_parallel_attention(q, k, v, mesh, impl="ring_flash",
                                          causal=causal, interpret=True)
        ref = full_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_full_attention(self, causal):
        q, k, v = self._qkv_big(seed=3)
        w = jnp.asarray(np.random.RandomState(4).randn(1, 1024, 2, 64)
                        .astype(np.float32))
        mesh = build_mesh((8,), ("sp",))

        def f(q, k, v):
            return jnp.sum(sequence_parallel_attention(
                q, k, v, mesh, impl="ring_flash", causal=causal,
                interpret=True) * w)

        def fr(q, k, v):
            return jnp.sum(full_attention_reference(q, k, v,
                                                    causal=causal) * w)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_rejects_unknown_impl(self):
        q, k, v = qkv()
        mesh = build_mesh((8,), ("sp",))
        with pytest.raises(ValueError, match="impl"):
            sequence_parallel_attention(q, k, v, mesh, impl="nope")


class TestGPTRingFlash:
    def test_gpt_sp_ring_flash_matches_dense(self):
        """GPT configured with sp_impl='ring_flash' (per-rank 128-token
        shards through the Pallas kernels, auto-interpret on CPU) == the
        same weights run dense."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        mesh = build_mesh((8,), ("sp",))
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=1,
                        num_heads=2, max_seq_len=1024, dropout=0.0,
                        sequence_parallel=True, sp_mesh=mesh,
                        sp_impl="ring_flash")
        model = GPTForCausalLM(cfg)
        ids = paddle.to_tensor(
            np.random.RandomState(0).randint(0, 256, (1, 1024))
            .astype(np.int64))
        logits_sp = model(ids)
        for blk in model.gpt.blocks:
            blk.attn.sp_mesh = None
        logits_dense = model(ids)
        np.testing.assert_allclose(np.asarray(logits_sp._data),
                                   np.asarray(logits_dense._data),
                                   atol=2e-3)

    def test_ring_flash_config_validation(self):
        from paddle_tpu.models import GPTConfig

        mesh = build_mesh((8,), ("sp",))
        with pytest.raises(ValueError, match="128 flash block"):
            GPTConfig(hidden_size=128, num_heads=2, max_seq_len=512,
                      dropout=0.0, sequence_parallel=True, sp_mesh=mesh,
                      sp_impl="ring_flash")  # 512/8 = 64-token shards
        with pytest.raises(ValueError, match="head_dim"):
            GPTConfig(hidden_size=64, num_heads=2, max_seq_len=1024,
                      dropout=0.0, sequence_parallel=True, sp_mesh=mesh,
                      sp_impl="ring_flash")
        with pytest.raises(ValueError, match="sp_impl"):
            GPTConfig(dropout=0.0, sequence_parallel=True, sp_mesh=mesh,
                      sp_impl="bogus")


class TestUlyssesFlash:
    def _qkv_big(self, seed=5):
        rng = np.random.RandomState(seed)
        # heads % sp == 0 (8 heads / 8 ranks); full seq 256 % 128 == 0
        return [jnp.asarray(rng.randn(1, 256, 8, 64).astype(np.float32) * .5)
                for _ in range(3)]

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal):
        q, k, v = self._qkv_big()
        mesh = build_mesh((8,), ("sp",))
        out = sequence_parallel_attention(q, k, v, mesh,
                                          impl="ulysses_flash",
                                          causal=causal, interpret=True)
        ref = full_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match(self):
        q, k, v = self._qkv_big(seed=6)
        w = jnp.asarray(np.random.RandomState(7).randn(1, 256, 8, 64)
                        .astype(np.float32))
        mesh = build_mesh((8,), ("sp",))

        def f(q, k, v):
            return jnp.sum(sequence_parallel_attention(
                q, k, v, mesh, impl="ulysses_flash", causal=True,
                interpret=True) * w)

        def fr(q, k, v):
            return jnp.sum(full_attention_reference(q, k, v,
                                                    causal=True) * w)

        g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(fr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


def test_runtime_seq_constraint_clear_error():
    """Config validates max_seq_len, but a SHORTER runtime batch must also
    fail with a clear message, not a deep pallas trace error."""
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    mesh = build_mesh((8,), ("sp",))
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=1,
                    num_heads=2, max_seq_len=1024, dropout=0.0,
                    sequence_parallel=True, sp_mesh=mesh,
                    sp_impl="ring_flash")
    model = GPTForCausalLM(cfg)
    ids = paddle.to_tensor(np.zeros((1, 512), np.int64))  # 64-token shards
    with pytest.raises(ValueError, match="128-token flash blocks"):
        model(ids)
