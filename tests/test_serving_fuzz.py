"""Seeded stress scenario for the serving engine's state machine: random
arrival times, prompt lengths, decoding knobs, shared prefixes, and chunk
settings — every greedy request must STILL match its solo generate run
exactly, and every request must finish exactly once with a sane reason.
Deterministic (fixed seeds), so a failure is replayable."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference.serving import ServingEngine
from paddle_tpu.models import GPTConfig, GPTForCausalLM


@pytest.mark.slow
@pytest.mark.parametrize("scenario_seed,engine_kw", [
    (0, {}),
    (1, {"prefill_chunk": 16}),
    (2, {"dtype": "bfloat16", "cache_dtype": "int8"}),
    (3, {"spec": True, "prefill_chunk": 16}),   # speculative rounds +
    # fallbacks + chunked admissions churning together (r5)
])
def test_random_scenario_exact_greedy_parity(scenario_seed, engine_kw):
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=160, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    rng = np.random.RandomState(scenario_seed)
    engine_kw = dict(engine_kw)
    if engine_kw.pop("spec", False):
        paddle.seed(11)
        d = GPTForCausalLM(GPTConfig(vocab_size=256, hidden_size=32,
                                     num_layers=1, num_heads=2,
                                     max_seq_len=160, dropout=0.0))
        d.eval()
        engine_kw.update(draft_model=d, spec_k=3)
    eng = ServingEngine(m, max_batch=3, **engine_kw)

    prefix = rng.randint(0, 256, (12,)).astype(np.int32)
    pid = eng.register_prefix(prefix)

    plan = []   # (rid, full_prompt, max_new, temperature)
    finished_events = []   # rids as the PUBLIC step() return reports them
    pending = 10
    while pending or eng.has_work():
        # random arrivals: 0-2 submits per step (capped by pending — an
        # uncapped draw once drove pending negative, which `while pending`
        # treats as truthy: infinite submissions). Shapes come from small
        # BUCKET sets so the reference generate() calls in the parity
        # check compile once per bucket, not once per request
        for _ in range(min(int(rng.randint(0, 3)), pending)):
            pending -= 1
            plen = int(rng.choice([6, 23]))
            p = rng.randint(0, 256, (plen,)).astype(np.int32)
            max_new = 9     # fixed: the reference generate compiles per
                            # (prompt_len, max_new) signature, ~30s each
            temp = float(rng.choice([0.0, 0.0, 0.8]))  # mostly greedy
            use_prefix = bool(rng.randint(0, 2))
            rid = eng.submit(p, max_new_tokens=max_new, temperature=temp,
                             prefix_id=pid if use_prefix else None)
            full = np.concatenate([prefix, p]) if use_prefix else p
            plan.append((rid, full, max_new, temp))
        finished_events.extend(r.rid for r in eng.step())

    # finish exactly once, observed through the public per-step returns
    assert sorted(finished_events) == sorted(r for r, *_ in plan)
    res = {rid: req for rid, req in eng._finished.items()}
    n_checked = 0
    for rid, full, max_new, temp in plan:
        req = res[rid]
        assert req.finished and req.finish_reason in ("length", "eos",
                                                      "capacity")
        assert 1 <= len(req.tokens) <= min(
            max_new, cfg.max_seq_len - len(full) + 1)
        if temp == 0.0 and req.finish_reason == "length":
            ref = m.generate(paddle.to_tensor(full[None]),
                             max_new_tokens=max_new, temperature=0.0,
                             **({k: v for k, v in engine_kw.items()
                                 if k in ("dtype", "cache_dtype")}))
            np.testing.assert_array_equal(
                req.tokens, np.asarray(ref._data)[0, len(full):],
                err_msg=f"rid {rid} diverged (seed {scenario_seed})")
            n_checked += 1
    assert n_checked >= 5   # the scenario actually exercised greedy parity
