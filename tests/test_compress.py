"""Unit + integration tests for the bandwidth-frugal dp stack (ISSUE 10):
distributed/compress.py quantize/dequantize/quantized_all_reduce, the
collective chokepoint's compressed opt-in, and the SpmdTrainer's
FLAGS_quantized_allreduce / FLAGS_shard_weight_update builds — error
feedback, guard/numerics composition, exact update-sharding parity,
checkpoint round-trips, and the construction-time flag contract.
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import monitor, nn  # noqa: E402
from paddle_tpu.distributed import collective  # noqa: E402
from paddle_tpu.distributed import compress  # noqa: E402
from paddle_tpu.distributed.mesh import build_mesh  # noqa: E402
from paddle_tpu.distributed.spmd import SpmdTrainer  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_flags():
    keys = ("quantized_allreduce", "shard_weight_update",
            "quantized_allreduce_bits", "quantized_allreduce_min_size",
            "check_nan_inf", "numerics", "numerics_interval")
    old = {k: paddle.get_flags(["FLAGS_" + k])["FLAGS_" + k] for k in keys}
    yield
    paddle.set_flags(old)


def _key(i=0):
    return jax.random.fold_in(jax.random.key(7), i)


# ---------------------------------------------------------------------------
# quantize / dequantize primitives
# ---------------------------------------------------------------------------

class TestQuantizePrimitives:
    def test_roundtrip_error_bounded_by_block_scale(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4 * compress.DEFAULT_BLOCK).astype(np.float32) * 3
        q, s = compress.quantize(jnp.asarray(x), _key())
        out = np.asarray(compress.dequantize(q, s))
        scales = np.repeat(np.asarray(s), compress.DEFAULT_BLOCK)
        # stochastic rounding moves each element by at most one step
        assert np.all(np.abs(out - x) <= scales + 1e-7)
        assert np.asarray(q).dtype == np.int8

    def test_deterministic_under_same_key(self):
        x = jnp.asarray(np.random.RandomState(1)
                        .randn(compress.DEFAULT_BLOCK).astype(np.float32))
        q1, s1 = compress.quantize(x, _key(3))
        q2, s2 = compress.quantize(x, _key(3))
        assert np.array_equal(np.asarray(q1), np.asarray(q2))
        assert np.array_equal(np.asarray(s1), np.asarray(s2))
        q3, _ = compress.quantize(x, _key(4))
        assert not np.array_equal(np.asarray(q1), np.asarray(q3))

    def test_stochastic_rounding_is_unbiased(self):
        # a constant mid-step value must average back to itself
        x = jnp.full((compress.DEFAULT_BLOCK,), 0.3, jnp.float32)
        x = x.at[0].set(1.27)      # pins the block scale at 0.01
        outs = np.stack([
            np.asarray(compress.quantize_dequantize(x, _key(i)))
            for i in range(200)])
        assert abs(float(outs[:, 1:].mean()) - 0.3) < 5e-4

    def test_zero_block_exact(self):
        x = jnp.zeros((compress.DEFAULT_BLOCK,), jnp.float32)
        out = compress.quantize_dequantize(x, _key())
        assert np.array_equal(np.asarray(out), np.zeros_like(x))

    def test_nan_poisons_its_block_loudly(self):
        x = np.ones((2 * compress.DEFAULT_BLOCK,), np.float32)
        x[3] = np.nan
        out = np.asarray(compress.quantize_dequantize(jnp.asarray(x),
                                                      _key()))
        # the poisoned block comes back non-finite (the NaN rides the
        # fp32 scale); the clean block is untouched
        assert not np.all(np.isfinite(out[:compress.DEFAULT_BLOCK]))
        assert np.all(np.isfinite(out[compress.DEFAULT_BLOCK:]))

    def test_shape_preserved_and_padding_trimmed(self):
        x = jnp.asarray(np.random.RandomState(2)
                        .randn(3, 17).astype(np.float32))
        out = compress.quantize_dequantize(x, _key())
        assert out.shape == x.shape

    def test_wire_bytes_math(self):
        b = compress.DEFAULT_BLOCK
        assert compress.padded_size(1, block=b) == b
        assert compress.padded_size(b + 1, block=b) == 2 * b
        assert compress.padded_size(10, block=b, world=4) == 4 * b
        # int8 payload + one fp32 scale per block
        assert compress.wire_bytes(b, block=b) == b + 4
        assert compress.wire_bytes(4 * b, block=b) == 4 * b + 16

    def test_unsupported_bits_raise(self):
        with pytest.raises(ValueError, match="bits"):
            compress.quantize(jnp.zeros(256), _key(), bits=4)
        with pytest.raises(ValueError, match="bits"):
            compress.wire_bytes(256, bits=16)


# ---------------------------------------------------------------------------
# quantized_all_reduce on a real dp axis
# ---------------------------------------------------------------------------

def _shard_reduce(x_per_rank, world, **kw):
    """Run quantized_all_reduce_ef under shard_map on `world` devices;
    returns the (replicated) reduced array from rank 0."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:world]), ("dp",))

    def body(v):
        out, _ = compress.quantized_all_reduce_ef(
            v[0], "dp", _key(9), **kw)
        return out[None]

    f = shard_map(body, mesh=mesh, in_specs=(P("dp"),),
                  out_specs=P("dp"), check_rep=False)
    return np.asarray(jax.jit(f)(jnp.asarray(x_per_rank)))


class TestQuantizedAllReduce:
    @pytest.mark.parametrize("world", [2, 8])
    def test_sum_close_and_identical_across_ranks(self, world):
        if len(jax.devices()) < world:
            pytest.skip(f"needs {world} devices")
        rng = np.random.RandomState(0)
        x = rng.randn(world, 2048).astype(np.float32)
        out = _shard_reduce(x, world)
        ref = x.sum(0)
        # every rank dequantizes the identical gathered bytes
        for r in range(1, world):
            assert np.array_equal(out[r], out[0])
        scale = np.max(np.abs(ref))
        assert np.max(np.abs(out[0] - ref)) / scale < 0.05

    def test_mean(self):
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        x = np.ones((2, 512), np.float32) * np.array([[1.0], [3.0]])
        out = _shard_reduce(x, 2, mean=True)
        assert np.allclose(out[0], 2.0, atol=0.05)

    def test_error_feedback_keeps_cumulative_error_one_step_deep(self):
        """The EF contract: sum of applied values over T steps equals
        T*x minus the CURRENT residual — the error never accumulates
        beyond one quantization step."""
        rng = np.random.RandomState(3)
        x = rng.randn(1024).astype(np.float32)
        res = np.zeros_like(x)
        applied_sum = np.zeros_like(x)
        T = 8
        for t in range(T):
            inp = jnp.asarray(x + res)
            rt = np.asarray(compress.quantize_dequantize(inp, _key(t)))
            applied_sum += rt
            res = np.asarray(inp) - rt
        one_step = np.max(np.abs(
            x - np.asarray(compress.quantize_dequantize(jnp.asarray(x),
                                                        _key(99)))))
        # the algebraic identity: what was applied is T*x minus exactly
        # the CURRENT residual — nothing was lost along the way
        assert np.allclose(applied_sum, T * x - res, atol=1e-4)
        # and that residual is one quantization step deep (1.5x slack:
        # the residual rides inside the quantized input, nudging the
        # block scale), NOT T steps deep
        assert np.max(np.abs(res)) <= 1.5 * one_step + 1e-6
        assert np.max(np.abs(applied_sum / T - x)) \
            <= 1.5 * one_step / T + 1e-6

    def test_ste_gradient_matches_psum_cotangent(self):
        data = np.random.RandomState(1).randn(4, 512).astype(np.float32)

        def quant_loss(v):
            s = compress.quantized_all_reduce(v, "c", key=_key(5))
            return jnp.sum(s * s)

        def exact_loss(v):
            s = jax.lax.psum(v, "c")
            return jnp.sum(s * s)

        g = jax.grad(lambda v: jnp.sum(jax.vmap(
            quant_loss, axis_name="c")(v)))(jnp.asarray(data))
        gref = jax.grad(lambda v: jnp.sum(jax.vmap(
            exact_loss, axis_name="c")(v)))(jnp.asarray(data))
        rel = float(jnp.max(jnp.abs(g - gref)) / jnp.max(jnp.abs(gref)))
        assert rel < 0.05   # straight-through: ct of the exact sum


# ---------------------------------------------------------------------------
# the collective chokepoint's compressed opt-in
# ---------------------------------------------------------------------------

def _op_series(snap, name):
    """{op: value} of one family's NON-ZERO series — robust to zeroed
    leftovers other tests' families leave in the shared registry."""
    for m in snap["metrics"]:
        if m["name"] == name:
            return {s["labels"].get("op"): s["value"]
                    for s in m["series"] if s["value"]}
    return {}


class TestChokepointCompressedPath:
    def test_eager_ws1_roundtrip_and_exact_metering(self):
        monitor.reset()
        n = 1000
        x = paddle.to_tensor(np.linspace(-1, 1, n).astype(np.float32))
        out = collective.all_reduce(x, compress=8)
        # paddle all_reduce is in-place — the round-trip lands in the
        # caller's tensor even at world size 1
        assert out is x
        err = np.max(np.abs(np.asarray(out._data)
                            - np.linspace(-1, 1, n)))
        assert 0 < err < 2.0 / 127
        snap = monitor.snapshot()
        wire = compress.wire_bytes(n)
        assert _op_series(snap, "collective_bytes_total") == {
            "quantized_all_reduce": wire}
        assert _op_series(snap, "collective_bytes_saved_total") == {
            "quantized_all_reduce": n * 4 - wire}
        assert _op_series(snap, "collective_calls_total") == {
            "quantized_all_reduce": 1}

    def test_uncompressed_metering_unchanged(self):
        """The PR 2 regression pin: an uncompressed all_reduce still
        counts its LOGICAL payload in collective_bytes_total and
        records nothing saved."""
        monitor.reset()
        x = paddle.to_tensor(np.ones(100, np.float32))
        collective.all_reduce(x)
        snap = monitor.snapshot()
        assert _op_series(snap, "collective_bytes_total") == {
            "all-reduce": 400}
        assert _op_series(snap, "collective_bytes_saved_total") == {}

    def test_integer_payload_raises(self):
        with pytest.raises(ValueError, match="float"):
            collective.all_reduce(paddle.to_tensor(np.arange(4)),
                                  compress=True)

    def test_max_op_raises(self):
        with pytest.raises(ValueError, match="SUM/AVG"):
            collective.all_reduce(
                paddle.to_tensor(np.ones(4, np.float32)),
                op=collective.ReduceOp.MAX, compress=8)

    def test_client_reduce_placed_compressed(self):
        from paddle_tpu.federated import client_map

        data = np.random.RandomState(0).randn(4, 512).astype(np.float32)

        def per_client(v):
            return collective.client_reduce(
                v, op=collective.ReduceOp.SUM, compress=8,
                compress_key=_key(11))

        res = client_map(per_client, paddle.to_tensor(data))
        ref = data.sum(0)
        rel = np.max(np.abs(np.asarray(res._data)[0] - ref)) \
            / np.max(np.abs(ref))
        assert rel < 0.05

    def test_client_reduce_leading_compressed(self):
        monitor.reset()
        data = np.random.RandomState(0).randn(4, 100).astype(np.float32)
        res = collective.client_reduce(paddle.to_tensor(data),
                                       placed=False, compress=8)
        ref = data.sum(0)
        rel = np.max(np.abs(np.asarray(res._data) - ref)) \
            / np.max(np.abs(ref))
        assert rel < 0.05
        # each row is its own payload: 4 x (one padded block + a scale),
        # NOT one contiguous 400-element encoding
        snap = monitor.snapshot()
        assert _op_series(snap, "collective_bytes_total") == {
            "federated_sum": 4 * compress.wire_bytes(100)}


# ---------------------------------------------------------------------------
# trainer integration — quantized all-reduce
# ---------------------------------------------------------------------------

def _build_trainer(mesh_n=1, flags=None, opt="adamw", lr=1e-2,
                   grad_clip=None, **kw):
    paddle.set_flags({"quantized_allreduce": False,
                      "shard_weight_update": False,
                      "quantized_allreduce_min_size": 1024,
                      **(flags or {})})
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(64, 64), nn.Linear(64, 8))
    opt_obj = {
        "adamw": lambda: paddle.optimizer.AdamW(
            learning_rate=lr, parameters=net.parameters(),
            grad_clip=grad_clip),
        "sgd": lambda: paddle.optimizer.SGD(
            learning_rate=lr, parameters=net.parameters()),
        "momentum": lambda: paddle.optimizer.Momentum(
            learning_rate=lr, parameters=net.parameters()),
        "lamb": lambda: paddle.optimizer.Lamb(
            learning_rate=lr, parameters=net.parameters()),
    }[opt]()
    mesh = build_mesh((mesh_n,), ("dp",), devices=jax.devices()[:mesh_n])
    return SpmdTrainer(net, opt_obj, loss_fn=nn.MSELoss(), mesh=mesh,
                       **kw)


_RNG = np.random.RandomState(0)
_X = _RNG.randn(16, 64).astype(np.float32)
_Y = _RNG.randn(16, 8).astype(np.float32)


def _run(tr, steps=3, x=_X, y=_Y):
    for _ in range(steps):
        loss = tr.train_step(x, y)
    return (float(np.asarray(loss._data)),
            {k: np.asarray(v) for k, v in tr.params.items()})


QFLAGS = {"quantized_allreduce": True, "quantized_allreduce_min_size": 1}

#: cached plain-dp references + one exercised quantized trainer — each
#: trainer build compiles a jitted step; sharing them keeps this file's
#: tier-1 wall time down without losing any assertion
_CACHE = {}


def _plain_ref(opt="adamw", mesh_n=1):
    key = (opt, mesh_n)
    if key not in _CACHE:
        _CACHE[key] = _run(_build_trainer(mesh_n=mesh_n, opt=opt))
    return _CACHE[key]


def _qtrainer():
    """A quantized dp1 trainer after 2 steps (built once)."""
    if "qtr" not in _CACHE:
        tr = _build_trainer(flags=QFLAGS)
        _run(tr, 2)
        _CACHE["qtr"] = tr
    return _CACHE["qtr"]


class TestTrainerQuantized:
    def test_loss_stays_in_band_vs_plain(self):
        l0, _ = _plain_ref()
        tr = _qtrainer()
        paddle.set_flags(QFLAGS)   # stepping a quantized-built trainer
        l1 = float(np.asarray(tr.train_step(_X, _Y)._data))
        assert abs(l1 - l0) / abs(l0) < 0.02

    def test_residuals_ride_opt_state_and_feed_back(self):
        tr = _qtrainer()
        assert set(tr.opt_state["__qar_residual__"]) == set(
            tr._qar_eligible) == set(tr.params)
        res = {k: np.asarray(v)
               for k, v in tr.opt_state["__qar_residual__"].items()}
        assert any(np.any(v != 0) for v in res.values())
        assert all(np.all(np.isfinite(v)) for v in res.values())

    def test_min_size_threshold_respected(self):
        # eligibility is a construction-time property — no step needed
        tr = _build_trainer(flags={"quantized_allreduce": True,
                                   "quantized_allreduce_min_size": 1024})
        # 64x64 weight (4096) eligible; 8/64-element biases are not
        assert "0.weight" in tr._qar_eligible
        assert not any(n.endswith("bias") for n in tr._qar_eligible)
        assert set(tr.opt_state["__qar_residual__"]) == set(
            tr._qar_eligible)

    def test_quantize_error_surfaced_lazily(self):
        monitor.reset()
        tr = _qtrainer()
        val = tr.quantize_error()
        assert val is not None and val > 0
        assert tr.stats()["quantize_error_norm"] == val
        snap = monitor.snapshot()
        fams = {m["name"] for m in snap["metrics"] if m["series"]}
        assert "quantize_error_norm" in fams
        # a trainer that never ran a quantized step has nothing banked
        fresh = _build_trainer()
        assert fresh.quantize_error() is None

    def test_checkpoint_roundtrip_bit_exact(self):
        tr = _build_trainer(flags=QFLAGS)
        _run(tr, 2)
        state = tr.state_dict()
        tr2 = _build_trainer(flags=QFLAGS)
        tr2.set_state_dict(state)
        a, _ = _run(tr, 1)
        b, _ = _run(tr2, 1)
        assert a == b

    def test_flag_toggle_after_ctor_raises(self):
        tr = _build_trainer()   # built unarmed
        paddle.set_flags({"quantized_allreduce": True})
        with pytest.raises(RuntimeError, match="constructed"):
            tr.train_step(_X, _Y)
        paddle.set_flags({"quantized_allreduce": False})
        tr2 = _build_trainer(flags=QFLAGS)   # built armed
        paddle.set_flags({"quantized_allreduce": False})
        with pytest.raises(RuntimeError, match="constructed"):
            tr2.train_step(_X, _Y)

    def test_incompatible_configs_raise_at_ctor(self):
        with pytest.raises(ValueError, match="sharding_stage"):
            _build_trainer(mesh_n=2, flags=QFLAGS, sharding_stage=2)
        with pytest.raises(ValueError, match="gradient merge"):
            _build_trainer(flags=QFLAGS, accumulate_steps=2)
        with pytest.raises(ValueError, match="outputs"):
            _build_trainer(flags=QFLAGS, return_outputs=True)
        with pytest.raises(ValueError, match="bits"):
            _build_trainer(flags={**QFLAGS,
                                  "quantized_allreduce_bits": 4})

    def test_localsgd_carve_out_ignores_flag(self):
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        tr = _build_trainer(mesh_n=2, flags=QFLAGS, opt="sgd",
                            localsgd_k=2)
        assert not tr._quantized
        tr.train_step(_X, _Y)   # no raise: the flag is ignored, not live

    def test_numerics_composition_rows_align(self):
        tr = _build_trainer(flags={**QFLAGS, "numerics": True,
                                   "numerics_interval": 1})
        _run(tr, 2)
        host = tr.numerics_fetch()
        layers = sorted(tr.params)
        assert host is not None
        assert host["grad_norm"].shape == (len(layers),)
        assert np.all(np.isfinite(host["grad_norm"]))
        assert float(np.sum(host["nonfinite"])) == 0.0

    def test_guard_skip_restores_residuals(self):
        from paddle_tpu.testing import failpoints as fp

        tr = _build_trainer(flags={**QFLAGS, "check_nan_inf": True})
        _run(tr, 2)
        snap_r = {k: np.asarray(v).copy()
                  for k, v in tr.opt_state["__qar_residual__"].items()}
        snap_p = {k: np.asarray(v).copy() for k, v in tr.params.items()}
        with fp.scoped("trainer/batch=scale:nan"):
            loss = tr.train_step(_X, _Y)
        assert np.isnan(float(np.asarray(loss._data)))
        for k in snap_p:
            assert np.asarray(tr.params[k]).tobytes() \
                == snap_p[k].tobytes()
        for k in snap_r:
            assert np.asarray(
                tr.opt_state["__qar_residual__"][k]).tobytes() \
                == snap_r[k].tobytes()
        # the reported error norm is the RESTORED residual's, not the
        # poisoned one the skipped step computed and threw away
        qerr = tr.quantize_error()
        assert qerr is not None and np.isfinite(qerr)
        after, _ = _run(tr, 1)
        assert np.isfinite(after)

    def test_dp_multi_device_trains_close_to_plain(self):
        # dp2 covers the real cross-rank exchange; the dp8 structure is
        # pinned by test_perf_budgets and the shard-map unit test above
        if len(jax.devices()) < 2:
            pytest.skip("needs 2 devices")
        l0, _ = _plain_ref(mesh_n=2)
        l1, _ = _run(_build_trainer(mesh_n=2, flags=QFLAGS))
        assert abs(l1 - l0) / abs(l0) < 0.05


# ---------------------------------------------------------------------------
# trainer integration — cross-replica update sharding
# ---------------------------------------------------------------------------

SFLAGS = {"shard_weight_update": True}


class TestTrainerShardUpdate:
    @pytest.mark.parametrize("opt", ["adamw", "sgd", "momentum"])
    def test_dp1_bit_exact_vs_plain(self, opt):
        _, p0 = _plain_ref(opt=opt)
        _, p1 = _run(_build_trainer(opt=opt, flags=SFLAGS))
        for k in p0:
            assert np.array_equal(p0[k], p1[k]), k

    def test_dp4_matches_plain_dp4(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        _, p0 = _plain_ref(mesh_n=4)
        _, p1 = _run(_build_trainer(mesh_n=4, flags=SFLAGS))
        for k in p0:
            assert np.allclose(p0[k], p1[k], rtol=1e-5, atol=1e-6), k

    def test_moments_stored_sharded(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        tr = _build_trainer(mesh_n=4, flags=SFLAGS)
        m1 = tr.opt_state["0.weight"]["moment1"]
        assert m1.shape == (4, tr._shard_ps["0.weight"])
        # beta powers stay replicated scalars
        assert tr.opt_state["0.weight"]["beta1_pow"].shape == ()
        _run(tr, 2)

    def test_global_norm_clip_matches_plain(self):
        clip = nn.ClipGradByGlobalNorm(0.01)
        _, p0 = _run(_build_trainer(grad_clip=clip))
        clip2 = nn.ClipGradByGlobalNorm(0.01)
        _, p1 = _run(_build_trainer(grad_clip=clip2, flags=SFLAGS))
        for k in p0:
            assert np.allclose(p0[k], p1[k], rtol=1e-6, atol=1e-7), k

    def test_lamb_rejected(self):
        with pytest.raises(ValueError, match="elementwise"):
            _build_trainer(opt="lamb", flags=SFLAGS)

    def test_checkpoint_roundtrip(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        tr = _build_trainer(mesh_n=4, flags=SFLAGS)
        _run(tr, 2)
        state = tr.state_dict()
        tr2 = _build_trainer(mesh_n=4, flags=SFLAGS)
        tr2.set_state_dict(state)
        a, _ = _run(tr, 1)
        b, _ = _run(tr2, 1)
        assert a == b

    def test_composed_with_quantized(self):
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 devices")
        l0, _ = _plain_ref(mesh_n=4)
        tr = _build_trainer(mesh_n=4, flags={**QFLAGS, **SFLAGS})
        assert tr._quantized and tr._shard_update
        l1, _ = _run(tr)
        assert abs(l1 - l0) / abs(l0) < 0.05
        assert set(tr.opt_state["__qar_residual__"]) == set(tr.params)
        # moments sharded AND residuals per-rank at once
        assert tr.opt_state["0.weight"]["moment1"].ndim == 2


# ---------------------------------------------------------------------------
# the parity harness targets, in-process
# ---------------------------------------------------------------------------

class TestParityTargets:
    def _batches(self, steps=3):
        rng = np.random.RandomState(5)
        return [(rng.randn(8, 64).astype(np.float32),
                 rng.randn(8, 8).astype(np.float32))
                for _ in range(steps)]

    def _build(self):
        net = nn.Sequential(nn.Linear(64, 64), nn.Linear(64, 8))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        return SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)

    def test_shard_weight_update_exact(self):
        from paddle_tpu.testing import parity

        report = parity.run_parity(
            self._build, self._batches(),
            candidate_flags={"shard_weight_update": True},
            loss_rtol=0.0, loss_atol=0.0, stat_rtol=0.0, stat_atol=0.0)
        assert not report["diverged"], report["first_divergence"]
        assert report["max_abs_loss_diff"] == 0.0

    @pytest.mark.slow
    def test_quantized_within_band_and_perturbed_diverges(self):
        # the CLI form of this pair (band + must-fail control) is the
        # tier-1-adjacent slow gate in test_compress_gate.py; this
        # in-process variant costs four trainer compiles, so it rides
        # the slow lane too
        from paddle_tpu.testing import parity

        report = parity.run_parity(
            self._build, self._batches(),
            candidate_flags={"quantized_allreduce": True,
                             "quantized_allreduce_min_size": 1},
            loss_rtol=0.08, loss_atol=0.05, stat_rtol=0.6, stat_atol=0.1)
        assert not report["diverged"], report["first_divergence"]

        def cand():
            tr = self._build()
            tr.optimizer.set_lr(8e-2)
            return tr

        bad = parity.run_parity(
            self._build, self._batches(), build_candidate=cand,
            candidate_flags={"quantized_allreduce": True,
                             "quantized_allreduce_min_size": 1},
            loss_rtol=0.08, loss_atol=0.05, stat_rtol=0.6, stat_atol=0.1)
        assert bad["diverged"]
