"""Worker script for the multi-process DP harness (test_dist_multiproc.py).

Launched via fleetrun (python -m paddle_tpu.distributed.fleet.launch): each
rank initializes jax.distributed over the PADDLE_TRAINER_* env protocol
(CPU backend, 1 device per process), trains a small model data-parallel via
SpmdTrainer over the GLOBAL mesh, and rank 0 writes the loss trajectory.

Reference parity: the test_dist_base.py pattern — real localhost processes,
loss parity asserted against a single-process run
(python/paddle/fluid/tests/unittests/test_dist_base.py:671,934-942).
"""
import argparse
import json
import os

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed import env as denv
    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer

    denv.init_distributed()  # no-op for world=1; coordination service for >1
    world = denv.get_world_size()
    rank = denv.get_rank()
    assert len(jax.devices()) == world, (len(jax.devices()), world)

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    rng = np.random.RandomState(0)
    init = {k: (rng.randn(*v.shape) * 0.1).astype(np.float32)
            for k, v in net.state_dict().items()}
    net.set_state_dict(init)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    mesh = build_mesh((len(jax.devices()),), ("dp",))
    trainer = SpmdTrainer(net, opt,
                          lambda o, l: ((o - l) ** 2).mean(), mesh=mesh)

    data_rng = np.random.RandomState(1)
    x = data_rng.randn(32, 16).astype(np.float32)
    y = data_rng.randn(32, 4).astype(np.float32)
    losses = []
    for _ in range(args.steps):
        loss = trainer.train_step(paddle.to_tensor(x), paddle.to_tensor(y))
        losses.append(float(np.asarray(loss._data)))

    if rank == 0:
        with open(args.out, "w") as f:
            json.dump({"world": world, "losses": losses}, f)
    print(f"rank {rank}/{world} done: {losses[-1]:.6f}")


if __name__ == "__main__":
    main()
