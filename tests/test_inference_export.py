"""Inference export round trip: StableHLO text + jax.export AOT predictor
(static/io.py — save/load_inference_model + AnalysisPredictor analog)."""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static.io import (
    load_aot_predictor, load_inference_model, save_inference_model,
)


class TestInferenceExport:
    def _save(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3))
        net.eval()
        x_spec = paddle.to_tensor(np.zeros((2, 6), np.float32))
        prefix = str(tmp_path / "infer_model")
        save_inference_model(prefix, [x_spec], None, layer=net)
        return net, prefix

    def test_stablehlo_text_exported(self, tmp_path):
        net, prefix = self._save(tmp_path)
        params, meta, hlo = load_inference_model(prefix)
        assert "stablehlo" in hlo or "func.func" in hlo
        assert meta["feed_shapes"] == [(2, 6)]
        assert any(k.endswith("weight") or "weight" in k for k in params)

    def test_aot_predictor_matches_layer(self, tmp_path):
        net, prefix = self._save(tmp_path)
        predict = load_aot_predictor(prefix)
        x = np.random.RandomState(0).randn(2, 6).astype(np.float32)
        out = predict(x)
        out = out[0] if isinstance(out, (tuple, list)) else out
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-5)

    def test_aot_predictor_without_original_layer(self, tmp_path):
        """Deployment contract: predictor works with only the saved files
        (fresh state, no Layer object)."""
        _, prefix = self._save(tmp_path)
        predict = load_aot_predictor(prefix)
        x = np.ones((2, 6), np.float32)
        out = predict(paddle.to_tensor(x))
        out = out[0] if isinstance(out, (tuple, list)) else out
        assert tuple(out.shape) == (2, 3)
        assert np.isfinite(np.asarray(out._data)).all()

    def test_predictor_api_uses_aot_artifact(self, tmp_path):
        """inference.Predictor transparently loads the jax.export artifact."""
        from paddle_tpu.inference.predictor import Config, Predictor

        net, prefix = self._save(tmp_path)
        pred = Predictor(Config(model_path=prefix))
        assert pred._aot is not None  # AOT path, no pickled Layer needed
        x = np.random.RandomState(1).randn(2, 6).astype(np.float32)
        h = pred.get_input_handle("input_0")
        h.copy_from_cpu(x)
        out = pred.run()[0]
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(out, ref, atol=1e-5)


class TestProgramPathSaveInferenceModel:
    """VERDICT r2 missing #2: the reference Program-path signature
    save_inference_model(path_prefix, feed_vars, fetch_vars, executor)
    (reference python/paddle/static/io.py:442) over the recorded static
    Program."""

    def _build_and_train(self):
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            img = paddle.static.data(name="img", shape=[None, 64],
                                     dtype="float32")
            label = paddle.static.data(name="label", shape=[None],
                                       dtype="int64")
            h = paddle.static.nn.fc(img, size=32, activation="relu")
            logits = paddle.static.nn.fc(h, size=10)
            loss = paddle.mean(
                paddle.nn.functional.cross_entropy(logits, label))
            opt = paddle.optimizer.Adam(learning_rate=1e-2)
            opt.minimize(loss)
        exe = paddle.static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        xs = rng.randn(32, 64).astype(np.float32)
        ys = rng.randint(0, 10, 32).astype(np.int64)
        for _ in range(3):
            exe.run(main, feed={"img": xs, "label": ys}, fetch_list=[loss])
        return main, exe, img, logits, xs

    def test_program_export_roundtrip(self, tmp_path):
        paddle.enable_static()
        try:
            main, exe, img, logits, xs = self._build_and_train()
            prefix = str(tmp_path / "static_mnist")
            res = save_inference_model(prefix, [img], [logits], exe,
                                       program=main)
            assert os.path.exists(prefix + ".pdmodel.stablehlo")
            # reference answer: the executor on the test clone
            (want,) = exe.run(main.clone(for_test=True),
                              feed={"img": xs[:4]}, fetch_list=[logits])
            predict = load_aot_predictor(prefix)
            got = predict(xs[:4])
            got = got[0] if isinstance(got, (tuple, list)) else got
            np.testing.assert_allclose(np.asarray(got._data), want,
                                       rtol=1e-4, atol=1e-5)
        finally:
            paddle.disable_static()

    def test_program_export_default_program(self, tmp_path):
        """No program= kwarg: exports the default main program, exactly the
        reference call shape save_inference_model(path, feeds, fetches, exe)."""
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            with paddle.static.program_guard(main):
                x = paddle.static.data(name="x", shape=[None, 5],
                                       dtype="float32")
                y = paddle.static.nn.fc(x, size=2)
            exe = paddle.static.Executor()
            prefix = str(tmp_path / "default_prog")
            with paddle.static.program_guard(main):
                save_inference_model(prefix, [x], [y], exe)
            predict = load_aot_predictor(prefix)
            out = predict(np.ones((3, 5), np.float32))
            out = out[0] if isinstance(out, (tuple, list)) else out
            assert tuple(out.shape) == (3, 2)
        finally:
            paddle.disable_static()

    def test_program_export_serves_fresh_process(self, tmp_path):
        """Deployment contract (VERDICT r3 ask): static program ->
        save_inference_model -> AOT Predictor serves it in a NEW process."""
        paddle.enable_static()
        try:
            main, exe, img, logits, xs = self._build_and_train()
            prefix = str(tmp_path / "deploy")
            save_inference_model(prefix, [img], [logits], exe, program=main)
            (want,) = exe.run(main.clone(for_test=True),
                              feed={"img": xs[:4]}, fetch_list=[logits])
        finally:
            paddle.disable_static()
        np.save(str(tmp_path / "x.npy"), xs[:4])
        np.save(str(tmp_path / "want.npy"), want)
        script = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            from paddle_tpu.inference import Config, create_predictor

            pred = create_predictor(Config(model_path={prefix!r}))
            x = np.load({str(tmp_path / 'x.npy')!r})
            want = np.load({str(tmp_path / 'want.npy')!r})
            h = pred.get_input_handle("img")
            h.copy_from_cpu(x)
            (got,) = pred.run()
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
            print("SERVED_OK")
        """)
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=600)
        assert "SERVED_OK" in r.stdout, r.stdout + r.stderr

    def test_program_export_batch_polymorphic(self, tmp_path):
        """None batch dims export symbolically: one artifact, many batches."""
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            with paddle.static.program_guard(main):
                x = paddle.static.data(name="x", shape=[None, 4],
                                       dtype="float32")
                y = paddle.static.nn.fc(x, size=3)
            prefix = str(tmp_path / "poly")
            save_inference_model(prefix, [x], [y], None, program=main)
            predict = load_aot_predictor(prefix)
            for bs in (1, 2, 7):
                out = predict(np.ones((bs, 4), np.float32))
                out = out[0] if isinstance(out, (tuple, list)) else out
                assert tuple(out.shape) == (bs, 3)
        finally:
            paddle.disable_static()

    def test_program_export_validates_feeds(self, tmp_path):
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            with paddle.static.program_guard(main):
                a = paddle.static.data(name="a", shape=[None, 2],
                                       dtype="float32")
                b = paddle.static.data(name="b", shape=[None, 2],
                                       dtype="float32")
                out = a + b
            with pytest.raises(ValueError, match="placeholder 'b'"):
                save_inference_model(str(tmp_path / "bad"), [a], [out],
                                     None, program=main)
            eager = paddle.to_tensor(np.ones((1, 2), np.float32))
            with pytest.raises(ValueError, match="not a static.data"):
                save_inference_model(str(tmp_path / "bad2"), [eager], [out],
                                     None, program=main)
        finally:
            paddle.disable_static()


class TestOnnxExportHonesty:
    """r3: export refused to write fake .onnx; r4 ships the real emitter
    (tests/test_onnx_export.py) — here we pin that the honesty contract
    SURVIVES it: a real .onnx is written only when validated, and the
    native artifact always saves alongside."""

    def test_writes_real_onnx_and_native_artifact(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 2))
        net.eval()
        prefix = str(tmp_path / "om")
        onnx_path = paddle.onnx.export(
            net, prefix,
            input_spec=[paddle.jit.InputSpec([2, 4], "float32")])
        assert os.path.exists(onnx_path)
        # the native artifact is still saved and loads
        loaded = paddle.jit.load(prefix)
        out = loaded(paddle.to_tensor(np.ones((2, 4), np.float32)))
        assert tuple(out.shape) == (2, 2)
        # and the .onnx re-executes in numpy to the same result
        from paddle_tpu.onnx import runtime
        x = np.ones((2, 4), np.float32)
        (got,) = runtime.run(open(onnx_path, "rb").read(), [x])
        np.testing.assert_allclose(got, np.asarray(net(
            paddle.to_tensor(x))._data), atol=1e-5, rtol=1e-5)


class TestConvertToMixedPrecision:
    """VERDICT r2 weak #3: convert_to_mixed_precision actually casts the
    saved params to bf16 (artifact shrinks) and the converted model serves."""

    def _saved_net(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 8))
        net.eval()
        x_spec = paddle.to_tensor(np.zeros((2, 64), np.float32))
        prefix = str(tmp_path / "src")
        save_inference_model(prefix, [x_spec], None, layer=net)
        return net, prefix

    def test_params_cast_and_shrunk(self, tmp_path):
        from paddle_tpu.inference import convert_to_mixed_precision
        from paddle_tpu.static.io import _load_params_npz

        net, src = self._saved_net(tmp_path)
        dst = str(tmp_path / "dst")
        convert_to_mixed_precision(src, src, dst, dst)
        import ml_dtypes

        params = _load_params_npz(dst + ".pdiparams.npz")
        assert all(v.dtype == ml_dtypes.bfloat16 for v in params.values()
                   if np.issubdtype(np.asarray(v).dtype, np.floating)
                   or v.dtype == ml_dtypes.bfloat16)
        assert any(v.dtype == ml_dtypes.bfloat16 for v in params.values())
        src_sz = os.path.getsize(src + ".pdiparams.npz")
        dst_sz = os.path.getsize(dst + ".pdiparams.npz")
        assert dst_sz < 0.6 * src_sz, (src_sz, dst_sz)

    def test_converted_model_serves(self, tmp_path):
        from paddle_tpu.inference import (Config, Predictor,
                                          convert_to_mixed_precision)

        net, src = self._saved_net(tmp_path)
        dst = str(tmp_path / "dst")
        convert_to_mixed_precision(src, src, dst, dst)
        x = np.random.RandomState(0).randn(2, 64).astype(np.float32)
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        pred = Predictor(Config(model_path=dst))
        h = pred.get_input_handle("input_0")
        h.copy_from_cpu(x)
        (got,) = pred.run()
        # bf16 params: expect ~1e-2 relative agreement, not exactness
        np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.05)

    def test_in_place_conversion(self, tmp_path):
        from paddle_tpu.inference import convert_to_mixed_precision
        from paddle_tpu.static.io import _load_params_npz

        net, src = self._saved_net(tmp_path)
        import ml_dtypes

        convert_to_mixed_precision(src, src, src, src)  # src == dst
        params = _load_params_npz(src + ".pdiparams.npz")
        assert any(v.dtype == ml_dtypes.bfloat16 for v in params.values())

    def test_black_list_keeps_fp32(self, tmp_path):
        from paddle_tpu.inference import convert_to_mixed_precision
        from paddle_tpu.static.io import _load_params_npz

        net, src = self._saved_net(tmp_path)
        names = list(net.state_dict().keys())
        keep = names[0]
        dst = str(tmp_path / "dstb")
        convert_to_mixed_precision(src, src, dst, dst, black_list=[keep])
        params = _load_params_npz(dst + ".pdiparams.npz")
        assert params[keep].dtype == np.float32
