"""Inference export round trip: StableHLO text + jax.export AOT predictor
(static/io.py — save/load_inference_model + AnalysisPredictor analog)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static.io import (
    load_aot_predictor, load_inference_model, save_inference_model,
)


class TestInferenceExport:
    def _save(self, tmp_path):
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3))
        net.eval()
        x_spec = paddle.to_tensor(np.zeros((2, 6), np.float32))
        prefix = str(tmp_path / "infer_model")
        save_inference_model(prefix, [x_spec], None, layer=net)
        return net, prefix

    def test_stablehlo_text_exported(self, tmp_path):
        net, prefix = self._save(tmp_path)
        params, meta, hlo = load_inference_model(prefix)
        assert "stablehlo" in hlo or "func.func" in hlo
        assert meta["feed_shapes"] == [(2, 6)]
        assert any(k.endswith("weight") or "weight" in k for k in params)

    def test_aot_predictor_matches_layer(self, tmp_path):
        net, prefix = self._save(tmp_path)
        predict = load_aot_predictor(prefix)
        x = np.random.RandomState(0).randn(2, 6).astype(np.float32)
        out = predict(x)
        out = out[0] if isinstance(out, (tuple, list)) else out
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(np.asarray(out._data), ref, atol=1e-5)

    def test_aot_predictor_without_original_layer(self, tmp_path):
        """Deployment contract: predictor works with only the saved files
        (fresh state, no Layer object)."""
        _, prefix = self._save(tmp_path)
        predict = load_aot_predictor(prefix)
        x = np.ones((2, 6), np.float32)
        out = predict(paddle.to_tensor(x))
        out = out[0] if isinstance(out, (tuple, list)) else out
        assert tuple(out.shape) == (2, 3)
        assert np.isfinite(np.asarray(out._data)).all()

    def test_predictor_api_uses_aot_artifact(self, tmp_path):
        """inference.Predictor transparently loads the jax.export artifact."""
        from paddle_tpu.inference.predictor import Config, Predictor

        net, prefix = self._save(tmp_path)
        pred = Predictor(Config(model_path=prefix))
        assert pred._aot is not None  # AOT path, no pickled Layer needed
        x = np.random.RandomState(1).randn(2, 6).astype(np.float32)
        h = pred.get_input_handle("input_0")
        h.copy_from_cpu(x)
        out = pred.run()[0]
        ref = np.asarray(net(paddle.to_tensor(x))._data)
        np.testing.assert_allclose(out, ref, atol=1e-5)
