"""Tier-1 acceptance gates for elastic preemption-tolerant training
(ISSUE 19).

Three gates, all tier-1 (deliberately NOT marked ``slow``):

1. **Import pinning** (subprocess): with ``FLAGS_elastic`` unset, a
   plain trainer run never imports ``paddle_tpu.distributed.elastic``
   — the supervisor is manifest-lazy, the disarmed loss transcript is
   byte-identical across two runs of the same binary, and the
   construction-pinned ``_elastic_active`` check costs < 5µs/call.
2. **Reshard correctness**: a dp8 checkpoint (FLAGS_shard_weight_update
   [dp, shard] moments + FLAGS_quantized_allreduce error-feedback
   residuals) restored onto a dp4 trainer re-lays every sharded moment
   BIT-exactly to the numpy re-layout of the writer's shards, passes
   ``__step__`` through exactly, folds the EF residual into rank 0
   exactly (the one deliberate divergence from a from-scratch dp4
   gather: the writer's accumulated residual is conserved, not zeroed
   — rows 1..3 zero), and the restored trainer trains on.
3. **Chaos passes** (subprocess): ``tools/chaos_check.py --only
   elastic_resume --only stage_replace`` exits 0 — the kill/resume and
   stage-death/rebind recovery paths hold end to end.
"""
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags

REPO = Path(__file__).resolve().parent.parent

CFG = dict(vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
           max_seq_len=32, dropout=0.0)


def _build(ndp, lr=1e-2):
    import jax

    from paddle_tpu.distributed.mesh import build_mesh
    from paddle_tpu.distributed.spmd import SpmdTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainLoss)

    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(**CFG))
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters())
    return SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                       mesh=build_mesh((ndp,), ("dp",),
                                       devices=jax.devices()[:ndp]))


def _batches(steps, batch=8, seq=12):
    rng = np.random.RandomState(0)
    return [(rng.randint(0, 64, (batch, seq)).astype(np.int32),
             rng.randint(0, 64, (batch, seq)).astype(np.int32))
            for _ in range(steps)]


_GATE_CODE = r"""
import sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainLoss
import jax

paddle.seed(0)
model = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                 num_layers=1, num_heads=2,
                                 max_seq_len=32, dropout=0.0))
opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                             parameters=model.parameters())
tr = SpmdTrainer(model, opt, loss_fn=GPTPretrainLoss(),
                 mesh=build_mesh((1,), ("dp",), devices=jax.devices()[:1]))
rng = np.random.RandomState(0)
losses = []
for _ in range(2):
    x = rng.randint(0, 64, (2, 12)).astype(np.int32)
    y = rng.randint(0, 64, (2, 12)).astype(np.int32)
    losses.append(float(np.asarray(tr.train_step(x, y)._data)))
assert "paddle_tpu.distributed.elastic" not in sys.modules, \
    "plain trainer imported distributed.elastic"
print("TOKENS", [f"{l:.17g}" for l in losses])
print("GATE_OK")
"""


def test_plain_trainer_never_imports_elastic():
    """The disarmed path is structurally untouched: no elastic import
    and a byte-identical loss transcript across two runs."""
    outs = []
    for _ in range(2):
        r = subprocess.run([sys.executable, "-c", _GATE_CODE], cwd=REPO,
                           capture_output=True, text=True, timeout=560)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "GATE_OK" in r.stdout
        outs.append([l for l in r.stdout.splitlines()
                     if l.startswith("TOKENS")])
    assert outs[0] == outs[1]


def test_disarmed_elastic_check_under_5us():
    """The construction-pinned flag check on the hot path is one dict
    lookup + compare — the same bar monitor.is_enabled() holds."""
    tr = _build(1)
    tr.train_step(*_batches(1, batch=2)[0])   # settle compilation
    n = 20000
    t0 = time.perf_counter()
    for _ in range(n):
        tr._elastic_active()
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 5e-6, f"{per_call * 1e6:.2f}µs per disarmed check"


def test_dp8_checkpoint_reshards_onto_dp4():
    """dp8 -> dp4: every [dp, shard] moment re-lays BIT-exactly to the
    numpy re-layout of the writer's shards, ``__step__`` passes through
    exactly, and the EF residual folds into rank 0 exactly — the one
    declared divergence from a from-scratch dp4 gather (which would
    start the residual at zero; the fold conserves the writer's
    accumulated error feedback instead). The restored trainer then
    trains a finite step."""
    old = {k: flags.get_flag(k)
           for k in ("elastic", "shard_weight_update",
                     "quantized_allreduce")}
    paddle.set_flags({"elastic": True, "shard_weight_update": True,
                      "quantized_allreduce": True})
    try:
        data = _batches(3)
        tr8 = _build(8)
        for x, y in data[:2]:
            tr8.train_step(x, y)
        state8 = tr8.state_dict()
        src = state8["shard_specs"]
        assert src is not None and src["ndp"] == 8
        assert src["qar_eligible"], "no EF residuals to reshard"

        tr4 = _build(4)
        tr4.set_state_dict(tr8.state_dict())
        state4 = tr4.state_dict()
        dst = state4["shard_specs"]
        assert dst["ndp"] == 4

        # layout parity with a from-scratch dp4 gather: same keys, same
        # shard geometry
        scratch4 = _build(4)
        sc = scratch4.state_dict()
        assert set(state4["opt_state"]) == set(sc["opt_state"])
        assert dst["shard_ps"] == sc["shard_specs"]["shard_ps"]

        opt8, opt4 = state8["opt_state"], state4["opt_state"]
        assert np.asarray(opt4["__step__"]) \
            == np.asarray(opt8["__step__"])
        checked = 0
        for pname, slots in opt8.items():
            if pname in ("__step__", "__qar_residual__"):
                continue
            meta = src["params"][pname]
            ps8 = src["shard_ps"][pname]
            ps4 = dst["shard_ps"][pname]
            for skey in src["sharded_keys"].get(pname, ()):
                a8 = np.asarray(slots[skey])
                assert a8.shape == (8, ps8)
                logical = a8.reshape(-1)[:meta["size"]]
                expect = np.pad(logical, (0, ps4 * 4 - meta["size"]))
                expect = expect.reshape(4, ps4)
                np.testing.assert_array_equal(
                    np.asarray(opt4[pname][skey]), expect,
                    err_msg=f"{pname}/{skey} not bit-exact across "
                            "the dp8 -> dp4 re-layout")
                checked += 1
        assert checked > 0, "no sharded moments exercised"

        res8, res4 = opt8["__qar_residual__"], opt4["__qar_residual__"]
        for rname in src["qar_eligible"]:
            r8 = np.asarray(res8[rname])
            r4 = np.asarray(res4[rname])
            assert r8.shape[0] == 8 and r4.shape[0] == 4
            np.testing.assert_array_equal(
                r4[0], r8.sum(axis=0),
                err_msg=f"{rname}: residual fold into rank 0 diverged")
            np.testing.assert_array_equal(
                r4[1:], np.zeros_like(r4[1:]),
                err_msg=f"{rname}: non-root residual rows not zeroed")

        loss = float(np.asarray(tr4.train_step(*data[2])._data))
        assert np.isfinite(loss)
    finally:
        paddle.set_flags(old)


def test_chaos_elastic_passes_exit_zero():
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "chaos_check.py"),
         "--only", "elastic_resume", "--only", "stage_replace"],
        cwd=REPO, capture_output=True, text=True, timeout=560)
    assert r.returncode == 0, r.stdout + r.stderr
