"""Native C++ sparse PS table tests — semantics vs the Python SparseTable."""
import numpy as np
import pytest

from paddle_tpu.distributed.ps.native_table import NativeSparseTable, available
from paddle_tpu.distributed.ps.tables import SparseTable, make_sparse_table

pytestmark = pytest.mark.skipif(not available(), reason="g++ build unavailable")


class TestNativeSparseTable:
    def test_pull_initializes_deterministically(self):
        t = NativeSparseTable(8, init_scale=0.05, seed=42)
        rows = t.pull([5, 9, 5])
        assert rows.shape == (3, 8)
        np.testing.assert_array_equal(rows[0], rows[2])  # same id, same row
        assert (np.abs(rows) <= 0.05).all()
        assert np.abs(rows).max() > 0
        # insertion order must not matter
        t2 = NativeSparseTable(8, init_scale=0.05, seed=42)
        rows2 = t2.pull([9, 5])
        np.testing.assert_array_equal(rows2[1], rows[0])
        np.testing.assert_array_equal(rows2[0], rows[1])

    def test_sgd_matches_python_table(self):
        ids = np.array([1, 7, 1, 3], np.int64)
        grads = np.random.RandomState(0).randn(4, 4).astype(np.float32)
        nat = NativeSparseTable(4, optimizer="sgd", lr=0.1, initializer="zeros")
        py = SparseTable(4, optimizer="sgd", lr=0.1, initializer="zeros")
        nat.pull(ids)
        py.pull(ids)
        nat.push(ids, grads)
        py.push(ids, grads)
        np.testing.assert_allclose(nat.pull([1, 3, 7]), py.pull([1, 3, 7]),
                                   atol=1e-6)

    @pytest.mark.parametrize("opt", ["adagrad", "adam", "sum"])
    def test_optimizer_rules_match_python(self, opt):
        ids = np.arange(16, dtype=np.int64) % 5
        nat = NativeSparseTable(8, optimizer=opt, lr=0.05, initializer="zeros")
        py = SparseTable(8, optimizer=opt, lr=0.05, initializer="zeros")
        rng = np.random.RandomState(1)
        for _ in range(3):
            grads = rng.randn(16, 8).astype(np.float32)
            nat.push(ids, grads)
            py.push(ids, grads)
        np.testing.assert_allclose(nat.pull(np.arange(5)), py.pull(np.arange(5)),
                                   rtol=1e-5, atol=1e-6)

    def test_growth_many_rows(self):
        t = NativeSparseTable(4, initializer="zeros")
        ids = np.arange(20000, dtype=np.int64)
        t.push(ids, np.ones((20000, 4), np.float32))
        assert t.size() == 20000
        # every row got exactly one -lr*grad step
        np.testing.assert_allclose(t.pull([0, 19999]), -0.01 * np.ones((2, 4)),
                                   atol=1e-6)

    def test_get_rows_no_init(self):
        t = NativeSparseTable(4, initializer="zeros")
        t.pull([1])
        out = t.get_rows([1, 2])
        assert t.size() == 1  # id 2 was NOT created
        np.testing.assert_array_equal(out[1], np.zeros(4))

    def test_save_load_roundtrip(self, tmp_path):
        t = NativeSparseTable(8, optimizer="adam", lr=0.01, seed=7)
        ids = np.array([3, 1, 4, 1, 5], np.int64)
        t.push(ids, np.random.RandomState(2).randn(5, 8).astype(np.float32))
        before = t.pull([1, 3, 4, 5])
        path = str(tmp_path / "table.bin")
        t.save(path)

        t2 = NativeSparseTable(8, optimizer="adam", lr=0.01, seed=7)
        t2.load(path)
        assert t2.size() == t.size()
        np.testing.assert_array_equal(t2.pull([1, 3, 4, 5]), before)
        # optimizer slots restored: one more identical push stays identical
        g = np.ones((4, 8), np.float32)
        t.push([1, 3, 4, 5], g)
        t2.push([1, 3, 4, 5], g)
        np.testing.assert_allclose(t2.pull([1, 3, 4, 5]), t.pull([1, 3, 4, 5]),
                                   atol=1e-7)

    def test_factory_prefers_native(self):
        t = make_sparse_table(4)
        assert isinstance(t, NativeSparseTable)
        t2 = make_sparse_table(4, backend="python")
        assert isinstance(t2, SparseTable)

    def test_perf_native_faster_than_python(self):
        """The point of the C++ engine: batch push must beat the per-row
        Python loop comfortably (>=3x on a 50k-row push)."""
        import time

        n, dim = 50000, 16
        ids = np.random.RandomState(0).randint(0, 10000, n).astype(np.int64)
        grads = np.random.RandomState(1).randn(n, dim).astype(np.float32)

        nat = NativeSparseTable(dim, optimizer="adam", initializer="zeros")
        py = SparseTable(dim, optimizer="adam", initializer="zeros")
        nat.push(ids, grads)  # warm (allocates rows)
        py.push(ids, grads)

        t0 = time.perf_counter()
        nat.push(ids, grads)
        t_nat = time.perf_counter() - t0
        t0 = time.perf_counter()
        py.push(ids, grads)
        t_py = time.perf_counter() - t0
        assert t_nat * 3 < t_py, f"native {t_nat:.4f}s vs python {t_py:.4f}s"
