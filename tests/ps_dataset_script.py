"""Driver for test_ps_dataset: 2 servers + 2 workers; each worker loads ITS
OWN MultiSlot file, global-shuffles THROUGH the PS servers, then trains a
sparse-embedding model from the dataset (data_set.cc GlobalShuffle +
hogwild_worker.cc train-from-dataset loop parity)."""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.distributed_strategy import DistributedStrategy
from paddle_tpu.distributed.fleet.role_maker import PaddleCloudRoleMaker
from paddle_tpu.io.multislot import InMemoryDataset


def _write_slot_file(path, worker_id, n=32):
    """ids slot (int64, ragged) + src slot (float: which worker wrote it) +
    label slot (float)."""
    rng = np.random.RandomState(worker_id)
    lines = []
    for i in range(n):
        n_ids = rng.randint(1, 4)
        ids = rng.randint(0, 50, n_ids)
        label = float((ids.sum() % 2))
        lines.append(f"{n_ids} " + " ".join(map(str, ids))
                     + f" 1 {float(worker_id)} 1 {label}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def main():
    workdir = os.environ["PS_DATASET_DIR"]
    strategy = DistributedStrategy()
    strategy.a_sync = False
    fleet.init(role_maker=PaddleCloudRoleMaker(is_collective=False),
               is_collective=False, strategy=strategy)
    if fleet.is_server():
        fleet.init_server()
        fleet.run_server()
        return

    fleet.init_worker()
    client = fleet.ps_runtime.client
    wid = fleet.worker_index()
    wnum = fleet.worker_num()

    # each worker owns a disjoint file: global shuffle must MIX the sources
    my_file = os.path.join(workdir, f"slots.part-{wid}")
    _write_slot_file(my_file, wid)
    ds = InMemoryDataset()
    ds.add_slot("ids", "int64")
    ds.add_slot("src", "float32")
    ds.add_slot("label", "float32")
    ds.set_batch_size(8)
    ds.set_filelist([my_file])
    n_local = ds.load_into_memory()
    assert n_local == 32, n_local

    ds.global_shuffle(client=client, worker_id=wid, worker_num=wnum, seed=7)
    n_after = ds.get_memory_data_size()
    srcs = set()
    for batch in ds.batch_iter():
        srcs |= set(np.asarray(batch["src"]).ravel().tolist())
    assert srcs == {0.0, 1.0}, f"worker {wid} sees only sources {srcs}"
    print(f"GLOBAL_SHUFFLE_OK worker={wid} n_after={n_after}")

    # train a sparse-embedding model from the shuffled dataset via PS tables
    from paddle_tpu.distributed.ps.runtime import PsEmbedding
    from paddle_tpu.distributed.fleet.meta_optimizers import PsDenseOptimizer

    paddle.seed(0)
    emb = PsEmbedding(table_id=100, embedding_dim=8, client=client)
    head = paddle.nn.Linear(8, 1)
    opt = PsDenseOptimizer(head.parameters(), client, optimizer="sgd", lr=0.2)
    first = last = None
    for epoch in range(6):
        for batch in ds.batch_iter(return_mask=True):
            ids = paddle.to_tensor(batch["ids"])
            mask = paddle.to_tensor(batch["ids_mask"])
            label = paddle.to_tensor(batch["label"])
            e = emb(ids)  # [b, L, d]
            pooled = (e * mask.unsqueeze(-1)).sum(axis=1) / mask.sum(
                axis=1, keepdim=True)
            pred = head(pooled)
            loss = paddle.mean((pred - label) ** 2)
            loss.backward()
            opt.step()
            emb.push_step()
            opt.clear_grad()
            v = float(np.asarray(loss._data))
            first = v if first is None else first
            last = v
    assert last < first, (first, last)
    print(f"PS_DATASET_OK worker={wid} first={first:.4f} last={last:.4f}")
    fleet.stop_worker()


if __name__ == "__main__":
    main()
