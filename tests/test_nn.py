"""nn.Layer system + layers/functionals (fluid/dygraph/layers.py + nn layer tests
pattern from fluid/tests/unittests/test_layers.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def t(a, sg=True):
    return paddle.to_tensor(np.asarray(a, dtype=np.float32), stop_gradient=sg)


class TestLayerSystem:
    def test_param_registration(self):
        l = nn.Linear(4, 3)
        names = [n for n, _ in l.named_parameters()]
        assert set(names) == {"weight", "bias"}
        assert l.weight.shape == [4, 3]
        assert not l.weight.stop_gradient

    def test_sublayers_state_dict(self):
        class Net(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc1 = nn.Linear(4, 8)
                self.fc2 = nn.Linear(8, 2)

            def forward(self, x):
                return self.fc2(F.relu(self.fc1(x)))

        net = Net()
        sd = net.state_dict()
        assert "fc1.weight" in sd and "fc2.bias" in sd
        net2 = Net()
        net2.set_state_dict(sd)
        np.testing.assert_allclose(net2.fc1.weight.numpy(), net.fc1.weight.numpy())

    def test_train_eval_mode(self):
        l = nn.Sequential(nn.Linear(3, 3), nn.Dropout(0.5))
        l.eval()
        assert not l[1].training
        l.train()
        assert l[1].training

    def test_forward_hooks(self):
        l = nn.Linear(2, 2)
        calls = []
        h = l.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
        l(t(np.zeros((1, 2))))
        assert calls == [1]
        h.remove()
        l(t(np.zeros((1, 2))))
        assert calls == [1]

    def test_layerlist_parameterlist(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3
        assert len(list(ll.parameters())) == 6


class TestActivations:
    def test_relu_gelu_softmax(self):
        a = np.random.randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(F.relu(t(a)).numpy(), np.maximum(a, 0))
        s = F.softmax(t(a), axis=-1).numpy()
        np.testing.assert_allclose(s.sum(-1), np.ones(3), rtol=1e-5)
        assert F.gelu(t(a)).shape == [3, 4]
        np.testing.assert_allclose(F.sigmoid(t(a)).numpy(), 1 / (1 + np.exp(-a)), rtol=1e-5)

    def test_activation_layers(self):
        a = np.random.randn(2, 3).astype(np.float32)
        for cls in [nn.ReLU, nn.GELU, nn.Tanh, nn.Sigmoid, nn.Softmax, nn.LeakyReLU,
                    nn.ELU, nn.SELU, nn.Hardswish, nn.Silu, nn.Mish]:
            out = cls()(t(a))
            assert out.shape == [2, 3]
        p = nn.PReLU(num_parameters=3)
        assert p(t(np.random.randn(2, 3, 4, 4).astype(np.float32))).shape == [2, 3, 4, 4]


class TestLinearConv:
    def test_linear_matches_numpy(self):
        l = nn.Linear(4, 3)
        x = np.random.rand(5, 4).astype(np.float32)
        out = l(t(x))
        np.testing.assert_allclose(out.numpy(), x @ l.weight.numpy() + l.bias.numpy(), rtol=1e-5)

    def test_conv2d_shape_and_grad(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = t(np.random.rand(2, 3, 16, 16), sg=False)
        out = conv(x)
        assert out.shape == [2, 8, 8, 8]
        out.sum().backward()
        assert conv.weight.grad is not None
        assert x.grad.shape == [2, 3, 16, 16]

    def test_conv2d_vs_manual(self):
        conv = nn.Conv2D(1, 1, 2, bias_attr=False)
        x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
        w = conv.weight.numpy()
        out = conv(t(x)).numpy()
        expect = np.zeros((1, 1, 2, 2), np.float32)
        for i in range(2):
            for j in range(2):
                expect[0, 0, i, j] = (x[0, 0, i : i + 2, j : j + 2] * w[0, 0]).sum()
        np.testing.assert_allclose(out, expect, rtol=1e-5)

    def test_conv_transpose(self):
        convt = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1)
        x = t(np.random.rand(1, 4, 8, 8))
        assert convt(x).shape == [1, 2, 15, 15]

    def test_conv1d_3d(self):
        assert nn.Conv1D(2, 4, 3)(t(np.random.rand(1, 2, 10))).shape == [1, 4, 8]
        assert nn.Conv3D(1, 2, 2)(t(np.random.rand(1, 1, 4, 4, 4))).shape == [1, 2, 3, 3, 3]

    def test_grouped_conv(self):
        conv = nn.Conv2D(4, 8, 3, groups=2, padding=1)
        assert conv(t(np.random.rand(1, 4, 5, 5))).shape == [1, 8, 5, 5]
        assert conv.weight.shape == [8, 2, 3, 3]


class TestNorm:
    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = np.random.rand(4, 3, 5, 5).astype(np.float32) * 2 + 1
        bn.train()
        out = bn(t(x)).numpy()
        np.testing.assert_allclose(out.mean((0, 2, 3)), np.zeros(3), atol=1e-4)
        np.testing.assert_allclose(out.std((0, 2, 3)), np.ones(3), atol=1e-2)
        assert bn._mean.numpy().mean() != 0  # running stats updated
        bn.eval()
        out2 = bn(t(x))
        assert out2.shape == [4, 3, 5, 5]

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = np.random.rand(2, 4, 8).astype(np.float32)
        out = ln(t(x)).numpy()
        np.testing.assert_allclose(out.mean(-1), np.zeros((2, 4)), atol=1e-5)

    def test_groupnorm_instancenorm(self):
        gn = nn.GroupNorm(2, 4)
        assert gn(t(np.random.rand(2, 4, 3, 3))).shape == [2, 4, 3, 3]
        inorm = nn.InstanceNorm2D(4)
        assert inorm(t(np.random.rand(2, 4, 3, 3))).shape == [2, 4, 3, 3]


class TestPooling:
    def test_maxpool_avgpool(self):
        x = np.random.rand(1, 2, 8, 8).astype(np.float32)
        mp = nn.MaxPool2D(2, 2)(t(x)).numpy()
        assert mp.shape == (1, 2, 4, 4)
        np.testing.assert_allclose(mp[0, 0, 0, 0], x[0, 0, :2, :2].max())
        ap = nn.AvgPool2D(2, 2)(t(x)).numpy()
        np.testing.assert_allclose(ap[0, 0, 0, 0], x[0, 0, :2, :2].mean(), rtol=1e-5)

    def test_adaptive_pools(self):
        x = t(np.random.rand(1, 3, 7, 9))
        assert nn.AdaptiveAvgPool2D((2, 3))(x).shape == [1, 3, 2, 3]
        assert nn.AdaptiveMaxPool2D(1)(x).shape == [1, 3, 1, 1]
        g = nn.AdaptiveAvgPool2D(1)(x).numpy()
        np.testing.assert_allclose(g[:, :, 0, 0], np.asarray(x.numpy()).mean((2, 3)), rtol=1e-5)


class TestLosses:
    def test_cross_entropy(self):
        logits = np.random.rand(4, 5).astype(np.float32)
        labels = np.array([0, 1, 2, 3])
        loss = F.cross_entropy(t(logits), paddle.to_tensor(labels))
        # numpy reference
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        expect = -np.log(p[np.arange(4), labels]).mean()
        np.testing.assert_allclose(float(loss.numpy()), expect, rtol=1e-5)

    def test_cross_entropy_soft_and_smoothing(self):
        logits = np.random.rand(4, 5).astype(np.float32)
        soft = np.random.dirichlet(np.ones(5), 4).astype(np.float32)
        l1 = F.cross_entropy(t(logits), t(soft), soft_label=True)
        assert l1.ndim == 0
        l2 = F.cross_entropy(t(logits), paddle.to_tensor(np.array([0, 1, 2, 3])), label_smoothing=0.1)
        assert l2.ndim == 0

    def test_mse_l1_bce(self):
        a = np.random.rand(6).astype(np.float32)
        b = np.random.rand(6).astype(np.float32)
        np.testing.assert_allclose(float(F.mse_loss(t(a), t(b)).numpy()), ((a - b) ** 2).mean(), rtol=1e-5)
        np.testing.assert_allclose(float(F.l1_loss(t(a), t(b)).numpy()), np.abs(a - b).mean(), rtol=1e-5)
        p = np.clip(a, 0.01, 0.99)
        y = (b > 0.5).astype(np.float32)
        bce = -(y * np.log(p) + (1 - y) * np.log(1 - p)).mean()
        np.testing.assert_allclose(float(F.binary_cross_entropy(t(p), t(y)).numpy()), bce, rtol=1e-4)

    def test_loss_layers(self):
        logits = t(np.random.rand(4, 5))
        labels = paddle.to_tensor(np.array([0, 1, 2, 3]))
        assert nn.CrossEntropyLoss()(logits, labels).ndim == 0
        assert nn.MSELoss()(logits, t(np.random.rand(4, 5))).ndim == 0

    def test_ctc_loss_smoke(self):
        T, B, C, S = 8, 2, 5, 3
        lp = t(np.random.rand(T, B, C), sg=False)
        labels = paddle.to_tensor(np.random.randint(1, C, (B, S)))
        in_len = paddle.to_tensor(np.array([T, T]))
        lab_len = paddle.to_tensor(np.array([S, S - 1]))
        loss = F.ctc_loss(lp, labels, in_len, lab_len)
        assert float(loss.numpy()) > 0
        loss.backward()
        assert lp.grad is not None


class TestEmbeddingDropout:
    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])

    def test_embedding_grad_sparse_rows(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor(np.array([0, 0, 5]))
        emb(ids).sum().backward()
        g = emb.weight.grad.numpy()
        np.testing.assert_allclose(g[0], 2 * np.ones(4))
        np.testing.assert_allclose(g[1], np.zeros(4))

    def test_dropout(self, seed):
        x = t(np.ones((100, 100)))
        d = nn.Dropout(0.5)
        out = d(x).numpy()
        assert 0.3 < (out == 0).mean() < 0.7
        np.testing.assert_allclose(out[out != 0], 2.0 * np.ones_like(out[out != 0]), rtol=1e-6)
        d.eval()
        np.testing.assert_allclose(d(x).numpy(), x.numpy())


class TestRNN:
    def test_lstm_cell_and_net(self):
        cell = nn.LSTMCell(4, 8)
        h, (h2, c2) = cell(t(np.random.rand(2, 4)))
        assert h.shape == [2, 8] and c2.shape == [2, 8]
        lstm = nn.LSTM(4, 8, num_layers=2)
        out, (h, c) = lstm(t(np.random.rand(2, 5, 4)))
        assert out.shape == [2, 5, 8]
        assert h.shape == [2, 2, 8]

    def test_gru_simple_rnn(self):
        gru = nn.GRU(4, 6)
        out, h = gru(t(np.random.rand(3, 7, 4)))
        assert out.shape == [3, 7, 6] and h.shape == [1, 3, 6]
        rnn = nn.SimpleRNN(4, 6, direction="bidirect")
        out, h = rnn(t(np.random.rand(3, 7, 4)))
        assert out.shape == [3, 7, 12]

    def test_rnn_grad_flows(self):
        lstm = nn.LSTM(3, 4)
        x = t(np.random.rand(2, 6, 3), sg=False)
        out, _ = lstm(x)
        out.sum().backward()
        assert x.grad is not None
        assert lstm.rnns[0].cell.weight_ih.grad is not None


class TestTransformer:
    def test_mha_shapes(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = t(np.random.rand(2, 5, 16))
        assert mha(x).shape == [2, 5, 16]

    def test_encoder_decoder(self):
        enc_l = nn.TransformerEncoderLayer(16, 4, 32)
        enc = nn.TransformerEncoder(enc_l, 2)
        src = t(np.random.rand(2, 6, 16))
        mem = enc(src)
        assert mem.shape == [2, 6, 16]
        dec_l = nn.TransformerDecoderLayer(16, 4, 32)
        dec = nn.TransformerDecoder(dec_l, 2)
        tgt = t(np.random.rand(2, 4, 16))
        assert dec(tgt, mem).shape == [2, 4, 16]

    def test_full_transformer_with_mask(self):
        model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32)
        src = t(np.random.rand(2, 5, 16))
        tgt = t(np.random.rand(2, 3, 16))
        mask = model.generate_square_subsequent_mask(3)
        out = model(src, tgt, tgt_mask=mask)
        assert out.shape == [2, 3, 16]

    def test_causal_mask_effect(self):
        # with a causal mask, output at position 0 must not depend on position 2
        mha = nn.MultiHeadAttention(8, 2)
        mha.eval()
        x1 = np.random.rand(1, 3, 8).astype(np.float32)
        x2 = x1.copy()
        x2[0, 2] += 1.0
        mask = np.triu(np.full((3, 3), -1e9, np.float32), 1)
        o1 = mha(t(x1), attn_mask=t(mask)).numpy()
        o2 = mha(t(x2), attn_mask=t(mask)).numpy()
        np.testing.assert_allclose(o1[0, 0], o2[0, 0], atol=1e-5)


class TestPadInterp:
    def test_pad(self):
        x = t(np.random.rand(1, 2, 3, 3))
        assert F.pad(x, [1, 1, 2, 2]).shape == [1, 2, 7, 5]
        assert F.pad(x, [1, 0], mode="reflect").shape == [1, 2, 3, 4]

    def test_interpolate(self):
        x = t(np.random.rand(1, 2, 4, 4))
        assert F.interpolate(x, size=[8, 8]).shape == [1, 2, 8, 8]
        assert F.interpolate(x, scale_factor=0.5, mode="bilinear").shape == [1, 2, 2, 2]
        up = nn.Upsample(scale_factor=2, mode="nearest")
        np.testing.assert_allclose(
            up(x).numpy()[0, 0, ::2, ::2], x.numpy()[0, 0], rtol=1e-6
        )

    def test_one_hot_label_smooth(self):
        oh = F.one_hot(paddle.to_tensor(np.array([0, 2])), 3).numpy()
        np.testing.assert_allclose(oh, [[1, 0, 0], [0, 0, 1]])


class TestClip:
    def test_global_norm_clip(self):
        from paddle_tpu.nn import ClipGradByGlobalNorm

        p = paddle.ParamBase(np.ones(4, np.float32))
        g = paddle.to_tensor(np.full(4, 10.0, np.float32))
        clip = ClipGradByGlobalNorm(1.0)
        (_, g2), = clip([(p, g)])
        np.testing.assert_allclose(np.linalg.norm(g2.numpy()), 1.0, rtol=1e-5)
