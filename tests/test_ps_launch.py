"""Subprocess PS-cluster launch test (test_fleet_launch_ps.sh /
test_dist_base.py analog): real server + trainer processes via the fleetrun
launcher's PS path."""
import os
import subprocess
import sys


def test_fleetrun_ps_mode(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "tests", "ps_launch_script.py")
    log_dir = str(tmp_path / "logs")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.fleet.launch",
         "--server_num", "2", "--worker_num", "2", "--log_dir", log_dir, script],
        cwd=repo, env=env, timeout=240, capture_output=True, text=True)
    worker_logs = ""
    for i in range(2):
        with open(os.path.join(log_dir, f"workerlog.{i}")) as f:
            worker_logs += f.read()
    assert proc.returncode == 0, (proc.stdout, proc.stderr, worker_logs)
    assert worker_logs.count("PS_LAUNCH_OK") == 2, worker_logs
