"""Tier-1 gate for the perf ledger (ISSUE 17): with FLAGS_perf_ledger
unset, training is EXACTLY the pre-PR path — paddle_tpu.monitor.
perfledger is never imported (subprocess pin), trained params are
byte-identical whether or not the armed ledger was ever exercised in
the same process (the ledger is NON-structural: it observes host-side
timings and joins no executable key), no perf_ledger_rows_total /
perf_regression_total series appears, and the disarmed per-step hook
costs the same one-lookup bar as every other disabled fast path. Plus
the tools/perf_report.py exit-code contract: --check against an empty
ledger is a loud error, --calibrate emits a table plan_search
--calibrated can price with."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import flags, monitor
from paddle_tpu.distributed.mesh import build_mesh
from paddle_tpu.distributed.spmd import SpmdTrainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: metric families this PR introduced — with the flag unset NONE may move
LEDGER_FAMILIES = ("perf_ledger_rows_total", "perf_regression_total")


def _tiny_dp():
    from paddle_tpu import nn

    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
    return SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)


_PLAIN_TRAIN = (
    "import os, tempfile\n"
    "os.environ.setdefault('XLA_FLAGS',\n"
    "    '--xla_force_host_platform_device_count=8')\n"
    "import jax; jax.config.update('jax_platforms', 'cpu')\n"
    "import hashlib\n"
    "import numpy as np\n"
    "import paddle_tpu as paddle\n"
    "from paddle_tpu import nn\n"
    "from paddle_tpu.distributed.mesh import build_mesh\n"
    "from paddle_tpu.distributed.spmd import SpmdTrainer\n"
    "def run():\n"
    "    paddle.seed(0)\n"
    "    net = nn.Linear(8, 4)\n"
    "    opt = paddle.optimizer.SGD(learning_rate=0.1,\n"
    "                               parameters=net.parameters())\n"
    "    mesh = build_mesh((1,), ('dp',), devices=jax.devices()[:1])\n"
    "    tr = SpmdTrainer(net, opt, loss_fn=nn.MSELoss(), mesh=mesh)\n"
    "    rng = np.random.RandomState(0)\n"
    "    for _ in range(3):\n"
    "        tr.train_step(rng.rand(4, 8).astype(np.float32),\n"
    "                      rng.rand(4, 4).astype(np.float32))\n"
    "    h = hashlib.sha256()\n"
    "    for k in sorted(tr.params):\n"
    "        h.update(np.ascontiguousarray(\n"
    "            np.asarray(tr.params[k])).tobytes())\n"
    "    return h.hexdigest()\n")


def _run(code):
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-2000:]
    return out.stdout


class TestInertByDefault:
    @pytest.mark.slow
    def test_plain_subprocess_never_imports_ledger_and_pins_params(self):
        """The zero-overhead pin, in one subprocess: plain runs (a)
        never import monitor.perfledger, and (b) train byte-identical
        params before vs after an ARMED run in the same process — and
        the armed run itself matches, because the ledger never touches
        the compiled program (non-structural)."""
        _run(
            _PLAIN_TRAIN +
            "h1 = run()\n"
            "import sys\n"
            "assert 'paddle_tpu.monitor.perfledger' not in sys.modules,\\\n"
            "    'perfledger imported on the plain path'\n"
            "path = tempfile.mktemp(suffix='.jsonl')\n"
            "paddle.set_flags({'perf_ledger': True,\n"
            "                  'perf_ledger_path': path,\n"
            "                  'perf_ledger_interval': 1})\n"
            "h_armed = run()\n"
            "assert 'paddle_tpu.monitor.perfledger' in sys.modules\n"
            "from paddle_tpu.monitor import perfledger\n"
            "rows = perfledger.load_rows(path)\n"
            "assert rows and rows[0]['site'] == 'trainer', rows[:1]\n"
            "assert h_armed == h1, ('armed params are not byte-identical'\n"
            "    ' — the ledger leaked into the compiled step')\n"
            "paddle.set_flags({'perf_ledger': False,\n"
            "                  'perf_ledger_path': ''})\n"
            "perfledger.reset_ledger()\n"
            "h2 = run()\n"
            "assert h1 == h2, ('flag-unset params drifted after the '\n"
            "    'armed ledger was exercised in-process')\n"
            "os.unlink(path)\n"
            "print('OK')\n")

    def test_flag_unset_zero_series(self):
        """In-process: a flag-unset run grows no ledger-PR series."""
        monitor.reset()
        tr = _tiny_dp()
        rng = np.random.RandomState(0)
        for _ in range(2):
            tr.train_step(rng.rand(4, 8).astype(np.float32),
                          rng.rand(4, 4).astype(np.float32))
        assert tr._perf_ledger is None
        flat = monitor.flatten(monitor.snapshot())
        # earlier tests in the same process may have left the (zeroed)
        # family registered — drift means a series actually moved
        ledger_series = [k for k, v in flat.items()
                         if k.startswith(LEDGER_FAMILIES) and v]
        assert not ledger_series, ledger_series

    def test_disarmed_flag_checks_under_5us(self):
        """The flag-unset per-step addition is one `is not None` on a
        construction-consumed attribute (plus the one get_flag lookup
        at construction) — bounded at the same bar as every other
        disabled fast path."""
        tr = _tiny_dp()
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            tr._perf_ledger is not None
            flags.get_flag("perf_ledger", False)
        per_call_us = (time.perf_counter() - t0) / (2 * n) * 1e6
        assert per_call_us < 5.0, (
            f"disarmed perf-ledger check costs {per_call_us:.2f}us")

    def test_flags_defined_and_default_off(self):
        assert flags.get_flag("perf_ledger") is False
        assert flags.get_flag("perf_ledger_path") == ""
        assert flags.get_flag("perf_ledger_sigma") == 4.0
        assert flags.get_flag("perf_ledger_warmup") == 5
        assert flags.get_flag("perf_ledger_interval") == 1


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.pop(name, None)
    spec.loader.exec_module(mod)
    return mod


class TestPerfReportGate:
    def test_check_empty_ledger_exits_1(self, capsys, tmp_path):
        """--check against a missing/empty ledger is a loud error
        (perf-ledger-empty), never a silent green."""
        pr = _load_tool("perf_report")
        rc = pr.main(["--check", "--path",
                      str(tmp_path / "missing.jsonl"), "--json"])
        assert rc == 1
        report = json.loads(capsys.readouterr().out)
        msgs = [f for f in report["targets"]["check"]["findings"]
                if f["pass"] == "perf-ledger-empty"]
        assert msgs and msgs[0]["severity"] == "error"

    def test_calibrate_table_prices_plan_search(self, capsys, tmp_path):
        """--calibrate over synthetic rows emits a constants table that
        CostModel(constants=) / plan_search --calibrated can consume."""
        from paddle_tpu.analysis import calibrate
        from paddle_tpu.monitor import perfledger as pl

        path, out = str(tmp_path / "l.jsonl"), str(tmp_path / "t.json")
        env = pl.env_fingerprint()
        for i in range(6):
            pl.append_row(path, {
                "v": pl.SCHEMA_VERSION, "ts": float(i), "site": "trainer",
                "sig": "s", "mesh": None, "env": env,
                "metrics": {"step_ms": 4.0, "exec_ms": 4.0,
                            "flops_per_step": 1e9,
                            "bytes_per_step": 1e8}})
        pr = _load_tool("perf_report")
        rc = pr.main(["--calibrate", "--path", path, "--out", out,
                      "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["targets"]["calibrate"]["counts"]["error"] == 0
        table = calibrate.load_table(out)
        constants = calibrate.constants_for_cost_model(table)
        # 1e9 flops in 4ms -> 2.5e11 flops/s, exactly
        assert constants["peak_flops"] == pytest.approx(2.5e11)
        assert constants["hbm_bandwidth"] == pytest.approx(2.5e10)
        ps = _load_tool("plan_search")
        report, results = ps.build_report(["gpt"], calibrated=out)
        assert report["totals"]["error"] == 0
        assert report["calibration"]["constants"][
            "peak_flops"] == pytest.approx(2.5e11)
        assert results["gpt"].ranked

    @pytest.mark.slow
    def test_record_then_check_contract_subprocess(self):
        """The acceptance loop, end to end in one subprocess: --record
        appends rows; a clean --check exits 0; a --check with a planted
        in-window slowdown exits 1 and names trainer/step_ms."""
        tool = os.path.join(REPO, "tools", "perf_report.py")
        import tempfile

        path = tempfile.mktemp(suffix=".jsonl")
        try:
            for _ in range(2):
                out = subprocess.run(
                    [sys.executable, tool, "--record", "--steps", "6",
                     "--path", path],
                    cwd=REPO, capture_output=True, text=True,
                    timeout=560)
                assert out.returncode == 0, out.stderr[-2000:]
            out = subprocess.run(
                [sys.executable, tool, "--check", "--steps", "6",
                 "--path", path, "--json"],
                cwd=REPO, capture_output=True, text=True, timeout=560)
            assert out.returncode == 0, \
                out.stdout[-2000:] + out.stderr[-2000:]
            out = subprocess.run(
                [sys.executable, tool, "--check", "--steps", "6",
                 "--path", path, "--inject", "trainer/batch=delay:400",
                 "--json"],
                cwd=REPO, capture_output=True, text=True, timeout=560)
            assert out.returncode == 1, \
                out.stdout[-2000:] + out.stderr[-2000:]
            report = json.loads(out.stdout)
            msgs = [f["message"]
                    for f in report["targets"]["check"]["findings"]
                    if f["pass"] == "perf-regression"]
            assert any("trainer/step_ms" in m for m in msgs), msgs
        finally:
            if os.path.exists(path):
                os.unlink(path)

    @pytest.mark.slow
    def test_metrics_dump_ledger_green_subprocess(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "metrics_dump.py"),
             "--ledger", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=560)
        assert out.returncode == 0, out.stderr[-2000:]
        report = json.loads(out.stdout)
        assert report["totals"]["error"] == 0
