"""Auto-checkpoint kill-restart e2e (reference proves this with
fluid/tests/unittests/test_auto_checkpoint*.py kill tests over
auto_checkpoint.py:265 TrainEpochRange).

A training subprocess is SIGKILLed mid-epoch; a restarted process must
resume at the first uncommitted epoch with bit-exact model AND optimizer
state — asserted the strongest way: the killed+resumed run's final
(params, Adam moments) hash equals an uninterrupted control run's.
"""
import os
import signal
import subprocess
import sys

import pytest

import paddle_tpu as paddle

WORKER = r'''
import os, sys, signal, hashlib
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate.checkpoint.auto_checkpoint import TrainEpochRange

save_dir, kill_epoch = sys.argv[1], int(sys.argv[2])
paddle.seed(0)
net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
loss_fn = nn.CrossEntropyLoss()
tr = TrainEpochRange(5, "killtest", save_dir=save_dir)
tr.add(layer=net, optimizer=opt)
print("START_EPOCH", tr._start_epoch, flush=True)
for epoch in tr:
    rng = np.random.RandomState(epoch)   # per-epoch deterministic data
    x = paddle.to_tensor(rng.randn(16, 8).astype(np.float32))
    y = paddle.to_tensor(rng.randint(0, 4, (16,)).astype(np.int64))
    for step in range(3):
        loss = loss_fn(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        if epoch == kill_epoch and step == 1:
            os.kill(os.getpid(), signal.SIGKILL)   # hard death mid-epoch

def blob(d, out):
    for k in sorted(d):
        v = d[k]
        if isinstance(v, dict):
            blob(v, out)
        else:
            a = np.asarray(v._data if hasattr(v, "_data") else v)
            out.append(np.ascontiguousarray(a).tobytes())

parts = []
blob(net.state_dict(), parts)
blob(opt.state_dict(), parts)
print("FINAL_HASH", hashlib.sha256(b"".join(parts)).hexdigest(), flush=True)
'''


def _run_worker(tmp_path, save_dir, kill_epoch):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    repo_root = os.path.dirname(os.path.dirname(paddle.__file__))
    env = dict(os.environ, PYTHONPATH=repo_root + (
        os.pathsep + os.environ["PYTHONPATH"]
        if os.environ.get("PYTHONPATH") else ""))
    env.pop("PADDLE_JOB_ID", None)   # pin the default_job path the test reads
    return subprocess.run(
        [sys.executable, str(script), str(save_dir), str(kill_epoch)],
        capture_output=True, text=True, timeout=300, env=env)


def _field(out, key):
    for line in out.splitlines():
        if line.startswith(key):
            return line.split()[1]
    raise AssertionError(f"{key} not in output:\n{out}")


@pytest.mark.slow
def test_sigkill_mid_epoch_resumes_bit_exact(tmp_path):
    killed_dir = tmp_path / "killed"
    control_dir = tmp_path / "control"

    # 1. train; SIGKILL mid-epoch-2 (epochs 0 and 1 committed)
    res = _run_worker(tmp_path, killed_dir, kill_epoch=2)
    assert res.returncode == -signal.SIGKILL, (res.returncode, res.stderr)
    assert _field(res.stdout, "START_EPOCH") == "0"

    # the partial epoch must NOT have committed a checkpoint
    from paddle_tpu.incubate.checkpoint.auto_checkpoint import \
        CheckpointSaver

    saver = CheckpointSaver(str(killed_dir / "default_job" / "killtest"))
    _, meta = saver.load_checkpoint()
    assert meta["epoch"] == 1

    # 2. restart: resumes at the first uncommitted epoch and finishes
    res2 = _run_worker(tmp_path, killed_dir, kill_epoch=-1)
    assert res2.returncode == 0, res2.stderr[-1500:]
    assert _field(res2.stdout, "START_EPOCH") == "2"
    resumed_hash = _field(res2.stdout, "FINAL_HASH")

    # 3. uninterrupted control run: the resumed trajectory must be
    # BIT-EXACT — params and Adam moments identical
    res3 = _run_worker(tmp_path, control_dir, kill_epoch=-1)
    assert res3.returncode == 0, res3.stderr[-1500:]
    assert _field(res3.stdout, "START_EPOCH") == "0"
    assert resumed_hash == _field(res3.stdout, "FINAL_HASH")


@pytest.mark.slow
def test_completed_run_restart_is_noop(tmp_path):
    done_dir = tmp_path / "done"
    res = _run_worker(tmp_path, done_dir, kill_epoch=-1)
    assert res.returncode == 0, res.stderr[-1500:]
    # all 5 epochs committed: a restart has nothing left to train
    res2 = _run_worker(tmp_path, done_dir, kill_epoch=-1)
    assert res2.returncode == 0, res2.stderr[-1500:]
    assert _field(res2.stdout, "START_EPOCH") == "5"
