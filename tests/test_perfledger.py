"""Perf-ledger unit tests (ISSUE 17): JSONL row schema + torn-tail
recovery, env-fingerprint gating of baselines, the regression
sentinel's direction/latch/negative behavior, least-squares calibration
recovering planted constants, and CostModel(constants=) actually
re-pricing the plan ranking."""
import json
import math
import os

import numpy as np
import pytest

from paddle_tpu import flags
from paddle_tpu.analysis import calibrate, cost_model, plan_search
from paddle_tpu.monitor import perfledger as pl


def _row(site="trainer", env=None, **metrics):
    return {"v": pl.SCHEMA_VERSION, "ts": 0.0, "site": site, "sig": None,
            "mesh": None, "env": env or pl.env_fingerprint(),
            "metrics": metrics}


def _ledger(tmp_path, warmup=3, sigma=4.0, interval=1):
    old = {k: flags.get_flag(k) for k in
           ("perf_ledger_warmup", "perf_ledger_sigma",
            "perf_ledger_interval")}
    flags.set_flags({"perf_ledger_warmup": warmup,
                     "perf_ledger_sigma": sigma,
                     "perf_ledger_interval": interval})
    try:
        return pl.PerfLedger(path=str(tmp_path / "ledger.jsonl"))
    finally:
        flags.set_flags(old)


class TestRows:
    def test_row_roundtrip_sanitizes_and_sorts(self, tmp_path):
        """One row, one line: numpy scalars become floats, non-finite
        values become null, foreign-schema rows are skipped on load."""
        path = str(tmp_path / "l.jsonl")
        pl.append_row(path, _row(step_ms=np.float32(4.25),
                                 mfu=float("nan"), cold=1))
        pl.append_row(path, dict(_row(step_ms=1.0), v=99))  # foreign
        rows = pl.load_rows(path)
        assert len(rows) == 1
        m = rows[0]["metrics"]
        assert m["step_ms"] == 4.25 and isinstance(m["step_ms"], float)
        assert m["mfu"] is None
        assert m["cold"] == 1
        # one JSON object per line, stable key order
        with open(path) as f:
            first = f.readline()
        assert json.loads(first)["site"] == "trainer"
        assert first.index('"env"') < first.index('"metrics"')

    def test_torn_tail_and_noise_skipped(self, tmp_path):
        """A killed writer's partial last line (and blank/garbage lines)
        never poison the readable prefix."""
        path = str(tmp_path / "l.jsonl")
        for i in range(3):
            pl.append_row(path, _row(step_ms=float(i)))
        with open(path, "a") as f:
            f.write("\n")
            f.write('{"v": 1, "site": "trainer", "metr')  # torn tail
        rows = pl.load_rows(path)
        assert [r["metrics"]["step_ms"] for r in rows] == [0.0, 1.0, 2.0]
        assert pl.tail(path, 2)[-1]["metrics"]["step_ms"] == 2.0
        assert pl.load_rows(str(tmp_path / "absent.jsonl")) == []

    def test_append_failure_drops_telemetry_not_the_step(self, tmp_path):
        """A revoked path swallows the OSError — the observed step must
        never pay for its own telemetry."""
        led = _ledger(tmp_path)
        led.path = str(tmp_path / "no" / "such" / "dir" / "l.jsonl")
        led.on_step("trainer", {"step_ms": 4.0})
        assert led.rows_written == 0
        assert led._last_row["trainer"]["metrics"]["step_ms"] == 4.0


class TestBaselines:
    def test_fingerprint_gates_foreign_rows(self):
        """A cross-machine row must never tighten this machine's
        floors: only rows whose CORE fingerprint matches fold in."""
        here = pl.env_fingerprint()
        there = dict(here, jax="9.9.99")
        rows = [_row(step_ms=4.0), _row(step_ms=4.0),
                _row(step_ms=400.0, env=there)]
        base = pl.baselines(rows)
        assert base[("trainer", "step_ms")].n == 2
        assert base[("trainer", "step_ms")].mean == pytest.approx(4.0)
        # ...and nothing folds under the foreign fingerprint's key
        assert pl.baselines(rows, env=there)[
            ("trainer", "step_ms")].n == 1

    def test_cold_and_nonsentinel_rows_stay_out(self):
        """Compile-resolving windows (cold) and direction-less metrics
        (dispatch_fraction) are recorded in rows but never baselined."""
        rows = [_row(step_ms=4.0, dispatch_fraction=0.9),
                _row(step_ms=4000.0, cold=1)]
        base = pl.baselines(rows)
        assert base[("trainer", "step_ms")].n == 1
        assert ("trainer", "dispatch_fraction") not in base

    def test_check_value_direction_and_floor(self):
        ema = pl.Ema()
        for _ in range(5):
            ema.update(4.0)
        regressed, excess = pl.check_value(ema, "step_ms", 400.0, 4.0)
        assert regressed and excess > 4.0
        assert not pl.check_value(ema, "step_ms", 4.1, 4.0)[0]
        # LOW_IS_BAD flips the direction: a HIGHER mfu is never a
        # regression, a collapsed one is
        for _ in range(5):
            ema.update(4.0)
        assert not pl.check_value(ema, "mfu", 8.0, 4.0)[0]
        assert pl.check_value(ema, "mfu", 0.1, 4.0)[0]


class TestSentinel:
    def test_regression_fires_once_per_episode(self, tmp_path):
        """Positive: a planted slowdown past warmup fires exactly one
        (site, metric)-named record; sustained breach stays latched; a
        return to band re-arms."""
        led = _ledger(tmp_path, warmup=3, sigma=4.0)
        for _ in range(4):
            assert led.on_step("trainer", {"step_ms": 4.0}) == []
        fired = led.on_step("trainer", {"step_ms": 400.0})
        assert [(f["site"], f["metric"]) for f in fired] == \
            [("trainer", "step_ms")]
        assert fired[0]["value"] == 400.0
        # latched: the sustained breach is one episode, not one per step
        assert led.on_step("trainer", {"step_ms": 400.0}) == []
        # the breach never dragged the baseline up to meet it
        assert led._ema[("trainer", "step_ms")].mean == pytest.approx(4.0)
        for _ in range(2):
            assert led.on_step("trainer", {"step_ms": 4.0}) == []
        assert led.on_step("trainer", {"step_ms": 400.0})  # re-armed
        assert len(pl.load_rows(led.path)) == 9

    def test_negative_no_fire_in_band_or_during_warmup(self, tmp_path):
        led = _ledger(tmp_path, warmup=3)
        assert led.on_step("trainer", {"step_ms": 900.0}) == []  # warmup
        led = _ledger(tmp_path, warmup=3)
        vals = [4.0, 4.2, 3.9, 4.1, 4.05, 3.95, 4.15]
        assert all(led.on_step("trainer", {"step_ms": v}) == []
                   for v in vals)

    def test_cold_step_skips_check_but_lands_row(self, tmp_path):
        led = _ledger(tmp_path, warmup=2)
        for _ in range(3):
            led.on_step("trainer", {"step_ms": 4.0})
        fired = led.on_step("trainer", {"step_ms": 4000.0, "cold": 1},
                            check=False)
        assert fired == []
        assert pl.load_rows(led.path)[-1]["metrics"]["cold"] == 1
        # the steady-state baseline survived the compile window
        assert led._ema[("trainer", "step_ms")].mean == pytest.approx(4.0)

    def test_interval_thins_rows_not_the_sentinel(self, tmp_path):
        led = _ledger(tmp_path, interval=3)
        for i in range(6):
            led.on_step("trainer", {"step_ms": 4.0})
        assert len(pl.load_rows(led.path)) == 2
        assert led._ema[("trainer", "step_ms")].n == 6
        led.on_step("trainer", {"step_ms": 4.0}, force=True)
        assert len(pl.load_rows(led.path)) == 3

    def test_snapshot_is_bundle_fodder(self, tmp_path):
        led = _ledger(tmp_path, warmup=2)
        for _ in range(3):
            led.on_step("trainer", {"step_ms": 4.0})
        led.on_step("trainer", {"step_ms": 400.0})
        snap = led.snapshot()
        assert snap["rows_written"] == 4
        assert snap["sites"] == {"trainer": 4}
        assert snap["regressions"][-1]["metric"] == "step_ms"
        assert snap["tail"]
        json.dumps(snap)  # bundle-safe


class TestCalibration:
    def test_fit_recovers_planted_constants_exactly(self):
        """Noise-free planted rows: 1e9 flops / 4ms -> 2.5e11 flops/s,
        1e8 bytes / 4ms -> 2.5e10 B/s, 1 MiB / 1ms -> ~1.05e9 B/s."""
        rows = [_row(exec_ms=4.0, flops_per_step=1e9, bytes_per_step=1e8,
                     collectives={"all-reduce": {"bytes": float(1 << 20),
                                                 "ms": 1.0}})
                for _ in range(4)]
        table, findings = calibrate.calibrate(rows)
        c = table["constants"]
        assert c["peak_flops"] == pytest.approx(2.5e11)
        assert c["hbm_bandwidth"] == pytest.approx(2.5e10)
        assert c["net_bandwidth"] == pytest.approx((1 << 20) / 1e-3)
        assert c["net_bandwidth_per_op"]["all-reduce"] == \
            pytest.approx((1 << 20) / 1e-3)
        assert not findings
        assert table["rows"] == 4 and table["fits"]["peak_flops"] == 4
        got = calibrate.constants_for_cost_model(table)
        assert set(got) == {"peak_flops", "hbm_bandwidth",
                            "net_bandwidth"}

    def test_cold_rows_and_foreign_env_stay_out_of_the_fit(self):
        """A compile-resolving step's step_ms fallback and another
        machine's rows must not bend the rates."""
        good = [_row(exec_ms=4.0, flops_per_step=1e9) for _ in range(3)]
        cold = [_row(step_ms=4000.0, flops_per_step=1e9, cold=1)]
        foreign = [_row(exec_ms=400.0, flops_per_step=1e9,
                        env=dict(pl.env_fingerprint(), jax="9.9.99"))]
        table, _ = calibrate.calibrate(good + cold + foreign)
        assert table["constants"]["peak_flops"] == pytest.approx(2.5e11)
        assert table["rows"] == 4  # foreign row filtered before fitting
        # ...but a cold row WITH exec_ms is usable: the exec window
        # excludes compile resolution by construction
        table2, _ = calibrate.calibrate(
            good + [_row(exec_ms=4.0, flops_per_step=1e9, cold=1)])
        assert table2["fits"]["peak_flops"] == 4

    def test_findings_name_missing_signal(self):
        """Too few rows -> calib-insufficient-rows; zero signal ->
        calib-no-signal; every fit degrades to the nominal constant."""
        table, findings = calibrate.calibrate(
            [_row(exec_ms=4.0, flops_per_step=1e9,
                  bytes_per_step=1e8)] * 2)
        assert table["constants"] == {}
        rules = sorted(f.pass_name for f in findings)
        assert rules == ["calib-insufficient-rows",
                         "calib-insufficient-rows", "calib-no-signal"]
        table, findings = calibrate.calibrate([_row(loss=1.0)] * 4)
        assert {f.pass_name for f in findings} == {"calib-no-signal"}
        assert all(f.severity == "warning" for f in findings)

    def test_table_roundtrip_rejects_foreign_schema(self, tmp_path):
        table, _ = calibrate.calibrate(
            [_row(exec_ms=4.0, flops_per_step=1e9)] * 3)
        path = str(tmp_path / "t.json")
        calibrate.save_table(table, path)
        assert calibrate.load_table(path)["constants"]["peak_flops"] == \
            pytest.approx(2.5e11)
        with open(path, "w") as f:
            json.dump({"v": 99}, f)
        with pytest.raises(ValueError, match="calibration table"):
            calibrate.load_table(path)

    def test_fit_rate_degenerate(self):
        assert calibrate.fit_rate([]) is None
        assert calibrate.fit_rate([(0.0, 1.0), (1.0, 0.0)]) is None
        assert calibrate.fit_rate([(2.0, 1.0)]) == pytest.approx(2.0)


class TestCostModelRerank:
    def test_constants_override_denominators(self):
        cm = cost_model.CostModel(constants={"peak_flops": 2.5e11,
                                             "hbm_bandwidth": 2.5e10,
                                             "net_bandwidth": 1e9})
        assert cm.peak == 2.5e11
        assert cm.hbm_bw == 2.5e10
        assert cm.net_bw == 1e9
        # explicit kwargs still win over the measured table
        cm = cost_model.CostModel(peak=1.0,
                                  constants={"peak_flops": 2.5e11})
        assert cm.peak == 1.0

    def test_calibrated_constants_rerank_the_search(self):
        """The acceptance pin: a measured interconnect so slow that
        every wire byte dominates must hand the win to the plan moving
        the fewest bytes — calibration changes the ORDER, not just the
        prices."""
        nominal = plan_search.search("gpt")
        assert nominal.ranked
        cm = cost_model.CostModel(
            constants={"net_bandwidth": 1.0})  # 1 B/s interconnect
        slow = plan_search.search("gpt", cm=cm)
        assert slow.ranked
        best_plan, best_score = slow.ranked[0]
        assert best_score["comm_bytes"] == min(
            s["comm_bytes"] for _, s in slow.ranked)
        # and the prices moved: the same winning plan costs more under
        # the measured (slower) constants than under the nominal table
        nom_by_desc = {p.describe(): s for p, s in nominal.ranked}
        moved = [d for p, s in slow.ranked
                 for d in [p.describe()]
                 if d in nom_by_desc and s["comm_bytes"] > 0
                 and s["total_s"] > nom_by_desc[d]["total_s"]]
        assert moved, "slow interconnect re-priced no comm-bearing plan"
