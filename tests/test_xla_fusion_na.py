"""Tests backing the op-coverage N/A claims for the reference's fused
kernels (VERDICT r3 #7): `conv2d_fusion`, `conv2d_inception_fusion`, and
`multi_gru` exist in the reference because CUDA needs hand-written fused
kernels; on this architecture XLA performs the fusion. These tests compile
the equivalent subgraphs and assert, on the optimized HLO, that the
elementwise epilogues really are fused (no standalone add/maximum/tanh
instructions in the ENTRY computation — they live inside fusion bodies).
"""
import re

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _entry_block(hlo_text):
    """The ENTRY computation's instruction lines (fusion bodies excluded)."""
    m = re.search(r"^ENTRY [^{]*\{(.*?)^\}", hlo_text,
                  re.DOTALL | re.MULTILINE)
    assert m, "no ENTRY computation in HLO"
    return m.group(1)


def _unfused_ops(entry, op_names):
    hits = []
    for line in entry.splitlines():
        for op in op_names:
            # instruction form: "%name = f32[...] add(...)"
            if re.search(rf"= [a-z0-9\[\],{{}}]+ {op}\(", line.strip()):
                hits.append(line.strip())
    return hits


def _compiled_text(layer, *args):
    from paddle_tpu.static.io import layer_pure_fn

    params = {n: np.asarray(t._data) for n, t in layer.state_dict().items()}
    pure = layer_pure_fn(layer, force_eval=True)
    return jax.jit(pure).lower(params, *args).compile().as_text()


class TestConv2dFusion:
    def test_conv_bias_relu_epilogue_is_fused(self):
        """conv2d_fusion = conv + bias + activation in one kernel
        (operators/fused/conv2d_fusion_op). XLA fuses the epilogue."""
        paddle.seed(0)
        net = nn.Sequential(nn.Conv2D(8, 16, 3, padding=1), nn.ReLU())
        txt = _compiled_text(net, np.zeros((1, 8, 16, 16), np.float32))
        assert txt.count("fusion(") > 0
        entry = _entry_block(txt)
        assert _unfused_ops(entry, ["add", "maximum"]) == []


class TestConv2dInceptionFusion:
    def test_inception_branches_one_program(self):
        """conv2d_inception_fusion = the 4-branch inception block as one
        kernel. Compiled here as ONE XLA program: branch epilogues fused,
        concat stitches device-side (no per-branch round trips)."""

        class Inception(nn.Layer):
            def __init__(self):
                super().__init__()
                self.b1 = nn.Conv2D(8, 8, 1)
                self.b3 = nn.Conv2D(8, 8, 3, padding=1)
                self.b5 = nn.Conv2D(8, 8, 5, padding=2)
                self.proj = nn.Conv2D(8, 8, 1)
                self.pool = nn.MaxPool2D(3, stride=1, padding=1)
                self.act = nn.ReLU()

            def forward(self, x):
                outs = [self.act(self.b1(x)), self.act(self.b3(x)),
                        self.act(self.b5(x)), self.act(self.proj(self.pool(x)))]
                return paddle.concat(outs, axis=1)

        paddle.seed(0)
        txt = _compiled_text(Inception(), np.zeros((1, 8, 12, 12),
                                                   np.float32))
        assert txt.count("fusion(") > 0
        entry = _entry_block(txt)
        # every branch's bias-add + relu epilogue is fused away
        assert _unfused_ops(entry, ["add", "maximum"]) == []
        # and the whole block compiled to a single executable containing
        # the concatenate (present somewhere, possibly inside a fusion)
        assert "concatenate" in txt


class TestMultiGRUFusion:
    def test_stacked_gru_gates_fused(self):
        """multi_gru = fused stacked-GRU inference kernel (oneDNN). Here
        the 2-layer GRU compiles to one program whose per-step gate math
        (matmul epilogues: add/sigmoid/tanh/mul) is XLA-fused inside the
        scan body."""
        paddle.seed(0)
        net = nn.GRU(input_size=16, hidden_size=16, num_layers=2)
        x = np.zeros((2, 8, 16), np.float32)
        txt = _compiled_text(net, x)
        assert txt.count("fusion(") > 0
        entry = _entry_block(txt)
        # the gate elementwise chain must not execute as standalone
        # ENTRY-level instructions
        assert _unfused_ops(entry, ["tanh", "logistic", "multiply"]) == []


class TestSparseTableInt8Serving:
    def test_lookup_table_dequant_roundtrip(self):
        """lookup_table_dequant parity (operators/lookup_table_dequant_op):
        the PS sparse table freezes to int8 rows + per-row absmax scale,
        pulls dequantize on the fly (~4x smaller serving table)."""
        from paddle_tpu.distributed.ps.tables import SparseTable

        t = SparseTable(dim=8, seed=0)
        ids = np.arange(32, dtype=np.int64)
        dense = t.pull(ids)                   # materialize rows
        assert t.size() == 32 and not t.quantized

        t.quantize()
        assert t.quantized and t.size() == 32
        got = t.pull(ids)
        # absmax int8: max error is scale/127 per element
        scales = np.max(np.abs(dense), axis=1, keepdims=True)
        assert np.all(np.abs(got - dense) <= scales / 127.0 + 1e-8)
        # storage really is int8 codes
        codes, scale = t._qrows[0]
        assert codes.dtype == np.int8
        # unknown keys read zeros; training pushes are refused
        assert np.allclose(t.pull([999]), 0.0)
        with pytest.raises(RuntimeError, match="quantized"):
            t.push(ids[:2], np.ones((2, 8), np.float32))


class TestNativeTableInt8Serving:
    def test_native_table_quantize_matches_contract(self):
        """The preferred native (C++) backend keeps the same quantize()
        contract — table.quantize() must not depend on which backend
        make_sparse_table picked."""
        from paddle_tpu.distributed.ps import native_table as nt

        try:
            t = nt.NativeSparseTable(dim=8, seed=0)
        except Exception:
            pytest.skip("native table lib unavailable")
        ids = np.arange(16, dtype=np.int64)
        dense = t.pull(ids)
        t.quantize()
        assert t.quantized
        got = t.pull(ids)
        scales = np.max(np.abs(dense), axis=1, keepdims=True)
        assert np.all(np.abs(got - dense) <= scales / 127.0 + 1e-8)
        assert np.allclose(t.pull([12345]), 0.0)   # miss reads zeros
        with pytest.raises(RuntimeError, match="quantized"):
            t.push(ids[:2], np.ones((2, 8), np.float32))


class TestGeoTableQuantizedGuard:
    def test_geo_push_delta_refused_when_quantized(self):
        from paddle_tpu.distributed.ps.tables import GeoSparseTable

        t = GeoSparseTable(dim=4, trainers=2, seed=0)
        t.pull(np.arange(4))
        t.quantize()
        with pytest.raises(RuntimeError, match="quantized"):
            t.push_delta(0, np.arange(2), np.ones((2, 4), np.float32))


class TestGradOpsAutodiffRealized:
    def test_cross_entropy_grad_via_tape(self):
        """cross_entropy_grad2 (and every *_grad registration) is realized
        by the generic tape/vjp autodiff, not per-op grad kernels: the
        gradient of cross_entropy matches the analytic softmax-minus-onehot
        form."""
        paddle.seed(0)
        logits = paddle.to_tensor(
            np.random.RandomState(0).randn(4, 5).astype(np.float32))
        logits.stop_gradient = False
        labels = paddle.to_tensor(np.array([1, 0, 3, 2], np.int64))
        loss = nn.CrossEntropyLoss()(logits, labels)
        loss.backward()
        g = np.asarray(logits.grad._data)
        p = np.exp(np.asarray(logits._data))
        p /= p.sum(-1, keepdims=True)
        onehot = np.eye(5, dtype=np.float32)[np.asarray(labels._data)]
        np.testing.assert_allclose(g, (p - onehot) / 4, atol=1e-5)
