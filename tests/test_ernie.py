"""ERNIE model family tests (BASELINE.json config #4)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import (
    ErnieConfig, ErnieForPretraining, ErnieForSequenceClassification,
    ErniePretrainLoss, knowledge_mask,
)


def _ids(b=2, s=16, v=1024, seed=0):
    return paddle.to_tensor(
        np.random.RandomState(seed).randint(0, v, (b, s)).astype(np.int64))


class TestErnieModel:
    def test_pretrain_forward_and_joint_loss(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        model = ErnieForPretraining(cfg)
        ids = _ids(v=cfg.vocab_size)
        mlm_logits, nsp_logits = model(ids)
        assert tuple(mlm_logits.shape) == (2, 16, cfg.vocab_size)
        assert tuple(nsp_logits.shape) == (2, 2)

        loss_fn = ErniePretrainLoss()
        nsp_labels = paddle.to_tensor(np.array([0, 1], np.int64))
        loss = loss_fn((mlm_logits, nsp_logits), (ids, nsp_labels))
        loss.backward()
        g = model.ernie.embeddings.word.weight.grad
        assert g is not None and np.isfinite(np.asarray(g._data)).all()

    def test_task_type_embedding_ernie2(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        cfg.task_type_vocab_size = 3
        model = ErnieForPretraining(cfg)
        ids = _ids(v=cfg.vocab_size)
        task = paddle.zeros([2, 16], dtype="int64")
        seq, pooled = model.ernie(ids, task_type_ids=task)
        assert tuple(pooled.shape) == (2, cfg.hidden_size)

    def test_sequence_classification_trains(self):
        paddle.seed(0)
        cfg = ErnieConfig.tiny()
        model = ErnieForSequenceClassification(cfg, num_classes=3)
        model.eval()  # no dropout for determinism
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        ids = _ids(v=cfg.vocab_size)
        labels = paddle.to_tensor(np.array([0, 2], np.int64))
        losses = []
        for _ in range(3):
            logits = model(ids)
            loss = paddle.nn.functional.cross_entropy(logits, labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]


class TestKnowledgeMasking:
    def test_whole_spans_masked_together(self):
        rng = np.random.RandomState(0)
        ids = rng.randint(10, 1000, (4, 32))
        spans = [[(0, 4), (8, 12), (20, 25)] for _ in range(4)]
        masked, labels = knowledge_mask(ids, spans, mask_token_id=3,
                                        vocab_size=1000, mask_prob=1.0,
                                        rng=np.random.RandomState(1))
        # every span position has a label; non-span positions have none
        span_mask = np.zeros_like(ids, bool)
        for b in range(4):
            for (s, e) in spans[b]:
                span_mask[b, s:e] = True
        assert (labels[span_mask] != -100).all()
        assert (labels[~span_mask] == -100).all()
        # spans are atomic: within a masked-to-[MASK] span, all positions change
        for b in range(4):
            for (s, e) in spans[b]:
                seg = masked[b, s:e]
                if (seg == 3).any():
                    assert (seg == 3).all()

    def test_mask_prob_zero_is_identity(self):
        ids = np.arange(64).reshape(2, 32) + 10
        masked, labels = knowledge_mask(ids, [[(0, 5)], [(3, 8)]],
                                        mask_token_id=3, vocab_size=100,
                                        mask_prob=0.0)
        np.testing.assert_array_equal(masked, ids)
        assert (labels == -100).all()
